"""The vectorized Flow-Updating round — the TPU replacement for SimGrid's DES.

Where the reference multiplexes one coroutine per actor through SimGrid's
sequential maestro (each peer: post one async receive, test it, tick, sleep
1 s — ``flowupdating-collectall.py:66-85``), here *all* N peers advance one
tick as a single bulk-synchronous step of dense edge-array ops, and R rounds
run as one ``jax.lax.scan``.  One round has two phases, mirroring the
reference loop body ordering (receive -> tick/fire -> average & send):

``deliver_phase``
    Pop this round's slot of the in-flight ring buffer (messages land in the
    slot of the *receiver's* edge, so arrival is elementwise), merge into the
    per-edge pending set (newer-wins — the protocol's state exchange is
    idempotent), then *drain*: unbounded in fast mode, or a per-node
    round-robin pick of ``cfg.drain`` messages (the reference's loop drains
    at most one message per simulated second).  Processing a message applies
    the antisymmetry write ``flow[e] = -msg.flow`` / ``est[e] = msg.estimate``
    (reference ``:98-99``) into the receiver's ledger.

``fire_phase``
    Decide who averages (all-neighbors-reported / tick-timeout for
    collect-all, receive-trigger / staleness for pairwise — or everyone, in
    fast mode), compute the averages with segment reductions, update ledgers,
    and scatter outgoing messages into future ring-buffer slots at
    ``(t + delay[e]) % D`` (unit delay or latency-warped rounds share this
    path).  Message loss (fault injection) masks the scatter only — the
    sender's ledger is updated regardless, exactly like a lost ``put_async``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from flow_updating_tpu.models.config import (
    COLLECTALL,
    RoundConfig,
    RoundParams,
)
from flow_updating_tpu.models.state import FlowUpdatingState, _ex, _feat
from flow_updating_tpu.utils import struct
from flow_updating_tpu.ops.segment import (
    ell_segment_all,
    ell_segment_max,
    ell_segment_min,
    ell_segment_sum,
    rows_segment_all,
    rows_segment_max,
    rows_segment_min,
    rows_segment_sum,
    segment_all,
    segment_max,
    segment_min,
    segment_sum,
)
from flow_updating_tpu.ops.segscan import segmented_affine_scan

_I32_MAX = jnp.iinfo(jnp.int32).max


# Per-node reductions over out-edges dispatch on the topology arrays:
# * topo.sweep_edge_rows (the batched sweep layout) unrolls a uniform-
#   width out-edge row matrix in edge order — scatter-free AND bit-exact
#   with the sorted scatter-add (ops/segment.rows_segment_*);
# * cfg.segment_impl='benes' (device_arrays(segment_benes=True)) routes
#   every reduction through the permutation-network segmented scan
#   (ops/seg_benes.py) — no gather, no scatter, the TPU path;
# * cfg.segment_impl='ell' (device_arrays(segment_ell=True)) uses the
#   degree-bucketed out-edge ELL gather + row-reduce;
# * otherwise the jax.ops segment primitives (scatter-based lowering).
# Node->edge broadcasts (`x[src]`) follow the same dispatch via _bcast.

def _seg_sum(x, topo, N):
    if topo.sweep_edge_rows is not None:
        return rows_segment_sum(x, topo.sweep_edge_rows)
    if topo.seg_plan is not None:
        from flow_updating_tpu.ops.seg_benes import seg_reduce

        return seg_reduce(x, "sum", topo.seg_plan, topo.seg_dist,
                          topo.seg_extract_masks)
    if topo.ell_edge_mats is not None:
        return ell_segment_sum(x, topo)
    return segment_sum(x, topo.src, N)


def _seg_min(x, topo, N, identity):
    if topo.sweep_edge_rows is not None:
        return rows_segment_min(x, topo.sweep_edge_rows, identity)
    if topo.seg_plan is not None:
        from flow_updating_tpu.ops.seg_benes import seg_reduce

        return seg_reduce(x, "min", topo.seg_plan, topo.seg_dist,
                          topo.seg_extract_masks)
    if topo.ell_edge_mats is not None:
        return ell_segment_min(x, topo, identity)
    return segment_min(x, topo.src, N)


def _seg_max(x, topo, N, identity):
    if topo.sweep_edge_rows is not None:
        return rows_segment_max(x, topo.sweep_edge_rows, identity)
    if topo.seg_plan is not None:
        from flow_updating_tpu.ops.seg_benes import seg_reduce

        return seg_reduce(x, "max", topo.seg_plan, topo.seg_dist,
                          topo.seg_extract_masks)
    if topo.ell_edge_mats is not None:
        return ell_segment_max(x, topo, identity)
    return segment_max(x, topo.src, N)


def _seg_all(pred, topo, N):
    if topo.sweep_edge_rows is not None:
        return rows_segment_all(pred, topo.sweep_edge_rows, topo.out_deg)
    if topo.seg_plan is not None:
        from flow_updating_tpu.ops.seg_benes import seg_reduce

        return seg_reduce(pred, "all", topo.seg_plan, topo.seg_dist,
                          topo.seg_extract_masks)
    if topo.ell_edge_mats is not None:
        return ell_segment_all(pred, topo)
    return segment_all(pred, topo.src, N)


def _bcast(x, topo):
    """Node array -> per-out-edge array (the ``x[src]`` gather; planned
    network when segment_impl='benes')."""
    if topo.seg_plan is not None:
        from flow_updating_tpu.ops.seg_benes import broadcast

        return broadcast(x, topo.seg_plan, topo.seg_dist,
                         topo.seg_place_masks)
    return x[topo.src]


def node_estimates(state: FlowUpdatingState, topo) -> jnp.ndarray:
    """Per-node current estimate: ``value - sum(out flows)``
    (reference ``flowupdating-collectall.py:106-107``)."""
    N = topo.out_deg.shape[0]
    return state.value - _seg_sum(state.flow, topo, N)


def deliver_phase(state: FlowUpdatingState, topo, cfg: RoundConfig):
    """Arrivals + drain + receive.  Returns (state, processed_mask).

    The per-edge pending mailbox is a depth-``Q`` FIFO (``cfg.pending_depth``;
    slot 0 = oldest): arrivals append at the first free slot (overwriting the
    newest on overflow), draining pops the head and shifts.  Q=1 degenerates
    to the newer-wins single slot.  SimGrid's mailbox queues unmatched puts
    unboundedly (reference ``flowupdating-collectall.py:74,123-125``); the
    depth-Q queue reproduces those per-message events up to Q deep —
    tests/test_dynamics_parity.py quantifies the difference against the DES
    oracle.
    """
    N = topo.out_deg.shape[0]
    D = cfg.delay_depth
    Q = cfg.pending_depth
    slot = state.t % D

    arr_valid = state.buf_valid[slot]                      # (E,)
    # append arrivals at each edge's first free queue slot (newest slot is
    # overwritten when the queue is full)
    depth = jnp.sum(state.pending_valid, axis=0)           # (E,) int32
    put = jnp.minimum(depth, Q - 1)                        # (E,)
    hit = arr_valid[None, :] & (
        jnp.arange(Q, dtype=put.dtype)[:, None] == put[None, :]
    )
    pending_flow = jnp.where(_ex(hit, state.pending_flow),
                             state.buf_flow[slot][None],
                             state.pending_flow)
    pending_est = jnp.where(_ex(hit, state.pending_est),
                            state.buf_est[slot][None],
                            state.pending_est)
    pending_stamp = jnp.where(hit, state.t, state.pending_stamp)
    pending_valid = state.pending_valid | hit
    buf_valid = state.buf_valid.at[slot].set(False)

    receiver_alive = _bcast(state.alive, topo)
    candidates = pending_valid[0] & receiver_alive         # head slot ready

    if cfg.drain == 0:
        process = candidates
    else:
        # FIFO pick of `drain` pending in-edges per node: primary key is the
        # head message's *arrival round* (SimGrid pops the oldest message
        # across the whole node mailbox — reference ``collectall.py:74``),
        # tie-broken by the edge's rank rotated by the round counter so
        # same-round arrivals are serviced round-robin.  Arrival order
        # matters: a rotating-rank-only pick services queued edges with
        # systematically stale replies, which destabilizes the pairwise
        # ping-pong (sustained oscillation at pending_depth > 1).
        process = jnp.zeros_like(candidates)
        remaining = candidates
        deg_e = (topo.deg_e if topo.deg_e is not None
                 else _bcast(topo.out_deg, topo))
        prio = jnp.mod(topo.edge_rank - state.t, jnp.maximum(deg_e, 1))
        for _ in range(cfg.drain):
            skey = jnp.where(remaining, pending_stamp[0], _I32_MAX)
            oldest = _seg_min(skey, topo, N, _I32_MAX)
            tie = (remaining & (skey == _bcast(oldest, topo))
                   & (skey < _I32_MAX))
            key = jnp.where(tie, prio, _I32_MAX)
            best = _seg_min(key, topo, N, _I32_MAX)
            pick = tie & (key == _bcast(best, topo)) & (key < _I32_MAX)
            process = process | pick
            remaining = remaining & ~pick

    recv_flow = pending_flow[0]
    if cfg.robust == "clip":
        # the receive-side half of the flow-ledger clamp (see fire_core):
        # the antisymmetry write honors the same +-robust_clip bound, so
        # a corrupted wire flow cannot install an oversized ledger entry
        clamp = jnp.asarray(cfg.robust_clip, recv_flow.dtype)
        recv_flow = jnp.clip(recv_flow, -clamp, clamp)
    flow = jnp.where(_ex(process, state.flow), -recv_flow, state.flow)
    est = jnp.where(_ex(process, state.est), pending_est[0], state.est)
    recv = state.recv | process

    # pop the head of each processed queue: shift slots down by one
    if Q > 1:
        shift = lambda a, fill: jnp.concatenate([a[1:], fill], axis=0)
        pending_flow = jnp.where(
            _ex(process[None], pending_flow),
            shift(pending_flow, pending_flow[-1:]),
            pending_flow,
        )
        pending_est = jnp.where(
            _ex(process[None], pending_est),
            shift(pending_est, pending_est[-1:]),
            pending_est,
        )
        pending_stamp = jnp.where(
            process[None, :], shift(pending_stamp, pending_stamp[-1:]),
            pending_stamp,
        )
        pending_valid = jnp.where(
            process[None, :],
            shift(pending_valid, jnp.zeros_like(pending_valid[:1])),
            pending_valid,
        )
    else:
        pending_valid = pending_valid & ~process[None, :]

    state = state.replace(
        flow=flow,
        est=est,
        recv=recv,
        pending_flow=pending_flow,
        pending_est=pending_est,
        pending_valid=pending_valid,
        pending_stamp=pending_stamp,
        buf_valid=buf_valid,
    )
    return state, process


def _align_drop(keep, topo):
    """Per-message loss draws are keyed by ORIGINAL edge id: on a
    topology-compiler-reordered graph (``topo.drop_perm`` set by
    ``plan.reorder_topology_stable``) the threefry draw for plan edge e
    is the one its original edge would have received, so a planned
    drop>0 run replays the exact original loss realization (bit-exact
    state evolution after unpermutation, tests/test_plan.py).  Identity
    (the common case) is free."""
    if getattr(topo, "drop_perm", None) is None:
        return keep
    return keep[topo.drop_perm]


def _trim_extreme_edges(state: FlowUpdatingState, topo, cfg: RoundConfig,
                        N: int, dt):
    """The trimmed-mean mark (robust='trim', both protocol families): a
    node with degree >= 3 whose neighbor-estimate spread exceeds
    ``cfg.robust_tol`` marks its single highest and single lowest
    neighbor-estimate edge (one edge each — ties broken by edge rank, so
    the mark is deterministic).  Returns the ``(E,)`` marked-edge mask;
    the caller decides what exclusion means for its family (collect-all
    freezes the edge out of the average and the flow exchange, pairwise
    refuses to match / fire along it)."""
    est_hi = _seg_max(state.est, topo, N,
                      jnp.asarray(jnp.finfo(dt).min, dt))
    est_lo = _seg_min(state.est, topo, N,
                      jnp.asarray(jnp.finfo(dt).max, dt))
    tol = jnp.asarray(cfg.robust_tol, dt)
    can = (topo.out_deg >= 3) & (est_hi - est_lo > tol)
    can_e = _bcast(can, topo)
    # one edge per extreme: among the edges attaining the neighborhood
    # max (resp. min), keep the lowest edge rank
    at_hi = can_e & (state.est >= _bcast(est_hi, topo))
    at_lo = can_e & (state.est <= _bcast(est_lo, topo))
    pick = lambda at: at & (topo.edge_rank == _bcast(_seg_min(
        jnp.where(at, topo.edge_rank, _I32_MAX), topo, N,
        _I32_MAX), topo))
    return pick(at_hi) | pick(at_lo)


def _reject_vec_trim(vec: bool) -> None:
    if vec:
        raise ValueError(
            "robust='trim' marks per-edge extreme ESTIMATES, a "
            "control-plane (feature-free) decision; vector "
            "payloads would need per-feature firing — use "
            "robust='clip' for (N, D) payloads")


def fire_core(state: FlowUpdatingState, topo, cfg: RoundConfig, trigger,
              params: RoundParams | None = None):
    """Tick + averaging + ledger update; outgoing messages are *computed*
    but not yet delivered.

    ``params`` (optional) supplies the TRACED numeric knobs — timeout and
    drop rate — in place of ``cfg``'s static fields, so one compiled
    program serves a parameter grid (see :class:`RoundParams`).  ``None``
    keeps the exact historical static program.

    Returns ``(state, msg_est, send_mask)`` where the message payload for
    edge ``e`` is ``(state.flow[e], msg_est[e])`` — the sender's ledger after
    the update, exactly what the reference puts on the wire
    (``flowupdating-collectall.py:116-125``).  The caller scatters it into
    ring-buffer slots: :func:`send_messages` on one device, the halo
    exchange in :mod:`flow_updating_tpu.parallel.sharded` across devices.
    """
    N = topo.out_deg.shape[0]
    E = topo.src.shape[0]
    D = cfg.delay_depth
    dt = state.flow.dtype
    t = state.t
    src = topo.src

    timeout = cfg.timeout if params is None else params.timeout
    ticks = state.ticks
    stamp = state.stamp
    recv = state.recv
    last_avg = state.last_avg
    fired_ctr = state.fired

    # collect-all needs up to three same-structure reductions of the
    # current state (flow sum, est sum, all-heard); with the planned
    # segment networks they share one batched extraction application
    # (ops/seg_benes.seg_reduce_multi) instead of paying it three times
    # vector payloads skip the batched-lane multi helpers (they assume
    # (E,) lanes); each payload reduction/broadcast instead rides the
    # generalized per-op path with its own trailing feature axis
    vec = state.flow.ndim > 1
    if topo.lane_modes is not None and (cfg.variant != COLLECTALL or not vec):
        raise ValueError(
            "per-lane reduction modes (flow_updating_tpu.aggregates) ride "
            "the collectall vector-payload round; build the fabric with "
            "variant='collectall' and a (N, D) lane payload")
    all_heard = None
    if topo.seg_plan is not None and cfg.variant == COLLECTALL and not vec:
        from flow_updating_tpu.ops.seg_benes import seg_reduce_multi

        xs = [(state.flow, "sum"), (state.est, "sum")]
        if cfg.fire_policy != "every_round":
            xs.append((recv, "all"))
        red = seg_reduce_multi(xs, topo.seg_plan, topo.seg_dist,
                               topo.seg_extract_masks)
        flows_sum, est_sum = red[0], red[1]
        if cfg.fire_policy != "every_round":
            all_heard = red[2]
    else:
        flows_sum = _seg_sum(state.flow, topo, N)
        est_sum = (_seg_sum(state.est, topo, N)
                   if cfg.variant == COLLECTALL else None)
    estimate = state.value - flows_sum
    new_value = None

    if cfg.variant == COLLECTALL:
        ticks = ticks + 1
        if cfg.fire_policy == "every_round":
            fire_n = state.alive
        else:
            if all_heard is None:
                all_heard = _seg_all(recv, topo, N)
            fire_n = (all_heard | (ticks >= timeout)) & state.alive
        # avg over self + ALL neighbors' last-known estimates (unheard
        # neighbors contribute their defaultdict 0.0, as in the reference,
        # ``collectall.py:109-113``).
        trim_edge = None
        if cfg.robust == "trim":
            # trimmed-mean fire (robust aggregation, scenarios/): a node
            # with degree >= 3 whose neighbor-estimate spread exceeds
            # cfg.robust_tol marks its single highest and single lowest
            # neighbor estimate (one edge each — ties broken by edge
            # rank, so the mark is deterministic) and EXCLUDES those
            # edges outright — from the average AND from the flow
            # exchange (no ledger delta, no message).  Merely trimming
            # the average while still pumping flow += avg - est along
            # extreme edges is unstable (the extreme pair oscillates
            # with growing amplitude); freezing the edge is what
            # isolates a liar: its pinned-extreme estimate never moves
            # mass again.  Once a neighborhood's spread falls inside
            # robust_tol trimming disarms and the plain fire applies, so
            # honest regions converge to the historical fixed point
            # instead of freezing their extremes forever.
            _reject_vec_trim(vec)
            trim_edge = _trim_extreme_edges(state, topo, cfg, N, dt)
            t_sum = _seg_sum(
                jnp.where(trim_edge, jnp.asarray(0, dt), state.est),
                topo, N)
            t_cnt = topo.out_deg - _seg_sum(
                trim_edge.astype(jnp.int32), topo, N)
            avg = (estimate + t_sum) / _ex((t_cnt + 1).astype(dt),
                                           estimate)
        else:
            avg = (estimate + est_sum) / _ex((topo.out_deg + 1).astype(dt),
                                             estimate)
        if topo.seg_plan is not None and not vec:
            from flow_updating_tpu.ops.seg_benes import broadcast_multi

            fire_e, avg_e = broadcast_multi(
                [fire_n, avg], topo.seg_plan, topo.seg_dist,
                topo.seg_place_masks)
        else:
            fire_e = _bcast(fire_n, topo)
            avg_e = _bcast(avg, topo)
        # under trim, excluded edges apply no ledger delta (no mass moves
        # toward the extreme, and the last-heard extreme entry survives
        # for next round's spread detection) — but they still SEND the
        # unchanged ledger + fresh average below: silencing them too
        # deadlocks honest pairs (each side's stale view of the other
        # stays extreme, so both keep trimming forever)
        act_e = fire_e if trim_edge is None else fire_e & ~trim_edge
        fire_ex = _ex(act_e, state.flow)
        if cfg.robust == "clip":
            # clipped flows (robust aggregation, scenarios/): the flow
            # LEDGER is clamped to +-robust_clip, so no edge can hold
            # more than robust_clip of standing mass displacement.  The
            # fire applies only the delta the clamp admits and the
            # est/wire updates shrink with it, keeping ledger and
            # message consistent; the matching receive-side clamp lives
            # in deliver_phase, so a Byzantine wire gain cannot pump the
            # pair into a runaway amplifier (an unclamped pair with wire
            # gain g multiplies its ledger by g every round trip).
            clamp = jnp.asarray(cfg.robust_clip, dt)
            delta = jnp.clip(state.flow + (avg_e - state.est),
                             -clamp, clamp) - state.flow
            clipped = state.est + delta
            new_flow = jnp.where(fire_ex, state.flow + delta, state.flow)
            new_est = jnp.where(fire_ex, clipped, state.est)
            msg_est = clipped
        else:
            new_flow = jnp.where(fire_ex, state.flow + avg_e - state.est,
                                 state.flow)
            new_est = jnp.where(fire_ex, avg_e, state.est)
            msg_est = avg_e
        if topo.lane_modes is not None:
            # per-lane aggregate reduction modes (aggregates/): lanes in
            # mode 1 (max) / 2 (min) run a LATCHING consensus instead of
            # the additive mean ledger.  Their flow never moves (so the
            # estimate is the value column itself and the ledger residual
            # stays exactly +-0.0); the est ledger still records the last
            # value heard per in-edge, and a firing node latches the
            # extremum of {its own estimate, every last-heard neighbor
            # value} into its value column and broadcasts it.  0 is a
            # valid identity in both directions by the aggregates layer's
            # shifted-lattice contract (max lanes carry values >= 0, min
            # lanes <= 0), so unheard edges, scrubbed free lanes and ghost
            # slots all sit on the all-zero fixed point under every mode.
            # Mode 0 lanes keep the plain writes bit-exactly (the where
            # keeps the same elements), so mean lanes and extrema lanes
            # coexist in this single lowering.
            modes = topo.lane_modes
            ext_lane = modes > 0                     # (D,) per-lane mask
            is_max = modes == 1
            ext_n = jnp.where(
                is_max,
                jnp.maximum(estimate, _seg_max(state.est, topo, N, 0)),
                jnp.minimum(estimate, _seg_min(state.est, topo, N, 0)))
            ext_e = _bcast(ext_n, topo)
            new_value = jnp.where(ext_lane & _ex(fire_n, ext_n),
                                  ext_n, state.value)
            new_flow = jnp.where(ext_lane, state.flow, new_flow)
            new_est = jnp.where(ext_lane,
                                jnp.where(fire_ex, ext_e, state.est),
                                new_est)
            msg_est = jnp.where(ext_lane, ext_e, msg_est)
        send_mask = fire_e
        ticks = jnp.where(fire_n, 0, ticks)
        recv = recv & ~fire_e
        last_avg = jnp.where(_ex(fire_n, avg), avg, last_avg)
        fired_ctr = fired_ctr + fire_n.astype(jnp.int32)
    else:  # PAIRWISE
        if cfg.fire_policy == "every_round":
            # Fast synchronous pairwise = matching gossip in flow form: each
            # round fires one proper-edge-color class, and matched endpoints
            # exchange *directly* — in unit-delay synchronous mode both ends
            # of an edge are visible on-chip, so the 2-party average uses
            # both current estimates and writes exactly antisymmetric flow
            # deltas.  Mass is conserved every round by construction.
            # (Firing all edges at once through the message path diverges:
            # crossing messages transiently inflate mass faster than later
            # exchanges deflate it.)
            if topo.edge_color is None:
                raise ValueError(
                    "fast pairwise mode needs the edge coloring: build the "
                    "topology arrays with device_arrays(coloring=True)"
                )
            half = jnp.asarray(0.5, dt)
            # batched sweep arrays carry the color count as a traced
            # scalar (static num_colors would split the vmap treedef)
            n_colors = (topo.num_colors if topo.num_colors_arr is None
                        else topo.num_colors_arr)
            matched = (
                (topo.edge_color == t % n_colors)
                & state.alive[src]
                & state.alive[topo.dst]
                # direct (message-free) exchange: a failed link in either
                # direction disables the pair symmetrically, or antisymmetry
                # would break within the round
                & state.edge_ok
                & state.edge_ok[topo.rev]
            )
            if cfg.robust == "trim":
                # pairwise trimmed matching: an armed node refuses to
                # match along its extreme-estimate edges.  Standing down
                # is symmetric by construction (the direct exchange needs
                # both ends), so antisymmetry — and mass — are untouched;
                # a pinned-extreme liar simply never moves mass again
                # until the neighborhood spread falls inside robust_tol.
                _reject_vec_trim(vec)
                trim_edge = _trim_extreme_edges(state, topo, cfg, N, dt)
                matched = matched & ~trim_edge & ~trim_edge[topo.rev]
            x_u = estimate[src]
            x_v = estimate[topo.dst]
            if cfg.robust == "clip":
                # the pairwise form of the clipped-flow ledger clamp: the
                # 2-party exchange admits only the delta the +-robust_clip
                # bound allows.  clip is odd and fast-pairwise flow is
                # antisymmetric by construction, so delta[rev] == -delta
                # and mass is conserved exactly; each end's estimate moves
                # by exactly the admitted delta.
                clamp = jnp.asarray(cfg.robust_clip, dt)
                delta = jnp.clip(state.flow + (x_u - x_v) * half,
                                 -clamp, clamp) - state.flow
                avg_e = x_u - delta
                m_ex = _ex(matched, state.flow)
                new_flow = jnp.where(m_ex, state.flow + delta, state.flow)
            else:
                avg_e = (x_u + x_v) * half
                m_ex = _ex(matched, state.flow)
                new_flow = jnp.where(
                    m_ex, state.flow + (x_u - x_v) * half, state.flow
                )
            new_est = jnp.where(m_ex, avg_e, state.est)
            msg_est = avg_e
            send_mask = jnp.zeros_like(matched)  # direct exchange, no messages
            stamp = jnp.where(matched, t, stamp)
            fire_any = _seg_max(matched.astype(jnp.int32), topo, N, 0) > 0
            node_avg = _seg_sum(
                jnp.where(m_ex, avg_e, jnp.asarray(0, dt)), topo, N
            )
            last_avg = jnp.where(_ex(fire_any, node_avg), node_avg, last_avg)
            fired_ctr = fired_ctr + fire_any.astype(jnp.int32)
        else:
            # Faithful message-based dynamics.
            stale = stamp < (t - timeout)
            fire_e = (trigger | stale) & _bcast(state.alive, topo)
            if cfg.robust == "trim":
                # faithful-pairwise trim: an armed node's extreme-estimate
                # edges do not fire at all — no flow delta, no message (the
                # staleness trigger keeps re-arming, and trim keeps
                # standing the edge down while the spread exceeds
                # robust_tol, so a pinned-extreme liar is frozen out).
                _reject_vec_trim(vec)
                fire_e = fire_e & ~_trim_extreme_edges(state, topo, cfg,
                                                       N, dt)
            # Sequential-within-tick semantics: each firing out-edge applies
            # x -> (x + est)/2 to the node's running estimate, in edge order
            # (the reference's for-loop over stale neighbors,
            # ``pairwise.py:86-91,102-109``) — as one segmented affine scan.
            a = jnp.where(fire_e, jnp.asarray(0.5, dt), jnp.asarray(1.0, dt))
            b = jnp.where(
                _ex(fire_e, state.est), state.est * jnp.asarray(0.5, dt),
                jnp.asarray(0.0, dt)
            )
            seg_start = topo.edge_rank == 0
            A, B = segmented_affine_scan(a, b, seg_start)
            run_est = _ex(A, B) * _bcast(estimate, topo) + B  # est after edge e
            avg_e = run_est                  # == the 2-party average at firing e
            f_ex = _ex(fire_e, state.flow)
            if cfg.robust == "clip":
                # clipped flows for the message-based pairwise family: the
                # sequential affine scan keeps computing the UNclipped
                # 2-party targets (the clamp is not affine), and the
                # ledger write admits only the delta within +-robust_clip
                # — ledger, estimate entry and wire message all move by
                # the same admitted delta, and the matching receive-side
                # clamp in deliver_phase bounds what the reply can
                # install, so no edge pair can pump past the bound.
                clamp = jnp.asarray(cfg.robust_clip, dt)
                delta = jnp.clip(state.flow + (avg_e - state.est),
                                 -clamp, clamp) - state.flow
                clipped = state.est + delta
                new_flow = jnp.where(f_ex, state.flow + delta, state.flow)
                new_est = jnp.where(f_ex, clipped, state.est)
                msg_est = clipped
            else:
                new_flow = jnp.where(f_ex, state.flow + avg_e - state.est,
                                     state.flow)
                new_est = jnp.where(f_ex, avg_e, state.est)
                msg_est = avg_e
            send_mask = fire_e
            stamp = jnp.where(fire_e, t, stamp)
            # last_avg per node = average at its last firing edge == its
            # running estimate at the segment end (identity maps pass it
            # through).
            fire_any = _seg_max(fire_e.astype(jnp.int32), topo, N, 0) > 0
            if topo.seg_plan is not None:
                from flow_updating_tpu.ops.seg_benes import extract_row_ends

                final_est = extract_row_ends(
                    run_est, topo.seg_plan, topo.seg_extract_masks
                )
            else:
                seg_end = jnp.maximum(topo.row_start[1:] - 1, 0)
                final_est = run_est[seg_end]
            last_avg = jnp.where(_ex(fire_any, final_est), final_est,
                                 last_avg)
            fired_ctr = fired_ctr + fire_any.astype(jnp.int32)

    # --- device-side Byzantine wire injection (scenarios/adversary.py).
    # Each branch keys on pytree STRUCTURE (a None leaf is statically
    # absent), so adversary-free runs compile the exact plain program.
    # The honest ledgers are never touched — only what goes on the wire.
    if (topo.adv_lie_mask is not None or topo.adv_silent_mask is not None
            or topo.adv_down_mask is not None):
        if cfg.needs_coloring:
            raise ValueError(
                "Byzantine/fault injection targets the message-based "
                "protocols; fast synchronous pairwise exchanges estimates "
                "directly on-chip (no wire to attack) — use "
                "variant='collectall' or fire_policy='reference'")
    if topo.adv_lie_mask is not None:
        # value lies: every message a lying node sends reports
        # adv_lie_value as its estimate (its own state stays honest)
        lie_e = _bcast(topo.adv_lie_mask, topo)
        msg_est = jnp.where(_ex(lie_e, msg_est),
                            jnp.asarray(topo.adv_lie_value, dt), msg_est)
    if topo.adv_silent_mask is not None:
        # silent drops: the node's sends vanish on the wire while its
        # ledger updates regardless — exactly a lost put_async
        send_mask = send_mask & ~_bcast(topo.adv_silent_mask, topo)
    if topo.adv_down_mask is not None:
        # scheduled correlated link failure (partition-then-heal): the
        # masked edges lose every send during rounds [from, until) —
        # cutting a subtree's bridge edges in both directions isolates
        # it without touching node state, and the first post-heal
        # exchange restores the pair ledgers (self-healing)
        down = (topo.adv_down_mask
                & (t >= topo.adv_down_from) & (t < topo.adv_down_until))
        send_mask = send_mask & ~down

    # link-failure mask: a dead link loses every message put on it; the
    # sender's ledger is still updated, exactly like per-message loss
    send_mask = send_mask & state.edge_ok

    key = state.key
    if params is not None and params.drop_rate is not None:
        # traced drop probability: the keep mask is always drawn (no
        # branching on traced values), so the key advances even at 0.0 —
        # where the mask keeps everything and ledgers stay bit-identical
        # to the static path.  params.drop_rate=None omits the draw
        # statically (None is pytree structure, not a traced value).
        key, sub = jax.random.split(key)
        keep = jax.random.bernoulli(sub, 1.0 - params.drop_rate, (E,))
        send_mask = send_mask & _align_drop(keep, topo)
    elif params is None and cfg.drop_rate > 0.0:
        key, sub = jax.random.split(key)
        keep = jax.random.bernoulli(sub, 1.0 - cfg.drop_rate, (E,))
        send_mask = send_mask & _align_drop(keep, topo)

    state = state.replace(
        flow=new_flow,
        est=new_est,
        recv=recv,
        ticks=ticks,
        stamp=stamp,
        last_avg=last_avg,
        fired=fired_ctr,
        key=key,
    )
    if new_value is not None:
        # extrema lanes latch their consensus into the value column (mode
        # 0 lanes are kept bit-exactly by the lane mask above); the write
        # exists only when lane_modes is structurally present, so plain
        # runs compile the byte-identical program with no value output.
        state = state.replace(value=new_value)
    return state, msg_est, send_mask


def edge_delays(topo, cfg: RoundConfig, send_mask,
                inflight=None,
                params: RoundParams | None = None) -> jnp.ndarray:
    """Per-edge delivery delay for this round's sends.

    ``inflight`` ((E,) int — messages still in the ring buffer, i.e.
    sent in earlier rounds and not yet delivered) is counted as standing
    load on its route links when ``cfg.contention_backlog``: the
    cross-tick queueing that the dynamic LMM oracle models and a
    per-round-only solve misses.

    Static (``topo.delay``) unless ``cfg.contention``: then each SHARED
    link's capacity is split across this round's concurrent sends
    (bottleneck fair share — the quasi-static approximation of SimGrid's
    max-min LMM; FATPIPE links never share, SURVEY.md N3 /
    ``small_platform.xml:13-36``), and

        delay[e] = clamp(round(lat_rounds[e] +
                               max_{l in route(e)} load[l] * ser[l]),
                         1, delay_depth)

    where ``load[l]`` = number of concurrent sends crossing l (>= 1) on
    SHARED links, 1 on FATPIPE.
    """
    if not cfg.contention:
        if params is None:
            return topo.delay
        # traced latency scaling: the per-edge static delay stretched by
        # params.latency_scale and re-quantized to whole rounds (1.0
        # reproduces topo.delay exactly: rint(d * 1.0) == d)
        scaled = jnp.rint(
            topo.delay.astype(jnp.float32) * params.latency_scale
        ).astype(jnp.int32)
        return jnp.clip(scaled, 1, cfg.delay_depth)
    if topo.edge_links is None:
        raise ValueError(
            "cfg.contention needs a topology with a link model (platform-"
            "loaded with latency_scale > 0; generators have no links)"
        )
    Lp = topo.link_ser_rounds.shape[0]          # L + 1 (pad slot)
    K = topo.edge_links.shape[1]
    counts = send_mask.astype(jnp.int32)
    standing = jnp.zeros((Lp,), jnp.int32)
    if cfg.contention_backlog and inflight is not None:
        standing = standing.at[topo.edge_links.reshape(-1)].add(
            jnp.repeat(inflight.astype(jnp.int32), K))
    flows = standing.at[topo.edge_links.reshape(-1)].add(
        jnp.repeat(counts, K)
    )
    # traced scaling knobs (RoundParams): latency_scale stretches route
    # latencies, contention_scale every link's per-message serialization
    # cost; both 1.0 by construction when params is None
    lat_rounds = topo.lat_rounds
    link_ser = topo.link_ser_rounds
    if params is not None:
        lat_rounds = lat_rounds * params.latency_scale
        link_ser = link_ser * params.contention_scale
    if cfg.contention_iters == 0:
        # historical quasi-static model: every send pays its LOCAL
        # bottleneck share (equal split at its most-loaded link, no
        # redistribution) — bit-matched by the C++ same-model oracle
        load = jnp.where(topo.link_shared, jnp.maximum(flows, 1), 1)
        ser = load.astype(link_ser.dtype) * link_ser
        worst = jnp.max(ser[topo.edge_links], axis=1)  # pad slot adds 0
        dyn = jnp.rint(lat_rounds + worst).astype(jnp.int32)
        return jnp.clip(dyn, 1, cfg.delay_depth)

    # progressive-filling max-min (cfg.contention_iters unrolled rounds of
    # water-fill): fix the flows crossing the currently most-contended
    # link at its fair share, release the capacity they do NOT use on
    # their other links, repeat — the per-round solve of SimGrid's LMM
    # (exact when the send set has <= iters distinct bottleneck levels;
    # leftovers fall back to their local fair share).  Validated against
    # the dynamic native oracle in tests/test_lmm.py.
    INF = jnp.float32(jnp.inf)
    ser0 = link_ser.astype(jnp.float32)
    constraining = topo.link_shared & (ser0 > 0)
    cap_rem = jnp.where(constraining, 1.0 / jnp.maximum(ser0, 1e-30), INF)
    nflow = flows.astype(jnp.float32)
    el = topo.edge_links                        # (E, K), pad slot = Lp-1
    E = el.shape[0]
    # per-flow full-rate bound from NON-shared ser>0 links: FATPIPE never
    # shares, but each flow is still capped at the link bandwidth (the
    # quasi-static model's 1x ser charge on those links)
    own = jnp.where(~topo.link_shared & (ser0 > 0),
                    1.0 / jnp.maximum(ser0, 1e-30), INF)
    own_cap = jnp.min(own[el], axis=1)          # (E,)
    rate = jnp.zeros((E,), jnp.float32)
    fixed = ~send_mask                          # non-senders: irrelevant
    for _ in range(cfg.contention_iters):
        fair = jnp.where((nflow > 0.5) & constraining,
                         cap_rem / jnp.maximum(nflow, 1.0), INF)
        share = jnp.minimum(jnp.min(fair[el], axis=1), own_cap)
        m = jnp.min(jnp.where(fixed, INF, share))
        newly = (~fixed) & jnp.isfinite(share) & (share <= m * 1.000001)
        rate = jnp.where(newly, share, rate)
        newly_f = newly.astype(jnp.float32)
        cap_rem = jnp.maximum(
            cap_rem.at[el.reshape(-1)].add(
                -jnp.repeat(jnp.where(newly, share, 0.0), K)), 0.0)
        nflow = jnp.maximum(
            nflow.at[el.reshape(-1)].add(-jnp.repeat(newly_f, K)), 0.0)
        fixed = fixed | newly
    fair = jnp.where((nflow > 0.5) & constraining,
                     cap_rem / jnp.maximum(nflow, 1.0), INF)
    share = jnp.minimum(jnp.min(fair[el], axis=1), own_cap)
    rate = jnp.where(fixed, rate, share)
    transfer = jnp.where(jnp.isfinite(rate) & (rate > 0),
                         1.0 / jnp.maximum(rate, 1e-30), 0.0)
    dyn = jnp.rint(lat_rounds + transfer).astype(jnp.int32)
    return jnp.clip(dyn, 1, cfg.delay_depth)


def send_messages(
    state: FlowUpdatingState, topo, cfg: RoundConfig, msg_est, send_mask,
    params: RoundParams | None = None,
) -> FlowUpdatingState:
    """Single-device delivery into the receiver edge's ring-buffer slot at
    ``(t + delay) % D``.

    Two equivalent formulations (``cfg.delivery``):

    * ``gather`` (default): each *receiving* edge r pulls its payload from
      its reverse edge ``rev[r]`` — since ``rev`` is an involution, the
      scatter "sender pushes through rev" is exactly the gather "receiver
      pulls through rev".  The update is then elementwise over the (D, E)
      buffers with a slot-match mask — no scatter at all, which matters on
      TPU where 2-D dynamic-index scatters serialize.
    * ``scatter``: the literal push (kept for cross-checking; non-sending
      edges target an out-of-bounds index and are dropped).
    """
    E = topo.src.shape[0]
    t = state.t
    D = cfg.delay_depth
    if params is not None and cfg.delivery not in ("gather", "scatter"):
        # the benes delivery bakes delay[rev] in as a static lane; a
        # traced latency_scale would silently not apply to it
        raise ValueError(
            "traced RoundParams support delivery='gather'|'scatter'; "
            f"delivery={cfg.delivery!r} bakes static delays into the "
            "permutation network")
    # deliver_phase already cleared this round's arrival slots, so the
    # ring's remaining valid slots are exactly the still-in-flight sends.
    # Column r of the ring holds messages sent along edge rev[r] (the
    # sender writes at the receiver's ledger edge), so the standing load
    # of edge e's own transmissions — which occupy e's route links, not
    # rev[e]'s (asymmetric platform routes differ) — is the rev-gathered
    # occupancy.
    inflight = (state.buf_valid.sum(0, dtype=jnp.int32)[topo.rev]
                if cfg.contention_backlog else None)
    delay = edge_delays(topo, cfg, send_mask, inflight=inflight,
                        params=params)
    # device-side Byzantine flow corruption (scenarios/adversary.py): the
    # WIRE copy of the flow ledger is scaled on corrupted edges, so the
    # receiver's antisymmetry write no longer cancels the sender's honest
    # ledger.  adv_corrupt_mask=None (the default) is pytree structure:
    # wire_flow IS state.flow and the program is the plain one.
    wire_flow = state.flow
    if topo.adv_corrupt_mask is not None:
        if cfg.needs_coloring:
            raise ValueError(
                "Byzantine flow corruption targets the message wire; the "
                "fast synchronous pairwise mode exchanges directly "
                "on-chip — use variant='collectall' or "
                "fire_policy='reference'")
        wire_flow = jnp.where(
            _ex(topo.adv_corrupt_mask, wire_flow),
            wire_flow * jnp.asarray(topo.adv_corrupt_gain,
                                    wire_flow.dtype),
            wire_flow)
    if cfg.delivery in ("gather", "benes", "benes_fused"):
        if cfg.delivery != "gather":
            # same receiver-pull formulation, but the rev permutation runs
            # through the planned Beneš network (ops/permute.py) instead of
            # a dynamic gather — on TPU the gather lowers to a scalar loop.
            # All payload lanes ride one batched application; the delay
            # lane is only needed under contention (static otherwise).
            from flow_updating_tpu.ops.permute import apply_padded_perm

            if topo.rev_plan is None:
                raise ValueError(
                    "delivery='benes' needs device_arrays("
                    "delivery_benes=True)"
                )
            dt = state.flow.dtype
            # the delay lane carries int32 slot counts: a bf16/f16 ledger
            # dtype would corrupt delays > 256, so lanes ride in at least
            # float32 under contention (exact for int32 < 2^24; casting
            # payload values f32 -> bf16 afterwards is value-preserving)
            lane_dt = jnp.promote_types(dt, jnp.float32) \
                if cfg.contention else dt
            # a vector payload's features ride the SAME network as extra
            # lanes: (E, F) transposes to F lanes of (E,), so the batched
            # application stays one pass regardless of F
            nf = _feat(state.flow)
            as_lanes = (lambda x: x.T.astype(lane_dt) if x.ndim > 1
                        else x.astype(lane_dt)[None])
            lanes = [as_lanes(wire_flow), as_lanes(msg_est),
                     send_mask.astype(lane_dt)[None]]
            if cfg.contention:
                lanes.append(delay.astype(lane_dt)[None])
            moved = apply_padded_perm(
                jnp.concatenate(lanes), topo.rev_plan, topo.rev_masks
            )
            un_lanes = (lambda m: m.T.astype(dt) if state.flow.ndim > 1
                        else m[0].astype(dt))
            pay_flow = un_lanes(moved[:nf])
            pay_est = un_lanes(moved[nf:2 * nf])
            sending = moved[2 * nf] > 0.5
            delay_r = (moved[2 * nf + 1].astype(topo.delay.dtype)
                       if cfg.contention else topo.delay_rev)
            slot_r = (t + delay_r) % D
        else:
            rf = topo.rev
            sending = send_mask[rf]
            pay_flow = wire_flow[rf]
            pay_est = msg_est[rf]
            slot_r = (t + delay[rf]) % D
        hit = sending[None, :] & (
            slot_r[None, :] == jnp.arange(D, dtype=slot_r.dtype)[:, None]
        )
        hit_p = _ex(hit, state.buf_flow)
        buf_flow = jnp.where(hit_p, pay_flow[None], state.buf_flow)
        buf_est = jnp.where(hit_p, pay_est[None], state.buf_est)
        buf_valid = state.buf_valid | hit
    else:
        slot_idx = (t + delay) % D
        tgt = jnp.where(send_mask, topo.rev, E)
        buf_flow = state.buf_flow.at[slot_idx, tgt].set(wire_flow, mode="drop")
        buf_est = state.buf_est.at[slot_idx, tgt].set(msg_est, mode="drop")
        buf_valid = state.buf_valid.at[slot_idx, tgt].set(True, mode="drop")
    return state.replace(
        t=t + 1, buf_flow=buf_flow, buf_est=buf_est, buf_valid=buf_valid
    )


def fire_phase(
    state: FlowUpdatingState, topo, cfg: RoundConfig, trigger,
    params: RoundParams | None = None,
) -> FlowUpdatingState:
    """Tick, averaging, ledger update and message send (one device)."""
    state, msg_est, send_mask = fire_core(state, topo, cfg, trigger,
                                          params=params)
    return send_messages(state, topo, cfg, msg_est, send_mask,
                         params=params)


def round_step_aux(state: FlowUpdatingState, topo, cfg: RoundConfig,
                   params: RoundParams | None = None):
    """One full round, also surfacing the per-edge ``processed`` (messages
    drained this round) and ``send_mask`` (messages fired) masks — the
    telemetry counters.  :func:`round_step` discards them; XLA dead-code
    eliminates the unused outputs, so the plain path is unchanged."""
    state, processed = deliver_phase(state, topo, cfg)
    state, msg_est, send_mask = fire_core(state, topo, cfg, processed,
                                          params=params)
    state = send_messages(state, topo, cfg, msg_est, send_mask,
                          params=params)
    return state, processed, send_mask


def round_step(
    state: FlowUpdatingState, topo, cfg: RoundConfig,
    params: RoundParams | None = None,
) -> FlowUpdatingState:
    """One full gossip round (= one simulated second of the reference)."""
    return round_step_aux(state, topo, cfg, params=params)[0]


@functools.partial(jax.jit, static_argnames=("cfg", "num_rounds"))
def run_rounds(
    state: FlowUpdatingState, topo, cfg: RoundConfig, num_rounds: int,
    params: RoundParams | None = None,
) -> FlowUpdatingState:
    """Run ``num_rounds`` rounds as one compiled ``lax.scan``.

    ``params`` moves the numeric knobs (drop rate, timeout, latency /
    contention scaling) into traced inputs: calls differing only in
    params VALUES hit one jit cache entry.  ``None`` (default) is the
    historical static path — program-identical to before the split."""

    def body(s, _):
        return round_step(s, topo, cfg, params=params), None

    state, _ = jax.lax.scan(body, state, None, length=num_rounds)
    return state


# ---- pipelined chunked gossip (deep payloads, arXiv:1504.03277) ----------
#
# A deep (N, D) payload need not ride every edge monolithically: the
# chunked schedule time-multiplexes D/c INDEPENDENT protocol instances,
# one per contiguous c-feature chunk, each carrying its OWN wire state
# (ring-buffer + pending-mailbox slots).  One "visit" advances one
# chunk's instance by ``rounds_per_visit`` ordinary rounds — the
# unmodified :func:`round_step` on that chunk's (E, c) slice — and a
# pass visits every chunk once, so a full model streams through every
# edge over D/c visits while per-visit edge traffic is E*c payload
# lanes, not E*D.
#
# Layout is chunk-major — every payload leaf grows a LEADING
# ``n_chunks`` axis — and the pass runs as ``lax.scan`` over that axis
# with the chunk leaves as xs/ys: the scan machinery's per-iteration
# slice/stack is the in-place update pattern XLA handles on every
# backend (a cursor formulation with ``dynamic_update_slice`` on big
# scan carries measures ~30x slower on XLA:CPU, which falls back to
# full-ledger copies when several cross-coupled carries are updated).
#
# Guarantees (tests/test_dfl_scale.py):
# * each chunk's instance IS the plain protocol on its feature block:
#   a chunked run is bit-identical PER CHUNK to the monolithic run on
#   that block — for every fire policy, drop > 0 included (each chunk
#   carries its own round counter, tick/stamp clocks and PRNG key, so
#   its trajectory cannot depend on the visit schedule or on the other
#   chunks; every chunk starts from the same seed key, mirroring the
#   vector-payload rule that one drop draw serves all lanes);
# * ``c = D`` is ONE chunk and the pass scan degenerates to the plain
#   round scan — bit-identical vs :func:`run_rounds`;
# * per-feature mass conservation under drop > 0 and churn for all c:
#   each chunk owns its wire slots, so a message is always delivered
#   into the ledger slice it was computed from, and the self-healing
#   antisymmetry-write argument applies per chunk unchanged.  Churn is
#   the one SHARED control input (``state.alive`` / ``state.edge_ok``):
#   killing a node kills it for every in-flight chunk at once.


@struct.dataclass
class ChunkedState:
    """Chunk-major state of the pipelined schedule.

    ``state`` is a one-chunk working window: its ``alive`` / ``edge_ok``
    masks are the SHARED control inputs (churn applies to every chunk),
    every other window leaf is per-visit scratch.  The chunk-major
    leaves hold each instance's complete protocol state — payload
    ledgers, wire slots AND per-instance control (round counter,
    tick/stamp clocks, PRNG key) — so each chunk evolves exactly as a
    standalone run on its feature block (leading axis = chunk index
    over contiguous c-feature blocks)."""

    state: FlowUpdatingState   # shared churn masks + (E, c) scratch window
    flow: jnp.ndarray          # (n_chunks, E, c) standing ledgers
    est: jnp.ndarray           # (n_chunks, E, c)
    value: jnp.ndarray         # (n_chunks, N, c)
    last_avg: jnp.ndarray      # (n_chunks, N, c)
    pending_flow: jnp.ndarray  # (n_chunks, Q, E, c) per-instance mailbox
    pending_est: jnp.ndarray   # (n_chunks, Q, E, c)
    pending_valid: jnp.ndarray   # (n_chunks, Q, E)
    pending_stamp: jnp.ndarray   # (n_chunks, Q, E)
    buf_flow: jnp.ndarray      # (n_chunks, Dd, E, c) per-instance ring
    buf_est: jnp.ndarray       # (n_chunks, Dd, E, c)
    buf_valid: jnp.ndarray     # (n_chunks, Dd, E)
    t: jnp.ndarray             # (n_chunks,) per-instance round counters
    recv: jnp.ndarray          # (n_chunks, E) heard-since-last-avg
    ticks: jnp.ndarray         # (n_chunks, N) collect-all tick clocks
    stamp: jnp.ndarray         # (n_chunks, E) pairwise last-avg rounds
    fired: jnp.ndarray         # (n_chunks, N) averaging-event counters
    key: jnp.ndarray           # (n_chunks, ...) per-instance PRNG keys

    @property
    def n_chunks(self) -> int:
        return self.flow.shape[0]

    @property
    def chunk(self) -> int:
        return self.flow.shape[-1]

    @property
    def features(self) -> int:
        return self.n_chunks * self.chunk


#: ChunkedState leaf name == the FlowUpdatingState leaf it windows.
#: Everything here is PER-INSTANCE state riding the pass scan as xs/ys;
#: what is NOT here (alive, edge_ok) is shared control read from the
#: window each visit.
_CHUNK_LEAVES = ("flow", "est", "value", "last_avg", "pending_flow",
                 "pending_est", "pending_valid", "pending_stamp",
                 "buf_flow", "buf_est", "buf_valid",
                 "t", "recv", "ticks", "stamp", "fired", "key")


def chunk_count(features: int, chunk: int) -> int:
    """Number of chunks ``D / c`` (validates divisibility)."""
    if chunk <= 0 or features % chunk:
        raise ValueError(
            f"chunk={chunk} must be a positive divisor of the payload "
            f"feature count D={features} (pad D up to a multiple)")
    return features // chunk


def check_chunked_config(cfg: RoundConfig, features: int,
                         chunk: int) -> None:
    """Domain of validity of the chunked schedule: any edge-kernel
    dynamics (each chunk runs the unmodified round kernel), minus the
    modes that are scalar-only or read cross-round wire occupancy."""
    chunk_count(features, chunk)
    if cfg.kernel != "edge":
        raise ValueError(
            "chunked gossip streams the edge kernel's payload ledgers "
            "(kernel='edge')")
    if cfg.robust == "trim":
        _reject_vec_trim(True)
    if cfg.contention_backlog:
        raise ValueError(
            "contention_backlog reads the ring buffer's standing "
            "occupancy across rounds; under the chunked schedule each "
            "chunk's ring advances only on its own visits, so the "
            "backlog term would alias across instances")


def _chunk_major(x, n_chunks: int):
    """(..., D) -> (n_chunks, ..., c): contiguous feature blocks to the
    leading axis."""
    D = x.shape[-1]
    c = D // n_chunks
    split = x.reshape(x.shape[:-1] + (n_chunks, c))
    return jnp.moveaxis(split, -2, 0)


def _chunk_flat(x):
    """(n_chunks, ..., c) -> (..., D): inverse of :func:`_chunk_major`."""
    merged = jnp.moveaxis(x, 0, -2)
    return merged.reshape(merged.shape[:-2] + (-1,))


def init_chunked_state(topo, cfg: RoundConfig, chunk: int, values,
                       seed: int = 0) -> ChunkedState:
    """Fresh chunk-major state: ``values`` is the full ``(N, D)``
    payload; every instance starts with the usual empty ledgers."""
    values = jnp.asarray(values, cfg.jnp_dtype)
    if values.ndim != 2:
        raise ValueError(
            "chunked gossip streams a vector payload; pass values of "
            f"shape (N, D) — got {values.shape}")
    n = chunk_count(int(values.shape[1]), chunk)
    check_chunked_config(cfg, int(values.shape[1]), chunk)
    from flow_updating_tpu.models.state import init_state as _init

    window = _init(topo, cfg, seed=seed,
                   values=values[:, :chunk])
    E, Q, Dd = topo.num_edges, cfg.pending_depth, cfg.delay_depth
    dt = cfg.jnp_dtype
    rep = lambda x: jnp.broadcast_to(x, (n,) + x.shape)
    return ChunkedState(
        state=window,
        flow=jnp.zeros((n, E, chunk), dt),
        est=jnp.zeros((n, E, chunk), dt),
        value=_chunk_major(values, n),
        last_avg=jnp.zeros((n, topo.num_nodes, chunk), dt),
        pending_flow=jnp.zeros((n, Q, E, chunk), dt),
        pending_est=jnp.zeros((n, Q, E, chunk), dt),
        pending_valid=rep(window.pending_valid),
        pending_stamp=rep(window.pending_stamp),
        buf_flow=jnp.zeros((n, Dd, E, chunk), dt),
        buf_est=jnp.zeros((n, Dd, E, chunk), dt),
        buf_valid=rep(window.buf_valid),
        t=rep(window.t),
        recv=rep(window.recv),
        ticks=rep(window.ticks),
        stamp=rep(window.stamp),
        fired=rep(window.fired),
        # every instance starts from the SAME seed key — the chunk-major
        # form of the vector-payload rule that one drop draw serves all
        # lanes, and what makes c = D degenerate bit-exactly to the
        # plain run
        key=rep(window.key),
    )


def chunked_values(cs: ChunkedState) -> jnp.ndarray:
    """The full ``(N, D)`` input payload, original feature order."""
    return _chunk_flat(cs.value)


def chunked_node_estimates(cs: ChunkedState, topo) -> jnp.ndarray:
    """Per-node ``(N, D)`` estimates over every chunk (readback)."""
    N = topo.out_deg.shape[0]
    flow = _chunk_flat(cs.flow)
    return _chunk_flat(cs.value) - _seg_sum(flow, topo, N)


def _run_chunk_pass(cs: ChunkedState, topo, cfg: RoundConfig,
                    rounds_per_visit: int,
                    params: RoundParams | None = None) -> ChunkedState:
    """One pass: visit every chunk once, advancing its instance by
    ``rounds_per_visit`` unmodified rounds.  The per-instance leaves
    ride the scan as xs/ys; the carry window contributes the shared
    churn masks (``alive``/``edge_ok``) and absorbs per-visit scratch."""

    def visit(ctrl: FlowUpdatingState, xs):
        s = ctrl.replace(**dict(zip(_CHUNK_LEAVES, xs)))
        s = jax.lax.fori_loop(
            0, rounds_per_visit,
            lambda _, x: round_step(x, topo, cfg, params=params), s)
        return s, tuple(getattr(s, f) for f in _CHUNK_LEAVES)

    ctrl, ys = jax.lax.scan(
        visit, cs.state, tuple(getattr(cs, f) for f in _CHUNK_LEAVES))
    return cs.replace(state=ctrl, **dict(zip(_CHUNK_LEAVES, ys)))


@functools.partial(
    jax.jit, static_argnames=("cfg", "num_rounds", "rounds_per_visit"))
def run_rounds_chunked(
    cs: ChunkedState, topo, cfg: RoundConfig, num_rounds: int,
    rounds_per_visit: int = 1, params: RoundParams | None = None,
) -> ChunkedState:
    """Run ``num_rounds`` underlying rounds of the chunked schedule as
    one compiled scan-of-passes.

    ``num_rounds`` counts GLOBAL underlying rounds (visits x
    ``rounds_per_visit``, summed over chunks) and must cover whole
    passes: ``num_rounds % (n_chunks * rounds_per_visit) == 0``.  Each
    chunk advances ``num_rounds / n_chunks`` of its OWN rounds (each
    instance carries its own round counter/clocks/key, so its
    trajectory is schedule-independent — bit-exact vs the monolithic
    run on its block whatever ``rounds_per_visit``); larger
    ``rounds_per_visit`` amortizes the per-visit chunk-rotation cost at
    the price of coarser pipelining (see
    :func:`chunked_rounds_per_visit` and plan/select.py's
    payload-bytes model)."""
    check_chunked_config(cfg, cs.features, cs.chunk)
    per_pass = cs.n_chunks * rounds_per_visit
    if num_rounds % per_pass:
        raise ValueError(
            f"num_rounds={num_rounds} must be a multiple of the pass "
            f"length n_chunks*rounds_per_visit = {per_pass}")

    def one_pass(c, _):
        return _run_chunk_pass(c, topo, cfg, rounds_per_visit,
                               params=params), None

    cs, _ = jax.lax.scan(one_pass, cs, None,
                         length=num_rounds // per_pass)
    return cs


def chunked_rounds_per_visit(topo, cfg: RoundConfig) -> int:
    """The canonical visit length: 1 round, except fast pairwise where
    a visit is one full color sweep — not for correctness (each chunk's
    own round counter cycles its colors whatever the visit length) but
    for delivery latency: a full sweep per visit lets every edge of the
    chunk fire before the schedule rotates on, so a chunk's 2-party
    exchanges complete within one visit instead of straddling passes."""
    if cfg.needs_coloring:
        # TopoArrays carries num_colors as an int (0 = no coloring
        # built), Topology as Optional — reject both absent forms
        if not topo.num_colors:
            raise ValueError(
                "fast pairwise chunking needs the static edge coloring "
                "(device_arrays(coloring=True))")
        return int(topo.num_colors)
    return 1


def chunked_telemetry_sample(cs: ChunkedState, topo, spec, mean) -> dict:
    """One per-PASS metric row over every chunk (device-side).  Reduces
    the chunk-major ledgers directly, so a disabled-feature chunk
    between visits still reports its standing state — the resolution a
    convergence-vs-bytes curve needs (one sample per full model
    stream)."""
    est = chunked_node_estimates(cs, topo)
    alive = cs.state.alive
    # per-instance round counters agree at pass boundaries; max = the
    # per-chunk round count this row samples at
    out = {"t": jnp.max(cs.t)}
    a_ex = _ex(alive, est)
    err = jnp.where(a_ex, est - mean, 0)
    if spec.has("rmse"):
        cnt = (jnp.maximum(jnp.sum(alive), 1)
               * _feat(est)).astype(est.dtype)
        out["rmse"] = jnp.sqrt(jnp.sum(err * err) / cnt)
    if spec.has("max_abs_err"):
        out["max_abs_err"] = jnp.max(jnp.abs(err))
    if spec.has("mass") or spec.has("mass_residual"):
        mass = jnp.sum(jnp.where(a_ex, est, 0), axis=0)      # (D,)
        if spec.has("mass"):
            out["mass"] = mass
        if spec.has("mass_residual"):
            value = _chunk_flat(cs.value)
            out["mass_residual"] = mass - jnp.sum(
                jnp.where(_ex(alive, value), value, 0), axis=0)
    if spec.has("antisymmetry"):
        out["antisymmetry"] = jnp.max(
            jnp.abs(cs.flow + cs.flow[:, topo.rev]))
    if spec.has("active"):
        out["active"] = jnp.sum(alive.astype(jnp.int32))
    return out


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "num_rounds", "rounds_per_visit", "spec"))
def run_rounds_chunked_telemetry(
    cs: ChunkedState, topo, cfg: RoundConfig, num_rounds: int, spec,
    true_mean, rounds_per_visit: int = 1,
    params: RoundParams | None = None,
):
    """Chunked scan with one telemetry row PER PASS riding as ys (each
    row covers all D features).  Returns ``(cs, series)``."""
    if not spec.enabled:
        raise ValueError(
            "telemetry spec is disabled; run run_rounds_chunked() "
            "instead")
    check_chunked_config(cfg, cs.features, cs.chunk)
    per_pass = cs.n_chunks * rounds_per_visit
    if num_rounds % per_pass:
        raise ValueError(
            f"num_rounds={num_rounds} must be a multiple of the pass "
            f"length n_chunks*rounds_per_visit = {per_pass}")
    mean = jnp.asarray(true_mean, cs.value.dtype)

    def one_pass(c, _):
        c = _run_chunk_pass(c, topo, cfg, rounds_per_visit,
                            params=params)
        return c, chunked_telemetry_sample(c, topo, spec, mean)

    cs, series = jax.lax.scan(one_pass, cs, None,
                              length=num_rounds // per_pass)
    return cs, series


def _fired_acc():
    """Accumulator dtype for summed int32 fire counters: int64 when x64 is
    on, else float32 (never wraps; approximate beyond 2^24 events — fine
    for an observability counter)."""
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.float32


def telemetry_sample(state, topo, spec, mean, processed, send_mask) -> dict:
    """One round's metric row for the edge kernel (device-side, inside the
    scan body — no callbacks).  ``spec`` is a static
    :class:`~flow_updating_tpu.obs.telemetry.TelemetrySpec`; only the
    selected metrics are computed, so a narrow spec pays only for what it
    asks.  Metrics mask to alive nodes (excludes mesh-padding dummies and
    crash-stopped nodes), like :func:`_observe_chunk`."""
    out = {"t": state.t}
    alive = state.alive
    need_est = any(spec.has(m) for m in
                   ("rmse", "max_abs_err", "mass", "mass_residual"))
    if need_est:
        est = node_estimates(state, topo)
        a_ex = _ex(alive, est)
        if spec.has("rmse") or spec.has("max_abs_err"):
            err = jnp.where(a_ex, est - mean, 0)
            if spec.has("rmse"):
                cnt = (jnp.maximum(jnp.sum(alive), 1)
                       * _feat(est)).astype(est.dtype)
                out["rmse"] = jnp.sqrt(jnp.sum(err * err) / cnt)
            if spec.has("max_abs_err"):
                out["max_abs_err"] = jnp.max(jnp.abs(err))
        if spec.has("mass") or spec.has("mass_residual"):
            mass = jnp.sum(jnp.where(a_ex, est, 0), axis=0)  # per-feature
            if spec.has("mass"):
                out["mass"] = mass
            if spec.has("mass_residual"):
                out["mass_residual"] = mass - jnp.sum(
                    jnp.where(_ex(alive, state.value), state.value, 0),
                    axis=0)
    if spec.has("antisymmetry"):
        out["antisymmetry"] = jnp.max(
            jnp.abs(state.flow + state.flow[topo.rev]))
    if spec.has("sent"):
        out["sent"] = jnp.sum(send_mask.astype(jnp.int32))
    if spec.has("delivered"):
        out["delivered"] = jnp.sum(processed.astype(jnp.int32))
    if spec.has("fired_total"):
        out["fired_total"] = jnp.sum(state.fired, dtype=_fired_acc())
    if spec.has("active"):
        out["active"] = jnp.sum(alive.astype(jnp.int32))
    return out


@functools.partial(
    jax.jit, static_argnames=("cfg", "num_rounds", "spec")
)
def run_rounds_telemetry(
    state: FlowUpdatingState, topo, cfg: RoundConfig, num_rounds: int,
    spec, true_mean, params: RoundParams | None = None,
):
    """Run ``num_rounds`` rounds as one compiled scan, accumulating the
    ``spec``-selected per-round metric series ON DEVICE (scan ``ys``) —
    one bulk host transfer at the end, zero ``debug.callback``s in the
    body.  Returns ``(state, {metric: (R,) or (R, D) array})``.

    The device-resident replacement for the streamed observer: the full
    per-round curve of a run (the resolution Gossip-PGA-style convergence
    judgments need) at the cost of one extra set of reductions per round,
    only when enabled.  A disabled spec is rejected — callers dispatch to
    :func:`run_rounds` instead so telemetry-off compiles to the exact
    current program (``Engine.run_telemetry`` does this)."""
    if not spec.enabled:
        raise ValueError(
            "telemetry spec is disabled; run run_rounds() instead (the "
            "Engine.run_telemetry dispatcher handles this)")
    mean = jnp.asarray(true_mean, state.value.dtype)

    def body(s, _):
        s, processed, send_mask = round_step_aux(s, topo, cfg,
                                                 params=params)
        return s, telemetry_sample(s, topo, spec, mean, processed,
                                   send_mask)

    state, series = jax.lax.scan(body, state, None, length=num_rounds)
    return state, series


def _pool_abs(x):
    """Per-entity magnitude with trailing feature axes pooled (max |.|)."""
    if x.ndim > 1:
        return jnp.max(jnp.abs(x), axis=tuple(range(1, x.ndim)))
    return jnp.abs(x)


def _pool_sum(x):
    """Signed feature pooling (sum) — preserves flow antisymmetry."""
    if x.ndim > 1:
        return jnp.sum(x, axis=tuple(range(1, x.ndim)))
    return x


def field_sample(state, topo, spec, mean):
    """One recorded row of per-node/per-edge fields for the edge kernel
    (device-side, inside the scan — no callbacks).  ``spec`` is a static
    :class:`~flow_updating_tpu.obs.fields.FieldSpec`.  Returns
    ``(row, err)`` where ``err`` is the alive-masked signed estimate
    error (None when no selected field needs it) — the convergence
    frontier and topk ranking reuse it.

    The masking matches :func:`telemetry_sample` exactly: alive nodes
    only (mesh-padding dummies are born dead), so reducing each field
    reproduces the global telemetry series (tests/test_fields.py)."""
    row = {"t": state.t, "active": jnp.sum(state.alive.astype(jnp.int32))}
    err = None
    need_est = any(spec.has(f) for f in
                   ("node_err", "node_mass", "node_mass_residual",
                    "node_conv_round"))
    if need_est:
        est = node_estimates(state, topo)
        a_ex = _ex(state.alive, est)
        err = jnp.where(a_ex, est - mean, 0)
        if spec.has("node_err"):
            row["node_err"] = err
        if spec.has("node_mass"):
            row["node_mass"] = jnp.where(a_ex, est, 0)
        if spec.has("node_mass_residual"):
            row["node_mass_residual"] = jnp.where(a_ex, est - state.value, 0)
    if spec.has("node_fired"):
        row["node_fired"] = state.fired
    if spec.has("edge_flow"):
        row["edge_flow"] = _pool_sum(state.flow)
    if spec.has("edge_est"):
        row["edge_est"] = _pool_sum(state.est)
    if spec.has("edge_stale"):
        row["edge_stale"] = state.t - state.stamp
    return row, err


@functools.partial(
    jax.jit, static_argnames=("cfg", "num_rounds", "spec")
)
def run_rounds_fields(
    state: FlowUpdatingState, topo, cfg: RoundConfig, num_rounds: int,
    spec, true_mean, params: RoundParams | None = None,
):
    """Run ``num_rounds`` rounds as one compiled scan, accumulating the
    ``spec``-selected per-node/per-edge FIELD rows on device (scan ys).
    Returns ``(state, conv_round, series)`` — ``conv_round`` is the
    ``(N,)`` int32 convergence frontier (-1 = never within ``spec.tol``),
    ``series`` maps field name to a ``(R/stride, ...)`` device array.

    Recording is a pure observer: the scan body applies the exact
    :func:`round_step` sequence, so the state evolution is bit-identical
    to :func:`run_rounds` at any stride (asserted in
    tests/test_fields.py).  A disabled spec is rejected — callers
    dispatch to :func:`run_rounds` instead (``Engine.run_fields``)."""
    if not spec.enabled:
        raise ValueError(
            "field spec is disabled; run run_rounds() instead (the "
            "Engine.run_fields dispatcher handles this)")
    stride = spec.stride
    if num_rounds % stride:
        raise ValueError(
            f"num_rounds={num_rounds} must be a multiple of the field "
            f"stride {stride}")
    mean = jnp.asarray(true_mean, state.value.dtype)
    N = topo.out_deg.shape[0]
    conv0 = jnp.full((N,), -1, jnp.int32)
    track_conv = spec.has("node_conv_round")

    def chunk(carry, _):
        s, conv = carry
        s = jax.lax.fori_loop(
            0, stride, lambda _, x: round_step(x, topo, cfg, params=params),
            s)
        row, err = field_sample(s, topo, spec, mean)
        if track_conv:
            within = (_pool_abs(err) <= spec.tol) & s.alive
            conv = jnp.where((conv < 0) & within, s.t, conv)
        if spec.topk:
            _, idx = jax.lax.top_k(_pool_abs(err), spec.topk)
            for name in spec.node_series_fields:
                row[name] = row[name][idx]
            row["topk_idx"] = idx.astype(jnp.int32)
        return (s, conv), row

    (state, conv), series = jax.lax.scan(
        chunk, (state, conv0), None, length=num_rounds // stride)
    return state, conv, series


@functools.partial(
    jax.jit, static_argnames=("cfg", "num_rounds", "observe_every")
)
def run_rounds_observed(
    state: FlowUpdatingState,
    topo,
    cfg: RoundConfig,
    num_rounds: int,
    observe_every: int,
    true_mean,
):
    """Run rounds in chunks of ``observe_every``, emitting metrics per chunk.

    This is the watcher's sampling loop (reference
    ``flowupdating-collectall.py:139-142`` prints global state every 10
    simulated seconds) expressed as a chunked scan: metrics stay on device
    and come back stacked, one row per observation.
    """
    if num_rounds % observe_every:
        raise ValueError("num_rounds must be a multiple of observe_every")
    chunks = num_rounds // observe_every
    mean = jnp.asarray(true_mean, state.value.dtype)

    def chunk_body(s, _):
        s, (t, rmse, max_err, mass, fired) = _observe_chunk(
            s, topo, cfg, observe_every, mean
        )
        metrics = {
            "t": t,
            "rmse": rmse,
            "max_abs_err": max_err,
            "mass": mass,
            "fired_total": fired,
        }
        return s, metrics

    state, metrics = jax.lax.scan(chunk_body, state, None, length=chunks)
    return state, metrics


def _observe_chunk(s, topo, cfg, observe_every: int, mean):
    """``observe_every`` rounds + one watcher sample (shared by the stacked
    and streamed observers).

    Metrics cover *alive* nodes only — this excludes both mesh-padding
    dummies (born dead, see ``parallel.auto.pad_topology``) and
    crash-stopped nodes, whose frozen estimates would otherwise put a
    floor under the reported rmse.
    """
    s = jax.lax.fori_loop(
        0, observe_every, lambda _, x: round_step(x, topo, cfg), s
    )
    est = node_estimates(s, topo)
    alive = s.alive
    # vector payloads: rmse/max-err pool over features, mass sums over
    # them (per-feature mass is asserted where it matters —
    # workloads/gossip_sgd.py churn runs and tests/test_vector_payload.py)
    cnt = (jnp.maximum(jnp.sum(alive), 1) * _feat(est)).astype(est.dtype)
    err = jnp.where(_ex(alive, est), est - mean, 0)
    # Summing (N,) int32 fire counters keeps int32 in JAX and would wrap
    # once N*rounds exceeds ~2.1e9 — i.e. at the advertised ~1M-node bench
    # scale.  Accumulate in int64 when x64 is on; otherwise float32 (never
    # wraps; approximate beyond 2^24 events, fine for an observability
    # counter).
    fired_acc = jnp.int64 if jax.config.jax_enable_x64 else jnp.float32
    sample = (
        s.t,
        jnp.sqrt(jnp.sum(err * err) / cnt),
        jnp.max(jnp.abs(err)),
        jnp.sum(jnp.where(_ex(alive, est), est, 0)),
        jnp.sum(s.fired, dtype=fired_acc),
    )
    return s, sample


@functools.partial(
    jax.jit, static_argnames=("cfg", "chunks", "observe_every", "emit")
)
def _run_streamed(state, topo, cfg, chunks, observe_every, mean, emit):
    def host_emit(t, rmse_v, max_err, mass, fired):
        from flow_updating_tpu.utils.metrics import observer_sample

        emit(observer_sample(t, rmse_v, max_err, mass, fired))

    def chunk_body(s, _):
        s, sample = _observe_chunk(s, topo, cfg, observe_every, mean)
        jax.debug.callback(host_emit, *sample, ordered=True)
        return s, None

    state, _ = jax.lax.scan(chunk_body, state, None, length=chunks)
    return state


def run_rounds_streamed(
    state: FlowUpdatingState,
    topo,
    cfg: RoundConfig,
    num_rounds: int,
    observe_every: int,
    true_mean,
    emit,
) -> FlowUpdatingState:
    """Like :func:`run_rounds_observed`, but metrics *stream to the host
    while the run executes*: each observation chunk ends in a
    ``jax.debug.callback`` that invokes ``emit(metrics_dict)`` with host
    scalars, in order.  This is the live equivalent of the reference's
    watcher printing every 10 simulated seconds mid-run
    (``flowupdating-collectall.py:139-142``) — one compiled computation, no
    host round-trips between chunks, observability anyway.

    ``emit`` is a jit-static argument: passing the *same callable object*
    across calls reuses the compiled computation.  It must not block for
    long (it runs on the runtime's callback thread and backpressures the
    device queue).  Completion of all emits is only guaranteed after
    ``jax.effects_barrier()``.
    """
    if num_rounds % observe_every:
        raise ValueError("num_rounds must be a multiple of observe_every")
    chunks = num_rounds // observe_every
    mean = jnp.asarray(true_mean, state.value.dtype)
    return _run_streamed(state, topo, cfg, chunks, observe_every, mean, emit)
