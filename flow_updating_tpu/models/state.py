"""The Flow-Updating state pytree.

Everything a reference ``Peer`` keeps per actor (``flowupdating-collectall.py:
26-45``: ``value``, ``flows``, ``estimates``, ``msg_recvd_ids``,
``ticks_since_last_avg``, ``_last_avg``, pending comms) plus everything
SimGrid keeps *for* it (the mailbox queue and in-flight comms) lives here as
a handful of dense arrays.  Per-neighbor dicts become per-directed-edge
arrays; the mailbox + in-flight comm set becomes a ``(D, E)`` ring buffer
keyed by the *receiver's* edge index, so delivery is an elementwise select
and sending is one masked scatter through ``rev``.

Being a single pytree makes checkpoint/resume, vmapping over replicas and
sharding trivial — the reference has no checkpointing at all (SURVEY.md §5);
here it is a free by-product.

**Vector payloads.**  Every *payload* array (``value``, ``flow``, ``est``,
``last_avg`` and the pending/ring payload planes) may carry a trailing
feature axis: pass ``values`` of shape ``(N, D)`` to :func:`init_state` and
the aggregate becomes a D-vector averaged per-feature in one run — the
substrate of the decentralized-learning workloads
(:mod:`flow_updating_tpu.workloads`), where each node's payload is a model
parameter vector.  Control/mask arrays (``recv``, ``ticks``, ``alive``,
validity planes, …) never grow a feature axis: the protocol's firing and
delivery decisions are payload-independent, so a ``(N, D)`` run is exactly
D independent scalar protocol instances sharing one set of messages
(asserted in tests/test_vector_payload.py).
"""

from __future__ import annotations

from flow_updating_tpu.utils import struct
import jax
import jax.numpy as jnp

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.topology.graph import Topology


@struct.dataclass
class FlowUpdatingState:
    t: jnp.ndarray             # () int32 — round counter ("Engine.clock")
    value: jnp.ndarray         # (N,) — local input values
    flow: jnp.ndarray          # (E,) — flow[e] = f(src->dst) as known by src
    est: jnp.ndarray           # (E,) — src's last known estimate of dst
    recv: jnp.ndarray          # (E,) bool — src heard from dst since last avg
    ticks: jnp.ndarray         # (N,) int32 — ticks since last avg (collectall)
    stamp: jnp.ndarray         # (E,) int32 — round of last avg on edge (pairwise)
    last_avg: jnp.ndarray      # (N,) — last computed average per node
    fired: jnp.ndarray         # (N,) int32 — total averaging events per node
    alive: jnp.ndarray         # (N,) bool — failure-injection liveness mask
    edge_ok: jnp.ndarray       # (E,) bool — link-failure mask (False = no send)
    pending_flow: jnp.ndarray  # (Q, E) — undrained delivered message FIFO
    pending_est: jnp.ndarray   # (Q, E)    (slot 0 = oldest; Q = cfg.pending_depth)
    pending_valid: jnp.ndarray  # (Q, E) bool
    pending_stamp: jnp.ndarray  # (Q, E) int32 — arrival round (drain order key)
    buf_flow: jnp.ndarray      # (D, E) — in-flight ring buffer
    buf_est: jnp.ndarray       # (D, E)
    buf_valid: jnp.ndarray     # (D, E) bool
    key: jnp.ndarray           # PRNG key (fault injection)


def feature_shape(values) -> tuple:
    """Trailing feature axes of a payload array: ``()`` for the scalar
    protocol, ``(D,)`` for D-feature vector payloads."""
    return tuple(values.shape[1:])


def _ex(m, ref):
    """Broadcast a control-plane array (a mask or per-node/per-edge
    scalar) over a payload's trailing feature axes.

    The protocol's decisions (who fires, what is delivered, what is
    dropped) are computed on feature-free ``(N,)``/``(E,)`` arrays; the
    payloads they select between may carry a trailing ``(D,)`` feature
    axis.  ``_ex`` appends singleton axes so ``jnp.where(_ex(mask, x),
    a, x)`` broadcasts the mask across features instead of mis-aligning
    it against them.  Shared by every kernel (rounds, sync, sharded)."""
    extra = ref.ndim - m.ndim
    return m.reshape(m.shape + (1,) * extra) if extra > 0 else m


def _feat(x) -> int:
    """Number of feature lanes of a payload array (1 for scalar)."""
    return int(x.size // x.shape[0]) if x.ndim > 1 else 1


def check_payload_values(values, num_nodes: int) -> None:
    """Shared payload-shape contract for every state entry point
    (init_state, sync.NodeKernel, parallel.sharded.init_plan_state):
    ``(N,)`` scalar or ``(N, D)`` — ONE feature axis, because the lane
    packings (benes delivery, halo exchange) address features as
    ``x[:, d]``."""
    if values.shape[0] != num_nodes:
        raise ValueError(
            f"values must have leading dimension {num_nodes} "
            f"(got {values.shape})")
    if values.ndim > 2:
        raise ValueError(
            f"values must be (N,) or (N, D) — got shape {values.shape}; "
            "flatten extra feature axes to one")


def init_state(
    topo: Topology, cfg: RoundConfig, seed: int = 0, values=None
) -> FlowUpdatingState:
    """Fresh state: zero flows/estimates (the reference's ``defaultdict(float)``
    semantics, ``flowupdating-collectall.py:33-34``), empty buffers.

    ``values`` may be ``(N,)`` (the scalar protocol, default
    ``topo.values``) or ``(N, D)`` — then every payload array carries the
    trailing feature axis (see module docstring)."""
    N, E, D = topo.num_nodes, topo.num_edges, cfg.delay_depth
    if D < topo.max_delay:
        raise ValueError(
            f"delay_depth={D} too small for topology max delay "
            f"{topo.max_delay} (need delay_depth >= max_delay)"
        )
    dt = cfg.jnp_dtype
    if values is None:
        values = topo.values
    values = jnp.asarray(values, dt)
    check_payload_values(values, N)
    F = feature_shape(values)
    return FlowUpdatingState(
        t=jnp.zeros((), jnp.int32),
        value=values,
        flow=jnp.zeros((E,) + F, dt),
        est=jnp.zeros((E,) + F, dt),
        recv=jnp.zeros((E,), bool),
        ticks=jnp.zeros((N,), jnp.int32),
        stamp=jnp.zeros((E,), jnp.int32),
        last_avg=jnp.zeros((N,) + F, dt),
        fired=jnp.zeros((N,), jnp.int32),
        alive=jnp.ones((N,), bool),
        edge_ok=jnp.ones((E,), bool),
        pending_flow=jnp.zeros((cfg.pending_depth, E) + F, dt),
        pending_est=jnp.zeros((cfg.pending_depth, E) + F, dt),
        pending_valid=jnp.zeros((cfg.pending_depth, E), bool),
        pending_stamp=jnp.zeros((cfg.pending_depth, E), jnp.int32),
        buf_flow=jnp.zeros((D, E) + F, dt),
        buf_est=jnp.zeros((D, E) + F, dt),
        buf_valid=jnp.zeros((D, E), bool),
        key=jax.random.PRNGKey(seed),
    )
