from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.state import FlowUpdatingState, init_state
from flow_updating_tpu.models.rounds import (
    round_step,
    run_rounds,
    deliver_phase,
    fire_phase,
    node_estimates,
)

__all__ = [
    "RoundConfig",
    "FlowUpdatingState",
    "init_state",
    "round_step",
    "run_rounds",
    "deliver_phase",
    "fire_phase",
    "node_estimates",
]
