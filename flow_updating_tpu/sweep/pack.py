"""Packing: shape-bucketed padding of sweep instances into batched arrays.

``jax.vmap`` needs every lane to share one shape, but a sweep's
topologies differ in N and E.  Instances are therefore padded to a
bucket shape ``(N_pad, E_pad)`` chosen by rounding each axis up to the
next power of two — topologies of similar size share one compile, wildly
different sizes never share a bucket (padding a ring-16 to a 100k-node
lane would waste the batch).

Padding must not perturb the protocol.  The ghost-node / pad-self-loop
construction (shared with the streaming service engine) lives in
:mod:`flow_updating_tpu.topology.padding`; the sweep uses the ``'even'``
ghost-spreading policy — pad self-loops spread evenly across the ghosts,
capping every row's degree and therefore the uniform row width W of the
batched reduction layout.  The packed layout is bit-exact-pinned by
tests/test_sweep.py: real edge arrays stay a bit-identical *prefix* of
the padded arrays, so per-node reductions over ``src``, gathers through
``rev`` and the ring-buffer update all compute exactly the unpadded
values on the real slice (the per-lane bit-exactness guarantee).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.models.config import RoundConfig, RoundParams
from flow_updating_tpu.models.state import (
    check_payload_values,
    init_state,
)
from flow_updating_tpu.topology.graph import Topology
from flow_updating_tpu.topology.padding import (
    bucket_ceil as _bucket_ceil,
    edge_rows as _shared_edge_rows,
    mask_ghost_state,
    masked_values,
    pad_topology_to as _shared_pad_topology_to,
    row_width,
)

__all__ = [
    "SweepBucket", "SweepInstance", "bucket_shape", "pack_instance",
    "pack_instances", "pad_topology_to", "row_width",
]


def pad_topology_to(topo: Topology, n_pad: int, e_pad: int) -> Topology:
    """Sweep-layout padding: the shared ghost/pad construction with the
    historical even ghost spreading (see topology/padding.py)."""
    return _shared_pad_topology_to(topo, n_pad, e_pad, spread="even")


def bucket_shape(topo: Topology, n_min: int = 8,
                 e_min: int = 16) -> tuple[int, int]:
    """The padded ``(N_pad, E_pad)`` bucket an instance lands in:
    eighth-pow2 rounding of ``N + 1`` / ``E + 1`` (always at least one
    ghost node and one pad edge, so the padding invariants are exercised
    uniformly), floored so tiny instances coalesce."""
    n_pad = max(_bucket_ceil(topo.num_nodes + 1), n_min)
    e_pad = max(_bucket_ceil(topo.num_edges + 1), e_min)
    return n_pad, e_pad


@dataclasses.dataclass(frozen=True)
class SweepInstance:
    """One (topology, seed, params) point of a sweep grid.

    ``drop_rate`` / ``timeout`` / ``latency_scale`` / ``contention_scale``
    override the shared :class:`RoundConfig`'s numeric knobs for this
    instance only (they become the lane's traced :class:`RoundParams`);
    ``None`` inherits the config value.  ``values`` optionally replaces
    the topology's node values (``(N,)`` or ``(N, D)``); ``tag`` is
    free-form grid metadata echoed into the sweep manifest record.

    ``adversary`` (optional) is a device-side Byzantine fault spec (an
    :class:`~flow_updating_tpu.scenarios.adversary.Adversary`, or any
    object with ``device_leaves(n_pad, e_pad, dtype)`` /
    ``structure_key()``): its mask leaves are padded to the bucket shape
    and stacked per lane, so one compiled bucket program serves
    adversarial and honest lanes alike — but only lanes whose adversary
    STRUCTURE matches share a bucket (a None-mask lane would otherwise
    split the vmapped treedef)."""

    topo: Topology
    seed: int = 0
    drop_rate: float | None = None
    timeout: int | None = None
    latency_scale: float | None = None
    contention_scale: float | None = None
    values: object | None = None
    adversary: object | None = None
    tag: dict = dataclasses.field(default_factory=dict)

    def params(self, cfg: RoundConfig) -> RoundParams:
        return RoundParams.from_config(
            cfg, drop_rate=self.drop_rate, timeout=self.timeout,
            latency_scale=self.latency_scale,
            contention_scale=self.contention_scale)

    def true_mean(self):
        """Per-instance convergence target: mean over REAL nodes of the
        values this lane actually aggregates (scalar, or ``(D,)`` for
        vector payloads)."""
        if self.values is None:
            return self.topo.true_mean
        vals = np.asarray(self.values)
        return vals.mean(axis=0)


@dataclasses.dataclass
class SweepBucket:
    """One packed batch: stacked state/arrays/params with leading axis B
    plus the host-side per-instance bookkeeping."""

    shape: tuple          # (N_pad, E_pad) + feature shape
    states: object        # FlowUpdatingState, every leaf (B, ...)
    arrays: object        # TopoArrays, every array leaf (B, ...)
    params: RoundParams   # every leaf (B,)
    means: object         # (B,) or (B, D) convergence targets
    n_real: np.ndarray    # (B,) real node counts
    e_real: np.ndarray    # (B,) real directed-edge counts
    meta: list            # per-instance manifest records (dicts)

    @property
    def size(self) -> int:
        return len(self.meta)


def _validate_cfg(cfg: RoundConfig) -> None:
    if cfg.kernel != "edge":
        raise ValueError(
            "the sweep engine batches the edge kernel (per-edge state "
            "vmaps over lanes); kernel='node' collapses state per "
            "topology structure — use kernel='edge'")
    if cfg.delivery not in ("gather", "scatter"):
        raise ValueError(
            f"sweep buckets run delivery='gather'|'scatter'; "
            f"{cfg.delivery!r} plans a per-topology permutation network "
            "(static masks cannot batch across instances)")
    if cfg.segment_impl not in ("auto", "segment"):
        raise ValueError(
            f"sweep buckets run segment_impl='auto'|'segment'; "
            f"{cfg.segment_impl!r} builds per-topology layouts that do "
            "not batch")
    if cfg.contention:
        raise ValueError(
            "contention needs per-topology link route tables, which do "
            "not batch; sweep latency effects go through "
            "RoundParams.latency_scale instead")


# the sweep's (N_pad, W) out-edge row matrix is the shared construction
# (topology/padding.edge_rows); kept under the historical private name —
# tests and bench import it from here
_edge_rows = _shared_edge_rows


def pack_instance(inst: SweepInstance, cfg: RoundConfig,
                  n_pad: int, e_pad: int, width: int | None = None,
                  static_no_drop: bool = False):
    """Pad + build one lane: returns ``(state, arrays, params)`` device
    trees (unstacked) for the given bucket shape.  ``width`` is the
    bucket-wide uniform row width (defaults to this instance's own);
    ``static_no_drop`` omits the Bernoulli drop draw from the program
    (set when NO lane of the bucket drops messages)."""
    import jax.numpy as jnp

    padded = pad_topology_to(inst.topo, n_pad, e_pad)
    arrays = padded.device_arrays(coloring=cfg.needs_coloring)
    width = row_width(inst.topo, n_pad, e_pad) if width is None else width
    arrays = arrays.replace(
        sweep_edge_rows=jnp.asarray(_edge_rows(padded, width, e_pad)))
    if cfg.needs_coloring:
        # the color count moves into a traced scalar so lanes with
        # different counts share one treedef (and one compile)
        arrays = arrays.replace(
            num_colors=0,
            num_colors_arr=jnp.asarray(arrays.num_colors, jnp.int32))
    if inst.adversary is not None:
        # Byzantine mask leaves, padded to the bucket shape (ghost slots
        # never lie/corrupt/drop — they are dead and edge-failed anyway)
        arrays = arrays.replace(**inst.adversary.device_leaves(
            n_pad, e_pad, cfg.jnp_dtype))
    values = None
    if inst.values is not None:
        vals = np.asarray(inst.values, np.float64)
        check_payload_values(vals, inst.topo.num_nodes)
        values = masked_values(vals, n_pad)
    state = init_state(padded, cfg, seed=inst.seed, values=values)
    state = mask_ghost_state(state, inst.topo.num_nodes,
                             inst.topo.num_edges)
    params = inst.params(cfg)
    if static_no_drop:
        params = params.without_drop()
    return state, arrays, params


def pack_instances(instances, cfg: RoundConfig,
                   max_batch: int | None = None,
                   n_min: int = 8, e_min: int = 16) -> list[SweepBucket]:
    """Bucket + pad + stack ``instances`` into :class:`SweepBucket`\\ s.

    Instances are grouped by ``(bucket_shape, feature_shape)``; each
    group is split into chunks of at most ``max_batch`` lanes.  Bucket
    order and lane order within a bucket follow the input order, so the
    manifest's instance records stay aligned with the grid fan-out.
    """
    import jax

    from flow_updating_tpu.utils.checkpoint import topology_fingerprint

    if max_batch is not None and max_batch < 1:
        raise ValueError(f"max_batch must be >= 1 (got {max_batch}); "
                         "pass None for unbounded buckets")
    _validate_cfg(cfg)
    groups: dict = {}
    order: list = []
    for idx, inst in enumerate(instances):
        feat = (() if inst.values is None
                else np.asarray(inst.values).shape[1:])
        # the adversary's structure key is part of the bucket identity:
        # its mask leaves are pytree STRUCTURE, so a lie-mask lane and a
        # mask-free lane cannot stack into one vmapped treedef (and would
        # not share a compile anyway); an all-empty adversary emits zero
        # leaves, so it merges with the adversary-free lanes (truthiness,
        # matching Adversary.__bool__)
        adv = (inst.adversary.structure_key()
               if inst.adversary else None)
        shape = bucket_shape(inst.topo, n_min=n_min, e_min=e_min) + feat
        key = (shape, adv)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append((idx, inst))

    buckets = []
    for key in order:
        members = groups[key]
        shape = key[0]
        n_pad, e_pad = shape[0], shape[1]
        step = max_batch or len(members)
        for lo in range(0, len(members), step):
            chunk = members[lo: lo + step]
            width = max(row_width(inst.topo, n_pad, e_pad)
                        for _, inst in chunk)
            # a bucket where NO lane drops messages omits the Bernoulli
            # draw from its compiled program (pytree structure, so the
            # whole bucket must agree)
            no_drop = all(
                (inst.drop_rate if inst.drop_rate is not None
                 else cfg.drop_rate) == 0.0 for _, inst in chunk)
            lanes = [pack_instance(inst, cfg, n_pad, e_pad, width=width,
                                   static_no_drop=no_drop)
                     for _, inst in chunk]
            states = jax.tree.map(lambda *xs: jax.numpy.stack(xs),
                                  *[ln[0] for ln in lanes])
            arrays = jax.tree.map(lambda *xs: jax.numpy.stack(xs),
                                  *[ln[1] for ln in lanes])
            params = jax.tree.map(lambda *xs: jax.numpy.stack(xs),
                                  *[ln[2] for ln in lanes])
            means = jax.numpy.stack(
                [jax.numpy.asarray(inst.true_mean(), cfg.jnp_dtype)
                 for _, inst in chunk])
            meta = []
            for (idx, inst), (_, _, p) in zip(chunk, lanes):
                rec = {
                    "instance": idx,
                    "seed": int(inst.seed),
                    "topology": topology_fingerprint(inst.topo),
                    "params": inst.params(cfg).describe(),
                    "padded_shape": [int(n_pad), int(e_pad)],
                }
                if inst.tag:
                    rec["tag"] = dict(inst.tag)
                meta.append(rec)
            buckets.append(SweepBucket(
                shape=shape,
                states=states,
                arrays=arrays,
                params=params,
                means=means,
                n_real=np.asarray([i.topo.num_nodes for _, i in chunk]),
                e_real=np.asarray([i.topo.num_edges for _, i in chunk]),
                meta=meta,
            ))
    return buckets
