"""Vmapped bucket execution: one compile, B instances per round.

The edge kernel (:func:`~flow_updating_tpu.models.rounds.round_step_aux`)
and its telemetry sampler run unchanged under ``jax.vmap`` over the
bucket's leading batch axis — state, topology arrays AND the traced
:class:`~flow_updating_tpu.models.config.RoundParams` all carry one lane
per instance, so a single XLA program serves every (topology, seed,
drop_rate, timeout, ...) combination in the bucket.  ``cfg`` stays the
jit-static program selector shared by the whole bucket.

Convergence is tracked per lane *inside* the scan: a lane whose
alive-masked RMSE first drops to ``rmse_threshold`` records that round as
its effective early-exit round and keeps ticking (lock-step lanes cannot
exit individually — but the sweep report and the bench's effective-rounds
accounting use the recorded exit, so a converged lane stops counting).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.models.rounds import (
    round_step_aux,
    telemetry_sample,
)


@functools.partial(jax.jit, static_argnames=("cfg", "num_rounds"))
def _run_bucket(states, arrays, params, cfg, num_rounds):
    step = jax.vmap(
        lambda s, a, p: round_step_aux(s, a, cfg, params=p)[0])

    def body(ss, _):
        return step(ss, arrays, params), None

    states, _ = jax.lax.scan(body, states, None, length=num_rounds)
    return states


def run_bucket(bucket, cfg, num_rounds: int):
    """Advance every lane of ``bucket`` by ``num_rounds`` rounds as ONE
    compiled vmapped scan; returns the stacked final states."""
    return _run_bucket(bucket.states, bucket.arrays, bucket.params, cfg,
                       num_rounds)


@functools.partial(
    jax.jit, static_argnames=("cfg", "num_rounds", "spec"))
def _run_bucket_telemetry(states, arrays, params, means, threshold, cfg,
                          num_rounds, spec):
    sample_one = lambda s, a, m, pr, sn: telemetry_sample(
        s, a, spec, m, pr, sn)
    step = jax.vmap(lambda s, a, p: round_step_aux(s, a, cfg, params=p))
    vsample = jax.vmap(sample_one)

    def body(carry, _):
        ss, conv = carry
        ss, processed, send_mask = step(ss, arrays, params)
        sample = vsample(ss, arrays, means, processed, send_mask)
        newly = (conv < 0) & (sample["rmse"] <= threshold)
        conv = jnp.where(newly, ss.t, conv)
        return (ss, conv), sample

    conv0 = jnp.full(means.shape[:1], -1, jnp.int32)
    (states, conv), series = jax.lax.scan(
        body, (states, conv0), None, length=num_rounds)
    return states, conv, series


def bucket_program(bucket, cfg, num_rounds: int, spec,
                   rmse_threshold: float = 0.0):
    """``(jitted_fn, full_args, n_dynamic)`` for the bucket's vmapped
    telemetry scan — the AOT cost-attribution hook (``sweep --profile``
    attaches one record per bucket to the sweep manifest).  The same
    function/argument split :func:`run_bucket_telemetry` dispatches, so
    the profiled executable IS the bucket's program."""
    mean_dt = cfg.jnp_dtype
    return (_run_bucket_telemetry,
            (bucket.states, bucket.arrays, bucket.params,
             jnp.asarray(bucket.means, mean_dt),
             jnp.asarray(rmse_threshold, mean_dt), cfg, num_rounds, spec),
            5)


def run_bucket_telemetry(bucket, cfg, num_rounds: int, spec,
                         rmse_threshold: float = 0.0):
    """One compiled vmapped scan with per-round, per-lane telemetry.

    Returns ``(states, converged_round, series)``:

    * ``states`` — stacked final states (every lane ran the full
      ``num_rounds``; converged lanes keep ticking);
    * ``converged_round`` — ``(B,)`` int32, the round at which each
      lane's alive-masked RMSE first reached ``rmse_threshold`` (its
      effective early-exit round), or -1 if it never did;
    * ``series`` — ``{metric: (B, R, ...) numpy}`` per-instance series
      (the scan's ``(R, B)`` ys transposed lane-major for reporting).

    ``spec`` must include ``rmse`` — convergence tracking reads it from
    the sampled row (the sampler computes each reduction once).
    """
    if not spec.enabled or not spec.has("rmse"):
        raise ValueError(
            "run_bucket_telemetry needs a TelemetrySpec that includes "
            "'rmse' (convergence tracking reads the sampled rmse row)")
    mean_dt = cfg.jnp_dtype
    thr = jnp.asarray(rmse_threshold, mean_dt)
    states, conv, series = _run_bucket_telemetry(
        bucket.states, bucket.arrays, bucket.params,
        jnp.asarray(bucket.means, mean_dt), thr, cfg, num_rounds, spec)
    host = {}
    for k, v in series.items():
        arr = np.asarray(v)           # (R, B, ...) scan-major
        host[k] = np.swapaxes(arr, 0, 1) if arr.ndim > 1 else arr
    return states, np.asarray(conv), host
