"""Batched sweep engine: many (topology, seed, params) instances per XLA
program.

The reference paper's evaluation is a grid — topologies x loss rates x
timeouts — but one instance per program leaves dense hardware idle on
small graphs and recompiles per grid point.  This subsystem packs B
instances into ONE compiled computation:

* :mod:`flow_updating_tpu.sweep.pack` — shape-bucketed padding: instances
  are padded to a shared ``(N_pad, E_pad)`` with mass-neutral ghost nodes
  and masked self-loop edges, then stacked into batched device arrays;
* :mod:`flow_updating_tpu.sweep.batch` — vmapped execution: the edge
  kernel and its telemetry sampler run under ``jax.vmap`` over the batch
  axis, with traced per-instance :class:`~flow_updating_tpu.models.config.
  RoundParams` so one compile serves a whole parameter grid, plus
  per-instance convergence tracking (converged lanes keep ticking but
  report their effective early-exit round);
* :mod:`flow_updating_tpu.sweep.runner` — grid fan-out, bucket
  orchestration and the ``flow-updating-sweep-report/v1`` manifest (one
  record per instance).

See docs/SWEEP.md for packing rules, the static-vs-traced config table
and CLI examples (``flow-updating-tpu sweep ...``).
"""

from flow_updating_tpu.sweep.pack import (
    SweepBucket,
    SweepInstance,
    bucket_shape,
    pack_instances,
    pad_topology_to,
)
from flow_updating_tpu.sweep.batch import run_bucket, run_bucket_telemetry
from flow_updating_tpu.sweep.runner import grid_instances, run_sweep

__all__ = [
    "SweepBucket",
    "SweepInstance",
    "bucket_shape",
    "pack_instances",
    "pad_topology_to",
    "run_bucket",
    "run_bucket_telemetry",
    "grid_instances",
    "run_sweep",
]
