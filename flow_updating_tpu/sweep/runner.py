"""Sweep driving: grid fan-out, bucket orchestration, manifest assembly.

A sweep is a cross product — topologies x seeds x parameter values — run
through the packing (:mod:`flow_updating_tpu.sweep.pack`) and batched
execution (:mod:`flow_updating_tpu.sweep.batch`) layers, reduced to one
record per instance and bound into a single self-describing
``flow-updating-sweep-report/v1`` manifest (the sweep-shaped sibling of
the run manifest, same :mod:`flow_updating_tpu.obs.report` plumbing).
"""

from __future__ import annotations

import time

import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.sweep.batch import run_bucket_telemetry
from flow_updating_tpu.sweep.pack import SweepInstance, pack_instances


def grid_instances(topos, seeds=(0,), drop_rates=(None,),
                   timeouts=(None,), latency_scales=(None,)) -> list:
    """Fan a parameter grid out to :class:`SweepInstance`\\ s.

    ``topos`` is a list of ``(name, Topology)`` pairs (the name lands in
    each instance's tag); the remaining axes cross-multiply.  ``None``
    grid values inherit the shared config's knob."""
    instances = []
    for name, topo in topos:
        for seed in seeds:
            for dr in drop_rates:
                for to in timeouts:
                    for ls in latency_scales:
                        tag = {"topology": str(name), "seed": int(seed)}
                        if dr is not None:
                            tag["drop_rate"] = float(dr)
                        if to is not None:
                            tag["timeout"] = int(to)
                        if ls is not None:
                            tag["latency_scale"] = float(ls)
                        instances.append(SweepInstance(
                            topo=topo, seed=int(seed), drop_rate=dr,
                            timeout=to, latency_scale=ls, tag=tag))
    return instances


def _worst_offenders(states, bucket, top: int = 3) -> list:
    """Per-lane worst-offender summary: the ``top`` highest final
    per-node absolute errors of each instance, alive-masked (dead ghost
    padding never ranks).  One vmapped ``node_estimates`` over the final
    packed states — no extra rounds, no per-node series; the
    topology-resolved deep dive belongs to ``inspect --fields``."""
    import jax

    from flow_updating_tpu.models.rounds import node_estimates

    est = np.asarray(jax.vmap(node_estimates)(states, bucket.arrays))
    means = np.asarray(bucket.means)
    m = (means.reshape((-1,) + (1,) * (est.ndim - 1))
         if means.ndim == 1 else means[:, None])   # (B, 1[, D])
    err = np.abs(est - m)
    if err.ndim > 2:
        err = err.max(axis=tuple(range(2, err.ndim)))
    err = np.where(np.asarray(states.alive), err, -np.inf)
    out = []
    for lane in range(err.shape[0]):
        order = np.argsort(-err[lane])[:top]
        out.append([
            {"node": int(i), "abs_err": float(err[lane, i])}
            for i in order if np.isfinite(err[lane, i])
        ])
    return out


def run_sweep(instances, cfg: RoundConfig, rounds: int, spec=None,
              rmse_threshold: float = 1e-6, max_batch: int | None = None,
              include_series: bool = False, profile: bool = False):
    """Pack ``instances``, run every bucket, reduce to per-instance
    records.

    Returns ``(records, summary)``: ``records`` is one dict per instance
    (input order) — topology fingerprint, seed, params, convergence
    (effective early-exit round, final/min rmse) and, when
    ``include_series``, the per-round metric series; ``summary`` carries
    sweep-level aggregates (bucket shapes = compile count, wall time,
    converged count).

    ``profile=True`` AOT-compiles each bucket's vmapped program once
    more through the cost-attribution layer (obs/profile.py) and
    attaches flops / bytes / peak-memory / compile-wall per bucket to
    ``summary['buckets']`` — the per-bucket attribution the sweep
    manifest records.  The execution split comes from the real run
    (``run_s`` per bucket), so attribution never re-runs the sweep.
    """
    from flow_updating_tpu.obs.telemetry import TelemetrySpec

    instances = list(instances)
    spec = TelemetrySpec.default() if spec is None else spec
    spec = spec.for_kernel("edge")
    if not spec.has("rmse"):
        raise ValueError(
            "sweep telemetry needs 'rmse' for convergence tracking "
            "(the 'default' spec includes it)")
    t0 = time.perf_counter()
    buckets = pack_instances(instances, cfg, max_batch=max_batch)
    pack_s = time.perf_counter() - t0

    bucket_profiles: list = []
    if profile:
        from flow_updating_tpu.obs.profile import per_round, profile_program
        from flow_updating_tpu.sweep.batch import bucket_program

        for bucket in buckets:
            fn, args, nd = bucket_program(bucket, cfg, rounds, spec,
                                          rmse_threshold=rmse_threshold)
            rec = profile_program(fn, args, n_dynamic=nd, execute=False,
                                  label=f"bucket{bucket.shape}")
            rec["per_round"] = per_round(rec, rounds)
            bucket_profiles.append(rec)

    records: list = [None] * len(instances)
    converged = 0
    bucket_run_s: list = []
    t0 = time.perf_counter()
    for bucket in buckets:
        tb0 = time.perf_counter()
        _states, conv, series = run_bucket_telemetry(
            bucket, cfg, rounds, spec, rmse_threshold=rmse_threshold)
        bucket_run_s.append(round(time.perf_counter() - tb0, 6))
        worst = _worst_offenders(_states, bucket)
        for lane, meta in enumerate(bucket.meta):
            rmse_series = series["rmse"][lane]
            rec = dict(meta)
            rec["convergence"] = {
                "rounds": int(rounds),
                "converged_round": int(conv[lane]),
                "converged": bool(conv[lane] >= 0),
                "rmse_threshold": float(rmse_threshold),
                "final_rmse": float(rmse_series[-1]) if rounds else None,
                "min_rmse": float(rmse_series.min()) if rounds else None,
            }
            rec["worst_nodes"] = worst[lane]
            if conv[lane] >= 0:
                converged += 1
            if include_series:
                rec["series"] = {k: np.asarray(v[lane]).tolist()
                                 for k, v in series.items()}
            records[meta["instance"]] = rec
    run_s = time.perf_counter() - t0

    # a compile is keyed by the full traced structure, not just the
    # bucket shape: lane count and row width (both visible in
    # sweep_edge_rows' (B, N_pad, W) shape), payload feature shape
    # (means), the statically-absent drop leaf, and which adversary mask
    # families are present (scenarios/) all split the cache
    compile_keys = {
        (np.shape(np.asarray(b.arrays.sweep_edge_rows)),
         np.shape(np.asarray(b.means)),
         b.params.drop_rate is None,
         tuple(getattr(b.arrays, leaf) is not None
               for leaf in ("adv_lie_mask", "adv_corrupt_mask",
                            "adv_silent_mask", "adv_down_mask")))
        for b in buckets}
    bucket_rows = []
    for i, b in enumerate(buckets):
        row = {"shape": list(map(int, b.shape)), "size": b.size,
               "run_s": bucket_run_s[i]}
        if bucket_profiles:
            row["profile"] = bucket_profiles[i]
        bucket_rows.append(row)
    summary = {
        "instances": len(records),
        "buckets": bucket_rows,
        "compiled_programs": len(compile_keys),
        "rounds": int(rounds),
        "converged": converged,
        "timings": {"pack_s": round(pack_s, 6), "run_s": round(run_s, 6)},
    }
    return records, summary
