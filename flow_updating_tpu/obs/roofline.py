"""Backend roofline models + predicted-vs-measured reconciliation.

The perf lens (docs/OBSERVABILITY.md): every *predicted* cost the repo
computes (XLA ``cost_analysis`` flops / bytes in ``obs/profile.py``,
static wire budgets in ``analysis/budget.py``) and every *measured*
rate it banks (bench baseline rows, autotune probes, serve qps) meet
here.  A :class:`HardwareModel` declares what the backend can move and
compute per second; :func:`analyze` composes it with a
``profile_program`` record into arithmetic intensity, the binding
resource (HBM / compute / wire) and a predicted floor time per round;
:func:`reconcile` divides a measured rate by the predicted ceiling into
``roofline_frac`` — the fraction of the roofline the measurement
achieved, which MUST land in (0, 1]: a frac above 1 means the model or
the measurement is lying (doctor clause ``roofline_sane``), and a frac
below the per-mode floor without a pinned known discrepancy means the
implementation leaves declared hardware on the table (doctor clause
``roofline_floor``).

Model provenance, two kinds:

* **declared** — known TPU generations carry approximate public
  HBM / VPU / MXU / ICI figures.  They are *ceilings for reconciliation*,
  deliberately generous (an optimistic ceiling keeps ``roofline_sane``
  honest: measured can approach it, never beat it).
* **measured** — the CPU proxy has no published roofline, so it is
  calibrated once per machine with a STREAM-style triad (memory
  bandwidth) and a chained-FMA probe (vector flops), both single-thread
  rates scaled by the core count XLA:CPU's intra-op pool can recruit —
  again a ceiling, not an expectation.  The calibration persists beside
  the autotune cache (same directory as
  ``plan.select.autotune_cache_path()``; override with
  ``FLOW_UPDATING_ROOFLINE_CACHE``) so one probe serves every later
  session on the machine, mirroring the autotune cache-hit contract.

This module is pure host-side observation: importable without jax,
never touches lowering, and the lens off is byte-identical lowering +
bit-exact state (tests/test_perf_lens.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time

#: opt-in switch for the call sites that would otherwise pay extra
#: lowering (autotune probe annotation): off by default, the lens must
#: never slow a plain run
ROOFLINE_ENV = "FLOW_UPDATING_ROOFLINE"

#: calibration-record override (tests point it at a tmpdir); the
#: default lives beside autotune.json — one probe per machine
ROOFLINE_CACHE_ENV = "FLOW_UPDATING_ROOFLINE_CACHE"

#: calibration record version: bump when the probe method changes so a
#: stale persisted record re-probes instead of silently mismatching
_CALIBRATION_VERSION = 1


@dataclasses.dataclass(frozen=True)
class HardwareModel:
    """What one chip of a backend can move and compute per second.

    ``hbm_gbps`` is main-memory stream bandwidth (GB/s), ``vpu_gflops``
    elementwise vector throughput (GFLOP/s, fp32 FMA — the resource this
    protocol's fire/merge passes spend), ``mxu_gflops`` dense matmul
    throughput (the ``spmv='dense'`` oracle only), ``ici_gbps``
    per-chip interconnect bandwidth (GB/s; 0 = no wire / host loopback).
    """

    name: str
    hbm_gbps: float
    vpu_gflops: float
    mxu_gflops: float
    ici_gbps: float
    source: str = "declared"
    notes: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


#: declared per-chip figures for known TPU generations (approximate
#: public numbers; VPU is an fp32 estimate biased HIGH — the ceiling
#: discipline above).  Keys are matched as substrings of the lowered
#: jax ``device_kind`` (e.g. "TPU v5 lite").
TPU_MODELS: dict[str, HardwareModel] = {
    "v2": HardwareModel("tpu-v2", hbm_gbps=700.0, vpu_gflops=3_000.0,
                        mxu_gflops=45_000.0, ici_gbps=62.0),
    "v3": HardwareModel("tpu-v3", hbm_gbps=900.0, vpu_gflops=5_000.0,
                        mxu_gflops=123_000.0, ici_gbps=82.0),
    "v4": HardwareModel("tpu-v4", hbm_gbps=1_228.0, vpu_gflops=8_000.0,
                        mxu_gflops=275_000.0, ici_gbps=300.0),
    "v5 lite": HardwareModel("tpu-v5e", hbm_gbps=819.0,
                             vpu_gflops=6_000.0, mxu_gflops=197_000.0,
                             ici_gbps=200.0),
    "v5e": HardwareModel("tpu-v5e", hbm_gbps=819.0, vpu_gflops=6_000.0,
                         mxu_gflops=197_000.0, ici_gbps=200.0),
    "v5p": HardwareModel("tpu-v5p", hbm_gbps=2_765.0,
                         vpu_gflops=12_000.0, mxu_gflops=459_000.0,
                         ici_gbps=600.0),
    "v6 lite": HardwareModel("tpu-v6e", hbm_gbps=1_640.0,
                             vpu_gflops=15_000.0, mxu_gflops=918_000.0,
                             ici_gbps=448.0),
    "v6e": HardwareModel("tpu-v6e", hbm_gbps=1_640.0,
                         vpu_gflops=15_000.0, mxu_gflops=918_000.0,
                         ici_gbps=448.0),
}

#: per-mode roofline_frac floors for the ``roofline_floor`` doctor
#: clause: (mode regex, min frac).  First match wins; modes below their
#: floor FAIL unless a KNOWN_DISCREPANCIES entry pins them.  The floors
#: are deliberately loose — they catch catastrophic lying (a fused
#: kernel silently falling back to a gather path, a model declared 100x
#: wrong), not tuning headroom.
FLOOR_FRACS: tuple = (
    (r"^serve", 5e-4),          # fabric rounds ride host orchestration
    (r"^autotune", 5e-4),       # probe scale is launch-overhead bound
    (r"^halo", 5e-4),           # sharded rounds ride collective
                                # rendezvous the zero-ICI CPU-proxy
                                # wire term cannot floor
    (r"^edge", 1e-3),           # the reference edge kernel is the
                                # faithfulness oracle, not a tuned
                                # kernel: its floor catches collapse,
                                # not its honest distance from the roof
    (r".*", 2e-3),
)

#: the fallback floor when no pattern matches (unreachable with the
#: catch-all above; kept for callers composing their own tables)
DEFAULT_FLOOR_FRAC = 2e-3

#: pinned predicted-vs-measured discrepancies the repo knows about and
#: accepts: ``roofline_floor`` reports a below-floor frac on a matching
#: mode as KNOWN instead of failing.  The sharded one-kernel banded
#: round re-runs the full band pass after the DMA wait (~2x VPU work,
#: ROADMAP item "needless recompute"); the record here mirrors
#: ``parallel.banded_sharded.ROOFLINE_KNOWN_DISCREPANCY`` and
#: tests/test_perf_lens.py pins the two equal.
KNOWN_DISCREPANCIES: tuple = (
    {
        "name": "banded_sharded_recompute",
        "mode_re": r"banded_fused.*@s(?:[2-9]|\d{2,})",
        "factor": 2.0,
        "reason": ("sharded fused banded round recomputes the full band "
                   "pass after the remote-DMA wait (~2x VPU work) "
                   "instead of re-accumulating only boundary rows — "
                   "parallel/banded_sharded.py, ROADMAP item 1"),
    },
)


def known_discrepancy(mode: str | None) -> dict | None:
    """The pinned discrepancy record covering ``mode``, or None."""
    if not mode:
        return None
    for rec in KNOWN_DISCREPANCIES:
        if re.search(rec["mode_re"], str(mode)):
            return rec
    return None


def floor_frac(mode: str | None) -> float:
    """The ``roofline_floor`` threshold for ``mode`` (first regex
    match in :data:`FLOOR_FRACS` wins)."""
    for pat, frac in FLOOR_FRACS:
        if re.search(pat, str(mode or "")):
            return frac
    return DEFAULT_FLOOR_FRAC


# ---- CPU-proxy calibration ---------------------------------------------


def roofline_cache_path() -> str:
    """Where the CPU calibration record persists — beside the autotune
    cache (same directory as ``plan.select.autotune_cache_path()``;
    the path logic is duplicated, not imported, so this module stays
    importable without jax — tests pin the directories equal)."""
    env = os.environ.get(ROOFLINE_CACHE_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "flow_updating_tpu", "roofline_cpu.json")


def _measure_cpu(seconds: float = 0.12) -> dict:
    """STREAM-style single-thread probes: triad bandwidth over arrays
    far beyond LLC, chained FMA flops over an L2-resident array with
    preallocated outputs (no temporaries — the probe times arithmetic,
    not the allocator)."""
    import numpy as np

    n_big = 1 << 22                       # 3 x 16 MiB fp32: past LLC
    rng = np.random.default_rng(0)
    a = np.empty(n_big, np.float32)
    b = rng.random(n_big).astype(np.float32)
    c = rng.random(n_big).astype(np.float32)
    # triad a = b + s*c moves 3 arrays per pass (STREAM accounting)
    reps, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        np.multiply(c, np.float32(1.0001), out=a)
        np.add(a, b, out=a)
        reps += 1
    triad_s = (time.perf_counter() - t0) / max(reps, 1)
    bytes_per_pass = 3 * 4 * n_big
    bw = bytes_per_pass / max(triad_s, 1e-9)

    n_small = 1 << 16                     # 256 KiB fp32: cache-resident
    x = rng.random(n_small).astype(np.float32)
    y = rng.random(n_small).astype(np.float32)
    z = rng.random(n_small).astype(np.float32)
    t = np.empty(n_small, np.float32)
    reps, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        for _ in range(16):               # amortize the Python/ufunc call
            np.multiply(x, y, out=t)
            np.add(t, z, out=t)
        reps += 16
    fma_s = (time.perf_counter() - t0) / max(reps, 1)
    fl = 2.0 * n_small / max(fma_s, 1e-9)
    return {"stream_gbps_1t": bw / 1e9, "fma_gflops_1t": fl / 1e9,
            "triad_elems": n_big, "fma_elems": n_small}


def calibrate_cpu(*, force: bool = False, path: str | None = None,
                  threads: int | None = None) -> HardwareModel:
    """The CPU-proxy model: load the persisted calibration record if
    one exists for this probe version, else run the probes and persist
    it (atomic tmp + replace, the autotune-cache discipline).  The
    single-thread rates scale by ``threads`` (default: the machine's
    core count — the pool XLA:CPU can recruit), which biases the
    ceiling HIGH: perfect scaling is unreachable, so ``roofline_frac``
    stays honestly below 1."""
    p = path or roofline_cache_path()
    nthreads = threads if threads is not None else (os.cpu_count() or 1)
    rec = None
    if not force:
        try:
            with open(p) as fh:
                doc = json.load(fh)
            if isinstance(doc, dict) \
                    and doc.get("version") == _CALIBRATION_VERSION:
                rec = doc
        except (OSError, ValueError):
            rec = None
    if rec is None:
        rec = {"version": _CALIBRATION_VERSION, **_measure_cpu()}
        d = os.path.dirname(p)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{p}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(rec, fh, indent=1, sort_keys=True)
            os.replace(tmp, p)
        except OSError:
            pass                          # read-only FS: calibrate-only
    return HardwareModel(
        name="cpu-proxy",
        hbm_gbps=rec["stream_gbps_1t"] * nthreads,
        vpu_gflops=rec["fma_gflops_1t"] * nthreads,
        mxu_gflops=rec["fma_gflops_1t"] * nthreads,
        ici_gbps=0.0,
        source="measured",
        notes=(f"STREAM triad {rec['stream_gbps_1t']:.2f} GB/s + "
               f"chained FMA {rec['fma_gflops_1t']:.2f} GFLOP/s per "
               f"thread, x{nthreads} threads (ceiling bias)"),
    )


def model_for_device_kind(device_kind: str) -> HardwareModel | None:
    """Match a jax ``device_kind`` string against the TPU registry —
    longest key wins so 'v5 lite' beats 'v5'."""
    kind = str(device_kind).lower()
    best = None
    for key, model in TPU_MODELS.items():
        if key in kind and (best is None or len(key) > len(best[0])):
            best = (key, model)
    return best[1] if best else None


def resolve_model(device=None) -> HardwareModel:
    """The model for the ambient (or given) jax device: a declared TPU
    generation, or the measured CPU-proxy calibration."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    platform = getattr(dev, "platform", "cpu")
    if platform in ("tpu", "axon"):
        model = model_for_device_kind(getattr(dev, "device_kind", ""))
        if model is not None:
            return model
        # an unlisted generation still gets a ceiling: the newest
        # declared entry, flagged so doctor evidence shows the guess
        newest = TPU_MODELS["v6e"]
        return dataclasses.replace(
            newest, name=f"tpu-unknown({dev.device_kind})",
            notes="unlisted TPU generation; using the newest declared "
                  "model as the ceiling")
    return calibrate_cpu()


# ---- roofline math ------------------------------------------------------


def analyze(record: dict, model: HardwareModel, *,
            rounds: int | None = None, wire_bytes_per_round: float = 0.0,
            mode: str | None = None, compute_unit: str = "vpu") -> dict:
    """Compose one ``profile_program`` record with a hardware model:
    per-round arithmetic intensity, per-resource floor times, the
    binding resource and the predicted ceiling rate.

    ``compute_unit``: which compute roof applies — ``'vpu'`` for the
    elementwise fire/merge passes (every shipped kernel), ``'mxu'``
    only for the dense-matmul spmv oracle."""
    cost = record.get("cost") or {}
    flops, nbytes = cost.get("flops"), cost.get("bytes_accessed")
    r = max(int(rounds if rounds is not None
                else record.get("rounds") or 1), 1)
    out = {
        "mode": mode or record.get("mode") or record.get("label"),
        "model": model.name,
        "model_source": model.source,
        "compute_unit": compute_unit,
        "rounds": r,
    }
    if not isinstance(flops, (int, float)) \
            or not isinstance(nbytes, (int, float)) or nbytes <= 0:
        out.update({"error": "profile record carries no usable "
                    "flops/bytes_accessed cost analysis",
                    "floor_s_per_round": None,
                    "ceiling_rounds_per_sec": None})
        return out
    f_r, b_r = flops / r, nbytes / r
    w_r = max(float(wire_bytes_per_round), 0.0)
    rate_gflops = (model.vpu_gflops if compute_unit == "vpu"
                   else model.mxu_gflops)
    t_hbm = b_r / (model.hbm_gbps * 1e9) if model.hbm_gbps > 0 else 0.0
    t_compute = (f_r / (rate_gflops * 1e9)) if rate_gflops > 0 else 0.0
    t_wire = (w_r / (model.ici_gbps * 1e9)) if model.ici_gbps > 0 \
        and w_r > 0 else 0.0
    floors = {"hbm": t_hbm, "compute": t_compute, "wire": t_wire}
    binding = max(floors, key=lambda k: floors[k])
    floor = floors[binding]
    out.update({
        "flops_per_round": f_r,
        "bytes_per_round": b_r,
        "wire_bytes_per_round": w_r,
        "arithmetic_intensity": f_r / b_r,
        "t_hbm_s": t_hbm,
        "t_compute_s": t_compute,
        "t_wire_s": t_wire,
        "binding": binding,
        "floor_s_per_round": floor,
        "ceiling_rounds_per_sec": (1.0 / floor) if floor > 0 else None,
    })
    return out


def reconcile(roofline_rec: dict, measured_rounds_per_sec) -> dict:
    """Attach the measured rate and its ``roofline_frac`` (measured /
    predicted ceiling) to an :func:`analyze` record — THE frac every
    banked rate carries (bench rows, autotune probes, serve qps)."""
    out = dict(roofline_rec)
    ceiling = out.get("ceiling_rounds_per_sec")
    measured = (float(measured_rounds_per_sec)
                if isinstance(measured_rounds_per_sec, (int, float))
                else None)
    out["measured_rounds_per_sec"] = measured
    frac = (measured / ceiling
            if measured is not None and isinstance(ceiling, (int, float))
            and ceiling > 0 else None)
    out["roofline_frac"] = round(frac, 6) if frac is not None else None
    out["floor_frac"] = floor_frac(out.get("mode"))
    kd = known_discrepancy(out.get("mode"))
    out["known_discrepancy"] = kd["name"] if kd else None
    return out


def perf_lens_block(programs: list, model: HardwareModel, *,
                    calibration: dict | None = None,
                    extra: dict | None = None) -> dict:
    """Assemble the ``flow-updating-perf-lens/v1`` manifest block from
    reconciled program records (``doctor`` judges it via
    ``roofline_sane`` / ``roofline_floor``)."""
    from flow_updating_tpu.obs.report import PERF_LENS_SCHEMA

    block = {
        "schema": PERF_LENS_SCHEMA,
        "model": model.to_dict(),
        "programs": [dict(p) for p in programs],
        "known_discrepancies": [dict(d) for d in KNOWN_DISCREPANCIES],
    }
    if calibration is not None:
        block["calibration"] = dict(calibration)
    if extra:
        block.update(extra)
    return block


def _metric_slug(mode) -> str:
    return re.sub(r"[^a-zA-Z0-9]+", "_", str(mode or "unknown")).strip("_")


def export_metrics(registry, block: dict) -> None:
    """Surface a perf-lens block as MetricsRegistry gauges (rides the
    Prometheus text output every serving path already exports):
    ``roofline_frac_<mode>`` and ``roofline_ceiling_rps_<mode>``."""
    for prog in block.get("programs") or []:
        slug = _metric_slug(prog.get("mode"))
        frac = prog.get("roofline_frac")
        if isinstance(frac, (int, float)):
            registry.set_gauge(f"roofline_frac_{slug}", float(frac))
        ceil = prog.get("ceiling_rounds_per_sec")
        if isinstance(ceil, (int, float)):
            registry.set_gauge(f"roofline_ceiling_rps_{slug}",
                               float(ceil))


def enabled() -> bool:
    """The opt-in env switch for call sites that would otherwise pay
    extra lowering (the autotune probe annotation)."""
    return os.environ.get(ROOFLINE_ENV, "0") not in ("", "0")
