"""Streaming serving metrics: bounded counters/gauges/histograms.

The serving stack (ServiceEngine -> QueryFabric -> resilience ->
aggregates) runs indefinitely, so its metrics plane must be *streaming*:
every structure here is O(1) per observation and bounded in memory — a
monotone counter is one float, a gauge is one float, a histogram is a
fixed-window ring buffer of the most recent observations (quantiles are
computed over the window on demand, never stored per-sample forever),
and the per-boundary sample rows live in a bounded deque.  Everything is
host-side Python over values the boundary path already computes: zero
new device work, zero extra compiles (tests/test_serving_obs.py pins
``compile_count`` unchanged with the registry armed, and the golden
ledger pins the lowered program byte-identical with it off).

The registry is the black-box half of the flight recorder: its state
rides engine checkpoints (:meth:`MetricsRegistry.state_dict` under the
checkpoint's ``obs`` meta key) and WAL replay re-fires the increments,
so counters stay consistent with the manifest ground truth across a
SIGKILL + ``recover()`` — the doctor's ``metrics_consistency`` check
(obs/health.py) holds on a recovered fabric, not just a fresh one.

Export surfaces:

* :meth:`MetricsRegistry.to_prometheus` — the Prometheus text
  exposition format (``serve/query --metrics PATH``, ``bench --serve
  --metrics PATH``): counters, gauges, and histograms as summaries with
  p50/p95/p99 quantile lines;
* :meth:`MetricsRegistry.block` — the JSON block embedded in serving
  manifests under the ``flow-updating-serving-trace/v1`` schema
  (obs/report.py), judged by doctor and rendered by ``obs
  export-trace`` as Perfetto counter tracks (obs/trace.py).
"""

from __future__ import annotations

import math
from collections import deque

#: Ring-buffer window for histogram observations and boundary sample
#: rows: quantiles reflect the most recent ``window`` observations (a
#: streaming service cares about current latency, not the all-time
#: distribution); count/sum/max stay exact monotone accumulators.
DEFAULT_WINDOW = 4096

#: Quantiles exported by summaries — the SLO vocabulary (p95 is the
#: latency target doctor's ``slo_latency`` judges; docs/OBSERVABILITY.md).
QUANTILES = (0.5, 0.95, 0.99)


def _quantile(window, q: float) -> float:
    """Nearest-rank quantile over a histogram's ring-buffer window."""
    vals = sorted(window)
    if not vals:
        return float("nan")
    idx = min(len(vals) - 1, max(0, math.ceil(q * len(vals)) - 1))
    return float(vals[idx])


def _prom_name(name: str) -> str:
    """Prometheus metric-name sanitation ([a-zA-Z_:][a-zA-Z0-9_:]*)."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    return out if out and not out[0].isdigit() else "_" + out


class MetricsRegistry:
    """Bounded streaming counters, gauges, and windowed histograms.

    One registry per serving engine; observations are plain host floats.
    ``state_dict()``/``load_state()`` round-trip the full streaming
    state through checkpoint meta so a recovered engine's metrics plane
    is continuous with the pre-crash one.
    """

    def __init__(self, window: int = DEFAULT_WINDOW):
        self.window = int(window)
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        # name -> {"count", "sum", "max", "buf": deque(maxlen=window)}
        self._hists: dict[str, dict] = {}
        #: per-boundary gauge snapshots for counter-track rendering
        #: (obs export-trace); bounded like everything else
        self._samples: deque = deque(maxlen=self.window)

    # ---- write path ------------------------------------------------------

    def inc(self, name: str, value: float = 1.0) -> None:
        """Add ``value`` to a monotone counter (created at 0)."""
        self._counters[name] = self._counters.get(name, 0.0) + float(value)

    def set_counter(self, name: str, value: float) -> None:
        """Mirror an externally-accumulated monotone count (never
        lowered — a stale mirror must not rewind the counter)."""
        self._counters[name] = max(self._counters.get(name, 0.0),
                                   float(value))

    def set_gauge(self, name: str, value: float) -> None:
        self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        """Record one histogram observation (windowed quantiles)."""
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = {
                "count": 0, "sum": 0.0, "max": float("-inf"),
                "buf": deque(maxlen=self.window),
            }
        v = float(value)
        h["count"] += 1
        h["sum"] += v
        h["max"] = max(h["max"], v)
        h["buf"].append(v)

    def sample_row(self, t, **gauges) -> None:
        """One boundary snapshot: set each gauge and append a row to the
        bounded sample ring (the time axis of the counter tracks)."""
        for name, value in gauges.items():
            self.set_gauge(name, value)
        self._samples.append({"t": t, **{k: float(v)
                                         for k, v in gauges.items()}})

    # ---- read path -------------------------------------------------------

    def counter(self, name: str) -> float:
        return self._counters.get(name, 0.0)

    def gauge(self, name: str) -> float | None:
        return self._gauges.get(name)

    def histogram(self, name: str) -> dict | None:
        """Summary of one histogram: exact count/sum/max + windowed
        quantiles; None when nothing was observed."""
        h = self._hists.get(name)
        if h is None:
            return None
        out = {
            "count": int(h["count"]),
            "sum": float(h["sum"]),
            "max": float(h["max"]),
            "window_n": len(h["buf"]),
        }
        for q in QUANTILES:
            out[f"p{int(q * 100)}"] = _quantile(h["buf"], q)
        return out

    def block(self) -> dict:
        """The manifest-embeddable JSON block (serving-trace schema)."""
        return {
            "window": self.window,
            "counters": {k: self._counters[k]
                         for k in sorted(self._counters)},
            "gauges": {k: self._gauges[k] for k in sorted(self._gauges)},
            "histograms": {k: self.histogram(k)
                           for k in sorted(self._hists)},
            "samples": list(self._samples),
        }

    def to_prometheus(self, prefix: str = "fu") -> str:
        """Prometheus text exposition (v0.0.4): counters and gauges as
        single samples, histograms as summaries with quantile labels."""
        lines = []
        for name in sorted(self._counters):
            m = _prom_name(f"{prefix}_{name}")
            lines += [f"# TYPE {m} counter",
                      f"{m} {self._counters[name]:g}"]
        for name in sorted(self._gauges):
            m = _prom_name(f"{prefix}_{name}")
            lines += [f"# TYPE {m} gauge",
                      f"{m} {self._gauges[name]:g}"]
        for name in sorted(self._hists):
            m = _prom_name(f"{prefix}_{name}")
            h = self.histogram(name)
            lines.append(f"# TYPE {m} summary")
            for q in QUANTILES:
                v = h[f"p{int(q * 100)}"]
                if math.isfinite(v):
                    lines.append(f'{m}{{quantile="{q:g}"}} {v:g}')
            lines += [f"{m}_sum {h['sum']:g}",
                      f"{m}_count {h['count']}"]
        return "\n".join(lines) + ("\n" if lines else "")

    # ---- checkpoint ride -------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "window": self.window,
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "histograms": {k: {"count": h["count"], "sum": h["sum"],
                               "max": h["max"], "buf": list(h["buf"])}
                           for k, h in self._hists.items()},
            "samples": list(self._samples),
        }

    @classmethod
    def load_state(cls, state: dict) -> MetricsRegistry:
        reg = cls(window=int(state.get("window", DEFAULT_WINDOW)))
        reg._counters = {k: float(v)
                         for k, v in (state.get("counters") or {}).items()}
        reg._gauges = {k: float(v)
                       for k, v in (state.get("gauges") or {}).items()}
        for name, h in (state.get("histograms") or {}).items():
            reg._hists[name] = {
                "count": int(h["count"]), "sum": float(h["sum"]),
                "max": float(h["max"]),
                "buf": deque(h.get("buf") or [], maxlen=reg.window),
            }
        reg._samples.extend(state.get("samples") or [])
        return reg
