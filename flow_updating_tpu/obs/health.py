"""Rule-based run health: machine verdicts instead of eyeballed series.

Telemetry (PR 2) samples Flow-Updating's core invariants — mass
conservation and flow antisymmetry — and records convergence series, but
nothing *judges* them: a NaN'd run, a stalled RMSE plateau, or a slow
mass leak under churn is only visible by reading the curves.  This
module turns each of those into a check returning a
:class:`CheckResult` (``pass`` / ``warn`` / ``fail`` / ``skip`` with
evidence), and the ``doctor`` CLI subcommand runs them — live on a
fresh telemetry run, or offline on any saved
``flow-updating-*-report/v1`` manifest — with a CI-consumable exit
code.

Checks (each standalone; ``diagnose_series`` / ``diagnose_manifest``
bundle them):

* :func:`check_divergence` — NaN/Inf watchdog over every series plus
  runaway-RMSE detection (the estimate moving *away* from the mean);
* :func:`check_stall` — RMSE plateau above the convergence threshold
  (converged-flat is a pass; stuck-flat is the stall);
* :func:`check_mass_conservation` — |mass_residual| beyond what the
  dtype's float tolerance explains (the paper's invariant);
* :func:`check_antisymmetry` — max |flow[e] + flow[rev e]| beyond float
  tolerance (edge-ledger kernels only);
* :func:`check_environment` — backend sanity from a manifest's
  ``environment`` block (backend init failures, x64-vs-dtype mismatch);
* :func:`check_baselines` — recorded DES baselines violating the
  current :data:`SPREAD_VALIDITY_PCT` gate (entries written before the
  gate tightened; ``quarantined`` entries are acknowledged, not
  re-flagged).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from flow_updating_tpu.obs.report import SCHEMA as SCHEMA_RUN

PASS, WARN, FAIL, SKIP = "pass", "warn", "fail", "skip"

_ORDER = {SKIP: 0, PASS: 1, WARN: 2, FAIL: 3}

#: A recorded DES baseline whose min-max spread exceeds this percentage
#: of the mean is too noisy to divide a headline by.  Mirrored by
#: ``bench.SPREAD_VALIDITY_PCT`` (bench.py must stay importable without
#: jax in the parent process, so it cannot import this module at top
#: level); tests/test_doctor.py pins the two equal.
SPREAD_VALIDITY_PCT = 35.0


@dataclasses.dataclass
class CheckResult:
    """One check's verdict: machine-readable status + human evidence."""

    name: str
    status: str
    summary: str
    evidence: dict = dataclasses.field(default_factory=dict)

    def to_jsonable(self) -> dict:
        return dataclasses.asdict(self)


def overall(results) -> str:
    """The run's verdict: the worst individual status (skip < pass <
    warn < fail); ``skip`` if nothing ran."""
    results = list(results)
    if not results:
        return SKIP
    return max(results, key=lambda r: _ORDER[r.status]).status


def exit_code(results, strict: bool = False) -> int:
    """CI contract: 0 healthy, 1 on any ``fail`` (``warn`` too under
    ``strict``)."""
    worst = overall(results)
    if worst == FAIL or (strict and worst == WARN):
        return 1
    return 0


# ---- series access -------------------------------------------------------

def _get(series, name):
    """Uniform metric access over TelemetrySeries and plain dicts;
    None when the metric was not recorded."""
    if series is None:
        return None
    try:
        if name not in series:
            return None
        return np.asarray(series[name], dtype=np.float64)
    except TypeError:
        return None


def _metric_names(series) -> tuple:
    if series is None:
        return ()
    if hasattr(series, "metrics"):
        return tuple(series.metrics)
    return tuple(k for k in series if k != "t")


def _pooled(arr):
    """Per-feature series pooled to one value per round (worst feature
    magnitude) — invariant checks judge the worst offender."""
    a = np.asarray(arr, dtype=np.float64)
    return np.max(np.abs(a), axis=tuple(range(1, a.ndim))) if a.ndim > 1 \
        else np.abs(a)


def _float_tol(scale: float, dtype: str | None, rtol: float | None) -> float:
    """Accumulated-roundoff allowance: ``rtol`` when given, else 64 ULPs
    of the series' own magnitude (a generous bound for a few thousand
    adds), floored away from zero."""
    if rtol is not None:
        return float(max(rtol * scale, 1e-300))
    eps = float(np.finfo(np.dtype(dtype or "float32")).eps)
    return float(max(64.0 * eps * scale, 64.0 * eps))


def _inflight_allowance(series, w: int, factor: float) -> float:
    """What in-flight traffic explains: sent-but-undelivered messages
    perturb the mass/antisymmetry ledgers transiently (the invariant is
    exact only at quiescence — utils/metrics.py), and each in-flight
    message carries an O(per-node error) update.  The allowance is
    ``factor`` x the tail's worst per-node error x the active node
    count; at convergence it vanishes and the float tolerance is all
    that remains."""
    mae = _get(series, "max_abs_err")
    if mae is None or mae.size == 0:
        return 0.0
    worst = float(np.max(_pooled(mae)[-w:]))
    act = _get(series, "active")
    n = float(np.max(act[-w:])) if act is not None and act.size else 1.0
    return factor * worst * max(n, 1.0)


# ---- series checks -------------------------------------------------------

def check_divergence(series, *, explode_factor: float = 10.0,
                     threshold: float = 1e-6) -> CheckResult:
    """NaN/Inf watchdog over every recorded metric, plus runaway RMSE:
    a final RMSE ``explode_factor``x above its starting point is moving
    away from the mean, not toward it.  A final RMSE at or below
    ``threshold`` is never divergence, whatever the ratio says — a
    checkpoint-resumed run can START at the convergence floor, where
    roundoff wobble easily exceeds any multiple of the start."""
    name = "nan_divergence"
    metrics = _metric_names(series)
    if not metrics:
        return CheckResult(name, SKIP, "no telemetry series to judge")
    for m in metrics:
        v = np.asarray(_get(series, m))
        bad = ~np.isfinite(v)
        if bad.any():
            first = int(np.argwhere(bad)[0][0])
            return CheckResult(
                name, FAIL,
                f"non-finite {m} from round index {first}",
                {"metric": m, "first_bad_round": first,
                 "bad_rounds": int(bad.any(axis=tuple(range(1, v.ndim)))
                                   .sum() if v.ndim > 1 else bad.sum())})
    rmse = _get(series, "rmse")
    if rmse is None or rmse.size < 2:
        return CheckResult(name, PASS, "all series finite",
                           {"metrics": list(metrics)})
    start, final = float(rmse[0]), float(rmse[-1])
    if final > threshold and final > explode_factor * max(start, 1e-300):
        return CheckResult(
            name, FAIL,
            f"rmse diverged: {start:.3e} -> {final:.3e} "
            f"(> {explode_factor:g}x start)",
            {"start_rmse": start, "final_rmse": final,
             "explode_factor": explode_factor})
    return CheckResult(name, PASS, "all series finite, rmse not diverging",
                       {"start_rmse": start, "final_rmse": final})


def check_stall(series, *, threshold: float = 1e-6, window: int = 32,
                min_drop: float = 0.05) -> CheckResult:
    """RMSE plateau: still above ``threshold`` yet improving less than
    ``min_drop`` (fractional) over the trailing ``window`` rounds.  A
    converged series is flat *at* the threshold — that is a pass, not a
    stall."""
    name = "rmse_stall"
    rmse = _get(series, "rmse")
    if rmse is None or rmse.size == 0:
        return CheckResult(name, SKIP, "no rmse series recorded")
    if not np.isfinite(rmse).all():
        return CheckResult(name, SKIP,
                           "rmse non-finite (see nan_divergence)")
    final = float(rmse[-1])
    if final <= threshold:
        return CheckResult(name, PASS,
                           f"converged (rmse {final:.3e} <= "
                           f"{threshold:g})",
                           {"final_rmse": final, "threshold": threshold})
    if rmse.size < 8:
        return CheckResult(name, SKIP,
                           f"series too short to judge ({rmse.size} rounds)")
    w = min(int(window), rmse.size - 1)
    ref = float(rmse[-1 - w])
    drop = 1.0 - final / ref if ref > 0 else 0.0
    if drop < min_drop:
        return CheckResult(
            name, WARN,
            f"rmse plateaued at {final:.3e} ({100 * drop:.1f}% drop over "
            f"last {w} rounds, still above {threshold:g})",
            {"final_rmse": final, "window": w, "drop_fraction": drop,
             "threshold": threshold})
    return CheckResult(name, PASS,
                       f"still improving ({100 * drop:.1f}% over last "
                       f"{w} rounds)",
                       {"final_rmse": final, "window": w,
                        "drop_fraction": drop})


def check_mass_conservation(series, *, dtype: str | None = None,
                            rtol: float | None = None, tail: int = 8,
                            inflight_factor: float = 2.0) -> CheckResult:
    """Flow-Updating's mass invariant: the alive-masked estimate sum
    equals the input sum up to float roundoff *plus in-flight traffic*
    (sent-but-undelivered messages perturb it transiently; it is exact
    at quiescence).  The check therefore judges the trailing ``tail``
    rounds — where a healthy run has settled — against 64 ULPs of the
    mass magnitude plus the in-flight allowance; a residual the traffic
    cannot explain is a leak."""
    name = "mass_conservation"
    res = _get(series, "mass_residual")
    if res is None or res.size == 0:
        return CheckResult(name, SKIP, "no mass_residual series recorded")
    res_mag = _pooled(res)
    if not np.isfinite(res_mag).all():
        return CheckResult(name, FAIL, "non-finite mass_residual",
                           {"first_bad_round": int(np.argwhere(
                               ~np.isfinite(res_mag))[0][0])})
    w = max(min(int(tail), res_mag.size), 1)
    mass = _get(series, "mass")
    scale = float(np.max(_pooled(mass))) if mass is not None and \
        mass.size else 1.0
    allowance = _inflight_allowance(series, w, inflight_factor)
    tol = _float_tol(max(scale, 1.0), dtype, rtol) + allowance
    tail_mag = res_mag[-w:]
    worst_i = int(np.argmax(tail_mag))
    worst = float(tail_mag[worst_i])
    ev = {"max_abs_residual": worst,
          "round_index": res_mag.size - w + worst_i,
          "tail_rounds": w, "tolerance": tol,
          "inflight_allowance": allowance, "mass_scale": scale}
    if worst > tol:
        return CheckResult(
            name, FAIL,
            f"mass leak: |residual| {worst:.3e} over the last {w} "
            f"rounds exceeds tolerance {tol:.3e} (float roundoff + "
            "in-flight allowance)",
            ev)
    return CheckResult(name, PASS,
                       f"mass conserved (tail |residual| <= {worst:.3e})",
                       ev)


def check_antisymmetry(series, *, dtype: str | None = None,
                       rtol: float | None = None, tail: int = 8,
                       inflight_factor: float = 2.0) -> CheckResult:
    """Flow antisymmetry: max |flow[e] + flow[rev e]| within float
    tolerance once in-flight updates are accounted for (a sent,
    undelivered flow update leaves the pair transiently unbalanced —
    reference semantics).  Judged on the trailing ``tail`` rounds like
    the mass check.  Only edge-ledger kernels record it; absent =
    skip."""
    name = "antisymmetry"
    anti = _get(series, "antisymmetry")
    if anti is None or anti.size == 0:
        return CheckResult(
            name, SKIP,
            "no antisymmetry series (node-collapsed/halo kernels keep "
            "no pairable edge ledgers)")
    mag = _pooled(anti)
    if not np.isfinite(mag).all():
        return CheckResult(name, FAIL, "non-finite antisymmetry residual")
    w = max(min(int(tail), mag.size), 1)
    allowance = _inflight_allowance(series, w, inflight_factor)
    tol = _float_tol(1.0, dtype, rtol) + allowance
    tail_mag = mag[-w:]
    worst_i = int(np.argmax(tail_mag))
    worst = float(tail_mag[worst_i])
    ev = {"max_violation": worst,
          "round_index": mag.size - w + worst_i, "tail_rounds": w,
          "tolerance": tol, "inflight_allowance": allowance}
    if worst > tol:
        return CheckResult(
            name, FAIL,
            f"antisymmetry violated: {worst:.3e} over the last {w} "
            f"rounds exceeds tolerance {tol:.3e}",
            ev)
    return CheckResult(name, PASS,
                       f"flows antisymmetric (tail <= {worst:.3e})", ev)


# ---- manifest / environment / baseline checks ----------------------------

def check_environment(env: dict | None, *, config: dict | None = None
                      ) -> CheckResult:
    """Backend sanity from a manifest's ``environment`` block: a
    backend that failed to initialize is a fail; float64 configs on a
    non-x64 runtime silently downcast — a warn."""
    name = "environment"
    if not env:
        return CheckResult(name, SKIP, "no environment record")
    if "backend_error" in env:
        return CheckResult(name, FAIL,
                           f"backend failed to initialize: "
                           f"{env['backend_error']}",
                           {"backend_error": env["backend_error"]})
    if int(env.get("device_count", 1)) < 1:
        return CheckResult(name, FAIL, "no devices visible",
                           {"device_count": env.get("device_count")})
    dtype = (config or {}).get("dtype")
    if dtype == "float64" and not env.get("x64", True):
        return CheckResult(
            name, WARN,
            "config asks for float64 but jax x64 is disabled — arrays "
            "silently downcast to float32",
            {"dtype": dtype, "x64": env.get("x64")})
    return CheckResult(name, PASS,
                       f"backend {env.get('backend', '?')} with "
                       f"{env.get('device_count', '?')} device(s)",
                       {k: env.get(k) for k in
                        ("backend", "device_kind", "device_count", "jax")
                        if k in env})


def check_baselines(data: dict, *, gate: float = SPREAD_VALIDITY_PCT
                    ) -> CheckResult:
    """Audit ``BASELINE_MEASURED.json``: entries recorded before the
    spread gate tightened may carry a min-max spread the current gate
    would refuse — every ``vs_baseline`` ratio dividing by one is
    leaning on noise.  ``quarantined: true`` entries are excluded from
    ratio computation already (bench.recorded_baseline skips them), so
    they are acknowledged, not re-flagged."""
    name = "baseline_validity"
    if not data:
        return CheckResult(name, SKIP, "no recorded baselines")
    bad, quarantined = [], []
    for key, entry in data.items():
        if not isinstance(entry, dict):
            continue
        if entry.get("quarantined"):
            quarantined.append(key)
            continue
        spread = (entry.get("des") or {}).get("spread_pct")
        if spread is not None and spread > gate:
            bad.append({"key": key, "spread_pct": spread})
    ev = {"gate_pct": gate, "violations": bad, "quarantined": quarantined}
    if bad:
        keys = ", ".join(f"{b['key']} ({b['spread_pct']:g}%)" for b in bad)
        return CheckResult(
            name, FAIL,
            f"recorded baseline(s) exceed the {gate:g}% spread gate: "
            f"{keys} — re-measure or quarantine them",
            ev)
    return CheckResult(name, PASS,
                       f"all recorded baselines within the {gate:g}% "
                       f"spread gate"
                       + (f" ({len(quarantined)} quarantined)"
                          if quarantined else ""),
                       ev)


def check_plan(plan: dict | None, measured: dict | None = None, *,
               margin_pct: float = 10.0) -> CheckResult:
    """Audit a topology-compiler decision (``flow-updating-plan-report/
    v1``): when the manifest carries per-candidate MEASURED rates
    (``bench.py --generator`` records them), the chosen plan must be
    within ``margin_pct`` of the fastest measured candidate — "auto
    picked a slower plan than available" is a warn with the evidence
    named.  Without measurements the prediction is acknowledged, not
    judged."""
    name = "plan_selection"
    if not plan:
        return CheckResult(name, SKIP, "no plan decision recorded")
    chosen = plan.get("kernel", "?")
    # candidate labels pair kernel/impl; edge decisions carry spmv=None
    # but every measured block keys the edge candidate 'edge/gather'
    chosen = f"{chosen}/{plan.get('spmv') or 'gather'}"
    if not measured:
        # the measured-probe autotune cache records real banded-family
        # rates inside the decision itself — judge from those when a
        # bench measurement is absent and the chosen plan was among the
        # probed candidates (an analytic xla/edge pick is not judged
        # against a family it was never raced in)
        tune = plan.get("autotune")
        if isinstance(tune, dict):
            rates = tune.get("measured_rounds_per_sec")
            if isinstance(rates, dict) and chosen in rates:
                measured = rates
    if not measured:
        return CheckResult(
            name, PASS,
            f"plan {chosen} selected (predicted only — record measured "
            "candidate rates to audit the choice)",
            {"chosen": chosen,
             "predicted_cost": plan.get("predicted_cost")})
    rates = {k: float(v) for k, v in measured.items()
             if isinstance(v, (int, float)) and float(v) > 0}
    if not rates:
        return CheckResult(name, SKIP, "measured block carries no rates",
                           {"measured": measured})
    best = max(rates, key=rates.get)
    chosen_rate = rates.get(chosen)
    ev = {"chosen": chosen, "measured_rounds_per_sec": rates,
          "fastest": best, "margin_pct": margin_pct}
    if chosen_rate is None:
        return CheckResult(
            name, WARN,
            f"chosen plan {chosen} has no measured rate "
            f"(measured: {sorted(rates)})", ev)
    if chosen_rate < rates[best] * (1.0 - margin_pct / 100.0):
        return CheckResult(
            name, WARN,
            f"auto picked a slower plan than available: {chosen} at "
            f"{chosen_rate:.4g} r/s vs {best} at {rates[best]:.4g} r/s "
            f"({100 * (1 - chosen_rate / rates[best]):.1f}% slower)",
            ev)
    return CheckResult(
        name, PASS,
        f"chosen plan {chosen} is the fastest measured candidate "
        f"(within {margin_pct:g}%)", ev)


def scaling_row_efficiency(row: dict, base_rate: float | None) -> float | None:
    """Per-chip efficiency of one ladder row, as a fraction.

    Rows written by ``scripts/multichip_scaling.py --weak`` carry
    ``per_chip_efficiency`` directly (weak scaling: ideal rate is FLAT
    as nodes grow with shards, so efficiency = rate_S / rate_1).  Rows
    without it are strong-scaling rows on a fixed topology — ideal rate
    is S x the single-shard rate, so efficiency = rate_S / (S *
    rate_1), computable only when the same (path, topology) has an S=1
    row."""
    eff = row.get("per_chip_efficiency")
    if eff is not None:
        return float(eff)
    rate = row.get("rounds_per_sec")
    S = int(row.get("shards", 1))
    if base_rate is None or base_rate <= 0 or rate is None or S < 2:
        return None
    return float(rate) / (S * base_rate)


def scaling_base_rates(rows) -> dict:
    """Clean (non-noisy) S=1 anchor rates keyed by ``(path, topology)``
    — THE base map for per-chip efficiency, shared by the doctor's
    ``scaling_efficiency`` check and the ``regress`` CI gate so both
    layers judge the same row set with the same quarantine rule (a
    degraded baseline timing never anchors a ratio)."""
    base = {}
    for r in rows:
        if not isinstance(r, dict) or r.get("noisy"):
            continue
        if int(r.get("shards", 0)) == 1 and \
                isinstance(r.get("rounds_per_sec"), (int, float)):
            base[(r.get("path"), r.get("topology"))] = \
                float(r["rounds_per_sec"])
    return base


def check_scaling_efficiency(doc: dict, *, threshold_pct: float = 50.0
                             ) -> CheckResult:
    """Audit a ``MULTICHIP_SCALING_*`` ladder: warn when any shard
    count's per-chip efficiency drops below ``threshold_pct``, citing
    the offending path/topology row — the scaling analogue of the
    ``plan_selection`` check.  Rows flagged ``noisy`` (timing never met
    the spread gate) are quarantined: counted, never judged."""
    name = "scaling_efficiency"
    rows = doc.get("results") if isinstance(doc, dict) else None
    if not isinstance(rows, list) or not rows:
        return CheckResult(name, SKIP, "no scaling rows to judge")
    base = scaling_base_rates(rows)
    judged, bad, noisy = 0, [], 0
    for r in rows:
        if not isinstance(r, dict) or int(r.get("shards", 1)) < 2:
            continue
        eff = scaling_row_efficiency(
            r, base.get((r.get("path"), r.get("topology"))))
        if eff is None:
            continue
        if r.get("noisy"):
            noisy += 1
            continue
        judged += 1
        if 100.0 * eff < threshold_pct:
            bad.append({"path": r.get("path"),
                        "topology": r.get("topology"),
                        "shards": int(r.get("shards", 0)),
                        "efficiency_pct": round(100.0 * eff, 1)})
    ev = {"threshold_pct": threshold_pct, "rows_judged": judged,
          "noisy_quarantined": noisy, "violations": bad}
    if not judged and not noisy:
        return CheckResult(
            name, SKIP,
            "no multi-shard row carries a computable per-chip "
            "efficiency (need per_chip_efficiency or an S=1 row of the "
            "same path/topology)", ev)
    if bad:
        worst = min(bad, key=lambda b: b["efficiency_pct"])
        return CheckResult(
            name, WARN,
            f"per-chip efficiency below {threshold_pct:g}% on "
            f"{len(bad)} row(s) — worst {worst['path']} / "
            f"{worst['topology']} at S={worst['shards']}: "
            f"{worst['efficiency_pct']:g}%", ev)
    return CheckResult(
        name, PASS,
        f"all {judged} multi-shard rows at or above {threshold_pct:g}% "
        f"per-chip efficiency"
        + (f" ({noisy} noisy rows quarantined)" if noisy else ""), ev)


def check_perf_lens(block: dict | None) -> list:
    """The perf lens' doctor clauses over a
    ``flow-updating-perf-lens/v1`` block (obs/roofline.py):

    * **roofline_sane** — every program's ``roofline_frac`` must land
      in (0, 1]: the predicted ceiling is a physical bound, so a frac
      above 1 means the hardware model or the measurement is lying
      (and a non-positive frac means a degenerate measurement);
    * **roofline_floor** — a frac below the per-mode declared floor
      fails unless the mode is pinned as a KNOWN discrepancy
      (obs.roofline.KNOWN_DISCREPANCIES — e.g. the sharded banded
      round's post-DMA-wait full-band recompute), in which case it is
      reported as KNOWN instead of silently passing or spuriously
      failing.
    """
    if not isinstance(block, dict):
        return [CheckResult("roofline_sane", SKIP,
                            "no perf-lens block to judge — produce one "
                            "with `profile --roofline` or "
                            "`bench.py --roofline`")]
    programs = [p for p in (block.get("programs") or [])
                if isinstance(p, dict)]
    judged = [p for p in programs
              if isinstance(p.get("roofline_frac"), (int, float))]
    checks = []
    if not judged:
        checks.append(CheckResult(
            "roofline_sane", SKIP,
            "perf-lens block carries no reconciled roofline_frac "
            "(programs were analyzed but never measured?)",
            {"programs": len(programs)}))
        return checks
    insane = [{"mode": p.get("mode"), "frac": p["roofline_frac"],
               "ceiling_rounds_per_sec": p.get("ceiling_rounds_per_sec"),
               "measured_rounds_per_sec":
               p.get("measured_rounds_per_sec")}
              for p in judged
              if not 0.0 < float(p["roofline_frac"]) <= 1.0]
    ev = {"programs": len(judged),
          "fracs": {str(p.get("mode")): p["roofline_frac"]
                    for p in judged},
          "model": (block.get("model") or {}).get("name"),
          "violations": insane}
    if insane:
        worst = max(insane, key=lambda v: abs(float(v["frac"])))
        checks.append(CheckResult(
            "roofline_sane", FAIL,
            f"roofline_frac outside (0, 1] on {len(insane)} "
            f"program(s) — worst {worst['mode']}: "
            f"{worst['frac']:g} (frac > 1 means the hardware model or "
            "the measurement is lying; re-calibrate or re-measure)",
            ev))
    else:
        checks.append(CheckResult(
            "roofline_sane", PASS,
            f"all {len(judged)} measured programs land in (0, 1] of "
            f"the {ev['model'] or 'declared'} roofline", ev))
    below, known = [], []
    for p in judged:
        frac = float(p["roofline_frac"])
        floor = p.get("floor_frac")
        if not isinstance(floor, (int, float)):
            from flow_updating_tpu.obs import roofline as _rl

            floor = _rl.floor_frac(p.get("mode"))
        if frac >= float(floor) or frac <= 0.0:
            continue        # non-positive fracs are roofline_sane's case
        rec = {"mode": p.get("mode"), "frac": frac,
               "floor_frac": float(floor),
               "known_discrepancy": p.get("known_discrepancy")}
        (known if p.get("known_discrepancy") else below).append(rec)
    ev2 = {"programs": len(judged), "below_floor": below,
           "known": known}
    if below:
        worst = min(below, key=lambda v: v["frac"])
        checks.append(CheckResult(
            "roofline_floor", FAIL,
            f"{len(below)} program(s) below their declared roofline "
            f"floor with no pinned discrepancy — worst "
            f"{worst['mode']}: {worst['frac']:g} < "
            f"{worst['floor_frac']:g} (pin it in "
            "obs.roofline.KNOWN_DISCREPANCIES or fix the kernel)",
            ev2))
    elif known:
        names = sorted({k["known_discrepancy"] for k in known})
        checks.append(CheckResult(
            "roofline_floor", PASS,
            f"{len(judged) - len(known)} program(s) at or above their "
            f"floor; {len(known)} below-floor mode(s) KNOWN "
            f"({', '.join(names)})", ev2))
    else:
        checks.append(CheckResult(
            "roofline_floor", PASS,
            f"all {len(judged)} measured programs at or above their "
            "declared roofline floor", ev2))
    return checks


def _epoch_tol(sample: dict, scale: float, dtype: str | None,
               inflight_factor: float = 2.0) -> float:
    """Per-epoch mass tolerance: float roundoff at the mass magnitude
    plus the in-flight allowance derived from the SAME boundary sample
    (worst per-node error x active count — the convention of
    :func:`_inflight_allowance`)."""
    mae = float(sample.get("max_abs_err", 0.0) or 0.0)
    act = float(sample.get("active", 1) or 1)
    return (_float_tol(max(scale, 1.0), dtype, None)
            + inflight_factor * mae * max(act, 1.0))


def check_service(service: dict | None, *, dtype: str | None = None
                  ) -> list:
    """The streaming service's SLO checks (``flow-updating-service-
    report/v1`` manifests; docs/SERVICE.md):

    * **service_compile** — the zero-recompile contract: the round
      program compiled at most once across every membership epoch;
    * **service_capacity** — slot accounting is consistent (live <=
      members <= capacity; free lists complement the members);
    * **service_mass** — per-feature mass conserved at EVERY epoch
      boundary: the live residual within float tolerance + the epoch's
      own in-flight allowance;
    * **service_churn_recovery** — the paper's self-healing as an SLO:
      an epoch that applied membership/edge events must end with a
      residual no worse than it started (or below tolerance) — churn
      perturbs mass transiently, the rounds must heal it.
    """
    if not service:
        return [CheckResult("service", SKIP, "no service block recorded")]
    checks = []
    dtype = service.get("dtype", dtype)

    compiles = service.get("compile_count")
    if compiles is None:
        checks.append(CheckResult("service_compile", SKIP,
                                  "no compile count recorded"))
    elif int(compiles) > 1:
        checks.append(CheckResult(
            "service_compile", FAIL,
            f"round program compiled {compiles}x — membership events "
            "must be mask/buffer edits, never a retrace",
            {"compile_count": int(compiles)}))
    else:
        checks.append(CheckResult(
            "service_compile", PASS,
            f"zero recompiles ({compiles} compile across "
            f"{service.get('events_total', '?')} events)",
            {"compile_count": int(compiles),
             "events_total": service.get("events_total")}))

    cap = service.get("capacity") or {}
    if cap:
        n_cap = int(cap.get("nodes", 0))
        members = int(cap.get("members", 0))
        live = int(cap.get("live", 0))
        free_n = cap.get("free_node_slots")
        ok = (live <= members <= n_cap
              and (free_n is None or free_n == n_cap - members))
        checks.append(CheckResult(
            "service_capacity", PASS if ok else FAIL,
            (f"slot accounting consistent ({members}/{n_cap} members, "
             f"{live} live)") if ok else
            (f"slot accounting inconsistent: members={members}, "
             f"live={live}, capacity={n_cap}, "
             f"free_node_slots={free_n}"),
            dict(cap)))

    epochs = service.get("epochs") or []
    if not epochs:
        checks.append(CheckResult(
            "service_mass", SKIP, "no epochs recorded"))
        return checks
    scale = 1.0
    for ep in epochs:
        for side in ("before", "after"):
            m = (ep.get(side) or {}).get("mass")
            if m is not None:
                scale = max(scale, float(np.max(_pooled(m))))
    worst = None
    for ep in epochs:
        after = ep.get("after") or {}
        res = after.get("mass_residual")
        if res is None:
            continue
        mag = float(np.max(_pooled(res)))
        tol = _epoch_tol(after, scale, dtype)
        if not math.isfinite(mag) or mag > tol:
            worst = {"epoch": ep.get("epoch"), "residual": mag,
                     "tolerance": tol}
            break
    if worst is not None:
        checks.append(CheckResult(
            "service_mass", FAIL,
            f"per-feature mass leaked at epoch {worst['epoch']} "
            f"boundary: |residual| {worst['residual']:.3e} > tolerance "
            f"{worst['tolerance']:.3e} (float roundoff + in-flight "
            "allowance)", worst))
    else:
        checks.append(CheckResult(
            "service_mass", PASS,
            f"per-feature mass conserved at all {len(epochs)} epoch "
            "boundaries (within float tolerance + in-flight allowance)",
            {"epochs": len(epochs), "mass_scale": scale}))

    churned = [ep for ep in epochs if ep.get("events")]
    bad = None
    for ep in churned:
        before = ep.get("before") or {}
        after = ep.get("after") or {}
        if before.get("mass_residual") is None or \
                after.get("mass_residual") is None:
            continue
        r0 = float(np.max(_pooled(before["mass_residual"])))
        r1 = float(np.max(_pooled(after["mass_residual"])))
        tol = _epoch_tol(after, scale, dtype)
        if r1 > max(r0, tol):
            bad = {"epoch": ep.get("epoch"), "residual_after_events": r0,
                   "residual_after_rounds": r1, "tolerance": tol,
                   "events": len(ep.get("events") or [])}
            break
    if bad is not None:
        checks.append(CheckResult(
            "service_churn_recovery", FAIL,
            f"post-churn residual did not decay at epoch "
            f"{bad['epoch']}: {bad['residual_after_events']:.3e} -> "
            f"{bad['residual_after_rounds']:.3e} after the epoch's "
            "rounds (self-healing SLO)", bad))
    elif churned:
        checks.append(CheckResult(
            "service_churn_recovery", PASS,
            f"post-churn residual decayed (or stayed within tolerance) "
            f"across all {len(churned)} churned epochs",
            {"churned_epochs": len(churned)}))
    else:
        checks.append(CheckResult(
            "service_churn_recovery", SKIP,
            "no epoch applied membership events"))

    probe = service.get("mirror_probe")
    if isinstance(probe, dict):
        shared = probe.get("shared") or []
        if shared:
            checks.append(CheckResult(
                "service_mirror_aliasing", FAIL,
                f"{len(shared)} device leaf(s) alias in-place-mutated "
                "host mirrors (zero-copy jnp.asarray — the PR-13 "
                "restore race); build device leaves with jnp.array",
                {"shared": shared[:10],
                 "checked": probe.get("checked")}))
        else:
            checks.append(CheckResult(
                "service_mirror_aliasing", PASS,
                f"no device leaf shares memory with a host mirror "
                f"({probe.get('checked', 0)} pairs probed)",
                {"checked": probe.get("checked")}))
    return checks


def check_query(query: dict | None, *, dtype: str | None = None) -> list:
    """The query fabric's SLO checks (``flow-updating-query-report/v1``
    manifests; docs/QUERY.md):

    * **query_compile** — the lane zero-recompile contract: the round
      program compiled at most once across every admission, retirement
      and membership event (lane admission is a value-column write, a
      retirement is a payload scrub — never a retrace);
    * **query_lanes** — lane accounting is consistent (active + free =
      lane capacity; peak within capacity);
    * **query_lane_mass** — the per-lane mass SLO at EVERY segment
      boundary: free (scrubbed) lanes carry a ledger residual of
      exactly 0.0, active lanes stay within float tolerance + the
      boundary's own in-flight allowance;
    * **query_admission** — the admission-latency SLO: the measured p95
      rounds-in-queue within the fabric's declared budget.
    """
    if not query:
        return [CheckResult("query", SKIP, "no query block recorded")]
    checks = []
    dtype = query.get("dtype", dtype)

    compiles = query.get("compile_count")
    # aggregate fabrics declare budget 2 once the extrema lane-mode
    # leaf is installed (exactly one extra lowering — docs/AGGREGATES.md);
    # plain fabrics stay on the strict single-compile contract
    budget = int(query.get("compile_budget", 1))
    if compiles is None:
        checks.append(CheckResult("query_compile", SKIP,
                                  "no compile count recorded"))
    elif int(compiles) > budget:
        checks.append(CheckResult(
            "query_compile", FAIL,
            f"round program compiled {compiles}x (budget {budget}) — "
            "lane admission/retirement and membership events must be "
            "payload-plane edits, never a retrace",
            {"compile_count": int(compiles),
             "compile_budget": budget,
             "admitted_total": query.get("admitted_total"),
             "retired_total": query.get("retired_total")}))
    else:
        checks.append(CheckResult(
            "query_compile", PASS,
            f"compiles within budget ({compiles} compile <= {budget} "
            f"across {query.get('admitted_total', '?')} admissions / "
            f"{query.get('retired_total', '?')} retirements)",
            {"compile_count": int(compiles), "compile_budget": budget}))

    lanes = query.get("lanes") or {}
    if lanes:
        cap = int(lanes.get("capacity", 0))
        active = int(lanes.get("active", 0))
        free = lanes.get("free")
        peak = int(lanes.get("peak_active", 0))
        ok = (0 <= active <= cap and peak <= cap
              and (free is None or active + int(free) == cap))
        checks.append(CheckResult(
            "query_lanes", PASS if ok else FAIL,
            (f"lane accounting consistent ({active}/{cap} active, "
             f"peak {peak})") if ok else
            (f"lane accounting inconsistent: active={active}, "
             f"free={free}, capacity={cap}, peak={peak}"),
            dict(lanes)))

    rows = query.get("boundaries") or []
    if not rows:
        checks.append(CheckResult(
            "query_lane_mass", SKIP, "no boundary rows recorded"))
    else:
        bad = None
        for row in rows:
            free_res = float(row.get("max_resid_free", 0.0))
            if free_res != 0.0:
                bad = {"t": row.get("t"), "kind": "free_lane",
                       "residual": free_res}
                break
            scale = float(row.get("scale", 0.0) or 0.0)
            spread = float(row.get("max_spread", 0.0) or 0.0)
            live = float(row.get("live", 1) or 1)
            tol = (_float_tol(max(scale, 1.0), dtype, None)
                   + 2.0 * spread * max(live, 1.0))
            res = float(row.get("max_resid_active", 0.0))
            if not math.isfinite(res) or res > tol:
                bad = {"t": row.get("t"), "kind": "active_lane",
                       "residual": res, "tolerance": tol}
                break
        if bad is not None:
            kind = ("scrubbed free lane leaked mass"
                    if bad["kind"] == "free_lane" else
                    "active lane residual beyond the in-flight "
                    "allowance")
            checks.append(CheckResult(
                "query_lane_mass", FAIL,
                f"per-lane mass SLO violated at round {bad['t']}: "
                f"{kind} (|residual| {bad['residual']:.3e}"
                + (f" > tolerance {bad['tolerance']:.3e}"
                   if "tolerance" in bad else " != 0.0") + ")", bad))
        else:
            checks.append(CheckResult(
                "query_lane_mass", PASS,
                f"per-lane mass held at all {len(rows)} boundaries "
                "(free lanes exactly 0.0, active within float + "
                "in-flight allowance)", {"boundaries": len(rows)}))

    lat = query.get("admission_latency") or {}
    if not lat.get("count"):
        checks.append(CheckResult(
            "query_admission", SKIP, "no admissions recorded"))
    else:
        slo = lat.get("slo_rounds")
        p95 = lat.get("p95", 0.0)
        if slo is not None and p95 is not None and float(p95) > float(slo):
            checks.append(CheckResult(
                "query_admission", FAIL,
                f"admission-latency SLO violated: p95 {p95:.0f} rounds "
                f"in queue > budget {slo} (lanes saturated — raise "
                "lanes= or retire faster)", dict(lat)))
        else:
            checks.append(CheckResult(
                "query_admission", PASS,
                f"admission latency within SLO (p95 "
                f"{float(p95 or 0):.0f} <= {slo} rounds, "
                f"{lat['count']} admissions)", dict(lat)))
    return checks


def _span_chain_gap(chain: list, t_end: int) -> str | None:
    """Why one terminated query's span chain is NOT gap-free (None when
    it is): ``submitted`` opens it, exactly one admission instant, and
    the ``segment`` spans tile ``[admit, terminal]`` contiguously."""
    if not chain or chain[0].get("name") != "submitted":
        return "chain does not open with a submitted span"
    admits = [c for c in chain
              if str(c.get("name", "")).startswith("admitted@lane")]
    if len(admits) != 1:
        return (f"{len(admits)} admission instants (a query is admitted "
                "exactly once)")
    t_admit = int(admits[0]["t0"])
    if int(chain[0]["t1"]) != t_admit:
        return (f"submitted span ends at {chain[0]['t1']} but admission "
                f"is at {t_admit} (queue time unaccounted)")
    segs = sorted((c for c in chain if c.get("name") == "segment"),
                  key=lambda c: int(c["t0"]))
    if not segs:
        return "no segment spans between admission and the terminal"
    if int(segs[0]["t0"]) != t_admit:
        return (f"first segment starts at {segs[0]['t0']}, admission "
                f"was at {t_admit}")
    for a, b in zip(segs, segs[1:]):
        if int(b["t0"]) != int(a["t1"]):
            return (f"segment gap: [{a['t0']},{a['t1']}] then "
                    f"[{b['t0']},{b['t1']}]")
    if int(segs[-1]["t1"]) != int(t_end):
        return (f"last segment ends at {segs[-1]['t1']} but the "
                f"terminal is at {t_end}")
    return None


def _deferred_chain_gap(chain: list, t_end: int) -> str | None:
    """Why one DEFERRED query's chain is malformed (None when sound):
    strict admission turns the query away at the door, so the chain is
    ``submitted -> deferred`` — it must NOT carry an admission instant
    or segment spans (a deferral never held a lane), and the submitted
    span must account the full queue time up to the deferral."""
    if not chain or chain[0].get("name") != "submitted":
        return "chain does not open with a submitted span"
    if any(str(c.get("name", "")).startswith("admitted@lane")
           for c in chain):
        return ("deferred chain carries an admission instant (a "
                "deferral never holds a lane)")
    if any(c.get("name") == "segment" for c in chain):
        return "deferred chain carries segment spans"
    if int(chain[0]["t1"]) != int(t_end):
        return (f"submitted span ends at {chain[0]['t1']} but the "
                f"deferral is at {t_end} (queue time unaccounted)")
    return None


def check_serving_trace(trace: dict | None, *,
                        query: dict | None = None,
                        recovery: dict | None = None) -> list:
    """The serving flight recorder's checks
    (``flow-updating-serving-trace/v1`` blocks; docs/OBSERVABILITY.md §8):

    * **slo_latency** — every DECLARED latency target (admission /
      convergence p95 rounds) against the measured windowed p95 of the
      corresponding streaming histogram;
    * **span_complete** — every terminated query has a gap-free span
      chain (submitted → one admission → contiguous segments tiling
      ``[admit, terminal]``), and a manifest that records a crash
      recovery carries a ``recovery`` engine span whose replayed-record
      count covers the WAL gap — a replay-disabled control FAILS here,
      it does not skip;
    * **metrics_consistency** — the streaming counters against the
      manifest ground truth (query census totals, WAL sequence): the
      black box must agree with the engine it recorded, *including*
      across a SIGKILL + ``recover()`` (counters ride ring checkpoints,
      WAL replay re-fires the increments).
    """
    if not trace:
        return [CheckResult(
            "serving_trace", SKIP,
            "no serving_trace block recorded — the flight recorder was "
            "off (construct the engine with observe=True, or pass "
            "--metrics to serve/query)")]
    checks = []
    metrics = trace.get("metrics") or {}
    hists = metrics.get("histograms") or {}
    counters = metrics.get("counters") or {}
    gauges = metrics.get("gauges") or {}
    slo = trace.get("slo") or {}

    # -- slo_latency -------------------------------------------------------
    judged, unmeasured = [], []
    for key, hist_name, label in (
            ("admission_p95_rounds", "admission_latency_rounds",
             "admission"),
            ("convergence_p95_rounds", "convergence_latency_rounds",
             "convergence")):
        target = slo.get(key)
        if target is None:
            continue
        h = hists.get(hist_name)
        if not h or not h.get("count"):
            unmeasured.append(label)
            continue
        p95 = float(h.get("p95", float("nan")))
        judged.append({"slo": label, "target_rounds": float(target),
                       "p95_rounds": p95,
                       "ok": bool(math.isfinite(p95)
                                  and p95 <= float(target))})
    if not judged:
        checks.append(CheckResult(
            "slo_latency", SKIP,
            "no declared latency SLO with measured observations"
            + (f" (declared but unmeasured: {', '.join(unmeasured)})"
               if unmeasured else ""),
            {"declared": dict(slo)}))
    else:
        bad = [t for t in judged if not t["ok"]]
        if bad:
            worst = bad[0]
            checks.append(CheckResult(
                "slo_latency", FAIL,
                f"{worst['slo']} latency SLO violated: measured p95 "
                f"{worst['p95_rounds']:.0f} rounds > declared target "
                f"{worst['target_rounds']:.0f}",
                {"targets": judged, "unmeasured": unmeasured}))
        else:
            checks.append(CheckResult(
                "slo_latency", PASS,
                "measured p95 within every declared target ("
                + ", ".join(f"{t['slo']} {t['p95_rounds']:.0f} <= "
                            f"{t['target_rounds']:.0f} rounds"
                            for t in judged) + ")",
                {"targets": judged, "unmeasured": unmeasured}))

    # -- span_complete -----------------------------------------------------
    spans = trace.get("spans")
    if not isinstance(spans, dict):
        checks.append(CheckResult(
            "span_complete", SKIP, "no span chains recorded"))
    else:
        chains = spans.get("queries") or {}
        engine_spans = spans.get("engine") or []
        terminal = ("retired", "quarantined", "deferred")
        bad_chains, n_terminated = [], 0
        for qid, chain in chains.items():
            terms = [c for c in chain if c.get("name") in terminal]
            if not terms:
                continue          # in-flight/queued: judged when done
            n_terminated += 1
            if terms[0].get("name") == "deferred":
                # the forecast-aware admission terminal: no lane, no
                # segments — its own gap rules
                gap = _deferred_chain_gap(chain, int(terms[0]["t0"]))
            else:
                gap = _span_chain_gap(chain, int(terms[0]["t0"]))
            if gap is not None:
                bad_chains.append({"qid": qid, "problem": gap})
        recovery_problem = None
        replay = (recovery or {}).get("replay") or {}
        if "records_pending" in replay:
            pending = int(replay.get("records_pending", 0))
            rspans = [s for s in engine_spans
                      if s.get("name") == "recovery"]
            if not rspans:
                recovery_problem = (
                    f"manifest records a recovery with {pending} WAL "
                    "records pending but the trace has no recovery span "
                    "— the trace is not continuous across the crash")
            else:
                s = rspans[-1]
                replayed = int(s.get("records_replayed", 0))
                if replayed != pending or (pending > 0
                                           and not s.get("replay_enabled",
                                                         False)):
                    recovery_problem = (
                        f"recovery span replayed {replayed} of {pending} "
                        "pending WAL records (replay_enabled="
                        f"{s.get('replay_enabled')}) — the span chains "
                        "after the restored checkpoint were never "
                        "regenerated")
        if bad_chains or recovery_problem:
            problems = ([recovery_problem] if recovery_problem else []) \
                + [f"qid {b['qid']}: {b['problem']}"
                   for b in bad_chains[:3]]
            checks.append(CheckResult(
                "span_complete", FAIL,
                f"trace not gap-free: {problems[0]}"
                + (f" (+{len(bad_chains) - 1} more chains)"
                   if len(bad_chains) > 1 else ""),
                {"bad_chains": bad_chains,
                 "recovery_problem": recovery_problem,
                 "terminated": n_terminated}))
        elif n_terminated == 0 and "records_pending" not in replay:
            checks.append(CheckResult(
                "span_complete", SKIP,
                "no terminated query to judge (all chains in flight)",
                {"chains": len(chains)}))
        else:
            checks.append(CheckResult(
                "span_complete", PASS,
                f"all {n_terminated} terminated chains gap-free"
                + (" incl. continuity across a recorded recovery "
                   f"({int(replay.get('records_pending', 0))} WAL "
                   "records replayed)"
                   if "records_pending" in replay else ""),
                {"terminated": n_terminated, "chains": len(chains),
                 "engine_spans": len(engine_spans)}))

    # -- metrics_consistency -----------------------------------------------
    if not counters and not gauges:
        checks.append(CheckResult(
            "metrics_consistency", SKIP, "no counters recorded"))
    else:
        mismatches, compared = [], []

        def _cmp(counter_name, truth, source):
            if truth is None:
                return
            got = float(counters.get(counter_name, 0.0))
            compared.append({"counter": counter_name, "value": got,
                             "truth": float(truth), "source": source})
            if got != float(truth):
                mismatches.append(compared[-1])

        if query:
            qs = query.get("queries")
            _cmp("queries_submitted_total",
                 len(qs) if isinstance(qs, list) else None,
                 "len(query.queries)")
            _cmp("queries_admitted_total", query.get("admitted_total"),
                 "query.admitted_total")
            _cmp("queries_retired_total", query.get("retired_total"),
                 "query.retired_total")
            _cmp("queries_quarantined_total",
                 query.get("quarantined_total"),
                 "query.quarantined_total")
            fore = query.get("forecast") or {}
            if fore.get("enabled"):
                _cmp("queries_at_risk_total",
                     fore.get("at_risk_total"),
                     "query.forecast.at_risk_total")
                _cmp("queries_deferred_total",
                     fore.get("deferred_total"),
                     "query.forecast.deferred_total")
        wal = (recovery or {}).get("wal") or {}
        if wal.get("last_seq") is not None \
                and gauges.get("wal_last_seq") is not None:
            got = float(gauges["wal_last_seq"])
            truth = float(wal["last_seq"])
            compared.append({"counter": "wal_last_seq (gauge)",
                             "value": got, "truth": truth,
                             "source": "recovery.wal.last_seq"})
            if got != truth:
                mismatches.append(compared[-1])
        if not compared:
            checks.append(CheckResult(
                "metrics_consistency", SKIP,
                "no manifest ground truth to compare the counters "
                "against (no query/recovery block)"))
        elif mismatches:
            m = mismatches[0]
            checks.append(CheckResult(
                "metrics_consistency", FAIL,
                f"counter {m['counter']} = {m['value']:g} but "
                f"{m['source']} = {m['truth']:g} — the black box "
                "disagrees with the engine it recorded",
                {"mismatches": mismatches, "compared": compared}))
        else:
            checks.append(CheckResult(
                "metrics_consistency", PASS,
                f"all {len(compared)} counters match the manifest "
                "ground truth",
                {"compared": compared}))
    return checks


#: structural-vs-measured gap estimates farther apart than this factor
#: mean one provenance is lying (mixing_sane; obs/spectral.py — the
#: measured fit sees the transient, so modest disagreement is expected)
MIXING_AGREE_FACTOR = 4.0

#: a single forecast_ratio beyond band x this factor fails
#: forecast_calibrated outright, p90 notwithstanding: the p90 clause
#: tolerates a 10% tail of noisy fits, but an ETA off by 8x the band
#: (16x at the default band of 2) is a broken — or forged — banking
#: path, not fit noise (the smoke test's single-ratio negative control)
FORECAST_OUTLIER_FACTOR = 8.0


def check_forecast(query: dict | None) -> list:
    """The convergence observatory's reconciliation checks (the
    ``forecast`` sub-block of a query manifest; docs/OBSERVABILITY.md
    §10):

    * **forecast_calibrated** — the banked ``forecast_ratio``
      distribution (first-warm-forecast ETA / measured rounds, one per
      converged forecasted lane) against the fabric's declared band:
      p90 of ``|log ratio|`` must be within ``log(band)`` — i.e. 90%
      of ratios inside ``[1/band, band]`` — and no single ratio may
      exceed :data:`FORECAST_OUTLIER_FACTOR` x the band.  A forged
      ``forecast_ratio = 25`` FAILS even in an otherwise-honest
      population (the negative control of scripts/forecast_smoke.py);
    * **slo_admission** — forecast-aware admission accounting: the
      ``at_risk``/``deferred`` counters must agree with the query
      census, deferrals require the strict policy AND imply at-risk,
      and under ``admit_policy='strict'`` every at-risk query must
      actually have been deferred (none slipped onto a lane).
    """
    fore = (query or {}).get("forecast")
    if not isinstance(fore, dict) or not fore.get("enabled"):
        return [CheckResult(
            "forecast_calibrated", SKIP,
            "no forecast block recorded — the convergence forecaster "
            "was off (construct the fabric with forecast=True, the "
            "default with the flight recorder on)")]
    checks = []
    band = float(fore.get("band", 2.0))
    ratios = [float(r) for r in fore.get("ratios") or ()
              if isinstance(r, (int, float)) and math.isfinite(r)
              and r > 0]
    if not ratios:
        checks.append(CheckResult(
            "forecast_calibrated", SKIP,
            "no converged lane banked a forecast_ratio (queries "
            "retired before the fit window warmed — lengthen runs or "
            "shrink segment_rounds)", {"band": band}))
    else:
        logs = sorted(abs(math.log(r)) for r in ratios)
        p90 = float(np.percentile(np.asarray(logs), 90))
        in_band = sum(1 for v in logs if v <= math.log(band))
        worst = max(ratios, key=lambda r: abs(math.log(r)))
        ev = {"ratios": len(ratios), "band": band,
              "p90_abs_log_ratio": round(p90, 6),
              "in_band_frac": round(in_band / len(ratios), 4),
              "worst_ratio": worst}
        if p90 > math.log(band):
            checks.append(CheckResult(
                "forecast_calibrated", FAIL,
                f"forecasts MIScalibrated: p90 |log forecast_ratio| "
                f"{p90:.3f} > log(band {band:g}) — predicted ETAs "
                f"disagree with measured convergence rounds (worst "
                f"ratio {worst:.3g})", ev))
        elif logs[-1] > math.log(band * FORECAST_OUTLIER_FACTOR):
            # the p90 clause tolerates a noisy tail; an individual
            # ratio this far out is a broken or forged banking path
            checks.append(CheckResult(
                "forecast_calibrated", FAIL,
                f"forecast_ratio {worst:.3g} is beyond "
                f"{FORECAST_OUTLIER_FACTOR:g}x the declared band "
                f"{band:g} — not fit noise; the ETA banking for that "
                "lane is broken (or the record was forged)", ev))
        else:
            checks.append(CheckResult(
                "forecast_calibrated", PASS,
                f"forecasts calibrated: p90 |log forecast_ratio| "
                f"{p90:.3f} <= log(band {band:g}) over {len(ratios)} "
                f"converged lanes ({in_band}/{len(ratios)} in band)",
                ev))

    at_risk = int(fore.get("at_risk_total", 0))
    deferred = int(fore.get("deferred_total", 0))
    policy = str(fore.get("admit_policy", "observe"))
    qs = (query or {}).get("queries") or []
    flagged = sum(1 for q in qs if q.get("at_risk"))
    deferred_census = sum(1 for q in qs
                          if q.get("status") == "deferred")
    at_risk_admitted = sum(1 for q in qs if q.get("at_risk")
                           and q.get("status") != "deferred")
    problems = []
    if qs and flagged != at_risk:
        problems.append(f"{at_risk} at_risk counted but {flagged} "
                        "queries carry the flag")
    if qs and deferred_census != deferred:
        problems.append(f"{deferred} deferrals counted but "
                        f"{deferred_census} queries are deferred")
    if deferred > at_risk:
        problems.append(f"{deferred} deferrals exceed {at_risk} "
                        "at-risk flags (only at-risk queries defer)")
    if policy != "strict" and deferred:
        problems.append(f"{deferred} deferrals under "
                        f"admit_policy={policy!r} (only strict defers)")
    if policy == "strict" and at_risk_admitted:
        problems.append(f"{at_risk_admitted} at-risk queries were "
                        "admitted under admit_policy='strict' (all "
                        "must defer)")
    ev = {"admit_policy": policy, "at_risk_total": at_risk,
          "deferred_total": deferred, "flagged": flagged,
          "deferred_census": deferred_census}
    slo = ((query or {}).get("convergence_latency") or {}).get(
        "slo_rounds")
    if problems:
        checks.append(CheckResult(
            "slo_admission", FAIL,
            f"forecast-aware admission inconsistent: {problems[0]}"
            + (f" (+{len(problems) - 1} more)"
               if len(problems) > 1 else ""),
            {**ev, "problems": problems}))
    elif slo is None and not at_risk:
        checks.append(CheckResult(
            "slo_admission", SKIP,
            "no convergence SLO declared — admission had nothing to "
            "price queries against (pass convergence_slo_rounds / "
            "--convergence-slo)", ev))
    else:
        checks.append(CheckResult(
            "slo_admission", PASS,
            f"admission accounting consistent under "
            f"admit_policy={policy!r} ({at_risk} at-risk, {deferred} "
            "deferred)", ev))
    return checks


def check_mixing(mixing: dict | None) -> list:
    """Sanity of an a-priori mixing record (obs/spectral.py
    ``mixing_report``; the ``mixing`` block of plan/query manifests):

    * every reported spectral gap must land in ``(0, 1]`` (the
      diffusion operator is aperiodic and row-stochastic — anything
      else is an estimator bug);
    * the structural (power-iteration) and measured (decay-fit)
      provenances must agree within :data:`MIXING_AGREE_FACTOR`;
    * when the record carries a ``control`` block (the scenario pair:
      ``bridge_bottleneck`` judged against ``expander_relief``), the
      record's predicted rounds must exceed the control's by the
      declared ``min_factor`` (default 2.0) — the ROADMAP item-4
      baseline, asserted, not eyeballed.
    """
    if not isinstance(mixing, dict):
        return [CheckResult("mixing_sane", SKIP,
                            "no mixing block recorded")]
    problems = []
    gaps = {}
    for name in ("structural", "measured"):
        rec = mixing.get(name)
        if isinstance(rec, dict) and rec.get("gap") is not None:
            g = float(rec["gap"])
            gaps[name] = g
            if not (0.0 < g <= 1.0):
                problems.append(
                    f"{name} gap {g:g} outside (0, 1] — the diffusion "
                    "operator is aperiodic row-stochastic; this is an "
                    "estimator bug, not a slow graph")
    head = mixing.get("gap")
    if head is not None and not (0.0 < float(head) <= 1.0):
        problems.append(f"headline gap {float(head):g} outside (0, 1]")
    if len(gaps) == 2 and all(g > 0 for g in gaps.values()):
        factor = max(gaps["structural"] / gaps["measured"],
                     gaps["measured"] / gaps["structural"])
        if factor > MIXING_AGREE_FACTOR:
            problems.append(
                f"provenances disagree {factor:.1f}x (structural gap "
                f"{gaps['structural']:.4g} vs measured "
                f"{gaps['measured']:.4g}; allowed "
                f"{MIXING_AGREE_FACTOR:g}x)")
    ctrl = mixing.get("control")
    ctrl_ev = None
    if isinstance(ctrl, dict) and ctrl.get("gap") and head:
        # predicted rounds scale as 1/gap at fixed eps, so the ratio
        # of gaps IS the predicted slowdown of the record vs control
        min_factor = float(ctrl.get("min_factor", 2.0))
        ratio = float(ctrl["gap"]) / float(head)
        ctrl_ev = {"control": ctrl.get("name"),
                   "control_gap": float(ctrl["gap"]),
                   "predicted_slowdown": round(ratio, 3),
                   "min_factor": min_factor}
        if ratio < min_factor:
            problems.append(
                f"gap predicts only {ratio:.2f}x the "
                f"{ctrl.get('name', 'control')} rounds (declared "
                f">= {min_factor:g}x) — the bottleneck's conductance "
                "penalty is not visible in the estimate")
    ev = {"gaps": gaps, "headline_gap": head,
          "provenance": mixing.get("provenance"),
          "family": mixing.get("family")}
    if ctrl_ev:
        ev["control"] = ctrl_ev
    if problems:
        return [CheckResult(
            "mixing_sane", FAIL,
            f"mixing record unsound: {problems[0]}"
            + (f" (+{len(problems) - 1} more)"
               if len(problems) > 1 else ""),
            {**ev, "problems": problems})]
    if not gaps and head is None:
        return [CheckResult("mixing_sane", SKIP,
                            "mixing block carries no gap estimates")]
    return [CheckResult(
        "mixing_sane", PASS,
        "mixing estimates sound ("
        + ", ".join(f"{n} gap {g:.4g}" for n, g in sorted(gaps.items()))
        + (f"; predicts {ctrl_ev['predicted_slowdown']:g}x the "
           f"{ctrl_ev['control']} rounds" if ctrl_ev else "") + ")",
        ev)]


def check_aggregate_read(aggregates: dict | None, *,
                         query: dict | None = None,
                         dtype: str | None = None) -> list:
    """The aggregate algebra's read-contract checks (the ``aggregates``
    block of a query manifest; docs/AGGREGATES.md):

    * **aggregate_read** — every recorded aggregate's combined read is
      internally consistent with its kind's contract: sum/count pairing
      (the indicator lane's count within its own error bound of the
      live cohort, ``mean == sum / count``), quantile inversion inside
      the proven ``qeps * (hi - lo)`` bound with a monotone CDF and the
      value inside ``[lo, hi]``, extrema values finite with their
      spread-derived bound;
    * **aggregate_extrema_monotone** — per extrema lane, the
      per-boundary probe reduction vector is monotone until the lane
      converges (``max`` nondecreasing, ``min`` nonincreasing — the
      latching consensus never backtracks) except across boundaries
      where the live set changed (membership churn legitimately moves
      the probe), and the lane's ledger residual is EXACTLY ±0.0 at
      every boundary (extrema lanes never move flow);
    * **aggregate_kind_census** — the kind census and the extrema
      compile accounting agree (extrema kinds present iff the lane-mode
      leaf was installed, i.e. iff the declared budget is 2).
    """
    if not aggregates:
        return [CheckResult("aggregate_read", SKIP,
                            "no aggregates block recorded")]
    checks = []
    recs = [r for r in (aggregates.get("aggregates") or [])
            if isinstance(r, dict)]
    if not recs:
        return [CheckResult("aggregate_read", SKIP,
                            "aggregates block records no aggregates")]

    # ---- per-kind read contracts ----------------------------------------
    problems = []
    judged = 0
    for rec in recs:
        aid, kind = rec.get("aid"), rec.get("kind")
        read = rec.get("read") or {}
        res = read.get("result")
        label = f"agg {aid} ({kind})"
        if read.get("status") == "quarantined":
            continue                     # watchdog casework, not a read
        if res is None:
            if read.get("status") == "done":
                problems.append(f"{label}: done but combined no result")
            continue
        judged += 1
        val = res.get("value")
        if val is None or not math.isfinite(float(val)):
            problems.append(f"{label}: non-finite value {val!r}")
            continue
        bound = res.get("error_bound")
        if bound is None or not math.isfinite(float(bound)) \
                or float(bound) < 0.0:
            problems.append(f"{label}: bad error bound {bound!r}")
        if kind == "sum_count":
            count = float(res.get("count", math.nan))
            live = res.get("cohort_live")
            cb = float(res.get("count_error_bound", 0.0))
            tol = cb + _float_tol(max(1.0, abs(count)), dtype, None)
            if live is not None and not abs(count - float(live)) <= tol:
                problems.append(
                    f"{label}: count {count:.6g} vs {live} live cohort "
                    f"members (|Δ| > bound {tol:.3g}) — the paired "
                    "indicator lane disagrees with the value lane's "
                    "denominator")
            mean = res.get("mean")
            if mean is not None and count and not (
                    abs(float(mean) * count - float(res.get("sum", 0.0)))
                    <= 1e-9 * max(1.0, abs(float(res.get("sum", 0.0))))):
                problems.append(
                    f"{label}: mean {mean!r} != sum/count")
        elif kind == "quantile":
            cdf = res.get("cdf") or []
            if any(b < a - 1e-9 for a, b in zip(cdf, cdf[1:])):
                problems.append(
                    f"{label}: CDF not monotone ({cdf})")
            lo, hi = float(res.get("lo", 0.0)), float(res.get("hi", 0.0))
            qeps = float((rec.get("params") or {}).get("qeps", 0.05))
            if float(bound or 0.0) > qeps * (hi - lo) + 1e-12:
                problems.append(
                    f"{label}: error bound {bound:.3g} exceeds the "
                    f"declared qeps*(hi-lo) = {qeps * (hi - lo):.3g}")
            if not lo <= float(val) <= hi:
                problems.append(
                    f"{label}: value {val:.6g} outside [{lo:.6g}, "
                    f"{hi:.6g}]")
    if problems:
        checks.append(CheckResult(
            "aggregate_read", FAIL,
            f"{len(problems)} aggregate read(s) violate their kind's "
            "contract — " + "; ".join(problems[:4])
            + (" ..." if len(problems) > 4 else ""),
            {"problems": problems[:10], "aggregates": len(recs)}))
    else:
        checks.append(CheckResult(
            "aggregate_read", PASS,
            f"all {judged} combined reads honor their kind contracts "
            f"({len(recs)} aggregates, kinds: "
            f"{sorted(aggregates.get('kinds') or ())})",
            {"aggregates": len(recs), "judged": judged,
             "kinds": aggregates.get("kinds")}))

    # ---- extrema lane monotonicity over the probe rows -------------------
    ext_q = [q for q in ((query or {}).get("queries") or [])
             if isinstance(q, dict) and q.get("lane_mode") in (1, 2)]
    probe_rows = (query or {}).get("probe_rows") or []
    if ext_q and not probe_rows:
        checks.append(CheckResult(
            "aggregate_extrema_monotone", SKIP,
            "extrema lanes ran but the manifest has no probe_rows — "
            "record with probe_manifest=True (AggregateFabric default)"))
    elif ext_q:
        viol = []
        for q in ext_q:
            qid, is_max = q.get("qid"), q.get("lane_mode") == 1
            prev = None                  # (t, live, value)
            for row in probe_rows:
                binding = row.get("lane_q") or []
                if qid not in binding:
                    continue
                lane = binding.index(qid)
                if abs(float(row["resid"][lane])) != 0.0:
                    viol.append(
                        f"qid {qid} lane {lane} t={row.get('t')}: "
                        f"extrema ledger residual "
                        f"{row['resid'][lane]!r} != ±0.0")
                    break
                v = float(row["max" if is_max else "min"][lane])
                cur = (row.get("t"), row.get("live"), v)
                if prev is not None and prev[1] == cur[1] and (
                        v < prev[2] if is_max else v > prev[2]):
                    viol.append(
                        f"qid {qid} lane {lane}: probe "
                        f"{'max' if is_max else 'min'} moved "
                        f"{prev[2]:.6g} -> {v:.6g} between t={prev[0]} "
                        f"and t={cur[0]} with the live set unchanged — "
                        "a latching consensus never backtracks")
                    break
                prev = cur
        checks.append(CheckResult(
            "aggregate_extrema_monotone",
            PASS if not viol else FAIL,
            f"all {len(ext_q)} extrema lanes monotone over "
            f"{len(probe_rows)} probe rows with ledger residual "
            "exactly 0.0" if not viol else
            f"{len(viol)} extrema lane(s) violate the latching "
            "contract — " + "; ".join(viol[:3]),
            {"extrema_lanes": len(ext_q), "probe_rows": len(probe_rows),
             "violations": viol[:10]}))

    # ---- kind census vs compile accounting -------------------------------
    kinds = aggregates.get("kinds") or {}
    has_ext = bool(kinds.get("max") or kinds.get("min"))
    installed = bool(aggregates.get("extrema_installed"))
    budget = aggregates.get("compile_budget")
    ok = (installed or not has_ext) and \
        (budget is None or int(budget) == (2 if installed else 1))
    checks.append(CheckResult(
        "aggregate_kind_census", PASS if ok else FAIL,
        (f"kind census consistent with compile accounting "
         f"(extrema_installed={installed}, budget {budget})") if ok else
        (f"extrema kinds ran without the lane-mode leaf (or the budget "
         f"disagrees): kinds={kinds}, extrema_installed={installed}, "
         f"compile_budget={budget}"),
        {"kinds": kinds, "extrema_installed": installed,
         "compile_budget": budget,
         "compile_count": aggregates.get("compile_count")}))
    return checks


#: Planted faults whose recovery path MUST include a WAL replay —
#: used by check_recovery when a chaos manifest carries ground truth.
_CRASH_FAULTS = ("kill_at_segment", "kill_mid_checkpoint",
                 "truncate_wal_tail", "corrupt_newest_ckpt",
                 "bitflip_archive")


def check_recovery(recovery: dict | None) -> list:
    """The crash-safety SLO checks over a manifest's ``recovery`` block
    (``flow-updating-recovery-report/v1``; docs/RESILIENCE.md):

    * **wal_replay_exact** — the recovery replayed every journaled
      record after its base checkpoint, and — when a harness recorded a
      control digest — the recovered state is bit-exact vs the
      uninterrupted run;
    * **ring_integrity** — recovery restored an undamaged archive,
      falling back past every corrupt newer one (the scan's per-archive
      integrity verdicts are the evidence), retention within bounds;
    * **quarantine_mass** — every watchdog quarantine scrubbed its lane
      back to a ledger residual of exactly 0.0 (the mass-neutral
      free-lane fixed point);
    * **degraded_mode_bounded** — every lane-exhaustion episode ended
      (the queue drained) with the admission backoff within its cap.

    When the block carries chaos ``ground_truth``, the planted fault's
    expected evidence becomes mandatory: a recovery-disabled control
    FAILS instead of skipping (the PR-9 conformance loop closed over
    the infrastructure layer)."""
    if not recovery:
        return [CheckResult("recovery", SKIP,
                            "no recovery block recorded")]
    checks = []
    gt = (recovery.get("ground_truth") or {}).get("fault")
    replay = recovery.get("replay") or {}
    verify = recovery.get("verify") or replay.get("verify")

    name = "wal_replay_exact"
    if verify:
        exact = bool(verify.get("exact"))
        checks.append(CheckResult(
            name, PASS if exact else FAIL,
            "recovered state bit-exact vs the uninterrupted control "
            "(digests match)" if exact else
            "recovered state DIVERGED from the uninterrupted control "
            "(digest mismatch — events lost or replayed out of order)",
            {"verify": dict(verify),
             "records_replayed": replay.get("records_replayed")}))
    elif replay:
        pending = int(replay.get("records_pending", 0))
        applied = int(replay.get("records_replayed", 0))
        if not replay.get("enabled", True):
            checks.append(CheckResult(
                name, FAIL,
                f"recovery disabled: WAL replay skipped with {pending} "
                "journaled record(s) pending — the recovered state is "
                "the stale checkpoint, not the acknowledged timeline",
                dict(replay)))
        elif applied < pending:
            checks.append(CheckResult(
                name, FAIL,
                f"replay incomplete: {applied}/{pending} journaled "
                "records applied", dict(replay)))
        else:
            checks.append(CheckResult(
                name, PASS,
                f"replayed all {applied} journaled record(s) "
                f"({replay.get('events_replayed', 0)} events, "
                f"{replay.get('rounds_replayed', 0)} rounds) since "
                f"wal_seq {replay.get('base_wal_seq')} (no control "
                "digest recorded — exactness asserted by the chaos "
                "harness)", dict(replay)))
    elif gt in _CRASH_FAULTS:
        checks.append(CheckResult(
            name, FAIL,
            f"planted fault {gt!r} requires a crash recovery, but no "
            "replay was recorded", {"ground_truth": gt}))
    else:
        checks.append(CheckResult(
            name, SKIP, "no crash recovery ran (durability-only run)"))

    name = "ring_integrity"
    ring = recovery.get("ring")
    if not isinstance(ring, dict):
        checks.append(CheckResult(name, SKIP, "no ring block recorded"))
    else:
        scanned = ring.get("scanned") or []
        used = ring.get("used")
        fallbacks = int(ring.get("fallbacks", 0))
        kept = ring.get("kept")
        retain = ring.get("retain")
        bad = None
        if scanned and used is None:
            bad = ("no archive in the ring restored — recovery could "
                   "not fall back to a valid checkpoint")
        elif used and used.get("integrity") not in ("valid",
                                                    "unindexed"):
            bad = (f"recovery restored a damaged archive "
                   f"({used.get('integrity')}: "
                   f"{used.get('path')}) instead of falling back")
        elif retain is not None and kept is not None \
                and int(kept) > int(retain):
            bad = (f"retention violated: {kept} archives kept, "
                   f"retain={retain}")
        elif gt in ("corrupt_newest_ckpt", "bitflip_archive") \
                and fallbacks == 0:
            bad = (f"planted fault {gt!r} should have forced a "
                   "fallback, but every archive restored cleanly")
        ev = {"used": used, "fallbacks": fallbacks, "kept": kept,
              "retain": retain,
              "scanned": [{k: s.get(k) for k in
                           ("path", "integrity", "status")}
                          for s in scanned]}
        if bad:
            checks.append(CheckResult(name, FAIL, bad, ev))
        else:
            checks.append(CheckResult(
                name, PASS,
                "ring intact: restored "
                + (str(used.get("path")) if used else "no archive")
                + (f" after falling back past {fallbacks} damaged "
                   f"newer archive(s)" if fallbacks else
                   " (newest archive valid)"), ev))

    wd = recovery.get("watchdog") or {}
    actions = wd.get("actions") or []
    name = "quarantine_mass"
    if actions:
        leaked = [a for a in actions
                  if float(a.get("post_scrub_residual", 0.0)) != 0.0]
        if leaked:
            worst = leaked[0]
            checks.append(CheckResult(
                name, FAIL,
                f"quarantined lane {worst.get('lane')} left a non-zero "
                f"ledger residual {worst.get('post_scrub_residual')!r} "
                "after the scrub (the free-lane fixed point must be "
                "exactly 0.0)",
                {"leaked": leaked, "actions": len(actions)}))
        else:
            reasons = sorted({a.get("reason") for a in actions})
            checks.append(CheckResult(
                name, PASS,
                f"{len(actions)} lane(s) quarantined "
                f"({'/'.join(str(r) for r in reasons)}), every "
                "post-scrub residual exactly 0.0",
                {"actions": actions}))
    elif gt == "nan_poison_lane":
        checks.append(CheckResult(
            name, FAIL,
            "planted NaN-poisoned lane was never quarantined (watchdog "
            "absent or blind) — the poison stays in the compiled "
            "engine", {"ground_truth": gt, "watchdog": bool(wd)}))
    else:
        checks.append(CheckResult(
            name, SKIP, "no quarantine actions recorded"))

    name = "degraded_mode_bounded"
    episodes = wd.get("degraded") or []
    if episodes:
        cap = ((wd.get("config") or {}).get("backoff_max"))
        unended = [e for e in episodes if e.get("end_t") is None]
        overcap = [e for e in episodes
                   if cap is not None
                   and int(e.get("max_backoff", 0)) > int(cap)]
        if unended:
            e = unended[0]
            checks.append(CheckResult(
                name, FAIL,
                f"degraded episode starting at round "
                f"{e.get('start_t')} never ended (queue never drained "
                f"over {e.get('boundaries')} boundaries)",
                {"unended": unended}))
        elif overcap:
            checks.append(CheckResult(
                name, FAIL,
                f"admission backoff exceeded its cap {cap}",
                {"overcap": overcap}))
        else:
            longest = max(int(e.get("boundaries", 0)) for e in episodes)
            checks.append(CheckResult(
                name, PASS,
                f"{len(episodes)} degraded episode(s), all drained "
                f"(longest {longest} boundaries, backoff within "
                f"{cap})", {"episodes": episodes,
                            "deferred_admissions":
                            wd.get("deferred_admissions")}))
    elif gt == "admission_storm":
        checks.append(CheckResult(
            name, FAIL,
            "planted admission storm left no degraded-mode episode "
            "(watchdog absent or backoff never engaged)",
            {"ground_truth": gt, "watchdog": bool(wd)}))
    else:
        checks.append(CheckResult(
            name, SKIP, "no degraded-mode episodes recorded"))
    return checks


def check_report(report: dict | None, *, dtype: str | None = None
                 ) -> CheckResult:
    """Final-state sanity from a run manifest's convergence report:
    non-finite rmse or a mass residual beyond float tolerance at the
    end of the run."""
    name = "final_report"
    if not report:
        return CheckResult(name, SKIP, "no convergence report")
    rmse = report.get("rmse")
    if rmse is not None and not math.isfinite(float(rmse)):
        return CheckResult(name, FAIL, f"final rmse is {rmse}",
                           {"rmse": rmse})
    residual = report.get("mass_residual")
    if residual is not None:
        mag = float(np.max(np.abs(np.asarray(residual, dtype=np.float64))))
        tol = _float_tol(max(abs(float(report.get("true_mean", 1.0)))
                             * float(report.get("nodes", 1)), 1.0),
                         dtype, None)
        if not math.isfinite(mag):
            return CheckResult(name, FAIL, "non-finite final mass residual",
                               {"mass_residual": residual})
        if mag > tol:
            return CheckResult(
                name, FAIL,
                f"final mass residual {mag:.3e} > tolerance {tol:.3e}",
                {"mass_residual": mag, "tolerance": tol})
    return CheckResult(name, PASS, "final report sane",
                       {k: report.get(k) for k in
                        ("rmse", "mass_residual", "t") if k in report})


# ---- bundles -------------------------------------------------------------

# ---- scenario conformance (flow_updating_tpu.scenarios) ------------------

def _scn_instances(rec) -> list:
    return [i for i in (rec.get("instances") or []) if isinstance(i, dict)]


def _scn_conv(inst) -> dict:
    return inst.get("convergence") or {}


def _scn_seed(inst):
    return (inst.get("tag") or {}).get("seed", inst.get("seed"))


def _scn_series(inst, name):
    s = (inst.get("series") or {}).get(name)
    return None if s is None else np.asarray(s, np.float64)


def _blame_symptom(rec, symptom: str):
    """The blame bundle list a clause's symptom refers to (the
    ``straggler`` alias names the stall ranking)."""
    bundle = rec.get("blame") or {}
    key = {"straggler": "stall"}.get(symptom, symptom)
    return bundle.get(key), key


def _eval_scenario_clause(rec: dict, clause: dict, by_name: dict,
                          idx: int) -> CheckResult:
    """Judge ONE declared signature clause against a scenario record.
    Every verdict cites the measured per-seed numbers (or blamed
    entries) it was judged on."""
    scn = rec.get("name", "?")
    kind = clause.get("check")
    name = f"scn:{scn}:{kind}#{idx}"

    if kind in ("agg_err_above", "agg_err_below", "agg_latched"):
        # aggregate scenarios (aggregates/scenarios.py) record per-kind
        # reads instead of sweep instances — judge those directly
        ares = rec.get("aggregate_results") or {}
        label = clause.get("agg")
        entry = ares.get(label)
        if not isinstance(entry, dict):
            return CheckResult(
                name, FAIL,
                f"{scn}: no aggregate result recorded for {label!r}",
                {"clause": clause, "recorded": sorted(ares)})
        value = entry.get("value")
        if value is None or not math.isfinite(float(value)):
            return CheckResult(
                name, FAIL,
                f"{scn}: aggregate {label!r} read no finite value "
                f"({value!r})", {"clause": clause, "entry": entry})
        value = float(value)
        if kind == "agg_latched":
            target = float(clause["value"])
            ok = value == target
            return CheckResult(
                name, PASS if ok else FAIL,
                f"{scn}: {label} consensus latched EXACTLY at the "
                f"planted {target:g}" if ok else
                f"{scn}: {label} read {value:g}, expected the planted "
                f"{target:g} latched exactly",
                {"clause": clause, "value": value,
                 "true": entry.get("true")})
        err = abs(value - float(entry.get("true", math.nan)))
        bound = float(clause["value"])
        above = kind == "agg_err_above"
        ok = err > bound if above else err <= bound
        word = ">" if above else "<="
        return CheckResult(
            name, PASS if ok else FAIL,
            f"{scn}: {label} read error {err:.3g} {word} {bound:g}"
            + ("" if ok else " VIOLATED"),
            {"clause": clause, "value": value,
             "true": entry.get("true"), "error": err})

    insts = _scn_instances(rec)
    if not insts:
        return CheckResult(name, FAIL,
                           f"{scn}: no sweep instances recorded",
                           {"clause": clause})

    if kind == "converges":
        within = int(clause["within"])
        rounds = {f"seed{_scn_seed(i)}":
                  int(_scn_conv(i).get("converged_round", -1))
                  for i in insts}
        bad = {k: r for k, r in rounds.items() if r < 0 or r > within}
        status = PASS if not bad else FAIL
        return CheckResult(
            name, status,
            f"{scn}: every seed converges within {within} rounds"
            if not bad else
            f"{scn}: {len(bad)}/{len(rounds)} seeds missed the "
            f"{within}-round convergence deadline",
            {"clause": clause, "converged_round": rounds,
             "rmse_threshold": rec.get("rmse_threshold")})

    if kind in ("final_rmse_below", "final_rmse_above"):
        bound = float(clause["value"])
        finals = {f"seed{_scn_seed(i)}": _scn_conv(i).get("final_rmse")
                  for i in insts}
        vals = [v for v in finals.values() if v is not None]
        if not vals:
            return CheckResult(name, SKIP,
                               f"{scn}: no final rmse recorded",
                               {"clause": clause})
        below = kind == "final_rmse_below"
        ok = all((v <= bound) if below else (v > bound) for v in vals)
        word = "<=" if below else ">"
        return CheckResult(
            name, PASS if ok else FAIL,
            f"{scn}: final rmse {word} {bound:g} on every seed"
            + ("" if ok else " VIOLATED"),
            {"clause": clause, "final_rmse": finals})

    if kind == "rmse_at_least":
        r = int(clause["round"])
        bound = float(clause["value"])
        vals = {}
        for i in insts:
            s = _scn_series(i, "rmse")
            if s is None or r >= s.shape[0]:
                return CheckResult(
                    name, SKIP,
                    f"{scn}: no per-round rmse series covering round "
                    f"{r} (re-run the scenario sweep with series)",
                    {"clause": clause})
            vals[f"seed{_scn_seed(i)}"] = float(s[r])
        ok = all(v >= bound for v in vals.values())
        return CheckResult(
            name, PASS if ok else FAIL,
            f"{scn}: rmse at round {r} >= {bound:g} on every seed "
            "(the fault visibly disrupts the run)" if ok else
            f"{scn}: rmse at round {r} under {bound:g} — the planted "
            "fault left no observable disruption",
            {"clause": clause, "rmse_at_round": vals})

    if kind == "mass_bounded":
        bound = float(clause["value"])
        start = clause.get("from_round")
        worst = {}
        for i in insts:
            s = _scn_series(i, "mass_residual")
            if s is None:
                return CheckResult(
                    name, SKIP,
                    f"{scn}: no mass_residual series recorded",
                    {"clause": clause})
            mag = np.abs(s) if s.ndim == 1 else np.max(
                np.abs(s), axis=tuple(range(1, s.ndim)))
            window = mag[int(start):] if start is not None else mag[-1:]
            if window.size == 0:
                return CheckResult(
                    name, SKIP,
                    f"{scn}: mass_residual series ends before round "
                    f"{int(start)} (re-run the scenario sweep with a "
                    "full-length series)",
                    {"clause": clause, "series_rounds": int(mag.shape[0])})
            worst[f"seed{_scn_seed(i)}"] = float(window.max())
        ok = all(v <= bound for v in worst.values())
        span = (f"from round {int(start)} on" if start is not None
                else "at the final round")
        return CheckResult(
            name, PASS if ok else FAIL,
            f"{scn}: |mass residual| {span} <= {bound:g} on every seed"
            + ("" if ok else " VIOLATED"),
            {"clause": clause, "worst_abs_mass_residual": worst})

    if kind == "relative_rounds":
        other_name = clause["of"]
        other = by_name.get(other_name)
        if other is None:
            return CheckResult(
                name, SKIP,
                f"{scn}: comparison scenario {other_name!r} not in this "
                "manifest — run both in one `scenarios` invocation",
                {"clause": clause})

        def _median_rounds(r):
            rounds = [int(_scn_conv(i).get("converged_round", -1))
                      for i in _scn_instances(r)]
            return None if any(x < 0 for x in rounds) or not rounds \
                else float(np.median(rounds))

        mine, theirs = _median_rounds(rec), _median_rounds(other)
        if mine is None or theirs is None or theirs <= 0:
            return CheckResult(
                name, FAIL,
                f"{scn}: convergence rounds unavailable for the "
                f"{other_name!r} comparison (unconverged seeds)",
                {"clause": clause, "median_rounds": mine,
                 "other_median_rounds": theirs})
        ratio = mine / theirs
        lo = float(clause.get("min_factor", 0.0))
        hi = float(clause.get("max_factor", math.inf))
        ok = lo <= ratio <= hi
        return CheckResult(
            name, PASS if ok else FAIL,
            f"{scn}: converges in {ratio:.2f}x the rounds of "
            f"{other_name} (declared [{lo:g}, {hi:g}]x)"
            + ("" if ok else " VIOLATED"),
            {"clause": clause, "median_rounds": mine,
             "other_median_rounds": theirs, "ratio": round(ratio, 4)})

    if kind == "blame":
        symptom = clause.get("symptom", "?")
        ranked, key = _blame_symptom(rec, symptom)
        gt = rec.get("ground_truth") or {}
        if clause.get("block") is not None:
            part = (rec.get("blame") or {}).get("partition")
            want = int(clause["block"])
            ok = isinstance(part, dict) and part.get("block") == want
            return CheckResult(
                name, PASS if ok else FAIL,
                f"{scn}: partition blame names block {want} from the "
                "cut-edge residuals" if ok else
                f"{scn}: partition blame did not localize block {want} "
                f"(got {part})",
                {"clause": clause, "partition": part,
                 "cut": (rec.get("blame") or {}).get("cut")})
        if not ranked:
            return CheckResult(
                name, FAIL,
                f"{scn}: blame ranked no {symptom!r} culprit (field "
                f"bundle key {key!r} empty)",
                {"clause": clause, "blame": ranked})
        if "nodes" in clause:
            want = [int(n) for n in clause["nodes"]]
            got = [e.get("node") for e in ranked[:len(want)]]
            ok = set(got) == set(want)
            return CheckResult(
                name, PASS if ok else FAIL,
                f"{scn}: {symptom} blame names node(s) {want} at rank 1"
                if ok else
                f"{scn}: {symptom} blame ranked {got}, expected {want}",
                {"clause": clause, "ranked": ranked[:3]})
        if "edge_of" in clause:
            fam = gt.get(clause["edge_of"]) or {}
            want = {int(e) for e in fam.get("edges", ())}
            top = ranked[0]
            got = {top.get("edge"), top.get("rev")}
            ok = bool(want & got)
            return CheckResult(
                name, PASS if ok else FAIL,
                f"{scn}: {symptom} blame names planted edge pair "
                f"{sorted(got)} at rank 1" if ok else
                f"{scn}: {symptom} blame ranked pair {sorted(got)}, "
                f"expected one of {sorted(want)}",
                {"clause": clause, "ranked": ranked[:3],
                 "planted_edges": sorted(want)})
        return CheckResult(name, SKIP,
                           f"{scn}: blame clause declares no "
                           "expectation (nodes/edge_of/block)",
                           {"clause": clause})

    return CheckResult(name, SKIP,
                       f"{scn}: unknown signature clause {kind!r}",
                       {"clause": clause})


def check_scenario_conformance(manifest: dict) -> list:
    """Judge a ``flow-updating-scenario-report/v1`` manifest: every
    registered scenario's declared signature clause becomes one check
    with field-cited evidence (per-seed convergence rounds, series
    values at the declared rounds, ranked blame entries vs the planted
    ground truth).  Scenario series are judged ONLY against their own
    declared signature — a Byzantine run failing the healthy-run mass
    rule is the scenario working, not a defect."""
    records = [r for r in (manifest.get("scenarios") or [])
               if isinstance(r, dict)]
    if not records:
        return [CheckResult(
            "scenario_conformance", SKIP,
            "manifest has no scenario records — run "
            "`flow_updating_tpu scenarios --report PATH`")]
    by_name = {r.get("name"): r for r in records}
    checks = []
    for rec in records:
        clauses = rec.get("signature") or []
        if not clauses:
            checks.append(CheckResult(
                f"scn:{rec.get('name', '?')}", WARN,
                f"scenario {rec.get('name', '?')!r} declares no "
                "signature — nothing to conform to"))
        for idx, clause in enumerate(clauses):
            if not isinstance(clause, dict):
                continue
            checks.append(_eval_scenario_clause(rec, clause, by_name,
                                                idx))
        if rec.get("perturb"):
            checks.append(CheckResult(
                f"scn:{rec.get('name', '?')}:perturbed", WARN,
                f"scenario {rec.get('name', '?')!r} ran PERTURBED "
                f"({rec['perturb']}) — this manifest is a negative "
                "control, not a conformance record",
                {"perturb": rec["perturb"]}))
    return checks


def diagnose_series(series, *, threshold: float = 1e-6,
                    dtype: str | None = None) -> list:
    """The full series rule set (live doctor / manifest telemetry)."""
    return [
        check_divergence(series, threshold=threshold),
        check_stall(series, threshold=threshold),
        check_mass_conservation(series, dtype=dtype),
        check_antisymmetry(series, dtype=dtype),
    ]


#: Which blame symptom localizes which failing series check — the
#: culprit attachment map for field manifests.
_FIELD_CULPRITS = {
    "rmse_stall": "stall",
    "mass_conservation": "leak",
    "nan_divergence": "divergence",
}


def attach_field_culprits(checks, fields_block: dict) -> None:
    """Enrich non-passing series checks with culprit node/edge ids from
    a manifest's ``fields`` block (``inspect``'s blame layer): a stall
    cites its straggler nodes, a mass leak its non-antisymmetric edge
    pairs, a divergence its origin node — the localization the global
    series alone cannot provide."""
    from flow_updating_tpu.obs import inspect as _inspect

    try:
        verdicts = _inspect.blame(fields_block)
    except (ValueError, TypeError, KeyError) as exc:
        for c in checks:
            if c.name in _FIELD_CULPRITS:
                c.evidence.setdefault(
                    "culprits_error", f"{type(exc).__name__}: {exc}")
        return
    for c in checks:
        symptom = _FIELD_CULPRITS.get(c.name)
        if symptom is None or c.status not in (WARN, FAIL):
            continue
        culprits = verdicts.get(symptom)
        if culprits:
            c.evidence["culprits"] = culprits


def check_program_conformance(audit_report: dict) -> CheckResult:
    """Judge a golden-program audit report
    (:func:`flow_updating_tpu.analysis.golden.audit` output, or the
    ``golden`` block of a ``flow-updating-audit-report/v1`` manifest):
    FAIL names every drifted/missing cell and the first divergent HLO
    line; an environment mismatch (different jax version/backend than
    the ledger was lowered under) is a WARN naming the fix, never a
    false drift verdict."""
    name = "program_conformance"
    if not isinstance(audit_report, dict) or "overall" not in audit_report:
        return CheckResult(
            name, SKIP,
            "no golden audit report — run `python -m flow_updating_tpu "
            "audit --report PATH`")
    overall_ = audit_report.get("overall")
    if overall_ == "env-mismatch":
        return CheckResult(
            name, WARN, audit_report.get("reason",
                                         "lowering environment mismatch"),
            {"environment": audit_report.get("environment")})
    cells = audit_report.get("cells") or []
    n = len(cells)
    if overall_ == "pass":
        return CheckResult(
            name, PASS,
            f"all {n} golden-program cells lower bit-identically",
            {"cells": n})
    bad = [r for r in cells if r.get("status") != "match"]
    detail = "; ".join(
        f"{r.get('cell')}: {r.get('status')}"
        + (f" @ HLO line {r['first_divergence'].get('line')}"
           if r.get("first_divergence") else "")
        for r in bad[:5])
    return CheckResult(
        name, FAIL,
        f"{len(bad)}/{n} golden-program cells drifted — {detail}"
        + (" ..." if len(bad) > 5 else ""),
        {"drifted": [r.get("cell") for r in bad],
         "details": bad[:10]})


def check_budget(budget_report: dict | None) -> CheckResult:
    """Judge a collective-byte-budget report
    (:func:`flow_updating_tpu.analysis.budget.verify_matrix` output, or
    the ``budget`` block of a ``flow-updating-budget-report/v1``
    manifest): FAIL names every over-budget cell and every unbudgeted
    collective with its HLO position."""
    name = "collective_budget"
    if not isinstance(budget_report, dict) \
            or "overall" not in budget_report:
        return CheckResult(
            name, SKIP,
            "no budget report — run `python -m flow_updating_tpu audit "
            "--budget PATH`")
    cells = budget_report.get("cells") or []
    bad = [r for r in cells if r.get("status") != "pass"]
    if budget_report.get("overall") == "pass" and not bad:
        total = sum(r.get("measured_bytes") or 0 for r in cells)
        return CheckResult(
            name, PASS,
            f"all {len(cells)} budgeted programs within "
            f"±{budget_report.get('tolerance_pct')}% of plan "
            f"accounting, no unbudgeted collectives "
            f"({total} B/round total)",
            {"cells": len(cells), "measured_bytes_total": total})
    detail = "; ".join(
        f"{r.get('cell')}: " + (r.get("detail")
                                or "; ".join(r.get("problems") or []))
        for r in bad[:4])
    return CheckResult(
        name, FAIL,
        f"{len(bad)}/{len(cells)} budgeted programs violate their "
        f"collective-byte budget — {detail}"
        + (" ..." if len(bad) > 4 else ""),
        {"failed": [r.get("cell") for r in bad], "details": bad[:10]})


def check_invariants(summary: dict | None) -> CheckResult:
    """Judge an invariant-prover summary
    (:func:`flow_updating_tpu.analysis.invariants.summarize` output):
    FAIL names every violated/error cell with its theorem citations;
    expected-violation cells (the adversary positive controls) pass."""
    name = "invariant_proofs"
    if not isinstance(summary, dict) or "overall" not in summary:
        return CheckResult(
            name, SKIP,
            "no invariant-prover summary — run `python -m "
            "flow_updating_tpu audit` (prover on by default)")
    counts = summary.get("counts") or {}
    bad = summary.get("violated") or []
    if summary.get("overall") == "pass" and not bad:
        return CheckResult(
            name, PASS,
            f"protocol invariants proved on {counts.get('proved', 0)} "
            f"cells ({counts.get('expected-violation', 0)} adversary "
            f"positive controls detected, "
            f"{counts.get('inapplicable', 0)} node-collapsed cells "
            "inapplicable)", {"counts": counts})
    cites = []
    for p in summary.get("proofs") or []:
        if p.get("cell") in bad:
            cites.extend(p.get("violations") or
                         [f"{p.get('cell')}: {p.get('detail')}"])
    return CheckResult(
        name, FAIL,
        f"{len(bad)} cell(s) violate protocol invariants — "
        + "; ".join(cites[:4]) + (" ..." if len(cites) > 4 else ""),
        {"violated": bad, "citations": cites[:10]})


def diagnose_manifest(manifest: dict) -> list:
    """Judge a saved ``flow-updating-*-report/v1`` manifest: the
    environment block, the final convergence report, and — when the run
    recorded telemetry — the per-round series.  A manifest that recorded
    nothing judgeable degrades to an explicit skip (how to record is in
    the summary), never a traceback; a field manifest's non-passing
    series checks additionally cite culprit node/edge ids
    (:func:`attach_field_culprits`)."""
    if not isinstance(manifest, dict):
        raise ValueError(
            f"expected a flow-updating-*-report/v1 manifest (a JSON "
            f"object), got {type(manifest).__name__} — event logs are "
            "JSONL and belong to `obs export-trace`, not doctor")
    config = manifest.get("config") or {}
    if isinstance(config, dict) and "round" in config:
        config = config.get("round") or {}
    dtype = config.get("dtype") if isinstance(config, dict) else None
    checks = [check_environment(manifest.get("environment"),
                                config=config if isinstance(config, dict)
                                else None)]
    if isinstance(manifest.get("scenarios"), list):
        # scenario manifests are judged against their DECLARED
        # signatures only; the healthy-run series rules would flag the
        # planted faults as defects (they are the point)
        checks.extend(check_scenario_conformance(manifest))
        return checks
    if isinstance(manifest.get("golden"), dict) \
            or isinstance(manifest.get("budget"), dict):
        # an audit-report or budget-report manifest (`audit --report` /
        # `audit --budget`): the conformance verdicts are the point
        if isinstance(manifest.get("golden"), dict):
            checks.append(check_program_conformance(manifest["golden"]))
        if isinstance(manifest.get("budget"), dict):
            checks.append(check_budget(manifest["budget"]))
        if isinstance(manifest.get("invariants"), dict):
            checks.append(check_invariants(manifest["invariants"]))
        return checks
    report = manifest.get("report")
    if isinstance(report, dict):
        checks.append(check_report(report, dtype=dtype))
    tel = manifest.get("telemetry")
    series_checks: list = []
    if isinstance(tel, dict) and tel.get("series"):
        series_checks = diagnose_series(tel["series"], dtype=dtype)
        checks.extend(series_checks)
    elif manifest.get("schema") == SCHEMA_RUN:
        checks.append(CheckResult(
            "telemetry", SKIP,
            "run manifest has no telemetry series — record one with "
            "`run --telemetry --report PATH` for series-level checks"))
    fields = manifest.get("fields")
    if isinstance(fields, dict):
        attach_field_culprits(series_checks, fields)
    plan_block = manifest.get("plan")
    if not isinstance(plan_block, dict) and isinstance(report, dict):
        plan_block = report.get("plan")  # run manifests embed it there
    if isinstance(plan_block, dict):
        checks.append(check_plan(plan_block, manifest.get("measured")))
    service = manifest.get("service")
    if isinstance(service, dict):
        checks.extend(check_service(service, dtype=dtype))
    query = manifest.get("query")
    if isinstance(query, dict):
        checks.extend(check_query(query, dtype=dtype))
        if isinstance(query.get("forecast"), dict):
            checks.extend(check_forecast(query))
    mixing = manifest.get("mixing")
    if not isinstance(mixing, dict) and isinstance(plan_block, dict):
        mixing = plan_block.get("mixing")
    if not isinstance(mixing, dict) and isinstance(query, dict):
        fq = query.get("forecast")
        if isinstance(fq, dict):
            mixing = fq.get("mixing")
    if isinstance(mixing, dict):
        checks.extend(check_mixing(mixing))
    aggregates = manifest.get("aggregates")
    if isinstance(aggregates, dict):
        checks.extend(check_aggregate_read(
            aggregates,
            query=query if isinstance(query, dict) else None,
            dtype=dtype))
    recovery = manifest.get("recovery")
    if isinstance(recovery, dict):
        # a flow-updating-recovery-report/v1 manifest (or any manifest
        # from a durability-armed engine): the crash-safety SLOs
        checks.extend(check_recovery(recovery))
    trace = manifest.get("serving_trace")
    if isinstance(trace, dict):
        # the serving flight recorder's block rides serve/query/recovery
        # manifests: latency SLOs, span-chain continuity (incl. across
        # a recorded crash recovery), counter-vs-ground-truth agreement
        checks.extend(check_serving_trace(
            trace,
            query=query if isinstance(query, dict) else None,
            recovery=recovery if isinstance(recovery, dict) else None))
    lens = manifest.get("perf_lens")
    if isinstance(lens, dict):
        # the perf lens' predicted-vs-measured block rides profile /
        # plan / bench manifests: roofline sanity + per-mode floors
        checks.extend(check_perf_lens(lens))
    results = manifest.get("results")
    if (isinstance(results, list) and results
            and isinstance(results[0], dict)
            and "rounds_per_sec" in results[0]):
        # a MULTICHIP_SCALING_* ladder artifact
        checks.append(check_scaling_efficiency(manifest))
    instances = manifest.get("instances")
    if isinstance(instances, list) and instances:
        n_conv = sum(1 for r in instances
                     if (r.get("convergence") or {}).get("converged"))
        status = PASS if n_conv else WARN
        checks.append(CheckResult(
            "sweep_convergence", status,
            f"{n_conv}/{len(instances)} sweep instances converged",
            {"converged": n_conv, "instances": len(instances)}))
    return checks
