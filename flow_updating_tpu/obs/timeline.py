"""Measured device timelines: parse ``jax.profiler`` captures.

``utils/trace.py`` wraps ``jax.profiler.trace`` (the ``--trace-dir``
flag on bench / run / serve / profile); this module reads what the
capture wrote.  The profiler drops a Chrome trace-event file
(``*.trace.json.gz``) under ``LOGDIR/plugins/profile/<run>/`` whose
device rows are per-op thunk slices — name, start, duration, and an
``args.hlo_op`` tag (XLA:CPU thunk runtime and TPU device rows both
carry it).  From those slices the overlap ratio of a sharded schedule
is *measured*: the fraction of wall time the wire ops (collective-
permute / all-reduce / remote DMA) spend concurrent with compute
slices, rather than inferred from the three-schedule wall-clock
arithmetic in :func:`obs.profile.overlap_report` (which stays as the
cross-check).

Everything here is host-side JSON parsing — stdlib only, importable
without jax.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re

#: device slices that ARE the wire: XLA collective ops and the Pallas
#: remote-DMA copies
WIRE_RE = re.compile(
    r"collective-permute|all-reduce|all-gather|reduce-scatter|"
    r"all-to-all|ppermute|remote_copy|copy-start|copy-done|send|recv",
    re.IGNORECASE)

#: executor scaffolding rows that are neither wire nor compute
_INFRA_RE = re.compile(
    r"ThunkExecutor|Executable::|ExecuteHelper|buffer|allocat|"
    r"infeed|outfeed|tuple|parameter",
    re.IGNORECASE)

#: thread names that host device-op slices even when an event misses
#: the args.hlo_op tag (the XLA:CPU client threads)
_DEVICE_THREAD_RE = re.compile(
    r"XLATfrtCpuClient|TFRT|/device:|XLA Launch|Stream #",
    re.IGNORECASE)


def latest_trace_file(log_dir: str) -> str | None:
    """The newest ``*.trace.json.gz`` under ``log_dir`` (the profiler
    nests captures as ``plugins/profile/<timestamp>/<host>...``)."""
    hits = glob.glob(os.path.join(log_dir, "**", "*.trace.json.gz"),
                     recursive=True)
    if not hits:
        return None
    return max(hits, key=os.path.getmtime)


def load_trace_events(path: str) -> tuple[list, dict]:
    """``(trace events, thread names)`` from one Chrome trace file;
    thread names key on ``(pid, tid)``."""
    with gzip.open(path, "rt") as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents") or []
    threads = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = \
                (e.get("args") or {}).get("name", "")
    return events, threads


def device_slices(events: list, threads: dict, *,
                  module: str | None = None) -> list:
    """Per-op device slices: complete ('X') events that carry an
    ``args.hlo_op`` tag or sit on a device-executor thread, with the
    scaffolding rows dropped.  ``module`` filters on the
    ``args.hlo_module`` tag (e.g. 'jit_run_rounds')."""
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        name = str(e.get("name", ""))
        on_device_thread = bool(_DEVICE_THREAD_RE.search(
            threads.get((e.get("pid"), e.get("tid")), "")))
        if "hlo_op" not in args and not on_device_thread:
            continue
        if _INFRA_RE.search(name):
            continue
        if module is not None \
                and module not in str(args.get("hlo_module", "")):
            continue
        dur = e.get("dur")
        ts = e.get("ts")
        if not isinstance(dur, (int, float)) \
                or not isinstance(ts, (int, float)) or dur <= 0:
            continue
        out.append({"name": name, "ts_us": float(ts),
                    "dur_us": float(dur),
                    "hlo_op": args.get("hlo_op"),
                    "hlo_module": args.get("hlo_module"),
                    "lane": (e.get("pid"), e.get("tid"))})
    return out


def annotation_spans(events: list, name: str) -> list:
    """Spans of one ``utils.trace.annotate`` marker (TraceMe splits a
    ``prefix:name`` at the colon, so span names here use dots —
    ``fu.segment``)."""
    return [{"ts_us": float(e["ts"]), "dur_us": float(e["dur"])}
            for e in events
            if e.get("ph") == "X" and e.get("name") == name
            and isinstance(e.get("ts"), (int, float))
            and isinstance(e.get("dur"), (int, float))]


def _union(intervals: list) -> list:
    """Merged ``(start, end)`` union of possibly-overlapping
    intervals."""
    merged: list = []
    for start, end in sorted(intervals):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def _overlap_with(interval: tuple, union: list) -> float:
    """Length of ``interval``'s intersection with a sorted disjoint
    union."""
    start, end = interval
    total = 0.0
    for a, b in union:
        if b <= start:
            continue
        if a >= end:
            break
        total += min(end, b) - max(start, a)
    return total


def measured_overlap(log_dir: str, *,
                     module: str | None = None) -> dict | None:
    """Measure the wire/compute overlap ratio from a captured device
    timeline: the fraction of total wire-slice time during which a
    compute slice is simultaneously active *on the same lane* (device
    row / executor thread).

    Same-lane is the quantity the split schedule buys: a shard's own
    compute hiding its own wire wait.  (Cross-lane concurrency is
    trivially ~1 on any multi-shard run — while one shard sits in a
    collective rendezvous its peer is computing — and says nothing
    about hiding.)  On a TPU the DMA engine runs beside the shard's
    compute, so a working overlap schedule pushes this toward 1.  Note
    the measured and inferred (:func:`obs.profile.overlap_report`)
    ratios answer different questions and may legitimately differ: the
    wall-clock arithmetic asks how much the *schedule split* saved over
    the serialized oracle, while the timeline asks how much of the wire
    time had concurrent compute — on XLA:CPU the thunk executor
    dispatches independent thunks out of order, so a collective
    rendezvous can overlap same-lane compute even under the serialized
    schedule.  Returns None when ``log_dir`` holds no capture; returns
    a record with ``overlap_ratio_measured=None`` when the capture has
    no wire slices (a single-device program)."""
    path = latest_trace_file(log_dir)
    if path is None:
        return None
    events, threads = load_trace_events(path)
    slices = device_slices(events, threads, module=module)
    wire = [s for s in slices if WIRE_RE.search(s["name"])]
    compute = [s for s in slices if not WIRE_RE.search(s["name"])]
    out = {
        "trace_file": path,
        "device_slices": len(slices),
        "wire_ops": len(wire),
        "compute_ops": len(compute),
        "lanes": len({s["lane"] for s in slices}),
        "module": module,
    }
    compute_by_lane: dict = {}
    for s in compute:
        compute_by_lane.setdefault(s["lane"], []).append(
            (s["ts_us"], s["ts_us"] + s["dur_us"]))
    compute_busy = sum(
        b - a for lane in compute_by_lane.values()
        for a, b in _union(lane))
    if not wire:
        out.update({
            "wire_busy_s": 0.0,
            "compute_busy_s": round(compute_busy / 1e6, 6),
            "overlapped_s": 0.0,
            "overlap_ratio_measured": None,
            "note": "capture holds no wire slices (single-device "
                    "program?) — nothing to overlap",
        })
        return out
    lane_unions = {lane: _union(iv)
                   for lane, iv in compute_by_lane.items()}
    wire_busy = sum(s["dur_us"] for s in wire)
    overlapped = sum(
        _overlap_with((s["ts_us"], s["ts_us"] + s["dur_us"]),
                      lane_unions.get(s["lane"], []))
        for s in wire)
    out.update({
        "wire_busy_s": round(wire_busy / 1e6, 6),
        "compute_busy_s": round(compute_busy / 1e6, 6),
        "overlapped_s": round(overlapped / 1e6, 6),
        "overlap_ratio_measured": round(
            min(overlapped / wire_busy, 1.0), 4) if wire_busy > 0
        else None,
    })
    return out
