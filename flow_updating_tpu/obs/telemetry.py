"""Telemetry spec + series: the device-resident metric contract.

The reference's only observability is the watcher actor's periodic
host-side dump (``flowupdating-collectall.py:131-148``); our earlier port
streamed a handful of scalars through ``jax.debug.callback`` — which
breaks fusion, is awkward under ``shard_map``, and leaves no
machine-readable record.  The telemetry subsystem instead threads metric
computation through the round ``lax.scan`` itself: every kernel's
telemetry runner returns one stacked series per metric (scan ``ys``) and
the host sees a single bulk transfer at the end — zero callbacks in the
scan body.

This module holds the *host-side* half of the contract:

* :class:`TelemetrySpec` — a static (hashable, jit-key) selection of
  metric names.  Disabled telemetry (``TelemetrySpec.off()``) makes every
  runner fall back to the plain kernel, so the compiled program is
  *exactly* the current one (asserted by tests/test_telemetry.py and
  scripts/telemetry_overhead.py).
* :class:`TelemetrySeries` — the numpy-backed per-round series with the
  conversions downstream consumers need: JSON for run manifests
  (:mod:`flow_updating_tpu.obs.report`) and ``observer_sample``-shaped
  watch records for the event log (the one ``obs`` emit shape that
  replaces the per-kernel streamed-observer copies).

The device-side samplers live next to their kernels
(``models/rounds.py``, ``models/sync.py``, ``parallel/sharded.py``,
``parallel/structured_sharded.py``) so they can reuse each kernel's
reduction machinery; they all emit the field names defined here.
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: Every metric the subsystem knows, in canonical emission order.
ALL_METRICS = (
    "rmse",            # alive-masked RMSE vs the true mean (pooled features)
    "max_abs_err",     # alive-masked max |estimate - mean|
    "mass",            # alive-masked sum of estimates, per feature
    "mass_residual",   # mass - alive-masked sum of inputs, per feature
    "antisymmetry",    # max |flow[e] + flow[rev[e]]| (edge ledgers)
    "sent",            # messages fired onto the wire this round
    "delivered",       # messages drained from mailboxes this round
    "fired_total",     # cumulative averaging events across all nodes
    "active",          # alive (communicating) node count
)

#: The subset cheap and meaningful on every kernel.
DEFAULT_METRICS = (
    "rmse", "max_abs_err", "mass", "mass_residual", "fired_total", "active",
)

#: What each execution mode can measure.  The node-collapsed kernels keep
#: no per-edge ledgers (no antisymmetry, no message counts); the halo
#: kernel's reverse edges live on other shards, so the antisymmetry pairing
#: would itself be a collective — it stays a single-device/GSPMD metric.
SUPPORTED_METRICS = {
    "edge": ALL_METRICS,
    "halo": tuple(m for m in ALL_METRICS if m != "antisymmetry"),
    "node": DEFAULT_METRICS,
    "pod": DEFAULT_METRICS,
}


@dataclasses.dataclass(frozen=True)
class TelemetrySpec:
    """Static metric selection — hashable, so it is a jit cache key.

    ``strict=True`` (an explicit user list) makes :meth:`for_kernel` raise
    on metrics the execution mode cannot measure; the ``full()``/``parse``
    presets are non-strict and silently narrow to what is supported.
    """

    metrics: tuple = ()
    strict: bool = True

    @property
    def enabled(self) -> bool:
        return bool(self.metrics)

    def has(self, name: str) -> bool:
        return name in self.metrics

    @classmethod
    def off(cls) -> TelemetrySpec:
        return cls(metrics=())

    @classmethod
    def default(cls) -> TelemetrySpec:
        return cls(metrics=DEFAULT_METRICS, strict=False)

    @classmethod
    def full(cls) -> TelemetrySpec:
        return cls(metrics=ALL_METRICS, strict=False)

    @classmethod
    def parse(cls, text: str | None) -> TelemetrySpec:
        """CLI surface: ``off`` / ``default`` / ``full`` / ``m1,m2,...``."""
        if text is None or text in ("", "off", "none"):
            return cls.off()
        if text in ("default", "on", "true", "1"):
            return cls.default()
        if text in ("full", "all"):
            return cls.full()
        names = tuple(m.strip() for m in text.split(",") if m.strip())
        unknown = [m for m in names if m not in ALL_METRICS]
        if unknown:
            # a typo must fail loudly with the whole vocabulary (and a
            # closest-match hint) — silently recording nothing is the
            # failure mode this guards against
            from flow_updating_tpu.obs.fields import _suggest

            raise ValueError(
                f"unknown telemetry metric(s) {unknown}"
                f"{_suggest(unknown[0], ALL_METRICS)}; valid: "
                f"{', '.join(ALL_METRICS)} (or 'default'/'full'/'off')")
        # canonical order regardless of user order — stable jit keys
        return cls(metrics=tuple(m for m in ALL_METRICS if m in names))

    def for_kernel(self, kind: str) -> TelemetrySpec:
        """Narrow to the metrics ``kind`` supports (or raise, if strict)."""
        try:
            sup = SUPPORTED_METRICS[kind]
        except KeyError:
            raise ValueError(
                f"unknown kernel kind {kind!r}; have "
                f"{sorted(SUPPORTED_METRICS)}") from None
        missing = [m for m in self.metrics if m not in sup]
        if missing and self.strict:
            raise ValueError(
                f"metric(s) {missing} are not measurable on the {kind!r} "
                f"kernel (supported: {', '.join(sup)})")
        return TelemetrySpec(
            metrics=tuple(m for m in self.metrics if m in sup),
            strict=self.strict)


class TelemetrySeries:
    """Host-side per-round metric series: ``{name: (R,) or (R, D) array}``
    plus the absolute round counter ``t``.  One instance per telemetry
    run; empty when telemetry was disabled."""

    def __init__(self, data: dict | None = None):
        self._data = {k: np.asarray(v) for k, v in (data or {}).items()}
        if self._data and "t" not in self._data:
            raise ValueError("telemetry series needs the 't' round axis")

    @classmethod
    def empty(cls) -> TelemetrySeries:
        return cls({})

    def __len__(self) -> int:
        return int(self._data["t"].shape[0]) if self._data else 0

    def __bool__(self) -> bool:
        return len(self) > 0

    @property
    def t(self) -> np.ndarray:
        return self._data.get("t", np.zeros((0,), np.int32))

    @property
    def metrics(self) -> tuple:
        return tuple(k for k in self._data if k != "t")

    def __getitem__(self, name: str) -> np.ndarray:
        return self._data[name]

    def __contains__(self, name: str) -> bool:
        return name in self._data

    def row(self, i: int) -> dict:
        out = {}
        for k, v in self._data.items():
            x = v[i]
            out[k] = x.tolist() if np.ndim(x) else x.item()
        return out

    def to_jsonable(self) -> dict:
        """Full series as JSON-ready lists (the run-manifest payload)."""
        return {k: v.tolist() for k, v in self._data.items()}

    def summary(self) -> dict:
        """Final-row digest for the printed report (full series belongs in
        the manifest, not on stdout)."""
        if not self:
            return {"rounds": 0, "metrics": []}
        out = {"rounds": len(self), "metrics": list(self.metrics),
               "final": self.row(len(self) - 1)}
        if "rmse" in self._data:
            out["min_rmse"] = float(np.min(self._data["rmse"]))
        return out

    def watch_records(self, observe_every: int = 1) -> list:
        """The series re-expressed as ``observer_sample`` watch records at
        the watcher grid — the single ``obs`` emit shape that replaces the
        per-kernel streamed-observer copies (contract-tested against them
        in tests/test_obs_tools.py)."""
        from flow_updating_tpu.utils.metrics import observer_sample

        need = ("rmse", "max_abs_err", "mass", "fired_total")
        missing = [m for m in need if m not in self._data]
        if missing:
            raise ValueError(
                f"watch records need metric(s) {missing}; enable them in "
                "the TelemetrySpec (the 'default' set has all of them)")
        every = max(int(observe_every), 1)
        recs = []
        t = self._data["t"]
        for i in range(len(self)):
            ti = int(t[i])
            if ti % every:
                continue
            recs.append(observer_sample(
                ti,
                self._data["rmse"][i],
                self._data["max_abs_err"][i],
                # observer mass is the pooled total (the watcher's
                # global_values-sum heritage); per-feature stays in the
                # series itself
                float(np.sum(self._data["mass"][i])),
                self._data["fired_total"][i],
            ))
        return recs
