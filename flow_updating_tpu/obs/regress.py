"""Bench/profile regression gating: the perf trajectory, machine-checked.

The repo accumulates ``BENCH_*.json`` headline artifacts and recorded
DES baselines (``BASELINE_MEASURED.json``), but nothing *reads* them —
a PR that halves the round rate ships unless a human happens to diff
the JSON.  This module compares a fresh measurement against the
history and flags drops beyond the recorded spread, with a
CI-consumable exit code (the ``regress`` CLI subcommand).

Two comparison shapes:

* **bench**: a fresh ``bench.py`` result line vs the ``BENCH_*.json``
  history.  Docs are grouped by ``(metric, unit, backend)`` — a CPU
  fallback never gates a TPU headline — and the allowed drop below the
  best recorded value is the larger of the history's own min-max
  spread and a noise floor (the same validity logic the DES baseline
  gate uses: spread is what the record itself proved the measurement
  can wobble).
* **profile**: a fresh ``flow-updating-profile-report/v1`` manifest vs
  a reference one.  FLOPs / bytes-accessed / peak-bytes are properties
  of the compiled program — deterministic, so any growth beyond the
  margin is a real cost regression, not noise; wall times are judged
  only at a much coarser margin.
* **scaling**: a fresh ``MULTICHIP_SCALING_*`` ladder vs the banked
  ladder history — per-chip efficiency per (path, topology, shards)
  key.  Efficiency is a rate RATIO (rate_S over rate_1 on the same
  harness), so it gates across machines where raw rounds/s cannot;
  rows flagged ``noisy`` are quarantined on both sides, exactly like
  degraded bench artifacts.
"""

from __future__ import annotations

import glob as _glob
import json
import os

from flow_updating_tpu.obs.health import (
    FAIL,
    PASS,
    SKIP,
    WARN,
    CheckResult,
)

#: minimum allowed drop (percent) before a bench value counts as a
#: regression — two clean runs on the same machine wobble this much
FLOOR_PCT = 10.0

#: deterministic program-cost metrics: growth beyond this is real
PROGRAM_MARGIN_PCT = 2.0

#: wall-clock metrics (compile/execute) are machine-noisy; only flag
#: coarse blowups
WALL_MARGIN_PCT = 50.0


def load_history(pattern: str) -> list:
    """``(path, doc)`` for every parseable bench artifact matching
    ``pattern``, oldest first (glob order is lexicographic, which the
    ``BENCH_r<N>`` naming makes chronological).  Driver-wrapped
    artifacts (the repo's ``BENCH_r*.json``: ``{n, cmd, rc, parsed}``)
    are unwrapped to their ``parsed`` result line, and multi-row
    artifacts (``BENCH_DFL_r*.json``: a dict of named result lines) to
    one history entry per row."""
    out = []
    for path in sorted(_glob.glob(pattern)):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        if "metric" not in doc and isinstance(doc.get("parsed"), dict):
            doc = doc["parsed"]
        if "metric" in doc:
            out.append((path, doc))
            continue
        for key, row in doc.items():
            if isinstance(row, dict) and "metric" in row:
                out.append((f"{path}#{key}", row))
    return out


def _bench_group(doc: dict) -> tuple:
    return (doc.get("metric"), doc.get("unit"), doc.get("backend"))


def compare_bench(fresh: dict, history, *, margin_pct: float | None = None,
                  floor_pct: float = FLOOR_PCT) -> list:
    """Judge a fresh bench doc against same-group history entries."""
    name = "bench_regression"
    value = fresh.get("value")
    if value is None:
        return [CheckResult(name, FAIL,
                            "fresh bench carries no measurement "
                            "(value is null)",
                            {"fresh": fresh.get("metric")})]
    if fresh.get("ok") is False:
        return [CheckResult(
            name, WARN,
            "fresh bench is a degraded/fallback measurement "
            f"({fresh.get('degraded', 'ok=false')}) — not gated",
            {"degraded": fresh.get("degraded")})]
    group = _bench_group(fresh)
    same = [(p, d) for p, d in history
            if _bench_group(d) == group and d.get("value") is not None
            and d.get("ok") is not False]
    if not same:
        return [CheckResult(
            name, SKIP,
            f"no history for metric {group[0]!r} on backend "
            f"{group[2]!r}",
            {"metric": group[0], "backend": group[2]})]
    values = [d["value"] for _, d in same]
    best = max(values)
    best_path = next(p for p, d in same if d["value"] == best)
    hist_spread = (100.0 * (best - min(values)) / best) if best > 0 else 0.0
    allowed = (margin_pct if margin_pct is not None
               else max(hist_spread, floor_pct))
    drop = 100.0 * (best - value) / best if best > 0 else 0.0
    ev = {"fresh_value": value, "best_value": best,
          "best_artifact": os.path.basename(best_path),
          "history_runs": len(same), "history_spread_pct":
          round(hist_spread, 1), "allowed_drop_pct": round(allowed, 1),
          "drop_pct": round(drop, 1)}
    if drop > allowed:
        return [CheckResult(
            name, FAIL,
            f"regression: {value:g} is {drop:.1f}% below the best "
            f"recorded {best:g} ({os.path.basename(best_path)}), "
            f"beyond the {allowed:.1f}% spread",
            ev)]
    verdict = ("new best" if value >= best else
               f"within {allowed:.1f}% of the best recorded")
    return [CheckResult(name, PASS, f"{value:g} {fresh.get('unit', '')}: "
                        f"{verdict}", ev)]


def load_scaling_history(pattern: str) -> list:
    """``(path, doc)`` for every parseable scaling-ladder artifact
    matching ``pattern`` (docs shaped ``{"meta":…, "results":[…]}``)."""
    out = []
    for path in sorted(_glob.glob(pattern)):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("results"), list):
            out.append((path, doc))
    return out


def _efficiency_rows(doc: dict) -> dict:
    """Clean (non-noisy) multi-shard rows' per-chip efficiency, keyed by
    ``(path, topology, shards)``.  Rows flagged ``noisy`` are
    quarantined exactly like degraded bench artifacts — never gated,
    never the record (the BENCH_* convention)."""
    from flow_updating_tpu.obs.health import (
        scaling_base_rates,
        scaling_row_efficiency,
    )

    base = scaling_base_rates(doc.get("results", []))
    rows = {}
    for r in doc.get("results", []):
        if not isinstance(r, dict) or r.get("noisy") \
                or int(r.get("shards", 1)) < 2:
            continue
        eff = scaling_row_efficiency(
            r, base.get((r.get("path"), r.get("topology"))))
        if eff is not None:
            rows[(r.get("path"), r.get("topology"),
                  int(r["shards"]))] = eff
    return rows


def compare_scaling(fresh: dict, history, *, margin_pct: float | None = None,
                    floor_pct: float = FLOOR_PCT) -> list:
    """Gate a fresh scaling ladder's per-chip efficiency against the
    banked ``MULTICHIP_SCALING_*`` history — scaling losses fail CI
    like any perf regression.  Efficiency is a rate RATIO, so it
    travels across machines far better than raw rounds/s; the allowed
    drop below the best recorded value is the larger of the history's
    own spread and the noise floor, per (path, topology, shards) key."""
    name = "scaling_regression"
    fresh_rows = _efficiency_rows(fresh)
    if not fresh_rows:
        return [CheckResult(
            name, SKIP,
            "fresh ladder carries no gateable per-chip efficiency rows "
            "(noisy rows are quarantined; S=1 rows are the baseline)")]
    hist_rows = [(p, _efficiency_rows(d)) for p, d in history]
    checks = []
    for key, eff in sorted(fresh_rows.items()):
        same = [(p, rows[key]) for p, rows in hist_rows if key in rows]
        label = f"{key[0]}/{key[1]}@S={key[2]}"
        if not same:
            checks.append(CheckResult(
                name, SKIP, f"no efficiency history for {label}",
                {"key": list(key)}))
            continue
        values = [v for _, v in same]
        best = max(values)
        best_path = next(p for p, v in same if v == best)
        spread = (100.0 * (best - min(values)) / best) if best > 0 else 0.0
        allowed = (margin_pct if margin_pct is not None
                   else max(spread, floor_pct))
        drop = 100.0 * (best - eff) / best if best > 0 else 0.0
        ev = {"key": list(key), "fresh_efficiency": round(eff, 4),
              "best_efficiency": round(best, 4),
              "best_artifact": os.path.basename(best_path),
              "history_runs": len(same),
              "history_spread_pct": round(spread, 1),
              "allowed_drop_pct": round(allowed, 1),
              "drop_pct": round(drop, 1)}
        if drop > allowed:
            checks.append(CheckResult(
                name, FAIL,
                f"scaling regression on {label}: per-chip efficiency "
                f"{100 * eff:.1f}% is {drop:.1f}% below the best "
                f"recorded {100 * best:.1f}% "
                f"({os.path.basename(best_path)}), beyond the "
                f"{allowed:.1f}% spread", ev))
        else:
            verdict = ("new best" if eff >= best
                       else f"within {allowed:.1f}% of the record")
            checks.append(CheckResult(
                name, PASS,
                f"{label}: {100 * eff:.1f}% per-chip efficiency — "
                f"{verdict}", ev))
    return checks


def _profile_block(doc: dict) -> dict | None:
    """The attribution record inside either a bare ``Engine.profile``
    dict or a profile manifest."""
    if not isinstance(doc, dict):
        return None
    if "cost" in doc and "timings" in doc:
        return doc
    prof = doc.get("profile")
    if isinstance(prof, dict):
        return _profile_block(prof) or prof
    return None


def _pct_growth(new, old) -> float | None:
    if not isinstance(new, (int, float)) or not isinstance(old, (int, float)):
        return None
    if old <= 0:
        return None
    return 100.0 * (new - old) / old


def compare_profile(fresh: dict, against: dict, *,
                    margin_pct: float = PROGRAM_MARGIN_PCT) -> list:
    """Judge a fresh profile record against a reference one."""
    f, a = _profile_block(fresh), _profile_block(against)
    if f is None or a is None:
        return [CheckResult("profile_regression", SKIP,
                            "one of the documents carries no profile "
                            "record")]
    checks = []
    program_metrics = (
        ("flops", (f.get("cost") or {}).get("flops"),
         (a.get("cost") or {}).get("flops")),
        ("bytes_accessed", (f.get("cost") or {}).get("bytes_accessed"),
         (a.get("cost") or {}).get("bytes_accessed")),
        ("peak_bytes", (f.get("memory") or {}).get("peak_bytes"),
         (a.get("memory") or {}).get("peak_bytes")),
    )
    for metric, new, old in program_metrics:
        name = f"profile_{metric}"
        growth = _pct_growth(new, old)
        if growth is None:
            checks.append(CheckResult(name, SKIP,
                                      f"{metric} not recorded on both "
                                      "sides"))
            continue
        ev = {"fresh": new, "reference": old,
              "growth_pct": round(growth, 2),
              "margin_pct": margin_pct}
        if growth > margin_pct:
            checks.append(CheckResult(
                name, FAIL,
                f"{metric} grew {growth:.1f}% ({old:g} -> {new:g}) — "
                "the compiled program got more expensive",
                ev))
        else:
            checks.append(CheckResult(
                name, PASS, f"{metric} within {margin_pct:g}% "
                f"({growth:+.1f}%)", ev))
    new_t = (f.get("timings") or {}).get("execute_s")
    old_t = (a.get("timings") or {}).get("execute_s")
    growth = _pct_growth(new_t, old_t)
    if growth is not None:
        ev = {"fresh_s": new_t, "reference_s": old_t,
              "growth_pct": round(growth, 1),
              "margin_pct": WALL_MARGIN_PCT}
        if growth > WALL_MARGIN_PCT:
            checks.append(CheckResult(
                "profile_execute_wall", WARN,
                f"execution wall time grew {growth:.0f}% "
                f"({old_t:g}s -> {new_t:g}s) — wall noise or a real "
                "slowdown; re-measure",
                ev))
        else:
            checks.append(CheckResult(
                "profile_execute_wall", PASS,
                f"execution wall within {WALL_MARGIN_PCT:g}% "
                f"({growth:+.0f}%)", ev))
    return checks


def compare_budget(fresh: dict, against: dict | None, *,
                   margin_pct: float = PROGRAM_MARGIN_PCT) -> list:
    """Judge a fresh collective-byte-budget manifest: its own verdicts
    always gate (an over-budget or unbudgeted collective fails here
    too), and per-cell measured bytes are compared against a reference
    manifest when one is given — growth beyond ``margin_pct`` fails,
    naming the cell (wire bytes are a compile artifact: 2% growth is a
    payload-layout change, not noise)."""
    from flow_updating_tpu.obs.health import check_budget

    fb = fresh.get("budget") if isinstance(fresh, dict) else None
    checks = [check_budget(fb)]
    ab = against.get("budget") if isinstance(against, dict) else None
    if ab is None:
        if against is not None:
            checks.append(CheckResult(
                "budget_regression", SKIP,
                "reference document carries no budget block"))
        return checks
    ref = {r.get("cell"): r for r in ab.get("cells") or []}
    for rec in (fb or {}).get("cells") or []:
        cell = rec.get("cell")
        old = (ref.get(cell) or {}).get("measured_bytes")
        new = rec.get("measured_bytes")
        name = f"budget_bytes[{cell}]"
        if old is None or new is None:
            checks.append(CheckResult(
                name, SKIP, "cell not measured on both sides",
                {"fresh": new, "reference": old}))
            continue
        if old == 0:
            if new == 0:          # the collective-free claims
                checks.append(CheckResult(
                    name, PASS, "0 collective bytes on both sides",
                    {"fresh": new, "reference": old}))
            else:                 # 0 -> N is unbounded growth, not skip
                checks.append(CheckResult(
                    name, FAIL,
                    f"collective bytes grew from 0 to {new} B/round — "
                    "a collective-free program acquired a wire",
                    {"fresh": new, "reference": old,
                     "margin_pct": margin_pct}))
            continue
        growth = _pct_growth(new, old)
        if growth is None:
            checks.append(CheckResult(
                name, SKIP, "cell not comparable",
                {"fresh": new, "reference": old}))
            continue
        ev = {"fresh": new, "reference": old,
              "growth_pct": round(growth, 2), "margin_pct": margin_pct}
        if growth > margin_pct:
            checks.append(CheckResult(
                name, FAIL,
                f"collective bytes grew {growth:.1f}% ({old} -> {new} "
                "B/round) — the wire got fatter; update the plan "
                "accounting if intentional", ev))
        else:
            checks.append(CheckResult(
                name, PASS,
                f"collective bytes within {margin_pct:g}% "
                f"({growth:+.1f}%)", ev))
    return checks


def gate(fresh: dict, *, history_pattern: str | None = None,
         against: dict | None = None,
         margin_pct: float | None = None) -> list:
    """Dispatch on document shape: scaling ladders gate per-chip
    efficiency against the ``MULTICHIP_SCALING_*`` history; profile /
    budget manifests compare against a reference manifest; bench lines
    compare against the artifact history."""
    if isinstance(fresh, dict) and "metric" not in fresh \
            and isinstance(fresh.get("parsed"), dict):
        fresh = fresh["parsed"]  # driver-wrapped artifact
    if isinstance(fresh, dict) and isinstance(fresh.get("results"), list):
        # a MULTICHIP_SCALING_* ladder: gate per-chip efficiency
        history = load_scaling_history(
            history_pattern or "MULTICHIP_SCALING_*.json")
        return compare_scaling(fresh, history, margin_pct=margin_pct)
    if isinstance(fresh, dict) and isinstance(fresh.get("budget"), dict):
        return compare_budget(fresh, against,
                              **({"margin_pct": margin_pct}
                                 if margin_pct is not None else {}))
    if _profile_block(fresh) is not None and against is not None:
        return compare_profile(fresh, against,
                               **({"margin_pct": margin_pct}
                                  if margin_pct is not None else {}))
    if "metric" in fresh:
        history = load_history(history_pattern or "BENCH_*.json")
        return compare_bench(fresh, history, margin_pct=margin_pct)
    if _profile_block(fresh) is not None:
        return [CheckResult("profile_regression", SKIP,
                            "profile document needs --against REFERENCE "
                            "to compare with")]
    return [CheckResult("regression", SKIP,
                        "unrecognized document shape (neither a bench "
                        "result line nor a profile report)")]
