"""A-priori mixing estimation for Flow-Updating's round operator.

The protocol's averaging step applies the diffusion operator

    ``P = diag(1 / (deg + 1)) (I + A)``

(models/sync.py ``_fused_round_step``: ``avg = (...) * inv_depp1``) —
a row-stochastic matrix whose second eigenvalue ``lambda2`` sets how
fast the estimate spread contracts, so ``gap = 1 - |lambda2|`` is the
topology's convergence budget: a lane reaches relative tolerance
``eps`` in roughly ``ln(1/eps) / gap`` rounds.  The paper's bottleneck
graphs (scenarios/registry.py ``bridge_bottleneck``) converge ~5x
slower than their expander controls precisely because their gap is
~5x smaller — this module makes that number observable BEFORE a run.

Two provenances, the predict/measure shape the perf lens (PR 18)
established for throughput:

* **structural** — deflated power iteration for ``|lambda2|``, riding
  the EXISTING spmv lowerings as the matvec (``plan/banded.
  banded_neighbor_sum`` when an :class:`ExecutionPlan` is given — the
  probe then measures the operator the plan actually runs — or the
  edge-rows scatter-add otherwise).  Deterministic: the start vector
  comes from a seeded host RNG, never wall-clock entropy.
* **measured** — a short probe run of the diffusion itself from a
  seeded random value vector, fitting the log-spread slope
  (obs/forecast.py ``fit_log_decay`` — the same fit the online lane
  forecaster uses, so the two provenances disagree only when the
  model does, not the estimator).

Both are persisted in the PR-15 autotune cache (plan/select.py: same
file, same atomic writer, ``FLOW_UPDATING_AUTOTUNE_CACHE`` honored)
keyed by plan content hash — version-gated ``mixing-v1`` keys, so a
stale record re-probes instead of silently steering.

Math notes: ``P`` has right eigenvector ``1`` (row-stochastic) and
left stationary vector ``pi = (deg+1) / sum(deg+1)``; power iteration
deflates the stationary component by subtracting ``(pi . x) 1`` each
step.  The ``I`` term makes ``P`` aperiodic, so ``|lambda2| < 1`` on
any connected graph and the gap lands in ``(0, 1]``.  Closed forms
pinned by tests/test_forecast.py: cycle ``C_n`` has ``lambda2 = (1 +
2 cos(2 pi / n)) / 3``; the complete graph ``K_n`` has ``lambda2 = 0``
(gap exactly 1).
"""

from __future__ import annotations

import math

import numpy as np

from flow_updating_tpu.obs.forecast import fit_log_decay

#: bump to invalidate every persisted mixing record (estimator change)
MIXING_VERSION = "mixing-v1"

#: persisted-record traffic since import — the observable twin of the
#: probe-cost contract (a hit must recompute NOTHING); mirrors
#: plan/select.AUTOTUNE_CACHE_STATS
MIXING_CACHE_STATS = {"hits": 0, "misses": 0}

DEFAULT_POWER_ITERS = 128
DEFAULT_DECAY_ROUNDS = 64

#: successive |lambda2| estimates within this stop the power iteration
#: early (the Rayleigh sequence has converged)
_POWER_TOL = 1e-9


def predicted_rounds_to_eps(gap: float, eps: float) -> float:
    """``ln(1/eps) / gap`` — the a-priori rounds-to-tolerance estimate
    (inf on a non-positive gap; 0 when eps >= 1)."""
    if not (gap > 0.0):
        return float("inf")
    return max(0.0, math.log(1.0 / float(eps))) / float(gap)


def _diffusion_operator(topo, plan=None):
    """``(step, n, family)``: one application of ``P`` in the lowering
    family the caller runs — banded rolls + remainder when a compiled
    plan is given (plan-order vectors), edge-rows scatter-add
    otherwise.  ``step`` maps a device vector to a device vector."""
    import jax.numpy as jnp

    if plan is not None:
        from flow_updating_tpu.plan.compile import _topo_key

        if plan.source_key and plan.source_key != _topo_key(topo):
            raise ValueError(
                "mixing probe: the plan was compiled from a different "
                "topology (source_key mismatch) — its banded masks "
                "would compute a different operator's gap")
        from flow_updating_tpu.plan.banded import banded_neighbor_sum

        t = plan.topo               # RCM order — P's spectrum is
        n = t.num_nodes             # permutation-invariant
        deg = np.bincount(np.asarray(t.src), minlength=n)
        inv = jnp.asarray(1.0 / (deg + 1.0))

        def step(x):
            return (x + banded_neighbor_sum(x, plan.spmv,
                                            plan.leaves)) * inv

        return step, n, "banded"
    n = topo.num_nodes
    src = jnp.asarray(np.asarray(topo.src))
    dst = jnp.asarray(np.asarray(topo.dst))
    deg = np.bincount(np.asarray(topo.src), minlength=n)
    inv = jnp.asarray(1.0 / (deg + 1.0))

    def step(x):
        return (x + jnp.zeros_like(x).at[dst].add(x[src])) * inv

    return step, n, "edge"


def estimate_gap_structural(topo, *, plan=None,
                            iters: int = DEFAULT_POWER_ITERS,
                            seed: int = 0) -> dict:
    """Deflated power iteration for ``|lambda2|`` of the diffusion
    operator — the structural provenance."""
    import jax.numpy as jnp

    step, n, family = _diffusion_operator(topo, plan)
    if n < 2:
        return {"provenance": "structural", "family": family,
                "lambda2": 0.0, "gap": 1.0, "iters": 0,
                "seed": int(seed)}
    deg = (np.bincount(np.asarray((plan.topo if plan is not None
                                   else topo).src), minlength=n))
    pi = jnp.asarray((deg + 1.0) / float(np.sum(deg + 1.0)))
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal(n))
    x = x - jnp.sum(pi * x)                 # deflate the stationary mode
    x = x / jnp.linalg.norm(x)
    lam = prev = 0.0
    used = 0
    for used in range(1, int(iters) + 1):
        y = step(x)
        y = y - jnp.sum(pi * y)             # re-deflate (roundoff drift)
        norm = float(jnp.linalg.norm(y))
        if norm <= 0.0 or not math.isfinite(norm):
            lam = 0.0
            break
        lam = norm                          # ||P x|| / ||x||, ||x|| = 1
        x = y / norm
        if used > 8 and abs(lam - prev) < _POWER_TOL:
            break
        prev = lam
    lam = min(max(float(lam), 0.0), 1.0)
    return {
        "provenance": "structural",
        "family": family,
        "lambda2": lam,
        "gap": 1.0 - lam,
        "iters": int(used),
        "seed": int(seed),
    }


def estimate_gap_measured(topo, *, plan=None,
                          rounds: int = DEFAULT_DECAY_ROUNDS,
                          seed: int = 0) -> dict:
    """Short probe run of the diffusion from a seeded random value
    vector, fitting the log-spread slope — the measured provenance
    (``rate = exp(slope)``, ``gap = 1 - rate``)."""
    import jax.numpy as jnp

    step, n, family = _diffusion_operator(topo, plan)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.random(n))
    # stop fitting at the dtype's roundoff floor: past it the spread
    # hovers on accumulation noise and a flat tail would wreck the
    # slope (float32 runs hit it after ~15 decades less than float64)
    floor = 100.0 * float(np.finfo(np.asarray(x).dtype).eps)
    ts, spreads = [], []
    for t in range(1, int(rounds) + 1):
        x = step(x)
        spread = float(jnp.max(x) - jnp.min(x))
        if not math.isfinite(spread) or spread <= floor:
            break
        ts.append(t)
        spreads.append(spread)
    fit = fit_log_decay(ts, spreads)
    if fit is None:
        # converged inside one step (complete-graph-like): the decay is
        # too fast to fit — report the open gap the data witnessed
        return {"provenance": "measured", "family": family,
                "rate": 0.0, "gap": 1.0, "rounds": len(ts),
                "seed": int(seed), "fit": None}
    rate = min(max(math.exp(fit["slope"]), 0.0), 1.0)
    return {
        "provenance": "measured",
        "family": family,
        "rate": rate,
        "gap": 1.0 - rate,
        "rounds": len(ts),
        "seed": int(seed),
        "fit": {k: float(v) for k, v in fit.items()},
    }


def _mixing_key(topo, family: str, *, power_iters: int,
                decay_rounds: int, seed: int) -> str:
    """Cache key: version x plan content hash x backend x jax version x
    x64 x the probe configuration — any mismatch is a STALE entry that
    re-probes (the autotune-cache discipline, plan/select.py)."""
    import jax

    from flow_updating_tpu.plan.compile import _topo_key

    tk = _topo_key(topo)
    x64 = bool(jax.config.read("jax_enable_x64"))
    return (f"{MIXING_VERSION}|{jax.default_backend()}|"
            f"jax{jax.__version__}|x64:{int(x64)}|"
            f"n{tk[0]}e{tk[1]}|{tk[2][:16]}|fam{family}|"
            f"pi{int(power_iters)}|dr{int(decay_rounds)}|s{int(seed)}")


def mixing_report(topo, *, plan=None, eps: float = 1e-6,
                  power_iters: int = DEFAULT_POWER_ITERS,
                  decay_rounds: int = DEFAULT_DECAY_ROUNDS,
                  seed: int = 0, cache_path: str | None = None,
                  refresh: bool = False) -> dict:
    """The ``mixing`` block of plan/query manifests: both provenances,
    a headline gap, and the predicted rounds-to-``eps`` — persisted in
    the PR-15 autotune cache keyed by plan content hash.

    The headline ``gap`` prefers the measured provenance when its fit
    produced an in-range gap (it sees the transient the structural
    eigenvalue cannot), falling back to structural.  ``refresh=True``
    forces a re-probe; a version or configuration mismatch re-probes
    implicitly (stale keys never steer).
    """
    from flow_updating_tpu.plan.select import (
        _load_autotune_cache,
        _store_autotune_entry,
        autotune_cache_path,
    )

    family = "banded" if plan is not None else "edge"
    path = cache_path or autotune_cache_path()
    key = _mixing_key(topo, family, power_iters=power_iters,
                      decay_rounds=decay_rounds, seed=seed)
    entry = _load_autotune_cache(path).get(key)
    hit = (isinstance(entry, dict)
           and entry.get("version") == MIXING_VERSION
           and not refresh)
    if hit:
        MIXING_CACHE_STATS["hits"] += 1
    else:
        MIXING_CACHE_STATS["misses"] += 1
        entry = {
            "version": MIXING_VERSION,
            "structural": estimate_gap_structural(
                topo, plan=plan, iters=power_iters, seed=seed),
            "measured": estimate_gap_measured(
                topo, plan=plan, rounds=decay_rounds, seed=seed),
        }
        _store_autotune_entry(path, key, entry)
    st, me = entry["structural"], entry["measured"]
    if me.get("fit") is not None and 0.0 < float(me["gap"]) <= 1.0:
        gap, provenance = float(me["gap"]), "measured"
    else:
        gap, provenance = float(st["gap"]), "structural"
    return {
        "gap": gap,
        "provenance": provenance,
        "eps": float(eps),
        "predicted_rounds": predicted_rounds_to_eps(gap, eps),
        "family": family,
        "structural": dict(st),
        "measured": dict(me),
        "cache": {"path": path, "key": key, "hit": bool(hit)},
    }
