"""Online per-lane convergence forecasting for the query fabric.

The fabric's segment-boundary lane probe (query/fabric.py
``_lane_probe``) already reduces the estimate matrix to five
``(lanes,)`` vectors per boundary — max/min/sum of live estimates, the
ledger-form mass residual, live count.  This module turns that existing
stream into a *forecast*: Flow-Updating's estimate spread contracts
geometrically at the rate set by the diffusion operator's second
eigenvalue (obs/spectral.py estimates it a priori), so on a log axis
the trailing spread window is a line and its slope is the measured
contraction rate.  Extrapolating that line to the lane's retirement
threshold (``eps * scale`` for the spread signal, ``eps * max(1,
|mass|)`` for the residual signal — the fabric's own two-signal
verdict, :meth:`QueryFabric._lane_result`) yields ``eta_rounds``: the
predicted rounds until the lane retires, with a confidence band from
the fit's slope uncertainty.

Everything here is host-side float math over numbers the fabric
already holds: zero new compiles (the compile-count pin of
tests/test_forecast.py), zero device work, and with the forecaster off
the fabric lowers byte-identically and evolves bit-exactly (the
observer-purity contract every obs/ plane honours).

Calibration closes the loop (docs/OBSERVABILITY.md §10): when a
forecasted lane retires, the fabric banks ``forecast_ratio =
eta_predicted / rounds_actual`` using the FIRST warm forecast (the
earliest, hardest prediction — a last-boundary forecast is trivially
right).  Doctor's ``forecast_calibrated`` judges the p90 of
``|log ratio|`` against the declared band.
"""

from __future__ import annotations

import math

#: calibration band for ``forecast_ratio``: doctor passes when the p90
#: of ``|log ratio|`` is within ``log(FORECAST_BAND)`` — i.e. 90% of
#: banked ratios land in [1/band, band].  Mirrored into the query
#: manifest's ``forecast`` block so offline doctor judges the band the
#: fabric declared, not whatever the checker's default happens to be.
FORECAST_BAND = 2.0

#: slopes above this are "not decaying" — the fit is judged flat and no
#: ETA is extrapolated (a diverging or stalled lane is the watchdog's
#: jurisdiction, not the forecaster's)
_FLAT_SLOPE = -1e-12


def fit_log_decay(ts, ys) -> dict | None:
    """Least-squares fit of ``ln(y) = intercept + slope * t`` over the
    strictly-positive, finite points of ``(ts, ys)``.

    Returns ``{"slope", "intercept", "stderr", "slope_stderr",
    "points"}`` (stderr = residual standard error of ``ln y``), or
    ``None`` with fewer than two usable points or zero time spread.
    Plain host float math — no array backend, importable anywhere.
    """
    pts = [(float(t), math.log(float(y))) for t, y in zip(ts, ys)
           if float(y) > 0.0 and math.isfinite(float(y))]
    if len(pts) < 2:
        return None
    n = len(pts)
    mt = sum(t for t, _ in pts) / n
    my = sum(y for _, y in pts) / n
    sxx = sum((t - mt) ** 2 for t, _ in pts)
    if sxx <= 0.0:
        return None
    sxy = sum((t - mt) * (y - my) for t, y in pts)
    slope = sxy / sxx
    intercept = my - slope * mt
    rss = sum((y - (intercept + slope * t)) ** 2 for t, y in pts)
    stderr = math.sqrt(rss / (n - 2)) if n > 2 else 0.0
    return {
        "slope": slope,
        "intercept": intercept,
        "stderr": stderr,
        "slope_stderr": stderr / math.sqrt(sxx),
        "points": n,
    }


def _eta_from_fit(fit: dict, threshold: float, now: float):
    """Rounds from ``now`` until the fitted line crosses
    ``ln(threshold)`` — None when the fit is flat/rising (never
    crosses) or the threshold is non-positive."""
    if fit is None or threshold <= 0.0:
        return None
    slope = fit["slope"]
    if slope >= _FLAT_SLOPE:
        return None
    t_star = (math.log(threshold) - fit["intercept"]) / slope
    return max(0.0, t_star - float(now))


class LaneForecaster:
    """Trailing per-lane probe windows + the ETA extrapolation.

    ``observe()`` is fed once per (lane, boundary) from the fabric's
    existing probe vectors; ``forecast()`` fits the window and returns
    the lane's ETA record.  ``clear()`` drops a lane's window at
    retire/quarantine/recycle time (the same hygiene the watchdog
    applies to its ``_lane_trend`` — a recycled lane must not inherit
    the retired query's decay history).
    """

    def __init__(self, window: int = 8, min_points: int = 3):
        if window < 2:
            raise ValueError(f"window={window} must be >= 2")
        if not (2 <= min_points <= window):
            raise ValueError(
                f"min_points={min_points} must be in [2, window={window}]")
        self.window = int(window)
        self.min_points = int(min_points)
        #: lane -> list of (t, spread, scale, |resid|, |mass|) rows,
        #: trailing ``window`` entries
        self._hist: dict[int, list] = {}

    def observe(self, lane: int, t: int, *, spread: float, scale: float,
                resid: float, mass: float) -> None:
        rows = self._hist.setdefault(int(lane), [])
        rows.append((int(t), float(spread), float(scale),
                     abs(float(resid)), abs(float(mass))))
        if len(rows) > self.window:
            del rows[:len(rows) - self.window]

    def clear(self, lane: int) -> None:
        self._hist.pop(int(lane), None)

    def clear_all(self) -> None:
        self._hist.clear()

    def points(self, lane: int) -> int:
        return len(self._hist.get(int(lane), ()))

    def forecast(self, lane: int, eps: float, *, now: int) -> dict:
        """The lane's ETA record at round ``now``:

        * ``status`` — ``"warming"`` (window below ``min_points``),
          ``"flat"`` (no signal is decaying), or ``"ok"``;
        * ``eta_rounds`` — predicted rounds until BOTH retirement
          signals cross their thresholds (the max of the per-signal
          ETAs: the verdict needs spread AND residual settled);
        * ``eta_lo`` / ``eta_hi`` — the slope +/- 1 stderr band of the
          governing signal's fit;
        * ``rate`` — per-round contraction of the governing signal
          (``exp(slope)``; the measured twin of the spectral
          ``lambda2``).
        """
        rows = self._hist.get(int(lane), ())
        out = {"status": "warming", "eta_rounds": None, "eta_lo": None,
               "eta_hi": None, "rate": None, "points": len(rows)}
        if len(rows) < self.min_points:
            return out
        t_last, spread_last, scale_last, resid_last, mass_last = rows[-1]
        ts = [r[0] for r in rows]
        signals = (
            # (latest value, threshold, series)
            (spread_last, float(eps) * max(1.0, scale_last),
             [r[1] for r in rows]),
            (resid_last, float(eps) * max(1.0, mass_last),
             [r[3] for r in rows]),
        )
        etas = []
        for latest, threshold, ys in signals:
            if latest <= threshold:
                etas.append((0.0, None))       # already settled
                continue
            fit = fit_log_decay(ts, ys)
            eta = _eta_from_fit(fit, threshold, now)
            if eta is None:
                etas.append((None, fit))
                continue
            etas.append((eta, fit))
        if any(eta is None for eta, _ in etas):
            out["status"] = "flat"
            return out
        eta, fit = max(etas, key=lambda ef: ef[0])
        out["status"] = "ok"
        out["eta_rounds"] = float(eta)
        if fit is not None:
            out["rate"] = math.exp(fit["slope"])
            # eta ~ remaining-log-depth / |slope|, so a +/-1 stderr
            # slope perturbation maps to eta * |slope| / (|slope| -/+ se)
            se = fit["slope_stderr"]
            m = abs(fit["slope"])
            out["eta_lo"] = float(eta * m / (m + se)) if m + se > 0 \
                else 0.0
            out["eta_hi"] = (float(eta * m / (m - se))
                             if m - se > 0 else float("inf"))
        else:
            # the governing signal was already settled (eta 0 on both)
            out["eta_lo"] = out["eta_hi"] = 0.0
        return out
