"""Per-query span tracing for the serving stack.

Every query admitted into the fabric carries a *span chain* — the
time-resolved record of its life on the lane plane::

    submitted ── admitted@lane ── segment ── ... ── segment ── converged
                                                          └─ retired | quarantined

recorded host-side at the segment boundaries the fabric already owns
(query/fabric.py ``_boundary``): zero new device work, zero extra
compiles.  Span timestamps are *round clocks* (the fabric's logical
time), not wall time — the chain is therefore deterministic and
bit-reproducible across a WAL replay, which is what makes the trace
crash-surviving:

* the recorder's state rides ring checkpoints (``state_dict()`` under
  the checkpoint's ``obs`` meta key, next to the lane tables);
* spans between the restored checkpoint and the crash are regenerated
  by WAL replay — the replayed ``submit``/``run`` records re-fire the
  same hooks at the same round clocks;
* ``recover()`` (resilience/recover.py) appends an explicit engine-level
  ``recovery`` span covering ``[base_clock, recovered_clock]`` with the
  replay evidence, so a recovered trace is *continuous* and says so.

Doctor's ``span_complete`` check (obs/health.py) judges the result: every
completed query must have a gap-free chain — contiguous segment spans
from admission to retirement — and a manifest that records a crash
recovery must carry a ``recovery`` span whose replayed-record count
covers the WAL gap (a recovery-disabled control FAILS, not skips).

Chain vocabulary (docs/OBSERVABILITY.md §8):

* ``submitted`` — span ``[submit_round, admit_round]``: time in the
  admission queue (zero-length when a free lane was available);
* ``admitted@lane{L}`` — instant at admission, naming the lane;
* ``segment`` — one span per compiled scan segment the query was live
  for, ``[boundary, next boundary]``, contiguous by construction;
* ``converged`` — instant at the boundary whose probe verdict retired
  the lane;
* ``read`` — instant at the first successful ``read()`` (bounded: one
  per query, re-reads are not re-recorded);
* ``retired`` / ``quarantined`` — the terminal instant (quarantines
  carry the watchdog's reason);
* ``deferred`` — the terminal instant of a strict-admission turn-away
  (forecast-aware admission, docs/OBSERVABILITY.md §10): the query
  never held a lane, so its chain is ``submitted -> deferred`` with no
  admission instant and no segments.

Engine-level spans (not tied to one query) live on a separate track:
``recovery`` (above) and the watchdog's ``degraded`` backoff episodes
(resilience/watchdog.py), each ``[start_t, end_t]`` with evidence args.
"""

from __future__ import annotations


class SpanRecorder:
    """Host-side span chains, keyed by query id, plus engine-level spans.

    All timestamps are round clocks; memory is bounded by the query
    census the fabric already keeps (a handful of spans per query, one
    open-segment cursor per active lane).
    """

    def __init__(self):
        # qid (str keys: JSON round-trips through checkpoint meta)
        self._chains: dict[str, list] = {}
        self._engine: list = []
        #: qid -> start clock of the currently open segment span
        self._open_seg: dict[str, int] = {}

    # ---- recording hooks (called by the serving engines) ----------------

    def span(self, qid, name: str, t0, t1, **attrs) -> None:
        rec = {"name": name, "t0": int(t0), "t1": int(t1)}
        if attrs:
            rec.update(attrs)
        self._chains.setdefault(str(qid), []).append(rec)

    def engine_span(self, name: str, t0, t1, **attrs) -> None:
        rec = {"name": name, "t0": int(t0), "t1": int(t1)}
        if attrs:
            rec.update(attrs)
        self._engine.append(rec)

    def submitted(self, qid, t) -> None:
        """Open the chain: the ``submitted`` span starts in the queue
        (t1 back-filled at admission; an unadmitted query keeps
        ``t1 == t0`` so partial chains still render)."""
        self.span(qid, "submitted", t, t)

    def admitted(self, qid, lane: int, t) -> None:
        chain = self._chains.get(str(qid))
        if chain and chain[0]["name"] == "submitted":
            chain[0]["t1"] = int(t)       # queue time now known
        self.span(qid, f"admitted@lane{int(lane)}", t, t, lane=int(lane))
        self._open_seg[str(qid)] = int(t)

    def boundary(self, t) -> None:
        """Close one ``segment`` span per active query at a segment
        boundary (called at the top of the fabric's ``_boundary``,
        before the watchdog/retire verdicts stamp terminals at ``t``)."""
        t = int(t)
        for qid, start in self._open_seg.items():
            if t > start:
                chain = self._chains.get(qid)
                lane = None
                if chain:
                    for rec in chain:
                        if "lane" in rec:
                            lane = rec["lane"]
                self.span(qid, "segment", start, t,
                          **({"lane": lane} if lane is not None else {}))
                self._open_seg[qid] = t

    def converged(self, qid, t) -> None:
        self.span(qid, "converged", t, t)

    def retired(self, qid, t) -> None:
        self.span(qid, "retired", t, t)
        self._open_seg.pop(str(qid), None)

    def quarantined(self, qid, t, reason: str | None = None) -> None:
        self.span(qid, "quarantined", t, t,
                  **({"reason": reason} if reason else {}))
        self._open_seg.pop(str(qid), None)

    def deferred(self, qid, t, **attrs) -> None:
        """Terminal instant for a strict-admission deferral (the
        forecast-aware admission path, query/fabric.py): the query
        never held a lane, so the chain is ``submitted -> deferred`` —
        no admission instant, no segments.  ``attrs`` carry the ETA
        evidence (``eta_rounds``, ``slo_rounds``)."""
        chain = self._chains.get(str(qid))
        if chain and chain[0]["name"] == "submitted":
            chain[0]["t1"] = int(t)       # queue time now known
        self.span(qid, "deferred", t, t, **attrs)
        self._open_seg.pop(str(qid), None)

    def read(self, qid, t) -> None:
        """First-read instant; bounded to one per query (aggregate
        fabrics re-read every lane per ``aggregate_block``)."""
        chain = self._chains.get(str(qid))
        if chain is not None and not any(r["name"] == "read"
                                         for r in chain):
            self.span(qid, "read", t, t)

    def annotate(self, qid, **attrs) -> None:
        """Attach attributes (aggregate kind, tag, ...) to the chain's
        opening span."""
        chain = self._chains.get(str(qid))
        if chain:
            chain[0].update(attrs)

    # ---- read path -------------------------------------------------------

    def chain(self, qid) -> list:
        return list(self._chains.get(str(qid), ()))

    def block(self) -> dict:
        """The manifest-embeddable JSON block (serving-trace schema)."""
        return {
            "queries": {qid: list(chain)
                        for qid, chain in sorted(self._chains.items(),
                                                 key=lambda kv: kv[0])},
            "engine": list(self._engine),
            "total": (sum(len(c) for c in self._chains.values())
                      + len(self._engine)),
        }

    # ---- checkpoint ride -------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "chains": {qid: list(chain)
                       for qid, chain in self._chains.items()},
            "engine": list(self._engine),
            "open_seg": dict(self._open_seg),
        }

    @classmethod
    def load_state(cls, state: dict) -> SpanRecorder:
        rec = cls()
        rec._chains = {str(k): list(v)
                       for k, v in (state.get("chains") or {}).items()}
        rec._engine = list(state.get("engine") or ())
        rec._open_seg = {str(k): int(v)
                         for k, v in (state.get("open_seg") or {}).items()}
        return rec
