"""AOT cost attribution: what a compiled round program *costs*.

The telemetry subsystem (PR 2) records what happened per round; nothing
records what the compiled program itself costs — FLOPs, bytes moved
through HBM, device-memory residency, or how the wall time splits
between XLA compilation and execution.  That attribution is exactly what
communication-vs-compute trade-off work optimizes for (Gossip-PGA,
arXiv:2105.09080), and it is available *without instrumenting the
program*: ``jit(f).lower(...).compile()`` hands back XLA's own
``cost_analysis()`` / ``memory_analysis()`` for the exact executable the
plain path runs.  Profiling is therefore a pure *observer* — the
program it measures is bit-identical to the un-profiled one (asserted in
tests/test_profile.py).

Entry points:

* :func:`profile_program` — lower + compile + (optionally) execute one
  jitted callable, returning the normalized attribution record;
* :meth:`Engine.profile <flow_updating_tpu.engine.Engine.profile>` —
  attribution for the engine's configured kernel dispatch mode
  (edge / node / halo / pod);
* the ``profile`` CLI subcommand and ``bench.py --profile`` — the same
  record written as a ``flow-updating-profile-report/v1`` manifest;
* batched sweeps (``sweep --profile``) attach one record per shape
  bucket to the sweep manifest.

Repeated profiles of the same program are served from a small in-process
executable cache (so ``Engine.profile`` is cheap to call mid-run); the
hit/miss counters are part of every record — the "did this recompile?"
question the compile-cache counters exist to answer.
"""

from __future__ import annotations

import time

import numpy as np

#: process-wide AOT-executable cache counters (every record carries a
#: snapshot; reset_cache() zeroes them — test isolation)
CACHE_STATS = {"hits": 0, "misses": 0}

_COMPILED: dict = {}

#: CompiledMemoryStats field -> record key.  ``peak_memory_in_bytes`` is
#: only populated by some backends (TPU); see the fallback below.
_MEM_FIELDS = {
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "temp_size_in_bytes": "temp_bytes",
    "alias_size_in_bytes": "alias_bytes",
    "generated_code_size_in_bytes": "generated_code_bytes",
    "peak_memory_in_bytes": "peak_bytes",
}


def reset_cache() -> None:
    """Drop cached executables and zero the hit/miss counters."""
    _COMPILED.clear()
    CACHE_STATS["hits"] = CACHE_STATS["misses"] = 0


def _num(x):
    if isinstance(x, (np.floating, np.integer)):
        return x.item()
    return x


#: program-level cost_analysis keys worth recording; the per-operand
#: breakdown ("bytes accessed3{}", "utilization17{}", ...) is dozens of
#: keys of manifest noise
_RAW_KEYS = ("flops", "bytes accessed", "bytes accessedout{}",
             "transcendentals", "optimal_seconds", "utilization")


def normalize_cost_analysis(ca) -> dict:
    """XLA's ``cost_analysis()`` across jax versions (list-of-dict per
    partition, or a bare dict) -> ``{flops, bytes_accessed, raw}``."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    raw = {str(k): _num(v) for k, v in dict(ca or {}).items()
           if isinstance(v, (int, float, np.floating, np.integer))
           and str(k) in _RAW_KEYS}
    return {
        "flops": raw.get("flops"),
        "bytes_accessed": raw.get("bytes accessed"),
        "raw": raw,
    }


def normalize_memory_analysis(ma) -> dict:
    """``memory_analysis()`` -> byte counts.  ``peak_bytes`` uses XLA's
    own peak when the backend reports one; otherwise the live-set bound
    arguments + outputs + temps - aliased (what the program holds
    resident while running) with ``peak_source`` saying so."""
    if ma is None:
        return {"available": False}
    out: dict = {"available": True}
    for field, key in _MEM_FIELDS.items():
        v = getattr(ma, field, None)
        if v is not None:
            out[key] = int(v)
    if "peak_bytes" not in out:
        out["peak_bytes"] = (out.get("argument_bytes", 0)
                             + out.get("output_bytes", 0)
                             + out.get("temp_bytes", 0)
                             - out.get("alias_bytes", 0))
        out["peak_source"] = "arguments+outputs+temps-aliased"
    else:
        out["peak_source"] = "xla_peak_memory"
    return out


def device_memory_stats(device=None) -> dict | None:
    """The runtime allocator's view (``device.memory_stats()``): live
    ``bytes_in_use`` / high-water ``peak_bytes_in_use`` on TPU; None on
    backends that keep no stats (CPU)."""
    import jax

    d = device if device is not None else jax.devices()[0]
    try:
        stats = d.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {str(k): _num(v) for k, v in stats.items()}


def _jit_cache_size(fn):
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def _fingerprint(fn, args) -> tuple:
    """Executable-cache key: the callable plus every argument's aval (or
    its hash/repr for static leaves) — two calls with the same key lower
    to the same XLA program."""
    import jax

    leaves, treedef = jax.tree.flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append(("arr", tuple(shape), str(dtype)))
        else:
            try:
                sig.append(("st", hash(leaf)))
            except TypeError:
                sig.append(("st", repr(leaf)))
    return (fn, str(treedef), tuple(sig))


def profile_program(fn, args=(), *, n_dynamic=None, execute=True,
                    label=None, device=None) -> dict:
    """Lower + compile ``fn(*args)`` ahead of time and return the cost
    attribution record.

    ``fn`` is a ``jax.jit``-wrapped callable; ``args`` is the FULL
    argument tuple (static argnames included, exactly as a normal call);
    ``n_dynamic`` is how many leading args are dynamic — the compiled
    executable is invoked with ``args[:n_dynamic]`` (default: all).
    ``execute=False`` skips the timed execution (cost/memory only).

    The compiled executable is cached on the argument fingerprint, so
    repeated profiles of an unchanged program are hits (compile wall
    time is then the cached miss's measurement, flagged ``cache_hit``).
    Profiling never touches the jit call cache — the plain path's
    program is exactly what it was.
    """
    import jax

    key = _fingerprint(fn, args)
    hit = key in _COMPILED
    if hit:
        CACHE_STATS["hits"] += 1
        compiled, lower_s, compile_s = _COMPILED[key]
    else:
        CACHE_STATS["misses"] += 1
        t0 = time.perf_counter()
        lowered = fn.lower(*args)
        lower_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        _COMPILED[key] = (compiled, lower_s, compile_s)

    try:
        cost = normalize_cost_analysis(compiled.cost_analysis())
    except Exception as exc:
        cost = {"flops": None, "bytes_accessed": None, "raw": {},
                "error": f"{type(exc).__name__}: {exc}"}
    try:
        memory = normalize_memory_analysis(compiled.memory_analysis())
    except Exception as exc:
        memory = {"available": False,
                  "error": f"{type(exc).__name__}: {exc}"}

    execute_s = None
    if execute:
        dyn = args if n_dynamic is None else args[:n_dynamic]
        t0 = time.perf_counter()
        out = compiled(*dyn)
        jax.block_until_ready(out)
        execute_s = time.perf_counter() - t0
        del out

    return {
        "label": label,
        "cost": cost,
        "memory": memory,
        "timings": {
            "lower_s": round(lower_s, 6),
            "compile_s": round(compile_s, 6),
            "execute_s": (round(execute_s, 6)
                          if execute_s is not None else None),
        },
        "compile_cache": {
            "cache_hit": hit,
            "hits": CACHE_STATS["hits"],
            "misses": CACHE_STATS["misses"],
            "jit_cache_size": _jit_cache_size(fn),
        },
        "device_memory_stats": device_memory_stats(device),
    }


def per_round(record: dict, rounds: int) -> dict:
    """Amortize a whole-scan attribution over its round count — the
    figure to compare across scan lengths and against round-rate
    benches."""
    r = max(int(rounds), 1)
    cost = record.get("cost", {})
    out = {}
    for key in ("flops", "bytes_accessed"):
        v = cost.get(key)
        out[key] = (v / r) if isinstance(v, (int, float)) else None
    return out
