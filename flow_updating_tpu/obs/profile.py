"""AOT cost attribution: what a compiled round program *costs*.

The telemetry subsystem (PR 2) records what happened per round; nothing
records what the compiled program itself costs — FLOPs, bytes moved
through HBM, device-memory residency, or how the wall time splits
between XLA compilation and execution.  That attribution is exactly what
communication-vs-compute trade-off work optimizes for (Gossip-PGA,
arXiv:2105.09080), and it is available *without instrumenting the
program*: ``jit(f).lower(...).compile()`` hands back XLA's own
``cost_analysis()`` / ``memory_analysis()`` for the exact executable the
plain path runs.  Profiling is therefore a pure *observer* — the
program it measures is bit-identical to the un-profiled one (asserted in
tests/test_profile.py).

Entry points:

* :func:`profile_program` — lower + compile + (optionally) execute one
  jitted callable, returning the normalized attribution record;
* :meth:`Engine.profile <flow_updating_tpu.engine.Engine.profile>` —
  attribution for the engine's configured kernel dispatch mode
  (edge / node / halo / pod);
* the ``profile`` CLI subcommand and ``bench.py --profile`` — the same
  record written as a ``flow-updating-profile-report/v1`` manifest;
* batched sweeps (``sweep --profile``) attach one record per shape
  bucket to the sweep manifest.

Repeated profiles of the same program are served from a small in-process
executable cache (so ``Engine.profile`` is cheap to call mid-run); the
hit/miss counters are part of every record — the "did this recompile?"
question the compile-cache counters exist to answer.
"""

from __future__ import annotations

import re
import time

import numpy as np

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4,
    "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}
_COLLECTIVES = ("all-gather", "all-reduce", "collective-permute",
                "reduce-scatter", "all-to-all")
# `f32[8,522]{1,0} all-gather(...)`; tuple-shaped collectives list every
# element shape: `(f32[522]{0}, f32[522]{0}) all-reduce(...)`
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# sync form ` = <shape> <kind>(`; async lowering splits each op into a
# `<kind>-start`/`<kind>-done` pair (see hlo_collective_bytes)
_COLLECTIVE_RE = re.compile(
    r"= (.+?) (" + "|".join(_COLLECTIVES) + r")(-start|-done)?\(")


def hlo_collective_bytes(hlo_text: str) -> dict:
    """Sum output bytes of collective ops in optimized HLO, by op kind.

    A ``lax.scan`` body appears once in HLO but executes every round, so
    on a round-scan program this is PER-ROUND, PER-SHARD traffic (plus
    any one-time prologue collectives, negligible and included).  Used
    by ``scripts/multichip_scaling.py`` and by the planned-vs-actual
    byte budget assertion in ``tests/test_parallel.py``."""
    per_kind: dict = {k: 0 for k in _COLLECTIVES}
    count = 0
    for line in hlo_text.splitlines():
        s = line.strip()
        # async pairs (TPU, or CPU/GPU with async collectives — the
        # overlap regime) are counted at the -done, whose output is the
        # result shape alone (the -start's tuple aliases the operand
        # buffers and would double-count)
        m = _COLLECTIVE_RE.search(s)
        if not m or m.group(3) == "-start":
            continue
        shapes, kind = m.group(1), m.group(2)
        nbytes = 0
        for dt, dims in _SHAPE_RE.findall(shapes):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        per_kind[kind] += nbytes
        count += 1
    return {"total": sum(per_kind.values()), "ops": count,
            **{k: v for k, v in per_kind.items() if v}}

#: process-wide AOT-executable cache counters (every record carries a
#: snapshot; reset_cache() zeroes them — test isolation)
CACHE_STATS = {"hits": 0, "misses": 0}

_COMPILED: dict = {}

#: CompiledMemoryStats field -> record key.  ``peak_memory_in_bytes`` is
#: only populated by some backends (TPU); see the fallback below.
_MEM_FIELDS = {
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "temp_size_in_bytes": "temp_bytes",
    "alias_size_in_bytes": "alias_bytes",
    "generated_code_size_in_bytes": "generated_code_bytes",
    "peak_memory_in_bytes": "peak_bytes",
}


def reset_cache() -> None:
    """Drop cached executables and zero the hit/miss counters."""
    _COMPILED.clear()
    CACHE_STATS["hits"] = CACHE_STATS["misses"] = 0


def _num(x):
    if isinstance(x, (np.floating, np.integer)):
        return x.item()
    return x


#: program-level cost_analysis keys worth recording; the per-operand
#: breakdown ("bytes accessed3{}", "utilization17{}", ...) is dozens of
#: keys of manifest noise
_RAW_KEYS = ("flops", "bytes accessed", "bytes accessedout{}",
             "transcendentals", "optimal_seconds", "utilization")


def normalize_cost_analysis(ca) -> dict:
    """XLA's ``cost_analysis()`` across jax versions (list-of-dict per
    partition, or a bare dict) -> ``{flops, bytes_accessed, raw}``."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    raw = {str(k): _num(v) for k, v in dict(ca or {}).items()
           if isinstance(v, (int, float, np.floating, np.integer))
           and str(k) in _RAW_KEYS}
    return {
        "flops": raw.get("flops"),
        "bytes_accessed": raw.get("bytes accessed"),
        "raw": raw,
    }


def normalize_memory_analysis(ma) -> dict:
    """``memory_analysis()`` -> byte counts.  ``peak_bytes`` uses XLA's
    own peak when the backend reports one; otherwise the live-set bound
    arguments + outputs + temps - aliased (what the program holds
    resident while running) with ``peak_source`` saying so."""
    if ma is None:
        return {"available": False}
    out: dict = {"available": True}
    for field, key in _MEM_FIELDS.items():
        v = getattr(ma, field, None)
        if v is not None:
            out[key] = int(v)
    if "peak_bytes" not in out:
        out["peak_bytes"] = (out.get("argument_bytes", 0)
                             + out.get("output_bytes", 0)
                             + out.get("temp_bytes", 0)
                             - out.get("alias_bytes", 0))
        out["peak_source"] = "arguments+outputs+temps-aliased"
    else:
        out["peak_source"] = "xla_peak_memory"
    return out


def device_memory_stats(device=None) -> dict | None:
    """The runtime allocator's view (``device.memory_stats()``): live
    ``bytes_in_use`` / high-water ``peak_bytes_in_use`` on TPU; None on
    backends that keep no stats (CPU)."""
    import jax

    d = device if device is not None else jax.devices()[0]
    try:
        stats = d.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {str(k): _num(v) for k, v in stats.items()}


def _jit_cache_size(fn):
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def _fingerprint(fn, args) -> tuple:
    """Executable-cache key: the callable plus every argument's aval (or
    its hash/repr for static leaves) — two calls with the same key lower
    to the same XLA program."""
    import jax

    leaves, treedef = jax.tree.flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append(("arr", tuple(shape), str(dtype)))
        else:
            try:
                sig.append(("st", hash(leaf)))
            except TypeError:
                sig.append(("st", repr(leaf)))
    return (fn, str(treedef), tuple(sig))


def profile_program(fn, args=(), *, n_dynamic=None, execute=True,
                    label=None, device=None) -> dict:
    """Lower + compile ``fn(*args)`` ahead of time and return the cost
    attribution record.

    ``fn`` is a ``jax.jit``-wrapped callable; ``args`` is the FULL
    argument tuple (static argnames included, exactly as a normal call);
    ``n_dynamic`` is how many leading args are dynamic — the compiled
    executable is invoked with ``args[:n_dynamic]`` (default: all).
    ``execute=False`` skips the timed execution (cost/memory only).

    The compiled executable is cached on the argument fingerprint, so
    repeated profiles of an unchanged program are hits (compile wall
    time is then the cached miss's measurement, flagged ``cache_hit``).
    Profiling never touches the jit call cache — the plain path's
    program is exactly what it was.
    """
    import jax

    key = _fingerprint(fn, args)
    hit = key in _COMPILED
    if hit:
        CACHE_STATS["hits"] += 1
        compiled, lower_s, compile_s = _COMPILED[key]
    else:
        CACHE_STATS["misses"] += 1
        t0 = time.perf_counter()
        lowered = fn.lower(*args)
        lower_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        _COMPILED[key] = (compiled, lower_s, compile_s)

    try:
        cost = normalize_cost_analysis(compiled.cost_analysis())
    except Exception as exc:
        cost = {"flops": None, "bytes_accessed": None, "raw": {},
                "error": f"{type(exc).__name__}: {exc}"}
    try:
        memory = normalize_memory_analysis(compiled.memory_analysis())
    except Exception as exc:
        memory = {"available": False,
                  "error": f"{type(exc).__name__}: {exc}"}

    execute_s = None
    if execute:
        dyn = args if n_dynamic is None else args[:n_dynamic]
        t0 = time.perf_counter()
        out = compiled(*dyn)
        jax.block_until_ready(out)
        execute_s = time.perf_counter() - t0
        del out

    return {
        "label": label,
        "cost": cost,
        "memory": memory,
        "timings": {
            "lower_s": round(lower_s, 6),
            "compile_s": round(compile_s, 6),
            "execute_s": (round(execute_s, 6)
                          if execute_s is not None else None),
        },
        "compile_cache": {
            "cache_hit": hit,
            "hits": CACHE_STATS["hits"],
            "misses": CACHE_STATS["misses"],
            "jit_cache_size": _jit_cache_size(fn),
        },
        "device_memory_stats": device_memory_stats(device),
    }


def overlap_report(state, plan, cfg, mesh, rounds: int, *, arrays=None,
                   repeats: int = 3, execute: bool = True,
                   mode: str = "overlap",
                   trace_dir: str | None = None) -> dict:
    """Overlap ratio of the halo kernel's split schedule: the fraction
    of the cut-edge exchange time hidden behind interior compute.

    Times three compilations of the SAME round scan — ``'ppermute'``
    (the serialized oracle), ``mode`` (the overlap schedule the run
    actually dispatches: ``'overlap'`` or ``'overlap_pallas'``), and
    ``'interior'`` (the schedule with the exchange elided, a
    timing-only probe) — best of ``repeats`` executions each, and
    reports::

        exchange_s = t_ppermute - t_interior   (the serialized wire)
        hidden_s   = t_ppermute - t_overlap    (what the split saved)
        overlap_ratio = hidden_s / exchange_s  (clamped to [0, 1])

    On a backend without async collectives (XLA:CPU) the ratio honestly
    reads ~0 — the schedule is testable everywhere but only hides wire
    time where the hardware can overlap it.  Attached to halo-mode
    profile manifests by :meth:`Engine.profile`."""
    from flow_updating_tpu.parallel import overlap as _ovl
    from flow_updating_tpu.parallel import sharded

    if mode not in ("overlap", "overlap_pallas"):
        raise ValueError(f"overlap_report measures an overlap schedule; "
                         f"got mode={mode!r}")
    times: dict = {}
    for m in ("ppermute", mode, "interior"):
        fn, args, nd = sharded.round_program(
            state, plan, cfg, mesh, rounds, arrays=arrays, halo=m,
            _internal=(m == "interior"))
        best = None
        for _ in range(max(int(repeats), 1)):
            rec = profile_program(fn, args, n_dynamic=nd,
                                  execute=execute, label=f"halo:{m}")
            t = rec["timings"]["execute_s"]
            if t is not None:
                best = t if best is None else min(best, t)
            if not execute:
                break
        times[m] = best
    out = {"rounds": int(rounds), "mode": mode,
           "schedule": _ovl.resolve_mode(plan, mode),
           "execute_s": {k: (round(v, 6) if v is not None else None)
                         for k, v in times.items()},
           "note": (f"overlap_ratio = (t_ppermute - t_{mode}) / "
                    "(t_ppermute - t_interior); 'interior' is a "
                    "timing-only probe with the exchange elided")}
    if any(v is None for v in times.values()):
        out.update({"exchange_s": None, "hidden_s": None,
                    "overlap_ratio": None})
        return out
    exchange, hidden, ratio = overlap_ratio_from_times(
        times["ppermute"], times[mode], times["interior"])
    out.update({"exchange_s": round(exchange, 6),
                "hidden_s": round(hidden, 6),
                "overlap_ratio": (round(ratio, 3)
                                  if ratio is not None else None)})
    if trace_dir and execute:
        out["measured"] = _measure_overlap_trace(
            state, plan, cfg, mesh, rounds, arrays=arrays, mode=mode,
            trace_dir=trace_dir)
        measured_ratio = (out["measured"] or {}).get(
            "overlap_ratio_measured")
        if measured_ratio is not None:
            # the device timeline carries the authoritative figure —
            # the three-schedule wall-clock arithmetic above stays as
            # the cross-check
            out["overlap_ratio_measured"] = measured_ratio
            out["overlap_ratio_source"] = "device-trace"
    return out


def _measure_overlap_trace(state, plan, cfg, mesh, rounds: int, *,
                           arrays, mode: str, trace_dir: str) -> dict:
    """Run the overlap-mode schedule once under ``jax.profiler.trace``
    and measure the wire/compute overlap from the captured per-op
    device slices (obs/timeline.py) — the measured twin of the
    inferred three-schedule ratio.  Contained: a capture or parse
    failure reports itself in the record, never breaks the report."""
    import jax

    from flow_updating_tpu.obs import timeline as _tl
    from flow_updating_tpu.parallel import sharded
    from flow_updating_tpu.utils.trace import annotate, trace as _trace

    try:
        fn, args, _nd = sharded.round_program(
            state, plan, cfg, mesh, rounds, arrays=arrays, halo=mode)
        jax.block_until_ready(fn(*args))    # compile + warm outside
        with _trace(trace_dir):
            with annotate("fu.overlap_capture"):
                jax.block_until_ready(fn(*args))
        measured = _tl.measured_overlap(trace_dir)
        if measured is None:
            return {"overlap_ratio_measured": None,
                    "error": f"profiler wrote no capture under "
                             f"{trace_dir}"}
        return measured
    except Exception as exc:
        return {"overlap_ratio_measured": None,
                "error": f"{type(exc).__name__}: {exc}"[:300]}


def overlap_ratio_from_times(t_serial: float, t_overlap: float,
                             t_interior: float):
    """``(exchange_s, hidden_s, overlap_ratio)`` from the three schedule
    timings — THE definition of the hidden fraction, shared by
    :func:`overlap_report` and the weak-scaling ladder so the manifest-
    embedded and banked figures can never use different formulas.
    ``overlap_ratio`` is None when the serialized wire cost is inside
    timing noise."""
    import math

    exchange = max(t_serial - t_interior, 0.0)
    hidden = max(t_serial - t_overlap, 0.0)
    ratio = (max(0.0, min(hidden / exchange, 1.0))
             if exchange > 1e-9 and math.isfinite(exchange) else None)
    return exchange, hidden, ratio


def per_round(record: dict, rounds: int) -> dict:
    """Amortize a whole-scan attribution over its round count — the
    figure to compare across scan lengths and against round-rate
    benches."""
    r = max(int(rounds), 1)
    cost = record.get("cost", {})
    out = {}
    for key in ("flops", "bytes_accessed"):
        v = cost.get(key)
        out[key] = (v / r) if isinstance(v, (int, float)) else None
    return out


# ---- payload-bytes attribution (DFL model scale, arXiv:2506.10607) ------


def payload_bytes_per_round(num_edges: int, features: int, *,
                            chunk: int | None = None,
                            feature_shards: int = 1,
                            dtype_bytes: int = 4) -> dict:
    """Edge-payload wire bytes of one underlying gossip round — the
    denominator of the DFL rounds/s-per-byte efficiency metric and the
    x-axis increment of convergence-vs-bytes curves.

    One round moves one ledger entry (flow + estimate, but the estimate
    rides the same message, so ONE payload word per lane per directed
    edge... the accounting convention is LANES: ``E * width`` payload
    words) across every directed edge:

    * monolithic: ``width = D`` — the whole model on every wire, every
      round;
    * chunked (``chunk=c``): ``width = c`` — each underlying round moves
      one ``(E, c)`` slice; a full model stream costs ``D/c`` rounds and
      the same TOTAL bytes as one monolithic round (chunking re-times
      the traffic, it never inflates it);
    * feature sharding divides the PER-DEVICE share by ``S_f`` without
      changing the global total (lanes move between device pairs of
      their own shard).

    Returns a dict with the global and per-device figures plus the
    full-model-stream cost, so bench rows and manifests can cite one
    accounting."""
    if features <= 0:
        raise ValueError("features must be >= 1 for payload accounting")
    width = int(chunk) if chunk else int(features)
    if chunk and (chunk <= 0 or features % chunk):
        raise ValueError(
            f"chunk={chunk} must be a positive divisor of D={features}")
    if feature_shards < 1:
        raise ValueError("feature_shards must be >= 1")
    per_round = num_edges * width * dtype_bytes
    return {
        "features": int(features),
        "chunk": int(chunk) if chunk else None,
        "width": width,
        "dtype_bytes": int(dtype_bytes),
        "bytes_per_round": per_round,
        "bytes_per_round_per_device": per_round // feature_shards,
        "rounds_per_model_stream": (int(features) // width),
        "bytes_per_model_stream": num_edges * features * dtype_bytes,
    }


def fused_round_report(kernel) -> dict | None:
    """HBM-pass and bytes-per-round attribution of a fused-round
    NodeKernel (``spmv='banded_fused'``) — the profile/plan manifest
    block ``regress --against`` gates: a fused program that silently
    grows extra HBM passes (a de-fusion regression) moves the
    ``bytes_per_round`` figure, which the >2% growth gate catches.

    Returns None for kernels without a fused spec (the caller embeds
    the block only when it applies)."""
    spec = getattr(getattr(kernel, "arrays", None), "ns_fused", None)
    if spec is None:
        return None
    import numpy as np

    from flow_updating_tpu.ops.pallas_round import fused_round_bytes

    feats = int(np.prod(kernel.feature_shape)) \
        if getattr(kernel, "feature_shape", ()) else 1
    import jax.numpy as jnp

    dtype_bytes = jnp.dtype(kernel.cfg.jnp_dtype).itemsize
    return fused_round_bytes(spec, dtype_bytes=dtype_bytes,
                             features=feats)


def dfl_efficiency(rate: float, bytes_per_round: float,
                   anchor_rate: float, anchor_bytes_per_round: float
                   ) -> float | None:
    """The DFL bytes-efficiency ratio: rounds/s per wire byte, relative
    to an anchor row (the D=64 monolithic record).  1.0 means a round
    that moves the same bytes as the anchor's costs the same wall-clock;
    the chunked schedule's whole point is keeping this near 1.0 while D
    grows by orders of magnitude (each chunked round does a D=64-sized
    unit of work)."""
    if not rate or not anchor_rate or not bytes_per_round \
            or not anchor_bytes_per_round:
        return None
    return (rate / bytes_per_round) / (anchor_rate / anchor_bytes_per_round)
