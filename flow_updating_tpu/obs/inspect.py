"""Fault localization ("blame"), run diffing and topology heatmaps.

The field layer (:mod:`flow_updating_tpu.obs.fields`) records WHERE a run
misbehaves; this module turns those fields into verdict-grade evidence:

* :func:`blame` — rank culprit node/edge ids for each failing global
  symptom: a **stall** blames straggler nodes whose error stopped
  dropping while still above threshold; a **mass leak** blames edge
  pairs whose flow ledgers lost antisymmetry (``flow[e] + flow[rev[e]]``
  far from 0 — exactly the pairing the Flow-Updating paper's invariant
  rests on); a **divergence** blames the origin of the first non-finite
  value.  ``doctor`` attaches these culprits to its check evidence when
  a field manifest is present (obs/health.py).
* :func:`diff_fields` — align two runs' field series on their common
  round grid and report per-node/per-metric deltas (the drop=0 vs
  drop>0, or CPU vs TPU backend, comparison tool).  Two identical-seed
  runs diff to zero.
* :func:`ascii_heatmap` — render a per-node field row over the topology
  generator's coordinates (grids render as the grid; everything else
  wraps node-id order into rows), shades ``" .:-=+*#%@"``.

Everything here is host-side numpy over
:class:`~flow_updating_tpu.obs.fields.FieldSeries` (live runs) or
manifest ``fields`` blocks (offline) — no jax import.
"""

from __future__ import annotations

import numpy as np

from flow_updating_tpu.obs.fields import FieldSeries


def _as_series(fields) -> FieldSeries:
    if isinstance(fields, FieldSeries):
        return fields
    if isinstance(fields, dict):
        return FieldSeries.from_jsonable(fields)
    raise TypeError(
        f"expected a FieldSeries or a manifest fields block, got "
        f"{type(fields).__name__}")


def _node_ids(series: FieldSeries, row: int, local_idx) -> np.ndarray:
    """Recorded-row column index -> original node id (identity unless the
    run recorded only the topk worst nodes)."""
    local_idx = np.asarray(local_idx)
    if series.topk_idx is None:
        return local_idx
    return np.asarray(series.topk_idx[row])[local_idx]


def blame_stall(fields, *, threshold: float = 1e-6, window: int = 8,
                min_drop: float = 0.05, top: int = 5) -> list:
    """Straggler nodes: still above ``threshold`` at the end AND
    improving less than ``min_drop`` (fractional) over the trailing
    ``window`` recorded rows — ranked by final error.  Needs the
    ``node_err`` field; returns ``[{"node", "final_err",
    "drop_fraction"}, ...]`` (empty when nothing qualifies)."""
    s = _as_series(fields)
    if "node_err" not in s.node or len(s) == 0:
        return []
    mag = s.pooled("node_err")                       # (R, cols)
    final = mag[-1]
    w = min(int(window), mag.shape[0] - 1)
    if w < 1:
        # a single recorded row cannot show a trend; rank by error alone
        drop = np.zeros_like(final)
    else:
        ref = mag[-1 - w]
        with np.errstate(divide="ignore", invalid="ignore"):
            drop = np.where(ref > 0, 1.0 - final / ref, 0.0)
    stuck = (final > threshold) & (drop < min_drop)
    if not stuck.any():
        return []
    order = np.argsort(-np.where(stuck, final, -np.inf))[:top]
    out = []
    for i in order:
        if not stuck[i]:
            break
        out.append({
            "node": int(_node_ids(s, -1, i)),
            "final_err": float(final[i]),
            "drop_fraction": float(drop[i]),
        })
    return out


def blame_leak(fields, *, tail: int = 4, rtol: float | None = None,
               inflight_factor: float = 2.0, top: int = 5) -> list:
    """Leaking edge pairs: ``|flow[e] + flow[rev[e]]|`` (the antisymmetry
    residual) over the trailing ``tail`` recorded rows, ranked per
    undirected pair.  Needs the ``edge_flow`` field plus the manifest's
    edge arrays; returns ``[{"edge", "rev", "src", "dst", "residual"},
    ...]``.

    A residual the traffic can explain is not a leak: sent-but-
    undelivered flow updates unbalance a pair transiently by O(the local
    estimate error) — the same in-flight allowance the doctor's global
    mass check applies (obs/health.py) — and float roundoff contributes
    64 ULPs of the flow magnitude (float32 ULPs by default, since the
    manifest does not record the dtype; pass ``rtol`` for a stricter
    float64 analysis)."""
    s = _as_series(fields)
    if "edge_flow" not in s.edge or s.edges is None or len(s) == 0:
        return []
    flow = np.asarray(s.edge["edge_flow"], np.float64)   # (R, E)
    rev = np.asarray(s.edges["rev"], np.int64)
    w = max(min(int(tail), flow.shape[0]), 1)
    resid = np.abs(flow[-w:] + flow[-w:][:, rev]).max(axis=0)   # (E,)
    scale = float(np.max(np.abs(flow[-w:]))) if flow.size else 0.0
    tol = (rtol if rtol is not None else 64.0 * np.finfo(np.float32).eps) \
        * max(scale, 1.0)
    if "node_err" in s.node:
        tol += inflight_factor * float(np.max(s.pooled("node_err")[-w:]))
    # one entry per undirected pair (the residual is symmetric)
    e_ids = np.arange(resid.shape[0])
    primary = e_ids <= rev
    bad = primary & (resid > tol)
    if not bad.any():
        return []
    order = np.argsort(-np.where(bad, resid, -np.inf))[:top]
    src = np.asarray(s.edges["src"], np.int64)
    dst = np.asarray(s.edges["dst"], np.int64)
    out = []
    for e in order:
        if not bad[e]:
            break
        out.append({
            "edge": int(e), "rev": int(rev[e]),
            "src": int(src[e]), "dst": int(dst[e]),
            "residual": float(resid[e]),
        })
    return out


def _mad_dev(x: np.ndarray) -> np.ndarray:
    """|x - median| in median-absolute-deviation units (robust z-score;
    the scale a planted anomaly cannot poison the way it poisons a
    mean/std)."""
    med = np.median(x)
    mad = np.median(np.abs(x - med))
    return np.abs(x - med) / (mad + 1e-12)


def blame_liar(fields, *, significance: float = 30.0, top: int = 5) -> list:
    """Byzantine value-liars: nodes whose mass anomaly — own
    MAD-normalized ``node_mass`` deviation plus the mean deviation of
    their neighborhood (one diffusion hop) — stands out.

    A liar's poison concentrates: every neighbor counts the lie in its
    average, so the deviation field peaks ON the liar and its ring; the
    one-hop diffusion makes the common center rank first whether the
    extreme mass sits on the liar itself (unprotected) or on its
    neighbors (clipped flows).  Honest runs measure a diffused score
    < ~3; a planted liar measures hundreds (the ``significance`` gate
    keeps honest runs silent).  Needs ``node_mass`` + the edge arrays;
    returns ``[{"node", "score", "mass"}, ...]`` ranked."""
    s = _as_series(fields)
    if "node_mass" not in s.node or s.edges is None or len(s) == 0 \
            or s.topk_idx is not None:
        return []
    mass = np.asarray(s.node["node_mass"], np.float64)[-1]
    if mass.ndim > 1:        # vector payloads: features summed, like mass
        mass = mass.sum(axis=tuple(range(1, mass.ndim)))
    dev = _mad_dev(mass)
    src = np.asarray(s.edges["src"], np.int64)
    dst = np.asarray(s.edges["dst"], np.int64)
    n = mass.shape[0]
    nsum = np.zeros(n)
    ncnt = np.zeros(n)
    np.add.at(nsum, src, dev[dst])
    np.add.at(ncnt, src, 1.0)
    score = dev + nsum / np.maximum(ncnt, 1.0)
    order = np.argsort(-score, kind="stable")[:top]
    return [{"node": int(i), "score": float(score[i]),
             "mass": float(mass[i])}
            for i in order if score[i] >= significance]


def blame_pinned(fields, *, significance: float = 50.0,
                 top: int = 5) -> list:
    """Frozen-out extremes: nodes whose in-view ``edge_est`` entries
    (what some neighbor last heard them claim) sit wildly off the
    consensus in MAD units.

    Under ``robust='trim'`` an excluded liar's entry is never
    overwritten by the owner's fire — the lie stays pinned at full
    magnitude while every kept entry tracks the tightening consensus
    (honest runs measure ~1; a planted liar measures > 10^6).  Returns
    ``[{"node", "score", "pinned_value"}, ...]`` ranked."""
    s = _as_series(fields)
    if "edge_est" not in s.edge or s.edges is None or len(s) == 0:
        return []
    est = np.asarray(s.edge["edge_est"], np.float64)[-1]
    dev = _mad_dev(est)
    dst = np.asarray(s.edges["dst"], np.int64)
    n = int(dst.max()) + 1 if dst.size else 0
    score = np.zeros(n)
    value = np.zeros(n)
    np.maximum.at(score, dst, dev)
    if dst.size:
        # each node's pinned value = est at its max-dev in-view entry;
        # reversed fancy assignment makes the lowest edge id win ties,
        # matching argmax-first semantics, in one vectorized pass
        at_max = np.flatnonzero(dev >= score[dst])[::-1]
        value[dst[at_max]] = est[at_max]
    order = np.argsort(-score, kind="stable")[:top]
    return [{"node": int(i), "score": float(score[i]),
             "pinned_value": float(value[i])}
            for i in order if score[i] >= significance]


def blame_cut(fields, *, gate: float = 0.2, factor: float = 3.0,
              top: int = 5) -> list:
    """Cut/partitioned links: edge pairs whose antisymmetry residual
    AFTER the initial mixing transient dwarfs the population.

    When a link dies mid-run the sender's ledger keeps moving while the
    receiver's mirror is frozen — the pair residual grows to the full
    standing displacement across the dead link, an order above the
    population's in-flight noise.  The transient gate (first recorded
    row where the mean node error fell to ``gate``× its initial value)
    keeps the early mixing burst — where EVERY pair is transiently
    unbalanced — out of the ranking.  A pair is blamed when its
    residual exceeds ``factor`` × the population's 90th percentile.
    Needs ``edge_flow`` + ``node_err``; returns ``[{"edge", "rev",
    "src", "dst", "residual"}, ...]``."""
    s = _as_series(fields)
    if ("edge_flow" not in s.edge or "node_err" not in s.node
            or s.edges is None or len(s) < 2 or s.topk_idx is not None):
        return []
    mean_err = s.pooled("node_err").mean(axis=1)
    past = np.flatnonzero(mean_err <= gate * max(mean_err[0], 1e-30))
    t0 = int(past[0]) if past.size else 0
    flow = np.asarray(s.edge["edge_flow"], np.float64)[t0:]
    if flow.shape[0] == 0:
        return []
    rev = np.asarray(s.edges["rev"], np.int64)
    resid = np.abs(flow + flow[:, rev]).max(axis=0)
    primary = np.arange(resid.shape[0]) <= rev
    pop = resid[primary]
    thr = factor * max(float(np.percentile(pop, 90.0)) if pop.size
                       else 0.0, 1e-30)
    pr = np.where(primary, resid, -np.inf)
    order = np.argsort(-pr, kind="stable")[:top]
    src = np.asarray(s.edges["src"], np.int64)
    dst = np.asarray(s.edges["dst"], np.int64)
    return [{"edge": int(e), "rev": int(rev[e]), "src": int(src[e]),
             "dst": int(dst[e]), "residual": float(resid[e])}
            for e in order if pr[e] > thr]


def blame_partition(fields, membership, bridge_edges, *,
                    gate: float = 0.2, factor: float = 3.0) -> dict | None:
    """Localize a partitioned community: the block ALL of whose bridge
    edges are blamed by :func:`blame_cut` (with planted-partition
    metadata from the ``community`` generator, nothing is re-derived).
    Returns ``{"block", "edges", "residual"}`` for the smallest fully
    cut block, or None."""
    s = _as_series(fields)
    cut = blame_cut(s, gate=gate, factor=factor,
                    top=max(16, 2 * len(bridge_edges)))
    if not cut or s.edges is None:
        return None
    memb = np.asarray(membership, np.int64)
    src = np.asarray(s.edges["src"], np.int64)
    dst = np.asarray(s.edges["dst"], np.int64)
    blamed = set()
    for c in cut:
        blamed.add(c["edge"])
        blamed.add(c["rev"])
    candidates = []
    for b in np.unique(memb):
        bridges = {int(e) for e in bridge_edges
                   if memb[src[e]] == b or memb[dst[e]] == b}
        if bridges and bridges <= blamed:
            candidates.append((len(bridges), int(b), sorted(bridges)))
    if not candidates:
        return None
    nb, block, edges = sorted(candidates)[0]
    resid = max(c["residual"] for c in cut
                if c["edge"] in edges or c["rev"] in edges)
    return {"block": block, "edges": edges, "residual": float(resid)}


def blame_sweep(manifest: dict, *, top: int = 3) -> dict:
    """Blame over a ``flow-updating-sweep-report/v1`` manifest: rank
    instances by how badly they ended (diverged/non-converged first,
    then final RMSE) and cite each lane's recorded worst nodes as its
    stragglers.  Returns ``{"worst_instance", "instances": [...]}`` —
    the per-lane verdict ``inspect --blame`` prints for sweeps."""
    instances = manifest.get("instances")
    if not isinstance(instances, list) or not instances:
        raise ValueError(
            "sweep manifest has no instance records to blame (was the "
            "sweep written with `sweep --report PATH`?)")

    def _key(rec):
        conv = rec.get("convergence") or {}
        final = conv.get("final_rmse")
        final = float("inf") if final is None or not np.isfinite(final) \
            else float(final)
        return (bool(conv.get("converged")), -final)

    ranked = sorted(instances, key=_key)
    out = []
    for rec in ranked[:top]:
        conv = rec.get("convergence") or {}
        out.append({
            "instance": rec.get("instance"),
            "tag": rec.get("tag"),
            "converged": bool(conv.get("converged")),
            "converged_round": conv.get("converged_round"),
            "final_rmse": conv.get("final_rmse"),
            "stragglers": rec.get("worst_nodes") or [],
        })
    return {"worst_instance": out[0] if out else None,
            "instances": out,
            "ranked_of": len(instances)}


def blame_recovery(manifest: dict) -> dict:
    """Blame over a ``flow-updating-recovery-report/v1`` manifest: rank
    the registered infra faults (flow_updating_tpu.resilience.chaos) by
    how strongly the recovery evidence implicates each — the chaos
    harness asserts its planted fault ranks first.

    The evidence → fault map (each signature is written by a different
    layer, so they compose rather than collide):

    * a truncated WAL tail (``wal.torn_bytes_truncated``) → the journal
      was torn mid-append (``truncate_wal_tail``);
    * a ring archive classified ``truncated`` by its sidecar → a torn
      archive copy (``corrupt_newest_ckpt``); ``bitflipped`` (size
      intact, digest off) → in-place corruption (``bitflip_archive``);
    * stale ``*.tmp.*`` files swept at recovery → the crash hit between
      the atomic write's temp and its rename
      (``kill_mid_checkpoint``);
    * watchdog quarantines with reason ``nan`` →
      ``nan_poison_lane``;
    * degraded-mode episodes / deferred admissions →
      ``admission_storm``;
    * a bare replay with none of the above → a plain
      ``kill_at_segment`` (every crash recovery replays, so this only
      ranks first when nothing more specific fired).
    """
    rec = manifest.get("recovery") if isinstance(manifest, dict) else None
    if not isinstance(rec, dict):
        raise ValueError(
            "manifest has no recovery block to blame (recovery "
            "manifests are written by the chaos harness / the "
            "serve|query CLIs' --recover path)")
    wal = rec.get("wal") or {}
    ring = rec.get("ring") or {}
    wd = rec.get("watchdog") or {}
    replay = rec.get("replay") or {}
    scanned = ring.get("scanned") or []
    scores: dict = {}

    def _vote(fault, score, why):
        cur = scores.get(fault)
        if cur is None or score > cur["score"]:
            scores[fault] = {"fault": fault, "score": score,
                             "evidence": why}

    torn = int(wal.get("torn_bytes_truncated", 0) or 0)
    if torn or wal.get("torn_tail"):
        _vote("truncate_wal_tail", 3,
              f"WAL tail torn ({torn} bytes truncated on open)")
    for s in scanned:
        if s.get("integrity") == "truncated":
            _vote("corrupt_newest_ckpt", 3,
                  f"{s.get('path')} shrank vs its integrity sidecar")
        elif s.get("integrity") == "bitflipped":
            _vote("bitflip_archive", 3,
                  f"{s.get('path')} digest mismatch at intact size")
    if rec.get("stale_tmp_swept"):
        _vote("kill_mid_checkpoint", 3,
              f"stale atomic-write temp(s) swept: "
              f"{rec['stale_tmp_swept']}")
    nan_acts = [a for a in (wd.get("actions") or [])
                if a.get("reason") == "nan"]
    if nan_acts:
        # score 4: a quarantine is the most specific evidence there is
        # — a storm that happens to accompany the poisoned workload
        # (deferred admissions, score 3) must not outrank it
        _vote("nan_poison_lane", 4,
              f"{len(nan_acts)} lane(s) quarantined with non-finite "
              "probe entries")
    if wd.get("degraded"):
        # a storm DEFERS admissions (backoff active while lanes free
        # up); a brief full-lane blip records an episode with zero
        # deferrals — weak evidence that must not outrank a specific
        # fault like a NaN quarantine
        deferred = int(wd.get("deferred_admissions", 0) or 0)
        _vote("admission_storm", 3 if deferred else 1,
              f"{len(wd['degraded'])} lane-exhaustion episode(s), "
              f"{deferred} deferred admissions")
    if int(replay.get("records_replayed", 0) or 0) > 0:
        _vote("kill_at_segment", 1,
              f"crash recovery replayed "
              f"{replay.get('records_replayed')} journaled record(s)")
    ranked = sorted(scores.values(),
                    key=lambda v: (-v["score"], v["fault"]))
    return {"ranked": ranked,
            "top": ranked[0]["fault"] if ranked else None}


def blame_divergence(fields) -> dict | None:
    """Origin of the first non-finite value: the earliest recorded row
    any per-node field goes NaN/Inf, and the node ids carrying it.
    Returns ``{"round", "field", "nodes"}`` or None when every field is
    finite."""
    s = _as_series(fields)
    first_row, first_field = None, None
    for name, v in s.node.items():
        v = np.asarray(v, np.float64)
        bad = ~np.isfinite(v)
        if v.ndim > 2:
            bad = bad.any(axis=tuple(range(2, v.ndim)))
        rows = np.flatnonzero(bad.any(axis=1))
        if rows.size and (first_row is None or rows[0] < first_row):
            first_row, first_field = int(rows[0]), name
    if first_row is None:
        return None
    v = np.asarray(s.node[first_field], np.float64)
    bad = ~np.isfinite(v[first_row])
    if bad.ndim > 1:
        bad = bad.any(axis=tuple(range(1, bad.ndim)))
    nodes = [int(_node_ids(s, first_row, i))
             for i in np.flatnonzero(bad)[:16]]
    return {
        "round": int(s.t[first_row]) if len(s) else first_row,
        "field": first_field,
        "nodes": nodes,
    }


def blame(fields, *, threshold: float = 1e-6, top: int = 5,
          membership=None, bridge_edges=None) -> dict:
    """The full localization bundle: one ranked culprit list per
    symptom.  Symptoms whose prerequisite fields were not recorded come
    back as ``None`` with a ``skipped`` note.

    Beyond the stall/leak/divergence triple, the adversarial symptoms of
    the scenario registry (flow_updating_tpu.scenarios) are ranked when
    their fields are present: ``liar`` (Byzantine mass anomaly),
    ``pinned`` (trimmed-out extreme claims), ``cut`` (dead-link pair
    residuals) and — when planted-partition ``membership`` +
    ``bridge_edges`` metadata is supplied — ``partition``."""
    s = _as_series(fields)
    out: dict = {}
    div = blame_divergence(s)
    out["divergence"] = div
    if "node_err" in s.node:
        out["stall"] = blame_stall(s, threshold=threshold, top=top)
    else:
        out["stall"] = None
        out.setdefault("skipped", []).append(
            "stall blame needs the node_err field")
    if "edge_flow" in s.edge and s.edges is not None:
        out["leak"] = blame_leak(s, top=top)
    else:
        out["leak"] = None
        out.setdefault("skipped", []).append(
            "leak blame needs the edge_flow field (edge-ledger kernels)")
    if "node_mass" in s.node and s.edges is not None \
            and s.topk_idx is None:
        out["liar"] = blame_liar(s, top=top)
    else:
        out["liar"] = None
        out.setdefault("skipped", []).append(
            "liar blame needs full node_mass rows + the edge arrays")
    if "edge_est" in s.edge and s.edges is not None:
        out["pinned"] = blame_pinned(s, top=top)
    else:
        out["pinned"] = None
        out.setdefault("skipped", []).append(
            "pinned blame needs the edge_est field")
    if ("edge_flow" in s.edge and "node_err" in s.node
            and s.edges is not None and s.topk_idx is None):
        out["cut"] = blame_cut(s, top=top)
        if membership is not None and bridge_edges is not None:
            out["partition"] = blame_partition(s, membership, bridge_edges)
    else:
        out["cut"] = None
        out.setdefault("skipped", []).append(
            "cut blame needs full edge_flow + node_err rows")
    return out


def diff_fields(a, b, *, top: int = 5, atol: float = 0.0) -> dict:
    """Align two runs' field series on their common round grid and
    report per-field deltas.

    Returns ``{"rounds_compared", "identical", "fields": {name:
    {"max_abs_delta", "mean_abs_delta", "worst": [{"node"|"edge",
    "round", "delta"}, ...]}}}``.  ``identical`` is True when every
    common field agrees within ``atol`` everywhere (two identical-seed
    runs report exactly zero).  Runs recorded with topk cannot be
    aligned entity-wise and are rejected."""
    sa, sb = _as_series(a), _as_series(b)
    if sa.spec.topk or sb.spec.topk:
        raise ValueError(
            "diff needs full field rows; topk-downsampled runs record "
            "different node subsets per round and cannot be aligned")
    ta, tb = np.asarray(sa.t), np.asarray(sb.t)
    common, ia, ib = np.intersect1d(ta, tb, return_indices=True)
    if common.size == 0:
        raise ValueError(
            "the two runs share no recorded rounds (check --rounds and "
            "the field stride)")
    names = sorted((set(sa.node) & set(sb.node))
                   | (set(sa.edge) & set(sb.edge)))
    if sa.conv_round is not None and sb.conv_round is not None:
        names.append("node_conv_round")
    if not names:
        raise ValueError("the two runs share no recorded fields")
    fields: dict = {}
    worst_overall = 0.0
    for name in names:
        va = np.asarray(sa[name], np.float64)
        vb = np.asarray(sb[name], np.float64)
        if name != "node_conv_round":
            va, vb = va[ia], vb[ib]
        if va.shape != vb.shape:
            raise ValueError(
                f"field {name!r} has shape {va.shape} in A but "
                f"{vb.shape} in B — different topologies cannot be "
                "diffed entity-wise")
        delta = va - vb
        mag = np.abs(delta)
        if mag.ndim > 2:
            mag = mag.max(axis=tuple(range(2, mag.ndim)))
        entry = {
            "max_abs_delta": float(mag.max()) if mag.size else 0.0,
            "mean_abs_delta": float(mag.mean()) if mag.size else 0.0,
        }
        kind = "edge" if name in sa.edge else "node"
        if mag.size and entry["max_abs_delta"] > atol:
            flat = np.argsort(-mag, axis=None)[:top]
            worst = []
            for f in flat:
                if name == "node_conv_round":
                    ent, val = int(f), float(delta[f])
                    worst.append({kind: ent, "delta": val})
                else:
                    r, ent = np.unravel_index(f, mag.shape)
                    worst.append({kind: int(ent),
                                  "round": int(common[r]),
                                  "delta": float(delta[r, ent]
                                                 if delta.ndim == 2
                                                 else mag[r, ent])})
            entry["worst"] = worst
        fields[name] = entry
        worst_overall = max(worst_overall, entry["max_abs_delta"])
    return {
        "rounds_compared": int(common.size),
        "fields_compared": [n for n in names],
        "identical": bool(worst_overall <= atol),
        "max_abs_delta": worst_overall,
        "fields": fields,
    }


# ---- coordinates + heatmap ----------------------------------------------

def node_coordinates(topo) -> np.ndarray | None:
    """(N, 2) integer plot coordinates from the topology generator's
    structure descriptor, where one exists: grids/tori use their (row,
    col); rings/complete graphs a single row.  None otherwise (the
    heatmap then wraps node-id order)."""
    s = getattr(topo, "structure", None)
    if s is None:
        return None
    h = getattr(s, "h", None)
    w = getattr(s, "w", None)
    if h is not None and w is not None and h * w == topo.num_nodes:
        ids = np.arange(topo.num_nodes)
        return np.stack([ids // w, ids % w], axis=1)
    n = getattr(s, "n", None)
    if n == topo.num_nodes:
        ids = np.arange(topo.num_nodes)
        return np.stack([np.zeros_like(ids), ids], axis=1)
    return None


_SHADES = " .:-=+*#%@"


def ascii_heatmap(values, coords=None, *, width: int = 64,
                  log: bool = True) -> str:
    """Render one per-node field row as an ASCII heatmap.

    ``coords`` (``(N, 2)`` ints) lays nodes out on their generator
    geometry; without them, node-id order wraps into rows of ``width``.
    Magnitudes bin into ``" .:-=+*#%@"`` (log-scaled by default — error
    fields span orders of magnitude); a legend line maps the extremes."""
    v = np.abs(np.asarray(values, np.float64))
    if v.ndim > 1:
        v = v.max(axis=tuple(range(1, v.ndim)))
    n = v.shape[0]
    if coords is not None:
        coords = np.asarray(coords, np.int64)
        rows = int(coords[:, 0].max()) + 1
        cols = int(coords[:, 1].max()) + 1
    else:
        cols = min(int(width), n)
        rows = -(-n // cols)
        ids = np.arange(n)
        coords = np.stack([ids // cols, ids % cols], axis=1)
    vmax = float(v.max())
    finite = np.isfinite(v)
    if log:
        pos = v[finite & (v > 0)]
        lo = float(pos.min()) if pos.size else 1.0
        hi = max(vmax, lo)
        if hi > lo:
            scale = np.zeros_like(v)
            with np.errstate(divide="ignore"):
                scale[finite] = np.clip(
                    (np.log10(np.maximum(v[finite], lo)) - np.log10(lo))
                    / (np.log10(hi) - np.log10(lo)), 0.0, 1.0)
        else:
            scale = np.where(v > 0, 1.0, 0.0)
    else:
        scale = v / vmax if vmax > 0 else np.zeros_like(v)
    idx = np.minimum((scale * (len(_SHADES) - 1)).astype(int),
                     len(_SHADES) - 1)
    grid = np.full((rows, cols), " ", dtype="<U1")
    for i in range(n):
        r, c = coords[i]
        grid[r, c] = "!" if not finite[i] else _SHADES[idx[i]]
    lines = ["".join(row) for row in grid]
    lines.append(f"[{_SHADES[0]}..{_SHADES[-1]}] 0..{vmax:.3e}"
                 + (" (log)" if log else "") + "; '!' = non-finite")
    return "\n".join(lines)
