"""Unified run manifests: one self-describing JSON artifact per run.

The reference leaves no machine-readable record of a run at all — its
output is the watcher's eye-ball dump plus whatever scrolled past on
stderr.  A manifest binds everything needed to *audit* a run into one
document: the exact invocation (argv + resolved config), the topology
(size + content fingerprint, the same digest that binds checkpoints to
their graph), the execution substrate (backend/devices/versions),
compile-vs-execute wall times, the final convergence report, and — when
telemetry was enabled — the full per-round metric series.

``run --report PATH``, ``train --report PATH`` and ``bench.py --report
PATH`` all write this schema (``flow-updating-run-report/v1``).

Batched sweeps (``sweep --report PATH``) write the sibling
``flow-updating-sweep-report/v1``: same environment/config/argv binding,
but ``instances`` replaces the single run report — one record per packed
instance (topology fingerprint, seed, resolved per-instance params,
convergence with the effective early-exit round), in grid fan-out order.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

SCHEMA = "flow-updating-run-report/v1"
SWEEP_SCHEMA = "flow-updating-sweep-report/v1"
PROFILE_SCHEMA = "flow-updating-profile-report/v1"
FIELD_SCHEMA = "flow-updating-field-report/v1"
PLAN_SCHEMA = "flow-updating-plan-report/v1"
SERVICE_SCHEMA = "flow-updating-service-report/v1"
SCENARIO_SCHEMA = "flow-updating-scenario-report/v1"
AUDIT_SCHEMA = "flow-updating-audit-report/v1"
QUERY_SCHEMA = "flow-updating-query-report/v1"
RECOVERY_SCHEMA = "flow-updating-recovery-report/v1"
BUDGET_SCHEMA = "flow-updating-budget-report/v1"
#: The serving flight recorder's embedded block (NOT a top-level
#: manifest schema): serve/query/recovery manifests carry it under the
#: ``serving_trace`` key — declared SLO targets + streaming metrics +
#: span chains (obs/metrics.py, obs/spans.py; doctor's ``slo_latency``
#: / ``span_complete`` / ``metrics_consistency`` checks judge it).
SERVING_TRACE_SCHEMA = "flow-updating-serving-trace/v1"
#: The perf lens' embedded block (NOT a top-level manifest schema):
#: profile/plan/bench manifests carry it under the ``perf_lens`` key —
#: the backend hardware model, per-program roofline records and their
#: ``roofline_frac`` reconciliation (obs/roofline.py; doctor's
#: ``roofline_sane`` / ``roofline_floor`` checks judge it).
PERF_LENS_SCHEMA = "flow-updating-perf-lens/v1"


def environment_info() -> dict:
    """Backend/device/version facts (imports jax lazily; safe pre-pin)."""
    info: dict = {"python": sys.version.split()[0]}
    try:
        import jax

        devs = jax.devices()
        info.update({
            "jax": jax.__version__,
            "backend": devs[0].platform,
            "device_kind": getattr(devs[0], "device_kind", str(devs[0])),
            "device_count": len(devs),
            "process_count": jax.process_count(),
            "x64": bool(jax.config.jax_enable_x64),
        })
    except Exception as exc:  # backend init can fail; the manifest must not
        info["backend_error"] = f"{type(exc).__name__}: {exc}"
    try:
        import numpy as np

        info["numpy"] = np.__version__
    except Exception:
        pass
    return info


def topology_summary(topo) -> dict:
    """Size + degree stats + the checkpoint-grade content fingerprint."""
    import numpy as np

    from flow_updating_tpu.utils.checkpoint import topology_fingerprint

    deg = np.asarray(topo.out_deg)
    out = topology_fingerprint(topo)
    out.update({
        "degree_min": int(deg.min()) if deg.size else 0,
        "degree_mean": round(float(deg.mean()), 3) if deg.size else 0.0,
        "degree_max": int(deg.max()) if deg.size else 0,
        "true_mean": float(topo.true_mean),
    })
    return out


def _config_dict(config) -> dict:
    if config is None:
        return {}
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        return dataclasses.asdict(config)
    if isinstance(config, dict):
        return dict(config)
    return {"repr": repr(config)}


def build_manifest(*, argv=None, config=None, topo=None, report=None,
                   timings=None, telemetry=None, extra=None) -> dict:
    """Assemble the v1 manifest.  ``telemetry`` is a
    :class:`~flow_updating_tpu.obs.telemetry.TelemetrySeries` (or None);
    ``config`` may be a dataclass, a dict, or a dict of dataclasses."""
    manifest = {
        "schema": SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "config": (
            {k: _config_dict(v) for k, v in config.items()}
            if isinstance(config, dict) else _config_dict(config)
        ),
        "topology": topology_summary(topo) if topo is not None else None,
        "environment": environment_info(),
        "timings": dict(timings) if timings else None,
        "report": report,
    }
    if telemetry is not None and len(telemetry):
        manifest["telemetry"] = {
            "metrics": list(telemetry.metrics),
            "rounds": len(telemetry),
            "series": telemetry.to_jsonable(),
        }
    if extra:
        manifest.update(extra)
    return manifest


def build_sweep_manifest(*, argv=None, config=None, instances=None,
                         summary=None, timings=None,
                         extra=None) -> dict:
    """Assemble the sweep-shaped v1 manifest: the run manifest's
    environment/config/argv binding with one record per packed instance
    (``instances``: each carrying its own topology fingerprint, params
    and convergence) plus the sweep-level ``summary`` (bucket shapes,
    compile count, aggregate timings)."""
    manifest = {
        "schema": SWEEP_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "config": (
            {k: _config_dict(v) for k, v in config.items()}
            if isinstance(config, dict) else _config_dict(config)
        ),
        "environment": environment_info(),
        "summary": dict(summary) if summary else None,
        "timings": dict(timings) if timings else None,
        "instances": list(instances) if instances is not None else [],
    }
    if extra:
        manifest.update(extra)
    return manifest


def build_profile_manifest(*, argv=None, config=None, topo=None,
                           profile=None, extra=None) -> dict:
    """Assemble the profile-shaped v1 manifest: the run manifest's
    argv/config/topology/environment binding around one AOT cost
    attribution record (``Engine.profile()`` /
    :func:`flow_updating_tpu.obs.profile.profile_program` output)."""
    manifest = {
        "schema": PROFILE_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "config": (
            {k: _config_dict(v) for k, v in config.items()}
            if isinstance(config, dict) else _config_dict(config)
        ),
        "topology": topology_summary(topo) if topo is not None else None,
        "environment": environment_info(),
        "profile": profile,
    }
    if extra:
        manifest.update(extra)
    return manifest


def build_plan_manifest(*, argv=None, config=None, topo=None,
                        plan=None, measured=None, extra=None) -> dict:
    """Assemble the plan-shaped v1 manifest: the run manifest's
    argv/config/topology/environment binding around one topology-compiler
    decision (``PlanDecision.describe()`` — kernel/spmv choice, band
    statistics, predicted per-candidate cost).  ``measured`` optionally
    records per-candidate measured rates (``{candidate:
    rounds_per_sec}``, e.g. from ``bench.py --generator``) so the doctor
    can audit "auto picked a slower plan than available"
    (``obs.health.check_plan``)."""
    manifest = {
        "schema": PLAN_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "config": (
            {k: _config_dict(v) for k, v in config.items()}
            if isinstance(config, dict) else _config_dict(config)
        ),
        "topology": topology_summary(topo) if topo is not None else None,
        "environment": environment_info(),
        "plan": dict(plan) if plan else None,
    }
    if measured:
        manifest["measured"] = dict(measured)
    if extra:
        manifest.update(extra)
    return manifest


def build_field_manifest(*, argv=None, config=None, topo=None,
                         fields=None, report=None, timings=None,
                         extra=None) -> dict:
    """Assemble the field-shaped v1 manifest: the run manifest's
    argv/config/topology/environment binding around one
    :class:`~flow_updating_tpu.obs.fields.FieldSeries` — the per-node /
    per-edge field block plus (when the run recorded full rows) the
    GLOBAL series re-derived by reducing the fields, under the standard
    ``telemetry`` key so the doctor's series checks run unchanged on
    field manifests (and can then cite culprit ids from the fields —
    obs/health.py)."""
    manifest = {
        "schema": FIELD_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "config": (
            {k: _config_dict(v) for k, v in config.items()}
            if isinstance(config, dict) else _config_dict(config)
        ),
        "topology": topology_summary(topo) if topo is not None else None,
        "environment": environment_info(),
        "timings": dict(timings) if timings else None,
        "report": report,
    }
    if fields is not None and fields:
        manifest["fields"] = fields.to_jsonable()
        reduced = fields.reduced_series()
        if reduced:
            manifest["telemetry"] = {
                "metrics": [k for k in reduced if k != "t"],
                "rounds": len(fields),
                "derived_from": "fields",
                "series": reduced,
            }
    if extra:
        manifest.update(extra)
    return manifest


def build_service_manifest(*, argv=None, config=None, topo=None,
                           service=None, series=None, report=None,
                           timings=None, extra=None) -> dict:
    """Assemble the service-shaped v1 manifest: the run manifest's
    argv/config/environment binding around a live-engine ``service``
    block (capacity accounting, per-epoch membership/mass history,
    compile count — ``ServiceEngine.service_block()``).  ``series`` is
    the boundary-sample series (one row per segment boundary), embedded
    under the standard ``telemetry`` key so the doctor's series checks
    run unchanged; ``topo`` is the INITIAL topology (the graph is
    mutable state afterwards — the epochs record how it evolved)."""
    manifest = {
        "schema": SERVICE_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "config": (
            {k: _config_dict(v) for k, v in config.items()}
            if isinstance(config, dict) else _config_dict(config)
        ),
        "topology": topology_summary(topo) if topo is not None else None,
        "environment": environment_info(),
        "timings": dict(timings) if timings else None,
        "report": report,
        "service": dict(service) if service else None,
    }
    if series:
        manifest["telemetry"] = {
            "metrics": [k for k in series if k != "t"],
            "rounds": len(series.get("t", ())),
            "derived_from": "segment_boundaries",
            "series": {k: list(v) for k, v in series.items()},
        }
    if extra:
        manifest.update(extra)
    return manifest


def build_query_manifest(*, argv=None, config=None, topo=None,
                         query=None, timings=None, extra=None) -> dict:
    """Assemble the query-fabric v1 manifest: the run manifest's
    argv/config/environment binding around a ``query`` block
    (``QueryFabric.query_block()`` — lane/compile accounting, the
    admission-latency distribution vs its SLO, per-boundary lane-mass
    rows, per-query lifecycle records with results).  The doctor judges
    it via ``obs.health.check_query`` (lane compile-count, per-lane
    mass SLO, admission-latency SLO); ``topo`` is the INITIAL topology
    (membership is mutable state afterwards)."""
    manifest = {
        "schema": QUERY_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "config": (
            {k: _config_dict(v) for k, v in config.items()}
            if isinstance(config, dict) else _config_dict(config)
        ),
        "topology": topology_summary(topo) if topo is not None else None,
        "environment": environment_info(),
        "timings": dict(timings) if timings else None,
        "query": dict(query) if query else None,
    }
    if extra:
        manifest.update(extra)
    return manifest


def build_recovery_manifest(*, argv=None, config=None, recovery=None,
                            service=None, query=None, timings=None,
                            extra=None) -> dict:
    """Assemble the crash-recovery v1 manifest: the standard
    argv/config/environment binding around one ``recovery`` block
    (``ServiceEngine.resilience_block()`` /
    ``QueryFabric.resilience_block()`` — WAL accounting incl. torn-tail
    truncation, the checkpoint-ring scan with per-archive integrity
    verdicts and the fallback chain, the replay record, the watchdog's
    quarantine/degraded evidence, and — when a harness planted a fault —
    the ``ground_truth`` + digest ``verify`` blocks).  The doctor judges
    it via ``obs.health.check_recovery`` (wal_replay_exact,
    ring_integrity, quarantine_mass, degraded_mode_bounded);
    ``inspect --blame`` ranks the infra faults that explain it.  The
    post-recovery ``service``/``query`` blocks ride along so the
    standard SLO checks run on the recovered engine too."""
    manifest = {
        "schema": RECOVERY_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "config": (
            {k: _config_dict(v) for k, v in config.items()}
            if isinstance(config, dict) else _config_dict(config)
        ),
        "environment": environment_info(),
        "timings": dict(timings) if timings else None,
        "recovery": dict(recovery) if recovery else None,
    }
    if service:
        manifest["service"] = dict(service)
    if query:
        manifest["query"] = dict(query)
    if extra:
        manifest.update(extra)
    return manifest


def build_scenario_manifest(*, argv=None, scenarios=None, summary=None,
                            timings=None, extra=None) -> dict:
    """Assemble the scenario-conformance v1 manifest: the standard
    argv/environment binding around one record per executed scenario
    (``scenarios``: each carrying the registered declaration, the planted
    ground truth, per-seed sweep instance records with series, the
    representative run's field block and blame bundle —
    :func:`flow_updating_tpu.scenarios.run.run_scenarios` output).  The
    doctor judges each record against its declared signature
    (``obs.health.check_scenario_conformance``); per-scenario series
    live INSIDE the records, so the healthy-run series rules are never
    applied to an intentionally hostile run."""
    manifest = {
        "schema": SCENARIO_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "environment": environment_info(),
        "summary": dict(summary) if summary else None,
        "timings": dict(timings) if timings else None,
        "scenarios": list(scenarios) if scenarios is not None else [],
    }
    if extra:
        manifest.update(extra)
    return manifest


def build_audit_manifest(*, argv=None, audit=None, ledger_path=None,
                         lint=None, extra=None) -> dict:
    """Assemble the program-conformance v1 manifest: the standard
    argv/environment binding around a golden-ledger audit report
    (:func:`flow_updating_tpu.analysis.golden.audit` output, under
    ``golden``) and optionally the lint findings that ran alongside it
    (``lint``: list of formatted finding strings).  The doctor judges
    the ``golden`` block via
    ``obs.health.check_program_conformance``."""
    manifest = {
        "schema": AUDIT_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "environment": environment_info(),
        "ledger": ledger_path,
        "golden": dict(audit) if audit is not None else None,
        "lint": list(lint) if lint is not None else None,
    }
    if extra:
        manifest.update(extra)
    return manifest


def build_budget_manifest(*, argv=None, budget=None, invariants=None,
                          extra=None) -> dict:
    """Assemble the collective-byte-budget v1 manifest: the standard
    argv/environment binding around a budget verification report
    (:func:`flow_updating_tpu.analysis.budget.verify_matrix` output,
    under ``budget``) and optionally the invariant-prover summary that
    ran alongside it (``invariants``:
    :func:`flow_updating_tpu.analysis.invariants.summarize` output).
    ``doctor`` judges the ``budget`` block via
    ``obs.health.check_budget``; ``regress --against`` gates
    measured-byte growth between two manifests."""
    manifest = {
        "schema": BUDGET_SCHEMA,
        "created_unix": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "environment": environment_info(),
        "budget": dict(budget) if budget is not None else None,
        "invariants": (dict(invariants) if invariants is not None
                       else None),
    }
    if extra:
        manifest.update(extra)
    return manifest


def write_report(path: str, manifest: dict) -> None:
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1, default=str)
        f.write("\n")
