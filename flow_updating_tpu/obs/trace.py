"""EventLog JSONL -> Chrome trace-event / Perfetto JSON.

SimGrid ships Paje tracing (``--cfg=tracing:yes``) that the reference
never turns on; the TPU-native equivalent is this converter: it takes the
framework's structured event log (watch samples, engine lifecycle, and —
in host-actors mode — the s4u runtime's actor/comm lifecycle events) and
emits the Chrome trace-event JSON format, which both ``chrome://tracing``
and https://ui.perfetto.dev open directly.

Mapping:

* each s4u actor gets its own *thread lane* (pid 1 "simulation"); its
  lifetime ``actor_spawn -> actor_exit`` renders as one complete ("X")
  slice on that lane;
* message flows render as flow arrows: ``comm_put`` starts a flow ("s")
  on the sender's lane, ``comm_deliver`` finishes it ("f") on the
  receiving mailbox's lane (mailbox name == actor name, the reference's
  convention) — arrows point from put to delivery across lanes;
* ``watch`` / ``train_sample`` records become counter ("C") tracks
  (pid 2 "metrics"): rmse, max_abs_err, mass, fired_total, ... — the
  watcher's convergence curves, scrubbable against the actor timeline;
* engine ``advance`` records render as compiled-chunk slices on an
  "engine" lane; ``run_start``/``run_end``/``kill_all`` as instants.

Timestamps are *simulated* seconds (the records' ``t``), scaled to the
trace format's microseconds; records without ``t`` fall back to wall
time so pure-host logs still order sensibly.
"""

from __future__ import annotations

import json

_US = 1_000_000.0  # simulated seconds -> trace microseconds

PID_SIM = 1
PID_METRICS = 2

#: record fields that never become counters
_NON_COUNTER_FIELDS = {"t", "kind", "wall_s", "step"}


def read_eventlog(path: str) -> list:
    """Parse a JSONL event log, skipping non-JSON lines (a truncated tail
    from a killed run must not void the rest of the trace)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _ts(rec: dict) -> float:
    t = rec.get("t")
    if t is None:
        t = rec.get("wall_s", 0.0)
    try:
        return float(t) * _US
    except (TypeError, ValueError):
        return 0.0


class _Lanes:
    """Stable actor -> tid assignment with thread_name metadata."""

    def __init__(self, events: list):
        self._events = events
        self._tids: dict = {}

    def tid(self, name: str) -> int:
        if name not in self._tids:
            tid = len(self._tids) + 1
            self._tids[name] = tid
            self._events.append({
                "ph": "M", "name": "thread_name", "pid": PID_SIM,
                "tid": tid, "args": {"name": name},
            })
        return self._tids[name]


def eventlog_to_chrome_trace(records) -> dict:
    """Convert event-log records to a Chrome trace-event document."""
    events: list = [
        {"ph": "M", "name": "process_name", "pid": PID_SIM,
         "args": {"name": "simulation"}},
        {"ph": "M", "name": "process_name", "pid": PID_METRICS,
         "args": {"name": "metrics"}},
        {"ph": "M", "name": "thread_name", "pid": PID_METRICS, "tid": 0,
         "args": {"name": "watcher"}},
    ]
    lanes = _Lanes(events)
    spawn_ts: dict = {}          # actor -> spawn timestamp (us)
    comm_src: dict = {}          # cid -> source actor
    last_ts = 0.0

    for rec in records:
        kind = rec.get("kind")
        ts = _ts(rec)
        last_ts = max(last_ts, ts)
        if kind == "actor_spawn":
            actor = str(rec.get("actor", "?"))
            lanes.tid(actor)
            spawn_ts[actor] = ts
            events.append({
                "ph": "i", "name": f"spawn {actor}", "cat": "actor",
                "pid": PID_SIM, "tid": lanes.tid(actor), "ts": ts, "s": "t",
            })
        elif kind == "actor_exit":
            actor = str(rec.get("actor", "?"))
            start = spawn_ts.pop(actor, ts)
            events.append({
                "ph": "X", "name": actor, "cat": "actor",
                "pid": PID_SIM, "tid": lanes.tid(actor),
                "ts": start, "dur": max(ts - start, 0.0),
                "args": {"killed": bool(rec.get("killed", False))},
            })
        elif kind == "comm_put":
            src = str(rec.get("src", "?"))
            cid = rec.get("cid", len(comm_src))
            comm_src[cid] = src
            common = {"cat": "comm", "id": int(cid), "pid": PID_SIM,
                      "tid": lanes.tid(src), "ts": ts,
                      "name": f"msg:{rec.get('mailbox', '?')}"}
            events.append({"ph": "s", **common})
        elif kind == "comm_deliver":
            dst = str(rec.get("mailbox", "?"))
            cid = rec.get("cid", -1)
            events.append({
                "ph": "f", "bp": "e", "cat": "comm", "id": int(cid),
                "pid": PID_SIM, "tid": lanes.tid(dst), "ts": ts,
                "name": f"msg:{dst}",
                "args": {"src": comm_src.get(cid)},
            })
        elif kind in ("comm_cancel", "comm_drop"):
            events.append({
                "ph": "i", "name": kind, "cat": "comm", "pid": PID_SIM,
                "tid": 0, "ts": ts, "s": "p",
            })
        elif kind == "advance":
            rounds = float(rec.get("rounds", 0))
            events.append({
                "ph": "X", "name": f"advance x{int(rounds)}",
                "cat": "engine", "pid": PID_SIM, "tid": lanes.tid("engine"),
                "ts": ts, "dur": rounds * _US,
                "args": {"wall_s": rec.get("wall_s")},
            })
            last_ts = max(last_ts, ts + rounds * _US)
        elif kind in ("watch", "train_sample", "until_rmse"):
            for field, value in rec.items():
                if field in _NON_COUNTER_FIELDS or not isinstance(
                        value, (int, float)) or isinstance(value, bool):
                    continue
                events.append({
                    "ph": "C", "name": field, "pid": PID_METRICS, "tid": 0,
                    "ts": ts, "args": {field: value},
                })
        elif kind is not None:
            # run_start / run_end / kill_all / train_end / anything new:
            # an instant marker keeps unknown kinds visible, never dropped
            events.append({
                "ph": "i", "name": str(kind), "cat": "lifecycle",
                "pid": PID_SIM, "tid": 0, "ts": ts, "s": "g",
            })

    # actors that never exited (log truncated / still running): close
    # their slices at the last seen timestamp so lanes stay visible
    for actor, start in spawn_ts.items():
        events.append({
            "ph": "X", "name": actor, "cat": "actor", "pid": PID_SIM,
            "tid": lanes.tid(actor), "ts": start,
            "dur": max(last_ts - start, 1.0), "args": {"open": True},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
