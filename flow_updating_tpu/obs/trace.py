"""EventLog JSONL -> Chrome trace-event / Perfetto JSON.

SimGrid ships Paje tracing (``--cfg=tracing:yes``) that the reference
never turns on; the TPU-native equivalent is this converter: it takes the
framework's structured event log (watch samples, engine lifecycle, and —
in host-actors mode — the s4u runtime's actor/comm lifecycle events) and
emits the Chrome trace-event JSON format, which both ``chrome://tracing``
and https://ui.perfetto.dev open directly.

Mapping:

* each s4u actor gets its own *thread lane* (pid 1 "simulation"); its
  lifetime ``actor_spawn -> actor_exit`` renders as one complete ("X")
  slice on that lane;
* message flows render as flow arrows: ``comm_put`` starts a flow ("s")
  on the sender's lane, ``comm_deliver`` finishes it ("f") on the
  receiving mailbox's lane (mailbox name == actor name, the reference's
  convention) — arrows point from put to delivery across lanes;
* ``watch`` / ``train_sample`` records become counter ("C") tracks
  (pid 2 "metrics"): rmse, max_abs_err, mass, fired_total, ... — the
  watcher's convergence curves, scrubbable against the actor timeline;
* engine ``advance`` records render as compiled-chunk slices on an
  "engine" lane; ``run_start``/``run_end``/``kill_all`` as instants.

Timestamps are *simulated* seconds (the records' ``t``), scaled to the
trace format's microseconds; records without ``t`` fall back to wall
time so pure-host logs still order sensibly.
"""

from __future__ import annotations

import json

_US = 1_000_000.0  # simulated seconds -> trace microseconds

PID_SIM = 1
PID_METRICS = 2

#: record fields that never become counters
_NON_COUNTER_FIELDS = {"t", "kind", "wall_s", "step"}


def read_eventlog(path: str) -> list:
    """Parse a JSONL event log, skipping non-JSON lines (a truncated tail
    from a killed run must not void the rest of the trace)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _ts(rec: dict) -> float:
    t = rec.get("t")
    if t is None:
        t = rec.get("wall_s", 0.0)
    try:
        return float(t) * _US
    except (TypeError, ValueError):
        return 0.0


class _Lanes:
    """Stable actor -> tid assignment with thread_name metadata."""

    def __init__(self, events: list):
        self._events = events
        self._tids: dict = {}

    def tid(self, name: str) -> int:
        if name not in self._tids:
            tid = len(self._tids) + 1
            self._tids[name] = tid
            self._events.append({
                "ph": "M", "name": "thread_name", "pid": PID_SIM,
                "tid": tid, "args": {"name": name},
            })
        return self._tids[name]


def eventlog_to_chrome_trace(records) -> dict:
    """Convert event-log records to a Chrome trace-event document."""
    events: list = [
        {"ph": "M", "name": "process_name", "pid": PID_SIM,
         "args": {"name": "simulation"}},
        {"ph": "M", "name": "process_name", "pid": PID_METRICS,
         "args": {"name": "metrics"}},
        {"ph": "M", "name": "thread_name", "pid": PID_METRICS, "tid": 0,
         "args": {"name": "watcher"}},
    ]
    lanes = _Lanes(events)
    spawn_ts: dict = {}          # actor -> spawn timestamp (us)
    comm_src: dict = {}          # cid -> source actor
    last_ts = 0.0

    for rec in records:
        kind = rec.get("kind")
        ts = _ts(rec)
        last_ts = max(last_ts, ts)
        if kind == "actor_spawn":
            actor = str(rec.get("actor", "?"))
            lanes.tid(actor)
            spawn_ts[actor] = ts
            events.append({
                "ph": "i", "name": f"spawn {actor}", "cat": "actor",
                "pid": PID_SIM, "tid": lanes.tid(actor), "ts": ts, "s": "t",
            })
        elif kind == "actor_exit":
            actor = str(rec.get("actor", "?"))
            start = spawn_ts.pop(actor, ts)
            events.append({
                "ph": "X", "name": actor, "cat": "actor",
                "pid": PID_SIM, "tid": lanes.tid(actor),
                "ts": start, "dur": max(ts - start, 0.0),
                "args": {"killed": bool(rec.get("killed", False))},
            })
        elif kind == "comm_put":
            src = str(rec.get("src", "?"))
            cid = rec.get("cid", len(comm_src))
            comm_src[cid] = src
            common = {"cat": "comm", "id": int(cid), "pid": PID_SIM,
                      "tid": lanes.tid(src), "ts": ts,
                      "name": f"msg:{rec.get('mailbox', '?')}"}
            events.append({"ph": "s", **common})
        elif kind == "comm_deliver":
            dst = str(rec.get("mailbox", "?"))
            cid = rec.get("cid", -1)
            events.append({
                "ph": "f", "bp": "e", "cat": "comm", "id": int(cid),
                "pid": PID_SIM, "tid": lanes.tid(dst), "ts": ts,
                "name": f"msg:{dst}",
                "args": {"src": comm_src.get(cid)},
            })
        elif kind in ("comm_cancel", "comm_drop"):
            events.append({
                "ph": "i", "name": kind, "cat": "comm", "pid": PID_SIM,
                "tid": 0, "ts": ts, "s": "p",
            })
        elif kind == "advance":
            rounds = float(rec.get("rounds", 0))
            events.append({
                "ph": "X", "name": f"advance x{int(rounds)}",
                "cat": "engine", "pid": PID_SIM, "tid": lanes.tid("engine"),
                "ts": ts, "dur": rounds * _US,
                "args": {"wall_s": rec.get("wall_s")},
            })
            last_ts = max(last_ts, ts + rounds * _US)
        elif kind in ("watch", "train_sample", "until_rmse"):
            for field, value in rec.items():
                if field in _NON_COUNTER_FIELDS or not isinstance(
                        value, (int, float)) or isinstance(value, bool):
                    continue
                events.append({
                    "ph": "C", "name": field, "pid": PID_METRICS, "tid": 0,
                    "ts": ts, "args": {field: value},
                })
        elif kind is not None:
            # run_start / run_end / kill_all / train_end / anything new:
            # an instant marker keeps unknown kinds visible, never dropped
            events.append({
                "ph": "i", "name": str(kind), "cat": "lifecycle",
                "pid": PID_SIM, "tid": 0, "ts": ts, "s": "g",
            })

    # actors that never exited (log truncated / still running): close
    # their slices at the last seen timestamp so lanes stay visible
    for actor, start in spawn_ts.items():
        events.append({
            "ph": "X", "name": actor, "cat": "actor", "pid": PID_SIM,
            "tid": lanes.tid(actor), "ts": start,
            "dur": max(last_ts - start, 1.0), "args": {"open": True},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---- serving manifests (the flight recorder's span chains) ---------------

def _chain_parts(chain: list) -> dict:
    """Decompose one query's span chain: admission lane/time, terminal,
    segments, instants, and the opening span's attributes."""
    out: dict = {"lane": None, "t_submit": None, "t_admit": None,
                 "t_end": None, "terminal": None, "segments": [],
                 "instants": [], "attrs": {}}
    for rec in chain:
        name = str(rec.get("name", ""))
        if name == "submitted":
            out["t_submit"] = int(rec["t0"])
            out["attrs"] = {k: v for k, v in rec.items()
                            if k not in ("name", "t0", "t1")}
        elif name.startswith("admitted@lane"):
            out["lane"] = int(rec.get("lane", 0))
            out["t_admit"] = int(rec["t0"])
        elif name == "segment":
            out["segments"].append((int(rec["t0"]), int(rec["t1"])))
        elif name in ("retired", "quarantined", "deferred"):
            out["terminal"] = name
            out["t_end"] = int(rec["t0"])
            if name == "quarantined" and rec.get("reason"):
                out["attrs"]["reason"] = rec["reason"]
            if name == "deferred":
                # strict-admission turn-away: the ETA evidence rides
                # the terminal span (spans.deferred attrs)
                out["attrs"].update({k: v for k, v in rec.items()
                                     if k not in ("name", "t0", "t1")})
        elif name in ("converged", "read"):
            out["instants"].append((name, int(rec["t0"])))
    if out["t_end"] is None:       # still active: close at last segment
        if out["segments"]:
            out["t_end"] = out["segments"][-1][1]
        elif out["t_admit"] is not None:
            out["t_end"] = out["t_admit"]
    return out


def serving_manifest_to_chrome_trace(manifest: dict) -> dict:
    """Render a serve/query/recovery manifest carrying a
    ``serving_trace`` block (obs/metrics.py + obs/spans.py) as a Chrome
    trace-event document: one thread lane per fabric lane with each
    query's life as a complete slice (its segment spans nested inside),
    a ``queue`` lane for pre-admission waits, an ``engine`` lane for
    recovery/degraded spans, and counter tracks from the per-boundary
    metric samples (lane occupancy, queue depth, WAL/checkpoint
    accounting).  Timestamps are round clocks scaled like the event-log
    path (1 round == 1 simulated second)."""
    trace = manifest.get("serving_trace") or {}
    spans = trace.get("spans") or {}
    chains = spans.get("queries") or {}
    engine_spans = spans.get("engine") or []
    samples = (trace.get("metrics") or {}).get("samples") or []
    if not chains and not engine_spans and not samples:
        raise ValueError(
            "manifest has no serving_trace span chains or metric "
            "samples to render — run serve/query with the flight "
            "recorder on (observe=True, the default) and --report")
    events: list = [
        {"ph": "M", "name": "process_name", "pid": PID_SIM,
         "args": {"name": "lanes"}},
        {"ph": "M", "name": "process_name", "pid": PID_METRICS,
         "args": {"name": "metrics"}},
        {"ph": "M", "name": "thread_name", "pid": PID_METRICS, "tid": 0,
         "args": {"name": "boundary samples"}},
    ]
    lanes = _Lanes(events)
    queue_tid = lanes.tid("queue")
    engine_tid = lanes.tid("engine")

    def _qname(qid, attrs) -> str:
        kind = attrs.get("kind")
        return f"q{qid}" + (f" [{kind}]" if kind else "")

    for qid in sorted(chains, key=lambda q: (len(q), q)):
        p = _chain_parts(chains[qid])
        name = _qname(qid, p["attrs"])
        if p["t_submit"] is not None and p["t_admit"] is not None \
                and p["t_admit"] > p["t_submit"]:
            events.append({
                "ph": "X", "name": f"{name} queued", "cat": "queue",
                "pid": PID_SIM, "tid": queue_tid,
                "ts": p["t_submit"] * _US,
                "dur": (p["t_admit"] - p["t_submit"]) * _US,
            })
        if p["terminal"] == "deferred":
            # never held a lane: the forecast-aware turn-away renders
            # on the queue track with its ETA-vs-SLO evidence
            events.append({
                "ph": "i", "name": f"{name} deferred", "cat": "queue",
                "pid": PID_SIM, "tid": queue_tid,
                "ts": p["t_end"] * _US, "s": "p",
                "args": dict(p["attrs"]),
            })
            continue
        if p["lane"] is None:
            continue               # never admitted: queue slice only
        tid = lanes.tid(f"lane {p['lane']}")
        if p["attrs"].get("at_risk") and p["t_admit"] is not None:
            # admitted over-SLO (observe policy): flag the admission
            # instant so the at-risk population pops in Perfetto
            events.append({
                "ph": "i", "name": f"{name} at_risk", "cat": "query",
                "pid": PID_SIM, "tid": tid,
                "ts": p["t_admit"] * _US, "s": "p",
                "args": {"eta_admission":
                         p["attrs"].get("eta_admission")},
            })
        events.append({
            "ph": "X", "name": name, "cat": "query", "pid": PID_SIM,
            "tid": tid, "ts": p["t_admit"] * _US,
            "dur": max((p["t_end"] - p["t_admit"]) * _US, 1.0),
            "args": {**p["attrs"], "qid": qid,
                     "terminal": p["terminal"],
                     "segments": len(p["segments"])},
        })
        for t0, t1 in p["segments"]:
            events.append({
                "ph": "X", "name": "seg", "cat": "segment",
                "pid": PID_SIM, "tid": tid, "ts": t0 * _US,
                "dur": max((t1 - t0) * _US, 1.0),
            })
        for iname, t in p["instants"]:
            events.append({
                "ph": "i", "name": f"{name} {iname}", "cat": "query",
                "pid": PID_SIM, "tid": tid, "ts": t * _US, "s": "t",
            })
        if p["terminal"] == "quarantined":
            events.append({
                "ph": "i", "name": f"{name} quarantined",
                "cat": "query", "pid": PID_SIM, "tid": tid,
                "ts": p["t_end"] * _US, "s": "p",
                "args": dict(p["attrs"]),
            })
    for rec in engine_spans:
        t0, t1 = int(rec["t0"]), int(rec["t1"])
        events.append({
            "ph": "X", "name": str(rec.get("name", "?")),
            "cat": "engine", "pid": PID_SIM, "tid": engine_tid,
            "ts": t0 * _US, "dur": max((t1 - t0) * _US, 1.0),
            "args": {k: v for k, v in rec.items()
                     if k not in ("name", "t0", "t1")},
        })
    for row in samples:
        ts = float(row.get("t", 0)) * _US
        for field, value in row.items():
            if field == "t" or not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue
            events.append({
                "ph": "C", "name": field, "pid": PID_METRICS, "tid": 0,
                "ts": ts, "args": {field: value},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
