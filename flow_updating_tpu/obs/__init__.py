"""Observability subsystem: device-resident telemetry, run manifests,
and the DES trace exporter.

Three pillars (docs/OBSERVABILITY.md):

* :mod:`~flow_updating_tpu.obs.telemetry` — the metric spec/series
  contract for per-round series accumulated *inside* the compiled round
  scan (no ``jax.debug.callback`` in the hot path; one bulk host
  transfer at the end).  The per-kernel runners live with their kernels.
* :mod:`~flow_updating_tpu.obs.report` — the self-describing JSON run
  manifest every CLI entry point can emit (``--report``).
* :mod:`~flow_updating_tpu.obs.trace` — EventLog JSONL -> Chrome
  trace-event / Perfetto converter (``obs export-trace``), the TPU-native
  answer to SimGrid's Paje traces.

``observer_sample`` is re-exported here as the ONE watch-record shape:
every streamed-observer emit site and :meth:`TelemetrySeries.
watch_records` produce it, so the watcher contract cannot drift between
execution modes (contract-tested in tests/test_obs_tools.py).
"""

from flow_updating_tpu.obs.telemetry import (
    ALL_METRICS,
    DEFAULT_METRICS,
    SUPPORTED_METRICS,
    TelemetrySeries,
    TelemetrySpec,
)
from flow_updating_tpu.obs.report import build_manifest, write_report
from flow_updating_tpu.obs.trace import eventlog_to_chrome_trace, read_eventlog
from flow_updating_tpu.utils.metrics import observer_sample

__all__ = [
    "ALL_METRICS",
    "DEFAULT_METRICS",
    "SUPPORTED_METRICS",
    "TelemetrySeries",
    "TelemetrySpec",
    "build_manifest",
    "write_report",
    "eventlog_to_chrome_trace",
    "read_eventlog",
    "observer_sample",
]
