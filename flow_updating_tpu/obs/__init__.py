"""Observability subsystem: device-resident telemetry, run manifests,
the DES trace exporter, and the measurement-to-verdict layer.

The pillars (docs/OBSERVABILITY.md):

* :mod:`~flow_updating_tpu.obs.fields` +
  :mod:`~flow_updating_tpu.obs.inspect` — TOPOLOGY-RESOLVED
  observability: per-node/per-edge metric fields riding the round scan
  (stride/topk memory bounding), fault localization ("blame": straggler
  nodes, leaking edge pairs, divergence origins), run-to-run diffing and
  topology heatmaps (the ``inspect`` subcommand;
  ``flow-updating-field-report/v1`` manifests).

* :mod:`~flow_updating_tpu.obs.telemetry` — the metric spec/series
  contract for per-round series accumulated *inside* the compiled round
  scan (no ``jax.debug.callback`` in the hot path; one bulk host
  transfer at the end).  The per-kernel runners live with their kernels.
* :mod:`~flow_updating_tpu.obs.report` — the self-describing JSON
  manifests every CLI entry point can emit (``--report``): run, sweep,
  and profile schemas.
* :mod:`~flow_updating_tpu.obs.trace` — EventLog JSONL -> Chrome
  trace-event / Perfetto converter (``obs export-trace``), the TPU-native
  answer to SimGrid's Paje traces.
* :mod:`~flow_updating_tpu.obs.profile` — AOT cost attribution
  (flops / bytes / peak memory / compile-vs-execute split) for every
  kernel dispatch mode (``Engine.profile``, the ``profile`` subcommand,
  ``bench.py --profile``).
* :mod:`~flow_updating_tpu.obs.health` — rule-based health verdicts
  over series and manifests (the ``doctor`` subcommand): NaN/divergence
  watchdog, stall detection, invariant drift, environment and recorded-
  baseline sanity.
* :mod:`~flow_updating_tpu.obs.regress` — fresh bench/profile reports
  gated against the artifact history and recorded spreads (the
  ``regress`` subcommand; CI-consumable exit codes).
* :mod:`~flow_updating_tpu.obs.roofline` +
  :mod:`~flow_updating_tpu.obs.timeline` — the PERF LENS: per-backend
  hardware models (declared TPU generations, measured CPU-proxy
  calibration), predicted-vs-measured reconciliation (``roofline_frac``
  on every banked rate; doctor clauses ``roofline_sane`` /
  ``roofline_floor``), and measured device timelines (captured profiler
  traces parsed into wire/compute slices and a *measured*
  ``overlap_ratio`` — ``profile --roofline --trace-dir``).

``observer_sample`` is re-exported here as the ONE watch-record shape:
every streamed-observer emit site and :meth:`TelemetrySeries.
watch_records` produce it, so the watcher contract cannot drift between
execution modes (contract-tested in tests/test_obs_tools.py).
"""

from flow_updating_tpu.obs.telemetry import (
    ALL_METRICS,
    DEFAULT_METRICS,
    SUPPORTED_METRICS,
    TelemetrySeries,
    TelemetrySpec,
)
from flow_updating_tpu.obs.fields import (
    ALL_FIELDS,
    SUPPORTED_FIELDS,
    FieldSeries,
    FieldSpec,
)
from flow_updating_tpu.obs.health import CheckResult, diagnose_manifest
from flow_updating_tpu.obs.inspect import ascii_heatmap, blame, diff_fields
from flow_updating_tpu.obs.profile import profile_program
from flow_updating_tpu.obs.report import (
    build_field_manifest,
    build_manifest,
    build_profile_manifest,
    write_report,
)
from flow_updating_tpu.obs.roofline import (
    HardwareModel,
    calibrate_cpu,
    resolve_model,
)
from flow_updating_tpu.obs.timeline import measured_overlap
from flow_updating_tpu.obs.trace import eventlog_to_chrome_trace, read_eventlog
from flow_updating_tpu.utils.metrics import observer_sample

__all__ = [
    "ALL_FIELDS",
    "ALL_METRICS",
    "DEFAULT_METRICS",
    "SUPPORTED_FIELDS",
    "SUPPORTED_METRICS",
    "CheckResult",
    "FieldSeries",
    "FieldSpec",
    "HardwareModel",
    "calibrate_cpu",
    "measured_overlap",
    "resolve_model",
    "TelemetrySeries",
    "TelemetrySpec",
    "ascii_heatmap",
    "blame",
    "build_field_manifest",
    "build_manifest",
    "build_profile_manifest",
    "diagnose_manifest",
    "diff_fields",
    "profile_program",
    "write_report",
    "eventlog_to_chrome_trace",
    "read_eventlog",
    "observer_sample",
]
