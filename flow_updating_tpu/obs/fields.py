"""Topology-resolved observability: per-node / per-edge metric FIELDS.

The telemetry subsystem (PR 2, :mod:`flow_updating_tpu.obs.telemetry`)
records one *global scalar* per metric per round — enough for the doctor
to say THAT a run stalled or leaked, never WHERE.  Flow-Updating's
invariants are local (each node's estimate, each directed edge's
antisymmetric flow), so this module extends the same device-resident
design — fields ride the round ``lax.scan`` as extra ``ys``, zero host
callbacks, one bulk transfer — down to per-node and per-edge resolution:

* ``node_err``            — alive-masked signed estimate error vs the true
                            mean, ``(R, N[, D])``.  RMS-reducing it over
                            nodes+features reproduces the global ``rmse``
                            series (asserted in tests/test_fields.py).
* ``node_mass``           — alive-masked per-node estimate (the node's
                            contribution to global mass; sum-reduce ==
                            the global ``mass`` series).
* ``node_mass_residual``  — alive-masked ``estimate - input`` per node
                            (sum-reduce == global ``mass_residual`` up to
                            summation-order roundoff).
* ``node_fired``          — cumulative averaging events per node (the
                            straggler counter).
* ``node_conv_round``     — the convergence FRONTIER: the first round each
                            node's pooled ``|err|`` entered ``tol`` (-1 =
                            never), carried through the scan and emitted
                            once — an ``(N,)`` field, not a series.
* ``edge_flow``           — the signed per-edge flow ledger (features
                            summed), ``(R, E)``.  Pairing it through
                            ``rev`` localizes mass leaks: a healthy pair
                            has ``flow[e] + flow[rev[e]] ~ 0``.
* ``edge_est``            — the per-edge estimate ledger (features
                            summed), ``(R, E)``: what ``src`` last heard
                            ``dst`` claim.  The Byzantine tell — a value
                            liar's in-view entries sit pinned at the lie
                            and a silent node's never leave 0 while the
                            consensus moves (``inspect`` blame,
                            scenarios/).
* ``edge_stale``          — rounds since the edge last averaged
                            (``t - stamp``; meaningful for the pairwise
                            variant, monotone for collect-all).

Memory is bounded by two knobs on the spec: ``stride`` records every
k-th round only (the scan runs k rounds per emitted row — state
evolution is untouched), and ``topk`` keeps only the ``m`` worst nodes
per row (ranked by pooled ``|node_err|``; the recorded ``topk_idx`` row
carries their ids).  ``stride`` works on every kernel; ``topk`` needs a
device-global ranking and is restricted to the single-device/GSPMD
kernels (edge, node).

The per-kernel samplers live with their kernels (``models/rounds.py``,
``models/sync.py``, ``parallel/sharded.py``,
``parallel/structured_sharded.py``); ``Engine.run_fields`` dispatches and
re-assembles everything into ORIGINAL node/edge order.  The localization
("blame") and run-diffing layers consuming these fields live in
:mod:`flow_updating_tpu.obs.inspect`.
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: Per-node fields, in canonical emission order.
NODE_FIELDS = (
    "node_err",            # (R, N[, D]) signed alive-masked est - mean
    "node_mass",           # (R, N[, D]) alive-masked estimate
    "node_mass_residual",  # (R, N[, D]) alive-masked est - input
    "node_fired",          # (R, N) int32 cumulative fires
    "node_conv_round",     # (N,) int32 convergence frontier (-1 = never)
)

#: Per-edge fields (edge-ledger kernels only).
EDGE_FIELDS = (
    "edge_flow",           # (R, E) signed flow ledger (features summed)
    "edge_est",            # (R, E) estimate ledger: src's last-heard
    #                        view of dst (features summed)
    "edge_stale",          # (R, E) int32 rounds since last avg on edge
)

ALL_FIELDS = NODE_FIELDS + EDGE_FIELDS

DEFAULT_FIELDS = (
    "node_err", "node_mass", "node_mass_residual", "node_conv_round",
)

#: What each execution mode can record.  The node-collapsed kernels keep
#: no per-edge ledgers; the halo kernel's per-edge ledgers exist but its
#: reverse edges live on other shards (pairing stays a host-side job on
#: the gathered field).
SUPPORTED_FIELDS = {
    "edge": ALL_FIELDS,
    "halo": ALL_FIELDS,
    "node": NODE_FIELDS,
    "pod": NODE_FIELDS,
}

#: Kernels whose sampler can rank nodes globally on device (lax.top_k).
TOPK_KINDS = ("edge", "node")


def _suggest(name: str, vocabulary) -> str:
    import difflib

    close = difflib.get_close_matches(name, vocabulary, n=1)
    return f" (did you mean {close[0]!r}?)" if close else ""


@dataclasses.dataclass(frozen=True)
class FieldSpec:
    """Static field selection + downsampling knobs — hashable, a jit key.

    ``stride`` — emit one field row every ``stride`` rounds (the rounds in
    between still run; only recording is skipped).  ``topk`` — keep only
    the ``topk`` worst nodes per row (0 = all; needs ``node_err`` as the
    ranking key).  ``tol`` — the convergence-frontier threshold for
    ``node_conv_round``.  ``strict=True`` (an explicit user list) makes
    :meth:`for_kernel` raise on fields the execution mode cannot record;
    the presets narrow silently, mirroring
    :class:`~flow_updating_tpu.obs.telemetry.TelemetrySpec`."""

    fields: tuple = ()
    stride: int = 1
    topk: int = 0
    tol: float = 1e-6
    strict: bool = True

    def __post_init__(self):
        if self.stride < 1:
            raise ValueError(f"field stride must be >= 1 (got {self.stride})")
        if self.topk < 0:
            raise ValueError(f"field topk must be >= 0 (got {self.topk})")

    @property
    def enabled(self) -> bool:
        return bool(self.fields)

    def has(self, name: str) -> bool:
        return name in self.fields

    @property
    def node_series_fields(self) -> tuple:
        """Selected per-node fields that emit one row per recorded round
        (everything but the one-shot convergence frontier)."""
        return tuple(f for f in self.fields
                     if f in NODE_FIELDS and f != "node_conv_round")

    @property
    def edge_series_fields(self) -> tuple:
        return tuple(f for f in self.fields if f in EDGE_FIELDS)

    @classmethod
    def off(cls) -> FieldSpec:
        return cls(fields=())

    @classmethod
    def default(cls, stride: int = 1, topk: int = 0,
                tol: float = 1e-6) -> FieldSpec:
        return cls(fields=DEFAULT_FIELDS, stride=stride, topk=topk,
                   tol=tol, strict=False)

    @classmethod
    def full(cls, stride: int = 1, topk: int = 0,
             tol: float = 1e-6) -> FieldSpec:
        return cls(fields=ALL_FIELDS, stride=stride, topk=topk, tol=tol,
                   strict=False)

    @classmethod
    def parse(cls, text: str | None, stride: int = 1, topk: int = 0,
              tol: float = 1e-6) -> FieldSpec:
        """CLI surface: ``off`` / ``default`` / ``full`` / ``f1,f2,...``.
        Unknown names fail loudly with the valid vocabulary (and a
        closest-match hint) — a typo must never silently record
        nothing."""
        if text is None or text in ("", "off", "none"):
            return cls.off()
        if text in ("default", "on", "true", "1"):
            return cls.default(stride=stride, topk=topk, tol=tol)
        if text in ("full", "all"):
            return cls.full(stride=stride, topk=topk, tol=tol)
        names = tuple(f.strip() for f in text.split(",") if f.strip())
        unknown = [f for f in names if f not in ALL_FIELDS]
        if unknown:
            raise ValueError(
                f"unknown field(s) {unknown}{_suggest(unknown[0], ALL_FIELDS)}"
                f"; valid: {', '.join(ALL_FIELDS)} "
                "(or 'default'/'full'/'off')")
        return cls(fields=tuple(f for f in ALL_FIELDS if f in names),
                   stride=stride, topk=topk, tol=tol)

    def for_kernel(self, kind: str) -> FieldSpec:
        """Narrow to what ``kind`` can record (or raise, if strict), and
        validate the downsampling knobs against the mode."""
        try:
            sup = SUPPORTED_FIELDS[kind]
        except KeyError:
            raise ValueError(
                f"unknown kernel kind {kind!r}; have "
                f"{sorted(SUPPORTED_FIELDS)}") from None
        missing = [f for f in self.fields if f not in sup]
        if missing and self.strict:
            raise ValueError(
                f"field(s) {missing} are not recordable on the {kind!r} "
                f"kernel (supported: {', '.join(sup)})")
        fields = tuple(f for f in self.fields if f in sup)
        if self.topk:
            if kind not in TOPK_KINDS:
                raise ValueError(
                    f"topk downsampling needs a device-global node ranking "
                    f"and is limited to the {'/'.join(TOPK_KINDS)} kernels; "
                    f"the {kind!r} kernel supports stride downsampling "
                    "only")
            if "node_err" not in fields:
                raise ValueError(
                    "topk ranks nodes by |node_err|; add 'node_err' to "
                    "the field list")
        return dataclasses.replace(self, fields=fields)


class FieldSeries:
    """Host-side field bundle in ORIGINAL node/edge order.

    ``node``: ``{name: (R, N[, D])}`` (or ``(R, m[, D])`` under topk,
    with ``topk_idx`` ``(R, m)`` carrying the original node ids per row);
    ``edge``: ``{name: (R, E)}``; ``t``/``active``: ``(R,)``;
    ``conv_round``: ``(N,)`` or None.  ``edges`` (``{"src", "dst",
    "rev"}``) and ``coords`` (``(N, 2)``) travel along when available so
    offline consumers (blame, heatmaps) need no topology object."""

    def __init__(self, t=None, active=None, node=None, edge=None,
                 conv_round=None, topk_idx=None, spec: FieldSpec | None = None,
                 edges: dict | None = None, coords=None):
        self.t = np.asarray(t if t is not None else np.zeros((0,), np.int32))
        self.active = (np.asarray(active) if active is not None else None)
        self.node = {k: np.asarray(v) for k, v in (node or {}).items()}
        self.edge = {k: np.asarray(v) for k, v in (edge or {}).items()}
        self.conv_round = (np.asarray(conv_round)
                           if conv_round is not None else None)
        self.topk_idx = np.asarray(topk_idx) if topk_idx is not None else None
        self.spec = spec or FieldSpec.off()
        self.edges = ({k: np.asarray(v) for k, v in edges.items()}
                      if edges else None)
        self.coords = np.asarray(coords) if coords is not None else None

    @classmethod
    def empty(cls) -> FieldSeries:
        return cls()

    def __len__(self) -> int:
        return int(self.t.shape[0])

    def __bool__(self) -> bool:
        return len(self) > 0 or self.conv_round is not None

    @property
    def fields(self) -> tuple:
        out = tuple(self.node) + tuple(self.edge)
        if self.conv_round is not None:
            out = out + ("node_conv_round",)
        return out

    def __contains__(self, name: str) -> bool:
        return name in self.fields

    def __getitem__(self, name: str):
        if name == "node_conv_round":
            if self.conv_round is None:
                raise KeyError(name)
            return self.conv_round
        if name in self.node:
            return self.node[name]
        return self.edge[name]

    @property
    def num_nodes(self) -> int | None:
        if self.conv_round is not None:
            return int(self.conv_round.shape[0])
        for v in self.node.values():
            if self.topk_idx is None:
                return int(v.shape[1])
        return None

    def pooled(self, name: str) -> np.ndarray:
        """A field's per-entity magnitude with feature axes pooled
        (max |.|): ``(R, N)`` whatever the payload width."""
        v = np.asarray(self[name], dtype=np.float64)
        if v.ndim > 2:
            return np.max(np.abs(v), axis=tuple(range(2, v.ndim)))
        return np.abs(v)

    def reduced_series(self) -> dict | None:
        """The global telemetry series re-derived by reducing the fields
        (None under topk — partial rows cannot reproduce global sums).
        Keys follow :mod:`~flow_updating_tpu.obs.telemetry` naming so
        the doctor's series checks run unchanged on field manifests."""
        if self.spec.topk or not len(self):
            return None
        out = {"t": self.t.tolist()}
        reduce_axes = lambda v: tuple(range(1, v.ndim))
        if "node_err" in self.node and self.active is not None:
            err = np.asarray(self.node["node_err"], np.float64)
            feat = int(err[0].size // err.shape[1]) if err.ndim > 1 else 1
            cnt = np.maximum(self.active.astype(np.float64), 1.0) * feat
            out["rmse"] = np.sqrt(
                np.sum(err * err, axis=reduce_axes(err)) / cnt).tolist()
            out["max_abs_err"] = np.max(
                np.abs(err), axis=reduce_axes(err)).tolist()
        if "node_mass" in self.node:
            out["mass"] = np.sum(self.node["node_mass"], axis=1).tolist()
        if "node_mass_residual" in self.node:
            out["mass_residual"] = np.sum(
                self.node["node_mass_residual"], axis=1).tolist()
        if self.active is not None:
            out["active"] = self.active.tolist()
        return out

    def summary(self) -> dict:
        """Compact digest for stdout (full fields belong in the
        manifest)."""
        out = {
            "rounds_recorded": len(self),
            "stride": self.spec.stride,
            "topk": self.spec.topk,
            "fields": list(self.fields),
        }
        if len(self) and "node_err" in self.node:
            mag = self.pooled("node_err")[-1]
            worst = int(np.argmax(mag))
            if self.topk_idx is not None:
                worst = int(self.topk_idx[-1][worst])
            out["final_worst_node"] = {
                "node": worst, "abs_err": float(np.max(mag))}
        if self.conv_round is not None:
            conv = self.conv_round
            done = conv[conv >= 0]
            out["convergence_frontier"] = {
                "converged_nodes": int(done.size),
                "nodes": int(conv.size),
                "first_round": int(done.min()) if done.size else None,
                "last_round": int(done.max()) if done.size else None,
            }
        return out

    def to_jsonable(self) -> dict:
        """The manifest ``fields`` block (see obs/report.py
        FIELD_SCHEMA)."""
        block = {
            "spec": {
                "fields": list(self.spec.fields),
                "stride": self.spec.stride,
                "topk": self.spec.topk,
                "tol": self.spec.tol,
            },
            "t": self.t.tolist(),
            "node": {k: v.tolist() for k, v in self.node.items()},
            "edge": {k: v.tolist() for k, v in self.edge.items()},
        }
        if self.active is not None:
            block["active"] = self.active.tolist()
        if self.conv_round is not None:
            block["conv_round"] = self.conv_round.tolist()
        if self.topk_idx is not None:
            block["topk_idx"] = self.topk_idx.tolist()
        if self.edges is not None:
            block["edges"] = {k: v.tolist() for k, v in self.edges.items()}
        if self.coords is not None:
            block["coords"] = self.coords.tolist()
        return block

    @classmethod
    def from_jsonable(cls, block: dict) -> FieldSeries:
        """Rebuild from a manifest ``fields`` block (inspect / doctor
        offline paths)."""
        sp = block.get("spec") or {}
        spec = FieldSpec(fields=tuple(sp.get("fields", ())),
                         stride=int(sp.get("stride", 1)),
                         topk=int(sp.get("topk", 0)),
                         tol=float(sp.get("tol", 1e-6)), strict=False)
        return cls(
            t=np.asarray(block.get("t", []), np.int64),
            active=(np.asarray(block["active"])
                    if block.get("active") is not None else None),
            node=block.get("node") or {},
            edge=block.get("edge") or {},
            conv_round=(np.asarray(block["conv_round"], np.int64)
                        if block.get("conv_round") is not None else None),
            topk_idx=(np.asarray(block["topk_idx"], np.int64)
                      if block.get("topk_idx") is not None else None),
            spec=spec,
            edges=block.get("edges"),
            coords=block.get("coords"),
        )
