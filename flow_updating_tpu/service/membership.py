"""Membership primitives shared by every churn surface.

Three call sites used to hand-roll the same alive-mask edit: the
Engine's fault injection (``kill_nodes``/``revive_nodes``), the
gossip-SGD trainer's mid-training churn schedule, and now the streaming
service's suspend/resume path.  One implementation lives here so "node
churn" means exactly one thing everywhere: flipping the alive mask of a
:class:`~flow_updating_tpu.models.state.FlowUpdatingState` — dead nodes
stop firing, sending and draining; their ledgers stay intact, so a
revived node re-joins with its flow state and the protocol self-heals
(the Flow-Updating paper's fault model).

The service's *graceful* departure (``ServiceEngine.leave``) builds on
this plus ledger detachment; temporary failure (``suspend``/``resume``,
``kill_nodes``/``revive_nodes``) is the bare mask flip.
"""

from __future__ import annotations

import numpy as np


def as_id_array(ids) -> np.ndarray:
    """Normalize a node-id collection to a (k,) int32 numpy array."""
    arr = np.atleast_1d(np.asarray(ids, np.int32))
    if arr.ndim != 1:
        raise ValueError(f"node ids must be a flat sequence, got shape "
                         f"{arr.shape}")
    return arr


def set_alive(state, ids, alive: bool):
    """Flip the liveness mask of ``ids`` (ledgers untouched — the
    temporary-failure churn of the paper; see module docstring)."""
    import jax.numpy as jnp

    idx = jnp.asarray(as_id_array(ids))
    return state.replace(alive=state.alive.at[idx].set(bool(alive)))
