"""The streaming service engine: live aggregation with dynamic membership.

Every other mode of this repo runs a fixed-N batch job; production
aggregation is a *service* — users join, leave and update their values
continuously while the estimate stays live.  The paper's headline
property (self-healing mass conservation under churn) makes Flow-Updating
exactly the protocol for this shape, and the capacity-padding trick
proven offline by the sweep engine makes it compilable: the service
compiles ONE round program for a fixed capacity ``(n_cap, e_cap)`` and
then runs indefinitely in scan segments, with every membership event an
O(event-size) device-side mask/buffer edit between segments — **no
retrace, no recompile** (tests/test_service.py pins the compile count
across 100+ events).

Layout
------
* **node slots**: ``capacity`` usable slots plus one permanently-dead
  *parking* slot (the last id).  Live members carry ``alive=True``;
  free slots are mass-neutral ghosts (value 0, born dead) managed by a
  lowest-id-first free list, so ``join`` is deterministic slot reuse.
* **edge slots**: a fixed budget of ``edge_capacity`` directed slots.
  A free slot is a self-loop parked on the parking slot
  (``src == dst == park``, ``rev`` = itself, ``edge_ok=False``): the
  park never fires (dead), so a free slot's ledger stays exactly zero —
  the mass-neutral pad-edge invariant of
  :mod:`flow_updating_tpu.topology.padding`, held *dynamically*.
* **reductions** run over the sweep engine's uniform-width
  ``(n_cap, W)`` out-edge row matrix (``TopoArrays.sweep_edge_rows``,
  ``W = degree_budget``): per-node sums gather exactly the edge slots a
  row lists, so edge membership is data, not program structure — and the
  row folds are bit-identical to the sorted scatter-add segment ops
  (ops/segment.py), which is what makes a zero-event service run
  bit-identical to the plain engine at the same capacity.

Events edit *traced inputs* (state leaves and TopoArrays leaves) with
``.at[]`` updates of unchanged shape/dtype, so every segment dispatch
hits the same jit cache entry.  Mass accounting across events:

* ``join`` / ``update`` leave the residual ``sum(est) - sum(value)``
  over live nodes unchanged **bit-exactly** (a fresh slot has zero
  flows; a value shift moves ``est`` by the same delta);
* ``leave`` / ``remove_edges`` detach ledger pairs whose residual
  contribution is the pair's antisymmetry deficit — zero at quiescence,
  bounded by the doctor's in-flight allowance mid-flight — and the
  protocol re-converges to zero residual afterwards (the paper's
  self-healing, now an SLO checked by ``doctor``).
"""

from __future__ import annotations

import heapq

import numpy as np

from flow_updating_tpu.models.config import (
    COLLECTALL,
    RoundConfig,
    RoundParams,
)
from flow_updating_tpu.obs.metrics import MetricsRegistry
from flow_updating_tpu.obs.spans import SpanRecorder
from flow_updating_tpu.topology.padding import (
    bucket_ceil,
    edge_rows,
    mask_ghost_state,
    masked_values,
    pad_topology_to,
)
from flow_updating_tpu.service import membership

SERVICE_EVENTS = ("join", "leave", "update", "add_edge", "remove_edge",
                  "suspend", "resume")

_EST_JIT = None   # process-wide jitted node_estimates (boundary reads)


def validate_service_config(cfg: RoundConfig) -> None:
    """The service's config domain: the subset of round programs whose
    topology consumption is fully dynamic (edge membership as data).

    Pairwise modes are rejected — fast pairwise fires a static edge
    coloring and faithful pairwise orders its within-tick scan by the
    static CSR layout, both of which an edge edit would invalidate.
    ``drain > 0`` is rejected for the same reason (the round-robin drain
    priority bakes static per-edge ranks)."""
    if cfg.kernel != "edge":
        raise ValueError(
            "the service engine drives the edge kernel (per-edge state "
            "carries the membership masks); use kernel='edge'")
    if cfg.variant != COLLECTALL:
        raise ValueError(
            "the service engine runs variant='collectall': pairwise "
            "modes bake static per-edge structure (edge coloring / CSR "
            "scan order) that dynamic edge membership would invalidate")
    if cfg.drain != 0:
        raise ValueError(
            "the service engine needs drain=0 (unbounded): the bounded "
            "drain's round-robin priority bakes static per-edge ranks")
    if cfg.delivery not in ("gather", "scatter"):
        raise ValueError(
            f"the service engine runs delivery='gather'|'scatter'; "
            f"{cfg.delivery!r} plans a static permutation network")
    if cfg.segment_impl not in ("auto", "segment"):
        raise ValueError(
            f"the service engine runs segment_impl='auto'|'segment' "
            f"(reductions go through the dynamic row matrix); "
            f"{cfg.segment_impl!r} builds static layouts")
    if cfg.contention:
        raise ValueError(
            "contention needs a static link model; the service's "
            "dynamic edge set has none")


class ServiceEngine:
    """A live, capacity-padded Flow-Updating engine (module docstring).

    Parameters
    ----------
    topo:
        The initial membership graph (its nodes are members 0..N-1).
    capacity:
        Maximum concurrent members.  One extra hidden slot (the parking
        ghost) is appended, so the padded node axis is ``capacity + 1``.
    degree_budget:
        Per-member out-degree budget W (the row-matrix width).  Defaults
        to the initial max degree; ``add_edges`` beyond a row's budget
        raises.
    edge_capacity:
        Total directed edge slots.  Defaults to an eighth-pow2 rounding
        of the initial edge count plus headroom for the spare node slots.
    config:
        A :class:`RoundConfig` in the service domain
        (:func:`validate_service_config`); default
        ``RoundConfig.fast(variant='collectall')``.
    segment_rounds:
        The compiled scan length; ``run`` advances in whole segments.
    values:
        Optional ``(N,)`` / ``(N, D)`` initial payloads overriding the
        topology's values (vector payloads make every mass quantity
        per-feature).
    """

    def __init__(self, topo, capacity: int, *, degree_budget: int | None
                 = None, edge_capacity: int | None = None,
                 config: RoundConfig | None = None,
                 segment_rounds: int = 32, seed: int = 0, values=None,
                 boundary_samples: bool = True, observe: bool = True):
        import jax.numpy as jnp

        from flow_updating_tpu.models.state import (
            check_payload_values,
            init_state,
        )

        cfg = config or RoundConfig.fast(variant=COLLECTALL)
        validate_service_config(cfg)
        N, E = topo.num_nodes, topo.num_edges
        if capacity < N:
            raise ValueError(
                f"capacity={capacity} < initial member count {N}")
        if segment_rounds < 1:
            raise ValueError("segment_rounds must be >= 1")
        max_deg = int(topo.out_deg.max()) if N else 0
        W = max(max_deg, 1) if degree_budget is None else int(degree_budget)
        if W < max_deg:
            raise ValueError(
                f"degree_budget={W} < initial max degree {max_deg}")
        n_cap = int(capacity) + 1          # + the parking ghost
        if edge_capacity is None:
            e_cap = bucket_ceil(E + 4 * (capacity - N) + 2)
        else:
            e_cap = int(edge_capacity)
            if e_cap < E:
                raise ValueError(
                    f"edge_capacity={e_cap} < initial edge count {E}")

        padded = pad_topology_to(topo, n_cap, e_cap, spread="last")
        arrays = padded.device_arrays()
        rows = edge_rows(padded, W, e_cap)
        rows[N:] = e_cap        # ghosts + park list nothing: free slots
        #                         never enter any row's reduction
        deg = np.concatenate(
            [topo.out_deg.astype(np.int32),
             np.zeros(n_cap - N, np.int32)])   # live degrees only
        arrays = arrays.replace(
            sweep_edge_rows=jnp.asarray(rows),
            out_deg=jnp.asarray(deg),
        )
        pv = None
        if values is not None:
            vals = np.asarray(values, np.float64)
            check_payload_values(vals, N)
            pv = masked_values(vals, n_cap)
        state = init_state(padded, cfg, seed=seed, values=pv)
        state = mask_ghost_state(state, N, E)
        params = RoundParams.from_config(cfg)
        if cfg.drop_rate == 0.0:
            params = params.without_drop()

        self.config = cfg
        self.capacity = int(capacity)
        self.degree_budget = W
        self.edge_capacity = e_cap
        self.segment_rounds = int(segment_rounds)
        self.state = state
        self.arrays = arrays
        self.params = params
        self._n_cap = n_cap
        self._park = n_cap - 1
        # host mirrors of the dynamic topology leaves (the free-list /
        # row-occupancy bookkeeping reads these; device edits mirror them)
        self._src = np.asarray(padded.src).copy()
        self._dst = np.asarray(padded.dst).copy()
        self._rev = np.asarray(padded.rev).copy()
        self._src[E:] = self._park
        self._dst[E:] = self._park
        self._delay = np.asarray(padded.delay).copy()
        self._deg = deg.copy()
        self._rows = rows.copy()
        self._member = np.zeros(n_cap, bool)
        self._member[:N] = True
        self._free_nodes = list(range(N, self._park))
        heapq.heapify(self._free_nodes)
        self._free_edges = list(range(E, e_cap))
        heapq.heapify(self._free_edges)
        self._epoch = 0
        self._event_counts = {k: 0 for k in SERVICE_EVENTS}
        self._pending_events = []       # since the last run()
        self.history: list = []         # one record per epoch (run call)
        self._samples: list = []        # boundary telemetry rows
        self._est_cache = None          # (t, est (n_cap,)+F, alive)
        # the flight recorder (obs/metrics.py, obs/spans.py): host-side
        # event/latency accounting plus engine-level spans (recovery,
        # degraded episodes); the query fabric turns this off on its
        # inner service and owns ONE registry for the whole stack
        self.metrics = MetricsRegistry() if observe else None
        self.spans = SpanRecorder() if observe else None
        self._init_resilience()
        self._capture_cache_floor()
        if boundary_samples:
            # a construction-time sample materializes the full (n_cap,)+F
            # estimate matrix on host; a driver that samples per LANE
            # (the query fabric's device-side probe) opts out
            self._sample("init")

    # ---- resilience (flow_updating_tpu.resilience) -----------------------
    def _init_resilience(self) -> None:
        self._wal = None            # WriteAheadLog when durability is on
        self._ring = None           # CheckpointRing when durability is on
        self._resil_dir = None
        self._replaying = False     # recovery replay: never re-journal
        self._wal_applied_seq = 0   # last journaled seq reflected in state
        self._recovery = None       # recover()'s evidence block

    def _journal(self, kind: str, args: dict) -> None:
        """Write-ahead: journal the validated event (fsync'd) BEFORE it
        is applied; recovery re-applies journaled-but-unapplied events."""
        if self._wal is not None and not self._replaying:
            self._wal_applied_seq = self._wal.append(kind, args,
                                                     self.clock)
            if self.metrics is not None:
                self.metrics.observe("wal_fsync_seconds",
                                     self._wal.last_fsync_s)

    def enable_durability(self, directory: str, *,
                          checkpoint_every: int = 8, retain: int = 3,
                          fsync: bool = True) -> ServiceEngine:
        """Arm the event WAL + checkpoint ring in ``directory``: every
        subsequent event/run is journaled before it is applied, and a
        ring archive is written every ``checkpoint_every`` segments
        (``retain`` kept).  Recover after a crash with
        :meth:`recover` (docs/RESILIENCE.md)."""
        from flow_updating_tpu.resilience.recover import arm_durability

        arm_durability(self, directory, kind="service",
                       checkpoint_every=checkpoint_every,
                       retain=retain, fsync=fsync)
        return self

    @classmethod
    def recover(cls, directory: str) -> ServiceEngine:
        """Rebuild the service journaled in ``directory``: newest valid
        ring checkpoint (corrupt newest falls back) + WAL replay of
        every event since — bit-exact vs the uninterrupted run at ANY
        kill point, with the evidence in :meth:`resilience_block`."""
        from flow_updating_tpu.resilience.recover import recover

        return recover(directory, kind="service")

    def state_digest(self) -> str:
        """sha256 over every state leaf + the dynamic topology mirrors
        + free lists — bit-exactness in one comparable string (the
        chaos harness's recovered-vs-control verdict)."""
        import hashlib

        h = hashlib.sha256()
        for name in sorted(self.state.__dataclass_fields__):
            a = np.ascontiguousarray(np.asarray(getattr(self.state,
                                                        name)))
            h.update(name.encode())
            h.update(a.tobytes())
        for name, arr in (("src", self._src), ("dst", self._dst),
                          ("rev", self._rev), ("deg", self._deg),
                          ("rows", self._rows), ("delay", self._delay),
                          ("member", self._member)):
            h.update(name.encode())
            h.update(np.ascontiguousarray(arr).tobytes())
        h.update(repr(sorted(self._free_nodes)).encode())
        h.update(repr(sorted(self._free_edges)).encode())
        return h.hexdigest()

    def resilience_block(self) -> dict | None:
        """The manifest's ``recovery`` block: live WAL/ring accounting
        plus — after :meth:`recover` — the scan/replay evidence
        (``obs.health.check_recovery`` judges it).  None when
        durability is off."""
        if self._wal is None and self._recovery is None:
            return None
        out = {"dir": self._resil_dir, "kind": "service"}
        if self._recovery is not None:
            out.update(self._recovery)
        if self._wal is not None:
            # live accounting wins over the recovery-time scan (its
            # extra evidence keys survive; the pre-replay seq is kept
            # as replay.base_wal_seq): doctor's metrics_consistency
            # compares the gauge against same-moment figures
            wal = dict(out.get("wal") or {})
            wal.update(self._wal.block())
            out["wal"] = wal
        if self._ring is not None:
            ring = dict(out.get("ring") or {})
            ring.update(self._ring.block())
            out["ring"] = ring
        return out

    # ---- compile accounting ---------------------------------------------
    def _capture_cache_floor(self) -> None:
        from flow_updating_tpu.models.rounds import (
            run_rounds,
            run_rounds_telemetry,
        )

        self._cache0 = (run_rounds._cache_size(),
                        run_rounds_telemetry._cache_size())

    @property
    def compile_count(self) -> int:
        """Compiles of the round program since this service was built —
        the zero-recompile SLO (must stay at 1: the first segment).
        Measured on the global jit caches, so it can only over-count
        (never hide a recompile)."""
        from flow_updating_tpu.models.rounds import (
            run_rounds,
            run_rounds_telemetry,
        )

        return ((run_rounds._cache_size() - self._cache0[0])
                + (run_rounds_telemetry._cache_size() - self._cache0[1]))

    # ---- views -----------------------------------------------------------
    @property
    def clock(self) -> int:
        """Completed rounds (the state's round counter)."""
        return int(np.asarray(self.state.t))

    @property
    def live_count(self) -> int:
        return int(np.asarray(self.state.alive).sum())

    @property
    def member_count(self) -> int:
        return int(self._member.sum())

    @property
    def feature_shape(self) -> tuple:
        return tuple(self.state.value.shape[1:])

    def live_ids(self) -> np.ndarray:
        return np.where(np.asarray(self.state.alive))[0].astype(np.int32)

    def member_edges(self) -> list:
        """Current undirected member edges as (u, v) pairs, u < v."""
        live = self._src != self._park
        u, v = self._src[live], self._dst[live]
        keep = u < v
        return list(zip(u[keep].tolist(), v[keep].tolist()))

    # ---- event plumbing --------------------------------------------------
    def _log(self, kind: str, **detail) -> None:
        self._event_counts[kind] += 1
        detail["kind"] = kind
        self._pending_events.append(detail)
        self._est_cache = None   # membership changed: staleness resets
        if self.metrics is not None:
            self.metrics.inc("events_total")
            self.metrics.inc(f"events_{kind}_total")

    def _check_member(self, ids, verb: str) -> np.ndarray:
        ids = membership.as_id_array(ids)
        for i in ids:
            i = int(i)
            if i < 0 or i >= self._park or not self._member[i]:
                raise ValueError(
                    f"{verb}: node {i} is not a member "
                    f"(members occupy slots 0..{self._park - 1})")
        return ids

    def _edge_slot_of(self, u: int, v: int) -> int | None:
        """Directed slot u->v, via u's row (O(degree_budget) scan)."""
        for e in self._rows[u]:
            if e != self.edge_capacity and self._dst[e] == v:
                return int(e)
        return None

    # ---- membership events ----------------------------------------------
    def join(self, value) -> int:
        """Admit one member with payload ``value`` (scalar, or a
        ``(D,)`` vector matching the service's feature shape).  Returns
        the assigned slot id.  The fresh member has zero flows, so its
        estimate equals its value and the live mass residual is
        unchanged bit-exactly.  It starts edgeless — wire it in with
        :meth:`add_edges`."""
        import jax.numpy as jnp

        if not self._free_nodes:
            raise RuntimeError(
                f"service at capacity: {self.capacity} node slots, "
                f"{self.member_count} members and no free slot — raise "
                "capacity= at construction")
        v = np.asarray(value, np.float64)
        if v.shape != self.feature_shape:
            raise ValueError(
                f"join value shape {v.shape} != service feature shape "
                f"{self.feature_shape}")
        self._journal("join", {"value": v.tolist()})
        slot = heapq.heappop(self._free_nodes)
        st = self.state
        z = jnp.zeros(self.feature_shape, st.last_avg.dtype)
        self.state = st.replace(
            value=st.value.at[slot].set(jnp.asarray(v, st.value.dtype)),
            alive=st.alive.at[slot].set(True),
            ticks=st.ticks.at[slot].set(0),
            fired=st.fired.at[slot].set(0),
            last_avg=st.last_avg.at[slot].set(z),
        )
        self._member[slot] = True
        self._log("join", node=int(slot))
        return int(slot)

    def leave(self, ids) -> ServiceEngine:
        """Graceful departure: detach every incident edge pair (both
        ledger directions zeroed, in-flight on those slots invalidated),
        then free the slot (dead, value 0).  Each neighbor's estimate
        absorbs its zeroed ledger entry, so the survivors' mass residual
        changes only by the detached pairs' antisymmetry deficit — zero
        at quiescence, within the in-flight allowance mid-flight — and
        the protocol re-converges (the paper's self-healing)."""
        import jax.numpy as jnp

        ids = self._check_member(ids, "leave")
        self._journal("leave", {"ids": [int(i) for i in ids]})
        pairs = set()
        for u in ids:
            for e in self._rows[int(u)]:
                if e != self.edge_capacity:
                    pairs.add((min(int(e), int(self._rev[e])),
                               max(int(e), int(self._rev[e]))))
        if pairs:
            self._detach_pairs(sorted(pairs))
        st = self.state
        idx = jnp.asarray(ids)
        z = jnp.zeros(ids.shape + self.feature_shape, st.value.dtype)
        self.state = st.replace(
            value=st.value.at[idx].set(z),
            alive=st.alive.at[idx].set(False),
            ticks=st.ticks.at[idx].set(0),
            fired=st.fired.at[idx].set(0),
            last_avg=st.last_avg.at[idx].set(
                jnp.zeros(ids.shape + self.feature_shape,
                          st.last_avg.dtype)),
        )
        for i in ids:
            self._member[int(i)] = False
            heapq.heappush(self._free_nodes, int(i))
            self._log("leave", node=int(i))
        return self

    def update(self, ids, values) -> ServiceEngine:
        """Overwrite members' input values (the protocol tracks dynamic
        inputs natively: estimates shift by the same delta as values, so
        the mass residual is unchanged bit-exactly)."""
        import jax.numpy as jnp

        ids = self._check_member(ids, "update")
        vals = np.asarray(values, np.float64)
        want = ids.shape + self.feature_shape
        if vals.shape != want:
            raise ValueError(
                f"update values shape {vals.shape} != {want} "
                f"(one row per id, feature shape {self.feature_shape})")
        self._journal("update", {"ids": [int(i) for i in ids],
                                 "values": vals.tolist()})
        self.state = self.state.replace(
            value=self.state.value.at[jnp.asarray(ids)].set(
                jnp.asarray(vals, self.state.value.dtype)))
        for i in ids:
            self._log("update", node=int(i))
        return self

    def suspend(self, ids) -> ServiceEngine:
        """Temporary failure (the paper's crash churn): alive mask off,
        ledgers intact — :func:`membership.set_alive`.  A suspended node
        keeps its slot; :meth:`resume` revives it in place."""
        ids = self._check_member(ids, "suspend")
        self._journal("suspend", {"ids": [int(i) for i in ids]})
        self.state = membership.set_alive(self.state, ids, False)
        for i in ids:
            self._log("suspend", node=int(i))
        return self

    def resume(self, ids) -> ServiceEngine:
        ids = self._check_member(ids, "resume")
        self._journal("resume", {"ids": [int(i) for i in ids]})
        self.state = membership.set_alive(self.state, ids, True)
        for i in ids:
            self._log("resume", node=int(i))
        return self

    # ---- edge events -----------------------------------------------------
    def add_edges(self, pairs) -> ServiceEngine:
        """Add undirected member edges: each (u, v) claims two free edge
        slots and one free row-matrix column at each endpoint.  The
        whole batch is validated first, then applied as one device edit
        — an invalid pair leaves the service untouched.  Added edges
        deliver with UNIT delay (a dynamic edge has no platform route;
        detach resets freed slots to delay 1, so slot reuse never leaks
        an old latency-derived delay)."""
        import jax.numpy as jnp

        e_sent = self.edge_capacity
        eidx, srcs, dsts, revs = [], [], [], []
        rows_r, rows_c, rows_v = [], [], []
        nodes, done = [], []
        # validate + stage against scratch copies; commit only if the
        # whole batch is admissible
        rows_scratch = None
        free_scratch = sorted(self._free_edges)
        taken = 0
        for u, v in pairs:
            u, v = int(u), int(v)
            if u == v:
                raise ValueError(f"add_edges: self-loop ({u}, {u})")
            self._check_member([u, v], "add_edges")
            if self._edge_slot_of(u, v) is not None or (u, v) in done \
                    or (v, u) in done:
                raise ValueError(f"add_edges: edge ({u}, {v}) already "
                                 "present")
            if rows_scratch is None:
                rows_scratch = self._rows.copy()
            cu = int(np.argmax(rows_scratch[u] == e_sent))
            cv = int(np.argmax(rows_scratch[v] == e_sent))
            if rows_scratch[u, cu] != e_sent:
                raise RuntimeError(
                    f"add_edges: node {u} is at its degree budget "
                    f"({self.degree_budget}) — raise degree_budget= at "
                    "construction")
            if rows_scratch[v, cv] != e_sent:
                raise RuntimeError(
                    f"add_edges: node {v} is at its degree budget "
                    f"({self.degree_budget}) — raise degree_budget= at "
                    "construction")
            if taken + 2 > len(free_scratch):
                raise RuntimeError(
                    f"add_edges: edge capacity {self.edge_capacity} "
                    "exhausted — raise edge_capacity= at construction")
            e1, e2 = free_scratch[taken], free_scratch[taken + 1]
            taken += 2
            rows_scratch[u, cu] = e1
            rows_scratch[v, cv] = e2
            eidx += [e1, e2]
            srcs += [u, v]
            dsts += [v, u]
            revs += [e2, e1]
            rows_r += [u, v]
            rows_c += [cu, cv]
            rows_v += [e1, e2]
            nodes += [u, v]
            done.append((u, v))
        if not eidx:
            return self
        self._journal("add_edges", {"pairs": [[u, v] for u, v in done]})
        # commit: host mirrors ...
        self._rows = rows_scratch
        self._free_edges = free_scratch[taken:]
        heapq.heapify(self._free_edges)
        for e, s, d, r in zip(eidx, srcs, dsts, revs):
            self._src[e], self._dst[e], self._rev[e] = s, d, r
        for n in nodes:
            self._deg[n] += 1
        for u, v in done:
            self._log("add_edge", u=u, v=v)
        # ... then one batched device edit
        ar = self.arrays
        ei = jnp.asarray(np.asarray(eidx, np.int32))
        self.arrays = ar.replace(
            src=ar.src.at[ei].set(jnp.asarray(np.asarray(srcs, np.int32))),
            dst=ar.dst.at[ei].set(jnp.asarray(np.asarray(dsts, np.int32))),
            rev=ar.rev.at[ei].set(jnp.asarray(np.asarray(revs, np.int32))),
            out_deg=ar.out_deg.at[
                jnp.asarray(np.asarray(nodes, np.int32))].add(1),
            sweep_edge_rows=ar.sweep_edge_rows.at[
                jnp.asarray(np.asarray(rows_r, np.int32)),
                jnp.asarray(np.asarray(rows_c, np.int32))].set(
                jnp.asarray(np.asarray(rows_v, np.int32))),
        )
        # freed slots are scrubbed at detach time, so the new edges start
        # with exactly zero ledgers; only the link mask needs flipping
        self.state = self.state.replace(
            edge_ok=self.state.edge_ok.at[ei].set(True))
        return self

    def remove_edges(self, pairs) -> ServiceEngine:
        """Remove undirected member edges (ledger pair zeroed — mass-
        neutral up to the pair's antisymmetry deficit, see :meth:`leave`).
        Validated as a batch before anything is applied."""
        todo, logs = [], []
        for u, v in pairs:
            u, v = int(u), int(v)
            self._check_member([u, v], "remove_edges")
            e1 = self._edge_slot_of(u, v)
            if e1 is None:
                raise ValueError(f"remove_edges: no edge ({u}, {v})")
            e2 = int(self._rev[e1])
            todo.append((min(e1, e2), max(e1, e2)))
            logs.append((u, v))
        if todo:
            self._journal("remove_edges",
                          {"pairs": [[u, v] for u, v in logs]})
            self._detach_pairs(sorted(set(todo)))
            for u, v in logs:
                self._log("remove_edge", u=u, v=v)
        return self

    def _detach_pairs(self, pairs) -> None:
        """Scrub + park a set of (e, rev e) slot pairs: ledgers, mailbox
        and ring-buffer lanes zeroed (in-flight on a detached edge is
        dropped), row-matrix columns cleared, slots onto the free list."""
        import jax.numpy as jnp

        e_sent = self.edge_capacity
        eidx, nodes = [], []
        rows_r, rows_c = [], []
        for e1, e2 in pairs:
            for e in (e1, e2):
                u = int(self._src[e])
                col = int(np.argmax(self._rows[u] == e))
                assert self._rows[u, col] == e, "row matrix out of sync"
                self._rows[u, col] = e_sent
                rows_r.append(u)
                rows_c.append(col)
                self._deg[u] -= 1
                nodes.append(u)
                self._src[e] = self._dst[e] = self._park
                self._rev[e] = e
                self._delay[e] = 1
                eidx.append(e)
                heapq.heappush(self._free_edges, e)
        ar = self.arrays
        ei = jnp.asarray(np.asarray(eidx, np.int32))
        self.arrays = ar.replace(
            src=ar.src.at[ei].set(self._park),
            dst=ar.dst.at[ei].set(self._park),
            rev=ar.rev.at[ei].set(ei),
            # freed slots return to the pad convention — including UNIT
            # delay: a latency-derived topology's slot must not leak its
            # old delivery delay into a later, unrelated edge that
            # happens to reuse it (re-added edges are unit-delay, like
            # the initial pad slots)
            delay=ar.delay.at[ei].set(1),
            out_deg=ar.out_deg.at[
                jnp.asarray(np.asarray(nodes, np.int32))].add(-1),
            sweep_edge_rows=ar.sweep_edge_rows.at[
                jnp.asarray(np.asarray(rows_r, np.int32)),
                jnp.asarray(np.asarray(rows_c, np.int32))].set(e_sent),
        )
        st = self.state
        zf = jnp.zeros((len(eidx),) + self.feature_shape, st.flow.dtype)
        self.state = st.replace(
            flow=st.flow.at[ei].set(zf),
            est=st.est.at[ei].set(zf),
            recv=st.recv.at[ei].set(False),
            stamp=st.stamp.at[ei].set(0),
            edge_ok=st.edge_ok.at[ei].set(False),
            pending_valid=st.pending_valid.at[:, ei].set(False),
            pending_stamp=st.pending_stamp.at[:, ei].set(0),
            pending_flow=st.pending_flow.at[:, ei].set(0),
            pending_est=st.pending_est.at[:, ei].set(0),
            buf_valid=st.buf_valid.at[:, ei].set(False),
            buf_flow=st.buf_flow.at[:, ei].set(0),
            buf_est=st.buf_est.at[:, ei].set(0),
        )

    # ---- execution -------------------------------------------------------
    def _estimates_device(self) -> np.ndarray:
        """(n_cap,)+F current estimates, via a jitted ``node_estimates``
        (the eager row-fold is ~W dispatches — too slow to pay twice per
        segment boundary; this is a tiny separate program, not a
        recompile of the round scan)."""
        import jax

        from flow_updating_tpu.models.rounds import node_estimates

        global _EST_JIT
        if _EST_JIT is None:
            _EST_JIT = jax.jit(node_estimates)
        return np.asarray(_EST_JIT(self.state, self.arrays))

    def _live_mean(self) -> np.ndarray:
        alive = np.asarray(self.state.alive)
        vals = np.asarray(self.state.value)
        cnt = max(int(alive.sum()), 1)
        return vals[alive].sum(axis=0) / cnt

    def _sample(self, label: str) -> dict:
        """One boundary telemetry row (host side, between segments)."""
        est = self._estimates_device()
        alive = np.asarray(self.state.alive)
        vals = np.asarray(self.state.value)
        live = int(alive.sum())
        a_ex = alive.reshape(alive.shape + (1,) * (est.ndim - 1))
        mass = np.where(a_ex, est, 0).sum(axis=0)
        residual = self._ledger_residual(alive)
        mean = self._live_mean()
        err = est[alive] - mean
        row = {
            "label": label,
            "t": self.clock,
            "active": live,
            "rmse": float(np.sqrt(np.mean(err * err))) if live else 0.0,
            "max_abs_err": float(np.max(np.abs(err))) if live else 0.0,
            "mass": np.atleast_1d(mass).tolist(),
            "mass_residual": np.atleast_1d(residual).tolist(),
        }
        self._samples.append(row)
        self._est_cache = (self.clock, est, alive)
        if self.metrics is not None:
            self.metrics.inc("boundary_samples_total")
            gauges = {"live_members": live,
                      "rmse": row["rmse"],
                      "max_abs_err": row["max_abs_err"]}
            if self._wal is not None:
                gauges["wal_last_seq"] = self._wal.last_seq
                gauges["wal_fsync_seconds_total"] = \
                    self._wal.fsync_seconds_total
            if self._ring is not None:
                gauges["checkpoint_writes"] = self._ring.written_total
                gauges["checkpoint_write_seconds_total"] = \
                    self._ring.write_seconds_total
            self.metrics.sample_row(self.clock, **gauges)
        return row

    def run(self, rounds: int, telemetry=None):
        """Advance ``rounds`` (a whole number of compiled segments) as
        one membership epoch.  Events queued since the previous ``run``
        are bound to this epoch's record, and boundary samples (mass /
        residual / rmse over live members) are taken after the events
        and after the rounds — the doctor's SLO inputs.

        ``telemetry``: an optional
        :class:`~flow_updating_tpu.obs.telemetry.TelemetrySpec` — each
        segment then runs the telemetry scan (same static shape every
        segment: still one compile) and the per-round series is
        returned; otherwise returns ``self``.
        """
        from flow_updating_tpu.models.rounds import (
            run_rounds,
            run_rounds_telemetry,
        )
        from flow_updating_tpu.utils.trace import annotate

        if rounds < 0:
            raise ValueError("rounds must be >= 0")
        if rounds % self.segment_rounds:
            raise ValueError(
                f"rounds={rounds} must be a whole number of compiled "
                f"segments (segment_rounds={self.segment_rounds}) — the "
                "zero-recompile contract fixes the scan length")
        self._journal("run", {"rounds": int(rounds)})
        events = self._pending_events
        self._pending_events = []
        if events or not self._samples \
                or self._samples[-1]["t"] != self.clock:
            before = self._sample("epoch_start")
        else:
            # no events since the last boundary: the state is the one
            # the previous sample measured — reuse it instead of paying
            # another device read
            before = dict(self._samples[-1])
        series_rows = None
        if telemetry is not None and not telemetry.enabled:
            telemetry = None
        for _ in range(rounds // self.segment_rounds):
            # a segment-boundary span for `--trace-dir` captures: a
            # no-op TraceMe when no profiler is recording, so the
            # zero-recompile hot loop stays untouched
            if telemetry is None:
                with annotate("fu.segment"):
                    self.state = run_rounds(
                        self.state, self.arrays, self.config,
                        self.segment_rounds, params=self.params)
            else:
                import jax.numpy as jnp

                mean = jnp.asarray(self._live_mean(),
                                   self.config.jnp_dtype)
                with annotate("fu.segment"):
                    self.state, seg = run_rounds_telemetry(
                        self.state, self.arrays, self.config,
                        self.segment_rounds, telemetry, mean,
                        params=self.params)
                seg = {k: np.asarray(v) for k, v in seg.items()}
                if series_rows is None:
                    series_rows = {k: [v] for k, v in seg.items()}
                else:
                    for k, v in seg.items():
                        series_rows[k].append(v)
        after = self._sample("epoch_end")
        self.history.append({
            "epoch": self._epoch,
            "rounds": int(rounds),
            "t0": before["t"],
            "t1": after["t"],
            "events": [dict(e) for e in events],
            "live": after["active"],
            "before": {k: before[k] for k in
                       ("rmse", "max_abs_err", "mass", "mass_residual",
                        "active")},
            "after": {k: after[k] for k in
                      ("rmse", "max_abs_err", "mass", "mass_residual",
                       "active")},
        })
        self._epoch += 1
        if self.metrics is not None and rounds:
            self.metrics.inc("runs_total")
            self.metrics.inc("segments_total",
                             rounds // self.segment_rounds)
        if self._ring is not None and rounds:
            # the archive reflects every journaled record up to
            # _wal_applied_seq (this run's record included) — recovery
            # replays only what came after
            wrote = self._ring.tick(self, self._wal_applied_seq,
                                    segments=rounds // self.segment_rounds)
            if wrote and self.metrics is not None:
                self.metrics.inc("checkpoints_written_total")
                self.metrics.observe("checkpoint_write_seconds",
                                     self._ring.last_write_s)
        if series_rows is not None:
            from flow_updating_tpu.obs.telemetry import TelemetrySeries

            return TelemetrySeries({
                k: np.concatenate(v) for k, v in series_rows.items()})
        return self

    # ---- reads -----------------------------------------------------------
    def estimates(self, max_staleness: int | None = None):
        """Live members' current estimates: ``(ids, values)`` numpy
        arrays.  ``max_staleness=k`` accepts the boundary sample if it is
        at most ``k`` rounds old — a bounded-staleness read that costs
        nothing while segments run; ``None`` forces a fresh computation.
        Membership events always invalidate the sample (a read after a
        join/leave reflects the new membership)."""
        cache = self._est_cache
        if (max_staleness is not None and cache is not None
                and self.clock - cache[0] <= max_staleness):
            t, est, alive = cache
        else:
            est = self._estimates_device()
            alive = np.asarray(self.state.alive)
            self._est_cache = (self.clock, est, alive)
        ids = np.where(alive)[0].astype(np.int32)
        return ids, est[alive]

    def _ledger_residual(self, alive: np.ndarray) -> np.ndarray:
        """Per-feature live-mass residual ``sum_alive(est) -
        sum_alive(value)``, computed in its mathematically equal ledger
        form ``-sum(flow[e] for live src[e])`` as a fixed-edge-order
        masked sum.  That form makes the event-conservation contract
        *bit-exact*: a ``join`` contributes no edge terms, an ``update``
        touches no flow, so neither can move the residual by even a ulp
        (tests/test_service.py pins this); ``leave``/``remove_edges``
        move it by exactly the detached pairs' antisymmetry deficit."""
        flow = np.asarray(self.state.flow)
        live_e = alive[self._src]
        mask = live_e.reshape(live_e.shape + (1,) * (flow.ndim - 1))
        return -np.where(mask, flow, 0).sum(axis=0)

    def mass_residual(self) -> np.ndarray:
        """(D,) (or scalar as (1,)) per-feature live-mass residual now
        (the ledger form — see :meth:`_ledger_residual`)."""
        return np.atleast_1d(
            self._ledger_residual(np.asarray(self.state.alive)))

    def convergence_report(self) -> dict:
        s = self._sample("report")
        flow = np.asarray(self.state.flow)
        anti = flow + flow[self._rev]
        return {
            "t": self.clock,
            "rmse": s["rmse"],
            "max_abs_err": s["max_abs_err"],
            "mass_residual": s["mass_residual"],
            "antisymmetry_residual": float(np.max(np.abs(anti))),
            "live": self.live_count,
            # scalar scale for check_report's tolerance; the per-feature
            # vector rides alongside
            "true_mean": float(np.max(np.abs(self._live_mean()))),
            "true_mean_per_feature": np.atleast_1d(
                self._live_mean()).tolist(),
            "nodes": self.live_count,
        }

    def service_block(self) -> dict:
        """The manifest's ``service`` block: capacity accounting, epoch
        history, compile count — the inputs of ``doctor``'s service SLO
        checks (obs/health.check_service)."""
        return {
            "capacity": {
                "nodes": self.capacity,
                "edges": self.edge_capacity,
                "degree_budget": self.degree_budget,
                "live": self.live_count,
                "members": self.member_count,
                "free_node_slots": len(self._free_nodes),
                "free_edge_slots": len(self._free_edges),
            },
            "segment_rounds": self.segment_rounds,
            "compile_count": self.compile_count,
            "epochs": [dict(h) for h in self.history],
            "events_total": int(sum(self._event_counts.values())),
            "event_counts": {k: v for k, v in self._event_counts.items()
                             if v},
            "dtype": self.config.dtype,
            "mirror_probe": _mirror_probe(self),
        }

    def _refresh_obs_gauges(self) -> None:
        """Point-in-time gauges ahead of an export/embed (the sampled
        rows carry the history; these carry *now*)."""
        if self.metrics is None:
            return
        m = self.metrics
        m.set_gauge("live_members", self.live_count)
        m.set_gauge("member_count", self.member_count)
        m.set_gauge("free_node_slots", len(self._free_nodes))
        m.set_gauge("free_edge_slots", len(self._free_edges))
        m.set_gauge("compile_count", self.compile_count)
        if self._wal is not None:
            m.set_gauge("wal_last_seq", self._wal.last_seq)
            m.set_gauge("wal_fsync_seconds_total",
                        self._wal.fsync_seconds_total)
        if self._ring is not None:
            m.set_gauge("checkpoint_writes", self._ring.written_total)
            m.set_gauge("checkpoint_write_seconds_total",
                        self._ring.write_seconds_total)

    def serving_trace_block(self) -> dict | None:
        """The manifest's ``serving_trace`` block
        (``flow-updating-serving-trace/v1``): the flight recorder's
        metrics plane + engine-level spans.  None when observation is
        off (a disabled recorder embeds nothing — purity)."""
        if self.metrics is None:
            return None
        from flow_updating_tpu.obs.report import SERVING_TRACE_SCHEMA

        self._refresh_obs_gauges()
        return {
            "schema": SERVING_TRACE_SCHEMA,
            "slo": {},
            "metrics": self.metrics.block(),
            "spans": (self.spans.block()
                      if self.spans is not None else None),
        }

    def boundary_series(self) -> dict:
        """The boundary samples as a telemetry-shaped series dict (one
        row per segment boundary) — doctor's standard series checks run
        on it unchanged."""
        if not self._samples:
            return {}
        keys = ("t", "rmse", "max_abs_err", "mass", "mass_residual",
                "active")
        return {k: [s[k] for s in self._samples] for k in keys}

    # ---- durability ------------------------------------------------------
    def save_checkpoint(self, path: str,
                        extra_meta: dict | None = None) -> ServiceEngine:
        """Write the full service state — protocol state, dynamic
        topology leaves, free lists, epoch counters — as one versioned
        archive (utils/checkpoint.py, ``service-checkpoint`` schema).
        Restore via :meth:`restore_checkpoint`; round-trip is bit-exact
        (tests/test_service.py).  ``extra_meta`` merges extra JSON blocks
        into the service meta (the query fabric's lane tables ride here —
        a plain :meth:`restore_checkpoint` ignores them)."""
        from flow_updating_tpu.utils.checkpoint import (
            save_service_checkpoint,
        )

        topo_arrays = {
            "src": self._src, "dst": self._dst, "rev": self._rev,
            "out_deg": self._deg, "rows": self._rows,
            "delay": self._delay,
            "free_nodes": np.asarray(sorted(self._free_nodes), np.int32),
            "free_edges": np.asarray(sorted(self._free_edges), np.int32),
            "member": self._member,
        }
        meta = {
            "capacity": self.capacity,
            "edge_capacity": self.edge_capacity,
            "degree_budget": self.degree_budget,
            "segment_rounds": self.segment_rounds,
            "epoch": self._epoch,
            "event_counts": dict(self._event_counts),
            "observe": self.metrics is not None,
        }
        if self.metrics is not None:
            # the black box rides the archive: a recovered engine's
            # metrics/span planes are continuous with the pre-crash ones
            meta["obs"] = {
                "metrics": self.metrics.state_dict(),
                "spans": (self.spans.state_dict()
                          if self.spans is not None else None),
            }
        if extra_meta:
            meta.update(extra_meta)
        save_service_checkpoint(path, self.state, self.config,
                                topo_arrays, meta)
        return self

    @classmethod
    def restore_checkpoint(cls, path: str) -> ServiceEngine:
        """Rebuild a service from :meth:`save_checkpoint`'s archive —
        same capacity, same membership, bit-exact state."""
        from flow_updating_tpu.utils.checkpoint import (
            load_service_checkpoint,
        )

        import jax
        import jax.numpy as jnp

        state, cfg, topo_arrays, meta = load_service_checkpoint(path)
        self = object.__new__(cls)
        self.config = cfg
        self.capacity = int(meta["capacity"])
        self.edge_capacity = int(meta["edge_capacity"])
        self.degree_budget = int(meta["degree_budget"])
        self.segment_rounds = int(meta["segment_rounds"])
        self._n_cap = self.capacity + 1
        self._park = self.capacity
        # device-resident leaves: the jit fast path keys on concrete
        # input types, so numpy-leaved state would retrace the round
        # program — breaking the zero-recompile contract on resume
        self.state = jax.tree.map(jnp.asarray, state)
        self._src = topo_arrays["src"].astype(np.int32)
        self._dst = topo_arrays["dst"].astype(np.int32)
        self._rev = topo_arrays["rev"].astype(np.int32)
        self._deg = topo_arrays["out_deg"].astype(np.int32)
        self._rows = topo_arrays["rows"].astype(np.int32)
        self._delay = topo_arrays["delay"].astype(np.int32)
        self._member = topo_arrays["member"].astype(bool)
        self._free_nodes = topo_arrays["free_nodes"].astype(int).tolist()
        heapq.heapify(self._free_nodes)
        self._free_edges = topo_arrays["free_edges"].astype(int).tolist()
        heapq.heapify(self._free_edges)
        # rebuild the device topology pytree from the mirrors; the
        # treedef matches the constructed path (one jit cache entry
        # whichever way the service came up)
        row_start = np.zeros(self._n_cap + 1, np.int64)
        np.cumsum(np.bincount(self._src, minlength=self._n_cap),
                  out=row_start[1:])
        self.arrays = _service_topo_arrays(
            self._src, self._dst, self._rev, self._deg, row_start,
            self._rows, self._delay)
        params = RoundParams.from_config(cfg)
        self.params = (params.without_drop() if cfg.drop_rate == 0.0
                       else params)
        self._epoch = int(meta.get("epoch", 0))
        self._event_counts = {k: 0 for k in SERVICE_EVENTS}
        self._event_counts.update(meta.get("event_counts", {}))
        self._pending_events = []
        self.history = []
        self._samples = []
        self._est_cache = None
        if bool(meta.get("observe", False)):
            obs = meta.get("obs") or {}
            self.metrics = MetricsRegistry.load_state(
                obs.get("metrics") or {})
            sp = obs.get("spans")
            self.spans = (SpanRecorder.load_state(sp)
                          if sp is not None else SpanRecorder())
        else:
            self.metrics = None
            self.spans = None
        self._init_resilience()
        self._capture_cache_floor()
        # the PR-13 regression probe: a restored engine must never hold
        # device leaves aliasing its host mirrors (zero-copy asarray) —
        # fail at construction, not rounds later as a flaky race
        from flow_updating_tpu.analysis.aliasing import (
            assert_no_shared_mirrors,
        )

        assert_no_shared_mirrors(self)
        self._sample("restore")
        return self


def _mirror_probe(engine) -> dict:
    """The service block's host-mirror aliasing record
    (analysis/aliasing.py) — ``shared`` must be empty; doctor's
    ``service_mirror_aliasing`` check judges it."""
    from flow_updating_tpu.analysis.aliasing import shared_mirror_report

    return shared_mirror_report(engine)


def _service_topo_arrays(src, dst, rev, deg, row_start, rows, delay):
    """Assemble the service's TopoArrays pytree from host mirrors
    (restore path) — shape/dtype-identical to the constructed path.

    ``row_start``/``edge_rank``/``deg_e`` are DEAD leaves under the
    service config domain: their only consumers in the round kernel are
    the drain>0 priority pick and the faithful-pairwise scan, both
    rejected by :func:`validate_service_config` (a post-churn src array
    is not CSR-sorted, so a bincount row_start would be meaningless
    anyway).  They are rebuilt here solely so the pytree treedef and
    leaf set match the constructed path — the live leaves the kernel
    reads (src, rev, out_deg, delay, sweep_edge_rows) come from the
    checkpointed mirrors bit-exactly.  Relaxing the config domain means
    carrying these as mirrors too.

    The passed-in mirrors are the engine's HOST bookkeeping arrays,
    mutated in place by later events (``_detach_pairs`` does
    ``self._deg[u] -= 1``); ``jnp.asarray`` on CPU may alias the numpy
    buffer zero-copy, so the device leaves MUST be built with
    ``jnp.array`` (always copies) — an aliased leaf lets a host edit
    race the functional device edit of the same event, nondeterministic
    double-application (found by the recovery replay's bit-exactness
    gate, tests/test_resilience.py)."""
    import jax.numpy as jnp

    from flow_updating_tpu.topology.graph import TopoArrays

    E = src.shape[0]
    edge_rank = (np.arange(E, dtype=np.int64)
                 - row_start[src]).astype(np.int32)
    return TopoArrays(
        src=jnp.array(src),
        dst=jnp.array(dst),
        rev=jnp.array(rev),
        out_deg=jnp.array(deg),
        row_start=jnp.asarray(row_start, dtype=jnp.int32),
        edge_rank=jnp.asarray(edge_rank),
        delay=jnp.array(delay),
        deg_e=jnp.asarray(deg[src]),
        sweep_edge_rows=jnp.array(rows),
    )
