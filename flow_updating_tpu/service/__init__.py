"""Streaming service mode: live aggregation with dynamic membership.

The production shape of Flow-Updating (ROADMAP open item 3): a
long-running engine compiled ONCE for a fixed capacity, advancing in
scan segments while members join, leave, update values and rewire edges
between segments — zero recompiles, conserved per-feature mass, and the
paper's churn tolerance monitored as an SLO by ``doctor``.

* :mod:`flow_updating_tpu.service.engine` — the
  :class:`~flow_updating_tpu.service.engine.ServiceEngine`: capacity-
  padded state (the sweep engine's mass-neutral ghost construction,
  shared via :mod:`flow_updating_tpu.topology.padding`), free-list slot
  management, O(event)-cost device edits, bounded-staleness estimate
  reads, versioned checkpoint/restore;
* :mod:`flow_updating_tpu.service.membership` — the single alive-mask
  churn implementation shared with the Engine's fault injection and the
  gossip-SGD trainer's churn schedule.

CLI surface: the ``serve`` subcommand (scripted event files or stdin);
manifests use the ``flow-updating-service-report/v1`` schema.  See
docs/SERVICE.md.
"""

from flow_updating_tpu.service.engine import (
    ServiceEngine,
    validate_service_config,
)
from flow_updating_tpu.service.membership import set_alive

__all__ = ["ServiceEngine", "validate_service_config", "set_alive"]
