from flow_updating_tpu.topology.graph import Topology, build_topology
from flow_updating_tpu.topology.platform import Platform, load_platform
from flow_updating_tpu.topology.deployment import Deployment, load_deployment

__all__ = [
    "Topology",
    "build_topology",
    "Platform",
    "load_platform",
    "Deployment",
    "load_deployment",
]
