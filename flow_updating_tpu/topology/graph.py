"""Dense edge-index topology — the TPU-native replacement for SimGrid routing.

The reference delegates "who can talk to whom, and how fast" to SimGrid's
C++ platform layer: hosts/links/routes parsed from XML and consumed by a
flow-level network model (see SURVEY.md N3/N6; reference contact surface
``flowupdating-collectall.py:152-157``).  On TPU the natural representation
is a flat, static, *symmetrized* directed edge list:

* ``src/dst (E,) int32`` — directed edges sorted by ``(src, dst)``, so every
  node's out-edges are contiguous (CSR rows) and segment ops over ``src`` can
  use ``indices_are_sorted=True``;
* ``rev (E,) int32`` — index of the opposite direction.  The Flow-Updating
  antisymmetry invariant (``flows[sender] = -msg.flow``,
  reference ``flowupdating-collectall.py:99``) becomes a permutation by
  ``rev``; message delivery into the receiver's ledger is a scatter through
  ``rev`` at *send* time, making the delivery phase elementwise;
* ``delay (E,) int32`` — per-edge delivery latency in whole rounds, derived
  from route latencies for latency-warped ("async fidelity") execution.

Symmetrization absorbs the reference's runtime neighbor-adoption repair
(``flowupdating-collectall.py:94-96``; 6 of the 14 declared directed edges in
its ``actors.xml`` have no reverse): missing reverse edges are added at load
time and reported through :func:`build_topology`'s ``adopted`` output.
"""

from __future__ import annotations

import dataclasses
import logging
from collections.abc import Mapping, Sequence

import numpy as np

from flow_updating_tpu.utils import struct

logger = logging.getLogger("flow_updating_tpu")


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static graph for one run.  Host-side (numpy); device views on demand."""

    num_nodes: int
    src: np.ndarray        # (E,) int32, sorted
    dst: np.ndarray        # (E,) int32
    rev: np.ndarray        # (E,) int32, rev[rev[e]] == e
    out_deg: np.ndarray    # (N,) int32 (== in_deg after symmetrization)
    row_start: np.ndarray  # (N+1,) int64 CSR offsets into src/dst
    edge_rank: np.ndarray  # (E,) int32 position of edge within its src row
    delay: np.ndarray      # (E,) int32 delivery delay in rounds, >= 1
    values: np.ndarray     # (N,) float64 initial node values
    names: tuple | None = None          # (N,) host names, optional
    speeds: np.ndarray | None = None    # (N,) float64 host flop-rates, optional
    bandwidth: np.ndarray | None = None  # (E,) float64 route bandwidth, optional
    latency_s: np.ndarray | None = None  # (E,) float64 route latency (seconds)
    adopted: np.ndarray | None = None   # (A,2) int64 directed edges adopted at
    #                                     load to symmetrize a declared-
    #                                     asymmetric graph (the load-time
    #                                     mirror of the reference's runtime
    #                                     neighbor repair, collectall.py:94-96);
    #                                     None on the native big-graph path
    # --- link-level contention model (platform-loaded topologies only) ---
    edge_links: np.ndarray | None = None     # (E, K) int32 link ids along each
    #                                          edge's route, padded with L
    link_ser_rounds: np.ndarray | None = None  # (L,) f64 per-link serialization
    #                                          cost of ONE message in rounds
    #                                          (= msg_bytes * latency_scale /
    #                                          (tick * capacity))
    link_shared: np.ndarray | None = None    # (L,) bool — False = FATPIPE
    lat_rounds: np.ndarray | None = None     # (E,) f64 route latency in rounds
    #                                          (pre-scaled; no serialization)
    structure: object | None = None          # closed-form adjacency descriptor
    #                                          (ops/structured.py) attached by
    #                                          regular-graph generators; lets
    #                                          the node kernel compute A(x)
    #                                          as a stencil (spmv='structured')
    virtual: bool = False                    # True = edge arrays deliberately
    #                                          NOT materialized (mega-scale
    #                                          regular graphs); only the
    #                                          structured stencil can run —
    #                                          edge-consuming layouts raise
    drop_perm: np.ndarray | None = None      # (E,) int32 new-edge -> ORIGINAL
    #                                          edge id, set by the topology
    #                                          compiler's stable reorder
    #                                          (plan/compile.py): per-message
    #                                          loss draws stay keyed by
    #                                          original edge id, so a planned
    #                                          drop>0 run replays the exact
    #                                          original loss realization
    membership: np.ndarray | None = None     # (N,) int32 planted-partition
    #                                          block id — the community
    #                                          generator exposes its ground
    #                                          truth so scenarios, heatmaps
    #                                          and blame never re-derive it
    bridge_edges: np.ndarray | None = None   # (B,) int64 directed edge ids
    #                                          crossing community blocks
    #                                          (membership[src] !=
    #                                          membership[dst])

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    @property
    def max_delay(self) -> int:
        return int(self.delay.max()) if self.num_edges else 1

    @property
    def has_link_model(self) -> bool:
        return self.edge_links is not None

    def contended_max_delay(self, max_flows: int | None = None,
                            inflight_per_edge: int = 0) -> int:
        """Upper bound on the dynamic delay under contention: every edge's
        latency plus its worst link serialization when every edge whose
        route CROSSES that link sends at once (``max_flows`` caps the
        per-link count) — the safe ``delay_depth`` for ``cfg.contention``
        runs.  ``inflight_per_edge`` > 0 additionally counts that many
        standing in-flight messages per crossing edge
        (``cfg.contention_backlog`` sizing: each edge can hold up to
        ``delay_depth`` undelivered ring slots).  Uses exact per-link
        crossing counts: a link only ever sees the routes that traverse
        it, so sizing by total edge count would inflate the (D, E) ring
        buffers quadratically for nothing."""
        if not self.has_link_model:
            return self.max_delay
        L = self.link_ser_rounds.shape[0]
        cross = np.bincount(
            self.edge_links.reshape(-1), minlength=L + 1
        )[:L]
        cross = cross * (1 + max(int(inflight_per_edge), 0))
        if max_flows is not None:
            cross = np.minimum(cross, max_flows)
        ser = np.where(self.link_shared,
                       self.link_ser_rounds * np.maximum(cross, 1),
                       self.link_ser_rounds)
        serp = np.concatenate([ser, [0.0]])
        worst = serp[self.edge_links].max(axis=1)
        return max(
            1, int(np.ceil((self.lat_rounds + worst).max()))
        )

    @property
    def true_mean(self) -> float:
        return float(self.values.mean())

    def _require_edges(self, what: str) -> None:
        if self.virtual:
            raise ValueError(
                f"{what} needs materialized edge arrays, but this topology "
                "is virtual (generator called with materialize_edges=False "
                "for mega-scale runs); only the node kernel with "
                "spmv='structured' can execute it — rebuild with "
                "materialize_edges=True for any other path"
            )

    def edge_coloring(self) -> tuple[np.ndarray, int]:
        """Proper edge coloring (undirected; both directions share a color).
        Requires materialized edges (raises on virtual topologies).

        Computed by repeated maximal-matching extraction (each pass picks
        every edge that is the lowest-indexed uncolored edge at *both*
        endpoints — a maximal matching — and gives it the next color).
        Used by the fast synchronous pairwise mode: firing one color class
        per round makes concurrent 2-party averages disjoint, which keeps
        the crossing-message dynamics stable (all-edges-at-once pairwise
        averaging diverges on irregular graphs).

        Cached after first computation (and carried through checkpoints —
        ``utils/checkpoint.py`` re-seeds it on restore).  At scale
        (>= 50k directed edges) the C++ greedy coloring is used instead
        when available: hubs-first smallest-free-color, near-maxdeg color
        counts, ~20x faster than the matching extractor at BA-100k
        (measured 16.8 s -> well under a second).  Returns
        (color (E,) int32, C).
        """
        cached = getattr(self, "_edge_coloring", None)
        if cached is not None:
            return cached
        self._require_edges("edge_coloring")
        E = self.num_edges
        if E >= 50_000:
            from flow_updating_tpu import native

            out = native.edge_coloring(self)
            if out is not None:
                object.__setattr__(self, "_edge_coloring", out)
                return out
        und = np.where(self.src < self.dst)[0]
        u = self.src[und].astype(np.int64)
        v = self.dst[und].astype(np.int64)
        M = len(und)
        color = np.full(M, -1, np.int32)
        uncolored = np.ones(M, bool)
        idx = np.arange(M, dtype=np.int64)
        c = 0
        while uncolored.any():
            # grow one MAXIMAL matching (repeat Luby-style picks until no
            # uncolored edge has both endpoints free) -> <= 2*maxdeg - 1
            # colors total
            free = np.ones(self.num_nodes, bool)
            this = np.zeros(M, bool)
            avail = uncolored.copy()
            while True:
                eid = np.where(avail, idx, M)
                first = np.full(self.num_nodes, M, dtype=np.int64)
                np.minimum.at(first, u, eid)
                np.minimum.at(first, v, eid)
                pick = avail & (first[u] == idx) & (first[v] == idx)
                if not pick.any():
                    break
                this |= pick
                free[u[pick]] = False
                free[v[pick]] = False
                avail &= ~pick & free[u] & free[v]
            color[this] = c
            uncolored &= ~this
            c += 1
        full = np.full(E, -1, np.int32)
        full[und] = color
        full[self.rev[und]] = color
        object.__setattr__(self, "_edge_coloring", (full, c))
        return full, c

    def ell_buckets(self) -> EllBuckets:
        """Degree-bucketed ELL adjacency for scatter-free neighbor sums.

        Nodes are permuted into ascending-degree order and grouped into
        buckets keyed by the next power of two of their degree; each
        bucket stores a dense ``(rows, width)`` neighbor-index matrix
        whose width is the bucket's true max degree (NOT the power-of-two
        key — see the comment at the width computation below)
        (indices in *permuted* node space, padded with N → a zero slot).
        A neighbor sum then needs only per-bucket gathers + row reductions
        and one concatenate — no scatter, no segment ops.  This is the
        TPU answer to SURVEY.md §7's hard part (a): degree-skewed
        scatter/gather without serializing scatters.

        Cached after first computation.
        """
        cached = getattr(self, "_ell_buckets", None)
        if cached is not None:
            return cached
        self._require_edges("ell_buckets")
        N = self.num_nodes
        deg = self.out_deg.astype(np.int64)
        width = np.zeros(N, np.int64)
        nz = deg > 0
        width[nz] = 1 << np.ceil(np.log2(deg[nz])).astype(np.int64)
        order = np.argsort(width, kind="stable").astype(np.int32)
        inv = np.empty(N, np.int32)
        inv[order] = np.arange(N, dtype=np.int32)

        mats = []
        edge_mats = []
        row_counts = []
        widths = []
        start = 0
        sorted_w = width[order]
        while start < N:
            wkey = sorted_w[start]
            end = int(np.searchsorted(sorted_w, wkey, side="right"))
            rows = order[start:end]
            # the power of two is only the GROUPING key (bounds bucket
            # count at log2 maxdeg); the stored width is the bucket's true
            # max degree — e.g. fat-tree switches (degree 160, key 256)
            # would otherwise carry 37% pad slots, pushing the benes
            # network width P at k=160 from 8.4M to 16.8M elements
            w = int(deg[rows].max()) if wkey else 0
            if w == 0:
                mat = np.empty((len(rows), 0), np.int32)
                emat = np.empty((len(rows), 0), np.int32)
            else:
                lo = self.row_start[rows]
                d = deg[rows]
                ar = np.arange(int(w), dtype=np.int64)
                valid = ar[None, :] < d[:, None]
                col = np.where(valid, lo[:, None] + ar[None, :], 0)
                mat = np.where(valid, inv[self.dst[col]], N).astype(np.int32)
                emat = np.where(valid, col, self.num_edges).astype(np.int32)
            mats.append(mat)
            edge_mats.append(emat)
            row_counts.append(len(rows))
            widths.append(int(w))
            start = end
        out = EllBuckets(
            perm=order, inv_perm=inv, widths=tuple(widths),
            row_counts=tuple(row_counts), mats=tuple(mats),
            edge_mats=tuple(edge_mats),
        )
        object.__setattr__(self, "_ell_buckets", out)
        return out

    def name_to_id(self) -> dict:
        if self.names is None:
            raise ValueError("topology has no node names")
        return {n: i for i, n in enumerate(self.names)}

    def neighbors(self, node: int) -> np.ndarray:
        lo, hi = self.row_start[node], self.row_start[node + 1]
        return self.dst[lo:hi]

    def device_arrays(self, coloring: bool = False,
                      segment_ell: bool = False,
                      delivery_benes=False,
                      segment_benes=False):
        """Device-resident pytree of the arrays the round kernel consumes.

        ``coloring=True`` additionally materializes the edge coloring (only
        needed by the fast synchronous pairwise mode).  ``segment_ell=True``
        materializes the degree-bucketed out-edge ELL matrices used by the
        scatter-free segment reductions (``cfg.segment_impl='ell'``).
        ``segment_benes`` follows the same tri-state convention as
        ``delivery_benes``, selecting the fused executor for the segment
        networks with ``"fused"``.  ``delivery_benes`` is tri-state: ``True`` plans the reverse-edge
        permutation as a Beneš network (``cfg.delivery='benes'`` — message
        delivery without the scalar-gather lowering, see ops/permute.py);
        the string ``"fused"`` additionally routes it through the fused
        Pallas executor (``cfg.delivery='benes_fused'``,
        ops/pallas_fused.py); ``False`` keeps the gather formulation."""
        self._require_edges("device_arrays")
        import jax.numpy as jnp

        edge_color = None
        num_colors = 0
        if coloring:
            col, num_colors = self.edge_coloring()
            edge_color = jnp.asarray(col)
        ell_edge_mats = None
        ell_inv_perm = None
        if segment_ell:
            ell = self.ell_buckets()
            ell_edge_mats = tuple(jnp.asarray(m) for m in ell.edge_mats)
            ell_inv_perm = jnp.asarray(ell.inv_perm)
        deg_e = jnp.asarray(self.out_deg[self.src])
        seg_plan = None
        seg_dist = None
        seg_extract_masks = ()
        seg_place_masks = ()
        if segment_benes:
            from flow_updating_tpu.ops.seg_benes import plan_segments

            seg_plan, dist = plan_segments(
                self.row_start, self.out_deg, self.edge_rank,
                fused=segment_benes == "fused",
            )
            seg_dist = jnp.asarray(dist)
            seg_extract_masks, seg_place_masks = seg_plan.device_leaves()
        rev_plan = None
        rev_masks = ()
        delay_rev = None
        if delivery_benes:
            from flow_updating_tpu.ops.permute import padded_perm_plan

            rev_plan = padded_perm_plan(self.rev,
                                        fused=delivery_benes == "fused")
            rev_masks = rev_plan.device_masks()
            delay_rev = jnp.asarray(self.delay[self.rev])
        link = {}
        if self.has_link_model:
            # pad entry L: serialization 0 (never the max), not shared
            link = dict(
                edge_links=jnp.asarray(self.edge_links),
                link_ser_rounds=jnp.asarray(
                    np.concatenate([self.link_ser_rounds, [0.0]]),
                    dtype=jnp.float32,
                ),
                link_shared=jnp.asarray(
                    np.concatenate([self.link_shared, [False]])
                ),
                lat_rounds=jnp.asarray(self.lat_rounds, dtype=jnp.float32),
            )
        return TopoArrays(
            src=jnp.asarray(self.src),
            dst=jnp.asarray(self.dst),
            rev=jnp.asarray(self.rev),
            drop_perm=(None if self.drop_perm is None
                       else jnp.asarray(self.drop_perm)),
            out_deg=jnp.asarray(self.out_deg),
            row_start=jnp.asarray(self.row_start, dtype=jnp.int32),
            edge_rank=jnp.asarray(self.edge_rank),
            delay=jnp.asarray(self.delay),
            edge_color=edge_color,
            num_colors=num_colors,
            ell_edge_mats=ell_edge_mats,
            ell_inv_perm=ell_inv_perm,
            rev_plan=rev_plan,
            rev_masks=rev_masks,
            delay_rev=delay_rev,
            deg_e=deg_e,
            seg_plan=seg_plan,
            seg_dist=seg_dist,
            seg_extract_masks=seg_extract_masks,
            seg_place_masks=seg_place_masks,
            **link,
        )

    def with_values(self, values: np.ndarray) -> Topology:
        values = np.asarray(values, dtype=np.float64)
        if values.ndim not in (1, 2) or values.shape[0] != self.num_nodes:
            raise ValueError(
                f"values must have shape ({self.num_nodes},) or "
                f"({self.num_nodes}, D) — got {values.shape}")
        return dataclasses.replace(self, values=values)


@dataclasses.dataclass(frozen=True)
class EllBuckets:
    """Degree-bucketed ELL adjacency (host-side; see Topology.ell_buckets).

    ``perm`` maps permuted position -> original node id; bucket ``b`` covers
    permuted rows ``[sum(row_counts[:b]), sum(row_counts[:b+1]))`` with a
    dense ``(row_counts[b], widths[b])`` neighbor matrix in permuted space,
    padded with N.
    """

    perm: np.ndarray        # (N,) int32
    inv_perm: np.ndarray    # (N,) int32
    widths: tuple           # per-bucket padded width
    row_counts: tuple       # per-bucket row count
    mats: tuple             # per-bucket (rows, width) int32 NEIGHBOR indices
    #                         (permuted node space, padded with N)
    edge_mats: tuple        # per-bucket (rows, width) int32 OUT-EDGE indices
    #                         (CSR edge space, padded with E)


@struct.dataclass
class TopoArrays:
    """Pytree of device arrays the round kernel consumes."""

    src: object
    dst: object
    rev: object
    out_deg: object
    row_start: object
    edge_rank: object
    delay: object
    drop_perm: object = None  # (E,) i32 plan-edge -> original edge id: the
    #                           topology compiler's stable reorder keys the
    #                           per-message loss draw by ORIGINAL edge id so
    #                           planned drop>0 runs are bit-exact vs the
    #                           unplanned kernel; None = identity
    edge_color: object = None
    num_colors: int = struct.field(pytree_node=False, default=0)
    sweep_edge_rows: object = None  # (N, W) i32 out-edge indices, pad = E —
    #                                the sweep engine's uniform-width row
    #                                layout: per-node reductions unroll the
    #                                W columns in edge order (bit-exact with
    #                                the sorted scatter-add, no scatter at
    #                                all; ops/segment.rows_segment_*)
    num_colors_arr: object = None  # () i32 traced color count — the sweep
    #                                engine's batched arrays carry it so one
    #                                vmapped program serves instances with
    #                                different color counts (num_colors is
    #                                static metadata and would split the
    #                                treedef); None = use num_colors
    ell_edge_mats: object = None   # tuple of (rows, w) out-edge ELL buckets
    ell_inv_perm: object = None    # (N,) original node -> permuted row
    # link-level contention model (cfg.contention; platform topologies)
    edge_links: object = None        # (E, K) i32 link ids (pad = L)
    link_ser_rounds: object = None   # (L+1,) f32 one-message cost in rounds
    link_shared: object = None       # (L+1,) bool — False = FATPIPE / pad
    lat_rounds: object = None        # (E,) f32 route latency in rounds
    # gather-free message delivery (cfg.delivery='benes')
    rev_masks: tuple = ()            # Beneš stage masks for the rev perm
    delay_rev: object = None         # (E,) i32 = delay[rev] (static)
    rev_plan: object = struct.field(pytree_node=False, default=None)
    # gather/scatter-free segment reductions + broadcasts
    # (cfg.segment_impl='benes'; ops/seg_benes.py)
    deg_e: object = None             # (E,) i32 out_deg[src], baked at build
    #                                  (deliver's drain priority modulus — a
    #                                  topology constant; never recomputed
    #                                  through the broadcast network)
    seg_dist: object = None          # (P,) i32 edge_rank padded (free masks)
    seg_extract_masks: tuple = ()    # row-end -> node Beneš masks
    seg_place_masks: tuple = ()      # node -> row-head Beneš masks
    seg_plan: object = struct.field(pytree_node=False, default=None)
    # device-side Byzantine fault injection (flow_updating_tpu.scenarios):
    # the round kernel corrupts the WIRE, never the honest ledgers.  None
    # (the default everywhere) is pytree STRUCTURE — the injection is
    # statically absent and the compiled program is bit-identical to the
    # plain one.  Masks vmap per-lane under the sweep engine.
    adv_lie_mask: object = None      # (N,) bool — value-lying nodes: every
    #                                  message they send reports
    #                                  adv_lie_value as the estimate
    adv_lie_value: object = None     # () payload dtype — the reported lie
    adv_corrupt_mask: object = None  # (E,) bool — edges whose outgoing wire
    #                                  flow is scaled by adv_corrupt_gain
    #                                  (the receiver's antisymmetry write
    #                                  then no longer cancels the sender's)
    adv_corrupt_gain: object = None  # () — wire-flow multiplier
    adv_silent_mask: object = None   # (N,) bool — silently dropping
    #                                  senders: every send is lost on the
    #                                  wire, the sender's ledger updates
    #                                  regardless (exactly a lost put)
    adv_down_mask: object = None     # (E,) bool — scheduled correlated
    #                                  link failure: the masked edges
    #                                  lose every send during rounds
    #                                  [adv_down_from, adv_down_until)
    #                                  (partition a subtree, then heal)
    adv_down_from: object = None     # () int32 — first dead round
    adv_down_until: object = None    # () int32 — first healed round
    # per-lane aggregate reduction modes (flow_updating_tpu.aggregates):
    # (D,) int32 over the vector-payload lane axis — 0 = additive mean
    # ledger (the plain protocol), 1 = max consensus, 2 = min consensus.
    # Extrema lanes keep flow ≡ 0 and latch the cohort extremum into the
    # value column, so the all-zero free-lane fixed point holds under
    # every mode.  None (the default everywhere) is pytree STRUCTURE —
    # mode selection is statically absent and the compiled program is
    # bit-identical to the plain one; installing modes is ONE extra
    # lowering, after which mode changes are `.at[]` data edits.
    lane_modes: object = None


def _symmetrize(pairs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Both directions of every declared edge, deduped, self-loops dropped.

    Returns (directed_edges sorted by (src, dst), adopted) where ``adopted``
    lists directed edges that were only present via symmetrization — the
    load-time equivalent of the reference's "X was not Y's neighbor" repair
    path (``flowupdating-collectall.py:94-96``).
    """
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    keep = pairs[:, 0] != pairs[:, 1]
    pairs = pairs[keep]
    fwd = pairs
    bwd = pairs[:, ::-1]
    both = np.concatenate([fwd, bwd], axis=0)
    both = np.unique(both, axis=0)  # sorted lexicographically by (src, dst)
    declared = np.unique(fwd, axis=0)
    # adopted = directed edges present in `both` but not declared
    both_keys = both[:, 0] * (both.max() + 1 if both.size else 1) + both[:, 1]
    decl_keys = declared[:, 0] * (both.max() + 1 if both.size else 1) + declared[:, 1]
    adopted = both[~np.isin(both_keys, decl_keys)]
    return both, adopted


def locality_order(topo: Topology, start: int = 0) -> np.ndarray:
    """BFS node ordering for locality-aware partitioning.

    Contiguous-block sharding (``parallel.sharded.plan_sharding``) cuts
    every edge whose endpoints land in different blocks; renumbering nodes
    by BFS layers first places neighborhoods together, which drops the cut
    fraction sharply on topologies with spatial structure (fat-tree, grid,
    ring) and is a no-op-cost heuristic on expanders (ER) where no
    partition is good.  Returns ``order`` with ``order[new_id] = old_id``,
    covering all components (restart at the lowest unvisited node).
    """
    N = topo.num_nodes
    visited = np.zeros(N, bool)
    order = np.empty(N, np.int64)
    pos = 0
    frontier = np.array([start], np.int64) if N else np.empty(0, np.int64)
    visited[frontier] = True
    while pos < N:
        if frontier.size == 0:
            nxt = int(np.argmax(~visited))  # lowest unvisited node
            frontier = np.array([nxt], np.int64)
            visited[nxt] = True
        order[pos: pos + frontier.size] = frontier
        pos += frontier.size
        # all neighbors of the frontier, deduped, unvisited only
        # (vectorized ragged slice extraction: no per-node python loop)
        lo = topo.row_start[frontier]
        counts = topo.row_start[frontier + 1] - lo
        total = int(counts.sum())
        if total:
            seg = np.repeat(np.arange(frontier.size), counts)
            within = np.arange(total) - np.repeat(
                np.cumsum(counts) - counts, counts
            )
            idx = topo.dst[lo[seg] + within].astype(np.int64)
        else:
            idx = np.empty(0, np.int64)
        idx = np.unique(idx)
        idx = idx[~visited[idx]]
        visited[idx] = True
        frontier = idx.astype(np.int64)
    return order


def reorder_topology(topo: Topology, order: np.ndarray) -> Topology:
    """Renumber nodes by ``order`` (``order[new_id] = old_id``), rebuilding
    the sorted edge list, reverse permutation and CSR structure.  Per-edge
    attributes (delay, bandwidth, latency) follow their edges; ``adopted``
    is dropped (load-time report, already consumed)."""
    N, E = topo.num_nodes, topo.num_edges
    order = np.asarray(order, np.int64)
    inv = np.empty(N, np.int64)
    inv[order] = np.arange(N, dtype=np.int64)
    new_src = inv[topo.src]
    new_dst = inv[topo.dst]
    e_order = np.lexsort((new_dst, new_src))
    e_pos = np.empty(E, np.int64)
    e_pos[e_order] = np.arange(E, dtype=np.int64)
    src = new_src[e_order].astype(np.int32)
    dst = new_dst[e_order].astype(np.int32)
    rev = e_pos[topo.rev[e_order]].astype(np.int32)
    out_deg = topo.out_deg[order]
    row_start = np.zeros(N + 1, np.int64)
    np.cumsum(out_deg, out=row_start[1:])
    edge_rank = (np.arange(E, dtype=np.int64) - row_start[src]).astype(np.int32)
    pick_e = lambda a: None if a is None else a[e_order]
    out = dataclasses.replace(
        topo,
        src=src,
        dst=dst,
        rev=rev,
        out_deg=out_deg,
        row_start=row_start,
        edge_rank=edge_rank,
        delay=topo.delay[e_order],
        values=topo.values[order],
        names=(tuple(topo.names[i] for i in order)
               if topo.names is not None else None),
        speeds=None if topo.speeds is None else topo.speeds[order],
        bandwidth=pick_e(topo.bandwidth),
        latency_s=pick_e(topo.latency_s),
        adopted=None,
        edge_links=pick_e(topo.edge_links),
        lat_rounds=pick_e(topo.lat_rounds),
        # planted-partition ground truth follows the renumbering: block
        # ids travel with their nodes, bridge edge ids with their edges
        membership=(None if topo.membership is None
                    else topo.membership[order].astype(np.int32)),
        bridge_edges=(None if topo.bridge_edges is None
                      else np.sort(e_pos[topo.bridge_edges])),
        # a structure descriptor indexes sections by the GENERATOR's node
        # layout; after renumbering it would compute silently wrong
        # stencil sums (same reasoning as pad_topology)
        structure=None,
    )
    # a coloring is a property of the (undirected) edges, invariant under
    # renumbering — carry the cache through so a reordered partition runs
    # the SAME matching sequence as the original topology (exact parity)
    cached = getattr(topo, "_edge_coloring", None)
    if cached is not None:
        col, c = cached
        object.__setattr__(out, "_edge_coloring", (col[e_order], c))
    return out


def build_topology(
    num_nodes: int,
    pairs: np.ndarray | Sequence,
    values: np.ndarray | None = None,
    names: Sequence[str] | None = None,
    latency_s: Mapping[tuple, float] | None = None,
    bandwidth: Mapping[tuple, float] | None = None,
    speeds: np.ndarray | None = None,
    tick_interval: float = 1.0,
    latency_scale: float = 0.0,
    msg_bytes: float = 104.0,
    seed: int = 0,
    warn_asymmetric: bool = True,
    route_links: Mapping[tuple, tuple] | None = None,
    link_caps: np.ndarray | None = None,
    link_shared: np.ndarray | None = None,
) -> Topology:
    """Build a :class:`Topology` from (possibly asymmetric) directed pairs.

    Args:
      num_nodes: node count N.
      pairs: (M, 2) declared directed edges (asymmetric declarations allowed —
        they are symmetrized, mirroring the reference's runtime adoption).
      values: (N,) initial node values; defaults to uniform [0, 1) from `seed`.
      names: optional host names.
      latency_s: optional {(u, v): seconds} route latencies (symmetric lookup:
        (u,v) falls back to (v,u)).
      bandwidth: optional {(u, v): bytes/s} route bandwidths.
      tick_interval: simulated seconds per round (the reference's
        ``TICK_INTERVAL = 1.0``, ``flowupdating-collectall.py:23``).
      latency_scale: 0.0 -> unit delay (fast path, every edge delivers next
        round).  > 0 -> latency-warped rounds:
        ``delay = max(1, round((latency + msg_bytes/bandwidth) *
        latency_scale / tick_interval))``.
      msg_bytes: simulated wire size of one protocol message, the
        serialization term of the transfer time when route bandwidths are
        known (the reference self-reports ~104 bytes via
        ``FlowUpdatingMsg.size()``, ``flowupdating-collectall.py:13-19``).
      route_links / link_caps / link_shared: link-level route membership for
        the shared-link contention model (``Platform.link_table``) —
        {(u, v): tuple(link_idx)}, per-link capacities (bytes/s), and
        SHARED-vs-FATPIPE flags.  Requires ``latency_scale > 0``; enables
        ``RoundConfig(contention=True)`` runs where the per-round delay is
        recomputed from concurrent flow counts (SimGrid's max-min model
        approximated by bottleneck fair share, SURVEY.md N3).
    """
    pairs_arr = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    native_out = None
    if len(pairs_arr) >= 2_000_000 and not warn_asymmetric:
        # big-graph fast path: C++ symmetrize+sort+rev (generators only —
        # the adopted-edge report needs the numpy path).  Range-check
        # BEFORE the call: the native builder filters bad endpoints
        # instead of raising.
        if pairs_arr.size and (pairs_arr.min() < 0
                               or pairs_arr.max() >= num_nodes):
            raise ValueError("edge endpoint out of range")
        from flow_updating_tpu import native

        native_out = native.build_graph_arrays(num_nodes, pairs_arr)
    adopted = None
    if native_out is not None:
        src, dst, rev, out_deg = native_out
        E = len(src)
    else:
        edges, adopted = _symmetrize(pairs_arr)
        if len(adopted) and warn_asymmetric:
            shown = ", ".join(
                f"{int(a)}->{int(b)}" for a, b in adopted[:8]
            )
            logger.warning(
                "topology: %d directed edge(s) had no declared reverse; "
                "adopted at load time (%s%s)",
                len(adopted), shown, "..." if len(adopted) > 8 else "",
            )
        if edges.size and edges.max() >= num_nodes:
            raise ValueError("edge endpoint out of range")

        E = edges.shape[0]
        src = edges[:, 0].astype(np.int32)
        dst = edges[:, 1].astype(np.int32)

        # Reverse-edge permutation: position of (dst, src) in the sorted
        # edge list.
        order_keys = src.astype(np.int64) * num_nodes + dst.astype(np.int64)
        rev_keys = dst.astype(np.int64) * num_nodes + src.astype(np.int64)
        rev = np.searchsorted(order_keys, rev_keys).astype(np.int32)
        assert np.array_equal(order_keys[rev], rev_keys), "graph not symmetric"

        out_deg = np.bincount(src, minlength=num_nodes).astype(np.int32)
    row_start = np.zeros(num_nodes + 1, dtype=np.int64)
    np.cumsum(out_deg, out=row_start[1:])
    edge_rank = (np.arange(E, dtype=np.int64) - row_start[src]).astype(np.int32)

    if values is None:
        rng = np.random.default_rng(seed)
        values = rng.uniform(0.0, 1.0, size=num_nodes)
    values = np.asarray(values, dtype=np.float64)
    if values.shape != (num_nodes,):
        raise ValueError(f"values must have shape ({num_nodes},)")

    lat = None
    bw = None
    if latency_s is not None:
        lat = np.zeros(E, dtype=np.float64)
        for i in range(E):
            key = (int(src[i]), int(dst[i]))
            lat[i] = latency_s.get(key, latency_s.get((key[1], key[0]), 0.0))
    if bandwidth is not None:
        bw = np.zeros(E, dtype=np.float64)
        for i in range(E):
            key = (int(src[i]), int(dst[i]))
            bw[i] = bandwidth.get(key, bandwidth.get((key[1], key[0]), 0.0))

    if latency_scale > 0.0 and lat is not None:
        # transfer time = route latency + serialization at route bandwidth
        # (the flow-model cost of the reference's sized put_async:
        # FlowUpdatingMsg.size() ~= 104 bytes fed to put_async,
        # flowupdating-collectall.py:13-19,124)
        transfer_s = lat.copy()
        if bw is not None:
            pos = bw > 0
            transfer_s[pos] += msg_bytes / bw[pos]
        delay = np.maximum(
            1, np.rint(transfer_s * latency_scale / tick_interval)
        ).astype(np.int32)
    else:
        delay = np.ones(E, dtype=np.int32)

    edge_links_arr = None
    link_ser = None
    link_shared_arr = None
    lat_rounds = None
    if route_links is not None and latency_scale > 0.0:
        if link_caps is None or lat is None:
            raise ValueError(
                "route_links needs link_caps and latency_s for the "
                "contention model"
            )
        L = len(link_caps)
        K = max((len(v) for v in route_links.values()), default=1) or 1
        edge_links_arr = np.full((E, K), L, np.int32)
        for i in range(E):
            key = (int(src[i]), int(dst[i]))
            lks = route_links.get(key, route_links.get((key[1], key[0]), ()))
            edge_links_arr[i, : len(lks)] = lks
        link_ser = (msg_bytes * latency_scale
                    / (tick_interval * np.asarray(link_caps, np.float64)))
        link_shared_arr = (np.ones(L, bool) if link_shared is None
                           else np.asarray(link_shared, bool))
        lat_rounds = lat * latency_scale / tick_interval

    return Topology(
        num_nodes=num_nodes,
        src=src,
        dst=dst,
        rev=rev,
        out_deg=out_deg,
        row_start=row_start,
        edge_rank=edge_rank,
        delay=delay,
        values=values,
        names=tuple(names) if names is not None else None,
        speeds=np.asarray(speeds, dtype=np.float64) if speeds is not None else None,
        bandwidth=bw,
        latency_s=lat,
        adopted=adopted,
        edge_links=edge_links_arr,
        link_ser_rounds=link_ser,
        link_shared=link_shared_arr,
        lat_rounds=lat_rounds,
    )
