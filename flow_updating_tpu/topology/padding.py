"""Capacity padding: ghost nodes, self-loop pad edges, row layouts.

The mass-neutral padding trick — append dead ghost nodes and
``edge_ok=False`` self-loop pad edges so a topology fills a fixed
``(n_pad, e_pad)`` capacity without perturbing the protocol — was proven
by the batched sweep engine (:mod:`flow_updating_tpu.sweep.pack`) and is
promoted here so the streaming service engine
(:mod:`flow_updating_tpu.service`) shares ONE construction.  The rules
(asserted by tests/test_sweep.py and tests/test_service.py):

* **ghost nodes** are appended after the real nodes with value 0 and are
  *born dead* (``alive=False`` in the packed state): they never fire,
  never drain, and every alive-masked metric (rmse, mass, active)
  excludes them — the instance's true mean and per-feature mass are
  untouched;
* **pad edges** are self-loops on ghost nodes with ``edge_ok=False`` (a
  failed link loses every message put on it) and ``rev`` mapped to
  themselves, appended after the real edges.  Because edges sort by
  ``(src, dst)`` and every ghost id exceeds every real id, the real edge
  arrays stay a bit-identical *prefix* of the padded arrays;
* the **edge coloring** of a padded topology extends the real coloring
  with color ``-1`` on pad self-loops (``src == dst`` never enters the
  matching), which no round ever fires.

Two ghost-placement policies serve the two consumers:

* ``spread='even'`` (the sweep's historical layout, bit-exact-pinned by
  tests/test_sweep.py): pad self-loops are spread evenly across ALL
  ghosts, capping every row's degree — which bounds the uniform row
  width W of the batched reduction layout;
* ``spread='last'`` (the service layout): every pad self-loop parks on
  the LAST ghost — the service's permanently-dead parking slot — so the
  remaining ghosts are clean, zero-degree node slots a ``join`` can
  claim, and a freed edge slot always has a dead node to park on.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.topology.graph import Topology


def pow2_ceil(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length() if x > 1 else 1


def mask_ghost_state(state, n_real: int, e_real: int):
    """The packed-state ghost mask: nodes past ``n_real`` are born dead
    and edges past ``e_real`` are failed links — the ONE mass-neutral
    masking edit every capacity consumer applies after
    :func:`pad_topology_to` + ``init_state`` (the sweep packer, the
    streaming service, the query fabric's isolated comparators).  Dead
    ghosts never fire and failed pad links never carry a message, so
    the real prefix evolves bit-identically to the unpadded run."""
    return state.replace(
        alive=state.alive.at[n_real:].set(False),
        edge_ok=state.edge_ok.at[e_real:].set(False),
    )


def masked_values(values, n_rows: int, cohort=None) -> np.ndarray:
    """A ``(n_rows,) + F`` float64 value array with ``values`` written on
    ``cohort``'s slots and exactly ``0.0`` everywhere else — the
    mass-neutral masking rule: a slot outside the cohort contributes
    nothing to the aggregate (a ghost for THIS value stream), yet still
    relays like any other node.

    ``cohort=None`` writes ``values`` as the leading prefix — the
    capacity-padding case (sweep lanes, service construction), where the
    ghosts are the trailing pad slots.  An explicit ``cohort`` is the
    query fabric's per-lane case: one row per cohort slot id, every
    non-cohort slot (members included) masked to zero."""
    vals = np.asarray(values, np.float64)
    if cohort is None:
        if vals.shape[0] > n_rows:
            raise ValueError(
                f"masked_values: {vals.shape[0]} value rows exceed "
                f"{n_rows} slots")
        pad = np.zeros((n_rows - vals.shape[0],) + vals.shape[1:])
        return np.concatenate([vals, pad], axis=0)
    cohort = np.asarray(cohort, np.int64)
    if cohort.ndim != 1:
        raise ValueError(
            f"masked_values: cohort must be a 1-D id array "
            f"(got shape {cohort.shape})")
    if vals.shape[0] != cohort.shape[0]:
        raise ValueError(
            f"masked_values: {vals.shape[0]} value rows for "
            f"{cohort.shape[0]} cohort ids (need one row per id)")
    if cohort.size and (cohort.min() < 0 or cohort.max() >= n_rows):
        raise ValueError(
            f"masked_values: cohort ids must lie in [0, {n_rows}) "
            f"(got [{cohort.min()}, {cohort.max()}])")
    if np.unique(cohort).size != cohort.size:
        raise ValueError("masked_values: duplicate cohort ids")
    out = np.zeros((n_rows,) + vals.shape[1:])
    out[cohort] = vals
    return out


def bucket_ceil(x: int) -> int:
    """Round up to an eighth-power-of-two boundary: at most 12.5% pad
    waste per axis, at most 8 bucket sizes per octave (the
    compile-count/pad-waste trade)."""
    g = max(pow2_ceil(x) // 8, 1)
    return ((int(x) + g - 1) // g) * g


def pad_topology_to(topo: Topology, n_pad: int, e_pad: int,
                    spread: str = "even") -> Topology:
    """Pad ``topo`` to exactly ``(n_pad, e_pad)`` with ghost nodes and
    self-loop pad edges placed per ``spread`` (see module docstring).
    The real arrays remain a prefix; ghost values are 0."""
    topo._require_edges("pad_topology_to (capacity packing)")
    if spread not in ("even", "last"):
        raise ValueError(f"unknown ghost-placement policy {spread!r} "
                         "(use 'even' or 'last')")
    N, E = topo.num_nodes, topo.num_edges
    if n_pad <= N:
        raise ValueError(
            f"n_pad={n_pad} must exceed the real node count {N} (at "
            "least one ghost node carries the pad edges)")
    if e_pad < E:
        raise ValueError(f"e_pad={e_pad} < real edge count {E}")
    pad_n = n_pad - N
    pad_e = e_pad - E
    if spread == "even":
        # ghost i in [N, n_pad) takes an even contiguous share of the pad
        # self-loops; (g, g) pairs sort ascending by g, so the edge list
        # stays (src, dst)-sorted with the real edges as a prefix
        ghost_of = (N + (np.arange(pad_e, dtype=np.int64) * pad_n)
                    // max(pad_e, 1) % pad_n) if pad_e else \
            np.empty(0, np.int64)
        ghost_of = np.sort(ghost_of).astype(np.int32)
    else:
        # every pad self-loop on the LAST ghost (the service's parking
        # slot); still sorted — the park id exceeds every other id
        ghost_of = np.full(pad_e, n_pad - 1, np.int32)

    src = np.concatenate([topo.src, ghost_of])
    dst = np.concatenate([topo.dst, ghost_of])
    # self-loops reverse to themselves: rev stays an involution and the
    # antisymmetry permutation is the identity on the pad slice
    rev = np.concatenate([topo.rev, np.arange(E, e_pad, dtype=np.int32)])
    ghost_deg = np.bincount(ghost_of - N, minlength=pad_n) \
        if pad_e else np.zeros(pad_n, np.int64)
    pad_rank = (np.arange(pad_e, dtype=np.int64)
                - np.concatenate([[0], np.cumsum(ghost_deg)])[
                    ghost_of - N]) if pad_e else np.empty(0, np.int64)
    edge_rank = np.concatenate(
        [topo.edge_rank, pad_rank.astype(np.int32)])
    delay = np.concatenate([topo.delay, np.ones(pad_e, np.int32)])
    out_deg = np.concatenate(
        [topo.out_deg, ghost_deg.astype(np.int32)])
    values = np.concatenate([topo.values, np.zeros(pad_n)])
    counts = np.bincount(src, minlength=n_pad)
    row_start = np.zeros(n_pad + 1, np.int64)
    np.cumsum(counts, out=row_start[1:])

    padded = dataclasses.replace(
        topo,
        num_nodes=n_pad,
        src=src,
        dst=dst,
        rev=rev,
        out_deg=out_deg,
        row_start=row_start,
        edge_rank=edge_rank,
        delay=delay,
        values=values,
        names=None,
        speeds=None,
        bandwidth=None,
        latency_s=None,
        adopted=None,
        # the link-contention model is rejected by the packers (link
        # route tables don't batch); drop the arrays for consistency
        edge_links=None,
        link_ser_rounds=None,
        link_shared=None,
        lat_rounds=None,
        # a structure descriptor indexes the UNpadded node layout
        structure=None,
        # planted-partition ground truth is sized to the UNpadded arrays;
        # scenario/blame consumers read it from the original topology
        membership=None,
        bridge_edges=None,
    )
    # carry a computed coloring through (extended with -1 on pad
    # self-loops) so the padded instance runs the SAME matching sequence;
    # an uncached coloring recomputes identically (src==dst edges never
    # enter the matching)
    cached = getattr(topo, "_edge_coloring", None)
    if cached is not None:
        col, c = cached
        col = np.concatenate([col, np.full(pad_e, -1, np.int32)])
        object.__setattr__(padded, "_edge_coloring", (col, c))
    return padded


def edge_rows(padded: Topology, width: int, e_pad: int) -> np.ndarray:
    """The (N_pad, W) out-edge index matrix of the scatter-free row
    reduction layout (pad slot = e_pad; see ops/segment.rows_segment_*)."""
    lo = padded.row_start[:-1]
    deg = padded.out_deg.astype(np.int64)
    ar = np.arange(width, dtype=np.int64)
    valid = ar[None, :] < deg[:, None]
    return np.where(valid, lo[:, None] + ar[None, :], e_pad).astype(
        np.int32)


def row_width(topo: Topology, n_pad: int, e_pad: int) -> int:
    """Uniform row width this instance needs in an ``(n_pad, e_pad)``
    bucket under even ghost spreading: its real max degree, or the
    evenly-spread ghost degree if that is larger."""
    pad_n = n_pad - topo.num_nodes
    pad_e = e_pad - topo.num_edges
    ghost_deg = -(-pad_e // pad_n) if pad_n and pad_e else 0
    real = int(topo.out_deg.max()) if topo.num_nodes else 0
    return max(real, ghost_deg, 1)
