"""SimGrid platform-XML loader (the dialect of ``simgrid.dtd`` the reference uses).

Replaces SimGrid's C++ platform parser + routing tables (SURVEY.md N6; the
reference loads its platform at ``flowupdating-collectall.py:154``).  We parse
the same declarative dialect — ``<host id speed>``, ``<link id bandwidth
latency [sharing_policy]>``, ``<route src dst><link_ctn id/></route>`` inside
``<zone>``/``<AS>`` — but emit plain numpy tables instead of a routing engine:
per-route latency is the sum of link latencies along the declared path and
per-route bandwidth the min over links, which is all the Flow-Updating
workload observes of SimGrid's flow-level model.

Only the subset of the DTD exercised by gossip platforms is supported; rich
features (clusters, caburettor bandwidth profiles, state traces) are out of
scope and rejected loudly rather than silently misparsed.
"""

from __future__ import annotations

import dataclasses
import math
import re
import xml.etree.ElementTree as ET
from collections.abc import Mapping

import numpy as np

# Unit multipliers for SimGrid value strings, e.g. "98.095Mf", "41.2MBps",
# "59.904us", "35.083019ms".
_SI = {
    "": 1.0, "k": 1e3, "K": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
    "m": 1e-3, "u": 1e-6, "n": 1e-9, "p": 1e-12,
}

_NUM_RE = re.compile(r"^\s*([0-9.eE+-]+)\s*([A-Za-z]*)\s*$")


def _mult(unit: str, text: str, kind: str) -> float:
    if unit not in _SI:
        raise ValueError(f"unknown unit in {kind} value {text!r}")
    return _SI[unit]


def parse_value(text: str, kind: str) -> float:
    """Parse a SimGrid quantity: kind in {'speed', 'bandwidth', 'time'}."""
    m = _NUM_RE.match(text)
    if not m:
        raise ValueError(f"cannot parse {kind} value {text!r}")
    num, unit = float(m.group(1)), m.group(2)
    if kind == "speed":  # '98.095Mf' -> flops
        unit = unit[:-1] if unit.endswith("f") else unit
        return num * _mult(unit, text, kind)
    if kind == "bandwidth":  # '41.27MBps' or 'kBps' or 'Bps' -> bytes/s
        if unit.endswith("Bps"):
            unit = unit[:-3]
        elif unit.endswith("bps"):  # bits per second
            return num * _mult(unit[:-3], text, kind) / 8.0
        return num * _mult(unit, text, kind)
    if kind == "time":  # '59.904us' / '1.4ms' / '15s' / bare seconds
        if unit.endswith("s"):
            unit = unit[:-1]
        return num * _mult(unit, text, kind)
    raise ValueError(f"unknown kind {kind}")


@dataclasses.dataclass(frozen=True)
class Link:
    id: str
    bandwidth: float  # bytes/s
    latency: float    # seconds
    sharing_policy: str = "SHARED"


@dataclasses.dataclass(frozen=True)
class Route:
    src: str
    dst: str
    links: tuple  # link ids in path order

    def latency(self, links: Mapping[str, Link]) -> float:
        return float(sum(links[l].latency for l in self.links))

    def bandwidth(self, links: Mapping[str, Link]) -> float:
        return float(min(links[l].bandwidth for l in self.links))


@dataclasses.dataclass(frozen=True)
class Platform:
    """Parsed platform: host table + link table + explicit routes."""

    hosts: dict       # name -> speed (flops)
    links: dict       # id -> Link
    routes: dict      # (src, dst) -> Route, symmetric lookup via route()

    @property
    def host_names(self) -> tuple:
        return tuple(self.hosts.keys())

    def add_host(self, name: str, speed: float) -> Platform:
        """Programmatic host creation — the analogue of the reference's
        ``e.netzone_root.add_host("observer", 25e6)``
        (``flowupdating-collectall.py:159``)."""
        hosts = dict(self.hosts)
        hosts[name] = float(speed)
        return dataclasses.replace(self, hosts=hosts)

    def route(self, src: str, dst: str) -> Route | None:
        r = self.routes.get((src, dst))
        if r is None:
            r = self.routes.get((dst, src))
        return r

    def route_latency(self, src: str, dst: str, default: float = 0.0) -> float:
        r = self.route(src, dst)
        return r.latency(self.links) if r is not None else default

    def route_bandwidth(self, src: str, dst: str,
                        default: float = math.inf) -> float:
        r = self.route(src, dst)
        return r.bandwidth(self.links) if r is not None else default

    def latency_table(self, names: list) -> dict:
        """{(u_id, v_id): seconds} over the given host-name ordering."""
        out = {}
        for i, a in enumerate(names):
            for j, b in enumerate(names):
                if i == j:
                    continue
                r = self.route(a, b)
                if r is not None:
                    out[(i, j)] = r.latency(self.links)
        return out

    def bandwidth_table(self, names: list) -> dict:
        """{(u_id, v_id): bytes/s} (bottleneck link along the route)."""
        out = {}
        for i, a in enumerate(names):
            for j, b in enumerate(names):
                if i == j:
                    continue
                r = self.route(a, b)
                if r is not None:
                    out[(i, j)] = r.bandwidth(self.links)
        return out

    def link_table(self, names: list):
        """Link-level route membership for the contention model.

        Returns ``(link_caps (L,) f64, link_shared (L,) bool,
        route_links {(u_id, v_id): tuple(link_idx)})`` over a stable
        (declaration-ordered) link indexing.  This is what SimGrid's
        flow-level model contends over: concurrent transfers crossing the
        same SHARED link split its bandwidth; FATPIPE links don't share
        (SURVEY.md N3; reference links at
        ``platforms/small_platform.xml:13-36``).
        """
        link_ids = list(self.links.keys())
        idx = {lid: k for k, lid in enumerate(link_ids)}
        caps = np.array(
            [self.links[l].bandwidth for l in link_ids], dtype=np.float64
        )
        shared = np.array(
            [self.links[l].sharing_policy.upper() != "FATPIPE"
             for l in link_ids], dtype=bool,
        )
        route_links = {}
        for i, a in enumerate(names):
            for j, b in enumerate(names):
                if i == j:
                    continue
                r = self.route(a, b)
                if r is not None:
                    route_links[(i, j)] = tuple(idx[l] for l in r.links)
        return caps, shared, route_links


_UNSUPPORTED = {"cluster", "cabinet", "peer", "trace", "trace_connect", "bypassRoute"}


def load_platform(path: str) -> Platform:
    tree = ET.parse(path)
    root = tree.getroot()
    if root.tag != "platform":
        raise ValueError(f"{path}: root element is <{root.tag}>, expected <platform>")

    hosts: dict = {}
    links: dict = {}
    routes: dict = {}

    def walk(elem):
        for child in elem:
            tag = child.tag
            if tag in ("zone", "AS"):
                walk(child)
            elif tag == "host":
                hosts[child.attrib["id"]] = parse_value(child.attrib["speed"], "speed")
            elif tag == "link":
                links[child.attrib["id"]] = Link(
                    id=child.attrib["id"],
                    bandwidth=parse_value(child.attrib["bandwidth"], "bandwidth"),
                    latency=parse_value(child.attrib.get("latency", "0us"), "time"),
                    sharing_policy=child.attrib.get("sharing_policy", "SHARED"),
                )
            elif tag == "route":
                path_links = tuple(
                    lc.attrib["id"] for lc in child if lc.tag == "link_ctn"
                )
                r = Route(src=child.attrib["src"], dst=child.attrib["dst"], links=path_links)
                routes[(r.src, r.dst)] = r
            elif tag in _UNSUPPORTED:
                raise NotImplementedError(
                    f"{path}: platform element <{tag}> is not supported by the "
                    "gossip topology loader"
                )
            # silently ignore <prop> and comments
    walk(root)

    missing = {
        l for r in routes.values() for l in r.links if l not in links
    }
    if missing:
        raise ValueError(f"{path}: routes reference undeclared links {sorted(missing)}")
    return Platform(hosts=hosts, links=links, routes=routes)
