"""Synthetic topology generators for the benchmark configs.

The reference only ships one 7-host platform; the benchmark ladder
(BASELINE.json configs) needs Erdős–Rényi 10k, Barabási–Albert 100k and a
1M-node fat-tree.  Generators return a :class:`Topology`; undirected edges
are produced once and symmetrized by :func:`build_topology`.

numpy implementations here; the C++ native runtime
(``flow_updating_tpu.native``) accelerates the sequential BA process and
large builds when available.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.ops.structured import (
    CompleteStruct,
    FatTreeStruct,
    Grid2dStruct,
    HypercubeStruct,
    RingStruct,
    Torus2dStruct,
)
from flow_updating_tpu.topology.graph import Topology, build_topology


def _finish(n, pairs, seed, values) -> Topology:
    if values is None:
        values = np.random.default_rng(seed + 1).uniform(0.0, 1.0, n)
    # generators emit undirected edges as single-direction pairs by design;
    # symmetrization is intended, not a declaration repair
    return build_topology(n, pairs, values=values, seed=seed, warn_asymmetric=False)


def ring(n: int, k: int = 1, seed: int = 0, values=None) -> Topology:
    """Ring lattice: node i connected to i+1..i+k (mod n)."""
    i = np.arange(n, dtype=np.int64)
    pairs = np.concatenate(
        [np.stack([i, (i + d) % n], axis=1) for d in range(1, k + 1)], axis=0
    )
    topo = _finish(n, pairs, seed, values)
    if n > 2 * k:  # below this, symmetrization-dedup breaks the roll form
        topo = dataclasses.replace(topo, structure=RingStruct(n=n, k=k))
    return topo


def grid2d(h: int, w: int, seed: int = 0, values=None) -> Topology:
    """2-D grid (4-neighborhood)."""
    idx = np.arange(h * w, dtype=np.int64).reshape(h, w)
    right = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    down = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()], axis=1)
    topo = _finish(h * w, np.concatenate([right, down]), seed, values)
    return dataclasses.replace(topo, structure=Grid2dStruct(h=h, w=w))


def torus2d(h: int, w: int, seed: int = 0, values=None) -> Topology:
    """2-D torus (periodic 4-neighborhood)."""
    idx = np.arange(h * w, dtype=np.int64).reshape(h, w)
    right = np.stack([idx.ravel(), np.roll(idx, -1, axis=1).ravel()], axis=1)
    down = np.stack([idx.ravel(), np.roll(idx, -1, axis=0).ravel()], axis=1)
    topo = _finish(h * w, np.concatenate([right, down]), seed, values)
    if h >= 3 and w >= 3:  # wrap edges dedup below this
        topo = dataclasses.replace(topo, structure=Torus2dStruct(h=h, w=w))
    return topo


def hypercube(d: int, seed: int = 0, values=None) -> Topology:
    """d-dimensional hypercube: 2^d nodes, node i ~ i^(1<<b)."""
    if d < 1:
        raise ValueError("hypercube dimension d must be >= 1")
    i = np.arange(1 << d, dtype=np.int64)
    # emit each undirected edge once (from its 0-bit endpoint), per the
    # module convention — halves the symmetrize-sort input
    pairs = np.concatenate(
        [np.stack([lo, lo ^ (1 << b)], axis=1)
         for b in range(d)
         for lo in (i[(i >> b) & 1 == 0],)], axis=0
    )
    topo = _finish(1 << d, pairs, seed, values)
    return dataclasses.replace(topo, structure=HypercubeStruct(d=d))


def complete(n: int, seed: int = 0, values=None) -> Topology:
    i, j = np.triu_indices(n, k=1)
    topo = _finish(n, np.stack([i, j], axis=1), seed, values)
    if n >= 2:
        topo = dataclasses.replace(topo, structure=CompleteStruct(n=n))
    return topo


def erdos_renyi(n: int, avg_degree: float = 8.0, seed: int = 0, values=None) -> Topology:
    """G(n, m) with m = n * avg_degree / 2 undirected edges, plus a random
    Hamiltonian-cycle backbone so the graph is connected (convergence to the
    global mean needs one component)."""
    m = int(n * avg_degree / 2)
    if n >= 100_000:
        from flow_updating_tpu import native

        pairs = native.gen_erdos_renyi_pairs(n, m, seed)
        if pairs is not None:
            return _finish(n, pairs, seed, values)
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n, size=m, dtype=np.int64)
    v = rng.integers(0, n, size=m, dtype=np.int64)
    perm = rng.permutation(n).astype(np.int64)
    backbone = np.stack([perm, np.roll(perm, -1)], axis=1)
    pairs = np.concatenate([np.stack([u, v], axis=1), backbone], axis=0)
    return _finish(n, pairs, seed, values)


def barabasi_albert(n: int, m: int = 4, seed: int = 0, values=None) -> Topology:
    """Preferential attachment; degree-skewed (the hard case for segment ops).

    Uses the repeated-endpoints sampling trick; vectorized in chunks (targets
    for a whole chunk of new nodes are drawn from the endpoint multiset built
    so far, which is a faithful-enough BA approximation at framework-test
    scale — the C++ native generator does the exact sequential process).
    """
    if n > 10_000:
        from flow_updating_tpu import native

        pairs = native.gen_barabasi_albert_pairs(n, m, seed)
        if pairs is not None:
            return _finish(n, pairs, seed, values)
    rng = np.random.default_rng(seed)
    if n <= m + 1:
        return complete(n, seed=seed, values=values)
    # seed clique of m+1 nodes
    i, j = np.triu_indices(m + 1, k=1)
    endpoints = [np.concatenate([i, j]).astype(np.int64)]
    pairs = [np.stack([i, j], axis=1).astype(np.int64)]
    next_node = m + 1
    chunk = max(256, n // 64)
    while next_node < n:
        cnt = min(chunk, n - next_node)
        pool = np.concatenate(endpoints)
        new = np.arange(next_node, next_node + cnt, dtype=np.int64)
        tgt = pool[rng.integers(0, len(pool), size=(cnt, m))]
        srcs = np.repeat(new, m)
        dsts = tgt.ravel()
        pairs.append(np.stack([srcs, dsts], axis=1))
        endpoints.append(np.concatenate([srcs, dsts]))
        next_node += cnt
    return _finish(n, np.concatenate(pairs), seed, values)


def community(n: int, c: int = 8, k_in: float = 8.0, k_out: float = 0.5,
              seed: int = 0, values=None) -> Topology:
    """Planted-partition graph: ``c`` dense communities bridged sparsely.

    Nodes split into ``c`` contiguous blocks; inside each block an
    Erdős–Rényi layer with average degree ``k_in`` plus a random
    Hamiltonian backbone (intra-community connectivity); between blocks
    ``n * k_out / 2`` random bridge edges plus one guaranteed bridge per
    consecutive block pair (whole-graph connectivity).  ``k_out <<
    k_in`` gives the conductance-bottleneck regime (slow mixing across
    bridges) — the hard benchmark case the scenario roadmap names, and
    the friendly case for the topology compiler: blocks are contiguous,
    so RCM leaves the adjacency near-block-diagonal and the banded
    executor covers most edges with a few dense lanes."""
    if c < 1:
        raise ValueError("community count c must be >= 1")
    c = int(min(c, n)) or 1
    rng = np.random.default_rng(seed)
    bounds = np.linspace(0, n, c + 1).astype(np.int64)
    pairs = []
    for b in range(c):
        lo, hi = int(bounds[b]), int(bounds[b + 1])
        size = hi - lo
        if size < 2:
            continue
        m = int(size * k_in / 2)
        u = rng.integers(lo, hi, size=m, dtype=np.int64)
        v = rng.integers(lo, hi, size=m, dtype=np.int64)
        perm = lo + rng.permutation(size).astype(np.int64)
        backbone = np.stack([perm, np.roll(perm, -1)], axis=1)
        pairs.append(np.stack([u, v], axis=1))
        pairs.append(backbone)
    m_x = int(n * k_out / 2)
    if c > 1 and m_x:
        u = rng.integers(0, n, size=m_x, dtype=np.int64)
        # a bridge must leave its community: draw the partner from the
        # complement by offsetting past the block and wrapping
        block = np.searchsorted(bounds, u, side="right") - 1
        lo, hi = bounds[block], bounds[block + 1]
        # v = (hi + off) mod n with off < n - block_size sweeps exactly
        # the complement [hi, n) ∪ [0, lo) of u's block — never a
        # self-loop, never intra-community
        off = rng.integers(0, np.maximum(n - (hi - lo), 1), dtype=np.int64)
        v = (hi + off) % n
        pairs.append(np.stack([u, v], axis=1))
    if c > 1:
        # guaranteed chain of bridges: consecutive blocks stay connected
        # whatever the random draw did
        chain_u = bounds[1:-1] - 1
        chain_v = bounds[1:-1]
        pairs.append(np.stack([chain_u, chain_v], axis=1))
    all_pairs = (np.concatenate(pairs) if pairs
                 else np.empty((0, 2), np.int64))
    topo = _finish(n, all_pairs, seed, values)
    # planted-partition ground truth rides the topology: block membership
    # per node and the directed edge ids crossing blocks — scenarios,
    # membership-aware heatmaps and partition blame consume these instead
    # of re-deriving the partition from the edge list
    membership = (np.searchsorted(bounds, np.arange(n), side="right") - 1
                  ).astype(np.int32)
    bridge = np.flatnonzero(
        membership[topo.src] != membership[topo.dst]).astype(np.int64)
    return dataclasses.replace(topo, membership=membership,
                               bridge_edges=bridge)


def fat_tree(k: int, seed: int = 0, values=None, hosts_only_values: bool = True,
             materialize_edges: bool = True) -> Topology:
    """Al-Fares k-ary fat-tree; all hosts *and* switches are graph vertices.

    Layout: hosts [0, k^3/4), edge switches, aggregation switches, core
    switches.  k must be even.  Vertex count = k^3/4 + 5k^2/4; edge count
    (undirected) = 3k^3/4.  k=160 gives ~1.056M vertices — the 1M-node
    benchmark config.

    ``materialize_edges=False`` builds a *virtual* topology: node arrays
    and the structure descriptor only, no edge list (3k^3/4 pairs is
    ~6 GB of host int64 at k=640).  Degrees are analytic (hosts 1, every
    switch k).  Only the node kernel's ``spmv='structured'`` path can run
    it; edge-consuming layouts raise (``Topology._require_edges``).  This
    is the 50M+-node single-chip configuration.
    """
    if k % 2:
        raise ValueError("fat-tree arity k must be even")
    if not materialize_edges:
        half = k // 2
        n_host = half * half * k
        n = n_host + half * k * 2 + half * half
        if values is None:
            rng = np.random.default_rng(seed + 1)
            values = rng.uniform(0.0, 1.0, n)
            if hosts_only_values:
                values[n_host:] = 0.0
        out_deg = np.full(n, k, np.int32)
        out_deg[:n_host] = 1
        empty_i32 = np.zeros((0,), np.int32)
        return Topology(
            num_nodes=n,
            src=empty_i32, dst=empty_i32, rev=empty_i32,
            out_deg=out_deg,
            row_start=np.zeros(n + 1, np.int64),
            edge_rank=empty_i32,
            delay=empty_i32,
            values=np.asarray(values, np.float64),
            structure=FatTreeStruct(k=k),
            virtual=True,
        )
    half = k // 2
    n_host = half * half * k          # k^3/4
    n_edge_sw = half * k
    n_agg_sw = half * k
    n_core = half * half
    host0 = 0
    edge0 = n_host
    agg0 = edge0 + n_edge_sw
    core0 = agg0 + n_agg_sw
    n = core0 + n_core

    pod = np.arange(k, dtype=np.int64)
    e_in_pod = np.arange(half, dtype=np.int64)
    h_in_edge = np.arange(half, dtype=np.int64)

    # host <-> edge switch
    P, E_, H = np.meshgrid(pod, e_in_pod, h_in_edge, indexing="ij")
    hosts = host0 + (P * half + E_) * half + H
    edges_sw = edge0 + P * half + E_
    he = np.stack([hosts.ravel(), edges_sw.ravel()], axis=1)

    # edge <-> aggregation (full bipartite within pod)
    P, E_, A = np.meshgrid(pod, e_in_pod, e_in_pod, indexing="ij")
    ea = np.stack(
        [(edge0 + P * half + E_).ravel(), (agg0 + P * half + A).ravel()], axis=1
    )

    # aggregation <-> core: agg switch a in a pod connects to cores
    # [a*half, (a+1)*half)
    P, A, C = np.meshgrid(pod, e_in_pod, np.arange(half, dtype=np.int64), indexing="ij")
    ac = np.stack(
        [(agg0 + P * half + A).ravel(), (core0 + A * half + C).ravel()], axis=1
    )

    pairs = np.concatenate([he, ea, ac], axis=0)
    if values is None:
        rng = np.random.default_rng(seed + 1)
        values = rng.uniform(0.0, 1.0, n)
        if hosts_only_values:
            # switches carry value 0 — only hosts hold data; the converged
            # mean is then sum(host values) / all vertices, still a fixed
            # point of the same protocol.
            values[n_host:] = 0.0
    topo = build_topology(
        n, pairs, values=values, seed=seed, warn_asymmetric=False
    )
    return dataclasses.replace(topo, structure=FatTreeStruct(k=k))


def topology_from_spec(spec: str, seed: int = 0) -> Topology:
    """Build a topology from the CLI's ``name:params`` grammar
    (``'barabasi_albert:100000:4'``, ``'ring:64:2'``) — the ONE parser
    behind ``run``/``sweep``/``plan``'s ``--generator`` flags and
    ``bench.py --generator``.  Integer-looking params parse as int,
    the rest as float; unknown names raise ValueError listing the
    registry."""
    parts = spec.split(":")
    name = parts[0]
    if name not in GENERATORS:
        raise ValueError(
            f"unknown generator {name!r}; have {sorted(GENERATORS)}")
    try:
        params = [int(p) if p.lstrip("-").isdigit() else float(p)
                  for p in parts[1:]]
    except ValueError:
        raise ValueError(f"bad generator parameters in {spec!r}") from None
    return GENERATORS[name](*params, seed=seed)


GENERATORS = {
    "ring": ring,
    "grid2d": grid2d,
    "torus2d": torus2d,
    "hypercube": hypercube,
    "complete": complete,
    "erdos_renyi": erdos_renyi,
    "barabasi_albert": barabasi_albert,
    "community": community,
    "fat_tree": fat_tree,
}
