"""SimGrid deployment-XML loader (``actors.xml`` dialect).

Replaces SimGrid's deployment parser + actor factory (SURVEY.md N7; the
reference binds its ``peer`` function to hosts at
``flowupdating-collectall.py:156-157`` and receives ``(value,
"n1,n2,...")`` string arguments, ``actors.xml`` format).  Here the
deployment is data, not actor spawning: it resolves to an initial-value
vector plus declared directed neighbor pairs, which :func:`to_topology`
symmetrizes into a :class:`~flow_updating_tpu.topology.graph.Topology`.
"""

from __future__ import annotations

import dataclasses
import xml.etree.ElementTree as ET

import numpy as np

from flow_updating_tpu.topology.graph import Topology, build_topology
from flow_updating_tpu.topology.platform import Platform


@dataclasses.dataclass(frozen=True)
class ActorSpec:
    host: str
    function: str
    args: tuple

    @property
    def value(self) -> float:
        return float(self.args[0]) if self.args else 0.0

    @property
    def neighbors(self) -> tuple:
        if len(self.args) < 2 or not self.args[1]:
            return ()
        return tuple(self.args[1].split(","))


@dataclasses.dataclass(frozen=True)
class Deployment:
    actors: tuple  # of ActorSpec, in file order

    @property
    def host_names(self) -> tuple:
        return tuple(a.host for a in self.actors)

    def to_topology(
        self,
        platform: Platform | None = None,
        tick_interval: float = 1.0,
        latency_scale: float = 0.0,
        msg_bytes: float = 104.0,
    ) -> Topology:
        """Deployment (+ optional platform for latencies/speeds) -> Topology.

        Node ids follow actor declaration order.  Neighbor lists may be
        asymmetric, exactly as the reference's ``actors.xml`` is; the builder
        symmetrizes and logs the adopted reverse edges.
        """
        names = list(self.host_names)
        ids = {n: i for i, n in enumerate(names)}
        values = np.array([a.value for a in self.actors], dtype=np.float64)
        pairs = []
        for a in self.actors:
            for nb in a.neighbors:
                if nb not in ids:
                    raise ValueError(
                        f"actor {a.host!r} declares neighbor {nb!r} which has "
                        "no actor deployed"
                    )
                pairs.append((ids[a.host], ids[nb]))
        latency = None
        bandwidth = None
        speeds = None
        link_caps = None
        link_shared = None
        route_links = None
        if platform is not None:
            latency = platform.latency_table(names)
            bandwidth = platform.bandwidth_table(names)
            speeds = np.array(
                [platform.hosts.get(n, 0.0) for n in names], dtype=np.float64
            )
            if latency_scale > 0.0:
                # the link model only feeds latency-warped / contention
                # runs; build_topology discards it otherwise
                link_caps, link_shared, route_links = \
                    platform.link_table(names)
        return build_topology(
            num_nodes=len(names),
            pairs=np.array(pairs, dtype=np.int64).reshape(-1, 2),
            values=values,
            names=names,
            latency_s=latency,
            bandwidth=bandwidth,
            speeds=speeds,
            tick_interval=tick_interval,
            latency_scale=latency_scale,
            msg_bytes=msg_bytes,
            route_links=route_links,
            link_caps=link_caps,
            link_shared=link_shared,
        )


def load_deployment(path: str, function: str | None = None) -> Deployment:
    """Parse an actors.xml.  If ``function`` is given, keep only actors bound
    to that function name (the analogue of ``register_actor("peer", Peer)``:
    unregistered functions simply have no implementation here)."""
    tree = ET.parse(path)
    root = tree.getroot()
    actors = []
    for child in root:
        if child.tag != "actor":
            continue
        args = tuple(
            a.attrib["value"] for a in child if a.tag == "argument"
        )
        spec = ActorSpec(
            host=child.attrib["host"],
            function=child.attrib["function"],
            args=args,
        )
        if function is None or spec.function == function:
            actors.append(spec)
    if not actors:
        raise ValueError(f"{path}: no matching <actor> entries")
    return Deployment(actors=tuple(actors))
