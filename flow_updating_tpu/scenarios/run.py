"""Scenario execution: sweep-engine grids + representative blame runs.

One scenario executes as TWO coordinated artifacts, both bound into its
manifest record:

* a **seed grid under the sweep engine** — every seed is a
  :class:`~flow_updating_tpu.sweep.pack.SweepInstance` carrying the
  scenario's adversary (device-side mask leaves vmapped per lane, one
  compiled bucket program per shape × adversary-structure group), with
  per-lane telemetry series kept for the signature's series clauses;
* a **representative field run** (first seed) through
  ``Engine(adversary=...)`` — full per-node/per-edge field rows, reduced
  to the ``inspect`` blame bundle that the signature's blame clauses are
  judged against (planted culprit at rank 1).

``perturb`` re-runs a scenario with its fault withdrawn
(``'remove_adversary'``) or its healing disabled (``'no_heal'``) — the
negative control of the conformance suite: a signature that still passes
on the perturbed run is vacuous, and tests/test_scenarios.py pins that
every registered signature FAILS under its perturbation.
"""

from __future__ import annotations

import dataclasses
import time

from flow_updating_tpu.scenarios.registry import (
    REGISTRY,
    Scenario,
    get_scenario,
)

__all__ = ["perturbed_adversary", "run_scenario", "run_scenarios",
           "scenario_manifest"]

#: Field selection of the representative blame run: everything the
#: blame symptoms consume (stall/liar need node rows, leak/cut/pinned
#: the edge ledgers).
BLAME_FIELDS = "node_err,node_mass,edge_flow,edge_est"


def perturbed_adversary(scn: Scenario, adversary, perturb: str | None):
    """The adversary actually planted for this run.  ``None`` keeps the
    registered fault; ``'remove_adversary'`` withdraws it entirely;
    ``'no_heal'`` pushes the down-window past the end of the run (the
    partition never heals)."""
    if perturb is None:
        return adversary
    if perturb == "remove_adversary":
        return None
    if perturb == "no_heal":
        if adversary is None or not adversary.down_edges:
            raise ValueError(
                f"scenario {scn.name!r} schedules no link-down window; "
                "'no_heal' only perturbs partition scenarios")
        return dataclasses.replace(adversary,
                                   down_until=int(scn.rounds) + 1)
    raise ValueError(
        f"unknown perturbation {perturb!r} (use 'remove_adversary' or "
        "'no_heal')")


def run_scenario(scn: Scenario, seeds=(0, 1), *, perturb: str | None = None,
                 max_batch: int | None = None) -> dict:
    """Execute one scenario; returns its manifest record.

    The record carries the registered declaration (name, config,
    signature), the planted ground truth, one sweep instance record per
    seed (params, convergence, per-round ``rmse``/``mass_residual``
    series), the sweep summary (bucket shapes = compile count), and the
    representative run's field block + blame bundle."""
    from flow_updating_tpu.obs.report import topology_summary
    from flow_updating_tpu.sweep import run_sweep
    from flow_updating_tpu.sweep.pack import SweepInstance

    seeds = [int(s) for s in seeds]
    if not seeds:
        raise ValueError("run_scenario needs at least one seed")
    cfg = scn.round_config()
    cases = {s: scn.build(s) for s in seeds}
    instances = []
    for s in seeds:
        case = cases[s]
        adv = perturbed_adversary(scn, case.adversary, perturb)
        instances.append(SweepInstance(
            topo=case.topo, seed=s, adversary=adv if adv else None,
            tag={"scenario": scn.name, "seed": s}))
    records, summary = run_sweep(
        instances, cfg, scn.rounds,
        rmse_threshold=scn.rmse_threshold,
        include_series=True, max_batch=max_batch)

    rep = cases[seeds[0]]
    rep_adv = perturbed_adversary(scn, rep.adversary, perturb)
    fields, blame = _representative_blame(scn, rep, rep_adv, cfg,
                                          seed=seeds[0])
    record = scn.describe()
    record.update({
        "ground_truth": dict(rep.ground_truth),
        "perturb": perturb,
        "topology": topology_summary(rep.topo),
        "representative_seed": seeds[0],
        "instances": records,
        "sweep_summary": summary,
        "blame": blame,
    })
    if fields is not None:
        record["fields"] = fields.to_jsonable()
    return record


def _representative_blame(scn: Scenario, case, adversary, cfg, *,
                          seed: int):
    """Field-record the first seed through the engine and reduce to the
    blame bundle (with the planted-partition metadata handed through, so
    partition blame never re-derives the blocks)."""
    from flow_updating_tpu.engine import Engine
    from flow_updating_tpu.obs import inspect as _inspect
    from flow_updating_tpu.obs.fields import FieldSpec

    engine = Engine(config=cfg, adversary=adversary)
    engine.set_topology(case.topo)
    engine.build(seed=seed)
    spec = FieldSpec.parse(BLAME_FIELDS)
    series = engine.run_fields(scn.rounds, spec)
    gt = case.ground_truth
    bundle = _inspect.blame(
        series, threshold=scn.rmse_threshold,
        membership=gt.get("membership"),
        bridge_edges=gt.get("bridge_edges"))
    return series, bundle


def run_scenarios(names=None, seeds=(0, 1), *,
                  perturb: str | None = None,
                  max_batch: int | None = None):
    """Run a set of registered scenarios (default: all, in registration
    order).  Returns ``(records, summary)`` ready for
    :func:`scenario_manifest`."""
    names = list(names) if names else list(REGISTRY)
    scns = [get_scenario(n) for n in names]
    t0 = time.perf_counter()
    records = []
    compiled = 0
    for scn in scns:
        rec = run_scenario(scn, seeds, perturb=perturb,
                           max_batch=max_batch)
        compiled += int(rec["sweep_summary"]["compiled_programs"])
        records.append(rec)
    summary = {
        "scenarios": names,
        "seeds": [int(s) for s in seeds],
        "perturb": perturb,
        "sweep_compiles": compiled,
        "wall_s": round(time.perf_counter() - t0, 6),
    }
    return records, summary


def scenario_manifest(records, summary, *, argv=None) -> dict:
    """The ``flow-updating-scenario-report/v1`` manifest for a
    :func:`run_scenarios` result."""
    from flow_updating_tpu.obs.report import build_scenario_manifest

    return build_scenario_manifest(argv=argv, scenarios=records,
                                   summary=summary)
