"""Device-side fault/adversary specs for the scenario registry.

An :class:`Adversary` is a *host-side plan* of which nodes lie, which
edges corrupt their wire flow, which senders drop silently and which
link set suffers a scheduled correlated failure.  It lowers to the
``adv_*`` leaves of :class:`~flow_updating_tpu.topology.graph.TopoArrays`
(:meth:`Adversary.device_leaves`), where the round kernel injects the
faults **on the wire** — the honest per-edge ledgers are never touched,
so the observability stack sees exactly what a real deployment would:
honest state, corrupted messages (models/rounds.py ``fire_core`` /
``send_messages``).

Absence is pytree STRUCTURE: every leaf defaults to ``None`` and an
adversary-free topology compiles the bit-identical plain program.  Under
the sweep engine the leaves vmap per lane, so one compiled bucket serves
a whole scenario x seed grid — but only lanes with the same
:meth:`structure_key` may share a bucket (a ``None`` mask would split
the vmapped treedef), which is why the packing layer folds the key into
its bucket grouping (sweep/pack.py).
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["Adversary"]


def _ids(x) -> tuple:
    return tuple(int(i) for i in np.atleast_1d(np.asarray(x, np.int64)))


@dataclasses.dataclass(frozen=True)
class Adversary:
    """One scenario's planted faults, by original node/edge id.

    * ``lie_nodes`` / ``lie_value`` — value lies: every message a lying
      node sends reports ``lie_value`` as its estimate.
    * ``corrupt_edges`` / ``corrupt_gain`` — flow corruption: the WIRE
      copy of the flow ledger is scaled by ``corrupt_gain`` on these
      directed edges (the receiver's antisymmetry write then no longer
      cancels the sender's honest ledger).
    * ``silent_nodes`` — silent drops: every send from these nodes is
      lost on the wire while the sender's ledger updates regardless.
    * ``down_edges`` / ``down_from`` / ``down_until`` — scheduled
      correlated link failure: the edges lose every send during rounds
      ``[down_from, down_until)`` (partition a subtree, then heal).
    """

    lie_nodes: tuple = ()
    lie_value: float = 0.0
    corrupt_edges: tuple = ()
    corrupt_gain: float = 1.0
    silent_nodes: tuple = ()
    down_edges: tuple = ()
    down_from: int = 0
    down_until: int = 0

    def __post_init__(self):
        object.__setattr__(self, "lie_nodes", _ids(self.lie_nodes))
        object.__setattr__(self, "corrupt_edges", _ids(self.corrupt_edges))
        object.__setattr__(self, "silent_nodes", _ids(self.silent_nodes))
        object.__setattr__(self, "down_edges", _ids(self.down_edges))
        if self.down_edges and not self.down_until > self.down_from >= 0:
            raise ValueError(
                f"down window [{self.down_from}, {self.down_until}) is "
                "empty; schedule at least one dead round (or drop the "
                "down_edges)")

    def __bool__(self) -> bool:
        return bool(self.lie_nodes or self.corrupt_edges
                    or self.silent_nodes or self.down_edges)

    def structure_key(self) -> tuple:
        """Which leaf families are statically present — the part of the
        compiled program's identity this adversary contributes.  Lanes
        may share a vmapped sweep bucket iff their keys agree."""
        return (bool(self.lie_nodes), bool(self.corrupt_edges),
                bool(self.silent_nodes), bool(self.down_edges))

    def device_leaves(self, n_pad: int, e_pad: int, dtype) -> dict:
        """The ``TopoArrays.replace`` kwargs: masks padded to the bucket
        shape (ghost slots never lie/corrupt/drop), values as ()-shaped
        device scalars.  Only present families emit leaves — absence
        stays ``None`` (pytree structure)."""
        import jax.numpy as jnp

        def mask(ids, size):
            m = np.zeros(size, bool)
            ids = np.asarray(ids, np.int64)
            if ids.size and (ids.min() < 0 or ids.max() >= size):
                raise ValueError(
                    f"adversary id(s) {ids[(ids < 0) | (ids >= size)]} "
                    f"outside [0, {size})")
            m[ids] = True
            return jnp.asarray(m)

        out: dict = {}
        if self.lie_nodes:
            out["adv_lie_mask"] = mask(self.lie_nodes, n_pad)
            out["adv_lie_value"] = jnp.asarray(self.lie_value, dtype)
        if self.corrupt_edges:
            out["adv_corrupt_mask"] = mask(self.corrupt_edges, e_pad)
            out["adv_corrupt_gain"] = jnp.asarray(self.corrupt_gain, dtype)
        if self.silent_nodes:
            out["adv_silent_mask"] = mask(self.silent_nodes, n_pad)
        if self.down_edges:
            out["adv_down_mask"] = mask(self.down_edges, e_pad)
            out["adv_down_from"] = jnp.asarray(self.down_from, jnp.int32)
            out["adv_down_until"] = jnp.asarray(self.down_until, jnp.int32)
        return out

    def describe(self) -> dict:
        """Manifest-grade ground truth: the planted culprits a
        conformance check verifies blame against."""
        out: dict = {}
        if self.lie_nodes:
            out["lie"] = {"nodes": list(self.lie_nodes),
                          "value": float(self.lie_value)}
        if self.corrupt_edges:
            out["corrupt"] = {"edges": list(self.corrupt_edges),
                              "gain": float(self.corrupt_gain)}
        if self.silent_nodes:
            out["silent"] = {"nodes": list(self.silent_nodes)}
        if self.down_edges:
            out["down"] = {"edges": list(self.down_edges),
                           "from": int(self.down_from),
                           "until": int(self.down_until)}
        return out
