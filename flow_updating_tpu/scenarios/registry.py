"""The curated scenario registry: adversarial and poorly-connected cases.

Every benchmark graph elsewhere in the repo is well-connected and every
node honest; this registry holds the HARD cases the roadmap names — the
conductance-bottleneck bridge whose spreading time is governed by the
cut, not the node count (arXiv:1104.2944), Byzantine nodes lying on the
wire, corrupted flow ledgers, silent droppers, and correlated link
failure (partition a community, then heal).  Each :class:`Scenario`
bundles three things:

* a deterministic **construction** — topology (with planted-partition
  ground truth riding the metadata), per-seed node values (block-offset
  draws keep the bridge load-bearing: with i.i.d. values the blocks are
  pre-balanced and the cut is invisible), and an
  :class:`~flow_updating_tpu.scenarios.adversary.Adversary` plan;
* a **config** — including the robust-aggregation modes
  (``RoundConfig.robust``: trimmed-mean / clipped-flow variants of the
  collect-all fire step; statically off they leave the round program
  bit-identical);
* a declared **expected observable signature** — conformance clauses
  (:data:`Scenario.signature`) the doctor judges a scenario manifest
  against (obs/health.check_scenario_conformance): convergence bounds,
  bias/mass bounds under attack, heal deadlines, cross-scenario
  convergence factors, and blame clauses asserting the planted
  adversary is localized at rank 1 (obs/inspect.blame_adversary).

Thresholds are calibrated against measured behavior of the reference
construction (documented per scenario); the conformance tests pin both
directions — each signature passes on its own run and FAILS on a
perturbed run (adversary removed, healing disabled).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.scenarios.adversary import Adversary

#: Structural constants of the registry's community graph: 3 contiguous
#: 32-node blocks, dense inside (k_in = 8), connected ONLY by the two
#: guaranteed chain bridges (k_out = 0) — the conductance bottleneck.
COMMUNITY_N = 96
COMMUNITY_C = 3
_COMMUNITY_KW = dict(c=COMMUNITY_C, k_in=8.0, k_out=0.0, seed=0)


def block_values(membership: np.ndarray, seed: int) -> np.ndarray:
    """Per-seed node inputs with a +1.0 offset per community block.

    I.i.d. values leave every block's mean near the global mean, so
    nothing needs to cross the bridges and the bottleneck is invisible;
    the block offset plants ~``N/c`` units of mass imbalance per block,
    making the cut load-bearing (the registry's whole point) while the
    within-block draw still varies per seed."""
    rng = np.random.default_rng(seed)
    return (np.asarray(membership, np.float64)
            + rng.uniform(0.0, 1.0, membership.shape[0]))


def _community(seed: int):
    from flow_updating_tpu.topology.generators import community

    topo = community(COMMUNITY_N, **_COMMUNITY_KW)
    return topo.with_values(block_values(topo.membership, seed))


def _community_uniform(seed: int):
    """The same community graph with i.i.d. uniform per-seed values —
    the Byzantine scenarios' base: honest equilibrium flow ledgers stay
    small (no planted bulk transfer), so clipped/trimmed robustness
    thresholds sit cleanly between honest dynamics and the attack."""
    from flow_updating_tpu.topology.generators import community

    topo = community(COMMUNITY_N, **_COMMUNITY_KW)
    rng = np.random.default_rng(1000 + seed)
    return topo.with_values(rng.uniform(0.0, 1.0, COMMUNITY_N))


def _community_meta(topo) -> dict:
    return {
        "membership": [int(b) for b in topo.membership],
        "bridge_edges": [int(e) for e in topo.bridge_edges],
    }


def _expander(seed: int):
    """The same community graph augmented with two random perfect
    matchings over all nodes — the expander-augmented control: identical
    blocks and values, but the cut is no longer a bottleneck."""
    import dataclasses as _dc

    from flow_updating_tpu.topology.graph import build_topology

    base = _community(seed)
    pairs = np.stack([base.src, base.dst], axis=1)
    pairs = pairs[pairs[:, 0] < pairs[:, 1]]
    rng = np.random.default_rng(7)           # structural, not per-seed
    extra = [rng.permutation(COMMUNITY_N).reshape(-1, 2) for _ in range(2)]
    topo = build_topology(COMMUNITY_N, np.concatenate([pairs] + extra),
                          values=base.values, seed=0,
                          warn_asymmetric=False)
    # membership still holds (augmentation adds edges, renames nothing)
    memb = base.membership
    bridge = np.flatnonzero(
        memb[topo.src] != memb[topo.dst]).astype(np.int64)
    return _dc.replace(topo, membership=memb, bridge_edges=bridge)


#: The planted Byzantine node / silent node of the registry's community
#: graph (block 0 interior) and the reported lie.
LIE_NODE = 5
SILENT_NODE = 7
LIE_VALUE = 100.0


def _corrupt_edge(topo) -> int:
    """First out-edge of node 3 — the planted wire-corruption site."""
    return int(np.flatnonzero(np.asarray(topo.src) == 3)[0])


def _block_bridges(topo, block: int) -> tuple:
    """All directed bridge edges touching ``block`` — cutting them
    isolates the block (k_out = 0 leaves no other path)."""
    memb = topo.membership
    src = np.asarray(topo.src)
    dst = np.asarray(topo.dst)
    return tuple(int(e) for e in topo.bridge_edges
                 if memb[src[e]] == block or memb[dst[e]] == block)


@dataclasses.dataclass(frozen=True)
class ScenarioCase:
    """One built instance of a scenario: the deterministic topology (node
    values already seeded in), the adversary plan, and the ground truth a
    conformance check verifies blame against."""

    topo: object
    adversary: Adversary | None
    ground_truth: dict


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A registered scenario: construction + config + expected signature.

    ``config`` holds :class:`~flow_updating_tpu.models.config.RoundConfig`
    keyword overrides applied on top of ``RoundConfig.fast()`` (the
    robust-aggregation modes live here); ``signature`` is the tuple of
    declarative conformance clauses (see
    :func:`flow_updating_tpu.obs.health.check_scenario_conformance` for
    the vocabulary).  ``builder(seed)`` must be deterministic in
    ``seed``."""

    name: str
    summary: str
    builder: object
    signature: tuple
    rounds: int
    rmse_threshold: float = 1e-3
    config: dict = dataclasses.field(default_factory=dict)

    def build(self, seed: int = 0) -> ScenarioCase:
        case = self.builder(seed)
        if not isinstance(case, ScenarioCase):
            raise TypeError(
                f"scenario {self.name!r}: builder returned "
                f"{type(case).__name__}, expected ScenarioCase")
        return case

    def round_config(self):
        from flow_updating_tpu.models.config import RoundConfig

        return RoundConfig.fast(**self.config)

    def describe(self) -> dict:
        """Manifest-grade record (everything but the built arrays)."""
        return {
            "name": self.name,
            "summary": self.summary,
            "rounds": int(self.rounds),
            "rmse_threshold": float(self.rmse_threshold),
            "config": dict(self.config),
            "signature": [dict(c) for c in self.signature],
        }


REGISTRY: dict = {}


def register(scn: Scenario) -> Scenario:
    if scn.name in REGISTRY:
        raise ValueError(f"scenario {scn.name!r} already registered")
    REGISTRY[scn.name] = scn
    return scn


def get_scenario(name: str) -> Scenario:
    if name not in REGISTRY:
        import difflib

        near = difflib.get_close_matches(name, REGISTRY, n=1)
        hint = f" (did you mean {near[0]!r}?)" if near else ""
        raise ValueError(
            f"unknown scenario {name!r}{hint}; registered: "
            f"{', '.join(sorted(REGISTRY))}")
    return REGISTRY[name]


def scenario_names() -> tuple:
    return tuple(REGISTRY)


# --------------------------------------------------------------------------
# the registry
# --------------------------------------------------------------------------

def _honest(seed):
    topo = _community(seed)
    return ScenarioCase(topo, None, _community_meta(topo))


def _honest_expander(seed):
    topo = _expander(seed)
    return ScenarioCase(topo, None, _community_meta(topo))


register(Scenario(
    name="bridge_bottleneck",
    summary="conductance-bottleneck community graph: 3 blocks joined "
            "only by 2 bridge edges; block-offset values force ~32 mass "
            "units across each cut",
    builder=_honest,
    rounds=800,
    # measured: converges at round ~252 at 1e-3 (seeds 0-2), vs ~48 for
    # the expander-augmented control — the cut, not N, sets the time
    signature=(
        {"check": "converges", "within": 500},
        {"check": "relative_rounds", "of": "expander_relief",
         "min_factor": 2.0, "max_factor": 10.0},
    ),
))

register(Scenario(
    name="expander_relief",
    summary="the same blocks + values with 2 random matchings added: "
            "the expander-augmented control the bridge case is judged "
            "against",
    builder=_honest_expander,
    rounds=200,
    signature=(
        {"check": "converges", "within": 100},   # measured: ~48
    ),
))


def _lie(seed):
    topo = _community_uniform(seed)
    adv = Adversary(lie_nodes=(LIE_NODE,), lie_value=LIE_VALUE)
    gt = {**_community_meta(topo), **adv.describe()}
    return ScenarioCase(topo, adv, gt)


register(Scenario(
    name="byzantine_lie",
    summary=f"node {LIE_NODE} reports {LIE_VALUE:g} in every message "
            "(state stays honest); no protection — the attack must "
            "visibly poison the average",
    builder=_lie,
    rounds=300,
    signature=(
        # measured: the poisoned consensus sits ~10 rmse off the mean
        {"check": "final_rmse_above", "value": 1.0},
        {"check": "blame", "symptom": "liar", "nodes": [LIE_NODE]},
    ),
))

register(Scenario(
    name="byzantine_lie_clip",
    summary="the same liar under robust='clip' (flow ledgers clamped to "
            "±0.5): displacement through any edge is bounded, so the "
            "bias is bounded by the clamp × degree, not the lie",
    builder=_lie,
    rounds=300,
    config={"robust": "clip", "robust_clip": 0.5},
    signature=(
        # measured: rmse ~0.9, |mass residual| ~= 2 x deg(liar) x clip
        # (deg 5 -> ~5.0); the unprotected run sits at rmse ~10 / 467
        {"check": "final_rmse_below", "value": 2.0},
        {"check": "mass_bounded", "value": 7.5},
        # the clamp bounds the poison but the anomaly still concentrates
        # on the liar's neighborhood — rank 1 through the clip, and an
        # adversary-free clipped run ranks someone else (negative
        # control discrimination)
        {"check": "blame", "symptom": "liar", "nodes": [LIE_NODE]},
    ),
))

register(Scenario(
    name="byzantine_lie_trim",
    summary="the same liar under robust='trim' (each armed node freezes "
            "its single highest/lowest neighbor out of the exchange): "
            "one extreme liar per neighborhood is excluded outright and "
            "the honest fixed point survives",
    builder=_lie,
    rounds=500,
    # robust_tol sits ABOVE the honest dynamic range (values in [0, 1],
    # lie at 100): honest neighborhoods never arm, the liar's always do
    config={"robust": "trim", "robust_tol": 2.0},
    signature=(
        {"check": "converges", "within": 450},   # measured: 109-168
        {"check": "mass_bounded", "value": 0.5},
        # the frozen-out lie stays pinned in the liar's in-view entries
        # while consensus tightens — the rank-1 tell
        {"check": "blame", "symptom": "pinned", "nodes": [LIE_NODE]},
    ),
))


def _corrupt(seed):
    topo = _community_uniform(seed)
    e = _corrupt_edge(topo)
    adv = Adversary(corrupt_edges=(e,), corrupt_gain=1.5)
    gt = {**_community_meta(topo), **adv.describe()}
    return ScenarioCase(topo, adv, gt)


register(Scenario(
    name="flow_corruption",
    summary="one edge's wire flow is scaled ×1.5 (the receiver's "
            "antisymmetry write no longer cancels the sender): an "
            "unprotected pair is a runaway amplifier",
    builder=_corrupt,
    rounds=120,    # gain^t grows without bound; 120 rounds stays finite
    signature=(
        {"check": "final_rmse_above", "value": 10.0},
        {"check": "blame", "symptom": "leak", "edge_of": "corrupt"},
    ),
))

register(Scenario(
    name="flow_corruption_clip",
    summary="the same corrupted wire under robust='clip': both ledger "
            "writes honor the clamp, the amplifier is cut and the run "
            "converges as if honest",
    builder=_corrupt,
    rounds=300,
    # robust_clip sits ABOVE the honest equilibrium |flow| (measured
    # <= 3.8 across seeds): honest convergence is never clipped, while
    # the x1.5 amplifier (unbounded growth) is cut at the clamp
    config={"robust": "clip", "robust_clip": 8.0},
    signature=(
        {"check": "converges", "within": 280},   # measured: 70-171
        {"check": "mass_bounded", "value": 0.5},
        # mid-run the wire gain mis-writes the receiver ledger by
        # 0.5 x f: the pair residual (2.5, vs 0.36 for the runner-up)
        # names the corrupted pair even though the clamp saves the run
        {"check": "blame", "symptom": "cut", "edge_of": "corrupt"},
    ),
))


def _silent(seed):
    topo = _community_uniform(seed)
    adv = Adversary(silent_nodes=(SILENT_NODE,))
    gt = {**_community_meta(topo), **adv.describe()}
    return ScenarioCase(topo, adv, gt)


register(Scenario(
    name="silent_node",
    summary=f"node {SILENT_NODE}'s sends vanish on the wire (its ledger "
            "updates regardless — a lost put): a liveness fault with "
            "bounded damage, localized as the worst straggler",
    builder=_silent,
    rounds=300,
    signature=(
        {"check": "final_rmse_above", "value": 0.005},
        {"check": "final_rmse_below", "value": 1.0},
        {"check": "blame", "symptom": "straggler",
         "nodes": [SILENT_NODE]},
    ),
))

#: Partition window of the ``partition_heal`` scenario (rounds).
PARTITION_FROM = 100
PARTITION_UNTIL = 200
PARTITION_BLOCK = 0


def _partition(seed):
    topo = _community(seed)
    cut = _block_bridges(topo, PARTITION_BLOCK)
    adv = Adversary(down_edges=cut, down_from=PARTITION_FROM,
                    down_until=PARTITION_UNTIL)
    gt = {**_community_meta(topo), **adv.describe(),
          "partition_block": PARTITION_BLOCK}
    return ScenarioCase(topo, adv, gt)


register(Scenario(
    name="partition_heal",
    summary=f"every bridge of block {PARTITION_BLOCK} goes down for "
            f"rounds [{PARTITION_FROM}, {PARTITION_UNTIL}): the block "
            "is fully partitioned, then the links heal — "
            "self-healing must restore conservation and convergence",
    builder=_partition,
    rounds=800,
    signature=(
        # measured: rmse plateaus ~0.05 during the cut, the first
        # post-heal exchanges restore the pair ledgers (residual 2.4e-3
        # within 50 rounds, 3e-5 by the end), convergence resumes
        {"check": "rmse_at_least", "round": PARTITION_UNTIL - 1,
         "value": 0.01},
        {"check": "mass_bounded", "value": 5e-3,
         "from_round": PARTITION_UNTIL + 150},
        {"check": "converges", "within": 600},
        {"check": "blame", "symptom": "cut", "edge_of": "down",
         "block": PARTITION_BLOCK},
    ),
))


def _asym(seed):
    import dataclasses as _dc

    topo = _community(seed)
    delay = np.asarray(topo.delay).copy()
    src, dst = np.asarray(topo.src), np.asarray(topo.dst)
    for e in topo.bridge_edges:
        if src[e] < dst[e]:
            delay[e] = 4               # forward slow, reverse fast
    t = _dc.replace(topo, delay=delay)
    gt = {**_community_meta(topo),
          "asym_edges": [int(e) for e in topo.bridge_edges
                         if src[e] < dst[e]]}
    return ScenarioCase(t, None, gt)


register(Scenario(
    name="asym_latency",
    summary="weighted/asymmetric links: each bridge takes 4 rounds one "
            "way, 1 the other — Flow-Updating must stay mass-conserving "
            "and converge through asymmetric delivery",
    builder=_asym,
    rounds=800,
    config={"delay_depth": 4},
    signature=(
        {"check": "converges", "within": 780},
        {"check": "mass_bounded", "value": 0.05},
    ),
))
