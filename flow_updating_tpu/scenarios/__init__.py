"""Scenario conformance suite: adversarial and poorly-connected cases.

The observability stack watches healthy runs everywhere else in the
repo; this package turns it into an active conformance suite.  A
:class:`~flow_updating_tpu.scenarios.registry.Scenario` bundles a
deterministic hostile construction (conductance-bottleneck bridges,
Byzantine nodes injected device-side on the message wire, correlated
link failure), a config (including the robust-aggregation fire modes),
and a declared expected observable signature that ``doctor`` asserts
against the scenario's manifest — with ``inspect --blame`` required to
localize the planted adversary at rank 1.

Entry points: the ``scenarios`` CLI subcommand,
:func:`~flow_updating_tpu.scenarios.run.run_scenarios`, and
``bench.py --scenario`` (isolated ``scn_<name>`` baseline keys).
"""

from flow_updating_tpu.scenarios.adversary import Adversary
from flow_updating_tpu.scenarios.registry import (
    REGISTRY,
    Scenario,
    ScenarioCase,
    get_scenario,
    scenario_names,
)
from flow_updating_tpu.scenarios.run import (
    run_scenario,
    run_scenarios,
    scenario_manifest,
)

__all__ = [
    "Adversary", "REGISTRY", "Scenario", "ScenarioCase", "get_scenario",
    "run_scenario", "run_scenarios", "scenario_manifest",
    "scenario_names",
]
