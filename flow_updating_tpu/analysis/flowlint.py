"""Repo-specific AST lint — the rules ruff cannot express.

Five rules, each encoding a contract this codebase depends on but no
generic linter knows about:

``numpy-in-kernel`` (FL001)
    No ``np.*`` / ``numpy.*`` *calls* inside traced functions (functions
    that are jit-decorated, passed to ``lax.scan``/``while_loop``/
    ``cond``/``shard_map``, or nested inside one).  A numpy call on a
    traced value either crashes at trace time or — worse — silently
    constant-folds a value that should be data.  Attribute reads
    (``np.float32`` dtypes) stay legal.

``traced-if`` (FL002)
    No Python ``if`` on a scan/while/cond body function's parameters:
    those are traced values; branching on them is a
    ``TracerBoolConversionError`` at best and a silently specialized
    program at worst.  Use ``jnp.where`` / ``lax.cond``.

``kernel-round-program`` (FL003)
    Every ``*Kernel`` class must expose ``round_program`` — the AOT
    cost-attribution + golden-ledger hook (obs/profile.py,
    analysis/golden.py).  A kernel without it is invisible to the
    profiler and the conformance ledger.

``bare-prngkey`` (FL004)
    ``jax.random.PRNGKey`` only inside the documented seeding entry
    points (``init_state`` / ``init_plan_state``).  Anywhere else it
    manufactures a fresh root key mid-protocol — the classic correlated
    -randomness bug (two "independent" streams from seed 0).

``baseline-key-family`` (FL005)
    Keys handed to ``record_baseline``/``recorded_baseline`` in bench.py
    must come from the documented key families (k-configs, ``dfl_d*``,
    ``scn_*``, ``qps_*``, ``*_planned``, ``*_scale_s*``,
    ``*_sweep_b*``, ``*_service``).  An undocumented ad-hoc key
    silently shadows or forks the measurement history the regress gate
    judges against.

Suppression: append ``# flowlint: ok(<rule>) <reason>`` to the flagged
line (or the line above).  The reason is mandatory — a bare suppression
is itself an error.

Run via ``python -m flow_updating_tpu lint`` (which also runs the jaxpr
rule engine, :mod:`flow_updating_tpu.analysis.rules`) or call
:func:`lint_paths` directly.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

# jit / scan markers: attribute or name heads that make a callee traced
_TRACE_CALLS = {"scan", "while_loop", "fori_loop", "cond", "switch",
                "shard_map", "vmap", "pmap", "checkpoint", "remat",
                "custom_vmap"}
_JIT_NAMES = {"jit"}
_SEEDING_FUNCS = {"init_state", "init_plan_state"}

# documented baseline key families (bench.py `_baseline_key` prepends
# "k" to bare numerics, so the numeric family is a plain integer probe).
# Probes substitute "0" for every dynamic fragment of an f-string.
_KEY_FAMILIES = (
    r"\d+(_[a-z0-9]+)*",            # str(k) numeric configs + suffixes
    r"k\d+(_[a-z0-9]+)*",           # explicit k-configs
    r".+_planned",                  # topology-compiler rows
    r".+_fused",                    # one-kernel fused-round rows
    r".+_scale_s.+",                # weak-scaling ladder rows
    r".+_sweep_b.+",                # sweep-engine rows
    r".+_service",                  # streaming-service rows
    r"dfl_d.+",                     # model-scale DFL rows
    r"scn_.+",                      # scenario rows
    r"qps_.+",                      # query-fabric queries/s rows
    r"agg_.+",                      # aggregate-algebra per-kind rows
    r"chaos_.+",                    # chaos-harness fault rows
    r"recovery_.+",                 # crash-recovery timing rows
    r"slo_.+",                      # serving-SLO latency rows
    r"forecast_.+",                 # forecast-calibration rows
    r"roofline_.+",                 # perf-lens measured/ceiling fracs
    r"(er|ba)\d+k?_[a-z_0-9]+",     # named generator configs
)
_KEY_FAMILY_RES = tuple(re.compile(p) for p in _KEY_FAMILIES)

_SUPPRESS_RE = re.compile(
    r"#\s*flowlint:\s*ok\((?P<rule>[\w-]+)\)\s*(?P<reason>\S.*)?")

RULE_DOCS = {
    "numpy-in-kernel": "no numpy calls inside traced (jit/scan) functions",
    "traced-if": "no Python `if` on scan/cond body parameters (traced)",
    "kernel-round-program": "every *Kernel class exposes round_program",
    "bare-prngkey": "jax.random.PRNGKey only in seeding entry points",
    "baseline-key-family": "bench baseline keys from documented families",
    "device-from-mirror": "no zero-copy device arrays over in-place-"
                          "mutated host mirrors (analysis/aliasing.py)",
}


@dataclasses.dataclass(frozen=True)
class LintFinding:
    rule: str
    file: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


def _attr_tail(node) -> str:
    """Last attribute/name segment of a callee expression."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_jit_decorator(dec) -> bool:
    """``@jax.jit``, ``@jit``, ``@functools.partial(jax.jit, ...)`` and
    the ``@partial(jax.jit, static_argnames=...)`` spelling."""
    if _attr_tail(dec) in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        if _attr_tail(dec.func) in _JIT_NAMES:
            return True
        if _attr_tail(dec.func) == "partial" and dec.args \
                and _attr_tail(dec.args[0]) in _JIT_NAMES:
            return True
    return False


class _Module:
    """One parsed file plus the traced-function analysis shared by the
    per-rule passes."""

    def __init__(self, path: str, src: str):
        self.path = path
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        # parent links (ast has none)
        self.parent: dict = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.jit_fns: set = set()      # FunctionDef/Lambda, jit-decorated
        self.scan_body_fns: set = set()  # passed to scan/cond/... by name
        self._classify()

    def _classify(self) -> None:
        # name -> [FunctionDef] per enclosing scope, for resolving
        # `lax.scan(step, ...)` references
        defs_by_name: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
                if any(_is_jit_decorator(d) for d in node.decorator_list):
                    self.jit_fns.add(node)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _attr_tail(node.func)
            is_trace = callee in _TRACE_CALLS
            is_jit_call = callee in _JIT_NAMES  # jax.jit(fn, ...)
            if not (is_trace or is_jit_call):
                continue
            # the body-function positions of the control-flow callees
            if callee == "scan":
                cands = node.args[:1]
            elif callee == "while_loop":
                cands = node.args[:2]          # (cond_fun, body_fun, init)
            elif callee == "fori_loop":
                cands = node.args[2:3]         # (lo, hi, body_fun, init)
            else:
                cands = node.args
            for arg in cands:
                target = self.jit_fns if is_jit_call else self.scan_body_fns
                if isinstance(arg, ast.Lambda):
                    target.add(arg)
                elif isinstance(arg, ast.Name):
                    for fn in defs_by_name.get(arg.id, ()):
                        target.add(fn)
                elif isinstance(arg, ast.Call) and \
                        _attr_tail(arg.func) == "partial":
                    for sub in arg.args[:1]:
                        if isinstance(sub, ast.Name):
                            for fn in defs_by_name.get(sub.id, ()):
                                target.add(fn)

    def traced_functions(self) -> set:
        """Traced = jit-decorated, scan-body, or nested inside one."""
        roots = self.jit_fns | self.scan_body_fns
        out = set()
        for root in roots:
            for node in ast.walk(root):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    out.add(node)
        return out

    def suppressed(self, line: int, rule: str) -> bool | str:
        """Suppression state for a finding at ``line``: True (valid
        suppression), False (none), or "bare" (suppression without a
        reason — itself a violation)."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _SUPPRESS_RE.search(self.lines[ln - 1])
                if m and m.group("rule") == rule:
                    return True if m.group("reason") else "bare"
        return False


def _params_of(fn) -> set:
    args = fn.args
    names = [a.arg for a in args.args + args.posonlyargs + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


def _enclosing_function(mod: _Module, node):
    """Nearest enclosing NAMED function (lambdas are skipped: a seeding
    entry point's helper lambda still seeds on its behalf)."""
    cur = mod.parent.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        cur = mod.parent.get(cur)
    return cur


# ---------------------------------------------------------------------------
# the rule passes

def _attr_root(node):
    """Root Name of a dotted attribute chain (``np.linalg.norm`` ->
    the ``np`` Name node), or None."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node if isinstance(node, ast.Name) else None


def _r_numpy_in_kernel(mod: _Module):
    traced = mod.traced_functions()
    for fn in traced:
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                # the root of the dotted chain: catches np.asarray AND
                # submodule calls (np.random.rand, np.linalg.norm)
                root = _attr_root(node.func.value)
                if root is not None and root.id in ("np", "numpy",
                                                    "onp"):
                    dotted = ast.unparse(node.func)
                    yield LintFinding(
                        "numpy-in-kernel", mod.path, node.lineno,
                        node.col_offset,
                        f"numpy call `{dotted}(...)` inside a traced "
                        "function — use jnp, or hoist to trace-time "
                        "setup")


def _r_traced_if(mod: _Module):
    for fn in mod.scan_body_fns:
        if isinstance(fn, ast.Lambda):
            continue                      # a lambda has no If statements
        params = _params_of(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            hit = sorted({n.id for n in ast.walk(node.test)
                          if isinstance(n, ast.Name) and n.id in params})
            if hit:
                yield LintFinding(
                    "traced-if", mod.path, node.lineno, node.col_offset,
                    f"Python `if` on traced parameter(s) {hit} of scan/"
                    f"cond body `{fn.name}` — use jnp.where or lax.cond")


def _r_kernel_round_program(mod: _Module):
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef) or \
                not node.name.endswith("Kernel"):
            continue
        if node.bases:
            continue          # inherited hooks resolve dynamically
        methods = {n.name for n in node.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        if "round_program" not in methods:
            yield LintFinding(
                "kernel-round-program", mod.path, node.lineno,
                node.col_offset,
                f"kernel class `{node.name}` does not expose "
                "round_program — the profiler and the golden-program "
                "ledger cannot see it")


def _r_bare_prngkey(mod: _Module):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                _attr_tail(node.func) == "PRNGKey":
            fn = _enclosing_function(mod, node)
            name = getattr(fn, "name", "")
            if name in _SEEDING_FUNCS:
                continue
            yield LintFinding(
                "bare-prngkey", mod.path, node.lineno, node.col_offset,
                f"bare jax.random.PRNGKey outside the seeding entry "
                f"points {sorted(_SEEDING_FUNCS)} (enclosing: "
                f"`{name or '<module>'}`) — derive keys by split/"
                "fold_in from the run seed")


def _probe_strings(node, assigns: dict) -> list:
    """Render a key expression to probe strings: literal text kept,
    every dynamic fragment replaced by ``\"0\"``.  Names resolve through
    simple/augmented assignments; unresolvable expressions probe as
    bare ``\"0\"`` (dynamic keys pass — the rule judges literals)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("0")
        return ["".join(parts)]
    if isinstance(node, ast.Name) and node.id in assigns:
        base, suffixes = assigns[node.id]
        out = []
        for b in base:
            probe = b
            for s in suffixes:
                probe += s
            out.append(probe)
        return out
    return ["0"]


def _r_baseline_key_family(mod: _Module):
    if os.path.basename(mod.path) != "bench.py":
        return
    # name -> ([base probes], [suffix probes]) from `k = <expr>` and
    # `k += <expr>` at any nesting depth
    assigns: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            probes = _probe_strings(node.value, {})
            assigns.setdefault(name, ([], []))[0].extend(probes)
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name) and \
                isinstance(node.op, ast.Add):
            name = node.target.id
            for p in _probe_strings(node.value, {}):
                assigns.setdefault(name, ([], []))[1].append(p)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or _attr_tail(node.func) not in (
                "record_baseline", "recorded_baseline"):
            continue
        if not node.args:
            continue
        for probe in _probe_strings(node.args[0], assigns):
            if not any(r.fullmatch(probe) for r in _KEY_FAMILY_RES):
                yield LintFinding(
                    "baseline-key-family", mod.path, node.lineno,
                    node.col_offset,
                    f"baseline key shaped like {probe!r} matches no "
                    "documented key family (docs/ANALYSIS.md) — new "
                    "families need a doc row + a family regex here")


def _r_device_from_mirror(mod: _Module):
    # the AST+dataflow half of the host-mirror aliasing analysis lives
    # with its runtime probe (analysis/aliasing.py); imported lazily so
    # flowlint stays importable standalone
    from flow_updating_tpu.analysis import aliasing

    yield from aliasing.lint_device_from_mirror(mod)


_RULE_PASSES = {
    "numpy-in-kernel": _r_numpy_in_kernel,
    "traced-if": _r_traced_if,
    "kernel-round-program": _r_kernel_round_program,
    "bare-prngkey": _r_bare_prngkey,
    "baseline-key-family": _r_baseline_key_family,
    "device-from-mirror": _r_device_from_mirror,
}


# ---------------------------------------------------------------------------
# drivers

def lint_source(src: str, path: str, rules=None) -> list:
    """Lint one source text; returns surviving findings (suppressions
    applied; a reason-less suppression becomes its own finding)."""
    mod = _Module(path, src)
    out = []
    seen = set()
    for name in (rules or _RULE_PASSES):
        for f in _RULE_PASSES[name](mod):
            # nested traced functions are walked both standalone and as
            # part of their parent's body: keep one finding per site
            site = (f.rule, f.line, f.col)
            if site in seen:
                continue
            seen.add(site)
            state = mod.suppressed(f.line, f.rule)
            if state is True:
                continue
            if state == "bare":
                out.append(dataclasses.replace(
                    f, message=(
                        "suppression without a reason — write "
                        f"`# flowlint: ok({f.rule}) <why>` "
                        f"(suppressing: {f.message})")))
            else:
                out.append(f)
    return out


def default_targets(repo_root: str | None = None) -> list:
    """The repo surface ``lint`` covers: the package + bench.py."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = repo_root or os.path.dirname(here)
    targets = []
    for base, _dirs, files in os.walk(os.path.join(root,
                                                   "flow_updating_tpu")):
        if "__pycache__" in base:
            continue
        targets.extend(os.path.join(base, f) for f in sorted(files)
                       if f.endswith(".py"))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        targets.append(bench)
    return targets


def lint_paths(paths=None, rules=None) -> list:
    """Lint files (default: the whole repo surface).  Syntax errors in
    a target surface as findings, never tracebacks."""
    out = []
    for path in (paths if paths is not None else default_targets()):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as err:
            out.append(LintFinding("io", path, 0, 0, str(err)))
            continue
        try:
            out.extend(lint_source(src, path, rules=rules))
        except SyntaxError as err:
            out.append(LintFinding("syntax", path, err.lineno or 0, 0,
                                   str(err.msg)))
    return out
