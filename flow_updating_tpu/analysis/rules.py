"""Structural rules over round-program jaxprs — hazards caught at lint
time instead of at 100x-slowdown time.

Every rule here mechanizes a hazard this repo has already paid for once
by benchmark archaeology or debugging session:

``serializing-scatter``
    A *batched* ``scatter-add`` (non-empty ``update_window_dims`` /
    ``operand_batching_dims`` — the shape vmap produces) inside the
    round scan on a CPU-hot path.  XLA:CPU serializes batched scatters;
    the sweep engine's first vmapped build ran ~100x slow before the
    row-fold rewrite (PR 3, the dense-hardware recipe of
    arXiv:1906.11786 applied in reverse).

``gather-fast-path``
    A ``gather`` inside the round scan of a program claiming the
    TPU fast path.  ``plan/select.py`` models this penalty at ~2000x on
    TPU; the Benes/structured paths exist precisely to avoid it, so a
    gather showing up there is a silent fast-path regression.

``callback-in-scan``
    Any ``*_callback`` primitive inside a scan/while body: a host
    round-trip per round, the exact failure mode the device-resident
    telemetry layer (PR 2) was built to prevent.

``dtype-drift``
    A non-scalar float width change (``convert_element_type`` f32<->f64)
    inside the round scan: an fp32 ledger silently widening (2x HBM +
    wire) or narrowing (silent precision loss) mid-round.  Scalars are
    exempt — weak-type literal promotion is idiomatic and free.

``key-reuse``
    The same PRNG key consumed by two independent random draws/splits
    (jaxpr dataflow, not name matching).  Correlated "independent" drop
    draws corrupt loss realizations silently.  ``fold_in`` derivations
    are treated as fresh streams (the documented per-edge/per-shard key
    family pattern); ``cond`` branches count as alternatives, not
    repetitions.

``scan-collective``
    Collectives inside the round scan over axes the program declared it
    would not touch.  Feature-mesh runs must have ZERO round-scan
    collectives (PR 10's bit-exactness argument rests on it); halo/pod
    programs allow exactly the node axis.

A rule runs over a traced jaxpr under a :class:`ProgramContext` (what
the program claims about itself: hot backend, fast-path claim, allowed
scan collectives) and returns :class:`Finding` records citing the
primitive path
(``pjit/scan/scatter-add``).  Nothing compiles or executes — rules run
on ``jax.make_jaxpr`` output only, so the whole kernel matrix audits in
seconds.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable

from flow_updating_tpu.analysis import walk

# ---------------------------------------------------------------------------
# findings + context

ERROR = "error"
WARN = "warn"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, citable: rule id, program label, primitive
    path, and the message naming the hazard."""

    rule: str
    message: str
    where: str = ""
    program: str = ""
    severity: str = ERROR

    def format(self) -> str:
        loc = f" at {self.where}" if self.where else ""
        prog = f"[{self.program}] " if self.program else ""
        return f"{prog}{self.rule}{loc}: {self.message}"


@dataclasses.dataclass(frozen=True)
class ProgramContext:
    """What the program under analysis claims about itself — rules are
    conditional on these claims, not on guesses.

    ``backend`` — where the program is hot ('cpu' or 'tpu').
    ``tpu_fast_path`` — the program claims the gather-free TPU fast
    path (Benes / structured / banded spmv, Benes delivery).
    ``allowed_scan_collective_axes`` — mesh axes whose collectives are
    expected inside the round scan (halo/pod: the node axis; feature
    -sharded payload programs: none at all).
    """

    backend: str = "cpu"
    tpu_fast_path: bool = False
    allowed_scan_collective_axes: frozenset = frozenset({"nodes"})


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    doc: str
    fn: Callable

    def run(self, closed_jaxpr, ctx: ProgramContext) -> list:
        return list(self.fn(closed_jaxpr, ctx))


RULES: dict[str, Rule] = {}


def _rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = Rule(name=name, doc=doc, fn=fn)
        return fn
    return deco


# ---------------------------------------------------------------------------
# scatter / gather / callback / dtype / collective rules

# combining scatters only: the serialization hazard is the REDUCTION
# form (segment sums).  A plain overwrite `scatter` (delay-line row
# writes via .at[i].set) is a contiguous window update, not the hazard.
_SCATTER_PRIMS = ("scatter-add", "scatter-mul", "scatter-min",
                  "scatter-max")
_GATHER_PRIMS = ("gather",)
_COLLECTIVE_PRIMS = ("psum", "psum2", "ppermute", "pmax", "pmin",
                     "pgather", "all_gather", "all_to_all",
                     "reduce_scatter", "collective_permute")


def _is_batched_scatter(eqn) -> bool:
    """The vmap-produced shape: a combining scatter whose operand keeps
    a window (batch) axis BEFORE the scattered axis, so every scatter
    index touches a strided slab — the form XLA:CPU serializes.  A
    payload scatter-add (window axis AFTER the scattered axis:
    contiguous row adds, ``(N, D)`` ledgers) is the fast form and does
    not fire."""
    dn = eqn.params.get("dimension_numbers")
    if dn is None:
        return False
    if getattr(dn, "operand_batching_dims", ()):
        return True
    if not getattr(dn, "update_window_dims", ()):
        return False
    operand = walk.aval_of(eqn.invars[0])
    rank = len(getattr(operand, "shape", ()) or ())
    excluded = set(getattr(dn, "inserted_window_dims", ())) \
        | set(getattr(dn, "operand_batching_dims", ()))
    window_dims = [d for d in range(rank) if d not in excluded]
    scattered = getattr(dn, "scatter_dims_to_operand_dims", ())
    return bool(window_dims and scattered
                and min(window_dims) < max(scattered))


@_rule(
    "serializing-scatter",
    "batched scatter-add inside the round scan on a CPU-hot path: "
    "XLA:CPU serializes it (the PR-3 ~100x sweep slowdown); use the "
    "custom_vmap flat-offset rule or a row-matrix fold instead",
)
def _r_serializing_scatter(jx, ctx):
    if ctx.backend != "cpu":
        return
    for site in walk.iter_sites(jx):
        if (site.prim in _SCATTER_PRIMS and site.loop_depth >= 1
                and _is_batched_scatter(site.eqn)):
            op = walk.aval_of(site.eqn.invars[0])
            yield Finding(
                rule="serializing-scatter",
                where=site.where,
                message=(
                    f"batched {site.prim} on operand "
                    f"{walk.fmt_aval(op)} inside the round scan — "
                    "XLA:CPU executes batched scatters serially"),
            )


@_rule(
    "gather-fast-path",
    "gather inside the round scan of a program claiming the gather-free "
    "TPU fast path (plan/select.py models ~2000x penalty on TPU)",
)
def _r_gather_fast_path(jx, ctx):
    if not ctx.tpu_fast_path:
        return
    for site in walk.iter_sites(jx):
        if site.prim in _GATHER_PRIMS and site.loop_depth >= 1:
            op = walk.aval_of(site.eqn.invars[0])
            yield Finding(
                rule="gather-fast-path",
                where=site.where,
                message=(
                    f"gather on {walk.fmt_aval(op)} inside the round "
                    "scan of a claimed gather-free fast path"),
            )


@_rule(
    "callback-in-scan",
    "host callback inside a scan/while body: a host round-trip per "
    "round (telemetry/fields ride the scan as ys exactly to avoid this)",
)
def _r_callback_in_scan(jx, ctx):
    del ctx
    for site in walk.iter_sites(jx):
        if "callback" in site.prim and site.loop_depth >= 1:
            yield Finding(
                rule="callback-in-scan",
                where=site.where,
                message=f"{site.prim} inside the round scan",
            )


def _float_width(dtype) -> int | None:
    import numpy as np

    try:
        dt = np.dtype(dtype)
    except TypeError:
        return None
    return dt.itemsize if dt.kind == "f" else None


@_rule(
    "dtype-drift",
    "non-scalar float width change inside the round scan: an fp32 "
    "ledger silently widening (2x HBM + wire bytes) or narrowing "
    "(precision loss) mid-round",
)
def _r_dtype_drift(jx, ctx):
    del ctx
    for site in walk.iter_sites(jx):
        if site.prim != "convert_element_type" or site.loop_depth < 1:
            continue
        src = walk.aval_of(site.eqn.invars[0])
        if src is None or not getattr(src, "shape", None):
            continue                       # scalars: weak-type idiom, free
        w_in = _float_width(getattr(src, "dtype", None))
        w_out = _float_width(site.eqn.params.get("new_dtype"))
        if w_in and w_out and w_in != w_out:
            yield Finding(
                rule="dtype-drift",
                where=site.where,
                message=(
                    f"{walk.fmt_aval(src)} converts to "
                    f"{site.eqn.params['new_dtype']} inside the round "
                    "scan (non-scalar float width change)"),
            )


def _collective_axes(eqn) -> tuple:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


@_rule(
    "scan-collective",
    "collective inside the round scan over an axis the program declared "
    "collective-free (feature-mesh runs must have ZERO round-scan "
    "collectives — PR 10's bit-exactness guarantee)",
)
def _r_scan_collective(jx, ctx):
    allowed = ctx.allowed_scan_collective_axes
    for site in walk.iter_sites(jx):
        if site.prim not in _COLLECTIVE_PRIMS or site.loop_depth < 1:
            continue
        bad = [a for a in _collective_axes(site.eqn) if a not in allowed]
        if bad:
            yield Finding(
                rule="scan-collective",
                where=site.where,
                message=(
                    f"{site.prim} over axis {bad} inside the round scan "
                    f"(allowed axes: {sorted(allowed) or 'none'})"),
            )


# ---------------------------------------------------------------------------
# key-reuse: dataflow over the PRNG primitives

# consume the key they are given (each key must be consumed at most once)
_KEY_CONSUMERS = ("random_bits", "random_split", "threefry2x32",
                  "random_gamma")
# derive a FRESH stream from data (the documented key-family pattern)
_KEY_DERIVERS = ("random_fold_in",)
# pure repackaging: output carries the same logical key as operand 0
_KEY_PASSTHROUGH = ("random_wrap", "random_unwrap", "convert_element_type",
                    "squeeze", "reshape", "broadcast_in_dim", "transpose",
                    "copy", "device_put")
_CALL_PRIMS = ("pjit", "closed_call", "core_call", "remat", "remat2",
               "custom_jvp_call", "custom_vjp_call", "custom_vmap_call",
               "shard_map", "xla_call")


def _key_flow(jaxpr, env: dict, sites: dict, path: tuple,
              uid: list | None = None) -> None:
    """Walk ``jaxpr`` propagating value tokens through key-shaped
    dataflow; record each consuming equation against its key's root
    token in ``sites`` (token -> list of locations).

    ``uid`` is the traversal-wide freshness counter: tokens must be
    unique PER VISIT, not per variable object — jax caches the traced
    body of identical scan calls, so two scans over the same function
    share one body jaxpr and ``id(var)`` alone would alias their
    (independent) key streams into a false reuse (multi-scan programs,
    tests/test_invariants.py)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    if uid is None:
        uid = [0]

    def tok(atom):
        return env.get(id(atom))

    def fresh(var, label):
        uid[0] += 1
        env[id(var)] = (label, uid[0])

    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        here = path + (name,)
        if name in _KEY_CONSUMERS:
            # threefry2x32 consumes (k1, k2, c1, c2): the key is the
            # first two operands; typed-key prims consume operand 0
            n_key_ops = 2 if name == "threefry2x32" else 1
            hit = set()
            for atom in eqn.invars[:n_key_ops]:
                t = tok(atom)
                if t is not None and t not in hit:
                    hit.add(t)
                    sites.setdefault(t, []).append("/".join(here))
            for ov in eqn.outvars:
                fresh(ov, name)
            continue
        if name in _KEY_DERIVERS:
            for ov in eqn.outvars:
                fresh(ov, name)
            continue
        if name in _KEY_PASSTHROUGH and eqn.invars:
            t = tok(eqn.invars[0])
            for ov in eqn.outvars:
                if t is not None:
                    env[id(ov)] = t
                else:
                    fresh(ov, name)
            continue
        if name == "slice" and eqn.invars:
            # slices of a key batch select DISTINCT children (the split
            # output pattern): refine the token by the slice window
            t = tok(eqn.invars[0])
            start = tuple(eqn.params.get("start_indices", ()))
            for ov in eqn.outvars:
                if t is not None:
                    env[id(ov)] = (t, ("slice", start))
                else:
                    fresh(ov, name)
            continue
        inner = walk.subjaxprs(eqn)
        if inner and name in walk.BRANCH_PRIMS:
            # branches are alternatives: merge consumption counts by MAX
            ops = eqn.invars[1:]        # invars[0] is the branch index
            merged: dict = {}
            for sub in inner:
                sub_sites: dict = {}
                sub_env = dict(env)
                _bind(sub, ops, sub_env, uid)
                _key_flow(sub, sub_env, sub_sites, here, uid)
                for t, locs in sub_sites.items():
                    if len(locs) > len(merged.get(t, ())):
                        merged[t] = locs
            for t, locs in merged.items():
                sites.setdefault(t, []).extend(locs)
        elif inner and name in walk.LOOP_PRIMS:
            # loop bodies re-execute: a carried key that is CONSUMED in
            # the body yet returned unchanged on the carry leg is drawn
            # from with the same value every iteration — the canonical
            # per-round reuse.  Record the body's consumptions, then add
            # a synthetic second site per consumed-and-passed-through
            # carry token.
            for sub in inner:
                sub_env = dict(env)
                _bind(sub, eqn.invars, sub_env, uid)
                before = {t: len(locs) for t, locs in sites.items()}
                _key_flow(sub, sub_env, sites, here, uid)
                sub_jaxpr = getattr(sub, "jaxpr", sub)
                for cin, cout in _loop_carry_pairs(eqn, sub_jaxpr):
                    t_in = sub_env.get(id(cin))
                    t_out = sub_env.get(id(cout))
                    if t_in is None or t_in != t_out:
                        continue
                    if len(sites.get(t_in, ())) > before.get(t_in, 0):
                        sites.setdefault(t_in, []).append(
                            "/".join(here) + "[carry-passthrough]")
        elif inner and (name in _CALL_PRIMS
                        or name == "custom_vmap_call_jvp"):
            for sub in inner:
                sub_env = dict(env)
                _bind(sub, eqn.invars, sub_env, uid)
                _key_flow(sub, sub_env, sites, here, uid)
        for ov in eqn.outvars:
            if id(ov) not in env:
                fresh(ov, name)


def _loop_carry_pairs(eqn, body_jaxpr):
    """(invar, outvar) carry-leg pairs of a scan/while body jaxpr.
    scan: invars = consts + carry + xs, outvars = carry + ys (counts in
    params).  while: only the params['body_jaxpr'] sub-jaxpr carries
    (the cond jaxpr returns a boolean and yields no pairs)."""
    name = eqn.primitive.name
    invars, outvars = list(body_jaxpr.invars), list(body_jaxpr.outvars)
    if name == "scan":
        nc = eqn.params.get("num_consts", 0)
        nk = eqn.params.get("num_carry", 0)
        return list(zip(invars[nc:nc + nk], outvars[:nk]))
    if name == "while":
        body = eqn.params.get("body_jaxpr")
        if body_jaxpr is not getattr(body, "jaxpr", body):
            return []
        nk = len(outvars)
        return list(zip(invars[len(invars) - nk:], outvars))
    return []


def _bind(jaxpr, outer_atoms, env: dict,
          uid: list | None = None) -> None:
    """Bind an inner jaxpr's invars to the outer operands' tokens
    (positional; extra/missing positions get fresh per-visit tokens —
    see the ``uid`` note on :func:`_key_flow`)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    invars = list(jaxpr.invars)
    # align from the END: call conventions prepend consts to invars
    outer = list(outer_atoms)[-len(invars):] if invars else []
    offset = len(invars) - len(outer)
    for k, iv in enumerate(invars):
        src = outer[k - offset] if k >= offset else None
        t = env.get(id(src)) if src is not None else None
        if t is None:
            if uid is None:
                t = ("arg", id(iv))
            else:
                uid[0] += 1
                t = ("arg", uid[0])
        env[id(iv)] = t


@_rule(
    "key-reuse",
    "the same PRNG key consumed by two independent draws/splits "
    "(dataflow, not name matching): correlated 'independent' randomness",
)
def _r_key_reuse(jx, ctx):
    del ctx
    jaxpr = getattr(jx, "jaxpr", jx)
    env: dict = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        env[id(v)] = ("arg", id(v))
    sites: dict = {}
    _key_flow(jaxpr, env, sites, ())
    for t, locs in sites.items():
        if len(locs) >= 2:
            yield Finding(
                rule="key-reuse",
                where=locs[1],
                message=(
                    f"one PRNG key reaches {len(locs)} draws/splits "
                    f"(first at {locs[0]}) — split the key, or fold_in "
                    "distinct data per stream"),
            )


# ---------------------------------------------------------------------------
# drivers

def analyze_jaxpr(closed_jaxpr, ctx: ProgramContext | None = None,
                  rules=None, program: str = "") -> list:
    """Run ``rules`` (default: all) over one traced jaxpr.  Findings
    are deduplicated: ``custom_vmap``-style equations carry BOTH the
    primal and the batching-rule jaxpr in their params, so the same
    site would otherwise report twice."""
    ctx = ctx or ProgramContext()
    out = []
    for name in (rules or RULES):
        for f in RULES[name].run(closed_jaxpr, ctx):
            out.append(dataclasses.replace(f, program=program))
    return list(dict.fromkeys(out))


def analyze_program(fn, args, n_dynamic: int | None = None,
                    ctx: ProgramContext | None = None, rules=None,
                    program: str = "") -> list:
    """Trace a round_program-convention callable and analyze it."""
    jx = walk.jaxpr_program(fn, args, n_dynamic)
    return analyze_jaxpr(jx, ctx, rules=rules, program=program)


def kernel_programs() -> list:
    """The standard audit matrix ``lint`` runs the rule engine over:
    one small program per dispatch mode plus the fast-path and
    feature-mesh claims.  Returns ``(label, fn, args, n_dynamic, ctx)``
    tuples; building them traces nothing yet."""
    import jax.numpy as jnp

    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.models.rounds import run_rounds
    from flow_updating_tpu.models.state import init_state
    from flow_updating_tpu.topology.generators import (
        erdos_renyi,
        fat_tree,
        ring,
    )

    progs = []
    topo = ring(16, k=2, seed=1)
    cfg = RoundConfig.fast()
    arrays = topo.device_arrays()
    state = init_state(topo, cfg, seed=0)
    progs.append(("edge/collectall", run_rounds,
                  (state, arrays, cfg, 4), 2, ProgramContext()))
    ref = RoundConfig.reference(variant="collectall")
    progs.append(("edge/reference", run_rounds,
                  (init_state(topo, ref, seed=0), arrays, ref, 4), 2,
                  ProgramContext()))

    from flow_updating_tpu.models import sync

    ntopo = erdos_renyi(24, avg_degree=4.0, seed=3)
    ncfg = RoundConfig.fast(kernel="node")
    nk = sync.NodeKernel(ntopo, ncfg)
    fn, args, nd = nk.round_program(nk.init_state(), 4)
    progs.append(("node/xla", fn, args, nd, ProgramContext()))
    bcfg = RoundConfig.fast(kernel="node", spmv="benes")
    bk = sync.NodeKernel(ntopo, bcfg)
    fn, args, nd = bk.round_program(bk.init_state(), 4)
    progs.append(("node/benes", fn, args, nd,
                  ProgramContext(backend="tpu", tpu_fast_path=True)))

    import jax

    if len(jax.devices()) >= 2:
        from flow_updating_tpu.parallel import sharded
        from flow_updating_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(2)
        ecfg = RoundConfig.fast()
        plan = sharded.plan_sharding(ntopo, 2)
        hstate = sharded.init_plan_state(plan, ecfg, mesh)
        fn, args, nd = sharded.round_program(
            hstate, plan, ecfg, mesh, 4)
        progs.append(("halo/ppermute", fn, args, nd,
                      ProgramContext(
                          allowed_scan_collective_axes=frozenset(
                              {"nodes"}))))

        from flow_updating_tpu.parallel import structured_sharded

        ft = fat_tree(4, seed=0)
        pcfg = RoundConfig.fast(kernel="node", spmv="structured")
        pk = structured_sharded.PodShardedFatTreeKernel(ft, pcfg, mesh)
        fn, args, nd = pk.round_program(pk.init_state(), 4)
        progs.append(("pod/structured", fn, args, nd,
                      ProgramContext(
                          backend="tpu", tpu_fast_path=True,
                          allowed_scan_collective_axes=frozenset(
                              {"nodes"}))))

        from flow_updating_tpu.parallel import feature
        from flow_updating_tpu.parallel.mesh import make_mesh2d

        fmesh = make_mesh2d(1, 2)
        vals = jnp.tile(jnp.asarray(ntopo.values)[:, None], (1, 4))
        fcfg = RoundConfig.fast()
        fstate = init_state(ntopo, fcfg, values=vals)
        farrays = ntopo.device_arrays()
        progs.append(("feature/sharded", feature.run_rounds_feature,
                      (fstate, farrays, fcfg, 4, fmesh), 2,
                      ProgramContext(
                          allowed_scan_collective_axes=frozenset())))
    return progs


def audit_kernels(rules=None) -> list:
    """Trace + analyze the whole standard matrix; the jaxpr half of the
    ``lint`` CLI.  Returns all findings (empty = clean)."""
    findings = []
    for label, fn, args, nd, ctx in kernel_programs():
        findings.extend(analyze_program(fn, args, nd, ctx, rules=rules,
                                        program=label))
    return findings
