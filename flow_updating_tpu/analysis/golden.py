"""Golden-program ledger — the mode x twin lowering matrix as ONE table.

The repo's deepest guarantees are *program identities*: telemetry/fields
/profile off must lower the byte-identical plain program,
``robust='none'`` and ``adversary=None`` must not perturb the lowering,
and ROADMAP item 5's round-program IR must reproduce every existing
lowering bit-exactly before it can land.  Until now each identity was a
hand-written ``lower().as_text()`` comparison scattered across test
files; this module replaces them with one canonicalizer and one
committed ledger (``GOLDEN_PROGRAMS.json``):

- every **cell** of the (dispatch mode edge/node/halo/pod) x (twin
  plain/telemetry/fields) x robust x adversary x payload matrix names a
  deterministic small program (fixed topology, fixed seed, CPU
  lowering);
- :func:`build_ledger` canonical-hashes each cell's StableHLO and
  stores the zlib-compressed canonical text;
- :func:`audit` re-lowers every cell and diffs against the ledger,
  naming the exact cell and the FIRST DIVERGENT HLO LINE on drift;
- ``audit --rebase`` regenerates the ledger after an intentional
  lowering change (docs/ANALYSIS.md records the workflow).

The ledger is keyed to the lowering environment (jax version, CPU
backend): an audit under a different jax version reports the mismatch
explicitly and judges nothing — program text is a compiler artifact,
not a cross-version invariant.

Tests use :func:`canonical_program` as the ONE canonicalizer for ad-hoc
program-identity asserts (test_fields.py, test_scenarios.py,
scripts/telemetry_overhead.py all route through it).
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import json
import re
import zlib

LEDGER_VERSION = "flow-updating-golden-programs/v1"
DEFAULT_LEDGER = "GOLDEN_PROGRAMS.json"

# number of rounds every cell lowers: programs scan, so text size is
# round-count independent, but the count is part of the cell identity
CELL_ROUNDS = 4


# ---------------------------------------------------------------------------
# canonicalization — the one place lowered text is normalized

_LOC_LINE = re.compile(r"^#loc\d*\s*=.*$", re.MULTILINE)
_LOC_ATTR = re.compile(r"\s+loc\(.*?\)")


def canonical_text(text: str) -> str:
    """Canonical form of a lowered module's text: location metadata
    stripped (``#loc`` lines and ``loc(...)`` attributes carry file
    paths and line numbers of the *caller*, not the program), trailing
    whitespace removed, single trailing newline."""
    text = _LOC_LINE.sub("", text)
    text = _LOC_ATTR.sub("", text)
    lines = [ln.rstrip() for ln in text.splitlines()]
    while lines and not lines[-1]:
        lines.pop()
    return "\n".join(lines) + "\n"


def canonical_program(fn, *args, **kwargs) -> str:
    """Canonical lowered text of ``fn(*args, **kwargs)`` — the one
    canonicalizer every program-identity assert routes through.  ``fn``
    is any jit-wrapped callable; static args pass exactly as a normal
    call."""
    return canonical_text(fn.lower(*args, **kwargs).as_text())


def program_digest(canonical: str) -> str:
    return hashlib.sha256(canonical.encode()).hexdigest()


def _pack(canonical: str) -> str:
    return base64.b85encode(zlib.compress(canonical.encode(), 9)).decode()


def _unpack(packed: str) -> str:
    return zlib.decompress(base64.b85decode(packed.encode())).decode()


def first_divergence(old: str, new: str) -> dict:
    """First line where two canonical programs diverge: 1-based line
    number plus both lines (missing side = None)."""
    old_lines = old.splitlines()
    new_lines = new.splitlines()
    for i, (a, b) in enumerate(zip(old_lines, new_lines)):
        if a != b:
            return {"line": i + 1, "ledger": a, "current": b}
    if len(old_lines) != len(new_lines):
        i = min(len(old_lines), len(new_lines))
        return {"line": i + 1,
                "ledger": old_lines[i] if i < len(old_lines) else None,
                "current": new_lines[i] if i < len(new_lines) else None}
    return {}


# ---------------------------------------------------------------------------
# the cell registry

@dataclasses.dataclass(frozen=True)
class Cell:
    """One coordinate of the conformance matrix.  ``key`` is the ledger
    key; ``build`` returns ``(jitted_fn, args, kwargs)`` ready to
    lower."""

    key: str
    mode: str          # edge | node | halo | pod | query
    twin: str          # plain | telemetry | fields
    build: object      # () -> (fn, args, kwargs)


class _Fixtures:
    """Shared deterministic inputs, built once per registry walk (cells
    reuse topologies/configs so a full audit stays seconds, not
    minutes)."""

    def __init__(self):
        self._cache: dict = {}

    def get(self, name: str, make):
        if name not in self._cache:
            self._cache[name] = make()
        return self._cache[name]


def _mean(topo, cfg):
    import jax.numpy as jnp

    return jnp.asarray(topo.true_mean, cfg.jnp_dtype)


def cells() -> list:
    """The registered matrix.  Builders are lazy: constructing the list
    touches nothing heavy; each cell builds its inputs when lowered.

    Coverage: all four dispatch modes x all three twins, plus the
    robust/adversary/payload/variant axes on the edge kernel (where
    those knobs live) and a vector-payload variant on halo — ≥24 cells
    by construction (pinned in tests/test_analysis.py)."""
    import jax.numpy as jnp

    fx = _Fixtures()
    out: list = []

    def _topo_edge():
        from flow_updating_tpu.topology.generators import community

        return community(32, c=2, k_in=6.0, k_out=0.5, seed=0)

    def _edge_inputs(cfg, *, adversary=None, vector=False):
        from flow_updating_tpu.models.state import init_state

        topo = fx.get("topo_edge", _topo_edge)
        arrays = fx.get(
            f"arrays_edge_coloring={cfg.needs_coloring}",
            lambda: topo.device_arrays(coloring=cfg.needs_coloring))
        if adversary is not None:
            arrays = arrays.replace(**adversary.device_leaves(
                topo.num_nodes, topo.num_edges, cfg.jnp_dtype))
        values = None
        if vector:
            import numpy as np

            values = jnp.asarray(
                np.linspace(0.0, 1.0, topo.num_nodes * 3,
                            dtype=np.float64).reshape(-1, 3))
        state = init_state(topo, cfg, seed=0, values=values)
        return topo, arrays, state

    def _edge_cell(key, cfg, twin="plain", adversary=None, vector=False):
        def build(cfg=cfg, twin=twin, adversary=adversary, vector=vector):
            from flow_updating_tpu.models.rounds import (
                run_rounds,
                run_rounds_fields,
                run_rounds_telemetry,
            )

            topo, arrays, state = _edge_inputs(cfg, adversary=adversary,
                                               vector=vector)
            if twin == "plain":
                return run_rounds, (state, arrays, cfg, CELL_ROUNDS), {}
            from flow_updating_tpu.obs.fields import FieldSpec
            from flow_updating_tpu.obs.telemetry import TelemetrySpec

            if twin == "telemetry":
                spec = TelemetrySpec.default().for_kernel("edge")
                return run_rounds_telemetry, (
                    state, arrays, cfg, CELL_ROUNDS, spec,
                    _mean(topo, cfg)), {}
            spec = FieldSpec.default().for_kernel("edge")
            return run_rounds_fields, (
                state, arrays, cfg, CELL_ROUNDS, spec,
                _mean(topo, cfg)), {}
        out.append(Cell(key=key, mode="edge", twin=twin, build=build))

    from flow_updating_tpu.models.config import RoundConfig

    fast = RoundConfig.fast()
    # -- edge x twin x payload ------------------------------------------
    for twin in ("plain", "telemetry", "fields"):
        _edge_cell(f"edge/{twin}/robust=none/adv=none/payload=scalar",
                   fast, twin=twin)
        _edge_cell(f"edge/{twin}/robust=none/adv=none/payload=vector3",
                   fast, twin=twin, vector=True)
    # -- edge robust modes ---------------------------------------------
    _edge_cell("edge/plain/robust=clip/adv=none/payload=scalar",
               RoundConfig.fast(robust="clip", robust_clip=1.0))
    _edge_cell("edge/plain/robust=trim/adv=none/payload=scalar",
               RoundConfig.fast(robust="trim", robust_tol=0.5))
    # -- edge adversaries ----------------------------------------------

    def _adv_lie():
        from flow_updating_tpu.scenarios.adversary import Adversary

        return Adversary(lie_nodes=(1,), lie_value=9.0)

    def _adv_flow():
        from flow_updating_tpu.scenarios.adversary import Adversary

        return Adversary(corrupt_edges=(0,), corrupt_gain=1.5)

    _edge_cell("edge/plain/robust=none/adv=lie/payload=scalar",
               fast, adversary=_adv_lie())
    _edge_cell("edge/plain/robust=clip/adv=lie/payload=scalar",
               RoundConfig.fast(robust="clip", robust_clip=1.0),
               adversary=_adv_lie())
    _edge_cell("edge/plain/robust=none/adv=corrupt/payload=scalar",
               fast, adversary=_adv_flow())
    # -- edge protocol variants ----------------------------------------
    _edge_cell("edge-reference/plain/robust=none/adv=none/payload=scalar",
               RoundConfig.reference(variant="collectall"))
    _edge_cell("edge-pairwise/plain/robust=none/adv=none/payload=scalar",
               RoundConfig.fast(variant="pairwise"))
    _edge_cell(
        "edge-pairwise-faithful/plain/robust=none/adv=none/payload=scalar",
        RoundConfig.reference(variant="pairwise"))
    _edge_cell("edge-pairwise/plain/robust=clip/adv=none/payload=scalar",
               RoundConfig.fast(variant="pairwise", robust="clip",
                                robust_clip=1.0))

    # -- edge chunked payload schedule ---------------------------------
    def _build_chunked():
        from flow_updating_tpu.models.rounds import (
            init_chunked_state,
            run_rounds_chunked,
        )

        topo = fx.get("topo_edge", _topo_edge)
        arrays = fx.get("arrays_edge_coloring=False",
                        lambda: topo.device_arrays())
        import numpy as np

        vals = jnp.asarray(
            np.linspace(0.0, 1.0, topo.num_nodes * 4,
                        dtype=np.float64).reshape(-1, 4))
        cs = init_chunked_state(topo, fast, 2, vals, seed=0)
        return run_rounds_chunked, (cs, arrays, fast, 4, 1), {}
    out.append(Cell(
        key="edge-chunked2/plain/robust=none/adv=none/payload=vector4",
        mode="edge", twin="plain", build=_build_chunked))

    # -- node x twin ----------------------------------------------------
    def _node_kernel(spmv="xla"):
        from flow_updating_tpu.models import sync
        from flow_updating_tpu.topology.generators import erdos_renyi

        topo = fx.get("topo_node",
                      lambda: erdos_renyi(24, avg_degree=4.0, seed=3))
        cfg = RoundConfig.fast(kernel="node", spmv=spmv)
        return fx.get(f"node_kernel_{spmv}",
                      lambda: sync.NodeKernel(topo, cfg)), topo, cfg

    def _node_cell(key, twin, spmv="xla"):
        def build(twin=twin, spmv=spmv):
            from flow_updating_tpu.models import sync

            kern, topo, cfg = _node_kernel(spmv)
            state = kern.init_state()
            if twin == "plain":
                fn, args, _ = kern.round_program(state, CELL_ROUNDS)
                return fn, args, {}
            from flow_updating_tpu.obs.fields import FieldSpec
            from flow_updating_tpu.obs.telemetry import TelemetrySpec

            if twin == "telemetry":
                spec = TelemetrySpec.default().for_kernel("node")
                return sync.run_rounds_node_telemetry, (
                    state, kern.arrays, cfg, CELL_ROUNDS, spec,
                    _mean(topo, cfg)), {}
            spec = FieldSpec.default().for_kernel("node")
            return sync.run_rounds_node_fields, (
                state, kern.arrays, cfg, CELL_ROUNDS, spec,
                _mean(topo, cfg)), {}
        out.append(Cell(key=key, mode="node", twin=twin, build=build))

    for twin in ("plain", "telemetry", "fields"):
        _node_cell(f"node/{twin}/robust=none/adv=none/payload=scalar",
                   twin)
    _node_cell("node-benes/plain/robust=none/adv=none/payload=scalar",
               "plain", spmv="benes")
    # the topology-compiler banded executor (PR 6): RCM reorder + dense
    # masked rolls + Beneš remainder — the fast path ROADMAP item 1
    # fuses next, so its lowering joins the ledger now
    _node_cell("node-banded/plain/robust=none/adv=none/payload=scalar",
               "plain", spmv="banded")
    # the ONE-KERNEL fused round (this PR): the banded plan executed as
    # a single VMEM-resident Pallas program, interpret-executed on CPU
    # so the ledger pins the SHIPPED kernel's lowering
    _node_cell("node-banded-fused/plain/robust=none/adv=none/"
               "payload=scalar", "plain", spmv="banded_fused")

    # -- halo x twin (2-shard virtual mesh) -----------------------------
    def _halo_parts(vector=False):
        from flow_updating_tpu.parallel import sharded
        from flow_updating_tpu.parallel.mesh import make_mesh
        from flow_updating_tpu.topology.generators import erdos_renyi

        topo = fx.get("topo_node",
                      lambda: erdos_renyi(24, avg_degree=4.0, seed=3))
        mesh = fx.get("mesh2", lambda: make_mesh(2))
        cfg = RoundConfig.fast()
        plan = fx.get("halo_plan",
                      lambda: sharded.plan_sharding(topo, 2))
        values = None
        if vector:
            import numpy as np

            values = np.linspace(
                0.0, 1.0, topo.num_nodes * 3).reshape(-1, 3)
        state = sharded.init_plan_state(plan, cfg, mesh, seed=0,
                                        values=values)
        return sharded, topo, mesh, cfg, plan, state

    def _halo_cell(key, twin, vector=False):
        def build(twin=twin, vector=vector):
            sharded, topo, mesh, cfg, plan, state = _halo_parts(vector)
            if twin == "plain":
                fn, args, _ = sharded.round_program(
                    state, plan, cfg, mesh, CELL_ROUNDS)
                return fn, args, {}
            from flow_updating_tpu.obs.fields import FieldSpec
            from flow_updating_tpu.obs.telemetry import TelemetrySpec

            # mirror the public wrappers' preamble
            # (run_rounds_sharded_telemetry/_fields), which call the
            # jitted twins with the plan arrays resolved
            plan_arrays, halo_tables, perm, ov, halo = \
                sharded._program_inputs(plan, cfg, mesh, None, "ppermute")
            mean = _mean(topo, cfg)
            if twin == "telemetry":
                spec = TelemetrySpec.default().for_kernel("halo")
                return sharded._run_sharded_telemetry, (
                    state, plan_arrays, halo_tables, perm, ov, mean,
                    cfg, mesh, CELL_ROUNDS, plan.Eb, plan.Nb,
                    plan.perm_offsets, halo, plan.num_colors, spec), {}
            spec = FieldSpec.default().for_kernel("halo")
            return sharded._run_sharded_fields, (
                state, plan_arrays, halo_tables, perm, ov, mean,
                cfg, mesh, CELL_ROUNDS, plan.Eb, plan.Nb,
                plan.perm_offsets, halo, plan.num_colors, spec), {}
        out.append(Cell(key=key, mode="halo", twin=twin, build=build))

    for twin in ("plain", "telemetry", "fields"):
        _halo_cell(f"halo-s2/{twin}/robust=none/adv=none/payload=scalar",
                   twin)
    _halo_cell("halo-s2/plain/robust=none/adv=none/payload=vector3",
               "plain", vector=True)

    # -- halo overlap schedules (PR 8): the interior/frontier split and
    # the single-kernel Pallas form (interpret-executed on the CPU mesh,
    # so the SHIPPED kernel's lowering is what the ledger pins)
    def _halo_overlap_cell(key, mode):
        def build(mode=mode):
            sharded, _topo, mesh, cfg, plan, state = _halo_parts()
            fn, args, _ = sharded.round_program(
                state, plan, cfg, mesh, CELL_ROUNDS, halo=mode)
            return fn, args, {}
        out.append(Cell(key=key, mode="halo", twin="plain", build=build))

    _halo_overlap_cell(
        "halo-s2-overlap/plain/robust=none/adv=none/payload=scalar",
        "overlap")
    _halo_overlap_cell(
        "halo-s2-overlap-pallas/plain/robust=none/adv=none/"
        "payload=scalar", "overlap_pallas")

    # -- sharded fused banded round (this PR): one remote-DMA Pallas
    # kernel per shard on the 2-shard virtual mesh, interpret mode
    def _banded_fused_sharded_cell(key):
        def build():
            from flow_updating_tpu.models.config import (
                RoundConfig as _RC,
            )
            from flow_updating_tpu.parallel.banded_sharded import (
                ShardedBandedKernel,
            )
            from flow_updating_tpu.parallel.mesh import make_mesh
            from flow_updating_tpu.topology.generators import erdos_renyi

            topo = fx.get("topo_node",
                          lambda: erdos_renyi(24, avg_degree=4.0, seed=3))
            mesh = fx.get("mesh2", lambda: make_mesh(2))
            cfg = _RC.fast(kernel="node", spmv="banded_fused")
            kern = fx.get(
                "banded_fused_sharded_kernel",
                lambda: ShardedBandedKernel(topo, cfg, mesh))
            fn, args, _ = kern.round_program(kern.init_state(),
                                             CELL_ROUNDS)
            return fn, args, {}
        out.append(Cell(key=key, mode="node", twin="plain", build=build))

    _banded_fused_sharded_cell(
        "node-banded-fused-s2/plain/robust=none/adv=none/payload=scalar")

    # -- pod x twin (fat-tree stencil, 2-shard mesh) --------------------
    def _pod_kernel():
        from flow_updating_tpu.parallel import structured_sharded
        from flow_updating_tpu.parallel.mesh import make_mesh
        from flow_updating_tpu.topology.generators import fat_tree

        topo = fx.get("topo_pod", lambda: fat_tree(4, seed=0))
        mesh = fx.get("mesh2_pod", lambda: make_mesh(2))
        cfg = RoundConfig.fast(kernel="node", spmv="structured")
        kern = fx.get(
            "pod_kernel",
            lambda: structured_sharded.PodShardedFatTreeKernel(
                topo, cfg, mesh))
        return kern, topo, cfg

    def _pod_cell(key, twin):
        def build(twin=twin):
            kern, topo, cfg = _pod_kernel()
            state = kern.init_state()
            if twin == "plain":
                fn, args, _ = kern.round_program(state, CELL_ROUNDS)
                return fn, args, {}
            from flow_updating_tpu.obs.fields import FieldSpec
            from flow_updating_tpu.obs.telemetry import TelemetrySpec

            mean = _mean(topo, cfg)
            if twin == "telemetry":
                spec = TelemetrySpec.default().for_kernel("pod")
                return kern._run_tel_jit, (
                    state, kern.value, kern.inv_depp1, kern.deg, mean), \
                    {"num_rounds": CELL_ROUNDS, "spec": spec}
            spec = FieldSpec.default().for_kernel("pod")
            return kern._run_fields_jit, (
                state, kern.value, kern.inv_depp1, kern.deg, mean), \
                {"num_rounds": CELL_ROUNDS, "spec": spec}
        out.append(Cell(key=key, mode="pod", twin=twin, build=build))

    for twin in ("plain", "telemetry", "fields"):
        _pod_cell(f"pod-s2/{twin}/robust=none/adv=none/payload=scalar",
                  twin)

    # -- query fabric (lane machine over the service engine) ------------
    # The fabric's round program IS run_rounds on the service layout
    # (capacity padding + dynamic row-matrix reductions) with a
    # lanes-wide payload and traced RoundParams; lane admission /
    # retirement must never change it (the zero-recompile contract), so
    # its lowering is pinned here — drop-free and drop>0 variants (the
    # two param structures a fabric can compile).
    def _build_query(drop=False):
        def build(drop=drop):
            from flow_updating_tpu.models.rounds import run_rounds
            from flow_updating_tpu.query import QueryFabric
            from flow_updating_tpu.topology.generators import ring

            cfg = RoundConfig.fast(
                variant="collectall",
                drop_rate=0.05 if drop else 0.0)
            fab = fx.get(
                f"query_fabric_drop={drop}",
                lambda: QueryFabric(
                    ring(12, k=2, seed=0), lanes=4, capacity=16,
                    degree_budget=6, config=cfg,
                    segment_rounds=CELL_ROUNDS))
            fab.submit(1.0)
            return (run_rounds,
                    (fab.svc.state, fab.svc.arrays, fab.svc.config,
                     CELL_ROUNDS), {"params": fab.svc.params})
        return build
    out.append(Cell(
        key="query-fabric/plain/robust=none/adv=none/payload=lanes4",
        mode="query", twin="plain", build=_build_query(False)))
    out.append(Cell(
        key="query-fabric-drop/plain/robust=none/adv=none/payload=lanes4",
        mode="query", twin="plain", build=_build_query(True)))

    # -- aggregate algebra: the mode-masked-write program ---------------
    # Installing ``TopoArrays.lane_modes`` (the fabric's one-time
    # extrema install) swaps the pytree's None placeholder for a (D,)
    # mode vector — the ONLY other lowering an aggregate fabric can run
    # (docs/AGGREGATES.md).  Its per-lane masked value write (extrema
    # lanes latch hi/lo, mean lanes average) and frozen extrema flow
    # must stay pinned, and the prover must still prove antisymmetry +
    # mask-neutrality through the mode selects.
    def _build_query_modes():
        from flow_updating_tpu.aggregates import AggregateFabric
        from flow_updating_tpu.models.rounds import run_rounds
        from flow_updating_tpu.topology.generators import ring

        cfg = RoundConfig.fast(variant="collectall")

        def make():
            fab = AggregateFabric(
                ring(12, k=2, seed=0), lanes=4, capacity=16,
                degree_budget=6, config=cfg,
                segment_rounds=CELL_ROUNDS)
            fab.submit_aggregate("max", 1.0)
            return fab
        fab = fx.get("aggregate_fabric_modes", make)
        assert fab.extrema_installed
        return (run_rounds,
                (fab.svc.state, fab.svc.arrays, fab.svc.config,
                 CELL_ROUNDS), {"params": fab.svc.params})
    out.append(Cell(
        key="query-fabric-modes/plain/robust=none/adv=none/"
            "payload=lanes4",
        mode="query", twin="plain", build=_build_query_modes))

    return out


def cell_index() -> dict:
    return {c.key: c for c in cells()}


# ---------------------------------------------------------------------------
# build / audit

def _environment() -> dict:
    import jax

    return {"jax": jax.__version__,
            "backend": jax.devices()[0].platform,
            "x64": bool(jax.config.jax_enable_x64),
            "device_count": len(jax.devices())}


def lower_cell(cell: Cell) -> str:
    """Canonical lowered text of one cell's program."""
    fn, args, kwargs = cell.build()
    return canonical_program(fn, *args, **kwargs)


def build_ledger(keys=None) -> dict:
    """Lower every registered cell (or the ``keys`` subset) and return
    the ledger document."""
    index = cell_index()
    keys = list(keys) if keys is not None else list(index)
    entries = {}
    for key in keys:
        canonical = lower_cell(index[key])
        entries[key] = {
            "sha256": program_digest(canonical),
            "lines": canonical.count("\n"),
            "text_z": _pack(canonical),
        }
    return {"version": LEDGER_VERSION,
            "rounds": CELL_ROUNDS,
            "environment": _environment(),
            "cells": entries}


def load_ledger(path: str = DEFAULT_LEDGER) -> dict:
    with open(path) as f:
        ledger = json.load(f)
    if ledger.get("version") != LEDGER_VERSION:
        raise ValueError(
            f"{path} is not a {LEDGER_VERSION} ledger "
            f"(version={ledger.get('version')!r})")
    return ledger


def save_ledger(ledger: dict, path: str = DEFAULT_LEDGER) -> None:
    with open(path, "w") as f:
        json.dump(ledger, f, indent=1, sort_keys=True)
        f.write("\n")


def environment_mismatch(ledger: dict) -> str | None:
    """Why this environment cannot judge the ledger (None = it can).
    Lowered text is a compiler artifact: a different jax version or
    backend legitimately changes it, so the audit refuses to call that
    drift."""
    env = _environment()
    want = ledger.get("environment", {})
    for field in ("jax", "backend", "x64"):
        if field in want and want[field] != env[field]:
            return (f"ledger lowered under {field}={want[field]!r}, "
                    f"running {field}={env[field]!r} — regenerate with "
                    "`audit --rebase` in the pinned environment "
                    "(the audit CLI pins cpu + x64, matching the test "
                    "suite)")
    if want.get("device_count", 0) > env["device_count"]:
        # halo/pod cells build a >=2-device mesh; auditing from a
        # process with fewer devices must read as an environment
        # problem, not as program drift
        return (f"ledger lowered with {want['device_count']} devices, "
                f"only {env['device_count']} visible — run the audit "
                "CLI (it pins 8 virtual CPU devices), or set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    return None


def audit(ledger: dict, keys=None) -> dict:
    """Re-lower every ledger cell and diff.  Returns the audit report:
    ``overall`` is ``pass`` | ``drift`` | ``env-mismatch``; each cell
    record is ``match`` / ``drift`` (with the first divergent HLO line)
    / ``missing`` (registered but not in the ledger) / ``unknown``
    (in the ledger but no longer registered) / ``error``."""
    mismatch = environment_mismatch(ledger)
    if mismatch:
        return {"overall": "env-mismatch", "reason": mismatch,
                "environment": _environment(), "cells": []}
    index = cell_index()
    want = ledger.get("cells", {})
    keys = list(keys) if keys is not None else sorted(
        set(index) | set(want))
    results = []
    for key in keys:
        if key not in want:
            results.append({"cell": key, "status": "missing",
                            "detail": "registered cell not in ledger — "
                                      "run `audit --rebase`"})
            continue
        if key not in index:
            results.append({"cell": key, "status": "unknown",
                            "detail": "ledger cell no longer registered "
                                      "— run `audit --rebase`"})
            continue
        try:
            current = lower_cell(index[key])
        except Exception as exc:  # a cell failing to lower IS a finding
            results.append({"cell": key, "status": "error",
                            "detail": f"{type(exc).__name__}: {exc}"})
            continue
        if program_digest(current) == want[key]["sha256"]:
            results.append({"cell": key, "status": "match"})
            continue
        old = _unpack(want[key]["text_z"])
        div = first_divergence(old, current)
        if not div:
            # digest mismatch but stored text == current text: the
            # ledger's own digest is inconsistent (hand-edited file)
            results.append({
                "cell": key, "status": "drift",
                "first_divergence": div,
                "detail": "ledger digest does not match the ledger's "
                          "own stored text (corrupted entry?) — "
                          "regenerate with `audit --rebase`"})
            continue
        results.append({
            "cell": key, "status": "drift",
            "first_divergence": div,
            "detail": (
                f"lowering drifted at HLO line {div['line']}: "
                f"ledger {div.get('ledger')!r} vs current "
                f"{div.get('current')!r}"),
        })
    bad = [r for r in results if r["status"] != "match"]
    return {"overall": "pass" if not bad else "drift",
            "environment": _environment(),
            "drifted": [r["cell"] for r in bad],
            "cells": results}


def assert_same_program(fn_a, args_a, fn_b, args_b, *, label: str = "",
                        kwargs_a=None, kwargs_b=None) -> None:
    """Assert two jitted calls lower to the identical canonical program
    — the migrated form of the hand-rolled ``lower().as_text()``
    comparisons.  On mismatch the AssertionError names the first
    divergent HLO line."""
    a = canonical_program(fn_a, *args_a, **(kwargs_a or {}))
    b = canonical_program(fn_b, *args_b, **(kwargs_b or {}))
    if a != b:
        div = first_divergence(a, b)
        raise AssertionError(
            f"programs differ{' (' + label + ')' if label else ''} at "
            f"HLO line {div.get('line', '?')}: {div.get('ledger')!r} vs "
            f"{div.get('current')!r}")
