"""Static analysis: jaxpr/HLO rule engine, repo-specific lint, and the
golden-program ledger (docs/ANALYSIS.md).

Three layers, one goal — catch program-level hazards and identity drift
at lint time instead of at benchmark-archaeology time:

- :mod:`~flow_updating_tpu.analysis.rules` — structural checks over
  round-program jaxprs (serializing scatters, fast-path gathers,
  callbacks/collectives inside the round scan, dtype drift, PRNG key
  reuse), run over every kernel's ``round_program`` lowering.
- :mod:`~flow_updating_tpu.analysis.flowlint` — AST rules ruff cannot
  express (numpy in kernels, Python ``if`` on traced values, kernel
  ``round_program`` coverage, bare PRNGKey, bench baseline key
  families).
- :mod:`~flow_updating_tpu.analysis.golden` — the canonical-hashed
  StableHLO ledger of the mode x twin matrix (``GOLDEN_PROGRAMS.json``)
  with drift-naming audit; the safety net ROADMAP item 5's IR refactor
  lowers against.

CLI: ``python -m flow_updating_tpu lint`` and ``... audit``.
"""

from flow_updating_tpu.analysis.flowlint import lint_paths  # noqa: F401
from flow_updating_tpu.analysis.golden import (  # noqa: F401
    assert_same_program,
    audit,
    build_ledger,
    canonical_program,
    load_ledger,
)
from flow_updating_tpu.analysis.rules import (  # noqa: F401
    Finding,
    ProgramContext,
    analyze_program,
    audit_kernels,
)
