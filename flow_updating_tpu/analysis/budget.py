"""Static collective/wire-byte budget verifier.

PR 8 pinned ONE identity — the halo round program's compiled HLO
collective bytes equal the shard plan's own per-round accounting within
±5% — as a single test.  This module generalizes that identity into an
analyzer that runs over the whole kernel matrix and *names the
offending collective* when it breaks:

* every budgeted program (halo ppermute / allgather / overlap) is
  compiled under the pinned analysis environment and its optimized HLO
  walked per collective op, attributing output bytes to ``(op kind,
  enclosing computation, HLO line)`` — the schedule position a finding
  cites;
* the per-round measured bytes (times shard count) are checked against
  ``ShardPlan.collective_bytes_per_round``'s accounting for that wire
  (±5% plus a one-time-prologue slack), so a payload-layout change that
  bends the wire — the compressed-wire work of ROADMAP item 2, per the
  bytes-per-accuracy methodology of arXiv:2506.10607 — must update the
  plan accounting to land;
* any collective of a kind the budget never declared (an
  ``all-to-all`` / ``reduce-scatter`` smuggled in by a resharding, an
  ``all-gather`` in a ppermute schedule) is an *unbudgeted collective*
  finding naming kind, bytes and position — regardless of totals;
* collective-free claims are budgets too: the feature-mesh program
  (PR 10's bit-exactness argument) and every single-device program
  must compile to ZERO collective bytes.

The verdicts ship as a ``flow-updating-budget-report/v1`` manifest
(``audit --budget PATH``) that ``doctor`` judges
(:func:`flow_updating_tpu.obs.health.check_budget`) and ``regress``
gates against a prior manifest (byte growth > 2% fails).
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np

from flow_updating_tpu.obs.profile import (
    _COLLECTIVE_RE,
    _DTYPE_BYTES,
    _SHAPE_RE,
)

#: measured-vs-budget tolerance: the PR-8 bar (one-time prologue
#: collectives are the only slack tolerated)
TOLERANCE_PCT = 5.0
SLACK_BYTES = 4096

_COMPUTATION_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\([^)]*\)"
                             r"\s*->\s*.*\{\s*$")


def hlo_collective_ops(hlo_text: str) -> list:
    """Per-op collective attribution over optimized HLO text: one
    record per collective — ``{kind, bytes, computation, line}`` —
    counted once per async pair (at the ``-done``, whose output is the
    result shape alone), exactly the counting rule of
    ``obs.profile.hlo_collective_bytes``."""
    ops = []
    computation = ""
    for lineno, line in enumerate(hlo_text.splitlines(), start=1):
        mc = _COMPUTATION_RE.match(line)
        if mc:
            computation = mc.group(1)
            continue
        m = _COLLECTIVE_RE.search(line.strip())
        if not m or m.group(3) == "-start":
            continue
        nbytes = 0
        for dt, dims in _SHAPE_RE.findall(m.group(1)):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        ops.append({"kind": m.group(2), "bytes": nbytes,
                    "computation": computation, "line": lineno})
    return ops


@dataclasses.dataclass(frozen=True)
class BudgetCell:
    """One budgeted program: ``build()`` returns ``(fn, args)`` ready
    to lower; ``budget_bytes`` is the planned per-round total across
    all shards (None = attribution-only, kind whitelist still gates);
    ``expected_kinds`` is the declared collective vocabulary."""

    label: str
    build: object
    budget_bytes: int | None
    expected_kinds: frozenset
    num_shards: int = 1
    note: str = ""


def verify_program(cell: BudgetCell, *, tolerance_pct: float =
                   TOLERANCE_PCT, slack: int = SLACK_BYTES) -> dict:
    """Compile one cell and judge its collective bytes against its
    budget.  The record names every op and every violation."""
    try:
        fn, args = cell.build()
        text = fn.lower(*args).compile().as_text()
    except Exception as exc:
        return {"cell": cell.label, "status": "error",
                "detail": f"{type(exc).__name__}: {exc}"}
    ops = hlo_collective_ops(text)
    per_shard = sum(op["bytes"] for op in ops)
    measured = per_shard * cell.num_shards
    unbudgeted = [op for op in ops
                  if op["kind"] not in cell.expected_kinds]
    record = {
        "cell": cell.label,
        "num_shards": cell.num_shards,
        "budget_bytes": cell.budget_bytes,
        "measured_bytes": measured,
        "collective_ops": len(ops),
        "by_kind": _by_kind(ops),
        "ops": ops,
        "expected_kinds": sorted(cell.expected_kinds),
        "note": cell.note,
    }
    problems = []
    for op in unbudgeted:
        problems.append(
            f"unbudgeted {op['kind']} ({op['bytes']} B/shard) at HLO "
            f"line {op['line']} in computation "
            f"{op['computation'] or '<entry>'} — the plan never "
            "declared this collective (unexpected resharding?)")
    if cell.budget_bytes is not None:
        budget = cell.budget_bytes
        lo = budget * (1 - tolerance_pct / 100.0) - slack
        hi = budget * (1 + tolerance_pct / 100.0) + slack
        deviation = ((measured - budget) / budget * 100.0
                     if budget else None)
        record["deviation_pct"] = (round(deviation, 2)
                                   if deviation is not None else None)
        if not (lo <= measured <= hi):
            worst = max(ops, key=lambda op: op["bytes"], default=None)
            cite = (f"; largest: {worst['kind']} {worst['bytes']} "
                    f"B/shard at HLO line {worst['line']}"
                    if worst else "")
            problems.append(
                f"measured {measured} B/round vs budget {budget} "
                f"B/round (±{tolerance_pct}% + {slack} B slack)" + cite)
    record["status"] = "fail" if problems else "pass"
    record["problems"] = problems
    return record


def _by_kind(ops) -> dict:
    out: dict = {}
    for op in ops:
        out[op["kind"]] = out.get(op["kind"], 0) + op["bytes"]
    return out


# ---------------------------------------------------------------------------
# the standard budget matrix

def budget_cells() -> list:
    """The budgeted program matrix: the three halo wires checked
    against the shard plan's own accounting, the pod stencil's psum
    vocabulary, and the two collective-free claims (feature mesh,
    single device)."""
    import jax

    from flow_updating_tpu.models.config import RoundConfig

    cells: list = []

    def _halo_fixture():
        from flow_updating_tpu.parallel import sharded
        from flow_updating_tpu.parallel.mesh import make_mesh
        from flow_updating_tpu.topology.generators import erdos_renyi

        topo = erdos_renyi(257, avg_degree=6.0, seed=7)
        cfg = RoundConfig.fast()
        mesh = make_mesh(8)
        plan = sharded.plan_sharding(topo, 8, partition="bfs")
        db = np.dtype(cfg.jnp_dtype).itemsize
        planned = plan.collective_bytes_per_round(dtype_bytes=db)
        state = sharded.init_plan_state(plan, cfg, mesh)
        return sharded, topo, cfg, mesh, plan, planned, state

    fixture: dict = {}

    def halo_build(mode):
        def build():
            if not fixture:
                fixture["v"] = _halo_fixture()
            sharded, _topo, cfg, mesh, plan, _pl, state = fixture["v"]
            fn, args, _ = sharded.round_program(state, plan, cfg, mesh,
                                                8, halo=mode)
            return fn, args
        return build

    def halo_budget(key):
        if not fixture:
            fixture["v"] = _halo_fixture()
        return fixture["v"][5][key]

    if len(jax.devices()) >= 8:
        for mode, key in (("ppermute", "ppermute_bytes"),
                          ("allgather", "allgather_bytes"),
                          ("overlap", "overlap_bytes")):
            kinds = frozenset({"all-gather"} if mode == "allgather"
                              else {"collective-permute"})
            cells.append(BudgetCell(
                label=f"halo-s8/{mode}",
                build=halo_build(mode),
                budget_bytes=halo_budget(key),
                expected_kinds=kinds, num_shards=8,
                note="plan.collective_bytes_per_round, the PR-8 "
                     "±5% identity"))

    if len(jax.devices()) >= 2:
        def pod_build():
            from flow_updating_tpu.parallel import structured_sharded
            from flow_updating_tpu.parallel.mesh import make_mesh
            from flow_updating_tpu.topology.generators import fat_tree

            topo = fat_tree(4, seed=0)
            cfg = RoundConfig.fast(kernel="node", spmv="structured")
            kern = structured_sharded.PodShardedFatTreeKernel(
                topo, cfg, make_mesh(2))
            fn, args, _ = kern.round_program(kern.init_state(), 8)
            return fn, args
        cells.append(BudgetCell(
            label="pod-s2/structured", build=pod_build,
            budget_bytes=None,
            expected_kinds=frozenset({"all-reduce"}), num_shards=2,
            note="attribution-only: the stencil's psum vocabulary is "
                 "the declared wire; byte totals ride profile "
                 "manifests"))

        def feature_build():
            import jax.numpy as jnp

            from flow_updating_tpu.models.state import init_state
            from flow_updating_tpu.parallel import feature
            from flow_updating_tpu.parallel.mesh import make_mesh2d
            from flow_updating_tpu.topology.generators import erdos_renyi

            topo = erdos_renyi(24, avg_degree=4.0, seed=3)
            cfg = RoundConfig.fast()
            vals = jnp.tile(jnp.asarray(topo.values)[:, None], (1, 4))
            state = init_state(topo, cfg, values=vals)
            fmesh = make_mesh2d(1, 2)
            return feature.run_rounds_feature, (
                state, topo.device_arrays(), cfg, 8, fmesh)
        cells.append(BudgetCell(
            label="feature-s2/sharded", build=feature_build,
            budget_bytes=0, expected_kinds=frozenset(),
            num_shards=2,
            note="PR 10's bit-exactness guarantee: ZERO round-scan "
                 "collectives on the feature mesh"))

    def edge_build():
        from flow_updating_tpu.models.rounds import run_rounds
        from flow_updating_tpu.models.state import init_state
        from flow_updating_tpu.topology.generators import ring

        topo = ring(16, k=2, seed=1)
        cfg = RoundConfig.fast()
        state = init_state(topo, cfg, seed=0)
        return run_rounds, (state, topo.device_arrays(), cfg, 8)
    cells.append(BudgetCell(
        label="edge/single-device", build=edge_build,
        budget_bytes=0, expected_kinds=frozenset(),
        note="single-device programs budget zero collective bytes"))
    return cells


def verify_matrix(cells=None) -> dict:
    """Compile + judge the whole budget matrix; the ``budget`` block of
    the flow-updating-budget-report/v1 manifest."""
    cells = list(cells) if cells is not None else budget_cells()
    results = [verify_program(c) for c in cells]
    bad = [r for r in results
           if r.get("status") in ("fail", "error")]
    return {
        "overall": "pass" if not bad else "fail",
        "tolerance_pct": TOLERANCE_PCT,
        "slack_bytes": SLACK_BYTES,
        "failed": [r["cell"] for r in bad],
        "cells": results,
    }
