"""Semantic invariant prover — protocol theorems checked on the jaxpr.

The structural rule engine (:mod:`flow_updating_tpu.analysis.rules`)
catches *performance hazards*; this module proves *protocol
correctness* properties as dataflow theorems over the round-scan jaxpr,
so the invariants the repo otherwise only samples at runtime (doctor's
trailing-window mass checks, the golden-hash observer tests) hold for
EVERY round of EVERY input by construction:

``ledger-negation`` (antisymmetry pairing)
    Every receive-side write into the flow ledger is a pure NEGATION of
    a wire-derived value (``flow[e] = -msg.flow`` through at most a
    symmetric clamp), and the wire payload itself derives from the flow
    ledger with no literal rescale — the two halves of Flow-Updating's
    ``flow[e] == -flow[rev[e]]`` self-healing argument.  A one-sided
    (positive) wire-to-ledger write, or a wire that ships a scaled copy
    of the ledger, is exactly the mass-leak amplifier the
    ``flow_corruption`` scenario plants — and the adversary cells are
    this prover's built-in positive controls.

``clip-symmetry`` (robust transform at BOTH ends)
    The ``robust='clip'`` clamp must appear on the send-side ledger
    delta AND on the receive-side antisymmetry write, with the same
    literal bound (per edge, not per endpoint).  A clamp at one end
    only lets a Byzantine peer pump the unclamped end past the bound —
    the planted ``clip-at-one-end`` mutation this prover must fail.

``mask-neutrality`` (the topology/padding.py contract)
    Masked writes keep the carried ledger BIT-exactly (the kept branch
    of every ledger-write ``where`` bottoms out at the carried value —
    never a rescaled copy), and every masked fill that directly feeds a
    segment reduction is exactly ``0.0`` (a ``1e-30``-style fill leaks
    mass through every ghost/cohort slot, every round).

``observer-purity``
    Telemetry/field taps ride the scan as ys and must never feed back
    into carried protocol state: the backward slice of the protocol
    carry legs in the observed twin is equation-for-equation the plain
    twin's slice.  This is the dataflow-theorem form of the golden
    "fields-off == plain" hash tests — it also covers fields ON.

Everything here is trace-only (``jax.make_jaxpr`` machinery — nothing
compiles, nothing executes).  The prover drives the same golden-ledger
cells as ``audit`` (:func:`prove_cells`), and each theorem cites the
primitive path of its violation, e.g.
``scan/pjit[_where]/select_n: wire-derived write is not negated``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.analysis import walk

# ---------------------------------------------------------------------------
# inlined dataflow graph over one loop body

#: call-like primitives the inliner makes transparent (their sub-jaxpr
#: is the same program, just wrapped); control-flow loops/branches stay
#: opaque nodes.
_CALL_PRIMS = ("pjit", "closed_call", "core_call", "remat", "remat2",
               "custom_jvp_call", "custom_vjp_call", "custom_vmap_call",
               "checkpoint", "custom_jvp_call_jaxpr")

#: ops through which a value keeps its identity (selection, layout,
#: permutation, dtype width) — the "sign/magnitude-preserving" set of
#: the negation-pairing walk.
_PRESERVING = ("squeeze", "reshape", "broadcast_in_dim", "transpose",
               "convert_element_type", "copy", "slice", "dynamic_slice",
               "gather", "rev", "expand_dims", "device_put")


@dataclasses.dataclass
class _N:
    """One value in the inlined dataflow graph."""

    prim: str                  # producing primitive ('carry'/'arg'/'lit'/
    #                            'const' for leaves)
    ins: tuple = ()            # operand nodes (pred first for select_n)
    lit: object = None         # concrete value for lit/const leaves
    role: str | None = None    # protocol role of a carry leaf
    path: str = ""             # citation: primitive path from the body root
    seq: int = 0               # creation order (topological)
    aval: object = None        # abstract value of the produced output


class BodyGraph:
    """The inlined dataflow graph of one loop body: every call-like
    primitive (pjit-wrapped jnp helpers, custom_* wrappers) is made
    transparent; scans/whiles/conds inside the body stay opaque."""

    def __init__(self, body_jaxpr, *, carry_offset: int, num_consts: int,
                 num_carry: int, roles: dict):
        self.nodes: list = []
        self._env: dict = {}
        self.roles = dict(roles)
        jaxpr = getattr(body_jaxpr, "jaxpr", body_jaxpr)
        consts = getattr(body_jaxpr, "consts", ())
        invars = list(jaxpr.invars)
        role_of_pos = {carry_offset + rel: name
                       for name, rel in roles.items()}
        for i, v in enumerate(invars):
            kind = ("carry" if num_consts <= i < num_consts + num_carry
                    else "arg")
            role = role_of_pos.get(i - num_consts) if kind == "carry" \
                else None
            self._env[id(v)] = self._new(kind, role=role,
                                         aval=walk.aval_of(v))
        for v, c in zip(jaxpr.constvars, consts):
            self._env[id(v)] = self._new("const", lit=c,
                                         aval=walk.aval_of(v))
        self._inline(jaxpr, path=())
        self.carry_in = {r: self._env[id(invars[num_consts + rel
                                              + carry_offset])]
                         for r, rel in roles.items()}
        outvars = list(jaxpr.outvars)
        self.carry_out = {}
        for r, rel in roles.items():
            self.carry_out[r] = self.node_of(
                outvars[carry_offset + rel])
        self.outvars = outvars
        self.num_carry = num_carry
        self.carry_offset = carry_offset

    # -- construction ------------------------------------------------------

    def _new(self, prim, *, ins=(), lit=None, role=None, path="",
             aval=None) -> _N:
        n = _N(prim=prim, ins=tuple(ins), lit=lit, role=role, path=path,
               seq=len(self.nodes), aval=aval)
        self.nodes.append(n)
        return n

    def node_of(self, atom) -> _N:
        node = self._env.get(id(atom))
        if node is not None:
            return node
        # a Literal atom (inline constant)
        val = getattr(atom, "val", None)
        node = self._new("lit", lit=val, aval=walk.aval_of(atom))
        self._env[id(atom)] = node
        return node

    def _inline(self, jaxpr, path: tuple) -> None:
        jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            label = name
            if name == "pjit":
                inner = eqn.params.get("name")
                if inner:
                    label = f"pjit[{inner}]"
            here = path + (label,)
            subs = walk.subjaxprs(eqn)
            if name in _CALL_PRIMS and subs:
                sub = subs[0]
                consts = ()
                closed = next((v for v in eqn.params.values()
                               if getattr(v, "jaxpr", None) is sub), None)
                if closed is not None:
                    consts = getattr(closed, "consts", ())
                inner_invars = list(sub.invars)
                for v, c in zip(sub.constvars, consts):
                    self._env[id(v)] = self._new("const", lit=c,
                                                 aval=walk.aval_of(v))
                # align operands from the END (call conventions prepend
                # consts to the inner invars)
                outer = list(eqn.invars)[-len(inner_invars):] \
                    if inner_invars else []
                offset = len(inner_invars) - len(outer)
                for k, iv in enumerate(inner_invars):
                    if k >= offset:
                        self._env[id(iv)] = self.node_of(outer[k - offset])
                    else:
                        self._env[id(iv)] = self._new(
                            "arg", aval=walk.aval_of(iv))
                self._inline(sub, here)
                for ov, inner_ov in zip(eqn.outvars, sub.outvars):
                    self._env[id(ov)] = self.node_of(inner_ov)
                continue
            ins = tuple(self.node_of(a) for a in eqn.invars)
            for ov in eqn.outvars:
                self._env[id(ov)] = self._new(
                    label if not subs else name, ins=ins,
                    path="/".join(here), aval=walk.aval_of(ov))


def _scalar_lit(node: _N):
    """Concrete scalar value of a lit/const (possibly broadcast /
    converted / negated at trace time), or None."""
    seen = 0
    while node is not None and seen < 16:
        if node.prim in ("lit", "const"):
            v = node.lit
            try:
                arr = np.asarray(v)
            except Exception:
                return None
            if arr.size == 1:
                return arr.reshape(()).item()
            # a broadcast constant plane counts when uniform
            if arr.size and (arr == arr.flat[0]).all():
                return arr.flat[0].item()
            return None
        if node.prim in _PRESERVING or node.prim == "neg":
            flip = node.prim == "neg"
            node = node.ins[0] if node.ins else None
            if node is not None and flip:
                v = _scalar_lit(node)
                return -v if v is not None else None
            seen += 1
            continue
        return None
    return None


# ---------------------------------------------------------------------------
# theorem machinery: write chains, provenance, clamps

_CLAMPS = ("max", "min", "clamp")


def _is_float(node: _N) -> bool:
    dt = getattr(node.aval, "dtype", None)
    try:
        return np.dtype(dt).kind == "f"
    except TypeError:
        return False


def _passthrough_case(graph: BodyGraph, node: _N, base: _N,
                      _depth=0) -> bool:
    """Does ``node`` bottom out at the carried value ``base`` through
    write-preserving structure only (selects keeping one branch, layout
    ops)?  This is the "masked slots keep the ledger bit-exactly" leg
    of mask-neutrality."""
    if _depth > 64:
        return False
    if node is base:
        return True
    if node.prim == "select_n":
        return any(_passthrough_case(graph, c, base, _depth + 1)
                   for c in node.ins[1:])
    if node.prim == "scatter" and node.ins:
        return _passthrough_case(graph, node.ins[0], base, _depth + 1)
    if node.prim in _PRESERVING and node.ins:
        return _passthrough_case(graph, node.ins[0], base, _depth + 1)
    return False


def write_chain(graph: BodyGraph, out: _N, base: _N) -> tuple:
    """Decompose a carry leg's out-node into its masked writes.

    Returns ``(writes, passthrough_ok)`` where each write is ``(value
    node, path)`` — the non-carried branch of a ``select_n`` (or the
    updates operand of an overwrite scatter) along the chain from the
    out-node back to the carried-in value — and ``passthrough_ok`` says
    the kept branch bottoms out at the carried value itself (bit-exact
    masked slots; False = a rescaled "keep" branch, the mask-neutrality
    violation)."""
    writes: list = []
    ok = True
    seen: set = set()
    stack = [out]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        if node is base:
            continue
        if node.prim == "select_n":
            cont = [c for c in node.ins[1:]
                    if _passthrough_case(graph, c, base)]
            if cont:
                stack.extend(cont)
                writes.extend((c, node.path) for c in node.ins[1:]
                              if not _passthrough_case(graph, c, base))
            else:
                ok = False
                writes.extend((c, node.path) for c in node.ins[1:])
            continue
        if node.prim == "scatter" and len(node.ins) >= 3:
            stack.append(node.ins[0])
            writes.append((node.ins[2], node.path))
            continue
        if node.prim in _PRESERVING and node.ins:
            stack.append(node.ins[0])
            continue
        # the leg is wholly rewritten (no masked keep) — treat the whole
        # expression as one write; passthrough does not apply
        writes.append((node, node.path))
    return writes, ok


@dataclasses.dataclass
class Prov:
    """Provenance of a value along sign/magnitude-preserving paths:
    which protocol-role carried values it IS (a selection / permutation
    / clamp of), with what sign, plus the float clamp bounds and any
    literal rescales met on the way."""

    origins: set = dataclasses.field(default_factory=set)  # (role, sign)
    clamps: set = dataclasses.field(default_factory=set)   # |bound|
    rescales: list = dataclasses.field(default_factory=list)  # (k, path)
    opaque: bool = False


def provenance(graph: BodyGraph, node: _N, _memo=None, _depth=0) -> Prov:
    """Walk backward through preserving ops only; arithmetic that mixes
    values (add/sub/div of two data operands) makes the result opaque —
    provenance answers "is this value still role X's value?", not "does
    it depend on X"."""
    if _memo is None:
        _memo = {}
    if id(node) in _memo:
        return _memo[id(node)]
    out = Prov()
    _memo[id(node)] = out
    if _depth > 256:
        out.opaque = True
        return out
    if node.role is not None:
        out.origins.add((node.role, +1))
        return out
    if node.prim in ("lit", "const", "arg", "carry"):
        return out

    def merge(p: Prov, flip=False):
        out.origins |= {(r, -s if flip else s) for r, s in p.origins}
        out.clamps |= p.clamps
        out.rescales.extend(p.rescales)
        out.opaque = out.opaque or p.opaque

    if node.prim == "neg":
        merge(provenance(graph, node.ins[0], _memo, _depth + 1),
              flip=True)
        return out
    if node.prim == "select_n":
        for c in node.ins[1:]:
            merge(provenance(graph, c, _memo, _depth + 1))
        return out
    if node.prim in _CLAMPS:
        # max/min against a literal bound = one half of a clamp; the
        # lax.clamp primitive is (lo, x, hi)
        data, bounds = [], []
        for c in node.ins:
            v = _scalar_lit(c)
            (bounds if v is not None else data).append((c, v))
        if _is_float(node):
            for _, v in bounds:
                out.clamps.add(abs(v))
        for c, _ in data:
            merge(provenance(graph, c, _memo, _depth + 1))
        if not data:
            out.opaque = True
        return out
    if node.prim == "mul":
        lits = [(c, _scalar_lit(c)) for c in node.ins]
        data = []
        for c, v in lits:
            if v is None:
                size = getattr(getattr(c, "aval", None), "size", None)
                if size == 1:
                    # a TRACED scalar multiplier rescales uniformly —
                    # the adversary corrupt_gain form (masks are
                    # elementwise planes, never scalars)
                    out.rescales.append(("<traced scalar>", node.path))
                else:
                    data.append(c)
                continue
            if v == 1 or v == -1:
                continue
            out.rescales.append((v, node.path))
        flip = any(v == -1 for _, v in lits)
        if not data:
            return out
        for c in data:
            # two data operands = masked routing (the Beneš butterfly:
            # value * mask) — origins union, signs kept
            merge(provenance(graph, c, _memo, _depth + 1), flip=flip)
        return out
    if node.prim in _PRESERVING or node.prim in ("concatenate", "pad"):
        for c in node.ins:
            merge(provenance(graph, c, _memo, _depth + 1))
        return out
    out.opaque = True
    return out


def _contains_prim(node: _N, prim: str, limit: int = 2048) -> bool:
    """Does ``node``'s backward cone (all ops) contain ``prim``?"""
    seen: set = set()
    stack = [node]
    while stack and len(seen) < limit:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if n.prim == prim:
            return True
        stack.extend(n.ins)
    return False


def _forward_index(graph: BodyGraph) -> dict:
    """node -> direct consumer nodes."""
    consumers: dict = {}
    for n in graph.nodes:
        for c in n.ins:
            consumers.setdefault(id(c), []).append(n)
    return consumers


def _reaches(consumers: dict, src: _N, dst: _N) -> bool:
    seen = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n is dst:
            return True
        if id(n) in seen:
            continue
        seen.add(id(n))
        stack.extend(consumers.get(id(n), ()))
    return False


# ---------------------------------------------------------------------------
# the theorems

ROLE_FIELDS = ("flow", "buf_flow", "pending_flow")
WIRE_ROLES = ("buf_flow", "pending_flow")
#: protocol-state legs whose defining slices observer twins must not
#: perturb (the purity theorem's quantifier)
PURITY_FIELDS = ("flow", "est", "value", "buf_flow", "buf_est",
                 "pending_flow", "pending_est")


@dataclasses.dataclass(frozen=True)
class Violation:
    theorem: str
    message: str
    where: str = ""
    program: str = ""

    def format(self) -> str:
        loc = f" at {self.where}" if self.where else ""
        prog = f"[{self.program}] " if self.program else ""
        return f"{prog}{self.theorem}{loc}: {self.message}"


def prove_antisymmetry(graph: BodyGraph, *, program: str = "",
                       expect_clip: bool | None = None) -> list:
    """The negation-pairing + clip-symmetry + masked-keep theorems on
    one round-loop body graph.  ``expect_clip`` pins the robust mode
    when the caller knows it (golden cell keys carry it); None infers
    nothing and only symmetry is judged."""
    out: list = []
    flow_in = graph.carry_in.get("flow")
    flow_out = graph.carry_out.get("flow")
    if flow_in is None or flow_out is None:
        return out
    wires_in = [graph.carry_in[r] for r in WIRE_ROLES
                if r in graph.carry_in]

    writes, keep_ok = write_chain(graph, flow_out, flow_in)
    if not keep_ok:
        out.append(Violation(
            "mask-neutrality", program=program,
            message="a masked flow-ledger write does not keep the "
                    "carried ledger bit-exactly on its kept branch "
                    "(non-firing slots must be untouched — the "
                    "topology/padding.py mass-neutral contract)"))
    memo: dict = {}
    recv_negs, recv_clamps = [], set()
    for value, where in writes:
        p = provenance(graph, value, memo)
        wire_hits = {(r, s) for r, s in p.origins if r in WIRE_ROLES}
        if not wire_hits:
            continue
        signs = {s for _, s in wire_hits}
        if signs == {-1}:
            recv_negs.append((value, where))
            recv_clamps |= p.clamps
        else:
            out.append(Violation(
                "ledger-negation", where=where, program=program,
                message="wire-derived flow-ledger write is not a pure "
                        "negation (one-sided write: the receiver "
                        "installs +msg.flow, so the edge pair no "
                        "longer cancels and mass leaks)"))
    if not recv_negs and wires_in:
        # a flow ledger fed by wire buffers must somewhere apply the
        # antisymmetry write; a program with wire roles but no negated
        # receive write has lost the pairing entirely
        out.append(Violation(
            "ledger-negation", program=program,
            message="no negated wire-to-ledger write found: the "
                    "antisymmetry receive write (flow[e] = -msg.flow) "
                    "is missing from the round body"))

    # -- wire integrity: the payload written into the ring buffer IS the
    # ledger (no literal rescale on any reachable branch)
    wire_out = graph.carry_out.get("buf_flow")
    wire_in = graph.carry_in.get("buf_flow")
    if wire_out is not None and wire_in is not None:
        w_writes, w_keep = write_chain(graph, wire_out, wire_in)
        if not w_keep:
            out.append(Violation(
                "mask-neutrality", program=program,
                message="a masked wire-buffer write does not keep the "
                        "carried buffer bit-exactly on its kept branch"))
        ledger_hit = in_kernel = False
        for value, where in w_writes:
            if _contains_prim(value, "pallas_call"):
                # the single-kernel Pallas form merges the delivery
                # INSIDE pallas_call (receiver-pull between the DMA
                # start and wait) — an analyzability boundary, not a
                # violation; the receive-negation theorem above still
                # sees the XLA half
                in_kernel = True
                continue
            p = provenance(graph, value, memo)
            if any(r == "flow" for r, _ in p.origins):
                ledger_hit = True
                for k, rp in p.rescales:
                    out.append(Violation(
                        "wire-integrity", where=rp or where,
                        program=program,
                        message=f"wire payload carries the flow ledger "
                                f"rescaled by literal {k!r} — the "
                                "receiver's antisymmetry write can no "
                                "longer cancel the sender's ledger "
                                "(the flow_corruption amplifier)"))
        if w_writes and not ledger_hit and not in_kernel:
            out.append(Violation(
                "wire-integrity", program=program,
                message="no wire-buffer write derives from the flow "
                        "ledger along a value-preserving path — the "
                        "wire does not carry the ledger"))

    # -- clip symmetry: the robust clamp must bound BOTH the send-side
    # ledger delta and the receive-side antisymmetry write, with equal
    # literal bounds (per edge, not per endpoint)
    consumers = _forward_index(graph)
    fire_clamps = set()
    for n in graph.nodes:
        if n.prim not in _CLAMPS or not _is_float(n):
            continue
        bounds = {abs(v) for v in
                  (_scalar_lit(c) for c in n.ins) if v is not None}
        if not bounds:
            continue
        if _reaches(consumers, n, flow_out):
            p = provenance(graph, n, memo)
            if {(r, s) for r, s in p.origins if r in WIRE_ROLES}:
                continue       # the receive-side clamp, counted above
            fire_clamps |= bounds
    if fire_clamps and not recv_clamps:
        out.append(Violation(
            "clip-symmetry", program=program,
            message=f"flow clamp bound(s) {sorted(fire_clamps)} applied "
                    "on the send-side ledger delta but NOT on the "
                    "receive-side antisymmetry write (clip at one end "
                    "only — the unclamped end can be pumped past the "
                    "bound)"))
    if recv_clamps and not fire_clamps:
        out.append(Violation(
            "clip-symmetry", program=program,
            message=f"flow clamp bound(s) {sorted(recv_clamps)} applied "
                    "on the receive-side write but NOT on the "
                    "send-side ledger delta (clip at one end only)"))
    if fire_clamps and recv_clamps and fire_clamps != recv_clamps:
        out.append(Violation(
            "clip-symmetry", program=program,
            message=f"send-side clamp bounds {sorted(fire_clamps)} != "
                    f"receive-side bounds {sorted(recv_clamps)} — the "
                    "robust transform must be the same at both ends"))
    if expect_clip is True and not (fire_clamps or recv_clamps):
        out.append(Violation(
            "clip-symmetry", program=program,
            message="robust='clip' program lowered without any float "
                    "clamp on the flow-ledger path"))
    if expect_clip is False and (fire_clamps | recv_clamps):
        out.append(Violation(
            "clip-symmetry", program=program,
            message=f"robust='none' program clamps the flow ledger at "
                    f"{sorted(fire_clamps | recv_clamps)} — the plain "
                    "lowering must not bound flows"))
    return out


#: reduction sinks of the masked-fill theorem
_REDUCTIONS = ("reduce_sum", "dot_general", "scatter-add")


def prove_masked_fills(graph: BodyGraph, *, program: str = "") -> list:
    """Every select fill / pad value that DIRECTLY feeds a segment
    reduction must be exactly 0.0: a near-zero fill (1e-30) contributes
    to every masked slot of every reduction, every round — the slow
    mass leak the padding contract exists to exclude."""
    out = []
    direct_src: dict = {}
    for n in graph.nodes:
        if n.prim in _REDUCTIONS:
            stack = list(n.ins)
            depth = 0
            while stack and depth < 512:
                depth += 1
                c = stack.pop()
                if c.prim in _PRESERVING or c.prim == "concatenate":
                    stack.extend(c.ins)
                elif c.prim == "select_n":
                    direct_src.setdefault(id(c), (c, n))
    for c, sink in direct_src.values():
        if not _is_float(c):
            continue
        for case in c.ins[1:]:
            v = _scalar_lit(case)
            if v is not None and v != 0.0:
                out.append(Violation(
                    "mask-neutrality", where=c.path, program=program,
                    message=f"masked fill {v!r} feeds a {sink.prim} "
                            "reduction — masked contributions must be "
                            "exactly 0.0 (topology/padding.py contract)"))
    return out


def carry_slice_signature(graph: BodyGraph, legs) -> list:
    """Ordered (prim, shape, dtype) signature of the backward slice of
    the given carry-leg out-nodes — the purity theorem's object."""
    seen: set = set()
    stack = [graph.carry_out[r] for r in legs if r in graph.carry_out]
    keep = []
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if n.prim not in ("lit", "const", "arg", "carry"):
            keep.append(n)
        stack.extend(n.ins)
    keep.sort(key=lambda n: n.seq)
    sig = []
    for n in keep:
        aval = n.aval
        sig.append((n.prim,
                    tuple(getattr(aval, "shape", ()) or ()),
                    str(getattr(aval, "dtype", "?"))))
    return sig


def prove_observer_purity(observed: BodyGraph, plain: BodyGraph, *,
                          program: str = "") -> list:
    """The observed twin's protocol-state carry slices must match the
    plain twin's equation-for-equation: an observer tap that feeds back
    into carried state grows the slice, and the first extra primitive
    is the citation."""
    legs = [r for r in PURITY_FIELDS
            if r in observed.carry_out and r in plain.carry_out]
    if not legs:
        legs = None
    if legs is None:
        n = min(observed.num_carry, plain.num_carry)
        obs_sig = _full_carry_signature(observed, n)
        plain_sig = _full_carry_signature(plain, n)
    else:
        obs_sig = carry_slice_signature(observed, legs)
        plain_sig = carry_slice_signature(plain, legs)
    if obs_sig == plain_sig:
        return []
    # order-insensitive fallback: CSE/tracing may reorder independent
    # equations without changing the slice's contents
    from collections import Counter

    co, cp = Counter(obs_sig), Counter(plain_sig)
    if co == cp:
        return []
    extra = list((co - cp).elements())
    missing = list((cp - co).elements())
    msg = []
    if extra:
        msg.append(f"observed slice grows {extra[:3]!r}")
    if missing:
        msg.append(f"observed slice loses {missing[:3]!r}")
    return [Violation(
        "observer-purity", program=program,
        message="protocol-state carry slice differs from the plain "
                "twin's (" + "; ".join(msg) + f"; plain {len(plain_sig)}"
                f" vs observed {len(obs_sig)} slice equations) — "
                "observer taps must ride the scan as ys only")]


def _full_carry_signature(graph: BodyGraph, n_legs: int) -> list:
    class _G:
        carry_out = {i: graph.node_of(graph.outvars[graph.carry_offset
                                                    + i])
                     for i in range(n_legs)}
    g = _G()
    g.nodes = graph.nodes
    return carry_slice_signature(g, list(range(n_legs)))


# ---------------------------------------------------------------------------
# locating the round loop + roles inside a traced program

def role_indices(state) -> dict | None:
    """role -> position among the flattened leaves of ``state`` (the
    scan carry order), for every protocol-state field present."""
    import jax.tree_util as jtu

    try:
        flat = jtu.tree_flatten_with_path(state)[0]
    except Exception:
        return None
    idx: dict = {}
    for i, (path, _leaf) in enumerate(flat):
        name = str(path[-1]) if path else ""
        name = name.strip(".")
        for field in set(ROLE_FIELDS) | set(PURITY_FIELDS):
            if name == field:
                idx[field] = i
    return idx or None


def find_state(args):
    """The protocol-state object inside a cell's argument tuple: the
    first pytree node exposing the ledger + wire fields (the
    FlowUpdatingState duck type, chunked window included)."""
    stack = list(args)
    while stack:
        x = stack.pop(0)
        if hasattr(x, "state") and hasattr(getattr(x, "state"), "flow"):
            # ChunkedState: the chunk-major leaves shadow the window's
            # field names; the round loop's carry is the one-chunk
            # working window
            return x.state
        if hasattr(x, "flow") and hasattr(x, "buf_flow"):
            return x
        if isinstance(x, (tuple, list)):
            stack.extend(x)
    return None


def _iter_loops(closed_jaxpr, depth=0, path=()):
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        here = path + (name,)
        if name in walk.LOOP_PRIMS:
            yield eqn, depth, here
        inner_depth = depth + (1 if name in walk.LOOP_PRIMS else 0)
        for sub in walk.subjaxprs(eqn):
            yield from _iter_loops(sub, inner_depth, here)


def _loop_layout(eqn):
    """(body, num_consts, num_carry) of a scan/while eqn, body as the
    closed jaxpr whose invars follow consts+carry(+xs)."""
    if eqn.primitive.name == "scan":
        return (eqn.params["jaxpr"], eqn.params.get("num_consts", 0),
                eqn.params.get("num_carry", 0))
    body = eqn.params.get("body_jaxpr")
    jaxpr = getattr(body, "jaxpr", body)
    nk = len(jaxpr.outvars)
    return body, len(jaxpr.invars) - nk, nk


def _avals_match(body, num_consts, offset, roles, state) -> bool:
    import jax

    leaves = jax.tree_util.tree_flatten(state)[0]
    jaxpr = getattr(body, "jaxpr", body)
    invars = list(jaxpr.invars)
    for role, rel in roles.items():
        pos = num_consts + offset + rel
        if pos >= len(invars):
            return False
        aval = walk.aval_of(invars[pos])
        leaf = leaves[rel]
        got = tuple(getattr(aval, "shape", ()) or ())
        want = tuple(leaf.shape)
        # sharded programs carry the PER-SHARD block inside shard_map:
        # the global leaf's leading shard axis is stripped in the body
        if got != want and got != want[1:]:
            return False
    return True


def find_round_loop(closed_jaxpr, roles: dict, state):
    """Locate the round loop: the deepest scan/while whose carry
    contains the protocol-state leaves (shape-matched at the role
    positions, at some carry offset).  Returns ``(eqn, offset)`` or
    ``None``."""
    best = None
    for eqn, depth, _path in _iter_loops(closed_jaxpr):
        body, nc, nk = _loop_layout(eqn)
        max_rel = max(roles.values())
        for offset in range(0, max(nk - max_rel, 0)):
            if _avals_match(body, nc, offset, roles, state):
                key = (depth, -offset)
                if best is None or key > best[0]:
                    best = (key, eqn, offset)
                break
    if best is None:
        return None
    return best[1], best[2]


def body_graph(eqn, offset: int, roles: dict) -> BodyGraph:
    body, nc, nk = _loop_layout(eqn)
    return BodyGraph(body, carry_offset=offset, num_consts=nc,
                     num_carry=nk, roles=roles)


def trace_program(fn, args, kwargs=None):
    """Closed jaxpr of a jit-wrapped call (trace only, no compile)."""
    kwargs = kwargs or {}
    tracer = getattr(fn, "trace", None)
    if tracer is not None:
        return tracer(*args, **kwargs).jaxpr
    import jax

    return jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)


# ---------------------------------------------------------------------------
# the golden-cell driver

@dataclasses.dataclass
class CellProof:
    cell: str
    status: str          # proved | violated | expected-violation |
    #                      inapplicable | error
    violations: list = dataclasses.field(default_factory=list)
    detail: str = ""

    def to_jsonable(self) -> dict:
        return {"cell": self.cell, "status": self.status,
                "detail": self.detail,
                "violations": [v.format() for v in self.violations]}


#: cells planted with a wire adversary ARE the prover's positive
#: controls: their expected theorem violations, by key fragment
_EXPECTED = {"adv=corrupt": ("wire-integrity",)}


def _expected_violations(key: str) -> tuple:
    for frag, theorems in _EXPECTED.items():
        if frag in key:
            return theorems
    return ()


def prove_cell(cell, *, plain_graphs: dict | None = None) -> CellProof:
    """Run every applicable theorem over one golden-ledger cell."""
    try:
        fn, args, kwargs = cell.build()
        state = find_state(args)
        roles = role_indices(state) if state is not None else None
        if not roles or "flow" not in roles:
            detail = ("no per-edge flow ledger in the carried state "
                      "(node-collapsed kernel) — antisymmetry holds by "
                      "algebraic construction there")
            if "banded-fused" in cell.key or "banded_fused" in cell.key:
                # the one-kernel round is an EXPLICIT analyzability
                # boundary, the pallas_halo DMA-merge precedent: fire,
                # band delivery and ledger merge execute inside
                # pallas_call, where the dataflow prover cannot follow
                # — its semantics are pinned instead by the bit-parity
                # suite (tests/test_pallas_round.py: fused == unfused
                # banded executor == edge kernel after unpermutation)
                detail += (
                    "; fused-round cells additionally keep their "
                    "delivery/merge INSIDE pallas_call (ops/"
                    "pallas_round.py) — a recognized analyzability "
                    "boundary like the pallas halo DMA merge, covered "
                    "by bit-exactness tests instead of the prover")
            return CellProof(cell.key, "inapplicable", detail=detail)
        jx = trace_program(fn, args, kwargs)
        loc = find_round_loop(jx, roles, state)
        if loc is None:
            return CellProof(cell.key, "error",
                             detail="round loop not located in the "
                                    "traced program")
        graph = body_graph(loc[0], loc[1], roles)
    except Exception as exc:
        return CellProof(cell.key, "error",
                         detail=f"{type(exc).__name__}: {exc}")
    expect_clip = None
    if "/robust=clip/" in cell.key or "robust=clip" in cell.key:
        expect_clip = True
    elif "robust=none" in cell.key:
        expect_clip = False
    violations = prove_antisymmetry(graph, program=cell.key,
                                    expect_clip=expect_clip)
    violations += prove_masked_fills(graph, program=cell.key)
    if plain_graphs is not None:
        for twin in ("telemetry", "fields"):
            if f"/{twin}/" not in cell.key:
                continue
            plain_key = cell.key.replace(f"/{twin}/", "/plain/")
            plain = plain_graphs.get(plain_key)
            if plain is not None:
                violations += prove_observer_purity(
                    graph, plain, program=cell.key)
    expected = _expected_violations(cell.key)
    if expected:
        hit = {v.theorem for v in violations}
        if set(expected) <= hit:
            spurious = [v for v in violations
                        if v.theorem not in expected]
            if spurious:
                return CellProof(cell.key, "violated", spurious)
            return CellProof(
                cell.key, "expected-violation", violations,
                detail="planted adversary correctly detected "
                       f"({', '.join(expected)})")
        return CellProof(
            cell.key, "violated",
            [Violation("positive-control", program=cell.key,
                       message=f"adversary cell must trip "
                               f"{expected} but the prover found "
                               f"{sorted(hit) or 'nothing'}")])
    if violations:
        return CellProof(cell.key, "violated", violations)
    return CellProof(cell.key, "proved",
                     detail="antisymmetry pairing, clip symmetry, "
                            "mask neutrality"
                            + (", observer purity"
                               if plain_graphs is not None
                               and ("/telemetry/" in cell.key
                                    or "/fields/" in cell.key)
                               else ""))


def prove_cells(keys=None) -> list:
    """Prove every golden-ledger cell (or the ``keys`` subset).
    Trace-only: the whole matrix proves in well under the audit's
    lowering time."""
    from flow_updating_tpu.analysis import golden

    index = golden.cell_index()
    keys = list(keys) if keys is not None else list(index)
    # build plain-twin graphs first (purity pairs against them)
    plain_graphs: dict = {}
    for key in keys:
        if "/plain/" not in key:
            continue
        cell = index[key]
        try:
            fn, args, kwargs = cell.build()
            state = find_state(args)
            roles = role_indices(state) if state is not None else None
            if not roles:
                continue
            jx = trace_program(fn, args, kwargs)
            loc = find_round_loop(jx, roles, state)
            if loc is not None:
                plain_graphs[key] = body_graph(loc[0], loc[1], roles)
        except Exception:
            continue
    return [prove_cell(index[k], plain_graphs=plain_graphs)
            for k in keys]


def summarize(proofs) -> dict:
    by = {}
    for p in proofs:
        by.setdefault(p.status, []).append(p.cell)
    return {
        "overall": ("fail" if any(p.status in ("violated", "error")
                                  for p in proofs) else "pass"),
        "counts": {k: len(v) for k, v in by.items()},
        "violated": by.get("violated", []) + by.get("error", []),
        "proofs": [p.to_jsonable() for p in proofs],
    }
