"""Host-mirror aliasing analysis — the PR-13 zero-copy race class,
mechanized.

The bug class: an engine keeps *host mirrors* (long-lived numpy arrays
mutated in place by event bookkeeping — ``self._deg[u] -= 1``) and
builds *device leaves* from them.  ``jnp.asarray`` / ``jax.device_put``
on CPU may alias the numpy buffer zero-copy, so a later in-place mirror
edit races the functional device edit of the same event —
nondeterministic double-application that five PRs of round-trip tests
never caught (the ``restore_checkpoint`` incident, fixed in PR 13 with
``jnp.array``, which always copies).  This module closes the class from
both ends:

* **static** — the ``device-from-mirror`` flowlint rule: an AST +
  dataflow pass flagging zero-copy device-array construction over a
  mutated host mirror, both directly (``jnp.asarray(self._deg)``) and
  one call deep (passing ``self._deg`` into a helper whose parameter
  feeds ``jnp.asarray`` — the exact historical shape);
* **runtime** — :func:`assert_no_shared_mirrors`, an
  ``np.shares_memory`` sweep of every device leaf against every host
  mirror, wired into the restore/recover paths of ``ServiceEngine`` /
  ``QueryFabric`` and surfaced to ``doctor`` through the service
  block's ``mirror_probe`` record.

The documented remedy is always the same: build device leaves with
``jnp.array`` (copies), or ``.copy()`` the mirror first.
"""

from __future__ import annotations

import ast

import numpy as np

RULE = "device-from-mirror"
RULE_DOC = ("no zero-copy device arrays (jnp.asarray/device_put) over "
            "in-place-mutated host mirrors — use jnp.array (copies)")

#: callables that may alias a numpy buffer zero-copy on CPU
_ZERO_COPY_CALLS = ("asarray", "device_put")


def _attr_tail(node) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _self_attr(node) -> str | None:
    """``self.X`` -> ``X`` (the mirror name), else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_zero_copy_call(call: ast.Call) -> bool:
    """``jnp.asarray(...)`` / ``jax.device_put(...)`` — the forms that
    may alias on CPU.  ``jnp.array`` copies and is the remedy."""
    return _attr_tail(call.func) in _ZERO_COPY_CALLS


def _mutated_attrs(cls: ast.ClassDef) -> set:
    """Attribute names the class mutates IN PLACE: subscript stores /
    subscript aug-assigns on ``self.X``, whole-array aug-assigns
    (``self.X += delta`` — ndarray ``__iadd__`` edits the buffer), and
    ``out=self.X`` keywords — the host-mirror bookkeeping edits."""
    out: set = set()
    for node in ast.walk(cls):
        tgt = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in tgts:
                if isinstance(t, ast.Subscript):
                    tgt = _self_attr(t.value)
                elif isinstance(node, ast.AugAssign) \
                        and isinstance(t, ast.Attribute):
                    tgt = _self_attr(t)
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "out":
                    tgt = _self_attr(kw.value)
        if tgt:
            out.add(tgt)
    return out


def _zero_copy_params(fn) -> set:
    """Parameter names of ``fn`` that flow BARE into a zero-copy device
    construction (directly, or through a trivial ``x = p`` alias)."""
    params = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                              + fn.args.kwonlyargs)}
    alias = {p: p for p in params}
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Name)
                and node.value.id in params):
            alias[node.targets[0].id] = node.value.id
    hits: set = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call) and _is_zero_copy_call(node)):
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                root = alias.get(arg.id)
                if root in params:
                    hits.add(root)
    return hits


def lint_device_from_mirror(mod):
    """The flowlint pass (registered as ``device-from-mirror`` in
    :mod:`flow_updating_tpu.analysis.flowlint`).  ``mod`` is flowlint's
    parsed ``_Module``."""
    from flow_updating_tpu.analysis.flowlint import LintFinding

    # module-local function defs, for the one-call-deep check
    fns = {n.name: n for n in ast.walk(mod.tree)
           if isinstance(n, ast.FunctionDef)}
    zero_copy_cache: dict = {}

    def zc_params(name: str) -> set:
        if name not in zero_copy_cache:
            fn = fns.get(name)
            zero_copy_cache[name] = _zero_copy_params(fn) if fn else set()
        return zero_copy_cache[name]

    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        mutated = _mutated_attrs(cls)
        if not mutated:
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            # direct: jnp.asarray(self.X) over a mutated mirror
            if _is_zero_copy_call(node) and node.args:
                attr = _self_attr(node.args[0])
                if attr in mutated:
                    yield LintFinding(
                        RULE, mod.path, node.lineno, node.col_offset,
                        f"zero-copy `{_attr_tail(node.func)}` over host "
                        f"mirror `self.{attr}` (mutated in place by "
                        f"`{cls.name}`) — on CPU the device leaf "
                        "aliases the numpy buffer and later mirror "
                        "edits race the device state; build it with "
                        "jnp.array (copies)")
                continue
            # one call deep: helper(self.X, ...) whose parameter feeds
            # jnp.asarray — the historical restore_checkpoint shape
            callee = node.func.id if isinstance(node.func, ast.Name) \
                else None
            if callee not in fns:
                continue
            fn = fns[callee]
            pos_params = [a.arg for a in fn.args.posonlyargs
                          + fn.args.args]
            for k, arg in enumerate(node.args):
                attr = _self_attr(arg)
                if attr not in mutated or k >= len(pos_params):
                    continue
                if pos_params[k] in zc_params(callee):
                    yield LintFinding(
                        RULE, mod.path, node.lineno, node.col_offset,
                        f"host mirror `self.{attr}` (mutated in place "
                        f"by `{cls.name}`) reaches a zero-copy "
                        f"jnp.asarray/device_put via parameter "
                        f"`{pos_params[k]}` of `{callee}` — the PR-13 "
                        "restore race; copy with jnp.array inside the "
                        "helper or pass a .copy()")


# ---------------------------------------------------------------------------
# runtime probe

def _host_mirrors(obj) -> dict:
    """name -> numpy mirror, over the instance's own attributes."""
    out = {}
    for name, v in vars(obj).items():
        if isinstance(v, np.ndarray):
            out[name] = v
    return out


def _device_leaves(obj):
    """(label, leaf) pairs for every device-array leaf of the engine's
    state + topology pytrees."""
    import jax

    for attr in ("state", "arrays"):
        tree = getattr(obj, attr, None)
        if tree is None:
            continue
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in flat:
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                yield f"{attr}{jax.tree_util.keystr(path)}", leaf


def shared_mirror_report(engine) -> dict:
    """``np.shares_memory`` sweep of every device leaf against every
    host mirror of ``engine`` (a ``ServiceEngine``, or a ``QueryFabric``
    — probed through its ``svc``).  Returns the ``mirror_probe`` record
    the service manifest embeds: ``{"checked": n_pairs, "shared":
    [{"leaf", "mirror"}, ...]}`` — ``shared`` must be empty."""
    import jax

    target = getattr(engine, "svc", engine)
    if jax.default_backend() != "cpu":
        # accelerator backends always copy host buffers to device
        # memory — the zero-copy class cannot exist, and np.asarray on
        # every leaf would cost a real device->host transfer
        return {"checked": 0, "shared": [],
                "skipped": "non-cpu backend (host buffers are copied)"}
    mirrors = _host_mirrors(target)
    shared, checked = [], 0
    for label, leaf in _device_leaves(target):
        try:
            view = np.asarray(leaf)
        except Exception:
            continue
        for name, mirror in mirrors.items():
            checked += 1
            try:
                if np.shares_memory(view, mirror):
                    shared.append({"leaf": label, "mirror": name})
            except Exception:
                continue
    return {"checked": checked, "shared": shared}


def assert_no_shared_mirrors(engine) -> None:
    """Raise if any device leaf aliases a host mirror — wired into the
    ``ServiceEngine`` / ``QueryFabric`` restore and recover paths so a
    reintroduced zero-copy build fails the moment it is constructed,
    not rounds later as a flaky double-applied event."""
    rep = shared_mirror_report(engine)
    if rep["shared"]:
        pairs = ", ".join(f"{s['leaf']}<->{s['mirror']}"
                          for s in rep["shared"])
        raise AssertionError(
            f"device leaves alias in-place-mutated host mirrors "
            f"({pairs}) — zero-copy jnp.asarray over a live numpy "
            "mirror; build device leaves with jnp.array (copies). "
            "See docs/ANALYSIS.md (device-from-mirror) and the PR-13 "
            "restore_checkpoint race.")
