"""Jaxpr traversal utilities — the substrate of the rule engine.

Everything in :mod:`flow_updating_tpu.analysis.rules` is a pass over the
recursive jaxpr structure jax builds for a round program: equations
nested inside ``pjit`` / ``scan`` / ``while`` / ``cond`` / ``shard_map``
/ ``custom_*`` bodies.  This module owns the one traversal all rules
share, so a rule is only the predicate, never the plumbing:

- :func:`iter_sites` — depth-first iteration over EVERY equation in a
  closed jaxpr, each wrapped in an :class:`EqnSite` carrying its loop
  depth (how many enclosing ``scan``/``while`` bodies — "inside the
  round scan" is ``loop_depth >= 1``) and the primitive path from the
  root (the location a finding cites).
- :func:`jaxpr_program` — trace a ``round_program``-convention callable
  (``(fn, full_args, n_dynamic)`` with the static args TRAILING, the
  contract every kernel's hook follows) into the closed jaxpr the rules
  inspect.  Tracing only: nothing compiles, nothing executes.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Iterator

# Control-flow primitives whose bodies execute repeatedly: an equation
# inside one runs once per round (or per inner step), which is what the
# "inside the round scan" rules scope to.
LOOP_PRIMS = ("scan", "while")
# Branch-style primitives: bodies are alternatives, not repetitions.
BRANCH_PRIMS = ("cond",)


@dataclasses.dataclass(frozen=True)
class EqnSite:
    """One equation plus where the traversal found it."""

    eqn: object                 # jax.core.JaxprEqn
    loop_depth: int             # enclosing scan/while bodies
    path: tuple                 # primitive names root -> here (inclusive)

    @property
    def prim(self) -> str:
        return self.eqn.primitive.name

    @property
    def where(self) -> str:
        """Human-citable location, e.g. ``pjit/scan/scatter-add``."""
        return "/".join(self.path)


def _jaxpr_types() -> tuple:
    """(ClosedJaxpr, Jaxpr) resolved version-portably: modern jax
    exposes them via ``jax.extend.core`` (they left the public
    ``jax.core`` namespace); older releases only have ``jax.core``."""
    try:
        from jax.extend import core as jex_core

        return jex_core.ClosedJaxpr, jex_core.Jaxpr
    except (ImportError, AttributeError):
        import jax

        return jax.core.ClosedJaxpr, jax.core.Jaxpr


def subjaxprs(eqn) -> list:
    """The inner jaxprs of one equation (scan body, cond branches, pjit
    call jaxpr, shard_map body, custom_* rules ...), uniformly as open
    ``Jaxpr`` objects.  Order is the params-dict order jax builds."""
    closed_t, open_t = _jaxpr_types()
    found = []

    def _collect(v):
        if isinstance(v, closed_t):
            found.append(v.jaxpr)
        elif isinstance(v, open_t):
            found.append(v)
        elif isinstance(v, (tuple, list)):
            for item in v:
                _collect(item)

    for v in eqn.params.values():
        _collect(v)
    return found


def iter_sites(closed_jaxpr, *, loop_depth: int = 0,
               path: tuple = ()) -> Iterator[EqnSite]:
    """Depth-first over every equation of ``closed_jaxpr`` (a
    ``ClosedJaxpr`` or open ``Jaxpr``), recursing into control-flow and
    call bodies.  ``loop_depth`` increments under scan/while bodies."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        here = path + (name,)
        yield EqnSite(eqn=eqn, loop_depth=loop_depth, path=here)
        inner_depth = loop_depth + (1 if name in LOOP_PRIMS else 0)
        for sub in subjaxprs(eqn):
            yield from iter_sites(sub, loop_depth=inner_depth, path=here)


def jaxpr_program(fn, args, n_dynamic: int | None = None):
    """Trace ``fn(*args)`` to a closed jaxpr without compiling.

    ``fn``/``args``/``n_dynamic`` follow the ``round_program``
    convention (obs/profile.py): ``args`` is the full tuple with the
    static arguments TRAILING, ``n_dynamic`` is how many leading args
    are dynamic (default: all).  Static args are closed over so
    hashability quirks (dataclass configs, meshes, specs) never reach
    ``jax.make_jaxpr``."""
    import jax

    if n_dynamic is None:
        n_dynamic = len(args)
    dyn, static = args[:n_dynamic], args[n_dynamic:]
    return jax.make_jaxpr(lambda *d: fn(*d, *static))(*dyn)


def aval_of(atom):
    """The abstract value of an invar/outvar atom (Var or Literal)."""
    return getattr(atom, "aval", None)


def fmt_aval(aval) -> str:
    if aval is None:
        return "?"
    dtype = getattr(aval, "dtype", "?")
    shape = getattr(aval, "shape", None)
    return f"{dtype}[{','.join(map(str, shape))}]" if shape is not None \
        else str(dtype)
