"""flow_updating_tpu — a TPU-native framework for large-scale gossip aggregation.

A ground-up re-design of the capabilities of
``AvilaAndre/simgrid-flow-updating-implementation`` (two Flow-Updating
distributed-averaging protocols running on SimGrid's C++ discrete-event
simulator) as an idiomatic JAX/XLA framework:

* the per-actor event loop of the reference
  (``flowupdating-collectall.py:66-85``) becomes a bulk-synchronous, fully
  vectorized round over dense edge-index arrays, wrapped in ``jax.lax.scan``;
* SimGrid's mailbox/rendezvous machinery becomes a per-edge in-flight message
  ring buffer (delivery is an elementwise select, sending is one masked
  scatter);
* SimGrid's platform/deployment XML files are parsed into a :class:`Topology`
  of ``(E,)`` edge arrays;
* asynchrony (1 msg/sec drain, 50-tick timeouts, link latencies) is preserved
  through static round-config knobs so the same kernel serves both a faithful
  mode and a fast synchronous mode;
* multi-chip scaling shards the node axis over a ``jax.sharding.Mesh`` with
  halo exchange for cross-shard edges.
"""

__version__ = "0.4.0"

from flow_updating_tpu.topology.graph import Topology, build_topology
from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.state import FlowUpdatingState, init_state
from flow_updating_tpu.models.rounds import round_step, run_rounds, node_estimates
from flow_updating_tpu.engine import Engine
from flow_updating_tpu.models.aggregates import (
    estimate_count,
    estimate_max,
    estimate_min,
    estimate_sum,
    estimate_weighted_mean,
)
from flow_updating_tpu.models.actor import (
    TopoView,
    VectorActor,
    push_sum_actor,
)

__all__ = [
    "Topology",
    "build_topology",
    "RoundConfig",
    "FlowUpdatingState",
    "init_state",
    "round_step",
    "run_rounds",
    "node_estimates",
    "Engine",
    "VectorActor",
    "TopoView",
    "push_sum_actor",
    "estimate_count",
    "estimate_max",
    "estimate_min",
    "estimate_sum",
    "estimate_weighted_mean",
    "__version__",
]
