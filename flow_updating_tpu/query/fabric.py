"""The query fabric: the payload feature axis promoted to a query axis.

A production aggregation service runs THOUSANDS of overlapping queries —
per cohort, per region, per window — over one shared topology.  The
repo's ``(N, D)`` payload machinery already evolves D independent scalar
protocol instances sharing one set of messages (models/state.py: control
arrays never grow a feature axis, so firing/delivery/drop decisions are
payload-independent — the bit-exact lane-parity guarantee of
tests/test_vector_payload.py).  The fabric makes each lane a *query*:

* **lane layout** — the fabric compiles the streaming service engine's
  round program ONCE for ``(capacity+1, edge_capacity)`` node/edge slots
  x ``lanes`` payload lanes.  A free lane is all-zero payload on every
  lane plane (``value``/``flow``/``est``/``last_avg`` columns and the
  pending/ring payload planes): zero is a fixed point of the per-lane
  dynamics (sums and averages of zeros are zero; control flow never
  reads payloads), so a free lane stays exactly zero through any number
  of rounds — the mass-neutral ghost-lane invariant, and the reason a
  later admission into that lane is bit-exact (below);

* **admission** — ``submit`` binds a query (cohort ids + one value per
  cohort member) to the lowest free lane (a free-lane heap, exactly the
  service's free-node list applied to D): one ``value[:, lane]`` column
  write of unchanged shape/dtype between scan segments — the capacity
  trick applied to the feature axis, so admission NEVER retraces the
  round program (``compile_count`` stays 1, pinned across hundreds of
  admit/retire events in tests/test_query.py).  Nodes outside the
  cohort carry value 0 on that lane (mass-neutral ghosts *for this
  value stream* — :func:`flow_updating_tpu.topology.padding.
  masked_values`) yet still relay like any other node, so the lane
  converges to ``sum(cohort values) / live`` network-wide;

* **bit-exactness** — lane ``d`` of the fabric is bit-identical to an
  isolated single-query service run at the same capacity/seed driven
  through the same membership events: the shared control plane (ticks,
  stamps, drop draws) evolves payload-independently, the lane starts
  from the all-zero fixed point, and the admission write is exactly the
  isolated run's value update (tests/test_query.py pins this for
  drop > 0, churn and cohort masks);

* **convergence detection + recycle** — between segments a single
  jitted *lane probe* reduces the full estimate matrix device-side to
  five ``(lanes,)`` vectors (max/min/sum of live estimates, the
  per-lane ledger-form mass residual, live count).  A lane whose live
  estimate spread is within its query's ``eps`` (relative to scale) AND
  whose ledger residual has settled (``|resid| <= eps * |mass|`` — on a
  symmetric query the spread is exactly 0.0 from round one while mass
  is still in flight) is
  **retired**: the result is recorded, the lane's payload planes are
  scrubbed back to exact zero in one batched device edit, and the lane
  returns to the free heap for the next admission — lane recycling
  mid-flight, zero recompiles;

* **bounded-staleness reads** — ``read(qid, max_staleness=k)`` serves
  the boundary probe while it is at most ``k`` rounds old (a read that
  costs nothing while segments run); membership and query events always
  invalidate it.  ``max_staleness=None`` forces a fresh probe.

Result semantics: a lane's converged network estimate is
``sum(cohort values alive at read time) / live``; the fabric reports
``sum`` (the lane's live mass — the cohort total) and ``mean``
(``sum / |cohort ∩ alive|``).  Churn mid-query follows the protocol's
self-healing: a departed cohort member's value leaves the lane mass and
the denominators shrink with it.
"""

from __future__ import annotations

import heapq
import math

import numpy as np

from flow_updating_tpu.models.config import COLLECTALL, RoundConfig
from flow_updating_tpu.obs.forecast import FORECAST_BAND, LaneForecaster
from flow_updating_tpu.obs.metrics import MetricsRegistry
from flow_updating_tpu.obs.spans import SpanRecorder
from flow_updating_tpu.service import ServiceEngine
from flow_updating_tpu.topology.padding import masked_values

_PROBE_JIT = None   # process-wide jitted lane probe (one compile per shape)


def _probe_jit():
    global _PROBE_JIT
    if _PROBE_JIT is None:
        import jax

        _PROBE_JIT = jax.jit(_lane_probe)
    return _PROBE_JIT


def _lane_probe(state, arrays):
    """Per-lane boundary statistics, reduced device-side: the full
    ``(n_cap, lanes)`` estimate matrix never reaches the host (at 100k
    nodes x 1024 lanes that is ~0.5 GB per boundary).  Returns
    ``(max, min, sum, mass_residual, live)`` — the first four ``(lanes,)``
    over live nodes, ``mass_residual`` in the service's ledger form
    (``-sum(flow[e] for live src[e])``, exactly 0.0 on a scrubbed free
    lane)."""
    import jax.numpy as jnp

    from flow_updating_tpu.models.rounds import node_estimates

    est = node_estimates(state, arrays)            # (n_cap, lanes)
    am = state.alive[:, None]
    mx = jnp.max(jnp.where(am, est, -jnp.inf), axis=0)
    mn = jnp.min(jnp.where(am, est, jnp.inf), axis=0)
    s = jnp.sum(jnp.where(am, est, 0.0), axis=0)
    live = jnp.sum(state.alive)
    src_alive = state.alive[arrays.src][:, None]
    resid = -jnp.sum(jnp.where(src_alive, state.flow, 0.0), axis=0)
    return mx, mn, s, resid, live


class QueryFabric:
    """A multi-tenant query engine over one compiled round program
    (module docstring; docs/QUERY.md).

    Parameters
    ----------
    topo:
        Initial membership graph (members 0..N-1).
    lanes:
        Concurrent-query capacity D — the compiled payload width.
    capacity / degree_budget / edge_capacity / segment_rounds / seed:
        Forwarded to the underlying :class:`ServiceEngine` (node-slot
        capacity defaults to the initial member count).
    config:
        A :class:`RoundConfig` in the service domain; default
        ``RoundConfig.fast(variant='collectall')``.
    conv_eps:
        Default per-query convergence tolerance: a lane retires when its
        live estimate spread (max - min) is within ``eps * scale``
        (``scale = max(1, |estimate|)``) and its ledger residual is
        within ``eps * max(1, |mass|)``.  ``submit(eps=...)`` overrides
        per query.
    admission_slo_rounds:
        The admission-latency SLO recorded in the manifest (rounds a
        query may wait in the queue before a lane frees up; doctor's
        ``query_admission`` check judges the measured p95 against it).
        Default: two segments.
    """

    def __init__(self, topo, *, lanes: int, capacity: int | None = None,
                 degree_budget: int | None = None,
                 edge_capacity: int | None = None,
                 config: RoundConfig | None = None,
                 segment_rounds: int = 32, seed: int = 0,
                 conv_eps: float = 1e-6,
                 admission_slo_rounds: int | None = None,
                 probe_manifest: bool = False,
                 convergence_slo_rounds: int | None = None,
                 observe: bool = True,
                 forecast: bool | None = None,
                 admit_policy: str = "observe",
                 mixing: dict | None = None,
                 forecast_window: int = 8):
        if lanes < 1:
            raise ValueError(f"lanes={lanes} must be >= 1")
        if conv_eps <= 0:
            raise ValueError(f"conv_eps={conv_eps} must be > 0")
        if admit_policy not in ("observe", "strict"):
            raise ValueError(
                f"admit_policy={admit_policy!r} must be 'observe' "
                "(flag at-risk queries and admit them anyway) or "
                "'strict' (defer them)")
        cfg = config or RoundConfig.fast(variant=COLLECTALL)
        cap = topo.num_nodes if capacity is None else int(capacity)
        self.svc = ServiceEngine(
            topo, cap, degree_budget=degree_budget,
            edge_capacity=edge_capacity, config=cfg,
            segment_rounds=segment_rounds, seed=seed,
            values=np.zeros((topo.num_nodes, int(lanes))),
            # the fabric owns the single flight recorder for the whole
            # stack; the inner service records nothing of its own
            boundary_samples=False, observe=False)
        self.lanes = int(lanes)
        self.conv_eps = float(conv_eps)
        self.admission_slo_rounds = (2 * self.svc.segment_rounds
                                     if admission_slo_rounds is None
                                     else int(admission_slo_rounds))
        # an OPTIONAL declared convergence-latency p95 target (rounds
        # admit->retire); doctor's slo_latency judges it when declared
        self.convergence_slo_rounds = (None if convergence_slo_rounds
                                       is None
                                       else int(convergence_slo_rounds))
        self._free_lanes = list(range(self.lanes))
        heapq.heapify(self._free_lanes)
        self._lane_q: list = [None] * self.lanes    # lane -> active qid
        self._queries: dict = {}                    # qid -> record
        self._queue: list = []                      # waiting qids (FIFO)
        self._next_qid = 0
        self._probe = None            # boundary probe cache (dict)
        self._boundaries: list = []   # one row per segment boundary
        # opt-in (the vectors are lanes-wide per boundary): record the
        # probe reduction vectors into the manifest so read-side
        # aggregate math is auditable offline (aggregates/; doctor's
        # aggregate_read checks)
        self.probe_manifest = bool(probe_manifest)
        self._probe_rows: list = []
        self._latencies: list = []    # admission latencies (rounds)
        self.admitted_total = 0
        self.retired_total = 0
        self.peak_active = 0
        self.quarantined_total = 0
        # the serving flight recorder (obs/metrics.py, obs/spans.py):
        # host-side streaming metrics + per-query span chains, sampled
        # at the boundaries this class already owns — zero device work.
        # ``observe=False`` turns the whole plane off (the purity twin:
        # tests pin the lowered program and state evolution identical)
        self.metrics = MetricsRegistry() if observe else None
        self.spans = SpanRecorder() if observe else None
        # the convergence observatory (obs/forecast.py, obs/spectral.py):
        # host-side ETA forecasting over the SAME lane-probe vectors the
        # boundary already reduces — zero device work, zero new
        # compiles, and with the forecaster off the fabric lowers
        # byte-identically and evolves bit-exactly (the observer-purity
        # contract; tests/test_forecast.py).  Default: on with the
        # flight recorder.
        self.admit_policy = admit_policy
        self._forecaster = (LaneForecaster(window=forecast_window)
                            if (observe if forecast is None
                                else bool(forecast)) else None)
        self._mixing = dict(mixing) if mixing else None
        self._lane_eta: dict = {}         # lane -> latest forecast
        self._forecast_ratios: list = []  # eta_predicted/rounds_actual
        self.at_risk_total = 0
        self.deferred_total = 0
        self._conv_latencies: list = []   # admit->retire rounds
        self._degraded_spanned = 0        # closed episodes span-recorded
        self._watchdog = None
        self._watchdog_pending_state = None
        self._init_resilience()
        self._probe_floor = _probe_jit()._cache_size()

    # ---- resilience (flow_updating_tpu.resilience) -----------------------
    def _init_resilience(self) -> None:
        self._wal = None
        self._ring = None
        self._resil_dir = None
        self._replaying = False
        self._wal_applied_seq = 0
        self._recovery = None

    def _journal(self, kind: str, args: dict) -> None:
        if self._wal is not None and not self._replaying:
            self._wal_applied_seq = self._wal.append(kind, args,
                                                     self.clock)
            if self.metrics is not None:
                self.metrics.observe("wal_fsync_seconds",
                                     self._wal.last_fsync_s)

    def enable_durability(self, directory: str, *,
                          checkpoint_every: int = 8, retain: int = 3,
                          fsync: bool = True) -> QueryFabric:
        """Arm the fabric's event WAL + checkpoint ring (the service
        engine's durability applied at the fabric level: submissions,
        query updates and membership events journal through the fabric
        so replay drives the fabric's own lifecycle — docs/
        RESILIENCE.md).  Recover with :meth:`recover`."""
        from flow_updating_tpu.resilience.recover import arm_durability

        arm_durability(self, directory, kind="query",
                       checkpoint_every=checkpoint_every,
                       retain=retain, fsync=fsync)
        return self

    @classmethod
    def recover(cls, directory: str) -> QueryFabric:
        """Rebuild the fabric journaled in ``directory`` (newest valid
        ring checkpoint + WAL replay; the watchdog re-attaches from the
        directory config) — bit-exact vs the uninterrupted run."""
        from flow_updating_tpu.resilience.recover import recover

        return recover(directory, kind="query")

    def attach_watchdog(self, config=None) -> QueryFabric:
        """Arm the inline lane watchdog
        (:class:`flow_updating_tpu.resilience.watchdog.Watchdog`):
        NaN/divergence/stall lanes are quarantined mass-neutrally at
        segment boundaries, admissions back off when lanes are
        exhausted.  When durability is armed, the config persists to
        the directory so :meth:`recover` re-arms it."""
        from flow_updating_tpu.resilience.recover import (
            _write_config,
            read_config,
        )
        from flow_updating_tpu.resilience.watchdog import (
            Watchdog,
            WatchdogConfig,
        )

        if config is None:
            config = WatchdogConfig()
        self._watchdog = Watchdog(config)
        if self._watchdog_pending_state is not None:
            # a checkpoint restored watchdog runtime (backoff counters,
            # open episode, stall windows): the admission schedule must
            # continue where the dead process stopped, or replay is no
            # longer bit-exact
            self._watchdog.load_state(self._watchdog_pending_state)
            self._watchdog_pending_state = None
        if self._resil_dir is not None:
            doc = read_config(self._resil_dir)
            doc["watchdog"] = config.to_jsonable()
            _write_config(self._resil_dir, doc)
        return self

    def attach_mixing(self, record: dict | None) -> QueryFabric:
        """Attach an a-priori mixing record (obs/spectral.py
        ``mixing_report``): its spectral gap prices admissions BEFORE a
        lane has probe history — a query whose predicted rounds-to-eps
        (``ln(1/eps)/gap``) provably exceeds the declared convergence
        SLO is flagged ``at_risk`` at admission (and deferred under
        ``admit_policy='strict'``)."""
        self._mixing = dict(record) if record else None
        return self

    def _admission_eta(self, q: dict) -> float | None:
        """The a-priori rounds-to-eps estimate for one query at
        admission time (None without forecasting + a mixing record —
        admission control only acts on *provable* misses)."""
        if self._forecaster is None or self._mixing is None:
            return None
        gap = self._mixing.get("gap")
        if not isinstance(gap, (int, float)) or not gap > 0:
            return None
        return (max(0.0, math.log(1.0 / q["eps"]))
                / float(gap)) if q["eps"] < 1.0 else 0.0

    def state_digest(self) -> str:
        """sha256 over the service digest + the lane tables — the
        fabric's bit-exactness verdict in one string."""
        import hashlib

        h = hashlib.sha256()
        h.update(self.svc.state_digest().encode())
        h.update(repr(sorted(self._free_lanes)).encode())
        h.update(repr(self._lane_q).encode())
        h.update(repr(self._queue).encode())
        h.update(repr(self._next_qid).encode())
        return h.hexdigest()

    def resilience_block(self) -> dict | None:
        """The manifest's ``recovery`` block (see
        ``ServiceEngine.resilience_block``); None with durability off
        and no watchdog attached."""
        if self._wal is None and self._recovery is None \
                and self._watchdog is None:
            return None
        out = {"dir": self._resil_dir, "kind": "query"}
        if self._recovery is not None:
            out.update(self._recovery)
        if self._wal is not None:
            # live accounting wins over the recovery-time scan (the
            # scan's extra evidence keys survive; the pre-replay seq is
            # kept as replay.base_wal_seq) so doctor's
            # metrics_consistency compares same-moment figures
            wal = dict(out.get("wal") or {})
            wal.update(self._wal.block())
            out["wal"] = wal
        if self._ring is not None:
            ring = dict(out.get("ring") or {})
            ring.update(self._ring.block())
            out["ring"] = ring
        if self._watchdog is not None:
            out["watchdog"] = self._watchdog.block()
        return out

    # ---- views -----------------------------------------------------------
    @property
    def clock(self) -> int:
        return self.svc.clock

    @property
    def compile_count(self) -> int:
        """Round-program compiles since construction — the fabric's
        zero-recompile SLO (must stay at 1 across every admission,
        retirement and membership event; the probe is a separate tiny
        program counted by :attr:`probe_compile_count`)."""
        return self.svc.compile_count

    @property
    def probe_compile_count(self) -> int:
        return _probe_jit()._cache_size() - self._probe_floor

    @property
    def active_lanes(self) -> int:
        return self.lanes - len(self._free_lanes)

    @property
    def queued(self) -> int:
        return len(self._queue)

    def query(self, qid: int) -> dict:
        """The query's current record (a copy; values stream omitted)."""
        q = self._queries[qid]
        return {k: v for k, v in q.items() if not k.startswith("_")}

    # ---- membership passthrough -----------------------------------------
    # Churn routes through the service engine unchanged; the fabric only
    # maintains the cohort bookkeeping (a departed member leaves every
    # cohort — its freed slot may be recycled by a later join that must
    # not count toward old queries) and invalidates the boundary probe.

    # Passthrough journaling happens AFTER the delegated call succeeds:
    # the call validates+applies atomically from the fabric's view, and
    # no checkpoint can interleave (ring writes only happen inside
    # run()), so a crash mid-event loses at most that one
    # never-acknowledged event — the same guarantee as write-ahead.

    def join(self) -> int:
        """Admit one member (contributes 0 to every in-flight lane; it
        enters future queries' cohorts).  Returns the slot id."""
        slot = self.svc.join(np.zeros(self.lanes))
        self._journal("join", {})
        self._probe = None
        return slot

    def leave(self, ids) -> QueryFabric:
        self.svc.leave(ids)
        self._journal("leave", {"ids": [int(i) for i in
                                        np.atleast_1d(np.asarray(ids))]})
        gone = {int(i) for i in np.atleast_1d(np.asarray(ids, np.int64))}
        for q in self._queries.values():
            if q["status"] in ("queued", "active") and \
                    not gone.isdisjoint(q["cohort"]):
                keep = [i not in gone for i in q["cohort"]]
                q["cohort"] = [i for i, k in zip(q["cohort"], keep) if k]
                if q.get("_values") is not None:
                    q["_values"] = q["_values"][np.asarray(keep, bool)]
        self._probe = None
        return self

    def add_edges(self, pairs) -> QueryFabric:
        self.svc.add_edges(pairs)
        self._journal("add_edges",
                      {"pairs": [[int(u), int(v)] for u, v in pairs]})
        self._probe = None
        return self

    def remove_edges(self, pairs) -> QueryFabric:
        self.svc.remove_edges(pairs)
        self._journal("remove_edges",
                      {"pairs": [[int(u), int(v)] for u, v in pairs]})
        self._probe = None
        return self

    def suspend(self, ids) -> QueryFabric:
        self.svc.suspend(ids)
        self._journal("suspend", {"ids": [int(i) for i in
                                          np.atleast_1d(np.asarray(ids))]})
        self._probe = None
        return self

    def resume(self, ids) -> QueryFabric:
        self.svc.resume(ids)
        self._journal("resume", {"ids": [int(i) for i in
                                         np.atleast_1d(np.asarray(ids))]})
        self._probe = None
        return self

    # ---- query lifecycle -------------------------------------------------
    def submit(self, values, cohort=None, *, eps: float | None = None,
               tag=None) -> int:
        """Submit one query: aggregate ``values`` over ``cohort`` (member
        slot ids; ``None`` = every currently live member).  ``values`` is
        one scalar per cohort member, or a single scalar broadcast to
        the whole cohort.  Returns the query id; the query admits into
        the lowest free lane immediately (admission latency 0) or waits
        in FIFO order for a retirement to free one."""
        if cohort is None:
            cohort = self.svc.live_ids()
        cohort = np.atleast_1d(np.asarray(cohort, np.int64))
        self.svc._check_member(cohort, "submit")
        if np.unique(cohort).size != cohort.size:
            raise ValueError("submit: duplicate cohort ids")
        vals = np.asarray(values, np.float64)
        if vals.ndim == 0:
            vals = np.full(cohort.shape, float(vals))
        if vals.shape != cohort.shape:
            raise ValueError(
                f"submit: values shape {vals.shape} != cohort shape "
                f"{cohort.shape} (one value per cohort member, or one "
                "scalar for all)")
        self._journal("submit", {
            "values": vals.tolist(),
            "cohort": [int(i) for i in cohort],
            "eps": eps, "tag": tag,
        })
        qid = self._next_qid
        self._next_qid += 1
        self._queries[qid] = {
            "qid": qid,
            "status": "queued",
            "lane": None,
            "submit_round": self.clock,
            "admit_round": None,
            "done_round": None,
            "cohort": [int(i) for i in cohort],
            "cohort_size": int(cohort.size),
            "eps": self.conv_eps if eps is None else float(eps),
            "tag": tag,
            "result": None,
            # the watchdog's divergence reference: a lane's healthy
            # estimate scale is bounded by its own input magnitude
            "value_scale": float(np.max(np.abs(vals)))
            if vals.size else 1.0,
            "_values": vals,
        }
        self._queue.append(qid)
        if self.spans is not None:
            self.spans.submitted(qid, self.clock)
        if self.metrics is not None:
            self.metrics.inc("queries_submitted_total")
        self._admit_free()
        return qid

    def update_query(self, qid: int, ids, values) -> QueryFabric:
        """Overwrite part of an active query's value stream (the
        protocol tracks dynamic inputs natively — the lane re-converges
        on the new cohort total).  ``ids`` must be live cohort members
        of ``qid``."""
        import jax.numpy as jnp

        q = self._queries[qid]
        if q["status"] != "active":
            raise ValueError(
                f"update_query: query {qid} is {q['status']} (only "
                "active queries hold a lane)")
        ids = np.atleast_1d(np.asarray(ids, np.int64))
        bad = sorted(set(int(i) for i in ids) - set(q["cohort"]))
        if bad:
            raise ValueError(
                f"update_query: nodes {bad} are not in query {qid}'s "
                "cohort")
        vals = np.asarray(values, np.float64)
        if vals.shape != ids.shape:
            raise ValueError(
                f"update_query: values shape {vals.shape} != ids shape "
                f"{ids.shape}")
        self._journal("update_query", {
            "qid": int(qid), "ids": [int(i) for i in ids],
            "values": vals.tolist()})
        q["value_scale"] = max(float(q.get("value_scale", 1.0)),
                               float(np.max(np.abs(vals)))
                               if vals.size else 1.0)
        st = self.svc.state
        self.svc.state = st.replace(
            value=st.value.at[jnp.asarray(ids), q["lane"]].set(
                jnp.asarray(vals, st.value.dtype)))
        self._probe = None
        return self

    def _admit_free(self) -> int:
        """Bind waiting queries to free lanes — one batched column write
        of unchanged shape/dtype (never a retrace).  Runs at submit time
        and at every segment boundary (after retirements).

        Forecast-aware admission (docs/OBSERVABILITY.md §10): with an
        attached mixing record, a query whose a-priori ETA exceeds the
        declared convergence SLO is flagged ``at_risk`` (span-annotated
        + counted) and, under ``admit_policy='strict'``, DEFERRED — a
        terminal state that never holds a lane, so the chain checks
        extend to it (submitted -> deferred)."""
        import jax.numpy as jnp

        if not self._queue or not self._free_lanes:
            return 0
        slo = self.convergence_slo_rounds
        n_cap = self.svc._n_cap
        lanes, cols = [], []
        while self._queue and self._free_lanes:
            qid = self._queue.pop(0)
            q = self._queries[qid]
            eta0 = self._admission_eta(q)
            if slo is not None and eta0 is not None and eta0 > slo:
                q["at_risk"] = True
                q["eta_admission"] = round(float(eta0), 3)
                self.at_risk_total += 1
                if self.metrics is not None:
                    self.metrics.inc("queries_at_risk_total")
                if self.spans is not None:
                    self.spans.annotate(
                        qid, at_risk=True,
                        eta_admission=round(float(eta0), 3))
                if self.admit_policy == "strict":
                    q.update(status="deferred", done_round=self.clock)
                    q["_values"] = None
                    self.deferred_total += 1
                    if self.spans is not None:
                        self.spans.deferred(
                            qid, self.clock,
                            eta_rounds=round(float(eta0), 3),
                            slo_rounds=int(slo))
                    if self.metrics is not None:
                        self.metrics.inc("queries_deferred_total")
                    continue
            lane = heapq.heappop(self._free_lanes)
            cohort = np.asarray(q["cohort"], np.int64)
            cols.append(masked_values(q["_values"], n_cap, cohort))
            q.update(status="active", lane=lane,
                     admit_round=self.clock)
            q["_values"] = None
            self._lane_q[lane] = qid
            self._latencies.append(self.clock - q["submit_round"])
            if self.spans is not None:
                self.spans.admitted(qid, lane, self.clock)
            if self.metrics is not None:
                self.metrics.observe("admission_latency_rounds",
                                     self.clock - q["submit_round"])
            lanes.append(lane)
        if not lanes:
            return 0          # every candidate deferred: no device work
        st = self.svc.state
        li = jnp.asarray(np.asarray(lanes, np.int32))
        self.svc.state = st.replace(
            value=st.value.at[:, li].set(
                jnp.asarray(np.stack(cols, axis=1), st.value.dtype)))
        self.admitted_total += len(lanes)
        if self.metrics is not None:
            self.metrics.inc("queries_admitted_total", len(lanes))
        self.peak_active = max(self.peak_active, self.active_lanes)
        self._probe = None
        return len(lanes)

    def _scrub_lanes(self, lanes) -> None:
        """Return retired lanes to the all-zero fixed point: every
        payload plane's lane column zeroed in one batched device edit
        (shared control arrays are untouched — they belong to every
        lane).  After the scrub the lane's ledger residual is exactly
        0.0 and the next admission starts bit-identically to a fresh
        fabric's lane."""
        import jax.numpy as jnp

        st = self.svc.state
        li = jnp.asarray(np.asarray(lanes, np.int32))
        self.svc.state = st.replace(
            value=st.value.at[:, li].set(0.0),
            flow=st.flow.at[:, li].set(0.0),
            est=st.est.at[:, li].set(0.0),
            last_avg=st.last_avg.at[:, li].set(0.0),
            pending_flow=st.pending_flow.at[:, :, li].set(0.0),
            pending_est=st.pending_est.at[:, :, li].set(0.0),
            buf_flow=st.buf_flow.at[:, :, li].set(0.0),
            buf_est=st.buf_est.at[:, :, li].set(0.0),
        )

    def _quarantine(self, items) -> list:
        """Watchdog-ordered lane quarantine: scrub each pathological
        lane's payload planes back to the exact-zero fixed point (the
        retirement scrub — mass-neutral, every OTHER lane untouched),
        return the lanes to the free heap, and mark the queries
        ``quarantined``.  ``items``: ``[(lane, qid, reason, evidence),
        ...]``.  Returns one action record per lane with the post-scrub
        ledger residual measured off a fresh probe (exactly 0.0 — the
        doctor's ``quarantine_mass`` evidence)."""
        lanes = [lane for lane, *_ in items]
        self._scrub_lanes(lanes)
        for lane, qid, reason, _ev in items:
            q = self._queries[qid]
            q.update(status="quarantined", done_round=self.clock,
                     result=None)
            q.pop("_forecast_total", None)
            self._lane_q[lane] = None
            heapq.heappush(self._free_lanes, lane)
            if self._forecaster is not None:
                self._forecaster.clear(lane)
                self._lane_eta.pop(lane, None)
            if self.spans is not None:
                self.spans.quarantined(qid, self.clock, reason=reason)
        self.quarantined_total += len(items)
        if self.metrics is not None:
            self.metrics.inc("queries_quarantined_total", len(items))
        self._probe = None
        probe = self._probe_fresh()
        return [{
            "t": self.clock,
            "lane": int(lane),
            "qid": int(qid),
            "reason": reason,
            "evidence": evidence,
            # abs(): the ledger form of a scrubbed lane sums zeros to
            # -0.0; the record must read "exactly 0.0"
            "post_scrub_residual": float(np.abs(probe["resid"][lane])),
        } for lane, qid, reason, evidence in items]

    # ---- execution -------------------------------------------------------
    def run(self, rounds: int) -> QueryFabric:
        """Advance ``rounds`` (a whole number of compiled segments).  At
        every segment boundary: probe the lanes, retire + recycle the
        converged ones, admit waiting queries into the freed slots, and
        record one boundary row (the doctor's SLO inputs)."""
        from flow_updating_tpu.models.rounds import run_rounds

        if rounds < 0:
            raise ValueError("rounds must be >= 0")
        seg = self.svc.segment_rounds
        if rounds % seg:
            raise ValueError(
                f"rounds={rounds} must be a whole number of compiled "
                f"segments (segment_rounds={seg}) — the zero-recompile "
                "contract fixes the scan length")
        self._journal("run", {"rounds": int(rounds)})
        svc = self.svc
        # membership events queued on the service since the last segment
        # belong to the fabric's timeline, not a service epoch
        svc._pending_events = []
        for _ in range(rounds // seg):
            svc.state = run_rounds(svc.state, svc.arrays, svc.config,
                                   seg, params=svc.params)
            self._boundary()
            svc._pending_events = []
        if self._ring is not None and rounds:
            wrote = self._ring.tick(self, self._wal_applied_seq,
                                    segments=rounds // seg)
            if wrote is not None and self.metrics is not None:
                self.metrics.inc("checkpoints_written_total")
                self.metrics.observe("checkpoint_write_seconds",
                                     self._ring.last_write_s)
        return self

    def _boundary(self) -> dict:
        if self.spans is not None:
            # close one segment span per active query BEFORE the
            # watchdog/retire verdicts stamp terminals at this clock —
            # the chain stays gap-free up to the terminal
            self.spans.boundary(self.clock)
        probe = self._probe_fresh()
        if self._watchdog is not None:
            # the watchdog rides THIS probe (zero extra compiles); a
            # quarantine scrubs lane planes, so the verdict inputs
            # below must come from a fresh probe
            if self._watchdog.inspect(self, probe):
                probe = self._probe_fresh()
        mx, mn = probe["max"], probe["min"]
        resid, live = probe["resid"], probe["live"]
        if self.probe_manifest:
            self._probe_rows.append({
                "t": self.clock,
                "live": int(live),
                "max": [float(x) for x in mx],
                "min": [float(x) for x in mn],
                "sum": [float(x) for x in probe["sum"]],
                "resid": [float(x) for x in resid],
                # lane -> qid at THIS boundary (recycling re-keys lanes
                # between rows; the offline audit needs the binding)
                "lane_q": [None if x is None else int(x)
                           for x in self._lane_q],
            })
        active = [ln for ln in range(self.lanes)
                  if self._lane_q[ln] is not None]
        free = [ln for ln in range(self.lanes)
                if self._lane_q[ln] is None]
        if self._forecaster is not None:
            # feed every active lane's trailing window off THIS probe
            # (zero extra device work) and refresh its ETA — the first
            # warm forecast banks the query's predicted total, the
            # reconciliation input of doctor's forecast_calibrated
            for ln in active:
                q = self._queries[self._lane_q[ln]]
                self._forecaster.observe(
                    ln, self.clock,
                    spread=float(mx[ln] - mn[ln]),
                    scale=max(1.0, abs(float(mx[ln])),
                              abs(float(mn[ln]))),
                    resid=float(resid[ln]),
                    mass=float(probe["sum"][ln]))
                fc = self._forecaster.forecast(ln, q["eps"],
                                               now=self.clock)
                self._lane_eta[ln] = fc
                if fc["status"] == "ok" \
                        and q.get("_forecast_total") is None:
                    q["_forecast_total"] = (
                        (self.clock - q["admit_round"])
                        + fc["eta_rounds"])
        # retire converged lanes (admitted lanes are only probed after
        # their first full segment: admission runs AFTER this step)
        done = []
        for ln in active:
            q = self._queries[self._lane_q[ln]]
            r = self._lane_result(probe, q)
            # standing queries (aggregates/: windowed lanes restreamed
            # between segments) serve until released — convergence does
            # not retire them
            if r.pop("converged") and not q.get("standing"):
                r["rounds"] = self.clock - q["admit_round"]
                q.update(status="done", done_round=self.clock, result=r)
                done.append(ln)
                self._conv_latencies.append(int(r["rounds"]))
                if self._forecaster is not None:
                    pred = q.pop("_forecast_total", None)
                    if pred is not None and r["rounds"] > 0:
                        ratio = float(pred) / float(r["rounds"])
                        self._forecast_ratios.append(ratio)
                        q["forecast_ratio"] = round(ratio, 6)
                        if self.metrics is not None:
                            self.metrics.observe(
                                "forecast_abs_log_ratio",
                                abs(math.log(max(ratio, 1e-12))))
                    if self.metrics is not None:
                        self.metrics.observe(
                            f"lane{ln}_convergence_rounds",
                            r["rounds"])
                if self.spans is not None:
                    self.spans.converged(q["qid"], self.clock)
                    self.spans.retired(q["qid"], self.clock)
                if self.metrics is not None:
                    self.metrics.observe("convergence_latency_rounds",
                                         r["rounds"])
        if done:
            self._scrub_lanes(done)
            for ln in done:
                self._lane_q[ln] = None
                heapq.heappush(self._free_lanes, ln)
                if self._watchdog is not None:
                    # a recycled lane must not inherit the retired
                    # query's stall window
                    self._watchdog._lane_trend.pop(ln, None)
                if self._forecaster is not None:
                    # ... nor the retired query's decay history
                    self._forecaster.clear(ln)
                    self._lane_eta.pop(ln, None)
            self.retired_total += len(done)
            if self.metrics is not None:
                self.metrics.inc("queries_retired_total", len(done))
            self._probe = None   # lane planes changed under the probe
        if self._watchdog is not None \
                and not self._watchdog.admission_allowed(self):
            admitted = 0         # degraded mode: backoff defers this one
        else:
            admitted = self._admit_free()
        if self._watchdog is not None:
            self._watchdog.after_admission(self)
            if self.spans is not None:
                # closed lane-exhaustion episodes become engine-level
                # ``degraded`` spans (watchdog state rides checkpoints,
                # so the cursor below does too — no double recording
                # across a recovery)
                closed = [e for e in self._watchdog.degraded
                          if e.get("end_t") is not None]
                for ep in closed[self._degraded_spanned:]:
                    self.spans.engine_span(
                        "degraded", ep["start_t"], ep["end_t"],
                        boundaries=ep["boundaries"],
                        max_backoff=ep["max_backoff"],
                        peak_queued=ep["peak_queued"])
                self._degraded_spanned = len(closed)
            if self.metrics is not None:
                self.metrics.set_counter(
                    "watchdog_backoff_episodes_total",
                    len(self._watchdog.degraded))
                self.metrics.set_counter(
                    "watchdog_deferred_admissions_total",
                    self._watchdog.deferred_admissions)
        act_idx = np.asarray(active, np.int64)
        spread_a = (mx[act_idx] - mn[act_idx]) if active else \
            np.zeros(0)
        scale = float(np.max(np.abs(np.stack([mx[act_idx],
                                              mn[act_idx]])))) \
            if active else 0.0
        row = {
            "t": self.clock,
            "live": int(live),
            "active_lanes": len(active),
            "free_lanes": len(free),
            "queued": len(self._queue),
            "scale": scale,
            "max_spread": float(np.max(spread_a)) if active else 0.0,
            "max_resid_active": (float(np.max(np.abs(resid[act_idx])))
                                 if active else 0.0),
            "max_resid_free": (float(np.max(np.abs(
                resid[np.asarray(free, np.int64)]))) if free else 0.0),
            "retired": len(done),
            "admitted": admitted,
        }
        self._boundaries.append(row)
        if self.metrics is not None:
            self.metrics.inc("boundaries_total")
            gauges = {
                "lanes_active": self.active_lanes,
                "lanes_free": len(self._free_lanes),
                "queue_depth": len(self._queue),
                "live_members": int(live),
            }
            if self._wal is not None:
                gauges["wal_last_seq"] = self._wal.last_seq
                gauges["wal_fsync_seconds_total"] = \
                    self._wal.fsync_seconds_total
            if self._ring is not None:
                gauges["checkpoint_writes"] = self._ring.written_total
                gauges["checkpoint_write_seconds_total"] = \
                    self._ring.write_seconds_total
            self.metrics.sample_row(self.clock, **gauges)
        return row

    # ---- reads -----------------------------------------------------------
    def _lane_result(self, probe: dict, q: dict) -> dict:
        """THE two-signal convergence verdict + the lane's result
        fields, in one place for retirement (:meth:`_boundary`) and
        :meth:`read` — the criteria must never drift apart.  Converged
        needs the live estimate spread within ``eps * scale`` (everyone
        agrees) AND the ledger residual within ``eps * max(1, |mass|)``
        (the ledger has settled — on a symmetric query, e.g. a constant
        column on a vertex-transitive graph, every estimate is bitwise
        equal from round one while mass is still in flight, so spread
        alone would accept a ~%-wrong result)."""
        ln = q["lane"]
        spread = float(probe["max"][ln] - probe["min"][ln])
        scale = max(1.0, abs(float(probe["max"][ln])),
                    abs(float(probe["min"][ln])))
        total = float(probe["sum"][ln])
        live = probe["live"]
        settled = (abs(float(probe["resid"][ln]))
                   <= q["eps"] * max(1.0, abs(total)))
        cohort_live = int(sum(bool(probe["alive"][i])
                              for i in q["cohort"]))
        return {
            "sum": total,
            "mean": total / cohort_live if cohort_live else None,
            "estimate": total / live if live else None,
            "spread": spread,
            "converged": bool(np.isfinite(spread)
                              and spread <= q["eps"] * scale
                              and settled),
            "cohort_live": cohort_live,
        }

    def _probe_fresh(self) -> dict:
        mx, mn, s, resid, live = _probe_jit()(self.svc.state,
                                              self.svc.arrays)
        self._probe = {
            "t": self.clock,
            "max": np.asarray(mx), "min": np.asarray(mn),
            "sum": np.asarray(s), "resid": np.asarray(resid),
            "live": int(live),
            "alive": np.asarray(self.svc.state.alive),
        }
        return self._probe

    def read(self, qid: int, max_staleness: int | None = None) -> dict:
        """The query's current answer.  Completed queries return their
        recorded result; queued queries their position; active queries a
        live read off the boundary probe — served from the cache while
        it is at most ``max_staleness`` rounds old (events always
        invalidate it; ``None`` forces a fresh probe)."""
        q = self._queries[qid]
        base = {"qid": qid, "status": q["status"], "t": self.clock}
        if q["status"] == "done":
            if self.spans is not None:
                self.spans.read(qid, self.clock)
            out = {**base, "t": q["done_round"], "staleness": 0,
                   "converged": True, **q["result"]}
            if "forecast_ratio" in q:
                out["forecast_ratio"] = q["forecast_ratio"]
            if q.get("at_risk"):
                out["at_risk"] = True   # admitted over-SLO (observe policy)
            return out
        if q["status"] == "quarantined":
            # the lane was scrubbed by the watchdog: no result, and the
            # read says so instead of probing a lane it no longer owns
            return {**base, "t": q["done_round"], "converged": False,
                    "quarantined": True}
        if q["status"] == "deferred":
            # strict admission turned it away at the door: the a-priori
            # ETA that priced it out is the read's answer
            return {**base, "t": q["done_round"], "converged": False,
                    "deferred": True, "at_risk": True,
                    "eta_rounds": q.get("eta_admission"),
                    "slo_rounds": self.convergence_slo_rounds}
        if q["status"] == "queued":
            return {**base, "queue_position":
                    self._queue.index(qid),
                    "waited_rounds": self.clock - q["submit_round"]}
        probe = self._probe
        if (max_staleness is None or probe is None
                or self.clock - probe["t"] > max_staleness):
            probe = self._probe_fresh()
        out = {
            **base,
            "t": probe["t"],
            "staleness": self.clock - probe["t"],
            **self._lane_result(probe, q),
        }
        if self._forecaster is not None:
            # the per-lane ETA off the latest boundary forecast (the
            # read itself never refits — the forecast is as stale as
            # the last boundary, which the chain clocks make explicit)
            fc = self._lane_eta.get(q["lane"])
            if fc is None:
                out["forecast_status"] = "warming"
            else:
                out["forecast_status"] = fc["status"]
                if fc["status"] == "ok":
                    out["eta_rounds"] = fc["eta_rounds"]
                    out["eta_lo"] = fc["eta_lo"]
                    out["eta_hi"] = fc["eta_hi"]
            if q.get("at_risk"):
                out["at_risk"] = True
        return out

    def mass_residual(self) -> np.ndarray:
        """(lanes,) per-lane live-mass residual in the ledger form (the
        service's bit-exact event-conservation accounting, one entry per
        lane; exactly 0.0 on scrubbed free lanes)."""
        return np.atleast_1d(self.svc.mass_residual())

    # ---- manifest --------------------------------------------------------
    def query_block(self) -> dict:
        """The manifest's ``query`` block — the inputs of ``doctor``'s
        fabric SLO checks (obs/health.check_query): lane/compile
        accounting, admission-latency distribution vs its SLO, and the
        per-boundary lane-mass rows."""
        lat = np.asarray(self._latencies, np.float64)
        latency = {"count": int(lat.size), "slo_rounds":
                   self.admission_slo_rounds}
        if lat.size:
            latency.update({
                "p50": float(np.percentile(lat, 50)),
                "p95": float(np.percentile(lat, 95)),
                "p99": float(np.percentile(lat, 99)),
                "max": float(lat.max()),
            })
        conv = np.asarray(self._conv_latencies, np.float64)
        conv_latency = {"count": int(conv.size), "slo_rounds":
                        self.convergence_slo_rounds}
        if conv.size:
            conv_latency.update({
                "p50": float(np.percentile(conv, 50)),
                "p95": float(np.percentile(conv, 95)),
                "p99": float(np.percentile(conv, 99)),
                "max": float(conv.max()),
            })
        qs = []
        for q in self._queries.values():
            rec = {k: v for k, v in q.items() if not k.startswith("_")}
            if rec.get("tag") is None:
                rec.pop("tag", None)
            rec.pop("cohort", None)   # ids can be 100k+ wide; keep size
            qs.append(rec)
        out = {
            "lanes": {
                "capacity": self.lanes,
                "active": self.active_lanes,
                "free": len(self._free_lanes),
                "queued": len(self._queue),
                "peak_active": self.peak_active,
            },
            "compile_count": self.compile_count,
            "probe_compile_count": self.probe_compile_count,
            "segment_rounds": self.svc.segment_rounds,
            "admitted_total": self.admitted_total,
            "retired_total": self.retired_total,
            "quarantined_total": self.quarantined_total,
            "admission_latency": latency,
            "convergence_latency": conv_latency,
            "boundaries": [dict(b) for b in self._boundaries],
            "queries": qs,
            "service": self.svc.service_block(),
            "dtype": self.svc.config.dtype,
        }
        if self.probe_manifest:
            out["probe_rows"] = [dict(r) for r in self._probe_rows]
        if self._forecaster is not None:
            out["forecast"] = self._forecast_block()
        return out

    def _forecast_block(self) -> dict:
        """The ``forecast`` sub-block of the query manifest — doctor's
        ``forecast_calibrated`` / ``slo_admission`` inputs: the banked
        ``forecast_ratio`` distribution against the declared band, the
        admission-control counters, and the mixing record that priced
        admissions (when attached)."""
        ratios = [float(r) for r in self._forecast_ratios]
        fore = {
            "enabled": True,
            "admit_policy": self.admit_policy,
            "window": self._forecaster.window,
            "min_points": self._forecaster.min_points,
            "band": FORECAST_BAND,
            "ratios": [round(r, 6) for r in ratios],
            "at_risk_total": self.at_risk_total,
            "deferred_total": self.deferred_total,
        }
        pos = [r for r in ratios if r > 0 and math.isfinite(r)]
        if pos:
            logs = np.abs(np.log(np.asarray(pos)))
            fore["p90_abs_log_ratio"] = float(np.percentile(logs, 90))
            fore["in_band_frac"] = float(
                np.mean(logs <= math.log(FORECAST_BAND)))
        if self._mixing is not None:
            fore["mixing"] = dict(self._mixing)
        return fore

    # ---- serving flight recorder (obs/metrics.py, obs/spans.py) ----------
    def _refresh_obs_gauges(self) -> None:
        """Point-in-time gauges refreshed when the trace block is built
        (boundary sampling records the history; the block's gauges must
        reflect NOW — doctor's ``metrics_consistency`` compares them to
        the manifest ground truth written at the same moment)."""
        m = self.metrics
        m.set_gauge("lanes_active", self.active_lanes)
        m.set_gauge("lanes_free", len(self._free_lanes))
        m.set_gauge("queue_depth", len(self._queue))
        m.set_gauge("compile_count", self.compile_count)
        m.set_gauge("probe_compile_count", self.probe_compile_count)
        if self._forecaster is not None:
            for ln, fc in sorted(self._lane_eta.items()):
                if fc.get("status") == "ok":
                    m.set_gauge(f"lane{ln}_eta_rounds",
                                float(fc["eta_rounds"]))
        if self._wal is not None:
            m.set_gauge("wal_last_seq", self._wal.last_seq)
            m.set_gauge("wal_fsync_seconds_total",
                        self._wal.fsync_seconds_total)
        if self._ring is not None:
            m.set_gauge("checkpoint_writes", self._ring.written_total)
            m.set_gauge("checkpoint_write_seconds_total",
                        self._ring.write_seconds_total)
        if self._watchdog is not None:
            m.set_counter("watchdog_backoff_episodes_total",
                          len(self._watchdog.degraded))
            m.set_counter("watchdog_deferred_admissions_total",
                          self._watchdog.deferred_admissions)

    def serving_trace_block(self) -> dict | None:
        """The manifest's ``serving_trace`` block
        (``flow-updating-serving-trace/v1``): declared SLO targets, the
        streaming metrics registry, and every span chain — the inputs
        of doctor's ``slo_latency`` / ``span_complete`` /
        ``metrics_consistency`` checks.  None with ``observe=False``."""
        if self.metrics is None:
            return None
        from flow_updating_tpu.obs.report import SERVING_TRACE_SCHEMA

        self._refresh_obs_gauges()
        return {
            "schema": SERVING_TRACE_SCHEMA,
            "slo": {
                "admission_p95_rounds": self.admission_slo_rounds,
                "convergence_p95_rounds": self.convergence_slo_rounds,
            },
            "metrics": self.metrics.block(),
            "spans": (self.spans.block()
                      if self.spans is not None else None),
        }

    # ---- durability ------------------------------------------------------
    def save_checkpoint(self, path: str,
                        extra_meta: dict | None = None) -> QueryFabric:
        """One versioned archive: the full service checkpoint plus the
        fabric's lane tables (``meta['query']`` — the
        SERVICE_FORMAT_VERSION=2 extension).  Round-trip is bit-exact;
        a plain ``ServiceEngine.restore_checkpoint`` of the same file
        ignores the lane block (tests/test_checkpoint.py).
        ``extra_meta`` merges further JSON blocks (the checkpoint
        ring's ``resilience`` binding rides here)."""
        queries = []
        for q in self._queries.values():
            rec = {k: v for k, v in q.items() if not k.startswith("_")}
            if q.get("_values") is not None:
                rec["values"] = [float(v) for v in q["_values"]]
            queries.append(rec)
        qmeta = {
            "lanes": self.lanes,
            "conv_eps": self.conv_eps,
            "admission_slo_rounds": self.admission_slo_rounds,
            "free_lanes": sorted(self._free_lanes),
            "lane_q": list(self._lane_q),
            "queue": list(self._queue),
            "next_qid": self._next_qid,
            "admitted_total": self.admitted_total,
            "retired_total": self.retired_total,
            "peak_active": self.peak_active,
            "latencies": [int(x) for x in self._latencies],
            "quarantined_total": self.quarantined_total,
            "queries": queries,
            "convergence_slo_rounds": self.convergence_slo_rounds,
            "conv_latencies": [int(x) for x in self._conv_latencies],
            "observe": self.metrics is not None,
            "admit_policy": self.admit_policy,
            # the forecasting config + banked reconciliations persist
            # so WAL replay re-derives the SAME admission decisions
            # (strict deferral depends on the mixing gap); the trailing
            # fit windows are transient and re-warm from live probes
            "forecast": {
                "enabled": self._forecaster is not None,
                "window": (self._forecaster.window
                           if self._forecaster is not None else None),
                "min_points": (self._forecaster.min_points
                               if self._forecaster is not None
                               else None),
                "ratios": [float(r) for r in self._forecast_ratios],
                "at_risk_total": self.at_risk_total,
                "deferred_total": self.deferred_total,
                "mixing": self._mixing,
            },
        }
        if self._watchdog is not None:
            qmeta["watchdog_state"] = self._watchdog.state_dict()
        if self.metrics is not None:
            # the flight recorder's black box: metrics + span chains
            # ride every ring archive, so a recovered fabric's trace is
            # continuous with the pre-crash one (WAL replay regenerates
            # the spans after this checkpoint at the same round clocks)
            qmeta["obs"] = {
                "metrics": self.metrics.state_dict(),
                "spans": (self.spans.state_dict()
                          if self.spans is not None else None),
                "degraded_spanned": self._degraded_spanned,
            }
        self.svc.save_checkpoint(
            path, extra_meta={"query": qmeta, **(extra_meta or {})})
        return self

    @classmethod
    def restore_checkpoint(cls, path: str) -> QueryFabric:
        """Rebuild a fabric from :meth:`save_checkpoint`'s archive —
        same lanes, same in-flight queries, bit-exact state."""
        from flow_updating_tpu.utils.checkpoint import (
            _open_archive,
            _read_manifest,
        )

        svc = ServiceEngine.restore_checkpoint(path)
        with _open_archive(path) as z:
            manifest = _read_manifest(z, path)
        qmeta = (manifest.get("service") or {}).get("query")
        if qmeta is None:
            raise ValueError(
                f"checkpoint {path}: no query lane tables — a plain "
                "service checkpoint (service schema version "
                f"{manifest.get('service_version')}) restores via "
                "ServiceEngine.restore_checkpoint; query fabrics are "
                "saved by QueryFabric.save_checkpoint")
        lanes = int(qmeta["lanes"])
        if svc.feature_shape != (lanes,):
            raise ValueError(
                f"checkpoint {path}: lane table says {lanes} lanes but "
                f"the state payload is {svc.feature_shape}")
        self = object.__new__(cls)
        self.svc = svc
        self.lanes = lanes
        self.conv_eps = float(qmeta["conv_eps"])
        self.admission_slo_rounds = int(qmeta["admission_slo_rounds"])
        self._free_lanes = [int(x) for x in qmeta["free_lanes"]]
        heapq.heapify(self._free_lanes)
        self._lane_q = [None if x is None else int(x)
                        for x in qmeta["lane_q"]]
        self._queue = [int(x) for x in qmeta["queue"]]
        self._next_qid = int(qmeta["next_qid"])
        self._queries = {}
        for rec in qmeta["queries"]:
            q = dict(rec)
            q["_values"] = (np.asarray(q.pop("values"), np.float64)
                            if "values" in q else None)
            self._queries[int(q["qid"])] = q
        self.admitted_total = int(qmeta["admitted_total"])
        self.retired_total = int(qmeta["retired_total"])
        self.peak_active = int(qmeta["peak_active"])
        self.quarantined_total = int(qmeta.get("quarantined_total", 0))
        self._latencies = [int(x) for x in qmeta["latencies"]]
        self.convergence_slo_rounds = qmeta.get("convergence_slo_rounds")
        if self.convergence_slo_rounds is not None:
            self.convergence_slo_rounds = int(self.convergence_slo_rounds)
        self._conv_latencies = [int(x) for x in
                                qmeta.get("conv_latencies", [])]
        self.admit_policy = str(qmeta.get("admit_policy", "observe"))
        fq = qmeta.get("forecast") or {}
        on = (bool(fq["enabled"]) if "enabled" in fq
              else bool(qmeta.get("observe", False)))
        self._forecaster = (LaneForecaster(
            window=int(fq.get("window") or 8),
            min_points=int(fq.get("min_points") or 3))
            if on else None)
        self._mixing = (dict(fq["mixing"])
                        if fq.get("mixing") else None)
        self._forecast_ratios = [float(r)
                                 for r in fq.get("ratios") or ()]
        self.at_risk_total = int(fq.get("at_risk_total", 0))
        self.deferred_total = int(fq.get("deferred_total", 0))
        self._lane_eta = {}
        obs = qmeta.get("obs")
        if obs is not None:
            self.metrics = MetricsRegistry.load_state(obs["metrics"])
            self.spans = (SpanRecorder.load_state(obs["spans"])
                          if obs.get("spans") is not None else None)
            self._degraded_spanned = int(obs.get("degraded_spanned", 0))
        else:
            # pre-flight-recorder archives (or observe=False fabrics)
            # restore with the plane in the state the saver had it
            on = bool(qmeta.get("observe", False))
            self.metrics = MetricsRegistry() if on else None
            self.spans = SpanRecorder() if on else None
            self._degraded_spanned = 0
        self._probe = None
        self._boundaries = []
        self.probe_manifest = False
        self._probe_rows = []
        self._watchdog = None
        # watchdog runtime rides the archive; attach_watchdog (called
        # by recover() with the persisted config) resumes it
        self._watchdog_pending_state = qmeta.get("watchdog_state")
        self._init_resilience()
        self._probe_floor = _probe_jit()._cache_size()
        # the PR-13 regression probe (analysis/aliasing.py): lane-table
        # restore must not have re-introduced a mirror-aliased leaf
        from flow_updating_tpu.analysis.aliasing import (
            assert_no_shared_mirrors,
        )

        assert_no_shared_mirrors(self)
        return self
