"""Multi-tenant query fabric: thousands of concurrent aggregates on one
compiled engine.

The ``(N, D)`` payload feature axis is a bit-exact lane machine (each
feature lane is an independent scalar protocol instance sharing one set
of messages — models/state.py); this package promotes it to a **query
axis** on top of the streaming service engine: each lane is an
independent aggregate with its own value stream, node-cohort mask, start
round and lifecycle, admitted into free lanes with ZERO recompiles and
retired/recycled mid-flight between scan segments.  See
:mod:`flow_updating_tpu.query.fabric` and docs/QUERY.md.
"""

from flow_updating_tpu.query.fabric import QueryFabric

__all__ = ["QueryFabric"]
