"""Auto-mode plan selection: pick the fastest correct execution per
(topology, backend).

``Engine(plan='auto')`` calls :func:`select_plan` after resolving the
topology: candidates are enumerated from what the config *permits*
(the node-collapsed kernel covers exactly the fast synchronous
collect-all mode; everything else runs the edge kernel), each candidate
gets a predicted per-round cost from an analytic HBM-traffic model
(streamed element-passes, with the backend's dynamic-gather penalty —
the measured ~10 ns/element scalar-loop lowering on TPU is why the
Benes/banded paths exist at all, BENCH_NOTES.md), and the cheapest wins.
``probe='aot'`` replaces the analytic numbers with XLA's own
``cost_analysis()`` bytes/flops for the lowered candidate programs
(:mod:`flow_updating_tpu.obs.profile` — the ``plan --probe`` CLI path).

The fat-tree record is protected by construction: a topology carrying a
generator structure descriptor always selects the structured stencil
(its closed-form indexing beats any masked-band emulation of itself).
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from flow_updating_tpu.plan.compile import ExecutionPlan, compile_topology

#: relative cost of one dynamically-gathered element vs one streamed
#: element, per backend.  TPU lowers x[idx] to a scalar loop at ~10 ns
#: per element (BENCH_NOTES.md) while a dense streamed pass moves ~200 G
#: elements/s — a ratio of order 2000, which is exactly why the k=160
#: Benes network (~90 streamed stages) beats the one-gather xla path by
#: an order of magnitude.  CPU gathers are vectorized but cache-hostile.
#: 'axon' is the tunneled TPU platform name.
GATHER_COST = {"tpu": 2000.0, "axon": 2000.0, "cpu": 8.0}
DEFAULT_GATHER_COST = 8.0

#: per-collective launch overhead charged in wire-byte equivalents when
#: ranking halo exchange modes (a few-microsecond collective setup at
#: ~GB/s effective ICI bandwidth) — what makes the single-collective
#: allgather competitive when the cut is tiny but the offset count is
#: large, and irrelevant once real payload bytes dominate
HALO_LATENCY_BYTES = 8192.0

#: interior-to-cut work ratio at which the overlap schedule fully hides
#: the wire: one cut-edge payload byte costs roughly this many interior
#: edge-updates' worth of time to move, so intra/cut >= the ratio means
#: the exchange finishes inside the interior pass
OVERLAP_HIDE_RATIO = 4.0


@dataclasses.dataclass(frozen=True)
class PlanDecision:
    """What auto mode chose, and why (manifest-ready)."""

    kernel: str                 # 'edge' | 'node'
    spmv: str | None            # node kernel only
    plan: ExecutionPlan | None  # banded plans carry the compiled plan
    backend: str
    predicted: dict             # candidate -> predicted per-round cost
    reason: str
    fused: dict | None = None   # measured-probe autotune record (tile /
    #                             remainder route / per-candidate rates)
    #                             when the fused round was probed

    def describe(self) -> dict:
        out = {
            "kernel": self.kernel,
            "spmv": self.spmv,
            "backend": self.backend,
            "predicted_cost": {k: (round(float(v), 1)
                                   if isinstance(v, (int, float)) else v)
                               for k, v in self.predicted.items()},
            "reason": self.reason,
        }
        if self.fused is not None:
            out["autotune"] = self.fused
        if self.plan is not None:
            out["plan"] = self.plan.describe()
        return out


def _backend_name(backend: str | None) -> str:
    if backend:
        return backend
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def _remainder_cost(s, cg: float, N: float) -> float:
    """Streamed-pass cost of a plan's out-of-band remainder — shared by
    the banded and banded_fused candidates (both ride the same lanes)."""
    if s.rem_mode == "gather":
        return cg * (s.remainder_edges + N)  # + unpermute gather
    if s.rem_mode == "benes":
        P = float(s.rem_ns_plan.P)
        cost = len(s.rem_ns_plan.stages.dists) * P
        return cost + len(s.rem_unperm_plan.stages.dists) \
            * float(s.rem_unperm_plan.stages.n)
    return 0.0


def _analytic_costs(topo, plan: ExecutionPlan | None, backend: str,
                    candidates) -> dict:
    """Predicted per-round cost in streamed-element-pass units."""
    N = float(topo.num_nodes)
    E = float(topo.num_edges)
    cg = GATHER_COST.get(backend, DEFAULT_GATHER_COST)
    out = {}
    for cand in candidates:
        if cand == "node/structured":
            out[cand] = 4.0 * N
        elif cand == "node/xla":
            # bucketed gather of E neighbor slots + elementwise recurrence
            out[cand] = cg * E + 6.0 * N
        elif cand == "node/banded":
            s = plan.spmv
            out[cand] = (3.0 * len(s.offsets) * N + 6.0 * N
                         + _remainder_cost(s, cg, N))
        elif cand == "node/banded_fused":
            # the one-kernel round: every band lane reads its operands
            # from VMEM, so HBM traffic collapses to ~one read+write of
            # the state planes plus the bitpacked masks (L/8 bytes per
            # element-pass equivalent); the remainder rides the same
            # lanes as node/banded
            s = plan.spmv
            out[cand] = ((12.0 + len(s.offsets) / 8.0) * N
                         + _remainder_cost(s, cg, N))
        elif cand == "node/benes":
            from flow_updating_tpu.ops.permute import next_pow2

            P = float(next_pow2(int(E + N + 1)))
            out[cand] = (3 * np.log2(max(P, 2)) + 2) * P + 6.0 * N
        elif cand == "edge/gather":
            # ~a dozen streamed passes over (E,) state + 3 edge gathers
            out[cand] = 12.0 * E + 3.0 * cg * E
        else:
            raise ValueError(f"unknown candidate {cand!r}")
    return out


def _aot_costs(topo, cfg, plan, candidates) -> dict:
    """Replace analytic predictions with XLA ``cost_analysis`` bytes for
    the actually-lowered 1-round programs (CPU-safe; compiles each
    candidate once)."""
    import dataclasses as _dc

    from flow_updating_tpu.obs.profile import profile_program

    out = {}
    for cand in candidates:
        kernel, _, impl = cand.partition("/")
        try:
            if kernel == "node":
                from flow_updating_tpu.models import sync

                c = _dc.replace(cfg, kernel="node", spmv=impl)
                k = sync.NodeKernel(topo, c, plan=plan)
                fn, args, nd = k.round_program(k.init_state(), 1)
            else:
                from flow_updating_tpu.models.rounds import run_rounds
                from flow_updating_tpu.models.state import init_state

                c = _dc.replace(cfg, kernel="edge")
                arrays = topo.device_arrays(coloring=c.needs_coloring)
                fn, args, nd = (run_rounds,
                                (init_state(topo, c), arrays, c, 1), 2)
            rec = profile_program(fn, args, n_dynamic=nd, execute=False,
                                  label=f"plan:{cand}")
            bytes_ = rec["cost"].get("bytes_accessed")
            out[cand] = float(bytes_) if bytes_ else float("inf")
        except Exception as exc:  # a candidate that fails to lower loses
            out[cand] = float("inf")
            out[f"{cand}#error"] = f"{type(exc).__name__}: {exc}"[:200]
    return out


def select_halo_mode(plan, *, backend: str | None = None,
                     dtype_bytes: int = 4) -> dict:
    """Rank the halo kernel's cut-edge exchange modes for a built
    :class:`~flow_updating_tpu.parallel.sharded.ShardPlan`, using the
    measured cut-edge bytes already in the halo plan report
    (``plan.collective_bytes_per_round``).

    The model charges each mode its wire bytes plus a per-collective
    launch overhead, and credits the overlap schedule with the fraction
    of the wire the interior compute can hide (saturating once the
    intra-shard edge count exceeds :data:`OVERLAP_HIDE_RATIO` x the cut
    count).  Ties break toward the simpler serialized mode.  Returns a
    manifest-ready dict with the chosen ``halo`` and the evidence —
    ``Engine(halo='auto')`` resolves through this and records it."""
    backend = _backend_name(backend)
    rep = plan.collective_bytes_per_round(dtype_bytes)
    cut = rep["cut_edges"]
    intra = plan.topo.num_edges - cut
    n_off = max(rep["num_offsets"], 1)
    if cut == 0:
        return {"halo": "ppermute", "backend": backend,
                "cut_edges": 0, "intra_edges": intra,
                "predicted_effective_bytes": {},
                "reason": "no cut edges: nothing on the wire, the "
                          "point-to-point path compiles to no collective"}
    hide = float(min(1.0, intra / (cut * OVERLAP_HIDE_RATIO)))
    predicted = {
        "allgather": rep["allgather_bytes"] + 3 * HALO_LATENCY_BYTES,
        "ppermute": rep["ppermute_bytes"] + n_off * HALO_LATENCY_BYTES,
        "overlap": (rep["ppermute_bytes"] * (1.0 - hide)
                    + n_off * HALO_LATENCY_BYTES),
    }
    order = ("allgather", "ppermute", "overlap")  # ties -> simpler mode
    best = min(order, key=lambda k: predicted[k])
    return {
        "halo": best,
        "backend": backend,
        "cut_edges": cut,
        "intra_edges": intra,
        "hide_fraction": round(hide, 3),
        "predicted_effective_bytes": {k: round(v, 1)
                                      for k, v in predicted.items()},
        "reason": (f"{best} cheapest: cut={cut} edge payloads "
                   f"({rep['ppermute_bytes']} B point-to-point, "
                   f"{rep['allgather_bytes']} B broadcast) over "
                   f"{n_off} offset(s); interior {intra} edges hides "
                   f"{100 * hide:.0f}% of the wire under overlap"),
    }


#: per-round control-plane cost of one edge-kernel round, in streamed
#: edge-element-pass units — the payload-INDEPENDENT work (firing masks,
#: delivery selects, segment folds on the scalar control arrays) that
#: every underlying round pays whatever its payload width.  Measured on
#: the CPU proxy: a D=64 round costs ~2x a D=1 round, so the control
#: plane weighs about as much as ~60-80 payload lanes' streaming.
CONTROL_LANES_EQUIV = 64.0

#: per-visit overhead of the chunked schedule's scan machinery (slice +
#: stack of the chunk-major wire-state leaves), in payload-lane-pass
#: units per visit — amortized by rounds_per_visit
CHUNK_VISIT_LANES_EQUIV = 192.0


def select_payload_schedule(topo, *, features: int,
                            backend: str | None = None,
                            dtype_bytes: int = 4,
                            chunk: int | None = None,
                            rounds_per_visit: int | None = None,
                            anchor_features: int = 64,
                            max_round_bytes: float | None = None) -> dict:
    """The payload-bytes term of plan='auto' for deep-payload (DFL)
    runs: rank the chunked pipelined schedule against the monolithic
    one from the measured edge count and payload bytes
    (:func:`flow_updating_tpu.obs.profile.payload_bytes_per_round`),
    and pick the chunk width / visit length that maximizes predicted
    PER-LANE THROUGHPUT — i.e. wall-clock per full model stream, the
    quantity a training loop feels.  (This is deliberately NOT the
    bench's ``dfl_efficiency`` metric: that one normalizes the round
    rate by per-round bytes at a FIXED anchor width, so it compares
    schedules that move anchor-sized rounds; the two agree only at
    ``w == anchor``.  A planner optimizing rate-per-round-byte would
    always shrink chunks without bound — lane throughput is the
    decision-relevant objective.)

    The model: one underlying round of payload width ``w`` costs
    ``E * (w + CONTROL_LANES_EQUIV)`` streamed lane-passes, plus the
    chunked schedule's per-visit scan overhead amortized over
    ``rounds_per_visit``.  Its lane throughput relative to the anchor
    width ``a`` (the D=64 record) is

        lane_throughput(w, rpv) = (cost(a) / a) / (cost(w, rpv) / w)

    — monotonically better with larger ``w`` (control amortizes) and
    larger ``rpv`` (scan slicing amortizes).  What caps ``w`` is the
    PER-ROUND wire window ``max_round_bytes`` (per-message size limits,
    per-device HBM wire-state budget, latency-to-first-progress — the
    pipelining rationale of arXiv:1504.03277): schedules whose
    ``E * w * dtype_bytes`` exceeds it are excluded, which is exactly
    when the chunked schedule earns its keep.  With no window (the CPU
    proxy default) the monolithic schedule's fully-amortized control
    plane wins, and the decision records WHY.  Explicit ``chunk`` /
    ``rounds_per_visit`` pin those knobs; 'auto' searches the divisor
    grid and reports the ranking."""
    backend = _backend_name(backend)
    from flow_updating_tpu.obs.profile import payload_bytes_per_round

    E = float(topo.num_edges)
    a = float(anchor_features)

    def visit_cost(w, rpv):
        # per-underlying-round lane-passes: payload + control + amortized
        # per-visit scan slice/stack of the chunk wire state
        return E * (w + CONTROL_LANES_EQUIV
                    + CHUNK_VISIT_LANES_EQUIV / max(rpv, 1))

    anchor_cost = E * (a + CONTROL_LANES_EQUIV)

    def lane_throughput(w, rpv, chunked):
        cost = visit_cost(w, rpv) if chunked else E * (w
                                                       + CONTROL_LANES_EQUIV)
        return (anchor_cost / a) / (cost / w)

    if features <= anchor_features:
        return {
            "schedule": "monolithic", "chunk": None,
            "rounds_per_visit": None, "backend": backend,
            "predicted_lane_throughput": {"monolithic": round(
                lane_throughput(features, 1, False), 3)},
            "bytes": payload_bytes_per_round(
                topo.num_edges, features, dtype_bytes=dtype_bytes),
            "reason": (f"D={features} <= anchor {anchor_features}: "
                       "nothing to pipeline"),
        }
    rpv_grid = ([int(rounds_per_visit)] if rounds_per_visit
                else [1, 4, 8, 16])
    if chunk:
        c_grid = [int(chunk)]
    else:
        c_grid = [c for c in (64, 128, 256, 512)
                  if c < features and features % c == 0]
    fits = lambda w: (max_round_bytes is None
                      or E * w * dtype_bytes <= max_round_bytes)
    predicted = {"monolithic": lane_throughput(features, 1, False)}
    if chunk:
        # an explicit chunk pins the schedule: the ranking still reports
        # the monolithic prediction, but only chunked candidates compete
        best_key, best_eff, best = None, -1.0, None
    elif fits(features):
        best_key, best_eff, best = ("monolithic",
                                    predicted["monolithic"], None)
    else:
        predicted["monolithic#excluded"] = (
            f"{int(E * features * dtype_bytes)} B/round exceeds the "
            f"{int(max_round_bytes)} B wire window")
        best_key, best_eff, best = None, -1.0, None
    for c in c_grid:
        if not fits(c):
            predicted[f"chunked_c{c}#excluded"] = "over wire window"
            continue
        for rpv in rpv_grid:
            eff = lane_throughput(c, rpv, True)
            key = f"chunked_c{c}_rpv{rpv}"
            predicted[key] = eff
            if eff > best_eff:
                best_key, best_eff, best = key, eff, (c, rpv)
    if best_key is None:
        raise ValueError(
            f"no payload schedule fits max_round_bytes="
            f"{max_round_bytes} (smallest candidate chunk moves "
            f"{int(E * min(c_grid or [features]) * dtype_bytes)} B)")
    chosen_chunk, chosen_rpv = best if best else (None, None)
    bytes_rep = payload_bytes_per_round(
        topo.num_edges, features, chunk=chosen_chunk,
        dtype_bytes=dtype_bytes)
    return {
        "schedule": "chunked" if best else "monolithic",
        "chunk": chosen_chunk,
        "rounds_per_visit": chosen_rpv,
        "backend": backend,
        "predicted_lane_throughput": {k: (round(v, 3)
                                          if isinstance(v, float) else v)
                                      for k, v in predicted.items()},
        "bytes": bytes_rep,
        "reason": (
            f"{best_key} maximizes predicted per-lane throughput "
            f"({best_eff:.2f}x the D={anchor_features} anchor): each "
            f"underlying round moves {bytes_rep['bytes_per_round']} B "
            f"over {topo.num_edges} directed edges instead of "
            f"{topo.num_edges * features * dtype_bytes} B monolithic; "
            f"control plane ~{CONTROL_LANES_EQUIV:.0f} lane-equivalents "
            "amortized per visit"),
    }


# ---------------------------------------------------------------------
# measured-probe autotune cache: band width x tile shape x remainder
# route, timed on-device, persisted keyed by (plan hash, backend, jax)
# ---------------------------------------------------------------------

#: cache file override (tests point it at a tmpdir); default lives in
#: the user cache so TPU pods reuse probes across runs
AUTOTUNE_CACHE_ENV = "FLOW_UPDATING_AUTOTUNE_CACHE"
#: '0' disables measured probing entirely (analytic ranking only)
AUTOTUNE_ENV = "FLOW_UPDATING_AUTOTUNE"
#: plan='auto' probes only above this node count: probing costs a few
#: candidate compiles, worth paying exactly when the round itself is
#: expensive (CI-scale graphs keep the analytic model)
AUTOTUNE_MIN_NODES = 4096

#: on-device timing probes run since import — conformance tests pin the
#: cache-hit contract ("second select_plan call does ZERO probes") on it
PROBE_COUNT = 0

#: persisted-cache traffic since import, the observable twin of the
#: probe-count contract: a cache hit must show here AND as
#: ``probes_run == 0``.  :func:`autotune_metrics` exports both counters
#: (plus per-probe measured rates) onto a MetricsRegistry, which is how
#: they reach the Prometheus text output and the plan manifest.
AUTOTUNE_CACHE_STATS = {"hits": 0, "misses": 0}

#: rounds per timing probe (one warm compile + this many timed rounds,
#: twice — enough to beat scheduler noise at probe scale, cheap enough
#: that a full candidate sweep stays a few seconds)
PROBE_ROUNDS = 16

#: once a candidate's WARM run alone exceeds this, its rate is taken
#: from that run instead of a second timed pass — a pathological
#: candidate (e.g. the Beneš remainder replayed on a CPU proxy at
#: ~0.06 r/s for ba100k) must cost one bounded measurement, not two
PROBE_BUDGET_S = 20.0


def autotune_cache_path() -> str:
    env = os.environ.get(AUTOTUNE_CACHE_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "flow_updating_tpu", "autotune.json")


def _autotune_key(topo, backend: str, features: int, *,
                  max_lanes: int, min_fill, remainder: str,
                  dtype: str) -> str:
    """Cache key: plan content hash x backend x jax version (x x64 —
    lowering differs) x the probe configuration — payload dtype and the
    plan-shaping knobs the probes ran under.  Any mismatch is a STALE
    entry that must re-probe, never silently reuse (a record tuned on
    gather-remainder f32 plans must not steer a benes-remainder or f64
    call)."""
    import jax

    from flow_updating_tpu.plan.compile import _topo_key

    tk = _topo_key(topo)
    x64 = bool(jax.config.read("jax_enable_x64"))
    mf = "auto" if min_fill is None else f"{float(min_fill):g}"
    return (f"v1|{backend}|jax{jax.__version__}|x64:{int(x64)}|"
            f"n{tk[0]}e{tk[1]}|{tk[2][:16]}|f{int(features)}|"
            f"ml{int(max_lanes)}|mf{mf}|rem{remainder}|dt{dtype}")


def _load_autotune_cache(path: str) -> dict:
    try:
        with open(path) as fh:
            doc = json.load(fh)
        return doc if isinstance(doc, dict) else {}
    except (OSError, ValueError):
        return {}


def _store_autotune_entry(path: str, key: str, entry: dict) -> None:
    cache = _load_autotune_cache(path)
    cache[key] = entry
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(cache, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _probe_rate(kernel_factory, rounds: int) -> float:
    """Compile + warm one candidate, then time ``rounds`` rounds —
    rounds/s on the ambient device.  Every call counts one probe."""
    global PROBE_COUNT
    import time as _time

    import jax

    PROBE_COUNT += 1
    kern = kernel_factory()
    state = kern.init_state()
    t0 = _time.perf_counter()
    jax.block_until_ready(kern.run(state, rounds))  # compile + warm
    warm_s = _time.perf_counter() - t0
    if warm_s > PROBE_BUDGET_S:
        # slow enough that compile noise is irrelevant — and a second
        # multi-minute pass would not change the ranking
        return rounds / warm_s
    t0 = _time.perf_counter()
    jax.block_until_ready(kern.run(state, rounds))
    return rounds / max(_time.perf_counter() - t0, 1e-9)


def _fused_tile_candidates(plan) -> list:
    """Tile heights worth probing for one plan: the heuristic default,
    a 4x coarser tile (fewer grid steps, more VMEM), and the whole
    array when it differs — all validated against the bandwidth."""
    from flow_updating_tpu.ops.pallas_round import choose_block_rows

    H = max((abs(d) for d in plan.spmv.offsets), default=0)
    base = choose_block_rows(plan.spmv.n, H)
    cands = [base]
    if base * 4 * 128 < plan.spmv.n * 2:
        cands.append(base * 4)
    return sorted(set(cands))


def autotune_fused(topo, cfg, *, backend: str | None = None,
                   features: int = 0, max_lanes: int = 96,
                   min_fill: float | None = None,
                   remainder: str = "auto",
                   cache_path: str | None = None,
                   force: bool = False) -> dict:
    """Measured-probe autotune for the banded family: time the unfused
    banded executor and the one-kernel fused round over the band-width
    (``min_fill``) x tile x remainder-route grid, on the ambient
    device, and persist the record keyed by (plan content hash,
    backend, jax version).  A cache hit returns the stored record with
    ``probes_run == 0`` — the planner learns real rates once per
    (graph, environment).

    The record's ``measured_rounds_per_sec`` block uses the candidate
    label space of :func:`select_plan` (``node/banded``,
    ``node/banded_fused``) so ``doctor``'s ``plan_selection`` check can
    judge the decision offline."""
    import dataclasses as _dc

    from flow_updating_tpu.models import sync

    backend = _backend_name(backend)
    path = cache_path or autotune_cache_path()
    cg = GATHER_COST.get(backend, DEFAULT_GATHER_COST)
    if remainder == "auto" and cg < 100.0:
        # gather-friendly backends: probe the CPU/small-graph remainder
        # form.  build_banded's own 'auto' upgrades to Beneš lanes
        # whenever the native router exists — the right TPU call, but a
        # pathological probe on a CPU proxy (~300x slower than the
        # gather form at ba100k; measured, this PR)
        remainder = "gather"
    key = _autotune_key(topo, backend, features, max_lanes=max_lanes,
                        min_fill=min_fill, remainder=remainder,
                        dtype=str(cfg.dtype))
    if not force:
        hit = _load_autotune_cache(path).get(key)
        if isinstance(hit, dict) and "measured_rounds_per_sec" in hit:
            AUTOTUNE_CACHE_STATS["hits"] += 1
            return {**hit, "probes_run": 0, "cache": "hit"}
    AUTOTUNE_CACHE_STATS["misses"] += 1
    base_fill = min_fill if min_fill is not None \
        else float(np.clip(3.0 / cg, 1.0 / 64.0, 0.75))
    # band-width axis: the selector's fill plus one coarser band set
    # (fewer lanes, fatter remainder) when it changes the plan
    fills = sorted({round(float(base_fill), 6),
                    round(float(min(0.75, base_fill * 8)), 6)})
    probes = 0
    candidates: dict = {}
    best = None
    fam_best: dict = {}     # family -> (rate, plan, mf, tile, route)
    cfg_b = _dc.replace(cfg, kernel="node", spmv="banded")
    cfg_f = _dc.replace(cfg, kernel="node", spmv="banded_fused")
    plans = {}
    for mf in fills:
        plan = compile_topology(topo, max_lanes=max_lanes, min_fill=mf,
                                remainder=remainder, features=features)
        sig = (len(plan.spmv.offsets), plan.spmv.rem_mode)
        if sig in plans:
            continue        # a coarser fill that changed nothing
        plans[sig] = (mf, plan)
    for mf, plan in plans.values():
        label_b = f"node/banded[min_fill={mf}]"
        rate = _probe_rate(
            lambda plan=plan: sync.NodeKernel(topo, cfg_b, plan=plan),
            PROBE_ROUNDS)
        probes += 1
        candidates[label_b] = rate
        if best is None or rate > best[0]:
            best = (rate, "banded", mf, None, None)
        if "banded" not in fam_best or rate > fam_best["banded"][0]:
            fam_best["banded"] = (rate, plan, mf, None, None)
        routes = ["lanes"]
        if plan.spmv.rem_mode in ("gather",):
            routes.append("inline")
        if plan.spmv.rem_mode == "none":
            routes = ["auto"]
        for tile in _fused_tile_candidates(plan):
            for route in routes:
                label = (f"node/banded_fused[min_fill={mf},tile={tile},"
                         f"rem={route}]")
                try:
                    rate = _probe_rate(
                        lambda plan=plan, tile=tile, route=route:
                        sync.NodeKernel(topo, cfg_f, plan=plan,
                                        fused_tile=tile,
                                        fused_remainder=route),
                        PROBE_ROUNDS)
                except (ValueError, RuntimeError) as exc:
                    candidates[f"{label}#error"] = \
                        f"{type(exc).__name__}: {exc}"[:160]
                    continue
                probes += 1
                candidates[label] = rate
                if rate > best[0]:
                    best = (rate, "banded_fused", mf, tile, route)
                if ("banded_fused" not in fam_best
                        or rate > fam_best["banded_fused"][0]):
                    fam_best["banded_fused"] = (rate, plan, mf, tile, route)
    rate_banded = max((v for k, v in candidates.items()
                       if isinstance(v, (int, float))
                       and k.startswith("node/banded[")), default=0.0)
    rate_fused = max((v for k, v in candidates.items()
                      if isinstance(v, (int, float))
                      and k.startswith("node/banded_fused[")),
                     default=0.0)
    entry = {
        "key": key,
        "backend": backend,
        # the remainder route the probe plans were COMPILED with — the
        # consumer must ship a plan of the same family (select_plan
        # recompiles to match before applying best.fused_remainder)
        "remainder": remainder,
        "probe_rounds": PROBE_ROUNDS,
        "candidates": {k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in candidates.items()},
        "measured_rounds_per_sec": {
            k: round(v, 3) for k, v in
            (("node/banded", rate_banded),
             ("node/banded_fused", rate_fused)) if v > 0},
        "best": {
            "spmv": "banded_fused" if best[1] == "banded_fused"
            else "banded",
            "min_fill": best[2],
            "fused_tile": best[3],
            "fused_remainder": best[4],
            "rounds_per_sec": round(best[0], 3),
        },
        "probes_run": probes,
    }
    _annotate_roofline(entry, fam_best, topo, cfg_b, cfg_f)
    _store_autotune_entry(path, key, entry)
    return {**entry, "cache": "miss"}


def _annotate_roofline(entry: dict, fam_best: dict, topo,
                       cfg_b, cfg_f) -> None:
    """Attach a perf-lens block to a fresh autotune record: each probe
    family's best candidate is lowered once more (``execute=False`` —
    cost/memory only, no extra device time) and its measured probe rate
    reconciled against the ambient backend's roofline ceiling.  Opt-in
    via ``FLOW_UPDATING_ROOFLINE`` and fully contained — a lens failure
    never loses the probe record."""
    from flow_updating_tpu.obs import roofline as _roof

    if not _roof.enabled() or not fam_best:
        return
    try:
        from flow_updating_tpu.models import sync
        from flow_updating_tpu.obs.profile import profile_program

        model = _roof.resolve_model()
        programs = []
        fracs = {}
        for fam in sorted(fam_best):
            rate, plan, mf, tile, route = fam_best[fam]
            if fam == "banded_fused":
                kern = sync.NodeKernel(topo, cfg_f, plan=plan,
                                       fused_tile=tile,
                                       fused_remainder=route)
            else:
                kern = sync.NodeKernel(topo, cfg_b, plan=plan)
            fn, fargs, nd = kern.round_program(kern.init_state(),
                                               PROBE_ROUNDS)
            rec = profile_program(fn, fargs, n_dynamic=nd,
                                  execute=False,
                                  label=f"autotune/{fam}")
            mode = f"autotune/node/{fam}"
            rl = _roof.reconcile(
                _roof.analyze(rec, model, rounds=PROBE_ROUNDS,
                              mode=mode),
                rate)
            programs.append(rl)
            if rl.get("roofline_frac") is not None:
                fracs[f"node/{fam}"] = rl["roofline_frac"]
        if programs:
            entry["roofline"] = _roof.perf_lens_block(programs, model)
        if fracs:
            entry["roofline_frac"] = fracs
    except Exception as exc:      # noqa: BLE001 — lens must not break probes
        entry["roofline_error"] = f"{type(exc).__name__}: {exc}"[:160]


def autotune_metrics(registry, record: dict | None = None) -> None:
    """Export the autotune cache counters (and, when a record is given,
    its per-family measured rates and roofline fracs) into a
    :class:`~flow_updating_tpu.obs.metrics.MetricsRegistry` — the
    Prometheus face of the measured-probe cache."""
    registry.set_counter("autotune_cache_hits_total",
                         AUTOTUNE_CACHE_STATS["hits"])
    registry.set_counter("autotune_cache_misses_total",
                         AUTOTUNE_CACHE_STATS["misses"])
    registry.set_counter("autotune_probes_total", PROBE_COUNT)
    if not isinstance(record, dict):
        return

    def _slug(s: str) -> str:
        return "".join(c if c.isalnum() else "_" for c in s).strip("_")

    for label, rate in (record.get("measured_rounds_per_sec")
                        or {}).items():
        if isinstance(rate, (int, float)):
            registry.set_gauge(f"autotune_rate_{_slug(label)}",
                               float(rate))
    for label, frac in (record.get("roofline_frac") or {}).items():
        if isinstance(frac, (int, float)):
            registry.set_gauge(f"autotune_roofline_frac_{_slug(label)}",
                               float(frac))


def select_plan(topo, cfg, *, backend: str | None = None,
                features: int = 0, probe: str = "analytic",
                max_lanes: int = 96, min_fill: float | None = None,
                remainder: str = "auto",
                autotune: bool | None = None) -> PlanDecision:
    """Choose kernel/spmv for ``(topo, cfg, backend)``.

    Returns a :class:`PlanDecision`; ``decision.plan`` is the compiled
    :class:`ExecutionPlan` when the banded path won (or was a
    candidate), else None.  ``probe='aot'`` ranks candidates by XLA's
    own cost analysis instead of the analytic model."""
    backend = _backend_name(backend)
    if not cfg.is_fast_sync_collectall:
        # only the edge kernel implements these dynamics; there is one
        # correct program, nothing to rank
        return PlanDecision(
            kernel="edge", spmv=None, plan=None, backend=backend,
            predicted={}, reason=(
                "config requires the general edge kernel "
                f"(variant={cfg.variant!r}, fire_policy="
                f"{cfg.fire_policy!r}, drop_rate={cfg.drop_rate}); "
                "plan reordering stays available via "
                "plan.compile_topology for locality studies"))
    if topo.structure is not None and not features:
        return PlanDecision(
            kernel="node", spmv="structured", plan=None, backend=backend,
            predicted={}, reason=(
                "generator attached a closed-form structure descriptor "
                f"({type(topo.structure).__name__}): the exact stencil "
                "beats any banded emulation of itself"))
    if topo.virtual:
        raise ValueError(
            "cannot plan a virtual topology (no edge arrays); it only "
            "runs the structured stencil")
    cg = GATHER_COST.get(backend, DEFAULT_GATHER_COST)
    if min_fill is None:
        # lane economics: one roll lane costs ~3 streamed passes over
        # the n-vector and absorbs count_d edges of per-edge gather cost
        # — the break-even diagonal fill is 3/cg (clamped to sane bounds)
        min_fill = float(np.clip(3.0 / cg, 1.0 / 64.0, 0.75))
    if remainder == "auto" and not features and cg >= 100.0:
        # on a gather-hostile backend even a tiny remainder should ride
        # the Benes lanes: the bucketed-gather fallback pays cg on the
        # N-element unpermute alone.  Routing needs the C++ router to be
        # tractable at scale; without it the gather fallback stands.
        from flow_updating_tpu import native

        if native.available():
            remainder = "benes"
    plan = compile_topology(topo, max_lanes=max_lanes, min_fill=min_fill,
                            remainder=remainder, features=features)
    candidates = ["node/banded", "node/banded_fused", "node/xla",
                  "edge/gather"]
    if probe == "aot":
        predicted = _aot_costs(topo, cfg, plan, candidates)
    else:
        predicted = _analytic_costs(topo, plan, backend, candidates)

    # measured probes (cached): band width x tile x remainder route
    # timed on the ambient device — real rates replace the modeled
    # banded-family ranking when available
    if autotune is None:
        autotune = (os.environ.get(AUTOTUNE_ENV, "1") != "0"
                    and topo.num_nodes >= AUTOTUNE_MIN_NODES
                    and backend == _backend_name(None))
    tune = None
    if autotune:
        tune = autotune_fused(topo, cfg, backend=backend,
                              features=features, max_lanes=max_lanes,
                              min_fill=min_fill, remainder=remainder)
        rates = tune.get("measured_rounds_per_sec", {})
        rb, rf = rates.get("node/banded"), rates.get("node/banded_fused")
        if rb and rf and "node/banded" in predicted:
            # re-anchor the fused candidate on the measured ratio so it
            # stays comparable with the analytic xla/edge entries
            predicted["node/banded_fused"] = \
                predicted["node/banded"] * rb / rf
            predicted["node/banded_fused#measured"] = \
                f"{rf:.4g} r/s vs banded {rb:.4g} r/s (probed)"
    numeric = [c for c in candidates
               if isinstance(predicted.get(c), (int, float))]
    best = min(numeric, key=lambda c: predicted[c])
    kernel, _, impl = best.partition("/")
    s = plan.spmv
    fused_kw = None
    if impl == "banded_fused":
        fused_kw = {"fused_tile": None, "fused_remainder": "auto"}
        if tune is not None and \
                tune.get("best", {}).get("spmv") == "banded_fused":
            fused_kw = {"fused_tile": tune["best"].get("fused_tile"),
                        "fused_remainder":
                        tune["best"].get("fused_remainder") or "auto"}
            mf = tune["best"].get("min_fill")
            # ship the plan the probes actually RAN: the autotuner may
            # have probed a different remainder family (gather on CPU
            # proxies) or band width than the ranking plan — applying
            # its tile/route knobs to a foreign plan would mis-build
            # (inline route on a benes plan is a ValueError)
            probed_rem = tune.get("remainder", remainder)
            if (mf is not None and float(mf) != float(min_fill)) \
                    or probed_rem != remainder:
                plan = compile_topology(
                    topo, max_lanes=max_lanes,
                    min_fill=float(mf) if mf is not None else min_fill,
                    remainder=probed_rem, features=features)
                s = plan.spmv
    fused_doc = None
    if tune is not None:
        fused_doc = {k: tune[k] for k in
                     ("backend", "remainder", "candidates",
                      "measured_rounds_per_sec", "best", "probes_run",
                      "probe_rounds", "roofline", "roofline_frac",
                      "roofline_error")
                     if k in tune}
        fused_doc["cache"] = tune.get("cache")
    if fused_kw is not None:
        fused_doc = dict(fused_doc or {})
        fused_doc["chosen"] = fused_kw
    return PlanDecision(
        kernel=kernel, spmv=impl if kernel == "node" else None,
        plan=plan,  # losers keep the plan attached: stats feed manifests
        backend=backend, predicted=predicted,
        fused=fused_doc,
        reason=(f"{best} "
                + ("measured fastest" if tune is not None
                   and best in ("node/banded", "node/banded_fused")
                   else "predicted cheapest")
                + f" on {backend} "
                f"(bands cover {100 * s.coverage:.1f}% of edges in "
                f"{len(s.offsets)} lane(s), remainder via "
                f"{s.rem_mode}; bandwidth "
                f"{plan.stats['bandwidth_before']} -> "
                f"{plan.stats['bandwidth_after']} after RCM)"),
    )
