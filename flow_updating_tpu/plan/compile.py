"""``compile_topology``: one pass from an arbitrary graph to a plan.

The :class:`ExecutionPlan` binds together

* the RCM node order (``order[new] = old``) and its inverse,
* the reordered :class:`~flow_updating_tpu.topology.graph.Topology` —
  rebuilt with the *stable* edge relabeling
  (:func:`reorder_topology_stable`), which preserves every node's
  within-row edge order and records the edge permutation, so the edge
  kernel run on the plan's topology evolves **bit-for-bit** like the
  original-order run (per-node segment sums add the same floats in the
  same order; the ``drop_perm`` lane keeps fault-injection PRNG draws
  aligned with original edge ids),
* the banded spmv plan + its device leaves for the node kernel
  (``spmv='banded'``), and
* the statistics auto-selection and ``plan --explain`` consume
  (bandwidth before/after, lane count, band coverage, remainder
  fraction and route).

Plans are cached per (topology content, build knobs) in a small
in-process cache: the Engine, the bench and the CLI all compile the same
graph.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from flow_updating_tpu.plan.banded import (
    BandedLeaves,
    BandedSpmvPlan,
    build_banded,
)
from flow_updating_tpu.plan.rcm import adjacency_bandwidth, rcm_order
from flow_updating_tpu.topology.graph import Topology


def reorder_topology_stable(topo: Topology, order: np.ndarray,
                            ) -> tuple[Topology, np.ndarray]:
    """Renumber nodes by ``order`` keeping each row's ORIGINAL edge
    order.

    Unlike :func:`topology.graph.reorder_topology` (which lexsorts by
    ``(new_src, new_dst)``), edges here are grouped by new source but
    kept in their original relative order within each row.  Per-node
    reductions over out-edges therefore add the exact same floats in the
    exact same order as the un-reordered kernel — the property that
    makes a planned edge-kernel run bit-identical to the original after
    unpermutation (tests/test_plan.py).  Returns ``(topology,
    edge_order)`` with ``edge_order[new_e] = old_e``.
    """
    N, E = topo.num_nodes, topo.num_edges
    order = np.asarray(order, np.int64)
    inv = np.empty(N, np.int64)
    inv[order] = np.arange(N, dtype=np.int64)
    new_src = inv[topo.src]
    new_dst = inv[topo.dst]
    # stable: ties (same new source row) keep original edge order
    e_order = np.argsort(new_src, kind="stable")
    e_pos = np.empty(E, np.int64)
    e_pos[e_order] = np.arange(E, dtype=np.int64)
    src = new_src[e_order].astype(np.int32)
    dst = new_dst[e_order].astype(np.int32)
    rev = e_pos[topo.rev[e_order]].astype(np.int32)
    out_deg = topo.out_deg[order]
    row_start = np.zeros(N + 1, np.int64)
    np.cumsum(out_deg, out=row_start[1:])
    edge_rank = (np.arange(E, dtype=np.int64)
                 - row_start[src]).astype(np.int32)
    pick_e = lambda a: None if a is None else a[e_order]
    out = dataclasses.replace(
        topo,
        src=src,
        dst=dst,
        rev=rev,
        out_deg=out_deg,
        row_start=row_start,
        edge_rank=edge_rank,
        delay=topo.delay[e_order],
        values=topo.values[order],
        names=(tuple(topo.names[i] for i in order)
               if topo.names is not None else None),
        speeds=None if topo.speeds is None else topo.speeds[order],
        bandwidth=pick_e(topo.bandwidth),
        latency_s=pick_e(topo.latency_s),
        adopted=None,
        edge_links=pick_e(topo.edge_links),
        lat_rounds=pick_e(topo.lat_rounds),
        # the generator's structure descriptor indexes the ORIGINAL node
        # layout; the reordered graph's structure IS the banded plan
        structure=None,
        # fault-injection PRNG draws stay keyed by ORIGINAL edge id, so
        # a drop>0 planned run replays the exact original loss pattern
        drop_perm=e_order.astype(np.int32),
    )
    cached = getattr(topo, "_edge_coloring", None)
    if cached is not None:
        # a coloring is an edge property, invariant under renumbering —
        # carrying the cache keeps fast-pairwise matching sequences
        # identical between planned and original runs (exact parity)
        col, c = cached
        object.__setattr__(out, "_edge_coloring", (col[e_order], c))
    return out, e_order


@dataclasses.dataclass(frozen=True, eq=False)
class ExecutionPlan:
    """One compiled topology: reorder + bands + remainder + stats.

    Identity-hashed (``eq=False``) so it can ride through jit as static
    metadata; the device arrays live in ``leaves``
    (:class:`~flow_updating_tpu.plan.banded.BandedLeaves`, a pytree).
    """

    order: np.ndarray          # (N,) new -> old node id
    inv_order: np.ndarray      # (N,) old -> new node id
    topo: Topology             # RCM-reordered, stable edge order
    edge_order: np.ndarray     # (E,) new -> old edge id
    spmv: BandedSpmvPlan
    leaves: BandedLeaves
    stats: dict
    source_key: tuple = ()     # _topo_key of the SOURCE topology — the
    #                            consumers' cheap guard against running a
    #                            plan on a different graph that merely
    #                            shares the node count (silently wrong
    #                            banded masks otherwise)

    @property
    def num_nodes(self) -> int:
        return self.topo.num_nodes

    def unpermute_nodes(self, arr: np.ndarray, axis: int = 0) -> np.ndarray:
        """Plan-order per-node array -> original node order."""
        arr = np.asarray(arr)
        out = np.empty_like(arr)
        idx = [slice(None)] * arr.ndim
        idx[axis] = self.order
        out[tuple(idx)] = arr
        return out

    def unpermute_edges(self, arr: np.ndarray, axis: int = 0) -> np.ndarray:
        """Plan-order per-edge array -> original edge order."""
        arr = np.asarray(arr)
        out = np.empty_like(arr)
        idx = [slice(None)] * arr.ndim
        idx[axis] = self.edge_order
        out[tuple(idx)] = arr
        return out

    def original_node_ids(self, new_ids: np.ndarray) -> np.ndarray:
        """Map plan-space node ids to original ids (negatives pass
        through — the padding convention of topk_idx)."""
        new_ids = np.asarray(new_ids, np.int64)
        safe = np.clip(new_ids, 0, self.num_nodes - 1)
        return np.where(new_ids >= 0, self.order[safe], new_ids)

    def describe(self) -> dict:
        """JSON-ready summary (plan manifests, ``plan`` CLI)."""
        s = self.spmv
        return {
            "nodes": int(self.topo.num_nodes),
            "directed_edges": int(self.topo.num_edges),
            "band_lanes": len(s.offsets),
            "band_offsets": list(s.offsets[:64]),
            "in_band_edges": int(s.in_band_edges),
            "remainder_edges": int(s.remainder_edges),
            "band_coverage": round(s.coverage, 6),
            "remainder_fraction": round(1.0 - s.coverage, 6),
            "remainder_impl": s.rem_mode,
            **{k: v for k, v in self.stats.items()},
        }


_plan_cache: dict = {}


def _topo_key(topo: Topology) -> tuple:
    import hashlib

    h = hashlib.sha1()
    h.update(np.ascontiguousarray(topo.src))
    h.update(np.ascontiguousarray(topo.dst))
    return (topo.num_nodes, topo.num_edges, h.hexdigest())


def compile_topology(topo: Topology, *, max_lanes: int = 96,
                     min_fill: float = 0.05, remainder: str = "auto",
                     features: int = 0) -> ExecutionPlan:
    """Compile ``topo`` into an :class:`ExecutionPlan`.

    Knobs: ``max_lanes`` bounds the dense roll lanes (each costs one
    streamed pass per neighbor sum); ``min_fill`` is the occupancy floor
    below which a diagonal goes to the remainder; ``remainder`` routes
    the out-of-band edges ('auto' | 'gather' | 'benes' | 'none');
    ``features`` > 0 declares a vector payload (rolls broadcast over it,
    the remainder then gathers).  Plans are cached on (topology content,
    knobs)."""
    topo._require_edges("compile_topology")
    key = (_topo_key(topo), max_lanes, float(min_fill), remainder,
           bool(features))
    cached = _plan_cache.get(key)
    if cached is not None:
        return cached
    t0 = time.perf_counter()
    order = rcm_order(topo)
    bw_before = adjacency_bandwidth(topo)
    bw_after = adjacency_bandwidth(topo, order)
    if bw_after > bw_before:
        # RCM never *has* to win; on a pre-banded input keep the
        # original order (identity) rather than degrade it
        order = np.arange(topo.num_nodes, dtype=np.int64)
        bw_after = bw_before
    reordered, e_order = reorder_topology_stable(topo, order)
    spmv, leaves = build_banded(
        reordered.num_nodes, reordered.src, reordered.dst,
        max_lanes=max_lanes, min_fill=min_fill, remainder=remainder,
        features=features,
    )
    inv = np.empty(topo.num_nodes, np.int64)
    inv[order] = np.arange(topo.num_nodes, dtype=np.int64)
    plan = ExecutionPlan(
        order=order, inv_order=inv, topo=reordered, edge_order=e_order,
        spmv=spmv, leaves=leaves, source_key=key[0],
        stats={
            "bandwidth_before": bw_before,
            "bandwidth_after": bw_after,
            "build_s": round(time.perf_counter() - t0, 6),
            "max_lanes": max_lanes,
            "min_fill": min_fill,
        },
    )
    _plan_cache[key] = plan
    while len(_plan_cache) > 4:   # plans hold O(N) host arrays
        _plan_cache.pop(next(iter(_plan_cache)))
    return plan
