"""Reverse Cuthill-McKee node ordering — bandwidth reduction on the host.

The banded executor (:mod:`flow_updating_tpu.plan.banded`) pays one
masked roll per occupied diagonal, so its cost is the number of distinct
``dst - src`` offsets the adjacency occupies.  RCM is the classic
bandwidth-reducing permutation: breadth-first layers from a
pseudo-peripheral vertex, neighbors visited in ascending-degree order,
the whole order reversed (George & Liu).  On lattices, paths, community
graphs and anything with spatial structure it concentrates the adjacency
into a few near-full diagonals; on expanders (ER, BA cores) no ordering
can — the band statistics it produces are exactly what the planner's
remainder-fraction heuristics consume (docs/PLANNER.md).

Pure numpy, level-vectorized (no per-node Python loop inside a level);
the same ragged-slice extraction as :func:`topology.graph.locality_order`.
"""

from __future__ import annotations

import numpy as np


def _level_neighbors(topo, frontier: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """All neighbors of ``frontier`` (with repeats), plus the frontier
    position each came from — vectorized ragged CSR slice extraction."""
    lo = topo.row_start[frontier]
    counts = topo.row_start[frontier + 1] - lo
    total = int(counts.sum())
    if not total:
        e = np.empty(0, np.int64)
        return e, e
    seg = np.repeat(np.arange(frontier.size, dtype=np.int64), counts)
    within = np.arange(total, dtype=np.int64) - np.repeat(
        np.cumsum(counts) - counts, counts
    )
    return topo.dst[lo[seg] + within].astype(np.int64), seg


def _cm_component(topo, start: int, visited: np.ndarray) -> np.ndarray:
    """Cuthill-McKee order of ``start``'s component: BFS where each
    level's nodes are taken parent-by-parent (in the parent's level
    order), each parent's unvisited neighbors in ascending degree."""
    deg = topo.out_deg
    visited[start] = True
    out = [np.array([start], np.int64)]
    frontier = out[0]
    while True:
        nbrs, seg = _level_neighbors(topo, frontier)
        if not nbrs.size:
            break
        # textbook CM ordering key: (parent position, degree, node id)
        order = np.lexsort((nbrs, deg[nbrs], seg))
        nbrs = nbrs[order]
        # dedup keeping the FIRST occurrence (earliest parent wins)
        _, first = np.unique(nbrs, return_index=True)
        nbrs = nbrs[np.sort(first)]
        nbrs = nbrs[~visited[nbrs]]
        if not nbrs.size:
            break
        visited[nbrs] = True
        out.append(nbrs)
        frontier = nbrs
    return np.concatenate(out)


def _pseudo_peripheral(topo, start: int) -> int:
    """George-Liu pseudo-peripheral vertex: walk to the farthest BFS
    level's minimum-degree node until the eccentricity stops growing."""
    deg = topo.out_deg
    ecc = -1
    for _ in range(8):  # converges in 2-3 hops in practice
        visited = np.zeros(topo.num_nodes, bool)
        visited[start] = True
        frontier = np.array([start], np.int64)
        last = frontier
        depth = 0
        while True:
            nbrs, _ = _level_neighbors(topo, frontier)
            nbrs = np.unique(nbrs)
            nbrs = nbrs[~visited[nbrs]]
            if not nbrs.size:
                break
            visited[nbrs] = True
            last = nbrs
            frontier = nbrs
            depth += 1
        if depth <= ecc:
            return start
        ecc = depth
        start = int(last[np.argmin(deg[last])])
    return start


def rcm_order(topo) -> np.ndarray:
    """Reverse Cuthill-McKee permutation: ``order[new_id] = old_id``.

    Covers every connected component (each started at a
    pseudo-peripheral vertex of minimum degree); isolated nodes land at
    the front of the reversed order, harmlessly.  A graph with no edges
    returns the identity."""
    N = topo.num_nodes
    if topo.num_edges == 0:
        return np.arange(N, dtype=np.int64)
    visited = np.zeros(N, bool)
    parts = []
    # scan components cheapest-first: the unvisited node of least degree
    deg_key = topo.out_deg.astype(np.int64) * N + np.arange(N)
    by_deg = np.argsort(deg_key, kind="stable")
    cursor = 0
    while True:
        while cursor < N and visited[by_deg[cursor]]:
            cursor += 1
        if cursor >= N:
            break
        seed = int(by_deg[cursor])
        if topo.out_deg[seed] > 0:
            seed = _pseudo_peripheral(topo, seed)
        parts.append(_cm_component(topo, seed, visited))
    order = np.concatenate(parts)
    return order[::-1].copy()


def adjacency_bandwidth(topo, order: np.ndarray | None = None) -> int:
    """Max |new(dst) - new(src)| over the edges — the half-bandwidth of
    the permuted adjacency (0 for an edgeless graph)."""
    if topo.num_edges == 0:
        return 0
    if order is None:
        return int(np.max(np.abs(topo.dst.astype(np.int64)
                                 - topo.src.astype(np.int64))))
    inv = np.empty(topo.num_nodes, np.int64)
    inv[np.asarray(order, np.int64)] = np.arange(topo.num_nodes,
                                                 dtype=np.int64)
    return int(np.max(np.abs(inv[topo.dst] - inv[topo.src])))


def offset_profile(topo, order: np.ndarray | None = None,
                   top: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Distinct signed diagonals ``new(dst) - new(src)`` and their edge
    counts, most-occupied first (``top`` > 0 truncates) — the raw band
    statistics the planner and ``plan --explain`` report."""
    if topo.num_edges == 0:
        e = np.empty(0, np.int64)
        return e, e
    if order is None:
        d = topo.dst.astype(np.int64) - topo.src.astype(np.int64)
    else:
        inv = np.empty(topo.num_nodes, np.int64)
        inv[np.asarray(order, np.int64)] = np.arange(topo.num_nodes,
                                                     dtype=np.int64)
        d = inv[topo.dst] - inv[topo.src]
    offs, counts = np.unique(d, return_counts=True)
    rank = np.argsort(-counts, kind="stable")
    offs, counts = offs[rank], counts[rank]
    if top:
        offs, counts = offs[:top], counts[:top]
    return offs, counts
