"""Banded neighbor sums: occupied diagonals as dense masked rolls.

After RCM reordering, most edges of a structured-ish graph sit on a few
near-full diagonals of the adjacency.  Each such diagonal ``d``
contributes ``where(mask_d, roll(x, -d), 0)`` to the neighbor sum — one
dense streamed pass per band, the exact shape that makes
``ops/structured.py`` fast, with a mask instead of closed-form index
arithmetic.  Edges on low-occupancy diagonals form the *remainder*,
routed through either

* the existing Benes permutation lanes (``ops/spmv_benes.py`` plans the
  remainder's ELL matrices as a gather-free switching network, and a
  second small Benes network un-permutes the bucket-ordered rows back to
  RCM order — no dynamic gather anywhere, the TPU form), or
* a plain bucketed ELL gather + row-reduce (the CPU/small-graph form).

The plan object is identity-hashed static metadata (like
``NeighborSumPlan``); the big mask/index arrays travel separately as
pytree leaves (:class:`BandedLeaves`) so they never become jaxpr
constants.  Exactness vs the generic gather neighbor sum is asserted in
``tests/test_plan.py`` (bit-for-bit on integer-valued payloads, where
float addition is exact regardless of order).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.utils import struct


@struct.dataclass
class BandedLeaves:
    """Device-side arrays of one banded plan (pytree leaves)."""

    band_masks: tuple      # per kept offset: (n,) bool — row u has edge u->u+d
    rem_mats: tuple = ()   # 'gather': bucketed (rows, w) int32 neighbor mats
    #                        in RCM node space (pad index n -> zero slot)
    rem_pos: object = None  # 'gather': (n,) int32 — RCM row -> bucket position
    rem_ns_masks: tuple = ()      # 'benes': remainder network stage masks
    rem_unperm_masks: tuple = ()  # 'benes': bucket-order -> RCM-order masks


@dataclasses.dataclass(frozen=True, eq=False)
class BandedSpmvPlan:
    """Static banded-spmv descriptor (identity-hashed, jit-static).

    ``offsets`` are the kept signed diagonals in ascending order;
    ``rem_mode`` is 'none' | 'gather' | 'benes'.  The companion
    :class:`BandedLeaves` (built by :func:`build_banded`) carries the
    arrays.
    """

    n: int                     # real node count (RCM space)
    offsets: tuple             # kept signed diagonals, ascending
    in_band_edges: int
    remainder_edges: int
    rem_mode: str
    rem_bucket_shapes: tuple = ()
    rem_ns_plan: object = None       # spmv_benes.NeighborSumPlan ('benes')
    rem_unperm_plan: object = None   # permute.PaddedPermPlan ('benes')

    @property
    def coverage(self) -> float:
        """In-band fraction of the directed edges."""
        total = self.in_band_edges + self.remainder_edges
        return self.in_band_edges / total if total else 1.0


def _remainder_ell(n: int, src: np.ndarray, dst: np.ndarray):
    """Degree-bucketed ELL matrices for the remainder adjacency, rows
    grouped by next-pow2 remainder degree (same policy as
    ``Topology.ell_buckets``: the power of two is only the grouping key;
    stored width is the bucket's true max degree).  Returns
    ``(mats, pos)`` with ``pos[row] = position of RCM row`` in the
    concatenated bucket output."""
    deg = np.bincount(src, minlength=n).astype(np.int64)
    row_start = np.zeros(n + 1, np.int64)
    np.cumsum(deg, out=row_start[1:])
    wkey = np.zeros(n, np.int64)
    nz = deg > 0
    wkey[nz] = 1 << np.ceil(np.log2(deg[nz])).astype(np.int64)
    order = np.argsort(wkey, kind="stable").astype(np.int64)
    pos = np.empty(n, np.int64)
    pos[order] = np.arange(n, dtype=np.int64)
    mats = []
    sorted_w = wkey[order]
    start = 0
    while start < n:
        key = sorted_w[start]
        end = int(np.searchsorted(sorted_w, key, side="right"))
        rows = order[start:end]
        w = int(deg[rows].max()) if key else 0
        if w == 0:
            mats.append(np.empty((len(rows), 0), np.int32))
        else:
            lo = row_start[rows]
            d = deg[rows]
            ar = np.arange(w, dtype=np.int64)
            valid = ar[None, :] < d[:, None]
            col = np.where(valid, lo[:, None] + ar[None, :], 0)
            mats.append(np.where(valid, dst[col], n).astype(np.int32))
        start = end
    return tuple(mats), pos.astype(np.int32)


def build_banded(n: int, src: np.ndarray, dst: np.ndarray, *,
                 max_lanes: int = 96, min_fill: float = 0.05,
                 remainder: str = "auto", features: int = 0,
                 ) -> tuple[BandedSpmvPlan, BandedLeaves]:
    """Build the banded plan for an adjacency already in RCM node order.

    ``src``/``dst`` are the directed edges (RCM ids, any order).  A
    diagonal d is kept as a band lane while it holds at least
    ``min_fill * n`` edges, up to ``max_lanes`` lanes (most-occupied
    first): each lane costs ~3 streamed passes over the n-vector
    regardless of fill, and absorbs ``count_d`` edges from the
    remainder's per-edge (gather or network) cost — so the economic
    floor is ``count_d > 3 / gather_cost_ratio * n`` and the caller
    tunes ``min_fill`` per backend (``plan/select.py``: ~0.03 on TPU
    where gathers serialize, ~0.75 on CPU).  RCM makes this work:
    bandwidth B means the surviving offsets are FEW (<= 2B+1), and on
    lattice/community graphs most hold O(n) edges.  ``remainder`` is
    'auto' | 'gather' | 'benes' | 'none' ('none' raises if any edge is
    left over; 'auto' plans Benes lanes only when the native router
    makes that tractable, else gathers).  Vector payloads (``features >
    0``) ride the rolls natively but force the gather remainder (the
    Benes lane packing is scalar)."""
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    E = len(src)
    offs = dst - src
    uq, counts = (np.unique(offs, return_counts=True) if E
                  else (np.empty(0, np.int64), np.empty(0, np.int64)))
    rank = np.argsort(-counts, kind="stable")
    uq, counts = uq[rank], counts[rank]
    keep_mask = counts >= max(min_fill * n, 1.0)
    kept = uq[keep_mask][:max_lanes]
    kept = np.sort(kept)

    band_masks = []
    in_band = np.zeros(E, bool)
    for d in kept:
        sel = offs == d
        m = np.zeros(n, bool)
        m[src[sel]] = True
        band_masks.append(m)
        in_band |= sel
    n_in = int(in_band.sum())
    rem_src, rem_dst = src[~in_band], dst[~in_band]
    n_rem = E - n_in

    mode = remainder
    if mode == "none" and n_rem:
        raise ValueError(
            f"remainder='none' but {n_rem} edge(s) fall outside the "
            f"{len(kept)} kept band(s) — allow a remainder path "
            "('gather'/'benes'/'auto') or widen min_fill/max_lanes")
    if n_rem == 0:
        mode = "none"
    elif mode == "auto":
        mode = "gather"
        if not features:
            from flow_updating_tpu import native

            # the Benes router in pure python takes hours at scale; only
            # the C++ router makes the remainder network tractable
            if native.available() and n_rem >= 1 << 12:
                mode = "benes"
    if features and mode == "benes":
        raise ValueError(
            "remainder='benes' packs scalar lanes; vector payloads "
            "route the remainder through 'gather'")

    rem_mats: tuple = ()
    rem_pos = None
    rem_ns_plan = None
    rem_ns_masks: tuple = ()
    rem_unperm_plan = None
    rem_unperm_masks: tuple = ()
    shapes: tuple = ()
    if mode in ("gather", "benes"):
        rem_mats, rem_pos = _remainder_ell(n, rem_src, rem_dst)
        shapes = tuple(m.shape for m in rem_mats)
        if mode == "benes":
            from flow_updating_tpu.ops.permute import padded_perm_plan
            from flow_updating_tpu.ops.spmv_benes import plan_neighbor_sum

            # m1 = n + 1: the zero slot follows the generic convention
            rem_ns_plan = plan_neighbor_sum(rem_mats, n + 1)
            rem_ns_masks = rem_ns_plan.device_masks()
            rem_unperm_plan = padded_perm_plan(rem_pos.astype(np.int64))
            rem_unperm_masks = rem_unperm_plan.device_masks()
            rem_mats, rem_pos = (), None  # network replaces the gather

    import jax.numpy as jnp

    leaves = BandedLeaves(
        band_masks=tuple(jnp.asarray(m) for m in band_masks),
        rem_mats=tuple(jnp.asarray(m) for m in rem_mats),
        rem_pos=None if rem_pos is None else jnp.asarray(rem_pos),
        rem_ns_masks=rem_ns_masks,
        rem_unperm_masks=rem_unperm_masks,
    )
    plan = BandedSpmvPlan(
        n=n, offsets=tuple(int(d) for d in kept), in_band_edges=n_in,
        remainder_edges=n_rem, rem_mode=mode, rem_bucket_shapes=shapes,
        rem_ns_plan=rem_ns_plan, rem_unperm_plan=rem_unperm_plan,
    )
    return plan, leaves


def banded_neighbor_sum(x, plan: BandedSpmvPlan, leaves: BandedLeaves):
    """A(x) over the first ``plan.n`` entries of a (possibly padded) RCM
    -ordered vector; padding slots get 0, matching
    :func:`ops.structured.structured_neighbor_sum`.  ``x`` may carry a
    trailing feature axis — the rolls and the gather remainder broadcast
    over it."""
    import jax.numpy as jnp

    n = plan.n
    xv = x[:n]
    feat = xv.shape[1:]
    acc = jnp.zeros_like(xv)
    for d, mask in zip(plan.offsets, leaves.band_masks):
        contrib = jnp.roll(xv, -d, axis=0)
        m = mask.reshape(mask.shape + (1,) * len(feat))
        acc = acc + jnp.where(m, contrib, 0)
    if plan.rem_mode in ("gather", "benes"):
        acc = acc + _remainder_term(xv, plan, leaves)
    if x.shape[0] == n:
        return acc
    pad = jnp.zeros((x.shape[0] - n,) + feat, x.dtype)
    return jnp.concatenate([acc, pad])


def _remainder_term(xv, plan: BandedSpmvPlan, leaves: BandedLeaves):
    """The remainder addend for an ``(n, ...)`` plan-order vector — THE
    one implementation both :func:`banded_neighbor_sum` and
    :func:`banded_remainder_sum` add, so the fused round's
    ``rem_route='lanes'`` bit-parity contract cannot drift."""
    if plan.rem_mode == "gather":
        from flow_updating_tpu.models.sync import neighbor_sum

        return neighbor_sum(xv, leaves.rem_mats)[leaves.rem_pos]
    from flow_updating_tpu.ops.permute import apply_padded_perm
    from flow_updating_tpu.ops.spmv_benes import neighbor_sum_benes

    a = neighbor_sum_benes(xv, plan.rem_ns_plan, leaves.rem_ns_masks)
    return apply_padded_perm(a, plan.rem_unperm_plan,
                             leaves.rem_unperm_masks)


def banded_remainder_sum(x, plan: BandedSpmvPlan, leaves: BandedLeaves):
    """The remainder-only addend of :func:`banded_neighbor_sum` (zeros
    when the plan has no remainder), padded like ``x`` — the
    ``rem_route='lanes'`` input of the one-kernel fused round
    (``ops/pallas_round.py``)."""
    import jax.numpy as jnp

    n = plan.n
    # a slice is emitted only when x really is padded, keeping the
    # banded executor's own lowering (via _remainder_term) byte-stable
    xv = x[:n] if x.shape[0] != n else x
    feat = xv.shape[1:]
    if plan.rem_mode in ("gather", "benes"):
        acc = _remainder_term(xv, plan, leaves)
    else:
        acc = jnp.zeros_like(xv)
    if x.shape[0] == n:
        return acc
    pad = jnp.zeros((x.shape[0] - n,) + feat, x.dtype)
    return jnp.concatenate([acc, pad])
