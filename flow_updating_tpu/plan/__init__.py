"""Topology compiler: any graph -> a near-regular execution plan.

The structured stencil (``ops/structured.py``) holds the perf record
because the fat-tree's regularity turns the neighbor-sum gather into
dense shifted slices; the general ``xla`` edge path on the same graph is
~900x slower (ROADMAP open item 1).  This package closes that gap for
*arbitrary* graphs with the "sparse graphs on dense hardware" recipe of
arXiv:1906.11786:

1. **Reorder** — reverse Cuthill-McKee over the symmetric adjacency
   (:mod:`flow_updating_tpu.plan.rcm`) concentrates edges near the
   diagonal;
2. **Band** — high-occupancy diagonals execute as dense masked rolls,
   exactly the shape that makes the structured stencil fast
   (:mod:`flow_updating_tpu.plan.banded`);
3. **Remainder** — what the bands do not absorb routes through the
   existing Benes permutation lanes (``ops/spmv_benes.py``) or a plain
   gather, whichever the backend prefers.

:func:`compile_topology` produces the static
:class:`~flow_updating_tpu.plan.compile.ExecutionPlan`;
:func:`select_plan` is the auto-mode policy (``Engine(plan='auto')``)
choosing kernel/spmv per (topology, backend) from analytic or AOT cost
models (``obs/profile.py``) — or from MEASURED on-device probes via the
persistent autotune cache (:func:`~flow_updating_tpu.plan.select.
autotune_fused`: band width x fused-round tile x remainder route, keyed
by plan hash x backend x jax version).  The banded plan itself executes
either as separate XLA ops (``spmv='banded'``) or as ONE VMEM-resident
Pallas kernel per round (``spmv='banded_fused'``,
``ops/pallas_round.py``; sharded form
``parallel/banded_sharded.py`` — one remote-DMA kernel per shard).
"""

from flow_updating_tpu.plan.banded import (
    BandedLeaves,
    BandedSpmvPlan,
    banded_neighbor_sum,
    banded_remainder_sum,
)
from flow_updating_tpu.plan.compile import (
    ExecutionPlan,
    compile_topology,
    reorder_topology_stable,
)
from flow_updating_tpu.plan.rcm import adjacency_bandwidth, rcm_order
from flow_updating_tpu.plan.select import (
    PlanDecision,
    autotune_fused,
    select_plan,
)

__all__ = [
    "BandedLeaves",
    "BandedSpmvPlan",
    "ExecutionPlan",
    "PlanDecision",
    "adjacency_bandwidth",
    "autotune_fused",
    "banded_neighbor_sum",
    "banded_remainder_sum",
    "compile_topology",
    "rcm_order",
    "reorder_topology_stable",
    "select_plan",
]
