"""ctypes bindings for the C++ native runtime (``src/funative.cpp``).

Builds ``libfunative.so`` on demand with g++ (no pybind11 — plain C ABI).
Every entry point has a numpy fallback so the framework works without a
compiler; the native paths matter at 1M-node scale (exact sequential
Barabási–Albert, graph builds) and for the DES baseline oracle.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

logger = logging.getLogger("flow_updating_tpu.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "funative.cpp")
_SO = os.path.join(_HERE, "_build", "libfunative.so")

_lib = None
_tried = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = [
        "g++", "-O3", "-march=native", "-std=c++17", "-fPIC", "-Wall",
        "-shared", "-o", _SO, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception as exc:  # compiler missing or failed
        logger.warning("native build failed (%s); using numpy fallbacks", exc)
        return False


def get_lib():
    """Load (building if needed) the native library, or None."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    fresh = os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    if not fresh and not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as exc:
        logger.warning("native load failed (%s); using numpy fallbacks", exc)
        return None
    i64, u64, i32p, i64p, f64p = (
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
    )
    lib.fu_gen_erdos_renyi.restype = i64
    lib.fu_gen_erdos_renyi.argtypes = [i64, i64, u64, i64p]
    lib.fu_gen_barabasi_albert.restype = i64
    lib.fu_gen_barabasi_albert.argtypes = [i64, i64, u64, i64p]
    lib.fu_build_graph_count.restype = i64
    lib.fu_build_graph_count.argtypes = [i64, i64, i64p]
    lib.fu_build_graph.restype = i64
    lib.fu_build_graph.argtypes = [i64, i64, i64p, i32p, i32p, i32p, i32p]
    lib.fu_des_run.restype = i64
    lib.fu_des_run.argtypes = [
        i64, i64, i32p, i32p, i32p, i32p, i64p, f64p,
        ctypes.c_int32, i64, i64, f64p, f64p,
    ]
    lib.fu_des_run_traj.restype = i64
    lib.fu_des_run_traj.argtypes = [
        i64, i64, i32p, i32p, i32p, i32p, i64p, f64p,
        ctypes.c_int32, i64, i64, f64p, f64p,
        i64, ctypes.c_double, f64p,
    ]
    lib.fu_edge_coloring.restype = i64
    lib.fu_edge_coloring.argtypes = [i64, i64, i32p, i32p, i32p, i32p]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.fu_benes_route.restype = i64
    lib.fu_benes_route.argtypes = [i64, i64p, u8p]
    lib.fu_des_run_contend.restype = i64
    lib.fu_des_run_contend.argtypes = [
        i64, i64, i32p, i32p, i32p, i32p, i64p, f64p,
        ctypes.c_int32, i64, i64, f64p, f64p,
        i64, ctypes.c_double, f64p,
        i64, i32p, i64, f64p, u8p, f64p, i64, i64,
    ]
    lib.fu_des_run_lmm.restype = i64
    lib.fu_des_run_lmm.argtypes = lib.fu_des_run_contend.argtypes
    lib.fu_des_run_contend_backlog.restype = i64
    lib.fu_des_run_contend_backlog.argtypes = \
        lib.fu_des_run_contend.argtypes
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def gen_barabasi_albert_pairs(n: int, m: int, seed: int = 0) -> np.ndarray:
    """Exact sequential BA pair list (native), or None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    npairs = m * (m + 1) // 2 + (n - m - 1) * m
    out = np.empty(2 * npairs, dtype=np.int64)
    k = lib.fu_gen_barabasi_albert(n, m, seed, _ptr(out, ctypes.c_int64))
    if k < 0:
        raise ValueError("bad BA parameters")
    return out[: 2 * k].reshape(-1, 2)


def gen_erdos_renyi_pairs(n: int, m: int, seed: int = 0) -> np.ndarray:
    lib = get_lib()
    if lib is None:
        return None
    out = np.empty(2 * (m + n), dtype=np.int64)
    k = lib.fu_gen_erdos_renyi(n, m, seed, _ptr(out, ctypes.c_int64))
    if k < 0:
        raise ValueError("bad ER parameters")
    return out[: 2 * k].reshape(-1, 2)


def benes_route(perm: np.ndarray):
    """C++ Beneš router (same masks as the numpy recursion in
    ops/permute.py); None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    perm = np.ascontiguousarray(perm, np.int64)
    n = len(perm)
    if n < 2 or n & (n - 1):
        raise ValueError("benes_route needs power-of-two length >= 2")
    k = n.bit_length() - 1
    stages = 2 * k - 1
    # bool and uint8 share layout: rows come back as zero-copy views (the
    # buffer is ~800 MB at the 16M-element plans this path exists for)
    out = np.zeros((stages, n), np.bool_)
    rc = lib.fu_benes_route(n, _ptr(perm, ctypes.c_int64),
                            _ptr(out, ctypes.c_uint8))
    if rc < 0:
        raise ValueError("bad permutation")
    return [out[s] for s in range(stages)]


def edge_coloring(topo) -> tuple[np.ndarray, int] | None:
    """Native greedy proper edge coloring (hubs-first, near-maxdeg colors);
    None if the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    E = topo.num_edges
    src = np.ascontiguousarray(topo.src, np.int32)
    dst = np.ascontiguousarray(topo.dst, np.int32)
    rev = np.ascontiguousarray(topo.rev, np.int32)
    color = np.full(E, -1, np.int32)
    c = lib.fu_edge_coloring(
        topo.num_nodes, E, _ptr(src, ctypes.c_int32),
        _ptr(dst, ctypes.c_int32), _ptr(rev, ctypes.c_int32),
        _ptr(color, ctypes.c_int32),
    )
    if c < 0:
        raise ValueError("malformed edge list")
    return color, int(c)


def build_graph_arrays(num_nodes: int, pairs: np.ndarray):
    """Native symmetrize+sort+rev+deg.  Returns (src, dst, rev, out_deg) or
    None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    pairs = np.ascontiguousarray(pairs, dtype=np.int64)
    npairs = pairs.shape[0]
    flat = pairs.reshape(-1)
    E = lib.fu_build_graph_count(num_nodes, npairs, _ptr(flat, ctypes.c_int64))
    src = np.empty(E, dtype=np.int32)
    dst = np.empty(E, dtype=np.int32)
    rev = np.empty(E, dtype=np.int32)
    deg = np.empty(num_nodes, dtype=np.int32)
    E2 = lib.fu_build_graph(
        num_nodes, npairs, _ptr(flat, ctypes.c_int64),
        _ptr(src, ctypes.c_int32), _ptr(dst, ctypes.c_int32),
        _ptr(rev, ctypes.c_int32), _ptr(deg, ctypes.c_int32),
    )
    assert E2 == E
    return src, dst, rev, deg


def des_run(topo, variant: str = "collectall", timeout: int = 50,
            ticks: int = 1000):
    """Run the reference-style discrete-event simulator on a Topology.

    Returns (estimates (N,), last_avg (N,), events processed) — the oracle
    and baseline for the vectorized kernel.  Raises if native unavailable.
    """
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native DES unavailable (no compiler?)")
    n, E = topo.num_nodes, topo.num_edges
    src = np.ascontiguousarray(topo.src, np.int32)
    dst = np.ascontiguousarray(topo.dst, np.int32)
    rev = np.ascontiguousarray(topo.rev, np.int32)
    delay = np.ascontiguousarray(topo.delay, np.int32)
    row_start = np.ascontiguousarray(topo.row_start, np.int64)
    values = np.ascontiguousarray(topo.values, np.float64)
    est = np.empty(n, np.float64)
    last_avg = np.empty(n, np.float64)
    events = lib.fu_des_run(
        n, E, _ptr(src, ctypes.c_int32), _ptr(dst, ctypes.c_int32),
        _ptr(rev, ctypes.c_int32), _ptr(delay, ctypes.c_int32),
        _ptr(row_start, ctypes.c_int64), _ptr(values, ctypes.c_double),
        0 if variant == "collectall" else 1, timeout, ticks,
        _ptr(est, ctypes.c_double), _ptr(last_avg, ctypes.c_double),
    )
    return est, last_avg, int(events)


def des_run_traj(topo, variant: str = "collectall", timeout: int = 50,
                 ticks: int = 1000, obs_every: int = 10):
    """Like :func:`des_run`, but also returns the RMSE-vs-true-mean
    trajectory sampled every ``obs_every`` ticks — the dynamics-parity
    oracle curve (reference semantics per tick, see funative.cpp
    ``fu_des_run_traj``)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native DES unavailable (no compiler?)")
    n, E = topo.num_nodes, topo.num_edges
    src = np.ascontiguousarray(topo.src, np.int32)
    dst = np.ascontiguousarray(topo.dst, np.int32)
    rev = np.ascontiguousarray(topo.rev, np.int32)
    delay = np.ascontiguousarray(topo.delay, np.int32)
    row_start = np.ascontiguousarray(topo.row_start, np.int64)
    values = np.ascontiguousarray(topo.values, np.float64)
    est = np.empty(n, np.float64)
    last_avg = np.empty(n, np.float64)
    rmse = np.empty(ticks // obs_every, np.float64)
    events = lib.fu_des_run_traj(
        n, E, _ptr(src, ctypes.c_int32), _ptr(dst, ctypes.c_int32),
        _ptr(rev, ctypes.c_int32), _ptr(delay, ctypes.c_int32),
        _ptr(row_start, ctypes.c_int64), _ptr(values, ctypes.c_double),
        0 if variant == "collectall" else 1, timeout, ticks,
        _ptr(est, ctypes.c_double), _ptr(last_avg, ctypes.c_double),
        obs_every, float(topo.true_mean), _ptr(rmse, ctypes.c_double),
    )
    return rmse, est, last_avg, int(events)


def des_run_contend(topo, variant: str = "collectall", timeout: int = 50,
                    ticks: int = 1000, obs_every: int = 10,
                    clamp_d: int = 0, visit_seed: int = -1,
                    lmm: bool = False, backlog: bool = False):
    """DES with a link-level bandwidth model.

    ``lmm=False``: the quasi-static per-tick bottleneck fair share over
    SHARED links, FATPIPE exempt — the same model as the vectorized
    kernel's ``models.rounds.edge_delays`` (cross-implementation
    validation target).  ``lmm=True``: the dynamic max-min LMM — each
    in-flight transfer is a continuous flow whose rate is re-solved by
    progressive filling whenever a transfer starts or finishes, i.e.
    SimGrid's flow-model semantics (SURVEY.md N3); this is the fidelity
    oracle the quasi-static approximation is measured against
    (``tests/test_lmm.py``).  ``backlog=True`` (quasi-static only;
    combining with ``lmm`` raises ValueError) additionally counts
    messages whose arrival is still in the future as standing load on
    their route links — the same-model C++ twin of the kernel's
    ``cfg.contention_backlog``.  ``clamp_d`` mirrors the ring-buffer
    clamp of a ``delay_depth``-bounded run (0 = unclamped).

    ``visit_seed >= 0`` re-shuffles the within-tick node visit order
    every tick (mt19937 stream) — used to measure how much trajectory
    spread is pure event-ordering noise; ``-1`` keeps the fixed
    deterministic order.

    Returns (rmse trajectory, estimates, last_avg, events)."""
    if lmm and backlog:
        raise ValueError("backlog refines the quasi-static model; the "
                         "dynamic LMM already carries in-flight load")
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native DES unavailable (no compiler?)")
    if topo.edge_links is None:
        raise ValueError("topology has no link model (see build_topology)")
    n, E = topo.num_nodes, topo.num_edges
    src = np.ascontiguousarray(topo.src, np.int32)
    dst = np.ascontiguousarray(topo.dst, np.int32)
    rev = np.ascontiguousarray(topo.rev, np.int32)
    delay = np.ascontiguousarray(topo.delay, np.int32)
    row_start = np.ascontiguousarray(topo.row_start, np.int64)
    values = np.ascontiguousarray(topo.values, np.float64)
    elinks = np.ascontiguousarray(topo.edge_links, np.int32)
    K = elinks.shape[1]
    ser = np.ascontiguousarray(topo.link_ser_rounds, np.float64)
    shared = np.ascontiguousarray(
        topo.link_shared.astype(np.uint8)
    )
    latr = np.ascontiguousarray(topo.lat_rounds, np.float64)
    est = np.empty(n, np.float64)
    last_avg = np.empty(n, np.float64)
    rmse = np.empty(max(ticks // obs_every, 1), np.float64)
    entry = (lib.fu_des_run_lmm if lmm
             else lib.fu_des_run_contend_backlog if backlog
             else lib.fu_des_run_contend)
    events = entry(
        n, E, _ptr(src, ctypes.c_int32), _ptr(dst, ctypes.c_int32),
        _ptr(rev, ctypes.c_int32), _ptr(delay, ctypes.c_int32),
        _ptr(row_start, ctypes.c_int64), _ptr(values, ctypes.c_double),
        0 if variant == "collectall" else 1, timeout, ticks,
        _ptr(est, ctypes.c_double), _ptr(last_avg, ctypes.c_double),
        obs_every, float(topo.true_mean), _ptr(rmse, ctypes.c_double),
        K, _ptr(elinks, ctypes.c_int32), len(ser),
        _ptr(ser, ctypes.c_double), _ptr(shared, ctypes.c_uint8),
        _ptr(latr, ctypes.c_double), clamp_d, int(visit_seed),
    )
    return rmse[: ticks // obs_every], est, last_avg, int(events)
