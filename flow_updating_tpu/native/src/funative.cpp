// funative — the C++ runtime layer of flow_updating_tpu.
//
// The reference's entire runtime is SimGrid 4.0 (C++ behind pybind11): the
// DES kernel, network model, mailbox matching and platform routing
// (SURVEY.md §2b N1-N9).  This library provides the native pieces the
// TPU-first redesign still wants on the host side:
//
//  * exact graph generators at 1M+ node scale (the sequential
//    preferential-attachment process is miserable in Python),
//  * the symmetrize/dedup/sort/reverse-permutation graph builder,
//  * a discrete-event "reference-style" simulator: per-actor FIFO mailbox,
//    one message drained per 1.0s tick, collect-all and pairwise protocol
//    logic with their timeout semantics (mirroring
//    flowupdating-collectall.py:66-128 / flowupdating-pairwise.py:65-117).
//    It serves two purposes: (a) the measured SimGrid-CPU-class baseline
//    for bench.py (the reference publishes no numbers, BASELINE.md), and
//    (b) a convergence-dynamics oracle the vectorized TPU kernel is tested
//    against.  It is plain C ABI for ctypes consumption — no pybind11.
//
// Build: see Makefile (g++ -O3 -shared -fPIC).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <functional>
#include <limits>
#include <queue>
#include <random>
#include <utility>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Generators.  All emit directed pairs (u, v); symmetrization happens in
// fu_build_graph.  Return value = number of pairs written, or -1 on error.
// ---------------------------------------------------------------------------

// Erdos-Renyi G(n, m) + a random Hamiltonian backbone for connectivity.
// out_pairs must hold 2 * (m + n) int64 entries.
int64_t fu_gen_erdos_renyi(int64_t n, int64_t m, uint64_t seed,
                           int64_t* out_pairs) {
  if (n < 2 || m < 0) return -1;
  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<int64_t> pick(0, n - 1);
  int64_t k = 0;
  for (int64_t i = 0; i < m; ++i) {
    int64_t u = pick(rng), v = pick(rng);
    out_pairs[2 * k] = u;
    out_pairs[2 * k + 1] = v;
    ++k;
  }
  std::vector<int64_t> perm(n);
  for (int64_t i = 0; i < n; ++i) perm[i] = i;
  std::shuffle(perm.begin(), perm.end(), rng);
  for (int64_t i = 0; i < n; ++i) {
    out_pairs[2 * k] = perm[i];
    out_pairs[2 * k + 1] = perm[(i + 1) % n];
    ++k;
  }
  return k;
}

// Exact sequential Barabasi-Albert: seed clique on (m+1) nodes, then each
// new node attaches to m endpoints sampled from the endpoint multiset
// (preferential attachment).  out_pairs must hold
// 2 * (m*(m+1)/2 + (n-m-1)*m) entries.
int64_t fu_gen_barabasi_albert(int64_t n, int64_t m, uint64_t seed,
                               int64_t* out_pairs) {
  if (m < 1 || n < m + 2) return -1;
  std::mt19937_64 rng(seed);
  std::vector<int64_t> endpoints;
  endpoints.reserve(2 * (size_t)(m * (m + 1) / 2 + (n - m - 1) * m));
  int64_t k = 0;
  for (int64_t i = 0; i <= m; ++i)
    for (int64_t j = i + 1; j <= m; ++j) {
      out_pairs[2 * k] = i;
      out_pairs[2 * k + 1] = j;
      endpoints.push_back(i);
      endpoints.push_back(j);
      ++k;
    }
  std::vector<int64_t> targets(m);
  for (int64_t v = m + 1; v < n; ++v) {
    // sample m distinct targets from the endpoint multiset
    int64_t got = 0;
    while (got < m) {
      std::uniform_int_distribution<size_t> pick(0, endpoints.size() - 1);
      int64_t t = endpoints[pick(rng)];
      bool dup = false;
      for (int64_t j = 0; j < got; ++j) dup |= (targets[j] == t);
      if (!dup) targets[got++] = t;
    }
    for (int64_t j = 0; j < m; ++j) {
      out_pairs[2 * k] = v;
      out_pairs[2 * k + 1] = targets[j];
      ++k;
      endpoints.push_back(v);
      endpoints.push_back(targets[j]);
    }
  }
  return k;
}

// ---------------------------------------------------------------------------
// Graph builder: directed pairs -> symmetrized, deduped, (src,dst)-sorted
// edge list with reverse permutation and out-degrees.
// Two-phase: count then fill, so the caller can allocate exactly.
// scratch/out buffers are caller-allocated numpy arrays.
// ---------------------------------------------------------------------------

static void symmetrize_sort(int64_t n, int64_t npairs, const int64_t* pairs,
                            std::vector<int64_t>& keys) {
  keys.clear();
  keys.reserve(2 * (size_t)npairs);
  for (int64_t i = 0; i < npairs; ++i) {
    int64_t u = pairs[2 * i], v = pairs[2 * i + 1];
    if (u == v || u < 0 || v < 0 || u >= n || v >= n) continue;
    keys.push_back(u * n + v);
    keys.push_back(v * n + u);
  }
  std::sort(keys.begin(), keys.end());
  keys.erase(std::unique(keys.begin(), keys.end()), keys.end());
}

int64_t fu_build_graph_count(int64_t n, int64_t npairs, const int64_t* pairs) {
  std::vector<int64_t> keys;
  symmetrize_sort(n, npairs, pairs, keys);
  return (int64_t)keys.size();
}

// Fills src, dst (int32, length E), rev (int32, length E), out_deg (int32,
// length n).  E must equal fu_build_graph_count's return.
int64_t fu_build_graph(int64_t n, int64_t npairs, const int64_t* pairs,
                       int32_t* src, int32_t* dst, int32_t* rev,
                       int32_t* out_deg) {
  std::vector<int64_t> keys;
  symmetrize_sort(n, npairs, pairs, keys);
  const int64_t E = (int64_t)keys.size();
  memset(out_deg, 0, sizeof(int32_t) * (size_t)n);
  for (int64_t e = 0; e < E; ++e) {
    int64_t u = keys[e] / n, v = keys[e] % n;
    src[e] = (int32_t)u;
    dst[e] = (int32_t)v;
    out_deg[u]++;
  }
  for (int64_t e = 0; e < E; ++e) {
    int64_t rk = (int64_t)dst[e] * n + src[e];
    rev[e] = (int32_t)(std::lower_bound(keys.begin(), keys.end(), rk) -
                       keys.begin());
  }
  return E;
}

// ---------------------------------------------------------------------------
// Beneš network routing: swap masks realizing y = x[perm] as 2*log2(n)-1
// columns of 2x2 switches (mirrors ops/permute.py::benes_plan — the
// gather-free data-movement primitive; this router handles the
// 8M-16M-element plans the numpy recursion cannot).
// out must hold (2*log2(n)-1) * n uint8; returns 0, or -1 on bad input.
// ---------------------------------------------------------------------------

int64_t fu_benes_route(int64_t n, const int64_t* perm, uint8_t* out) {
  if (n < 2 || (n & (n - 1))) return -1;
  int k = 0;
  while ((int64_t(1) << k) < n) ++k;
  {
    std::vector<uint8_t> seen(n, 0);
    for (int64_t i = 0; i < n; ++i) {
      if (perm[i] < 0 || perm[i] >= n || seen[perm[i]]) return -1;
      seen[perm[i]] = 1;
    }
  }
  std::vector<int64_t> cur(perm, perm + n), nxt(n), pinv(n);
  std::vector<int8_t> color(n);
  for (int level = 0; level < k - 1; ++level) {
    const int64_t m = n >> level;
    const int64_t h = m >> 1;
    uint8_t* in_row = out + (int64_t)level * n;
    uint8_t* out_row = out + (int64_t)(2 * k - 2 - level) * n;
    for (int64_t start = 0; start < n; start += m) {
      const int64_t* p = &cur[start];
      for (int64_t o = 0; o < m; ++o) pinv[start + p[o]] = o;
      std::fill(color.begin() + start, color.begin() + start + m, -1);
      int8_t* col = &color[start];
      const int64_t* pv = &pinv[start];
      for (int64_t s = 0; s < m; ++s) {
        if (col[s] != -1) continue;
        int64_t i = s;
        int8_t c = 0;
        while (col[i] == -1) {
          col[i] = c;
          int64_t partner = i ^ h;
          col[partner] = 1 - c;
          i = p[pv[partner] ^ h];
        }
      }
      for (int64_t i = 0; i < h; ++i) {
        uint8_t sw = col[i] == 1;
        in_row[start + i] = sw;
        in_row[start + h + i] = sw;
      }
      for (int64_t o = 0; o < h; ++o) {
        bool top_u = col[p[o]] == 0;
        uint8_t sw = !top_u;
        out_row[start + o] = sw;
        out_row[start + h + o] = sw;
        int64_t s_u = top_u ? p[o] : p[o + h];
        int64_t s_l = top_u ? p[o + h] : p[o];
        nxt[start + o] = s_u & (h - 1);
        nxt[start + h + o] = s_l & (h - 1);
      }
    }
    std::swap(cur, nxt);
  }
  uint8_t* mid = out + (int64_t)(k - 1) * n;
  for (int64_t start = 0; start < n; start += 2) {
    uint8_t sw = cur[start] == 1;
    mid[start] = sw;
    mid[start + 1] = sw;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Greedy proper edge coloring (undirected; both directions share a color).
//
// Host-side prerequisite of the fast synchronous pairwise mode (one color
// class fires per round).  Edges are processed hubs-first (descending
// max-endpoint-degree): each takes the smallest color unused at both
// endpoints, found by merge-scanning the endpoints' sorted used-color
// lists.  Hubs-first keeps the color count near the trivial lower bound
// maxdeg (the numpy matching extractor achieves exactly maxdeg but costs
// O(colors * E) full passes — ~17 s at BA-100k vs well under a second
// here).  Directed inputs must be the framework's sorted symmetric edge
// list; color_out gets the shared color on BOTH directions.  Returns the
// number of colors, or -1 on malformed input.
// ---------------------------------------------------------------------------

int64_t fu_edge_coloring(int64_t n, int64_t E, const int32_t* src,
                         const int32_t* dst, const int32_t* rev,
                         int32_t* color_out) {
  std::vector<int64_t> und;
  und.reserve((size_t)E / 2);
  std::vector<int64_t> deg(n, 0);
  for (int64_t e = 0; e < E; ++e) {
    if (src[e] < 0 || src[e] >= n || dst[e] < 0 || dst[e] >= n) return -1;
    if (rev[e] < 0 || rev[e] >= E) return -1;  // color_out[rev[e]] writes
    deg[src[e]]++;
    if (src[e] < dst[e]) und.push_back(e);
  }
  std::sort(und.begin(), und.end(), [&](int64_t a, int64_t b) {
    int64_t da = std::max(deg[src[a]], deg[dst[a]]);
    int64_t db = std::max(deg[src[b]], deg[dst[b]]);
    if (da != db) return da > db;
    return a < b;
  });
  std::vector<std::vector<int32_t>> used(n);  // sorted per-node color lists
  for (int64_t v = 0; v < n; ++v) used[v].reserve((size_t)deg[v]);
  int32_t num_colors = 0;
  for (int64_t e : und) {
    const std::vector<int32_t>& a = used[src[e]];
    const std::vector<int32_t>& b = used[dst[e]];
    // smallest c >= 0 absent from both sorted lists
    int32_t c = 0;
    size_t i = 0, j = 0;
    while (true) {
      while (i < a.size() && a[i] < c) ++i;
      while (j < b.size() && b[j] < c) ++j;
      bool ina = (i < a.size() && a[i] == c);
      bool inb = (j < b.size() && b[j] == c);
      if (!ina && !inb) break;
      ++c;
    }
    color_out[e] = c;
    color_out[rev[e]] = c;
    auto& av = used[src[e]];
    av.insert(std::lower_bound(av.begin(), av.end(), c), c);
    auto& bv = used[dst[e]];
    bv.insert(std::lower_bound(bv.begin(), bv.end(), c), c);
    num_colors = std::max(num_colors, (int32_t)(c + 1));
  }
  return num_colors;
}

// ---------------------------------------------------------------------------
// Reference-style discrete-event simulator.
//
// Actor semantics mirrored from the reference scripts:
//  * every peer ticks once per simulated second and drains AT MOST ONE
//    mailbox message per tick (the single get_async per loop pass,
//    collectall.py:70-85);
//  * mailbox delivery order = message arrival order (FIFO per arrival);
//  * collect-all: average when all neighbors reported or after `timeout`
//    ticks (collectall.py:87-103);
//  * pairwise: every processed message triggers a 2-party average + reply;
//    neighbors silent for > timeout seconds are re-initiated each tick
//    (pairwise.py:86-100);
//  * per-edge latency in whole ticks (>= 1) models the link delay.
//
// variant: 0 = collect-all, 1 = pairwise.
// Returns number of processed messages (events), fills estimates (= value -
// sum(flows)) and last_avg per node after `ticks` simulated seconds.
// ---------------------------------------------------------------------------

struct Msg {
  int64_t arrival;   // tick at which the message is deliverable
  int64_t seq;       // global sequence for FIFO among equal arrivals
  int32_t edge;      // receiver's ledger edge (v -> u) the message updates
  double flow;
  double estimate;
};
struct MsgLater {
  bool operator()(const Msg& a, const Msg& b) const {
    if (a.arrival != b.arrival) return a.arrival > b.arrival;
    return a.seq > b.seq;
  }
};

// Optional link-level contention model (mirrors models/rounds.py::
// edge_delays): all sends buffered within one tick contend; each SHARED
// link's serialization cost scales with its concurrent-flow count
// (bottleneck fair share); FATPIPE links never share.  delay[e] =
// clamp(round(lat_rounds[e] + max_l load[l] * ser[l]), 1, clamp_d).
struct LinkModel {
  int64_t K = 0;                      // route length (padded)
  const int32_t* edge_links = nullptr;  // (E*K), pad = L
  int64_t L = 0;
  const double* link_ser_rounds = nullptr;  // (L,)
  const uint8_t* link_shared = nullptr;     // (L,)
  const double* lat_rounds = nullptr;       // (E,)
  int64_t clamp_d = 0;                // 0 = unclamped
  // 0 = quasi-static per-tick bottleneck share (the vectorized kernel's
  // model); 1 = dynamic max-min LMM: transfers are continuous flows whose
  // rates are re-solved by progressive filling whenever a transfer starts
  // or finishes — SimGrid's flow-model semantics (SURVEY.md N3), the
  // fidelity oracle the quasi-static approximation is measured against.
  int32_t lmm = 0;
  // quasi-static only: count messages still in flight (sent in earlier
  // ticks, arrival > t) as standing load on their route links — the
  // same-model C++ twin of the kernel's cfg.contention_backlog
  // (models/rounds.py::edge_delays inflight accounting).
  int32_t backlog = 0;
  bool active() const { return edge_links != nullptr; }
};

// One in-flight transfer under the dynamic LMM: a unit message draining
// at the max-min rate (msg/tick) the solver assigns it.
struct Transfer {
  double rem;     // message units remaining (starts at 1.0)
  double rate;    // msg/tick, filled by lmm_solve
  int32_t e;      // sending edge (delivery updates ledger rev[e])
  int64_t t0;     // send tick (origin for the delay clamp)
  double flow_v, est_v;
};

// Progressive-filling max-min: repeatedly find the most-contended
// constraining link, fix its flows at the fair share, release capacity,
// repeat.  Flows crossing no constraining link get +inf (latency-only).
static void lmm_solve(std::vector<Transfer>& act, const LinkModel& lm) {
  const double INF = std::numeric_limits<double>::infinity();
  const size_t F = act.size();
  if (F == 0) return;
  std::vector<double> cap_rem((size_t)lm.L);
  std::vector<int64_t> nflow((size_t)lm.L, 0);
  for (int64_t l = 0; l < lm.L; ++l)
    cap_rem[(size_t)l] = (lm.link_shared[l] && lm.link_ser_rounds[l] > 0.0)
                             ? 1.0 / lm.link_ser_rounds[l]
                             : INF;
  for (size_t f = 0; f < F; ++f)
    for (int64_t k = 0; k < lm.K; ++k) {
      int32_t l = lm.edge_links[(int64_t)act[f].e * lm.K + k];
      if (l < lm.L) nflow[(size_t)l]++;
    }
  auto fair_of = [&](size_t f) {
    // fair share on SHARED links, capped by the flow's own full-rate
    // bound on every ser>0 link it crosses: FATPIPE links never share,
    // but each flow is still rate-capped at the link bandwidth
    // (matches the quasi-static model's 1x ser charge on non-shared
    // links; SURVEY.md N3 / small_platform.xml FATPIPE)
    double mine = INF;
    for (int64_t k = 0; k < lm.K; ++k) {
      int32_t l = lm.edge_links[(int64_t)act[f].e * lm.K + k];
      if (l >= lm.L) continue;
      if (cap_rem[(size_t)l] < INF && nflow[(size_t)l] > 0)
        mine = std::min(mine, cap_rem[(size_t)l] / (double)nflow[(size_t)l]);
      if (!lm.link_shared[l] && lm.link_ser_rounds[l] > 0.0)
        mine = std::min(mine, 1.0 / lm.link_ser_rounds[l]);
    }
    return mine;
  };
  auto fix = [&](size_t f, double rate) {
    act[f].rate = rate;
    for (int64_t k = 0; k < lm.K; ++k) {
      int32_t l = lm.edge_links[(int64_t)act[f].e * lm.K + k];
      if (l < lm.L) {
        if (cap_rem[(size_t)l] < INF)
          cap_rem[(size_t)l] = std::max(cap_rem[(size_t)l] - rate, 0.0);
        nflow[(size_t)l]--;
      }
    }
  };
  std::vector<uint8_t> fixed(F, 0);
  size_t nfixed = 0;
  while (nfixed < F) {
    double best = INF;
    for (size_t f = 0; f < F; ++f)
      if (!fixed[f]) best = std::min(best, fair_of(f));
    if (best == INF) {  // rest cross no constraining link
      for (size_t f = 0; f < F; ++f)
        if (!fixed[f]) act[f].rate = INF;
      break;
    }
    bool any = false;
    for (size_t f = 0; f < F; ++f) {
      if (fixed[f]) continue;
      double mine = fair_of(f);
      if (mine <= best * (1.0 + 1e-12)) {
        fix(f, mine);
        fixed[f] = 1;
        ++nfixed;
        any = true;
      }
    }
    if (!any) {  // numerical guard — fix the single tightest flow
      size_t argf = 0;
      double mine = INF;
      for (size_t f = 0; f < F; ++f)
        if (!fixed[f] && fair_of(f) < mine) mine = fair_of(f), argf = f;
      fix(argf, mine);
      fixed[argf] = 1;
      ++nfixed;
    }
  }
}

static int64_t des_impl(int64_t n, int64_t E, const int32_t* src,
                        const int32_t* dst, const int32_t* rev,
                        const int32_t* delay, const int64_t* row_start,
                        const double* values, int32_t variant, int64_t timeout,
                        int64_t ticks, double* est_out, double* last_avg_out,
                        int64_t obs_every, double mean, double* rmse_out,
                        const LinkModel& lm = LinkModel(),
                        int64_t visit_seed = -1) {
  // Per-edge ledgers, exactly the per-neighbor dicts of a reference Peer.
  std::vector<double> flow((size_t)E, 0.0), est((size_t)E, 0.0);
  std::vector<uint8_t> recv((size_t)E, 0);          // collect-all
  std::vector<int64_t> stamp((size_t)E, 0);         // pairwise
  std::vector<int64_t> ticks_since(n, 0);           // collect-all
  std::vector<int32_t> recv_count(n, 0);
  std::vector<double> last_avg(n, 0.0);
  std::vector<std::priority_queue<Msg, std::vector<Msg>, MsgLater>> mailbox(n);
  int64_t seq = 0, events = 0;

  auto deg = [&](int64_t v) { return row_start[v + 1] - row_start[v]; };

  // contention mode: sends buffer within the tick, delays are assigned at
  // tick end from the per-link concurrent counts (same-model validation
  // target for the vectorized kernel's edge_delays)
  struct PendSend {
    int32_t e;
    double flow_v, est_v;
  };
  std::vector<PendSend> tick_sends;
  std::vector<int64_t> link_cnt(lm.active() ? (size_t)lm.L : 0, 0);

  // dynamic-LMM state: in-flight transfers + the continuous clock they
  // progress on (tick boundaries are integer points of the same axis)
  std::vector<Transfer> act;
  double now_c = 0.0;

  // quasi-static backlog state: per-LINK standing count of messages with
  // arrival > t (the kernel's buf_valid ring occupancy scattered onto
  // route links), maintained incrementally — O(K) per message instead of
  // an O(E*K) rescan per tick; expiry pops as the clock passes arrivals
  std::vector<int64_t> standing_link(
      lm.backlog && lm.active() ? (size_t)lm.L : 0, 0);
  std::priority_queue<std::pair<int64_t, int32_t>,
                      std::vector<std::pair<int64_t, int32_t>>,
                      std::greater<>> expiry;

  auto lmm_advance = [&](double t_end_c) {
    // progress continuous time to t_end_c, re-solving max-min rates at
    // every completion event (the dynamic re-solve the quasi-static
    // model lacks — transfers finishing mid-flight free capacity for
    // the survivors immediately)
    while (now_c < t_end_c - 1e-12 && !act.empty()) {
      lmm_solve(act, lm);
      double dt = t_end_c - now_c;
      bool any_inf = false;
      for (const auto& tr : act) {
        if (tr.rate == std::numeric_limits<double>::infinity())
          any_inf = true;
        else if (tr.rate > 0.0)
          dt = std::min(dt, tr.rem / tr.rate);
      }
      if (any_inf) dt = 0.0;
      if (dt > 0.0) {
        for (auto& tr : act)
          if (tr.rate < std::numeric_limits<double>::infinity())
            tr.rem -= tr.rate * dt;
        now_c += dt;
      }
      bool completed = false;
      for (size_t f = 0; f < act.size();) {
        bool done = act[f].rem <= 1e-9 ||
                    act[f].rate == std::numeric_limits<double>::infinity();
        if (done) {
          const auto& tr = act[f];
          double arr_c = now_c + lm.lat_rounds[tr.e];
          // ceil > t0 guarantees the one-round floor; clamp_d mirrors
          // the ring-buffer delay bound of a delay_depth-bounded run
          int64_t arr = (int64_t)std::ceil(arr_c - 1e-9);
          arr = std::max(arr, tr.t0 + 1);
          if (lm.clamp_d > 0) arr = std::min(arr, tr.t0 + lm.clamp_d);
          mailbox[dst[tr.e]].push(
              Msg{arr, seq++, rev[tr.e], tr.flow_v, tr.est_v});
          act[f] = act.back();
          act.pop_back();
          completed = true;
        } else {
          ++f;
        }
      }
      if (dt == 0.0 && !completed) break;  // safety: no progress possible
    }
    now_c = std::max(now_c, t_end_c);
  };

  auto send = [&](int64_t t, int32_t e) {
    if (lm.active()) {
      tick_sends.push_back({e, flow[e], est[e]});
      return;
    }
    // message travels edge e=(v,u); it updates the receiver's ledger rev[e]
    Msg msg{t + std::max<int32_t>(1, delay[e]), seq++, rev[e], flow[e], 0.0};
    msg.estimate = est[e];  // filled by caller via est[e] (set before send)
    mailbox[dst[e]].push(msg);
  };

  auto flush_tick_sends = [&](int64_t t) {
    if (!lm.active() || tick_sends.empty()) return;
    if (lm.lmm) {
      // dynamic mode: this tick's sends become in-flight transfers,
      // transmitting from the tick boundary (continuous time t); the
      // arrival ceil + one-round floor reproduce the quasi-static
      // minimum of one tick
      for (const auto& p : tick_sends)
        act.push_back(Transfer{1.0, 0.0, p.e, t, p.flow_v, p.est_v});
      tick_sends.clear();
      return;
    }
    std::fill(link_cnt.begin(), link_cnt.end(), 0);
    if (lm.backlog) {
      // standing load: messages sent in earlier ticks whose arrival is
      // still in the future (kernel equivalent: ring occupancy counted
      // AFTER deliver_phase cleared this tick's slot, BEFORE new sends)
      while (!expiry.empty() && expiry.top().first <= t) {
        int32_t e = expiry.top().second;
        expiry.pop();
        for (int64_t k = 0; k < lm.K; ++k) {
          int32_t l = lm.edge_links[(int64_t)e * lm.K + k];
          if (l < lm.L) standing_link[(size_t)l]--;
        }
      }
      for (int64_t l = 0; l < lm.L; ++l) link_cnt[l] += standing_link[l];
    }
    for (const auto& p : tick_sends)
      for (int64_t k = 0; k < lm.K; ++k) {
        int32_t l = lm.edge_links[(int64_t)p.e * lm.K + k];
        if (l < lm.L) link_cnt[l]++;
      }
    for (const auto& p : tick_sends) {
      // float32 accumulation + round-half-even (llrint under the default
      // FE_TONEAREST mode) to match the vectorized kernel bit-for-bit:
      // models/rounds.py::edge_delays computes in float32 and jnp.rint
      // rounds halves to even — llround (half away from zero) would
      // disagree at every half-integer transfer time
      float worst = 0.0f;
      for (int64_t k = 0; k < lm.K; ++k) {
        int32_t l = lm.edge_links[(int64_t)p.e * lm.K + k];
        if (l >= lm.L) continue;
        float load = lm.link_shared[l]
                         ? (float)std::max<int64_t>(link_cnt[l], 1)
                         : 1.0f;
        worst = std::max(worst, load * (float)lm.link_ser_rounds[l]);
      }
      int64_t d = (int64_t)std::llrint((float)lm.lat_rounds[p.e] + worst);
      d = std::max<int64_t>(d, 1);
      if (lm.clamp_d > 0) d = std::min(d, lm.clamp_d);
      mailbox[dst[p.e]].push(
          Msg{t + d, seq++, rev[p.e], p.flow_v, p.est_v});
      if (lm.backlog) {
        for (int64_t k = 0; k < lm.K; ++k) {
          int32_t l = lm.edge_links[(int64_t)p.e * lm.K + k];
          if (l < lm.L) standing_link[(size_t)l]++;
        }
        expiry.push({t + d, p.e});
      }
    }
    tick_sends.clear();
  };

  auto avg_all = [&](int64_t v, int64_t t) {  // collect-all avg_and_send
    double fsum = 0.0, esum = 0.0;
    for (int64_t e = row_start[v]; e < row_start[v + 1]; ++e) {
      fsum += flow[e];
      esum += est[e];
    }
    double estimate = values[v] - fsum;
    double avg = (estimate + esum) / (double)(deg(v) + 1);
    last_avg[v] = avg;
    for (int64_t e = row_start[v]; e < row_start[v + 1]; ++e) {
      flow[e] += avg - est[e];
      est[e] = avg;
      send(t, (int32_t)e);
      recv[e] = 0;
    }
    recv_count[v] = 0;
    ticks_since[v] = 0;
  };

  auto avg_pair = [&](int64_t v, int32_t e, int64_t t) {  // pairwise
    double fsum = 0.0;
    for (int64_t k = row_start[v]; k < row_start[v + 1]; ++k) fsum += flow[k];
    double estimate = values[v] - fsum;
    double avg = (est[e] + estimate) / 2.0;
    last_avg[v] = avg;
    flow[e] += avg - est[e];
    est[e] = avg;
    stamp[e] = t;
    send(t, e);
  };

  // Within-tick node visit order.  The reference's SimGrid scheduler
  // wakes actors in an order the protocol does not control; visit_seed
  // >= 0 re-shuffles the order every tick so callers can MEASURE how
  // much of any oracle-vs-kernel trajectory gap is ordering noise
  // (tests/test_contention.py).  visit_seed < 0 keeps the fixed 0..n-1
  // order (bit-stable baseline).
  std::vector<int64_t> visit((size_t)n);
  for (int64_t v = 0; v < n; ++v) visit[(size_t)v] = v;
  std::mt19937_64 vrng(visit_seed >= 0 ? (uint64_t)visit_seed : 0);

  for (int64_t t = 0; t < ticks; ++t) {
    if (lm.active() && lm.lmm)
      lmm_advance((double)t);  // completions up to this tick boundary
    if (visit_seed >= 0) std::shuffle(visit.begin(), visit.end(), vrng);
    for (int64_t vi = 0; vi < n; ++vi) {
      int64_t v = visit[(size_t)vi];
      // drain at most one deliverable message
      if (!mailbox[v].empty() && mailbox[v].top().arrival <= t) {
        Msg m = mailbox[v].top();
        mailbox[v].pop();
        ++events;
        int32_t e = m.edge;  // v's ledger entry about the sender
        est[e] = m.estimate;
        flow[e] = -m.flow;
        if (variant == 0) {
          if (!recv[e]) {
            recv[e] = 1;
            recv_count[v]++;
          }
          if (recv_count[v] >= deg(v)) avg_all(v, t);
        } else {
          avg_pair(v, e, t);
        }
      }
      // tick
      if (variant == 0) {
        ticks_since[v]++;
        if (ticks_since[v] >= timeout) avg_all(v, t);
      } else {
        for (int64_t e = row_start[v]; e < row_start[v + 1]; ++e)
          if (stamp[e] < t - timeout) avg_pair(v, (int32_t)e, t);
      }
    }
    flush_tick_sends(t);
    // trajectory observation (dynamics-parity oracle): RMSE of the node
    // estimates vs the true mean after every obs_every-th tick
    if (obs_every > 0 && (t + 1) % obs_every == 0) {
      double acc = 0.0;
      for (int64_t v = 0; v < n; ++v) {
        double fsum = 0.0;
        for (int64_t e = row_start[v]; e < row_start[v + 1]; ++e)
          fsum += flow[e];
        double d = values[v] - fsum - mean;
        acc += d * d;
      }
      rmse_out[(t + 1) / obs_every - 1] = std::sqrt(acc / (double)n);
    }
  }

  for (int64_t v = 0; v < n; ++v) {
    double fsum = 0.0;
    for (int64_t e = row_start[v]; e < row_start[v + 1]; ++e) fsum += flow[e];
    est_out[v] = values[v] - fsum;
    last_avg_out[v] = last_avg[v];
  }
  return events;
}

int64_t fu_des_run(int64_t n, int64_t E, const int32_t* src,
                   const int32_t* dst, const int32_t* rev,
                   const int32_t* delay, const int64_t* row_start,
                   const double* values, int32_t variant, int64_t timeout,
                   int64_t ticks, double* est_out, double* last_avg_out) {
  return des_impl(n, E, src, dst, rev, delay, row_start, values, variant,
                  timeout, ticks, est_out, last_avg_out, 0, 0.0, nullptr);
}

// Trajectory variant: additionally fills rmse_out[ticks / obs_every] with
// the RMSE (vs `mean`) of node estimates sampled every obs_every ticks.
int64_t fu_des_run_traj(int64_t n, int64_t E, const int32_t* src,
                        const int32_t* dst, const int32_t* rev,
                        const int32_t* delay, const int64_t* row_start,
                        const double* values, int32_t variant, int64_t timeout,
                        int64_t ticks, double* est_out, double* last_avg_out,
                        int64_t obs_every, double mean, double* rmse_out) {
  return des_impl(n, E, src, dst, rev, delay, row_start, values, variant,
                  timeout, ticks, est_out, last_avg_out, obs_every, mean,
                  rmse_out);
}

// Contention variant: per-tick shared-link bandwidth splitting (see
// LinkModel above) — the same-model oracle for cfg.contention runs.
int64_t fu_des_run_contend(
    int64_t n, int64_t E, const int32_t* src, const int32_t* dst,
    const int32_t* rev, const int32_t* delay, const int64_t* row_start,
    const double* values, int32_t variant, int64_t timeout, int64_t ticks,
    double* est_out, double* last_avg_out, int64_t obs_every, double mean,
    double* rmse_out, int64_t K, const int32_t* edge_links, int64_t L,
    const double* link_ser_rounds, const uint8_t* link_shared,
    const double* lat_rounds, int64_t clamp_d, int64_t visit_seed) {
  LinkModel lm;
  lm.K = K;
  lm.edge_links = edge_links;
  lm.L = L;
  lm.link_ser_rounds = link_ser_rounds;
  lm.link_shared = link_shared;
  lm.lat_rounds = lat_rounds;
  lm.clamp_d = clamp_d;
  return des_impl(n, E, src, dst, rev, delay, row_start, values, variant,
                  timeout, ticks, est_out, last_avg_out, obs_every, mean,
                  rmse_out, lm, visit_seed);
}

// Quasi-static + in-flight backlog: the same-model C++ twin of the
// kernel's cfg.contention_backlog (standing load from messages whose
// arrival is still in the future).
int64_t fu_des_run_contend_backlog(
    int64_t n, int64_t E, const int32_t* src, const int32_t* dst,
    const int32_t* rev, const int32_t* delay, const int64_t* row_start,
    const double* values, int32_t variant, int64_t timeout, int64_t ticks,
    double* est_out, double* last_avg_out, int64_t obs_every, double mean,
    double* rmse_out, int64_t K, const int32_t* edge_links, int64_t L,
    const double* link_ser_rounds, const uint8_t* link_shared,
    const double* lat_rounds, int64_t clamp_d, int64_t visit_seed) {
  LinkModel lm;
  lm.K = K;
  lm.edge_links = edge_links;
  lm.L = L;
  lm.link_ser_rounds = link_ser_rounds;
  lm.link_shared = link_shared;
  lm.lat_rounds = lat_rounds;
  lm.clamp_d = clamp_d;
  lm.backlog = 1;
  return des_impl(n, E, src, dst, rev, delay, row_start, values, variant,
                  timeout, ticks, est_out, last_avg_out, obs_every, mean,
                  rmse_out, lm, visit_seed);
}

// Dynamic max-min LMM variant: transfers are continuous flows; rates are
// re-solved by progressive filling at every start/finish event — the
// SimGrid-fidelity network oracle (closes SURVEY.md N3's remaining
// semantic gap; the quasi-static model above is the TPU kernel's
// approximation of THIS).
int64_t fu_des_run_lmm(
    int64_t n, int64_t E, const int32_t* src, const int32_t* dst,
    const int32_t* rev, const int32_t* delay, const int64_t* row_start,
    const double* values, int32_t variant, int64_t timeout, int64_t ticks,
    double* est_out, double* last_avg_out, int64_t obs_every, double mean,
    double* rmse_out, int64_t K, const int32_t* edge_links, int64_t L,
    const double* link_ser_rounds, const uint8_t* link_shared,
    const double* lat_rounds, int64_t clamp_d, int64_t visit_seed) {
  LinkModel lm;
  lm.K = K;
  lm.edge_links = edge_links;
  lm.L = L;
  lm.link_ser_rounds = link_ser_rounds;
  lm.link_shared = link_shared;
  lm.lat_rounds = lat_rounds;
  lm.clamp_d = clamp_d;
  lm.lmm = 1;
  return des_impl(n, E, src, dst, rev, delay, row_start, values, variant,
                  timeout, ticks, est_out, last_avg_out, obs_every, mean,
                  rmse_out, lm, visit_seed);
}

}  // extern "C"
