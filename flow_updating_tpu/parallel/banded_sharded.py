"""Sharded one-kernel banded round: fused Pallas round + halo DMA.

The single-device fused round (``ops/pallas_round.py``) keeps a band
tile of protocol state in VMEM for the whole fire → delivery → merge
pass.  This module is its multi-chip form: after RCM reordering the
graph's bandwidth ``H`` bounds every edge's |dst - src|, so a
**contiguous block partition** of the node axis needs only ``H``
elements of ``avg`` from each ring neighbor per round — the banded
analogue of the edge kernel's cut-edge halo.  Each shard then runs ONE
``pallas_call`` per round (``ops/pallas_round.fused_sharded_round``)
that

1. fires its own tile,
2. **starts** one ``pltpu.make_async_remote_copy`` per ring direction
   (the ``ops/pallas_halo.py`` exchange composed INSIDE the round
   kernel — SNIPPETS [1]/[2] taken to the whole-round conclusion),
3. accumulates every band lane and remainder gather on the zero-halo
   window while the wire is busy (bit-exact for all interior rows —
   their reads never leave the shard),
4. waits, re-reads the boundary rows through the received halos, and
5. merges the ledgers.

``exchange='ppermute'`` is the serialized XLA oracle — the same window
algebra through ``lax.ppermute`` and static slices — and the Pallas
path is pinned BIT-exact against it on the virtual CPU mesh in Pallas
interpret mode (``tests/test_pallas_round.py``), the ``pallas_halo``
testing discipline: interpret mode executes the real remote-copy
semantics, so the shipped kernel is the tested kernel.

Scope: the fast synchronous collect-all mode (the banded executor's
domain), scalar payloads, plans whose remainder is 'gather' (inlined)
or 'none'; a Beneš-remainder plan asks for recompilation with
``remainder='gather'``.  Wire cost: ``2 * H * dtype_bytes`` per shard
per round, independent of the cut edge count — compare
``parallel/sharded.py``'s per-cut-edge payload blocks.
"""

from __future__ import annotations

import functools

import numpy as np

from flow_updating_tpu.utils import struct
import jax
import jax.numpy as jnp

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.parallel.mesh import NODE_AXIS, shard_map
from flow_updating_tpu.topology.graph import Topology

P = jax.sharding.PartitionSpec

LANE = 128
_TILE = 8 * LANE  # per-shard length multiple (f32 min tile rows x lanes)

#: The perf lens' pinned predicted-vs-measured discrepancy for this
#: kernel (step 4 above re-runs the FULL band pass after the remote-DMA
#: wait instead of re-accumulating only the boundary rows — ~2x the VPU
#: work of the single-device fused round; ROADMAP item "needless
#: recompute").  ``doctor``'s ``roofline_floor`` clause reports a
#: below-floor frac on a matching mode as KNOWN instead of failing.
#: Mirrors ``obs.roofline.KNOWN_DISCREPANCIES[0]`` — duplicated, not
#: imported, so the obs layer stays importable without jax;
#: tests/test_perf_lens.py pins the two equal.
ROOFLINE_KNOWN_DISCREPANCY = {
    "name": "banded_sharded_recompute",
    "mode_re": r"banded_fused.*@s(?:[2-9]|\d{2,})",
    "factor": 2.0,
    "reason": ("sharded fused banded round recomputes the full band "
               "pass after the remote-DMA wait (~2x VPU work) "
               "instead of re-accumulating only boundary rows — "
               "parallel/banded_sharded.py, ROADMAP item 1"),
}


@struct.dataclass
class ShardedBandedArrays:
    """Constants, stacked per shard on the leading axis."""

    value: jnp.ndarray      # (S, L)
    inv_depp1: jnp.ndarray  # (S, L)
    deg: jnp.ndarray        # (S, L)
    planes: tuple           # per 32-offset group: (S, L/128, 128) uint32
    rem_idx: object = None  # 'inline': (S, L/128, 128, W) int32 window
    #                         coords, -1 = empty slot
    spec: object = struct.field(pytree_node=False, default=None)
    #                         static ops.pallas_round.ShardedRoundSpec
    exchange: str = struct.field(pytree_node=False, default="pallas")


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


class ShardedBandedKernel:
    """Node-collapsed fast collect-all over a device mesh, the banded
    plan executed as one fused Pallas kernel per shard.  Mirrors
    :class:`models.sync.NodeKernel`'s recurrence exactly (pinned
    bit-exact against the single-device banded executor and the
    ppermute oracle in tests)."""

    def __init__(self, topo: Topology, cfg: RoundConfig, mesh,
                 plan=None, exchange: str = "pallas"):
        from flow_updating_tpu.models import sync
        from flow_updating_tpu.ops.pallas_round import ShardedRoundSpec

        sync._check_cfg(cfg)
        if cfg.spmv != "banded_fused":
            raise ValueError(
                "ShardedBandedKernel is the spmv='banded_fused' mesh "
                "path")
        if exchange not in ("pallas", "ppermute"):
            raise ValueError(
                f"unknown exchange {exchange!r}: 'pallas' (one fused "
                "remote-DMA kernel per shard) or 'ppermute' (the "
                "serialized XLA oracle)")
        vals = topo.values
        if vals is not None and getattr(vals, "ndim", 1) > 1:
            raise ValueError(
                "the sharded fused round is scalar-payload (vector "
                "payloads run the single-device banded kernels or the "
                "feature-axis mesh, parallel/feature.py)")
        self.topo = topo
        self.cfg = cfg
        self.mesh = mesh
        S = mesh.devices.size
        if S < 2:
            raise ValueError("the sharded fused round needs >= 2 shards")

        if plan is None:
            from flow_updating_tpu.plan import compile_topology

            # the per-shard remainder is an in-kernel gather; a
            # self-compiled plan must not route it through global
            # Beneš lanes
            plan = compile_topology(topo, remainder="gather")
        from flow_updating_tpu.plan.compile import _topo_key

        if plan.source_key and plan.source_key != _topo_key(topo):
            raise ValueError(
                "execution plan was compiled from a different topology "
                "(edge-content fingerprint mismatch) — recompile with "
                "plan.compile_topology(topo)")
        self.plan = plan
        n = topo.num_nodes
        spmv = plan.spmv
        if spmv.rem_mode == "benes":
            raise ValueError(
                "the sharded fused round inlines a gather remainder "
                "per shard; this plan routes its remainder through "
                "global Beneš lanes — recompile with "
                "compile_topology(topo, remainder='gather')")
        rem_route = "none" if spmv.rem_mode == "none" else "inline"

        H = int(plan.stats.get("bandwidth_after", 0)) or 1
        Hr = _ceil_to(max(-(-H // LANE), 8), 8)
        M = _ceil_to(n, S * _TILE)
        L = M // S
        while Hr * LANE > L:
            # halo must fit one neighbor shard: grow the shard blocks
            M += S * _TILE
            L = M // S
        offs = tuple(int(d) for d in spmv.offsets)
        W = max((s[1] for s in spmv.rem_bucket_shapes), default=0) \
            if rem_route == "inline" else 0
        self.spec = spec = ShardedRoundSpec(
            n=n, P=M, local=L, halo_rows=Hr, num_shards=S,
            offsets=offs, rem_route=rem_route, rem_width=W,
            n_planes=-(-len(offs) // 32),
        )
        self.padded_size = M
        self._perm = np.asarray(plan.order, np.int64)

        value = np.zeros(M, np.float64)
        deg = np.zeros(M, np.float64)
        base_vals = np.asarray(topo.values, np.float64)
        value[:n] = base_vals[self._perm]
        deg[:n] = topo.out_deg[self._perm]

        planes = self._band_planes(spec)
        rem_idx = self._rem_window_index(spec) \
            if rem_route == "inline" else None

        import jax.sharding as jsh

        dt = cfg.jnp_dtype
        ns = lambda *ax: jsh.NamedSharding(mesh, P(NODE_AXIS, *ax))
        put = lambda x, sh: jax.device_put(np.ascontiguousarray(x), sh)
        rows = L // LANE
        self.arrays = ShardedBandedArrays(
            value=put(value.reshape(S, L).astype(dt), ns(None)),
            inv_depp1=put((1.0 / (deg + 1.0)).reshape(S, L).astype(dt),
                          ns(None)),
            deg=put(deg.reshape(S, L).astype(dt), ns(None)),
            planes=tuple(
                put(p.reshape(S, rows, LANE), ns(None, None))
                for p in planes),
            rem_idx=None if rem_idx is None else put(
                rem_idx.reshape(S, rows, LANE, spec.rem_width or 1),
                ns(None, None, None)),
            spec=spec,
            exchange=exchange,
        )

    def _band_planes(self, spec) -> list:
        """Global bitpacked band-mask planes, (P,) uint32 per group
        (the single-device packer, shared)."""
        from flow_updating_tpu.ops.pallas_round import pack_band_planes

        return pack_band_planes(self.plan.leaves.band_masks, spec.P,
                                spec.n_planes)

    def _rem_window_index(self, spec) -> np.ndarray:
        """Remainder ELL in per-shard WINDOW coordinates: global
        neighbor g of a row owned by shard s sits at ``g - (s*L -
        halo)`` inside that shard's [recv_lo; own; recv_hi] window."""
        from flow_updating_tpu.ops.pallas_round import (
            FusedRoundSpec,
            _rem_window_index,
        )

        one = FusedRoundSpec(
            n=spec.n, P=spec.P, rows=spec.P // LANE,
            block_rows=spec.local // LANE, grid=spec.num_shards,
            offsets=spec.offsets, rem_route="inline",
            rem_width=spec.rem_width, n_planes=spec.n_planes)
        idx = _rem_window_index(self.plan.spmv, self.plan.leaves, one)
        # the single-device window is [prev-tile; own; next] (origin
        # (s-1)*L); the sharded window is [halo; own; halo] (origin
        # s*L - halo*128): shift the coordinates by the difference
        shift = spec.local - spec.halo
        idx = idx.reshape(spec.P, -1).astype(np.int64)
        idx = np.where(idx >= 0, idx - shift, -1)
        span_ok = (idx < 0) | ((idx >= 0)
                               & (idx < spec.local + 2 * spec.halo))
        if not span_ok.all():
            raise ValueError(
                "remainder reach exceeds the halo window — the plan's "
                "bandwidth accounting is inconsistent (recompile the "
                "plan)")
        return idx.astype(np.int32)

    def init_state(self):
        from flow_updating_tpu.models.sync import NodeSyncState

        import jax.sharding as jsh

        spec = self.spec
        z = jax.device_put(
            jnp.zeros((spec.num_shards, spec.local), self.cfg.jnp_dtype),
            jsh.NamedSharding(self.mesh, P(NODE_AXIS, None)),
        )
        t = jax.device_put(jnp.zeros((), jnp.int32),
                           jsh.NamedSharding(self.mesh, P()))
        return NodeSyncState(t=t, S=z, G=z, avg_prev=z, A_prev=z)

    def run(self, state, num_rounds: int):
        return _run_sharded_banded(state, self.arrays, self.cfg,
                                   self.mesh, num_rounds)

    def round_program(self, state, num_rounds: int):
        """``(jitted_fn, full_args, n_dynamic)`` — the AOT
        cost-attribution + golden-ledger hook; exactly what :meth:`run`
        dispatches."""
        return (_run_sharded_banded,
                (state, self.arrays, self.cfg, self.mesh, num_rounds), 2)

    def _unpermute(self, padded: np.ndarray) -> np.ndarray:
        out = np.empty(self.topo.num_nodes, padded.dtype)
        out[self._perm] = padded[:self.topo.num_nodes]
        return out

    def estimates(self, state) -> np.ndarray:
        """Per-node estimates in original node order (the NodeKernel
        readback convention: value + G)."""
        flat = np.asarray(self.arrays.value + state.G).reshape(-1)
        return self._unpermute(flat)

    def last_avg(self, state) -> np.ndarray:
        return self._unpermute(np.asarray(state.avg_prev).reshape(-1))

    def run_streamed(self, state, num_rounds: int, observe_every: int,
                     emit):
        """Chunked host-side observer — same emit payload as
        sync.run_rounds_node_streamed (metrics over communicating
        nodes)."""
        if num_rounds % observe_every:
            raise ValueError("num_rounds must be a multiple of "
                             "observe_every")
        mean = float(self.topo.true_mean)
        deg = np.asarray(self.arrays.deg).reshape(-1)
        real = deg > 0
        cnt = max(int(real.sum()), 1)
        for _ in range(num_rounds // observe_every):
            state = self.run(state, observe_every)
            if emit is not None:
                est = np.asarray(
                    self.arrays.value + state.G).reshape(-1)
                err = np.where(real, est - mean, 0.0)
                emit({
                    "t": int(state.t),
                    "rmse": float(np.sqrt((err * err).sum() / cnt)),
                    "max_abs_err": float(np.abs(err).max()),
                    "mass": float(np.where(real, est, 0.0).sum()),
                    "fired_total": int(state.t) * cnt,
                })
        return state


def _oracle_step(st, value_l, inv_l, deg_l, planes_l, rem_l, spec):
    """The ppermute reference round: identical window algebra to the
    fused kernel — halos via two ``lax.ppermute``, bands via static
    window slices, the remainder gathered at the kernel's exact shapes
    so the float sequences agree to the bit."""
    S_ = spec.num_shards
    He = spec.halo
    avg_l = (value_l - st.S + st.A_prev) * inv_l
    fwd = [(j, (j + 1) % S_) for j in range(S_)]
    bwd = [(j, (j - 1) % S_) for j in range(S_)]
    lo = jax.lax.ppermute(avg_l[-He:], NODE_AXIS, fwd)
    hi = jax.lax.ppermute(avg_l[:He], NODE_AXIS, bwd)
    window = jnp.concatenate([lo, avg_l, hi])
    acc = jnp.zeros_like(avg_l)
    L = spec.local
    for gi, d in enumerate(spec.offsets):
        plane = planes_l[gi // 32].reshape(-1)
        bit = ((plane >> (gi % 32)) & 1) != 0
        acc = acc + jnp.where(bit, jax.lax.slice(window, (He + d,),
                                                 (He + d + L,)), 0)
    if rem_l is not None:
        idx = rem_l                       # (rows, 128, W)
        gathered = window[jnp.maximum(idx, 0)]
        rsum = jnp.sum(jnp.where(idx >= 0, gathered, 0), axis=-1)
        acc = acc + rsum.reshape(-1)
    S_next = -st.G - acc + deg_l * st.avg_prev
    G_next = -st.S - deg_l * avg_l + st.A_prev
    return st.replace(t=st.t + 1, S=S_next, G=G_next, avg_prev=avg_l,
                      A_prev=acc)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "mesh", "num_rounds"))
def _run_sharded_banded(state, arrays: ShardedBandedArrays,
                        cfg: RoundConfig,  # noqa: ARG001  # jit cache key
                        mesh, num_rounds: int):
    spec = arrays.spec
    exchange = arrays.exchange

    def body(value_l, inv_l, deg_l, planes_l, rem_l, st):
        value_l, inv_l, deg_l = (a[0] for a in (value_l, inv_l, deg_l))
        planes_l = tuple(p[0] for p in planes_l)
        rem_l = None if rem_l is None else rem_l[0]
        st = jax.tree.map(lambda x: x[0] if x.ndim == 2 else x, st)

        def step(st, _):
            if exchange == "ppermute":
                return _oracle_step(st, value_l, inv_l, deg_l,
                                    planes_l, rem_l, spec), None
            from flow_updating_tpu.ops.pallas_round import (
                fused_sharded_round,
            )

            S_next, G_next, avg_l, acc = fused_sharded_round(
                st.S, st.G, st.avg_prev, st.A_prev, value_l, inv_l,
                deg_l, planes_l, rem_l, spec, axis_name=NODE_AXIS)
            return st.replace(t=st.t + 1, S=S_next, G=G_next,
                              avg_prev=avg_l, A_prev=acc), None

        out, _ = jax.lax.scan(step, st, None, length=num_rounds)
        return jax.tree.map(
            lambda x: x[None] if x.ndim == 1 else x, out)

    sh = P(NODE_AXIS, None)
    plane_specs = tuple(P(NODE_AXIS, None, None) for _ in arrays.planes)
    rem_spec = None if arrays.rem_idx is None \
        else P(NODE_AXIS, None, None, None)
    state_spec = jax.tree.map(lambda x: sh if x.ndim == 2 else P(),
                              state)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(sh, sh, sh, plane_specs, rem_spec, state_spec),
        out_specs=state_spec,
        check_vma=False,
    )(arrays.value, arrays.inv_depp1, arrays.deg, arrays.planes,
      arrays.rem_idx, state)
