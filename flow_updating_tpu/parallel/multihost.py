"""Multi-host (multi-process) execution over DCN.

The reference's distributed story is SimGrid's *simulated* network; this
framework's real one is JAX's: each host runs one process, `jax.distributed`
wires them into a single logical runtime over DCN, and every `Mesh` in
:mod:`flow_updating_tpu.parallel.mesh` then spans all hosts' devices — the
GSPMD collectives (all-gather of the avg vector, halo payload exchange)
ride ICI within a pod slice and DCN across slices, with no change to any
kernel in this package (SPMD: computation follows the sharding).

Single-process runs (the common case, and all CI) need none of this; every
helper degrades to a no-op.

Typical launch (one process per host):

    JAX_COORDINATOR=host0:1234 NPROC=4 PROC_ID=$i python my_run.py

    import flow_updating_tpu.parallel.multihost as mh
    mh.initialize()                       # no-op if single process
    mesh = mh.global_mesh()               # all devices on all hosts
    eng = Engine(config=cfg, mesh=mesh)   # unchanged from single-host
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger("flow_updating_tpu.multihost")


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Join the multi-process JAX runtime.

    Arguments default from ``JAX_COORDINATOR`` / ``NPROC`` / ``PROC_ID``
    (and jax's own auto-detection on supported cluster schedulers).  Returns
    True if a multi-process runtime was initialized, False for the
    single-process no-op.
    """
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("NPROC", "0")) or None
    if process_id is None:
        pid = os.environ.get("PROC_ID")
        process_id = int(pid) if pid is not None else None
    if coordinator is None and num_processes in (None, 1):
        logger.debug("single-process run; jax.distributed not initialized")
        return False
    if coordinator is None:
        raise ValueError(
            f"num_processes={num_processes} but no coordinator address "
            "(set JAX_COORDINATOR=host:port or pass coordinator=)"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "multihost: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


def global_mesh(axis: str | None = None) -> jax.sharding.Mesh:
    """One-axis mesh over every device of every process (node axis)."""
    from flow_updating_tpu.parallel.mesh import NODE_AXIS

    devices = jax.devices()
    return jax.sharding.Mesh(devices, (axis or NODE_AXIS,))


def is_primary() -> bool:
    """True on the process that should write logs/checkpoints/reports."""
    return jax.process_index() == 0
