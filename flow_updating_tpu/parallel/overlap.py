"""Interior/frontier-split round schedule: hide the halo wire behind compute.

The plain halo round (:mod:`flow_updating_tpu.parallel.sharded`) is a
straight line: deliver -> fire -> local scatter -> cut-edge exchange ->
receive scatter, so every round pays the full wire latency serialized
after the compute (``MULTICHIP_SCALING_r5.json``: the 2-shard
``halo_allgather`` path runs at 223.7 r/s where one device does
5,631).  This module re-schedules the round in the pipelined-gossip
shape (arXiv:1504.03277, applied at the hardware layer):

1. **frontier pass** — the *cut-edge payloads* are computed first, on a
   compacted sub-problem containing exactly the frontier rows (nodes
   owning at least one cut edge) and their out-edge rows.  Per-row
   segment reductions see the same operands in the same order as the
   full pass, so the payloads are bit-identical to the unsplit round's
   (the decomposition parity asserted in ``tests/test_overlap.py``);
2. **start the exchange** with those payloads — ``lax.ppermute`` per
   plan-time shard offset (``halo='overlap'``: XLA's async collectives
   overlap them with everything that follows), or the Pallas
   ``make_async_remote_copy`` kernel (``halo='overlap_pallas'``,
   :mod:`flow_updating_tpu.ops.pallas_halo`);
3. **interior pass** — the full deliver/fire plus the intra-shard
   delivery merge run while the wire is busy;
4. **finish the frontier** — consume the received blocks into the cut
   edges' ring-buffer slots.

What each wire can actually hide differs.  ``'overlap'`` hides the
whole interior pass: the ppermutes are issued before it and consumed
after, so a backend with async collectives runs the wire under all of
step 3.  ``'overlap_pallas'`` is a single synchronous ``pallas_call``,
and only work *inside* the kernel sits between ``start()`` and
``wait()`` — that work is the receiver-pull delivery merge, whose
operands are the interior pass's fire outputs, so the DMAs necessarily
issue after deliver/fire and the hidden window is the O(D*Eb) merge,
not the full interior (fast pairwise has no merge, so its Pallas
exchange is serialized).  Hiding all of step 3 in-kernel would mean
writing deliver/fire in Pallas; until then ``'overlap'`` is the wider
window and ``'overlap_pallas'`` is the fused-DMA form of the same
bit-exact schedule.

The schedule only reorders independent ops: ``halo='overlap'`` is
bit-exact against ``halo='ppermute'`` (same values, same merge order —
asserted for every partition mode, scalar and vector payloads, and
drop>0).  The frontier rows are recomputed by the interior pass (the
redundancy is O(cut edges), the quantity the partition minimizes); the
single-pass state always comes from the full-width pass.

``halo='interior'`` is a **timing probe only**: it runs the identical
schedule with the exchange elided (received payloads never arrive), so
``t_ppermute - t_interior`` isolates the serialized wire cost and
``obs.profile.overlap_report`` can report the hidden fraction.  It is
not a correct protocol mode and the Engine refuses it.
"""

from __future__ import annotations

import dataclasses as _dc

import jax
import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.models.config import COLLECTALL
from flow_updating_tpu.models.rounds import deliver_phase, fire_core
from flow_updating_tpu.models.state import FlowUpdatingState, _ex
from flow_updating_tpu.parallel.mesh import NODE_AXIS
from flow_updating_tpu.parallel.sharded import (
    _lanes,
    _local_topo,
    _unlanes,
)
from flow_updating_tpu.topology.graph import TopoArrays
from flow_updating_tpu.utils import struct

#: halo modes implemented by this module ('interior' is the timing
#: probe; 'overlap_full' is the plan-time fat-frontier resolution of
#: 'overlap' — see :func:`resolve_mode`)
OVERLAP_MODES = ("overlap", "overlap_full", "overlap_pallas", "interior")

#: halo mode -> wire implementation for the exchange step
_WIRE = {"overlap": "ppermute", "overlap_full": "ppermute",
         "overlap_pallas": "pallas", "interior": "none"}

#: above this fraction of real edges in the frontier, the compact pass
#: duplicates more deliver/fire work than the early wire start can hide
#: — 'overlap' then resolves to 'overlap_full', whose full-width payload
#: replay CSEs with the interior pass (one pass, ppermute-rate compute;
#: the wire still issues as early as the data allows).  Thin frontiers —
#: the regime the locality partition produces — keep the compact pass
#: that makes the early DMA start real.
COMPACT_FRONTIER_MAX_FRACTION = 0.5


def resolve_mode(plan, halo: str) -> str:
    """Plan-time schedule resolution for ``halo='overlap'``: compact
    frontier pass when the frontier is thin, full-width payload replay
    when it is fat (both bit-identical to ppermute; only the redundant
    compute differs).  Other modes pass through.  The O(S*Eb) frontier
    count is computed once per plan and cached on it (the plan is
    immutable after construction; program builders re-resolve on every
    call)."""
    if halo != "overlap":
        return halo
    cached = getattr(plan, "_overlap_schedule", None)
    if cached is not None:
        return cached
    a = plan.arrays
    tl = np.asarray(a.tlocal)
    real = tl < plan.Eb
    ts = np.asarray(a.tshard)
    own = np.arange(plan.num_shards, dtype=ts.dtype).reshape(-1, 1)
    is_cut = (ts != own) & real
    src = np.asarray(a.src_local)
    frontier_edges = 0
    for s in range(plan.num_shards):
        rows = np.zeros(plan.Nb, bool)
        rows[src[s, is_cut[s]]] = True
        frontier_edges += int(rows[src[s]][real[s]].sum())
    total = max(int(real.sum()), 1)
    resolved = ("overlap" if frontier_edges <= COMPACT_FRONTIER_MAX_FRACTION
                * total else "overlap_full")
    object.__setattr__(plan, "_overlap_schedule", resolved)  # frozen-safe
    return resolved


@struct.dataclass
class OverlapTables:
    """Plan-time frontier/interior split metadata, stacked ``(S, ...)``.

    The compact frontier sub-topology holds every frontier row's FULL
    out-edge row (a row's fire decision needs all of its edges), in the
    shard's slot order — so compacted per-row reductions replay the
    full pass's addition order exactly.  Compact row ``Fn`` is the
    dummy (dead) row that owns the padded entries, mirroring the main
    kernel's ``Nb-1`` convention."""

    f_nodes: jnp.ndarray     # (S, Fn+1) i32 local node id per compact row
    #                          (pads + last entry = Nb-1, the dead dummy)
    f_edges: jnp.ndarray     # (S, Fe) i32 edge slot per compact slot
    #                          (ascending; pad = Eb sentinel)
    f_src: jnp.ndarray       # (S, Fe) i32 compact row of each slot
    #                          (pads -> Fn)
    f_out_deg: jnp.ndarray   # (S, Fn+1) i32 real out-degree per row
    f_row_start: jnp.ndarray  # (S, Fn+2) i32 compact CSR offsets
    f_edge_rank: jnp.ndarray  # (S, Fe) i32 original within-row rank
    f_delay: jnp.ndarray     # (S, Fe) i32
    send_pos: tuple          # per offset: (S, Hd) i32 position of each
    #                          ppermute send slot within f_edges (pad -> Fe)
    lrev: jnp.ndarray        # (S, Eb) i32 intra-shard sender slot whose
    #                          message lands in slot r (none -> Eb) — the
    #                          receiver-pull form of the local delivery,
    #                          the fused Pallas kernel's interior merge


def build_overlap(plan) -> OverlapTables:
    """Host-side construction from the existing partition metadata."""
    a = plan.arrays
    S, Eb, Nb = plan.num_shards, plan.Eb, plan.Nb
    src = np.asarray(a.src_local)
    ts = np.asarray(a.tshard)
    tl = np.asarray(a.tlocal)
    rank = np.asarray(a.edge_rank)
    delay = np.asarray(a.delay)
    out_deg = np.asarray(a.out_deg)
    own = np.arange(S, dtype=ts.dtype).reshape(S, 1)
    real = tl < Eb
    is_cut = (ts != own) & real

    fn_mask = np.zeros((S, Nb), bool)
    for s in range(S):
        fn_mask[s, src[s, is_cut[s]]] = True
    fn_mask[:, Nb - 1] = False          # the dummy row is never frontier
    fe_mask = fn_mask[np.arange(S)[:, None], src] & real
    Fn = max(int(fn_mask.sum(1).max()), 1)
    Fe = max(int(fe_mask.sum(1).max()), 1)

    f_nodes = np.full((S, Fn + 1), Nb - 1, np.int32)
    f_edges = np.full((S, Fe), Eb, np.int32)
    f_src = np.full((S, Fe), Fn, np.int32)
    f_out_deg = np.zeros((S, Fn + 1), np.int32)
    f_row_start = np.zeros((S, Fn + 2), np.int32)
    f_edge_rank = np.zeros((S, Fe), np.int32)
    f_delay = np.ones((S, Fe), np.int32)
    pos_of_slot = np.full((S, Eb + 1), Fe, np.int64)
    lrev = np.full((S, Eb), Eb, np.int32)
    for s in range(S):
        rows = np.where(fn_mask[s])[0]
        slots = np.where(fe_mask[s])[0]           # ascending = row-major
        f_nodes[s, : len(rows)] = rows
        f_edges[s, : len(slots)] = slots
        pos_of_slot[s, slots] = np.arange(len(slots))
        rank_of = np.full(Nb, Fn, np.int64)
        rank_of[rows] = np.arange(len(rows))
        f_src[s, : len(slots)] = rank_of[src[s, slots]]
        f_out_deg[s, : len(rows)] = out_deg[s, rows]
        counts = np.bincount(f_src[s, : len(slots)], minlength=Fn + 1)
        counts[Fn] += Fe - len(slots)             # pads live in the dummy row
        np.cumsum(counts, out=f_row_start[s, 1:])
        f_edge_rank[s, : len(slots)] = rank[s, slots]
        f_edge_rank[s, len(slots):] = np.arange(Fe - len(slots))
        f_delay[s, : len(slots)] = delay[s, slots]
        # receiver-pull map of the intra-shard delivery: slot r's local
        # sender is the edge e with tshard[e] == s and tlocal[e] == r
        loc = np.where((ts[s] == s) & real[s])[0]
        lrev[s, tl[s, loc]] = loc

    send_pos = tuple(
        pos_of_slot[np.arange(S)[:, None],
                    np.minimum(np.asarray(sidx), Eb)].astype(np.int32)
        for sidx in (plan.perm_tables.send_idx if plan.perm_tables else ())
    )
    return OverlapTables(
        f_nodes=f_nodes, f_edges=f_edges, f_src=f_src,
        f_out_deg=f_out_deg, f_row_start=f_row_start,
        f_edge_rank=f_edge_rank, f_delay=f_delay,
        send_pos=send_pos, lrev=lrev,
    )


def frontier_interior_rows(plan) -> tuple[np.ndarray, np.ndarray]:
    """Per-shard boolean masks ``(frontier, interior)`` over the real
    local rows — disjoint, jointly exhaustive (the decomposition's row
    coverage, asserted in tests)."""
    a = plan.arrays
    S, Eb, Nb = plan.num_shards, plan.Eb, plan.Nb
    tl = np.asarray(a.tlocal)
    ts = np.asarray(a.tshard)
    src = np.asarray(a.src_local)
    real = tl < Eb
    is_cut = (ts != np.arange(S, dtype=ts.dtype).reshape(S, 1)) & real
    frontier = np.zeros((S, Nb), bool)
    for s in range(S):
        frontier[s, src[s, is_cut[s]]] = True
    frontier[:, Nb - 1] = False
    alive_rows = np.zeros((S, Nb), bool)
    for s in range(S):
        alive_rows[s, src[s, real[s]]] = True
    alive_rows[:, Nb - 1] = False
    return frontier, alive_rows & ~frontier


# ---- compact frontier pass ----------------------------------------------

def _ftopo(ov: OverlapTables) -> TopoArrays:
    # rev is a placeholder: the frontier pass never delivers (that is
    # the exchange's job), mirroring _local_round's ltopo convention
    return TopoArrays(
        src=ov.f_src, dst=ov.f_src, rev=ov.f_src,
        out_deg=ov.f_out_deg, row_start=ov.f_row_start,
        edge_rank=ov.f_edge_rank, delay=ov.f_delay,
    )


def _frontier_state(st: FlowUpdatingState, ov: OverlapTables,
                    Eb: int) -> FlowUpdatingState:
    """Gather the frontier rows' state (compact layout).  Pad slots are
    clamped gathers whose edges belong to the dead compact dummy row —
    they can never receive, fire, or send (same invariant as the main
    kernel's padding)."""
    ge = jnp.minimum(ov.f_edges, Eb - 1)
    e_ok = ov.f_edges < Eb
    gn = ov.f_nodes
    edge = lambda x: x[ge]
    planes = lambda x: x[:, ge]
    node = lambda x: x[gn]
    return FlowUpdatingState(
        t=st.t, value=node(st.value), flow=edge(st.flow),
        est=edge(st.est), recv=edge(st.recv), ticks=node(st.ticks),
        stamp=edge(st.stamp), last_avg=node(st.last_avg),
        fired=node(st.fired), alive=node(st.alive),
        edge_ok=edge(st.edge_ok) & e_ok,
        pending_flow=planes(st.pending_flow),
        pending_est=planes(st.pending_est),
        pending_valid=planes(st.pending_valid) & e_ok[None],
        pending_stamp=planes(st.pending_stamp),
        buf_flow=planes(st.buf_flow), buf_est=planes(st.buf_est),
        buf_valid=planes(st.buf_valid) & e_ok[None],
        key=st.key,
    )


def frontier_core(st: FlowUpdatingState, ov: OverlapTables,
                  cfg, Eb: int):
    """The compact frontier pass for the message modes: deliver + fire
    on exactly the frontier rows.  Returns ``(flow, msg_est,
    send_mask)`` in the compact edge layout — bit-identical to the
    full pass's values at the same slots (the drop draw is taken
    full-width from the SAME key split and gathered, so loss
    realizations agree positionally)."""
    cst = _frontier_state(st, ov, Eb)
    cfg0 = _dc.replace(cfg, drop_rate=0.0) if cfg.drop_rate > 0.0 else cfg
    cst, processed = deliver_phase(cst, _ftopo(ov), cfg0)
    cst, msg_est, send_mask = fire_core(cst, _ftopo(ov), cfg0, processed)
    if cfg.drop_rate > 0.0:
        _, sub = jax.random.split(st.key)
        keep = jax.random.bernoulli(sub, 1.0 - cfg.drop_rate, (Eb,))
        send_mask = send_mask & keep[jnp.minimum(ov.f_edges, Eb - 1)]
    return cst.flow, msg_est, send_mask


def _msg_payloads(st, pl, ov, cfg, Eb, perm, offsets, compact: bool):
    """Per-offset wire blocks for the message modes (bit-equal to
    ``_local_round``'s ppermute payloads).

    ``compact=True`` runs the compact frontier pass (thin frontiers:
    the early wire start is real).  ``compact=False`` — the fat-
    frontier 'overlap_full' resolution — replays the frontier at FULL
    width, which XLA CSEs with the interior pass into one computation.
    Message-based pairwise always takes the full-width replay: its
    segmented affine scan's combine tree is length-dependent
    (``ops/segscan.py`` uses ``lax.associative_scan``), so a compacted
    replay would differ in the last ulp."""
    if cfg.variant == COLLECTALL and compact:
        flow_f, est_f, send_f = frontier_core(st, ov, cfg, Eb)
        Fe = ov.f_edges.shape[0]
        dt = flow_f.dtype
        payloads = []
        for di in range(len(offsets)):
            pos = ov.send_pos[di]
            in_r = pos < Fe
            pp = jnp.minimum(pos, Fe - 1)
            v = (send_f[pp] & in_r).astype(dt)
            payloads.append(jnp.concatenate(
                [_lanes(flow_f[pp]), _lanes(est_f[pp]), v[None]]))
        return payloads
    ltopo = _local_topo(pl)
    st2, processed = deliver_phase(st, ltopo, cfg)
    st2, msg_est, send_mask = fire_core(st2, ltopo, cfg, processed)
    dt = st2.flow.dtype
    payloads = []
    for di in range(len(offsets)):
        sidx = perm.send_idx[di]
        in_r = sidx < Eb
        slc = jnp.minimum(sidx, Eb - 1)
        v = (send_mask[slc] & in_r).astype(dt)
        payloads.append(jnp.concatenate(
            [_lanes(st2.flow[slc]), _lanes(msg_est[slc]), v[None]]))
    return payloads


def _fastpair_payloads(st, ov, pl, Eb, offsets):  # noqa: ARG001  # pl: signature parity with the message-mode payload builder
    """Per-offset wire blocks for fast synchronous pairwise: the
    frontier rows' current estimates + sender-side validity."""
    ge = jnp.minimum(ov.f_edges, Eb - 1)
    e_ok = ov.f_edges < Eb
    gn = ov.f_nodes
    Fe = ov.f_edges.shape[0]
    n_rows = gn.shape[0]
    flow_f = st.flow[ge]
    est_f = st.value[gn] - jax.ops.segment_sum(
        flow_f, ov.f_src, num_segments=n_rows)
    x_u = est_f[ov.f_src]
    valid_u = st.alive[gn][ov.f_src] & st.edge_ok[ge] & e_ok
    dt = st.flow.dtype
    payloads = []
    for di in range(len(offsets)):
        pos = ov.send_pos[di]
        in_r = pos < Fe
        pp = jnp.minimum(pos, Fe - 1)
        payloads.append(jnp.concatenate(
            [_lanes(x_u[pp]), (valid_u[pp] & in_r).astype(dt)[None]]))
    return payloads


def _start_exchange(payloads, offsets, S, wire):
    """Issue the per-offset exchanges.  ``'ppermute'`` returns the
    collective results (XLA schedules them async on TPU; consuming them
    late keeps the overlap window open); ``'none'`` is the interior
    timing probe (nothing arrives)."""
    if wire == "none" or not offsets:
        return []
    if wire == "ppermute":
        out = []
        for di, p in enumerate(payloads):
            pairs = [(s, (s + offsets[di]) % S) for s in range(S)]
            out.append(jax.lax.ppermute(p, NODE_AXIS, pairs))
        return out
    raise ValueError(f"unknown wire {wire!r}")


# ---- the overlap round bodies -------------------------------------------

def local_round_overlap(st, pl, halo, perm, ov, cfg,  # noqa: ARG001  # halo: drop-in signature of sharded._local_round
                        Eb: int, S: int, offsets, halo_mode: str):
    """One split-schedule round on one shard's block (message modes).
    Drop-in replacement for ``sharded._local_round`` — same return
    contract, bit-identical state evolution for ``halo='overlap'``."""
    from flow_updating_tpu.ops import pallas_halo

    wire = _WIRE[halo_mode]
    me = jax.lax.axis_index(NODE_AXIS)
    D = cfg.delay_depth
    nf = st.flow.shape[1] if st.flow.ndim > 1 else 1

    # 1) frontier pass + 2) exchange start
    got = []
    if wire != "none" and offsets:
        payloads = _msg_payloads(st, pl, ov, cfg, Eb, perm, offsets,
                                 compact=halo_mode != "overlap_full")
        if wire == "ppermute":
            got = _start_exchange(payloads, offsets, S, wire)

    # 3) interior pass: full deliver + fire (covers the frontier rows
    # again at full width — the state of record), then the intra-shard
    # delivery merge while the wire is busy
    ltopo = _local_topo(pl)
    st, processed = deliver_phase(st, ltopo, cfg)
    st, msg_est, send_mask = fire_core(st, ltopo, cfg, processed)
    t = st.t

    if wire == "pallas" and offsets:
        # fused kernel: DMAs start, the receiver-pull merge runs in the
        # DMA window, then the recv semaphores gate the frontier finish
        lr = jnp.minimum(ov.lrev, Eb - 1)
        has_local = ov.lrev < Eb
        sending_r = send_mask[lr] & has_local
        slot_r = (t + pl.delay[lr]) % D
        hit = sending_r[None, :] & (
            slot_r[None, :] == jnp.arange(D, dtype=slot_r.dtype)[:, None])
        got, buf_flow, buf_est, buf_valid = \
            pallas_halo.fused_exchange_merge(
                payloads, offsets, hit, st.flow[lr], msg_est[lr],
                st.buf_flow, st.buf_est, st.buf_valid,
                axis_name=NODE_AXIS, axis_size=S)
    else:
        slot = (t + pl.delay) % D
        local_ok = send_mask & (pl.tshard == me)
        tgt = jnp.where(local_ok, pl.tlocal, Eb)
        buf_flow = st.buf_flow.at[slot, tgt].set(st.flow, mode="drop")
        buf_est = st.buf_est.at[slot, tgt].set(msg_est, mode="drop")
        buf_valid = st.buf_valid.at[slot, tgt].set(True, mode="drop")

    # 4) finish the frontier rows: consume the received blocks
    for di in range(len(got)):
        g = got[di]
        rv = g[2 * nf] > 0.5
        rt = perm.recv_tlocal[di]
        slot_r2 = (t + perm.recv_delay[di]) % D
        tgt2 = jnp.where(rv & (rt < Eb), rt, Eb)
        buf_flow = buf_flow.at[slot_r2, tgt2].set(
            _unlanes(g[:nf], st.flow), mode="drop")
        buf_est = buf_est.at[slot_r2, tgt2].set(
            _unlanes(g[nf:2 * nf], st.flow), mode="drop")
        buf_valid = buf_valid.at[slot_r2, tgt2].set(True, mode="drop")

    st = st.replace(t=t + 1, buf_flow=buf_flow, buf_est=buf_est,
                    buf_valid=buf_valid)
    return st, processed, send_mask


def local_round_overlap_fastpair(st, pl, halo, perm, ov, cfg,  # noqa: ARG001  # halo/cfg: drop-in signature of _local_round_fastpair
                                 Eb: int, S: int, offsets, halo_mode: str,
                                 num_colors: int):
    """Split-schedule round for fast synchronous pairwise: the cut
    endpoints' estimates go on the wire first, the bulk est/partner
    compute runs behind it, receives finish the frontier's ``x_v``."""
    from flow_updating_tpu.ops import pallas_halo

    wire = _WIRE[halo_mode]
    dt = st.flow.dtype
    t = st.t
    Nb = st.value.shape[0]
    half = jnp.asarray(0.5, dt)
    nf = st.flow.shape[1] if st.flow.ndim > 1 else 1

    got = []
    if wire != "none" and offsets:
        payloads = _fastpair_payloads(st, ov, pl, Eb, offsets)
        if wire == "ppermute":
            got = _start_exchange(payloads, offsets, S, wire)
        else:
            got = pallas_halo.remote_block_exchange(
                payloads, offsets, axis_name=NODE_AXIS, axis_size=S)

    est_n = st.value - jax.ops.segment_sum(
        st.flow, pl.src_local, num_segments=Nb)
    F = st.flow.shape[1:]
    x_u = est_n[pl.src_local]
    valid_u = st.alive[pl.src_local] & st.edge_ok

    is_local = (pl.tshard == jax.lax.axis_index(NODE_AXIS)) & (
        pl.tlocal < Eb)
    lr = jnp.minimum(pl.tlocal, Eb - 1)
    x_v = jnp.where(_ex(is_local, x_u), x_u[lr], jnp.asarray(0, dt))
    valid_v = is_local & valid_u[lr]

    for di in range(len(got)):
        g = got[di]
        rt = perm.recv_tlocal[di]
        tgt = jnp.where(g[nf] > 0.5, jnp.minimum(rt, Eb), Eb)
        arrived = jnp.zeros((Eb + 1,), bool).at[tgt].set(
            True, mode="drop")[:Eb]
        xin = jnp.zeros((Eb + 1,) + F, dt).at[tgt].set(
            _unlanes(g[:nf], x_u), mode="drop")[:Eb]
        x_v = jnp.where(_ex(arrived, x_v), xin, x_v)
        valid_v = valid_v | arrived

    matched = (pl.edge_color == t % num_colors) & valid_u & valid_v
    m_ex = _ex(matched, x_u)
    avg_e = (x_u + x_v) * half
    flow = jnp.where(m_ex, st.flow + (x_u - x_v) * half, st.flow)
    est_e = jnp.where(m_ex, avg_e, st.est)
    stamp = jnp.where(matched, t, st.stamp)
    fire_any = jax.ops.segment_max(
        matched.astype(jnp.int32), pl.src_local, num_segments=Nb) > 0
    node_avg = jax.ops.segment_sum(
        jnp.where(m_ex, avg_e, jnp.asarray(0, dt)), pl.src_local,
        num_segments=Nb)
    last_avg = jnp.where(_ex(fire_any, node_avg), node_avg, st.last_avg)
    st = st.replace(
        t=t + 1, flow=flow, est=est_e, stamp=stamp, last_avg=last_avg,
        fired=st.fired + fire_any.astype(jnp.int32),
    )
    none = jnp.zeros((Eb,), bool)
    return st, none, none
