"""Multi-chip node-collapsed kernel with a sharded fused-network SpMV.

The single-device node kernel's only graph op is the neighbor sum
(models/sync.py); its multi-chip GSPMD form keeps the gather and lets
XLA all-gather the avg vector over ICI.  This module is the
circuit-based equivalent: the gather-free permutation network
(ops/spmv_benes.py, executed by ops/pallas_fused.py), sharded by hand
with ``shard_map``:

* **Round-robin degree-interleaved node partition.**  Nodes live in the
  ELL ascending-degree order (padded so every bucket's row count is a
  multiple of S); shard ``s`` owns padded rows ``s::S`` of every bucket.
  Every shard therefore holds the SAME per-bucket row counts and
  widths — and, crucially, per-shard networks of the SAME width P.
* **Identical pass skeletons.**  Each shard routes its own network
  (its local ELL rows against the global node vector), but the stage
  *structure* must be jit-static and shared.  The Beneš section's shape
  is fixed by P; the spread/fill sections are padded to canonical
  full-width dist lists with all-false (no-op) stages
  (``spmv_benes.pad_roll_section``) so every shard runs the same pass
  sequence with different masks.
* **Stacked mask planes.**  Per-pass mask planes stack on a leading
  (S, ...) axis sharded over the mesh; inside ``shard_map`` each shard
  sees exactly its own planes.
* **One collective per round.**  The avg vector is all-gathered over
  the mesh axis (4 bytes/node/round — identical volume to the GSPMD
  gather path) and re-interleaved to global padded order with static
  reshapes; everything else is local circuits.

Use :class:`ShardedNodeKernel` directly (``sync.NodeKernel`` raises a
pointer here when given ``spmv='benes_fused'`` with a mesh).
"""

from __future__ import annotations

import functools

from flow_updating_tpu.utils import struct
import jax
import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.parallel.mesh import NODE_AXIS, shard_map
from flow_updating_tpu.topology.graph import Topology

P = jax.sharding.PartitionSpec

_sharded_plan_cache: dict = {}


@struct.dataclass
class ShardedSpmvArrays:
    """Constants, stacked per shard on the leading axis."""

    value: jnp.ndarray      # (S, M/S)
    inv_depp1: jnp.ndarray  # (S, M/S)
    deg: jnp.ndarray        # (S, M/S)
    mask_planes: tuple      # per pass: (S, rows, 128)
    plan: object = struct.field(pytree_node=False, default=None)
    #                         static _ShardedPlan (identity-hashed)


class _ShardedPlan:
    """Identity-hashed static plan shared by every shard."""

    def __init__(self, fused, bucket_shapes, bucket_offs, m1, num_shards):
        self.fused = fused                  # pallas_fused.FusedPlan
        self.bucket_shapes = bucket_shapes  # LOCAL (rows/S, w) per bucket
        self.bucket_offs = bucket_offs      # global padded offsets per bucket
        self.m1 = m1                        # global padded vector len + 1
        self.num_shards = num_shards


def plan_sharded_spmv(mats: tuple, m1: int, num_shards: int):
    """Per-shard fused plans with a common skeleton + stacked masks.

    ``mats``: the GLOBAL padded ELL matrices (every row count a multiple
    of ``num_shards``); shard s owns rows ``s::num_shards``.
    """
    from flow_updating_tpu.ops.pallas_fused import (
        MIN_P,
        pack_masks,
        plan_fused,
    )
    from flow_updating_tpu.ops.permute import concat_plans
    from flow_updating_tpu.ops.spmv_benes import (
        _mats_key,
        pad_roll_section,
        plan_sections,
    )

    S = num_shards
    key = (_mats_key(mats, m1), S)
    cached = _sharded_plan_cache.get(key)
    if cached is not None:
        return cached
    sections = []
    for s in range(S):
        mats_s = tuple(np.ascontiguousarray(m[s::S]) for m in mats)
        sections.append(plan_sections(mats_s, m1, min_width=MIN_P))
    widths = {sec[3] for sec in sections}
    if len(widths) != 1:
        # runtime-input-dependent invariant: must survive `python -O`
        raise ValueError(f"shards disagree on network width: {widths}")
    Pw = widths.pop()

    # canonical full-width dist lists (descending for spread, ascending
    # for fill) — supersequences of every shard's actual stages
    kmax = Pw.bit_length() - 1
    spread_dists = tuple(1 << k for k in range(kmax - 1, -1, -1))
    fill_dists = tuple(1 << k for k in range(kmax))

    stage_plans = []
    for spread, fill, benes, _ in sections:
        stage_plans.append(concat_plans(
            pad_roll_section(spread, spread_dists),
            pad_roll_section(fill, fill_dists),
            benes,
        ))
    skeleton = (stage_plans[0].dists, stage_plans[0].kinds)
    for sp in stage_plans[1:]:
        if (sp.dists, sp.kinds) != skeleton:
            raise ValueError(
                "shard stage skeletons diverged; per-shard routing would "
                "be silently wrong")

    fused = plan_fused(stage_plans[0])
    # pack on the HOST (numpy) and stack there: materializing per-shard
    # device planes before the sharded device_put would transiently
    # triple HBM on one chip at the 1M-node scale
    per_shard_planes = [pack_masks(sp, fused) for sp in stage_plans]
    stacked = tuple(
        np.stack([per_shard_planes[s][i] for s in range(S)])
        for i in range(len(per_shard_planes[0]))
    )
    local_shapes = tuple((m.shape[0] // S, m.shape[1]) for m in mats)
    out = (fused, stacked, local_shapes)
    _sharded_plan_cache[key] = out
    while len(_sharded_plan_cache) > 2:   # stacked planes are big
        _sharded_plan_cache.pop(next(iter(_sharded_plan_cache)))
    return out


class ShardedNodeKernel:
    """Node-collapsed fast collect-all over a device mesh, SpMV as
    per-shard fused circuits.  Mirrors :class:`models.sync.NodeKernel`'s
    recurrence exactly (tests assert equality with the single-device
    kernel)."""

    def __init__(self, topo: Topology, cfg: RoundConfig, mesh):
        from flow_updating_tpu.models import sync

        sync._check_cfg(cfg)
        if cfg.spmv != "benes_fused":
            raise ValueError("ShardedNodeKernel is the spmv='benes_fused' "
                             "mesh path")
        self.topo = topo
        self.cfg = cfg
        self.mesh = mesh
        S = mesh.devices.size

        # reuse the single-device kernel's padding/remapping machinery
        # (row_multiple=S makes every bucket's row count divisible by S);
        # spmv='xla' here only to skip its own plan construction
        import dataclasses

        # pin the throwaway base kernel's arrays to host CPU: its
        # unsharded ELL matrices would otherwise spike one chip's HBM at
        # the 1M-node scale before the sharded copies are placed
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            base = sync.NodeKernel(
                topo, dataclasses.replace(cfg, spmv="xla"),
                row_multiple=S)
        M = base.padded_size
        self.padded_size = M
        # keep only the host readback indices; holding the base kernel
        # would pin a full unsharded device copy of the ELL matrices
        self._pos_of_real = base._pos_of_real
        self._perm = base._perm
        mats_np = tuple(np.asarray(m) for m in base.arrays.mats)
        fused, planes, local_shapes = plan_sharded_spmv(mats_np, M + 1, S)

        offs = np.concatenate(
            [[0], np.cumsum([m.shape[0] for m in mats_np])]
        ).astype(np.int64)
        plan = _ShardedPlan(fused=fused, bucket_shapes=local_shapes,
                            bucket_offs=tuple(int(o) for o in offs),
                            m1=M + 1, num_shards=S)
        self._plan = plan

        def interleave_local(x):
            # global padded (M,) -> (S, M/S): shard s takes rows s::S of
            # each bucket, buckets concatenated
            parts = []
            for b in range(len(mats_np)):
                blk = x[offs[b]: offs[b + 1]]
                parts.append(blk.reshape(-1, S).T)   # (S, rows/S)
            return np.concatenate(parts, axis=1)

        dt = cfg.jnp_dtype
        value = np.asarray(base.arrays.value)
        deg = np.asarray(base.arrays.deg)
        inv = np.asarray(base.arrays.inv_depp1)
        del base
        # host arrays -> one sharded device_put each (never a full
        # unsharded device copy)
        import jax.sharding as jsh

        ns = lambda spec: jsh.NamedSharding(mesh, spec)
        put = lambda x, spec: jax.device_put(np.ascontiguousarray(x),
                                             ns(spec))
        self.arrays = ShardedSpmvArrays(
            value=put(interleave_local(value).astype(dt),
                      P(NODE_AXIS, None)),
            inv_depp1=put(interleave_local(inv).astype(dt),
                          P(NODE_AXIS, None)),
            deg=put(interleave_local(deg).astype(dt), P(NODE_AXIS, None)),
            mask_planes=tuple(
                put(p, P(NODE_AXIS, None, None)) for p in planes
            ),
            plan=plan,
        )

    def init_state(self):
        from flow_updating_tpu.models.sync import NodeSyncState

        import jax.sharding as jsh

        S = self._plan.num_shards
        M = self.padded_size
        z = jax.device_put(
            jnp.zeros((S, M // S), self.cfg.jnp_dtype),
            jsh.NamedSharding(self.mesh, P(NODE_AXIS, None)),
        )
        # t replicates over the mesh: a single-device-committed scalar
        # next to mesh-committed leaves would make jit refuse the state
        # (checkpoint restore device_puts every leaf to this template)
        t = jax.device_put(jnp.zeros((), jnp.int32),
                           jsh.NamedSharding(self.mesh, P()))
        return NodeSyncState(t=t, S=z, G=z, avg_prev=z, A_prev=z)

    def run(self, state, num_rounds: int):
        return _run_sharded(state, self.arrays, self.cfg, self.mesh,
                            num_rounds)

    def round_program(self, state, num_rounds: int):
        """``(jitted_fn, full_args, n_dynamic)`` for the plain sharded
        round scan — the AOT cost-attribution + golden-ledger hook
        (obs/profile.py, analysis/golden.py); exactly what :meth:`run`
        dispatches, so the profiled executable IS the plain program."""
        return (_run_sharded,
                (state, self.arrays, self.cfg, self.mesh, num_rounds), 2)

    def _uninterleave(self, x_l: np.ndarray) -> np.ndarray:
        """(S, M/S) local-layout array -> (M,) global padded order."""
        plan = self._plan
        out = np.zeros(self.padded_size, x_l.dtype)
        col = 0
        for b, (rows, _) in enumerate(plan.bucket_shapes):
            lo = plan.bucket_offs[b]
            blk = x_l[:, col: col + rows]            # (S, rows)
            out[lo: lo + rows * plan.num_shards] = blk.T.reshape(-1)
            col += rows
        return out

    def _unpermute(self, padded: np.ndarray) -> np.ndarray:
        out = np.empty(self.topo.num_nodes, padded.dtype)
        out[self._perm] = padded[self._pos_of_real]
        return out

    def estimates(self, state) -> np.ndarray:
        """Per-node estimates in original node order (same readback
        convention as NodeKernel: value + G)."""
        return self._unpermute(self._uninterleave(
            np.asarray(self.arrays.value + state.G)))

    def last_avg(self, state) -> np.ndarray:
        return self._unpermute(
            self._uninterleave(np.asarray(state.avg_prev)))

    def run_streamed(self, state, num_rounds: int, observe_every: int,
                     emit):
        """Chunked host-side observer with the same emit payload as
        sync.run_rounds_node_streamed (metrics over communicating
        nodes)."""
        if num_rounds % observe_every:
            raise ValueError("num_rounds must be a multiple of "
                             "observe_every")
        mean = float(self.topo.true_mean)
        deg = np.asarray(self.arrays.deg)
        real = deg > 0
        cnt = max(int(real.sum()), 1)
        for _ in range(num_rounds // observe_every):
            state = self.run(state, observe_every)
            if emit is not None:
                est = np.asarray(self.arrays.value + state.G)
                err = np.where(real, est - mean, 0.0)
                emit({
                    "t": int(state.t),
                    "rmse": float(np.sqrt((err * err).sum() / cnt)),
                    "max_abs_err": float(np.abs(err).max()),
                    "mass": float(np.where(real, est, 0.0).sum()),
                    "fired_total": int(state.t) * cnt,
                })
        return state


def _neighbor_sum_local(avg_glob, planes_l, plan: _ShardedPlan):
    """Per-shard circuit: global padded avg -> local rows' neighbor
    sums.  Mirrors spmv_benes.neighbor_sum_benes with local buckets."""
    from flow_updating_tpu.ops.pallas_fused import apply_fused

    z = jnp.concatenate([
        avg_glob,
        jnp.zeros((plan.fused.P - plan.m1 + 1,), avg_glob.dtype),
    ])
    z = apply_fused(z, plan.fused, planes_l)
    parts = []
    off = plan.m1
    for rows, w in plan.bucket_shapes:
        if w == 0:
            parts.append(jnp.zeros((rows,), avg_glob.dtype))
        else:
            blk = z[off: off + rows * w].reshape(rows, w)
            parts.append(jnp.sum(blk, axis=1))
            off += rows * w
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def _interleave_global(gathered, plan: _ShardedPlan):
    """(S, M/S) all-gathered local avgs -> (M,) global padded order."""
    parts = []
    col = 0
    for rows, _ in plan.bucket_shapes:
        blk = gathered[:, col: col + rows]          # (S, rows)
        parts.append(blk.T.reshape(-1))             # (rows*S,)
        col += rows
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


@functools.partial(jax.jit,
                   static_argnames=("cfg", "mesh", "num_rounds"))
def _run_sharded(state, arrays: ShardedSpmvArrays,
                 cfg: RoundConfig,  # noqa: ARG001  # cfg: jit static argname — a cache key, not body data
                 mesh, num_rounds: int):
    plan = arrays.plan

    def body(value_l, inv_l, deg_l, planes_l, st):
        value_l, inv_l, deg_l = (a[0] for a in (value_l, inv_l, deg_l))
        planes_l = tuple(p[0] for p in planes_l)
        st = jax.tree.map(lambda x: x[0] if x.ndim == 2 else x, st)

        def step(st, _):
            avg_l = (value_l - st.S + st.A_prev) * inv_l
            gathered = jax.lax.all_gather(avg_l, NODE_AXIS)   # (S, M/S)
            avg_glob = _interleave_global(gathered, plan)
            A_cur = _neighbor_sum_local(avg_glob, planes_l, plan)
            S_next = -st.G - A_cur + deg_l * st.avg_prev
            G_next = -st.S - deg_l * avg_l + st.A_prev
            return st.replace(t=st.t + 1, S=S_next, G=G_next,
                              avg_prev=avg_l, A_prev=A_cur), None

        out, _ = jax.lax.scan(step, st, None, length=num_rounds)
        return jax.tree.map(
            lambda x: x[None] if x.ndim == 1 else x, out)

    sh = P(NODE_AXIS, None)
    plane_specs = tuple(P(NODE_AXIS, None, None) for _ in
                        arrays.mask_planes)
    state_spec = jax.tree.map(
        lambda x: sh if x.ndim == 2 else P(), state,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(sh, sh, sh, plane_specs, state_spec),
        out_specs=state_spec,
        check_vma=False,
    )(arrays.value, arrays.inv_depp1, arrays.deg, arrays.mask_planes,
      state)
