"""Device mesh helpers.

The framework's primary parallel axis is the *node axis* (named
``'nodes'``, the "graph" axis of the 2-D mesh) — the analogue of the
reference's "thousands of simulated actors" (SURVEY.md §2c): nodes and
their out-edge ledgers are sharded over devices; cross-shard edges ride
XLA collectives over ICI (the TPU-native replacement for the mailbox
rendezvous that SimGrid's kernel performs in shared memory).

Vector payloads add an orthogonal *feature axis* (``'feature'``): the D
payload lanes of an ``(N, D)`` run are D independent protocol instances
sharing one message schedule (models/state.py), so the feature dimension
shards across devices with NO cross-shard protocol traffic at all — the
model-parallel axis of the DFL workloads (:mod:`flow_updating_tpu
.parallel.feature`).  :func:`make_mesh2d` builds the combined
``('nodes', 'feature')`` mesh; either axis may be 1."""

from __future__ import annotations

import jax
import numpy as np

NODE_AXIS = "nodes"
FEATURE_AXIS = "feature"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``.

    jax >= 0.6 exposes ``jax.shard_map`` (replication check flag
    ``check_vma``); earlier releases ship it as
    ``jax.experimental.shard_map.shard_map`` with the flag spelled
    ``check_rep``.  Same semantics either way."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def make_mesh(n_devices: int | None = None, axis: str = NODE_AXIS) -> jax.sharding.Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.array(devices), (axis,))


def make_mesh2d(graph_shards: int = 1,
                feature_shards: int = 1) -> jax.sharding.Mesh:
    """The ``('nodes', 'feature')`` 2-D mesh: ``graph_shards`` halo
    shards x ``feature_shards`` payload-model shards.  Either axis may
    be 1 (a 1-axis mesh with the other axis present-but-trivial keeps
    every sharding spec valid, so single-axis and 2-D programs share
    code paths)."""
    need = graph_shards * feature_shards
    devices = jax.devices()
    if need > len(devices):
        raise ValueError(
            f"mesh {graph_shards}x{feature_shards} needs {need} devices, "
            f"only {len(devices)} visible")
    grid = np.array(devices[:need]).reshape(graph_shards, feature_shards)
    return jax.sharding.Mesh(grid, (NODE_AXIS, FEATURE_AXIS))
