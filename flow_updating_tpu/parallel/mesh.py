"""Device mesh helpers.

The framework's one parallel axis is the *node axis* — the analogue of the
reference's "thousands of simulated actors" (SURVEY.md §2c): nodes and their
out-edge ledgers are sharded over devices; cross-shard edges ride XLA
collectives over ICI (the TPU-native replacement for the mailbox rendezvous
that SimGrid's kernel performs in shared memory)."""

from __future__ import annotations

import jax
import numpy as np

NODE_AXIS = "nodes"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``.

    jax >= 0.6 exposes ``jax.shard_map`` (replication check flag
    ``check_vma``); earlier releases ship it as
    ``jax.experimental.shard_map.shard_map`` with the flag spelled
    ``check_rep``.  Same semantics either way."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def make_mesh(n_devices: int | None = None, axis: str = NODE_AXIS) -> jax.sharding.Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.array(devices), (axis,))
