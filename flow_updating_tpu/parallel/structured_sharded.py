"""Pod-sharded fat-tree stencil: mega-scale multi-chip with O(k) traffic.

The GSPMD node kernel's cross-chip cost is one all-gather of the whole
avg vector per round — O(N) bytes, 4 MB at k=160.  Naively sharding the
structured stencil is worse (PARITY.md: section slicing makes the
partitioner materialize per-section collectives).  But the fat-tree's
*pod* axis is embarrassingly parallel: hosts, edge switches and
aggregation switches of pod p interact only with each other — the ONE
cross-pod term in the whole round is the core neighbor sum

    A_core[a, c] = sum_p x_agg[p, a]

(`ops/structured.py:FatTreeStruct`), a ``psum`` over the pod axis of a
``(k/2,)`` partial — **2k bytes per round, independent of N**.  Core
switches are replicated: after the psum every device holds the same
A_core, so their (tiny, (k/2)^2-sized) state advances identically
everywhere, and no second collective is needed.

This is the TPU-native answer at its purest: the reference's NCCL-class
backend (SURVEY §2c-2) becomes a single sub-kilobyte ICI all-reduce per
round, and 8 chips hold 8x the virtual fat-tree
(``fat_tree(k, materialize_edges=False)`` — ~500M nodes at k=1280 on a
v5e-8 in principle).

State layout: per-section arrays, host/edge/agg sharded on the mesh's
pod axis (``shard_map`` in_specs P('nodes')), core replicated (P()).
Exactness vs the single-device structured kernel is asserted in
``tests/test_structured_sharded.py`` (the psum reassociates the pod sum,
so f64 agreement is 1e-12-tight, not bit-exact).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.utils import struct

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.ops.structured import FatTreeStruct
from flow_updating_tpu.parallel.mesh import NODE_AXIS, shard_map
from flow_updating_tpu.topology.graph import Topology


@struct.dataclass
class PodState:
    """Sections: host (k, h, h), edge (k, h), agg (k, h), core (h, h),
    where h = k/2; host/edge/agg are pod-sharded on axis 0."""

    t: jnp.ndarray
    S: tuple        # (host, edge, agg, core)
    G: tuple
    avg_prev: tuple
    A_prev: tuple


def _flatten(sections) -> jnp.ndarray:
    return jnp.concatenate([s.reshape(-1) for s in sections])


class PodShardedFatTreeKernel:
    """Fast synchronous collect-all on a virtual-or-materialized fat-tree,
    sharded by pod over ``mesh``.  Requires ``S | k`` (S = mesh size)."""

    def __init__(self, topo: Topology, cfg: RoundConfig, mesh,
                 overlap: bool = False):
        # ``overlap=True`` runs the communication-overlap round schedule:
        # the cross-pod psum of the core partial is ISSUED first, the
        # pod-local host/edge/agg sections (the O(N) interior) advance
        # while it is in flight, and the replicated core section (the
        # (k/2)^2 frontier) finishes after the all-reduce lands.  Same
        # ops on the same values — bit-identical results — but the
        # program order lets XLA's async collectives hide the ICI hop
        # behind the interior compute (Engine(halo='overlap')).
        if not cfg.is_fast_sync_collectall:
            raise ValueError(
                "the pod-sharded stencil covers exactly the fast "
                "synchronous collect-all mode (like kernel='node')"
            )
        if not isinstance(topo.structure, FatTreeStruct):
            raise ValueError(
                "PodShardedFatTreeKernel needs a fat-tree structure "
                "descriptor (topology.structure); got "
                f"{type(topo.structure).__name__}"
            )
        self.k = k = topo.structure.k
        self.S = S = int(mesh.devices.size)
        if k % S:
            raise ValueError(
                f"mesh size {S} must divide the fat-tree arity k={k} "
                "(pods shard evenly; pad k or change the mesh)"
            )
        self.topo = topo
        self.cfg = cfg
        self.mesh = mesh
        self.overlap = bool(overlap)
        overlap = self.overlap      # captured by the jit closures below
        dt = cfg.jnp_dtype

        deg = topo.out_deg.astype(np.float64)
        vals = np.asarray(topo.values, np.float64)
        sh = lambda spec: jax.sharding.NamedSharding(mesh, spec)
        pod = jax.sharding.PartitionSpec(NODE_AXIS)
        rep = jax.sharding.PartitionSpec()
        self._specs = (pod, pod, pod, rep)
        place = lambda secs: tuple(
            jax.device_put(jnp.asarray(s, dt), sh(sp))
            for s, sp in zip(secs, self._specs))
        struct = topo.structure
        self.value = place(struct.sections(vals))
        self.inv_depp1 = place(struct.sections(1.0 / (deg + 1.0)))
        self.deg = place(struct.sections(deg))

        @functools.partial(
            jax.jit, static_argnames=("num_rounds",))
        def _run(state: PodState, value, inv_depp1, deg,
                 num_rounds: int) -> PodState:
            shmap = shard_map(
                functools.partial(_scan_rounds, num_rounds=num_rounds,
                                  overlap=overlap),
                mesh=mesh,
                in_specs=(PodState(t=rep, S=self._specs, G=self._specs,
                                   avg_prev=self._specs,
                                   A_prev=self._specs),
                          self._specs, self._specs, self._specs),
                out_specs=PodState(t=rep, S=self._specs, G=self._specs,
                                   avg_prev=self._specs,
                                   A_prev=self._specs),
            )
            return shmap(state, value, inv_depp1, deg)

        self._run_jit = _run

        n_nodes = topo.num_nodes

        @functools.partial(
            jax.jit, static_argnames=("num_rounds", "spec"))
        def _run_tel(state: PodState, value, inv_depp1, deg, mean,
                     num_rounds: int, spec):
            st_specs = PodState(t=rep, S=self._specs, G=self._specs,
                                avg_prev=self._specs, A_prev=self._specs)
            shmap = shard_map(
                functools.partial(_scan_rounds_telemetry,
                                  num_rounds=num_rounds, spec=spec,
                                  n=n_nodes, overlap=overlap),
                mesh=mesh,
                in_specs=(st_specs, self._specs, self._specs, self._specs,
                          rep),
                out_specs=(st_specs,
                           jax.sharding.PartitionSpec(NODE_AXIS)),
            )
            return shmap(state, value, inv_depp1, deg, mean)

        self._run_tel_jit = _run_tel

        @functools.partial(
            jax.jit, static_argnames=("num_rounds", "spec"))
        def _run_fld(state: PodState, value, inv_depp1, deg, mean,
                     num_rounds: int, spec):
            st_specs = PodState(t=rep, S=self._specs, G=self._specs,
                                avg_prev=self._specs, A_prev=self._specs)
            shmap = shard_map(
                functools.partial(_scan_rounds_fields,
                                  num_rounds=num_rounds, spec=spec,
                                  n=n_nodes, overlap=overlap),
                mesh=mesh,
                in_specs=(st_specs, self._specs, self._specs, self._specs,
                          rep),
                out_specs=(st_specs,
                           jax.sharding.PartitionSpec(NODE_AXIS),
                           jax.sharding.PartitionSpec(NODE_AXIS)),
                # the convergence-frontier carry mixes replicated (core)
                # and pod-sharded sections; the replication checker cannot
                # prove the core leaf and rejects the scan — the blocks
                # are reassembled host-side anyway (as in parallel/sharded)
                check_vma=False,
            )
            return shmap(state, value, inv_depp1, deg, mean)

        self._run_fields_jit = _run_fld

    @property
    def padded_size(self) -> int:
        """Node-slot count: no padding — sections tile exactly."""
        return self.topo.num_nodes

    def init_state(self) -> PodState:
        z = lambda: tuple(jnp.zeros_like(v) for v in self.value)
        return PodState(t=jnp.zeros((), jnp.int32), S=z(), G=z(),
                        avg_prev=z(), A_prev=z())

    def run(self, state: PodState, num_rounds: int) -> PodState:
        return self._run_jit(state, self.value, self.inv_depp1, self.deg,
                             num_rounds)

    def round_program(self, state: PodState, num_rounds: int):
        """``(jitted_fn, full_args, n_dynamic)`` for the plain pod round
        scan — the AOT cost-attribution hook
        (:mod:`flow_updating_tpu.obs.profile`); exactly what :meth:`run`
        dispatches, so the profiled executable IS the plain program."""
        return (self._run_jit,
                (state, self.value, self.inv_depp1, self.deg, num_rounds),
                4)

    def run_streamed(self, state: PodState, num_rounds: int,
                     observe_every: int, emit) -> PodState:
        """Host-chunked observer; the emit record shape is
        `utils.metrics.observer_sample` (shared with the node kernel's
        sampler and the halo engine branch).  Metrics reduce ON DEVICE —
        each sample transfers three scalars, never the O(N) estimate
        vector (which at this kernel's design scale is gigabytes)."""
        from flow_updating_tpu.utils.metrics import observer_sample

        if num_rounds % observe_every:
            raise ValueError(
                "num_rounds must be a multiple of observe_every")
        n = self.topo.num_nodes
        mean = self.topo.true_mean
        for _ in range(num_rounds // observe_every):
            state = self.run(state, observe_every)
            sq, mx, mass = _pod_sample(self.value, state.G, mean)
            emit(observer_sample(state.t, np.sqrt(float(sq) / n), mx,
                                 mass, int(state.t) * n))
        return state

    def run_telemetry(self, state: PodState, num_rounds: int, spec):
        """Device-resident per-round series, psum-reduced over the pod
        axis (each round adds a handful of scalar psums to the existing
        (k/2,)-element one).  Returns ``(state, series)`` with the same
        field contract as the node kernel's sampler."""
        mean = jnp.asarray(self.topo.true_mean, self.value[0].dtype)
        state, series = self._run_tel_jit(
            state, self.value, self.inv_depp1, self.deg, mean,
            num_rounds=num_rounds, spec=spec)
        return state, {k: v[0] for k, v in series.items()}

    def run_fields(self, state: PodState, num_rounds: int, spec):
        """Device-resident per-node field rows, kept in per-section
        blocks on device (the host flattens with
        :meth:`flatten_field_series` / :meth:`flatten_field_final`).
        Returns ``(state, conv_sections, series)`` where each series
        leaf stacks a leading shard axis."""
        if num_rounds % spec.stride:
            raise ValueError(
                f"num_rounds={num_rounds} must be a multiple of the "
                f"field stride {spec.stride}")
        mean = jnp.asarray(self.topo.true_mean, self.value[0].dtype)
        return self._run_fields_jit(
            state, self.value, self.inv_depp1, self.deg, mean,
            num_rounds=num_rounds, spec=spec)

    def flatten_field_series(self, sections) -> np.ndarray:
        """Per-section stacked series -> ``(R, N)`` flat generator node
        order.  Pod-sharded sections arrive as ``(S, R, k/S, ...)``
        (shard-major pods == global pod order); the replicated core as
        ``(S, R, h, h)`` with identical blocks (take shard 0)."""
        parts = []
        last = len(sections) - 1
        for i, x in enumerate(sections):
            x = np.asarray(x)
            if i < last:
                R = x.shape[1]
                parts.append(np.moveaxis(x, 0, 1).reshape(R, -1))
            else:
                parts.append(x[0].reshape(x.shape[1], -1))
        return np.concatenate(parts, axis=1)

    def flatten_field_final(self, sections) -> np.ndarray:
        """One-shot per-node sections (the convergence frontier) ->
        ``(N,)`` flat generator node order."""
        parts = []
        last = len(sections) - 1
        for i, x in enumerate(sections):
            x = np.asarray(x)
            parts.append((x if i < last else x[0]).reshape(-1))
        return np.concatenate(parts)

    def estimates(self, state: PodState) -> np.ndarray:
        """value + G per node, original (generator) node order."""
        est = tuple(v + g for v, g in zip(self.value, state.G))
        return np.asarray(_flatten(est))

    def last_avg(self, state: PodState) -> np.ndarray:
        return np.asarray(_flatten(state.avg_prev))

    # ---- canonical (single-device structured NodeKernel) layout --------
    # The structured NodeKernel stores (N,) vectors in generator order
    # with no padding, so flattening sections IS the canonical layout:
    # pod-mode checkpoints are standard node-kernel checkpoints,
    # restorable by any execution mode (mirrors the halo kernel's
    # gather-to-canonical convention, engine.save_checkpoint).

    def to_canonical(self, state: PodState):
        from flow_updating_tpu.models.sync import NodeSyncState

        return NodeSyncState(
            t=state.t, S=_flatten(state.S), G=_flatten(state.G),
            avg_prev=_flatten(state.avg_prev),
            A_prev=_flatten(state.A_prev),
        )

    def from_canonical(self, ns) -> PodState:
        struct = self.topo.structure
        sec = lambda v: tuple(
            jax.device_put(s, jax.sharding.NamedSharding(self.mesh, sp))
            for s, sp in zip(struct.sections(jnp.asarray(v)), self._specs))
        return PodState(t=ns.t, S=sec(ns.S), G=sec(ns.G),
                        avg_prev=sec(ns.avg_prev), A_prev=sec(ns.A_prev))


@jax.jit
def _pod_sample(value, G, mean):
    """Device-side watcher reductions over the sections: returns
    (sum of squared error, max abs error, mass) — three scalars."""
    sq = 0.0
    mx = 0.0
    mass = 0.0
    for v, g in zip(value, G):
        est = v + g
        err = est - mean
        sq = sq + jnp.sum(err * err)
        mx = jnp.maximum(mx, jnp.max(jnp.abs(err)))
        mass = mass + jnp.sum(est)
    return sq, mx, mass


def _neighbor_sum_pod(x, axis_name: str):
    """A(x) per section: the shared pod-block stencil
    (`FatTreeStruct.pod_local_sums`) plus the one cross-pod psum for the
    core column sum."""
    xh, xe, xa, xc = x
    a_host, a_edge, a_agg, part = FatTreeStruct.pod_local_sums(
        xh, xe, xa, xc)
    a_core_col = jax.lax.psum(part, axis_name)   # (k/2,) — 2k bytes f32
    a_core = jnp.broadcast_to(a_core_col[:, None], xc.shape)
    return a_host, a_edge, a_agg, a_core


def _round(state: PodState, value, inv_depp1, deg,
           axis_name: str) -> PodState:
    ew = lambda f, *ts: tuple(f(*xs) for xs in zip(*ts))
    avg = ew(lambda v, s, a, i: (v - s + a) * i,
             value, state.S, state.A_prev, inv_depp1)
    A_cur = _neighbor_sum_pod(avg, axis_name)
    S_next = ew(lambda g, ac, d, ap: -g - ac + d * ap,
                state.G, A_cur, deg, state.avg_prev)
    G_next = ew(lambda s, d, av, ap: -s - d * av + ap,
                state.S, deg, avg, state.A_prev)
    return PodState(t=state.t + 1, S=S_next, G=G_next,
                    avg_prev=avg, A_prev=A_cur)


def _round_overlap(state: PodState, value, inv_depp1, deg,
                   axis_name: str) -> PodState:
    """The overlap schedule of :func:`_round`: issue the one cross-pod
    collective (the core column psum — the round's whole wire) FIRST,
    advance the pod-local host/edge/agg sections (the O(N) interior)
    while it is in flight, and finish the replicated ``(k/2)^2`` core
    section (the stencil's boundary band) after the all-reduce lands.
    Same formulas on the same operands — bit-identical to :func:`_round`
    (asserted in tests/test_overlap.py) — only the program order moves
    the wire behind the interior compute."""
    ew = lambda f, *ts: tuple(f(*xs) for xs in zip(*ts))
    avg = ew(lambda v, s, a, i: (v - s + a) * i,
             value, state.S, state.A_prev, inv_depp1)
    xh, xe, xa, xc = avg
    a_host, a_edge, a_agg, part = FatTreeStruct.pod_local_sums(
        xh, xe, xa, xc)
    part_sum = jax.lax.psum(part, axis_name)      # the wire, issued early
    # interior: every pod-local section advances without the collective
    local_A = (a_host, a_edge, a_agg)
    S_local = tuple(-g - ac + d * ap for g, ac, d, ap in zip(
        state.G[:3], local_A, deg[:3], state.avg_prev[:3]))
    G_next = ew(lambda s, d, av, ap: -s - d * av + ap,
                state.S, deg, avg, state.A_prev)
    # frontier: the replicated core finishes once the psum completes
    a_core = jnp.broadcast_to(part_sum[:, None], xc.shape)
    S_next = S_local + (-state.G[3] - a_core + deg[3] * state.avg_prev[3],)
    A_cur = local_A + (a_core,)
    return PodState(t=state.t + 1, S=S_next, G=G_next,
                    avg_prev=avg, A_prev=A_cur)


def _scan_rounds(state: PodState, value, inv_depp1, deg,
                 num_rounds: int, overlap: bool = False) -> PodState:
    step = _round_overlap if overlap else _round

    def body(s, _):
        return step(s, value, inv_depp1, deg, NODE_AXIS), None

    out, _ = jax.lax.scan(body, state, None, length=num_rounds)
    return out


def _pod_telemetry_sample(s: PodState, value, spec, mean, n: int,
                          axis_name: str) -> dict:
    """One round's metric row across the pod-sharded sections.  The core
    section is REPLICATED (every shard holds the same copy), so its sums
    enter the psum on shard 0 only; max is idempotent and needs no mask.
    In fast sync mode every node fires every round: fired_total = t * n."""
    from flow_updating_tpu.models.rounds import _fired_acc

    first = jax.lax.axis_index(axis_name) == 0
    dt = value[0].dtype
    zero = jnp.zeros((), dt)
    sq = mass = vsum = mx = zero
    last = len(value) - 1
    for i, (v, g) in enumerate(zip(value, s.G)):
        est = v + g
        err = est - mean
        lsq = jnp.sum(err * err)
        lmass = jnp.sum(est)
        lv = jnp.sum(v)
        if i == last:  # core: replicated — count once
            lsq = jnp.where(first, lsq, zero)
            lmass = jnp.where(first, lmass, zero)
            lv = jnp.where(first, lv, zero)
        sq = sq + lsq
        mass = mass + lmass
        vsum = vsum + lv
        mx = jnp.maximum(mx, jnp.max(jnp.abs(err)))
    psum = lambda x: jax.lax.psum(x, axis_name)
    out = {"t": s.t}
    if spec.has("rmse"):
        out["rmse"] = jnp.sqrt(psum(sq) / jnp.asarray(n, dt))
    if spec.has("max_abs_err"):
        out["max_abs_err"] = jax.lax.pmax(mx, axis_name)
    if spec.has("mass") or spec.has("mass_residual"):
        total = psum(mass)
        if spec.has("mass"):
            out["mass"] = total
        if spec.has("mass_residual"):
            out["mass_residual"] = total - psum(vsum)
    if spec.has("fired_total"):
        acc = _fired_acc()
        out["fired_total"] = s.t.astype(acc) * jnp.asarray(n, acc)
    if spec.has("active"):
        out["active"] = jnp.asarray(n, jnp.int32)
    return out


def _pod_field_sample(s: PodState, value, spec, mean, n: int,
                      axis_name: str):  # noqa: ARG001  # sampler signature parity (halo twin psums over it)
    """One recorded per-node field row across the sections, kept in
    section layout (the host flattens).  The fat-tree tiles exactly (no
    padding, no churn on this kernel), so no alive masking is needed; in
    fast sync mode every node fires every round (``node_fired = t``)."""
    row = {"t": s.t, "active": jnp.asarray(n, jnp.int32)}
    err = None
    need_est = any(spec.has(f) for f in
                   ("node_err", "node_mass", "node_mass_residual",
                    "node_conv_round"))
    if need_est:
        est = tuple(v + g for v, g in zip(value, s.G))
        err = tuple(e - mean for e in est)
        if spec.has("node_err"):
            row["node_err"] = err
        if spec.has("node_mass"):
            row["node_mass"] = est
        if spec.has("node_mass_residual"):
            row["node_mass_residual"] = tuple(
                e - v for e, v in zip(est, value))
    if spec.has("node_fired"):
        row["node_fired"] = tuple(
            jnp.broadcast_to(s.t, v.shape).astype(jnp.int32)
            for v in value)
    return row, err


def _scan_rounds_fields(state: PodState, value, inv_depp1, deg, mean,
                        num_rounds: int, spec, n: int,
                        overlap: bool = False):
    stride = spec.stride
    track_conv = spec.has("node_conv_round")
    step = _round_overlap if overlap else _round

    def chunk(carry, _):
        s, conv = carry
        s = jax.lax.fori_loop(
            0, stride,
            lambda _, x: step(x, value, inv_depp1, deg, NODE_AXIS), s)
        row, err = _pod_field_sample(s, value, spec, mean, n, NODE_AXIS)
        if track_conv:
            conv = tuple(
                jnp.where((c < 0) & (jnp.abs(e) <= spec.tol), s.t, c)
                for c, e in zip(conv, err))
        return (s, conv), row

    conv0 = tuple(jnp.full(v.shape, -1, jnp.int32) for v in value)
    (out, conv), series = jax.lax.scan(
        chunk, (state, conv0), None, length=num_rounds // stride)
    # unit shard axis on everything so the P(NODE_AXIS) out_specs can
    # concatenate per-shard blocks (host reads core blocks from shard 0)
    return (out, jax.tree.map(lambda x: x[None], conv),
            jax.tree.map(lambda x: x[None], series))


def _scan_rounds_telemetry(state: PodState, value, inv_depp1, deg, mean,
                           num_rounds: int, spec, n: int,
                           overlap: bool = False):
    step = _round_overlap if overlap else _round

    def body(s, _):
        s2 = step(s, value, inv_depp1, deg, NODE_AXIS)
        return s2, _pod_telemetry_sample(s2, value, spec, mean, n,
                                         NODE_AXIS)

    out, series = jax.lax.scan(body, state, None, length=num_rounds)
    # psum-reduced series are identical on every shard; stack a unit
    # shard axis so the P(NODE_AXIS) out_spec shards it (host reads [0])
    return out, jax.tree.map(lambda x: x[None], series)
